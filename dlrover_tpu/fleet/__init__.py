"""Elastic serving fleet: supervised replicas behind a slot-aware
gateway with staged weight rollout and autoscaling.

The serving-side twin of the training runtime's elasticity (ROADMAP
north star: "serve heavy traffic from millions of users"): where one
``tpurun-serve`` process is a single point of failure whose weight
swaps stall every live stream, the fleet runs N supervised replicas —
a replica death is a health-poll transition plus a relaunch, a
checkpoint push is a one-replica-at-a-time drain→swap→readmit, and
throughput scales with replica count under a queue/latency autoscaler.

Layers (each importable alone; nothing here imports jax):

- :mod:`.config`      — FleetConfig + the ``DLROVER_FLEET_*`` knobs
- :mod:`.replica`     — subprocess / in-process replica backends
- :mod:`.supervisor`  — ReplicaSupervisor (STARTING→READY→DRAINING→DEAD)
- :mod:`.gateway`     — slot-aware routing, re-dispatch, admission, prefixes
- :mod:`.rollout`     — staged zero-downtime weight rollout
- :mod:`.autoscaler`  — queue-depth / p95 fleet autoscaler
- :mod:`.cli`         — ``tpurun-fleet``

See docs/serving_fleet.md for topology, semantics, and the measured
availability SLO matrix.
"""

from .autoscaler import FleetAutoscaler  # noqa: F401
from .config import FleetConfig  # noqa: F401
from .gateway import (  # noqa: F401
    Gateway,
    GatewayBusy,
    NoReadyReplica,
    UnknownPrefix,
)
from .replica import InProcessReplica, SubprocessReplica  # noqa: F401
from .rollout import staged_rollout  # noqa: F401
from .supervisor import (  # noqa: F401
    ReplicaHandle,
    ReplicaState,
    ReplicaSupervisor,
)

__all__ = [
    "FleetAutoscaler",
    "FleetConfig",
    "Gateway",
    "GatewayBusy",
    "InProcessReplica",
    "NoReadyReplica",
    "ReplicaHandle",
    "ReplicaState",
    "ReplicaSupervisor",
    "SubprocessReplica",
    "UnknownPrefix",
    "staged_rollout",
]
