"""Slot-aware HTTP gateway over a replica fleet.

The fleet's single client-facing endpoint, speaking the same API one
``tpurun-serve`` replica speaks (``/v1/completions``,
``/v1/prefixes``) plus the fleet control surface (``/fleet/status``,
``/fleet/rollout``, ``/fleet/scale``). Behavior contract
(docs/serving_fleet.md):

- **Slot-aware least-loaded routing**: each request goes to the READY
  replica with the lowest load score — ``busy_slots + queue_depth``
  from its last health poll plus the gateway's own in-flight count to
  that replica (the poll snapshot alone lags by up to one health
  interval; the in-flight term keeps a burst from dogpiling one
  replica inside that window).
- **Stream pinning**: a streaming completion is pinned to its replica
  for its whole life (its KV cache lives there). If the replica dies
  mid-stream the stream errors — re-dispatching would silently replay
  token history from a different cache.
- **Transparent re-dispatch**: a NON-streamed request whose replica
  dies mid-flight — a connection error, or a replica-side 5xx (a
  SIGKILLed subprocess drops the socket; an in-process driver death
  answers ``500 serving daemon stopped`` on its way down) — is
  re-sent to another READY replica. Completions are deterministic per
  weight version and a failed attempt emitted nothing to the client,
  so a replay is safe; the client sees one slower success instead of
  an error. Replica 4xx are the client's own fault and forward as-is.
- **Admission control**: total in-flight proxied requests are bounded
  (``queue_limit``); beyond it the gateway answers 429 with a
  ``Retry-After`` hint instead of queueing without bound — overload
  degrades into explicit backpressure, not a wedged fleet.
- **Prefix fan-out**: ``/v1/prefixes`` registers on the gateway; the
  gateway replays registrations onto every replica — keyed by
  (generation, weight_version), so a relaunched or re-weighted
  replica gets fresh registrations before serving prefix requests
  (the engine refuses stale prefix encodings by construction; the
  gateway's job is re-registration, not cache validity).

Gateway request time is stamped into an attribution
:class:`PhaseAccumulator` (``route``/``proxy``/``redispatch`` —
attribution/phases.py), so ``/fleet/status`` reports the gateway's own
host fraction next to each replica's serving split.
"""

import json
import threading
import time
import urllib.error
import urllib.request
from collections import OrderedDict
from http.server import ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

from ..attribution.phases import PhaseAccumulator
from ..chaos import faults
from ..common.log import logger
from .config import FleetConfig
from .supervisor import ReplicaHandle, ReplicaSupervisor

__all__ = ["Gateway", "GatewayBusy", "NoReadyReplica"]


class GatewayBusy(Exception):
    """Admission control rejected the request (fleet queue bound)."""


class NoReadyReplica(Exception):
    """No READY replica can take the request right now."""


class UnknownPrefix(Exception):
    """The client named a fleet prefix_id that was never registered —
    a CLIENT error (400), never grounds for re-dispatch: every replica
    would reject it identically."""


class Gateway:
    """Routes fleet traffic; owns fleet-level prefix state."""

    def __init__(
        self,
        supervisor: ReplicaSupervisor,
        config: Optional[FleetConfig] = None,
    ):
        self.sup = supervisor
        self.cfg = config or supervisor.cfg
        self._mu = threading.Lock()
        self._inflight: Dict[int, int] = {}  # rid -> proxied now
        self._total_inflight = 0
        self.served = 0
        self.rejected = 0  # 429s
        self.redispatches = 0
        self.routed: Dict[int, int] = {}  # rid -> total routed
        # fleet prefixes: fleet_pid -> token list in LRU order (use
        # touches; register_prefix evicts past cfg.prefix_capacity),
        # and the per-replica registration map (rid, generation,
        # weight_version, fleet_pid) -> replica-local pid
        self._prefixes: "OrderedDict[int, List[int]]" = OrderedDict()
        self._next_prefix_id = 0
        self._replica_pids: Dict[tuple, int] = {}
        # fleet_pid -> in-flight requests referencing it; a referenced
        # prefix is never LRU-evicted mid-request
        self._prefix_refs: Dict[int, int] = {}
        self.prefix_evictions = 0
        self.affinity_hits = 0  # routed to a prefix-warm replica
        self.handoffs = 0  # prefill->decode disaggregated completions
        self.handoff_fallbacks = 0  # handoff failed; direct path served
        self.phases = PhaseAccumulator()
        self._rollout_mu = threading.Lock()
        self.last_rollout: Optional[Dict] = None
        # the supervisor announces every STARTING->READY transition;
        # fresh processes need their prefix registrations replayed
        supervisor.on_ready = self.replay_prefixes
        self._httpd = None
        self._http_thread = None
        self._register_metrics()

    # -- admission + routing --------------------------------------------

    def _admit(self) -> None:
        with self._mu:
            if self._total_inflight >= self.cfg.queue_limit:
                self.rejected += 1
                raise GatewayBusy(
                    f"fleet at queue_limit={self.cfg.queue_limit}"
                )
            self._total_inflight += 1

    def _release(self, rid: Optional[int]) -> None:
        with self._mu:
            self._total_inflight -= 1
            if rid is not None and rid in self._inflight:
                self._inflight[rid] -= 1

    def _pick(
        self, exclude=(), prefix_id: Optional[int] = None,
        role: Optional[str] = None,
    ) -> ReplicaHandle:
        """Least-loaded READY replica (the chaos ``fleet.route`` point
        fires here: an injected error models a routing-layer fault and
        surfaces as 503, not a wedge).

        ``prefix_id`` turns on prefix-affinity: replicas whose last
        health poll reported the request's prefix RESIDENT (registered
        at the replica's current generation/weight version AND present
        in its engine's ``resident_prefixes``) sort ahead of cold ones,
        so a shared prefix keeps hitting the replica already holding
        its KV blocks warm instead of re-prefilling fleet-wide.
        Affinity is a preference, not a pin — a loaded warm replica
        still loses to the least-loaded tiebreak among warm ones, and
        with no warm candidate the pick degrades to plain least-loaded.
        ``role`` restricts candidates to one disaggregation role."""
        faults.inject("fleet.route", exclude=list(exclude))
        candidates = [
            h for h in self.sup.ready_replicas(role=role)
            if h.rid not in exclude
        ]
        if not candidates:
            raise NoReadyReplica(
                f"no READY replica (role={role}, "
                f"excluded: {sorted(exclude)})"
            )
        with self._mu:
            def warm(h: ReplicaHandle) -> bool:
                if prefix_id is None:
                    return False
                rpid = self._replica_pids.get(
                    (h.rid, h.generation, h.weight_version, prefix_id)
                )
                return rpid is not None and rpid in (
                    h.stats.get("resident_prefixes") or ()
                )

            def load(h: ReplicaHandle) -> tuple:
                stats = h.stats
                return (
                    0 if warm(h) else 1,
                    (stats.get("busy_slots") or 0)
                    + (stats.get("queue_depth") or 0)
                    + self._inflight.get(h.rid, 0),
                    # equal load rotates by fewest-ever-routed (plain
                    # round-robin for an idle fleet), then rid
                    self.routed.get(h.rid, 0),
                    h.rid,
                )

            best = min(candidates, key=load)
            if warm(best):
                self.affinity_hits += 1
            self._inflight[best.rid] = (
                self._inflight.get(best.rid, 0) + 1
            )
            self.routed[best.rid] = self.routed.get(best.rid, 0) + 1
        return best

    def _unpin(self, rid: int) -> None:
        with self._mu:
            if rid in self._inflight:
                self._inflight[rid] -= 1

    # -- replica HTTP helpers -------------------------------------------

    def _post_replica(self, h: ReplicaHandle, path: str, payload: Dict,
                      timeout: float):
        req = urllib.request.Request(
            h.url + path,
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())

    def _delete_replica(self, h: ReplicaHandle, path: str, payload: Dict,
                        timeout: float):
        req = urllib.request.Request(
            h.url + path,
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
            method="DELETE",
        )
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())

    # -- prefix fan-out -------------------------------------------------

    def register_prefix(self, tokens: List[int]) -> int:
        """Fleet-level prefix registration: stored once here, replayed
        to replicas. Registration on the replicas is best-effort NOW
        (a dead replica catches up through replay_prefixes on its next
        READY transition) but at least one replica must accept —
        otherwise the client would hold an id nobody can serve."""
        with self._mu:
            pid = self._next_prefix_id
            self._next_prefix_id += 1
            self._prefixes[pid] = list(tokens)
            evicted = self._evict_prefixes_locked()
        self._forget_on_replicas(evicted)
        ok = 0
        last_err: Optional[Exception] = None
        for h in self.sup.ready_replicas():
            try:
                self._ensure_prefix(h, pid)
                ok += 1
            except urllib.error.HTTPError as e:
                if e.code < 500:
                    # a 4xx is the PREFIX being bad (too wide, empty):
                    # every replica would reject it the same way —
                    # forget it and surface the verdict verbatim
                    with self._mu:
                        self._prefixes.pop(pid, None)
                    raise
                last_err = e  # a 5xx is the replica failing, not the prefix
            except Exception as e:  # noqa: BLE001 — replica-side blip
                last_err = e
        if ok == 0:
            with self._mu:
                self._prefixes.pop(pid, None)
            raise NoReadyReplica(
                f"prefix registered on no replica ({last_err!r})"
            )
        return pid

    def _ensure_prefix(self, h: ReplicaHandle, fleet_pid: int) -> int:
        """The replica-local prefix id for ``fleet_pid`` at this
        replica's CURRENT (generation, weight_version) — registering
        on demand. The weight_version key is what makes rollout
        prefix serving version-consistent: the first request after a
        swap re-registers rather than trusting ids minted against the
        old weights."""
        key = (h.rid, h.generation, h.weight_version, fleet_pid)
        with self._mu:
            rpid = self._replica_pids.get(key)
            tokens = self._prefixes.get(fleet_pid)
            if tokens is not None:  # LRU touch: use protects from GC
                self._prefixes.move_to_end(fleet_pid)
        if rpid is not None:
            return rpid
        if tokens is None:
            raise UnknownPrefix(f"unknown fleet prefix_id {fleet_pid}")
        _, out = self._post_replica(
            h, "/v1/prefixes", {"tokens": tokens},
            timeout=self.cfg.request_timeout_s,
        )
        rpid = out["prefix_id"]
        with self._mu:
            self._replica_pids[key] = rpid
        return rpid

    def replay_prefixes(self, h: ReplicaHandle) -> int:
        """Re-register every fleet prefix on ``h`` (READY transitions
        and post-swap rollout calls). Returns how many registered."""
        with self._mu:
            pids = list(self._prefixes)
        n = 0
        for pid in pids:
            try:
                self._ensure_prefix(h, pid)
                n += 1
            except Exception as e:  # noqa: BLE001 — next poll retries
                logger.warning(
                    "fleet prefix %s replay on replica %s failed: %r",
                    pid, h.rid, e,
                )
        return n

    # -- prefix GC ------------------------------------------------------

    def _evict_prefixes_locked(self) -> List[Tuple[int, List[tuple]]]:
        """LRU-evict fleet prefixes past ``cfg.prefix_capacity`` —
        caller holds ``self._mu``. A prefix referenced by an in-flight
        request is skipped this round (its eviction would 409 on every
        replica still decoding it); in-flight references are bounded
        by ``queue_limit``, so the registry stays bounded by
        ``prefix_capacity + queue_limit`` even under pure-prefix load.
        Returns ``(fleet_pid, replica_registrations)`` pairs for the
        out-of-lock replica-side forget fan-out."""
        evicted: List[Tuple[int, List[tuple]]] = []
        if len(self._prefixes) <= self.cfg.prefix_capacity:
            return evicted
        for pid in list(self._prefixes):  # LRU-first iteration order
            if len(self._prefixes) <= self.cfg.prefix_capacity:
                break
            if self._prefix_refs.get(pid):
                continue
            del self._prefixes[pid]
            regs = [k for k in self._replica_pids if k[3] == pid]
            evicted.append(
                (pid, [(k, self._replica_pids.pop(k)) for k in regs])
            )
            self.prefix_evictions += 1
        return evicted

    def _forget_on_replicas(
        self, evicted: List[Tuple[int, List[tuple]]]
    ) -> None:
        """Best-effort replica-side unregistration of evicted/removed
        prefixes — frees the engines' prefix encodings (and, on paged
        replicas, their shared KV blocks). Failures are fine: a
        replica that missed the delete just holds a dead replica-local
        pid until its engine's own idle-prefix eviction or the next
        weight swap clears it."""
        ready = {h.rid: h for h in self.sup.ready_replicas()}
        for _fleet_pid, regs in evicted:
            for (rid, gen, wv, _pid), rpid in regs:
                h = ready.get(rid)
                if h is None or h.generation != gen or (
                    h.weight_version != wv
                ):
                    continue  # that registration's engine state is gone
                try:
                    self._delete_replica(
                        h, "/v1/prefixes", {"prefix_id": rpid},
                        timeout=self.cfg.request_timeout_s,
                    )
                except Exception as e:  # noqa: BLE001 — best-effort
                    logger.debug(
                        "fleet prefix forget on replica %s failed: %r",
                        rid, e,
                    )

    def unregister_prefix(self, fleet_pid: int) -> None:
        """Drop a fleet prefix (``DELETE /v1/prefixes``). Raises
        KeyError for an unknown id and ValueError while in-flight
        requests still reference it (the client retries after they
        drain). Replica-side forget is best-effort fan-out."""
        with self._mu:
            if fleet_pid not in self._prefixes:
                raise KeyError(f"unknown fleet prefix_id {fleet_pid}")
            if self._prefix_refs.get(fleet_pid):
                raise ValueError(
                    f"fleet prefix_id {fleet_pid} is referenced by "
                    f"{self._prefix_refs[fleet_pid]} in-flight request(s)"
                )
            del self._prefixes[fleet_pid]
            regs = [k for k in self._replica_pids if k[3] == fleet_pid]
            pairs = [(k, self._replica_pids.pop(k)) for k in regs]
        self._forget_on_replicas([(fleet_pid, pairs)])

    # -- prefill/decode disaggregation ----------------------------------

    def _decode_role(self) -> Optional[str]:
        """The role filter for completion routing: ``"decode"`` in a
        disaggregated fleet (prefill replicas are reserved for
        ``/v1/prefill`` work), None otherwise."""
        return "decode" if self.cfg.prefill_replicas > 0 else None

    def _maybe_disaggregate(self, body: Dict) -> Dict:
        """Prefill/decode handoff: in a disaggregated fleet, a long
        enough plain-prompt completion is prefilled on a PREFILL
        replica (``/v1/prefill`` fills one row and exports its KV
        state), then the request body is rewritten to the
        ``prefilled`` form a decode replica finishes without touching
        its own prefill program. Prefix-id requests skip handoff —
        their prefill is already amortized by the decode replica's
        prefix cache. Any handoff failure (no prefill replica, replica
        error, or the chaos ``prefill.handoff`` point dropping the
        payload) falls back to the direct path: the decode replica
        prefills the prompt itself — slower, never an error."""
        prompt = body.get("prompt")
        if (
            self.cfg.prefill_replicas <= 0
            or "prefilled" in body
            or body.get("prefix_id") is not None
            or not isinstance(prompt, list)
            or len(prompt) < max(1, self.cfg.disagg_min_prompt)
        ):
            return body
        ph = None
        try:
            mode = faults.inject(
                "prefill.handoff", prompt_len=len(prompt)
            )
            if mode == "drop":
                raise ConnectionError("prefill handoff dropped (chaos)")
            ph = self._pick(role="prefill")
            _, out = self._post_replica(
                ph, "/v1/prefill", {"tokens": prompt},
                timeout=self.cfg.request_timeout_s,
            )
        except urllib.error.HTTPError as e:
            if e.code < 500:
                raise  # the prompt itself is bad: verdict stands
            with self._mu:
                self.handoff_fallbacks += 1
            logger.warning(
                "fleet prefill handoff failed (HTTP %s); direct path",
                e.code,
            )
            return body
        except Exception as e:  # noqa: BLE001 — chaos drop, dead replica
            with self._mu:
                self.handoff_fallbacks += 1
            logger.warning(
                "fleet prefill handoff failed (%r); direct path", e
            )
            return body
        finally:
            if ph is not None:
                self._unpin(ph.rid)
        with self._mu:
            self.handoffs += 1
        handed = dict(body)
        handed.pop("prompt", None)
        handed["prefilled"] = out["prefilled"]
        return handed

    # -- completions ----------------------------------------------------

    def complete(self, body: Dict) -> Dict:
        """Route one NON-streamed completion; re-dispatch on replica
        death. Raises GatewayBusy (429), NoReadyReplica (503),
        UnknownPrefix (400), urllib.error.HTTPError (replica's own
        4xx, forwarded)."""
        self._admit()
        rid = None
        pid_ref = self._ref_prefix(body.get("prefix_id"))
        try:
            body = self._maybe_disaggregate(body)
            tried: set = set()
            t0 = time.perf_counter()
            while True:
                h = self._pick(
                    exclude=tried, prefix_id=pid_ref,
                    role=self._decode_role(),
                )
                rid = h.rid
                t1 = time.perf_counter()
                self.phases.add("route", t1 - t0)
                try:
                    payload = self._translate(h, body)
                    _, out = self._post_replica(
                        h, "/v1/completions", payload,
                        timeout=self.cfg.request_timeout_s,
                    )
                    self.phases.add("proxy", time.perf_counter() - t1)
                    self.phases.rounds += 1
                    with self._mu:
                        self.served += 1
                    out["replica"] = h.rid
                    return out
                except UnknownPrefix:
                    # the client's own bad prefix_id: every replica
                    # would reject it identically — never a re-dispatch
                    self._unpin(h.rid)
                    rid = None
                    raise
                except urllib.error.HTTPError as e:
                    if e.code < 500:
                        self.phases.add(
                            "proxy", time.perf_counter() - t1
                        )
                        raise  # the client's own error: verdict stands
                    # 5xx: the replica is failing, not the request —
                    # fall through to the re-dispatch path
                    self.phases.add("proxy", time.perf_counter() - t1)
                    t0 = time.perf_counter()
                    tried.add(h.rid)
                    self._unpin(h.rid)
                    rid = None
                    with self._mu:
                        self.redispatches += 1
                    logger.warning(
                        "fleet re-dispatching off replica %s "
                        "(HTTP %s)", h.rid, e.code,
                    )
                    self.phases.add(
                        "redispatch", time.perf_counter() - t0
                    )
                    continue
                except Exception as e:  # noqa: BLE001 — replica died mid-flight
                    self.phases.add("proxy", time.perf_counter() - t1)
                    t0 = time.perf_counter()
                    tried.add(h.rid)
                    self._unpin(h.rid)
                    rid = None
                    with self._mu:
                        self.redispatches += 1
                    logger.warning(
                        "fleet re-dispatching off replica %s: %r",
                        h.rid, e,
                    )
                    self.phases.add(
                        "redispatch", time.perf_counter() - t0
                    )
        finally:
            self._unref_prefix(pid_ref)
            self._release(rid)

    def _ref_prefix(self, pid) -> Optional[int]:
        """Pin a fleet prefix for a request's lifetime (LRU eviction
        skips referenced pids). Unknown/malformed ids pass through —
        the routing path raises UnknownPrefix with its usual 400."""
        if pid is None or isinstance(pid, bool) or not isinstance(
            pid, int
        ):
            return None
        with self._mu:
            self._prefix_refs[pid] = self._prefix_refs.get(pid, 0) + 1
        return pid

    def _unref_prefix(self, pid: Optional[int]) -> None:
        if pid is None:
            return
        with self._mu:
            n = self._prefix_refs.get(pid, 0) - 1
            if n > 0:
                self._prefix_refs[pid] = n
            else:
                self._prefix_refs.pop(pid, None)

    def _translate(self, h: ReplicaHandle, body: Dict) -> Dict:
        """Client payload -> replica payload (fleet prefix id -> the
        replica-local id at its current generation/weight version)."""
        payload = dict(body)
        pid = payload.get("prefix_id")
        if pid is not None:
            payload["prefix_id"] = self._ensure_prefix(h, int(pid))
        return payload

    # -- status ----------------------------------------------------------

    def _kv_aggregate(self) -> Dict[str, Optional[int]]:
        """Fleet-wide paged-KV occupancy summed over the READY
        replicas' last health polls. ``blocks_total`` None means no
        replica runs the paged layout (dense fleets report the
        prefix-hit counter alone)."""
        totals = {"blocks_total": 0, "blocks_free": 0,
                  "prefix_hits": 0, "alloc_failures": 0}
        paged = 0
        for h in self.sup.ready_replicas():
            stats = h.stats
            totals["prefix_hits"] += int(stats.get("prefix_hits") or 0)
            totals["alloc_failures"] += int(
                stats.get("alloc_failures") or 0
            )
            if stats.get("blocks_total") is not None:
                paged += 1
                totals["blocks_total"] += int(stats["blocks_total"])
                totals["blocks_free"] += int(
                    stats.get("blocks_free") or 0
                )
        if paged == 0:
            totals["blocks_total"] = None
            totals["blocks_free"] = None
        return totals

    def status(self) -> Dict:
        sup = self.sup.status()
        kv = self._kv_aggregate()
        with self._mu:
            gw = {
                "inflight": self._total_inflight,
                "served": self.served,
                "rejected": self.rejected,
                "redispatches": self.redispatches,
                "routed": dict(self.routed),
                "queue_limit": self.cfg.queue_limit,
                "prefixes": len(self._prefixes),
                "prefix_capacity": self.cfg.prefix_capacity,
                "prefix_evictions": self.prefix_evictions,
                "affinity_hits": self.affinity_hits,
                "handoffs": self.handoffs,
                "handoff_fallbacks": self.handoff_fallbacks,
            }
        return {
            **sup,
            "gateway": gw,
            "kv": kv,
            "phase_split": self.phases.split().summary(),
            "rollout": self.last_rollout,
        }

    def _register_metrics(self) -> None:
        """Bind gateway+fleet KV series into the unified metrics
        registry (render-time callbacks, the PR 12 idiom): paged block
        occupancy, prefix-hit/affinity counters, and per-role READY
        counts land on the same ``/metrics`` page as everything
        else."""
        from ..observability.metrics import get_registry

        registry = get_registry()
        registry.gauge_fn(
            "dlrover_fleet_inflight",
            lambda: float(self._total_inflight),
        )
        registry.gauge_fn(
            "dlrover_fleet_prefixes",
            lambda: float(len(self._prefixes)),
        )
        registry.gauge_fn(
            "dlrover_fleet_prefix_evictions",
            lambda: float(self.prefix_evictions),
        )
        registry.gauge_fn(
            "dlrover_fleet_affinity_hits",
            lambda: float(self.affinity_hits),
        )
        registry.gauge_fn(
            "dlrover_fleet_handoffs", lambda: float(self.handoffs)
        )
        registry.gauge_fn(
            "dlrover_fleet_handoff_fallbacks",
            lambda: float(self.handoff_fallbacks),
        )

        def _fleet_gauges() -> Dict[str, float]:
            flat: Dict[str, float] = {}
            kv = self._kv_aggregate()
            for key, val in kv.items():
                if val is not None:
                    flat[f"dlrover_fleet_kv_{key}"] = float(val)
            for role in ("prefill", "decode"):
                flat[f'dlrover_fleet_ready{{role="{role}"}}'] = float(
                    len(self.sup.ready_replicas(role=role))
                )
            return flat

        registry.collector(_fleet_gauges)

    # -- HTTP front end ---------------------------------------------------

    def serve(self, port: int = 0) -> ThreadingHTTPServer:
        """Bind the gateway's HTTP server (caller runs serve_forever,
        or use start_http for a daemon thread)."""
        self._httpd = ThreadingHTTPServer(
            ("0.0.0.0", port), _make_handler(self)
        )
        return self._httpd

    def start_http(self, port: int = 0) -> int:
        httpd = self.serve(port)
        self._http_thread = threading.Thread(
            target=httpd.serve_forever, name="fleet-gateway", daemon=True
        )
        self._http_thread.start()
        return httpd.server_address[1]

    def stop_http(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            if self._http_thread is not None:
                self._http_thread.join(timeout=10)


def _http_error_detail(e: "urllib.error.HTTPError") -> Dict:
    """The replica's own JSON verdict, or a synthesized one when the
    error body is unreadable/not JSON (the synthesized detail keeps
    the parse failure — an opaque 502 was PR 7's route-drift blind
    spot)."""
    try:
        return json.loads(e.read())
    except Exception as body_err:  # noqa: BLE001 — verdict body optional
        return {"error": str(e), "detail_unreadable": repr(body_err)[:200]}


def _make_handler(gw: Gateway):
    from ..common.http import JsonRequestHandler

    class Handler(JsonRequestHandler):
        def log_message(self, fmt, *args):
            logger.debug("fleet-gw: " + fmt, *args)

        def do_GET(self):
            if self.path in ("/fleet/status", "/healthz"):
                self._send(200, gw.status())
            else:
                self._send(404, {"error": f"unknown path {self.path}"})

        def do_POST(self):
            try:
                body = self._body()
            except ValueError as e:
                self._send(400, {"error": f"bad json: {e}"})
                return
            if self.path == "/v1/completions":
                if body.get("stream"):
                    self._stream(body)
                else:
                    self._complete(body)
            elif self.path == "/v1/prefixes":
                self._prefixes(body)
            elif self.path == "/fleet/rollout":
                self._rollout(body)
            elif self.path == "/fleet/scale":
                self._scale(body)
            else:
                self._send(404, {"error": f"unknown path {self.path}"})

        def do_DELETE(self):
            if self.path != "/v1/prefixes":
                self._send(404, {"error": f"unknown path {self.path}"})
                return
            try:
                body = self._body()
            except ValueError as e:
                self._send(400, {"error": f"bad json: {e}"})
                return
            pid = body.get("prefix_id")
            if not isinstance(pid, int) or isinstance(pid, bool):
                self._send(400, {"error": "prefix_id must be an int"})
                return
            try:
                gw.unregister_prefix(pid)
            except KeyError as e:
                self._send(404, {"error": str(e)})
                return
            except ValueError as e:
                # referenced by in-flight requests: retryable conflict
                self._send(409, {"error": str(e)})
                return
            except Exception as e:  # noqa: BLE001
                self._send(500, {"error": repr(e)[:200]})
                return
            self._send(200, {"removed": pid})

        # -- route handlers ------------------------------------------

        def _complete(self, body):
            try:
                out = gw.complete(body)
            except GatewayBusy as e:
                self._send(
                    429,
                    {"error": str(e)},
                    headers=(
                        ("Retry-After", str(gw.cfg.retry_after_s)),
                    ),
                )
                return
            except NoReadyReplica as e:
                self._send(503, {"error": str(e)})
                return
            except UnknownPrefix as e:
                self._send(400, {"error": str(e)})
                return
            except urllib.error.HTTPError as e:
                # the replica's own verdict (400 bad prompt, ...)
                self._send(e.code, _http_error_detail(e))
                return
            except Exception as e:  # noqa: BLE001
                self._send(500, {"error": repr(e)[:200]})
                return
            self._send(200, out)

        def _stream(self, body):
            """Pinned streaming proxy: relay the replica's chunked
            NDJSON. A replica death mid-stream breaks the relay — the
            client sees a truncated stream and re-submits (pinning
            contract; the KV died with the replica)."""
            try:
                gw._admit()
            except GatewayBusy as e:
                self._send(
                    429,
                    {"error": str(e)},
                    headers=(
                        ("Retry-After", str(gw.cfg.retry_after_s)),
                    ),
                )
                return
            rid = None
            pid_ref = gw._ref_prefix(body.get("prefix_id"))
            try:
                try:
                    h = gw._pick(
                        prefix_id=pid_ref, role=gw._decode_role()
                    )
                    rid = h.rid
                    payload = gw._translate(h, body)
                except NoReadyReplica as e:
                    self._send(503, {"error": str(e)})
                    return
                except UnknownPrefix as e:
                    self._send(400, {"error": str(e)})
                    return
                except urllib.error.HTTPError as e:
                    # on-demand prefix registration got the replica's
                    # verdict — forward it, don't drop the socket
                    self._send(e.code, _http_error_detail(e))
                    return
                except Exception as e:  # noqa: BLE001
                    self._send(503, {"error": repr(e)[:200]})
                    return
                req = urllib.request.Request(
                    h.url + "/v1/completions",
                    data=json.dumps(payload).encode(),
                    headers={"Content-Type": "application/json"},
                    method="POST",
                )
                try:
                    upstream = urllib.request.urlopen(
                        req, timeout=gw.cfg.request_timeout_s
                    )
                except urllib.error.HTTPError as e:
                    self._send(e.code, _http_error_detail(e))
                    return
                except Exception as e:  # noqa: BLE001
                    self._send(503, {"error": repr(e)[:200]})
                    return
                with upstream:
                    self.send_response(200)
                    self.send_header(
                        "Content-Type", "application/x-ndjson"
                    )
                    self.send_header("Transfer-Encoding", "chunked")
                    self.send_header("X-Fleet-Replica", str(h.rid))
                    self.end_headers()
                    try:
                        while True:
                            line = upstream.readline()
                            if not line:
                                break
                            self.wfile.write(
                                f"{len(line):x}\r\n".encode()
                            )
                            self.wfile.write(line + b"\r\n")
                            self.wfile.flush()
                        self.wfile.write(b"0\r\n\r\n")
                        with gw._mu:
                            gw.served += 1
                    except OSError:
                        # client or replica hung up mid-relay: the
                        # stream dies (pinned), nothing to clean here —
                        # the replica's own disconnect handling cancels
                        # the engine request
                        pass
            finally:
                gw._unref_prefix(pid_ref)
                gw._release(rid)

        def _prefixes(self, body):
            tokens = body.get("tokens")
            if not isinstance(tokens, list) or not all(
                isinstance(t, int) for t in tokens
            ):
                self._send(
                    400, {"error": "tokens must be a list of token ids"}
                )
                return
            try:
                pid = gw.register_prefix(tokens)
            except urllib.error.HTTPError as e:
                self._send(e.code, _http_error_detail(e))
                return
            except NoReadyReplica as e:
                self._send(503, {"error": str(e)})
                return
            except Exception as e:  # noqa: BLE001
                self._send(500, {"error": repr(e)[:200]})
                return
            self._send(200, {"prefix_id": pid})

        def _rollout(self, body):
            from .rollout import staged_rollout

            if not gw._rollout_mu.acquire(blocking=False):
                self._send(409, {"error": "rollout already running"})
                return
            if body.get("wait"):
                try:
                    report = staged_rollout(gw.sup, gw)
                finally:
                    gw._rollout_mu.release()
                self._send(200, report)
                return

            def run_and_release():
                try:
                    staged_rollout(gw.sup, gw)
                finally:
                    gw._rollout_mu.release()

            threading.Thread(
                target=run_and_release, name="fleet-rollout", daemon=True
            ).start()
            self._send(202, {"started": True})

        def _scale(self, body):
            n = body.get("replicas")
            if not isinstance(n, int) or isinstance(n, bool):
                self._send(400, {"error": "replicas must be an int"})
                return
            self._send(200, {"replicas": gw.sup.scale_to(n)})

    return Handler
