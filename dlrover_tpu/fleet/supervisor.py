"""ReplicaSupervisor: N supervised serving replicas, health-driven.

The serving-side twin of the training agent's worker supervision: where
the agent restarts a crashed JAX worker through a restart budget and
reports to the master, the supervisor drives each serving replica
through a STARTING→READY→DRAINING→DEAD state machine off ``/healthz``
polls and relaunches dead replicas under a per-slot relaunch budget
with exponential backoff. A replica death is a capacity dip, never an
outage: the gateway routes around anything not READY.

State machine (docs/serving_fleet.md)::

    STARTING --healthz 200--> READY <--readmit-- DRAINING
       |  ^                     |                   |
       |  | relaunch            | health_fails      | health_fails
       v  | (budget+backoff)    v                   v
      DEAD <-------------------DEAD <--------------DEAD

- STARTING: process launched, engine still compiling/restoring; a
  replica stuck past ``start_timeout_s`` is declared dead.
- READY: polls healthy — the ONLY state the gateway routes to.
- DRAINING: deliberately out of rotation (staged rollout, scale-down);
  still polled, still serving its in-flight requests.
- DEAD: process gone or ``health_fails`` consecutive poll failures;
  relaunched while the slot's budget lasts, else left dead (the fleet
  degrades to the surviving replicas — mirroring the agent's
  budget-exhausted RELAUNCH_REQUESTED path, not a crash loop).

Locking discipline: ``_mu`` guards the handle table only; every poll,
kill, spawn, and callback runs outside it (snapshot-under-lock /
act-outside — the PodScaler incident class).
"""

import json
import socket
import threading
import time
import urllib.request
from typing import Callable, Dict, List, Optional

from ..chaos import faults
from ..common.log import logger
from .config import FleetConfig

__all__ = ["ReplicaState", "ReplicaHandle", "ReplicaSupervisor"]


class ReplicaState:
    STARTING = "starting"
    READY = "ready"
    DRAINING = "draining"
    DEAD = "dead"


def free_port() -> int:
    """A currently-free TCP port (bind(0) probe). Inherently racy —
    the supervisor treats a failed bind as a normal replica death and
    relaunches on a fresh port."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class ReplicaHandle:
    """Supervisor-side bookkeeping for one replica slot."""

    def __init__(self, rid: int, proc, role: str = "decode"):
        self.rid = rid
        self.proc = proc
        self.role = role  # prefill/decode disaggregation role
        self.state = ReplicaState.STARTING
        self.state_since = time.monotonic()
        self.generation = 0  # bumps every (re)launch
        self.weight_version = 0  # bumps per adopted rollout swap
        self.relaunches = 0
        self.consecutive_fails = 0
        self.next_launch_t = 0.0  # backoff gate for the next relaunch
        self.stats: Dict = {}  # last /healthz payload
        self.last_error: Optional[str] = None

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.proc.port}"

    def set_state(self, state: str) -> None:
        if state != self.state:
            self.state = state
            self.state_since = time.monotonic()

    def snapshot(self) -> Dict:
        return {
            "rid": self.rid,
            "state": self.state,
            "role": self.role,
            "port": self.proc.port,
            "pid": self.proc.pid,
            "generation": self.generation,
            "weight_version": self.weight_version,
            "relaunches": self.relaunches,
            "busy_slots": self.stats.get("busy_slots"),
            "queue_depth": self.stats.get("queue_depth"),
            "latency_p95_s": self.stats.get("latency_p95_s"),
            "tokens_per_s": self.stats.get("tokens_per_s"),
            "last_error": self.last_error,
        }


class ReplicaSupervisor:
    """Spawns and supervises N replicas through a replica factory.

    ``factory(rid, port)`` returns a replica process object
    (fleet/replica.py protocol). ``on_ready(handle)`` fires from the
    monitor thread every time a replica TRANSITIONS to READY — the
    gateway hooks it to replay prefix registrations onto fresh
    processes (engine prefix state dies with a replica)."""

    # relaunch backoff: base * 2^(n-1), capped — the agent's
    # restart-budget idiom (bounded retries, growing spacing)
    BACKOFF_BASE_S = 0.5
    BACKOFF_CAP_S = 10.0

    def __init__(
        self,
        factory: Callable[[int, int], object],
        config: Optional[FleetConfig] = None,
        on_ready: Optional[Callable] = None,
    ):
        self._factory = factory
        self.cfg = config or FleetConfig.from_env()
        self.on_ready = on_ready
        self._mu = threading.Lock()
        self._handles: Dict[int, ReplicaHandle] = {}
        self._next_rid = 0
        self._stop = threading.Event()
        self._monitor: Optional[threading.Thread] = None

    # -- lifecycle ------------------------------------------------------

    def start(self) -> "ReplicaSupervisor":
        for _ in range(self.cfg.replicas):
            self._spawn_slot()
        self._monitor = threading.Thread(
            target=self._monitor_loop, name="fleet-monitor", daemon=True
        )
        self._monitor.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=30)
        for h in self.replicas():
            try:
                h.proc.terminate()
            except Exception as e:  # noqa: BLE001 — best-effort teardown
                logger.warning("fleet replica %s teardown: %r", h.rid, e)

    def role_of(self, rid: int) -> str:
        """Disaggregation role of a slot: the LOWEST rids run prefill
        (``cfg.prefill_replicas`` of them). Rid-derived so a relaunch
        keeps the role and autoscaler growth (fresh, higher rids)
        always adds decode capacity."""
        return (
            "prefill" if rid < self.cfg.prefill_replicas else "decode"
        )

    def _spawn_slot(self) -> ReplicaHandle:
        with self._mu:
            rid = self._next_rid
            self._next_rid += 1
        proc = self._factory(rid, free_port())
        handle = ReplicaHandle(rid, proc, role=self.role_of(rid))
        try:
            proc.start()
        except Exception as e:  # noqa: BLE001 — a bad spawn is a death
            handle.last_error = repr(e)[:200]
            handle.set_state(ReplicaState.DEAD)
            logger.error("fleet replica %s failed to spawn: %r", rid, e)
        with self._mu:
            self._handles[rid] = handle
        return handle

    # -- views ----------------------------------------------------------

    def replicas(self) -> List[ReplicaHandle]:
        with self._mu:
            return list(self._handles.values())

    def ready_replicas(
        self, role: Optional[str] = None
    ) -> List[ReplicaHandle]:
        return [
            h for h in self.replicas()
            if h.state == ReplicaState.READY
            and (role is None or h.role == role)
        ]

    def get(self, rid: int) -> Optional[ReplicaHandle]:
        with self._mu:
            return self._handles.get(rid)

    def status(self) -> Dict:
        reps = self.replicas()
        ready = [h for h in reps if h.state == ReplicaState.READY]
        return {
            "replicas": [h.snapshot() for h in reps],
            "ready": len(ready),
            # per-role counts: the disaggregation topology's health at
            # a glance (and the autoscaler/brain admission signal)
            "ready_prefill": sum(
                1 for h in ready if h.role == "prefill"
            ),
            "ready_decode": sum(
                1 for h in ready if h.role == "decode"
            ),
            "target": len(reps),
        }

    def wait_ready(self, n: Optional[int] = None,
                   timeout: float = 120.0) -> bool:
        """Block until ``n`` (default: every slot) replicas are READY."""
        want = len(self.replicas()) if n is None else n
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if len(self.ready_replicas()) >= want:
                return True
            if self._stop.is_set():
                return False
            time.sleep(0.05)
        return False

    # -- control surface ------------------------------------------------

    def drain(self, rid: int) -> bool:
        """Take a READY replica out of rotation (it keeps serving its
        in-flight work; the gateway stops routing to it)."""
        h = self.get(rid)
        if h is None or h.state != ReplicaState.READY:
            return False
        h.set_state(ReplicaState.DRAINING)
        return True

    def readmit(self, rid: int) -> bool:
        """Return a DRAINING replica to rotation."""
        h = self.get(rid)
        if h is None or h.state != ReplicaState.DRAINING:
            return False
        h.set_state(ReplicaState.READY)
        return True

    def kill_replica(self, rid: int) -> bool:
        """Hard-kill one replica (chaos drills, scale-down of a wedged
        member). The monitor detects the death and relaunches under
        the normal budget — this is an induced fault, not a removal."""
        h = self.get(rid)
        if h is None:
            return False
        faults.inject("fleet.replica_kill", replica=rid, state=h.state)
        h.proc.kill()
        return True

    def remove_replica(
        self, rid: int, drain_timeout_s: Optional[float] = None
    ) -> bool:
        """Scale-down removal: DRAIN (out of rotation, in-flight work
        finishes), then terminate and forget the slot (no relaunch —
        unlike kill_replica this shrinks N). A voluntary shrink must
        not truncate live streams; the drain is bounded by
        ``drain_timeout_s`` (default: config) and the replica is
        terminated regardless at the deadline."""
        h = self.get(rid)
        if h is None:
            return False
        h.set_state(ReplicaState.DRAINING)
        deadline = time.monotonic() + (
            self.cfg.drain_timeout_s
            if drain_timeout_s is None
            else drain_timeout_s
        )
        while time.monotonic() < deadline and not self._stop.is_set():
            try:
                with urllib.request.urlopen(
                    h.url + "/healthz",
                    timeout=self.cfg.health_timeout_s,
                ) as r:
                    stats = json.loads(r.read())
            except Exception as e:  # noqa: BLE001 — dead already: just reap
                logger.debug("drain poll of %s ended: %r", h.rid, e)
                break
            if (
                stats.get("busy_slots") == 0
                and stats.get("queue_depth") == 0
                and not stats.get("inflight_chunks")
            ):
                break
            time.sleep(0.05)
        with self._mu:
            self._handles.pop(rid, None)
        h.proc.terminate()
        return True

    def scale_to(self, n: int) -> int:
        """Grow/shrink toward ``n`` live slots within config bounds.
        Shrink picks the highest-rid replicas (newest first) so the
        fleet's stable core keeps its warmed caches."""
        n = max(self.cfg.min_replicas, min(n, self.cfg.max_replicas))
        current = self.replicas()
        if n > len(current):
            for _ in range(n - len(current)):
                self._spawn_slot()
        elif n < len(current):
            for h in sorted(current, key=lambda h: -h.rid)[
                : len(current) - n
            ]:
                self.remove_replica(h.rid)
        return n

    # -- monitor thread --------------------------------------------------

    def _monitor_loop(self) -> None:
        while not self._stop.is_set():
            for h in self.replicas():
                if self._stop.is_set():
                    break
                try:
                    self._poll_one(h)
                except Exception as e:  # noqa: BLE001 — monitor survives
                    logger.exception(
                        "fleet monitor error on replica %s: %s", h.rid, e
                    )
            self._stop.wait(self.cfg.health_interval_s)

    def _poll_one(self, h: ReplicaHandle) -> None:
        if h.state == ReplicaState.DEAD:
            self._maybe_relaunch(h)
            return
        if not h.proc.alive():
            self._declare_dead(h, "process exited")
            return
        try:
            # chaos hook: error mode models a health endpoint that
            # answers garbage / refuses; delay models a slow poll
            faults.inject(
                "fleet.replica_health", replica=h.rid, state=h.state
            )
            with urllib.request.urlopen(
                h.url + "/healthz", timeout=self.cfg.health_timeout_s
            ) as r:
                stats = json.loads(r.read())
        except Exception as e:  # noqa: BLE001 — one failed poll
            h.consecutive_fails += 1
            h.last_error = repr(e)[:200]
            if h.state == ReplicaState.STARTING:
                if (
                    time.monotonic() - h.state_since
                    > self.cfg.start_timeout_s
                ):
                    self._declare_dead(h, "start timeout")
            elif h.consecutive_fails >= self.cfg.health_fails:
                self._declare_dead(
                    h, f"{h.consecutive_fails} failed health polls"
                )
            return
        h.consecutive_fails = 0
        h.stats = stats
        if h.state == ReplicaState.STARTING:
            h.set_state(ReplicaState.READY)
            logger.info(
                "fleet replica %s READY on port %s (gen %s)",
                h.rid, h.proc.port, h.generation,
            )
            self._fire_ready(h)

    def _fire_ready(self, h: ReplicaHandle) -> None:
        if self.on_ready is None:
            return
        try:
            self.on_ready(h)
        except Exception as e:  # noqa: BLE001 — callback must not kill monitor
            logger.exception("fleet on_ready(%s) failed: %s", h.rid, e)

    def _declare_dead(self, h: ReplicaHandle, why: str) -> None:
        logger.error("fleet replica %s dead: %s", h.rid, why)
        h.last_error = why
        h.set_state(ReplicaState.DEAD)
        h.stats = {}
        h.proc.kill()  # reap whatever is left
        if h.relaunches < self.cfg.relaunch_budget:
            backoff = min(
                self.BACKOFF_BASE_S * (2 ** h.relaunches),
                self.BACKOFF_CAP_S,
            )
            h.next_launch_t = time.monotonic() + backoff
        else:
            h.next_launch_t = float("inf")
            logger.error(
                "fleet replica %s: relaunch budget (%s) exhausted — "
                "slot stays dead, fleet degraded",
                h.rid, self.cfg.relaunch_budget,
            )

    def _maybe_relaunch(self, h: ReplicaHandle) -> None:
        if time.monotonic() < h.next_launch_t:
            return
        h.relaunches += 1
        h.generation += 1
        h.consecutive_fails = 0
        proc = self._factory(h.rid, free_port())
        try:
            proc.start()
        except Exception as e:  # noqa: BLE001 — spawn failed: stay dead
            h.last_error = repr(e)[:200]
            self._declare_dead(h, f"relaunch spawn failed: {e!r}")
            return
        h.proc = proc
        h.set_state(ReplicaState.STARTING)
        logger.info(
            "fleet replica %s relaunched (gen %s, %s/%s budget) on "
            "port %s",
            h.rid, h.generation, h.relaunches,
            self.cfg.relaunch_budget, proc.port,
        )
