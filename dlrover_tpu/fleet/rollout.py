"""Staged zero-downtime weight rollout: drain → swap → readmit, one
replica at a time.

A checkpoint push to a serving fleet must never be an outage. The
rollout walks the READY replicas in rid order and, for each one:

1. **drain** — the gateway stops routing to it; the rollout waits for
   its in-flight work to retire (``busy_slots == 0``, empty queue, no
   in-flight pipeline chunk, no gateway proxy still open against it),
   bounded by ``drain_timeout_s``.
2. **swap** — ``POST /v1/weights/reload`` on the replica: the engine
   restores the new checkpoint and hot-swaps between chunks. A swap
   failure rides the engine's existing abort path (``swap_failures`` /
   ``last_swap_error`` — old weights keep serving), so rollback here
   is simply *readmitting the un-swapped replica* and aborting the
   rollout: the fleet keeps serving the old version at full strength.
3. **re-register prefixes** — the replica's ``weight_version`` bumps,
   which invalidates the gateway's (generation, weight_version) prefix
   map; the gateway re-registers every fleet prefix so prefix requests
   are version-consistent from the first post-swap completion (the
   engine itself already refuses to serve a stale prefix KV encoding —
   re-registration keeps the *ids* honest too).
4. **readmit** — back to READY; only then does the next replica drain.

Invariant: at most ONE replica is out of rotation at any instant, so a
rollout never takes the fleet below N−1 READY replicas; the report's
``max_unready`` proves it per run (bench: ``fleet_rollout_max_unready``).
"""

import json
import time
import urllib.request
from typing import Dict, Optional

from ..common.log import logger
from .supervisor import ReplicaState

__all__ = ["staged_rollout"]


def _replica_stats(h, timeout: float) -> Dict:
    """A FRESH /healthz snapshot (the supervisor's poll cache can lag
    a health interval — drain decisions need the live counters)."""
    with urllib.request.urlopen(
        h.url + "/healthz", timeout=timeout
    ) as r:
        return json.loads(r.read())


def _gateway_inflight(gateway, rid: int) -> int:
    with gateway._mu:
        return gateway._inflight.get(rid, 0)


def staged_rollout(
    supervisor,
    gateway,
    swap_async: bool = False,
    drain_timeout_s: Optional[float] = None,
) -> Dict:
    cfg = supervisor.cfg
    drain_timeout_s = (
        cfg.drain_timeout_s if drain_timeout_s is None else drain_timeout_s
    )
    targets = sorted(supervisor.ready_replicas(), key=lambda h: h.rid)
    report: Dict = {
        "replicas": [],
        "target_count": len(targets),
        "aborted": False,
        "max_unready": 0,
        "steps": [],
    }

    def sample_unready():
        reps = supervisor.replicas()
        unready = sum(
            1 for h in reps if h.state != ReplicaState.READY
        )
        report["max_unready"] = max(report["max_unready"], unready)

    for h in targets:
        entry: Dict = {"rid": h.rid, "generation": h.generation}
        report["replicas"].append(entry)
        if h.state != ReplicaState.READY:
            # died (or was drained by someone else) since the snapshot:
            # the supervisor owns its recovery; skip, don't abort — the
            # rollout's job is the replicas that ARE serving
            entry["skipped"] = h.state
            continue
        t0 = time.perf_counter()
        supervisor.drain(h.rid)
        sample_unready()

        # 1. wait for the replica to finish its in-flight work
        deadline = time.monotonic() + drain_timeout_s
        drained = False
        while time.monotonic() < deadline:
            sample_unready()
            try:
                stats = _replica_stats(h, cfg.health_timeout_s)
            except Exception as e:  # noqa: BLE001 — replica died mid-drain
                entry["error"] = f"died during drain: {e!r}"
                break
            if (
                stats.get("busy_slots") == 0
                and stats.get("queue_depth") == 0
                and not stats.get("inflight_chunks")
                and _gateway_inflight(gateway, h.rid) == 0
            ):
                drained = True
                break
            time.sleep(0.05)
        entry["drain_s"] = round(time.perf_counter() - t0, 3)
        if not drained:
            entry.setdefault("error", "drain timeout")
            supervisor.readmit(h.rid)
            report["aborted"] = True
            logger.error(
                "fleet rollout aborted at replica %s: %s",
                h.rid, entry["error"],
            )
            break

        # 2. swap — failure rolls back to the old weights (the engine
        #    aborts the swap itself via its swap_failures path; we just
        #    put the un-swapped replica back into rotation)
        t1 = time.perf_counter()
        failures_before = int(stats.get("swap_failures") or 0)
        try:
            req = urllib.request.Request(
                h.url + "/v1/weights/reload",
                data=json.dumps({"async": bool(swap_async)}).encode(),
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            with urllib.request.urlopen(
                req, timeout=cfg.request_timeout_s
            ) as r:
                out = json.loads(r.read())
            if swap_async:
                # adoption lands at a later drain point; wait it out so
                # an in-flight transfer failure still aborts THIS stage
                adopt_deadline = time.monotonic() + cfg.request_timeout_s
                while time.monotonic() < adopt_deadline:
                    stats = _replica_stats(h, cfg.health_timeout_s)
                    if not stats.get("swap_pending"):
                        break
                    time.sleep(0.05)
            else:
                stats = _replica_stats(h, cfg.health_timeout_s)
            if int(stats.get("swap_failures") or 0) > failures_before:
                raise RuntimeError(
                    f"engine aborted the swap: "
                    f"{stats.get('last_swap_error')}"
                )
        except Exception as e:  # noqa: BLE001 — swap failed: rollback
            entry["error"] = f"swap failed: {e!r}"[:300]
            supervisor.readmit(h.rid)
            sample_unready()
            report["aborted"] = True
            logger.error(
                "fleet rollout aborted at replica %s (old weights keep "
                "serving): %r", h.rid, e,
            )
            break
        entry["swap_s"] = round(time.perf_counter() - t1, 3)
        entry["step"] = out.get("step")
        report["steps"].append(out.get("step"))

        # 3. new weight version: re-register fleet prefixes against it
        h.weight_version += 1
        entry["weight_version"] = h.weight_version
        entry["prefixes_replayed"] = gateway.replay_prefixes(h)

        # 4. back into rotation before the next replica drains
        supervisor.readmit(h.rid)
        sample_unready()
        entry["total_s"] = round(time.perf_counter() - t0, 3)

    report["version_consistent"] = (
        len(set(report["steps"])) <= 1
    )
    gateway.last_rollout = report
    return report
