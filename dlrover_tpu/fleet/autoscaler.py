"""Queue-depth / p95-latency fleet autoscaler.

The serving twin of the master's job auto-scaler: a periodic evaluator
that grows or shrinks the replica count within
``[min_replicas, max_replicas]`` off two signals the replicas already
export through ``/healthz``:

- **pressure** — mean queued work per READY replica
  (``queue_depth + busy_slots`` beyond capacity is what actually backs
  up: the engine admits into slots immediately, so sustained
  ``queue_depth`` means every slot is full);
- **latency** — the worst READY replica's rolling ``latency_p95_s``
  (models/serving.py's completion-latency window) against the operator
  SLO ``p95_target_s``.

Grow on either signal. Shrink only on sustained idleness
(``shrink_after`` consecutive idle evaluations — hysteresis, so a gap
between bursts doesn't flap the fleet) and never below
``min_replicas``. ``decide()`` is pure (signals in, target out) so the
policy is unit-testable without a fleet; ``step()`` applies it through
``ReplicaSupervisor.scale_to``.
"""

import threading
from typing import Dict, List, Optional

from ..common.log import logger
from .config import FleetConfig

__all__ = ["FleetAutoscaler", "fleet_signals"]


def fleet_signals(supervisor) -> Dict:
    """Fleet-wide pressure/latency snapshot from the supervisor's
    health-poll cache. Shared by the autoscaler's grow/shrink policy
    and the chip-pool arbiter's serving tenant (pool/tenants.py) so
    one signal definition drives both layers."""
    ready = supervisor.ready_replicas()
    stats: List[Dict] = [h.stats for h in ready]
    queued = [int(s.get("queue_depth") or 0) for s in stats]
    busy = [int(s.get("busy_slots") or 0) for s in stats]
    p95s = [
        float(s["latency_p95_s"])
        for s in stats
        if s.get("latency_p95_s") is not None
    ]
    paged = [s for s in stats if s.get("blocks_total") is not None]
    return {
        "ready": len(ready),
        # disaggregation split: growth always adds decode capacity
        # (roles are rid-derived, lowest rids are prefill), so the
        # policy reads these to see what a scale step actually buys
        "ready_prefill": sum(
            1 for h in ready if getattr(h, "role", "decode") == "prefill"
        ),
        "ready_decode": sum(
            1 for h in ready if getattr(h, "role", "decode") == "decode"
        ),
        "queue_mean": (sum(queued) / len(queued) if queued else 0.0),
        "busy_total": sum(busy),
        "p95_worst_s": max(p95s) if p95s else None,
        # paged-KV headroom (None on dense fleets): sustained
        # exhaustion with an idle queue is a capacity signal the
        # queue-depth pressure metric alone cannot see
        "blocks_free": (
            sum(int(s["blocks_free"] or 0) for s in paged)
            if paged else None
        ),
        "blocks_total": (
            sum(int(s["blocks_total"]) for s in paged)
            if paged else None
        ),
    }


class FleetAutoscaler:
    # consecutive idle evaluations before a shrink (hysteresis)
    SHRINK_AFTER = 3

    def __init__(self, supervisor, config: Optional[FleetConfig] = None):
        self.sup = supervisor
        self.cfg = config or supervisor.cfg
        self._idle_evals = 0
        self.evaluations = 0
        self.scale_ups = 0
        self.scale_downs = 0
        self.last_signals: Dict = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- signals ----------------------------------------------------------

    def signals(self) -> Dict:
        """Fleet-wide pressure/latency snapshot (see
        :func:`fleet_signals` — the shared definition)."""
        return fleet_signals(self.sup)

    # -- policy -----------------------------------------------------------

    def decide(self, sig: Dict) -> int:
        """Target replica count for one evaluation (pure policy)."""
        n = len(self.sup.replicas())
        ready = sig.get("ready", 0)
        if ready == 0:
            return n  # nothing healthy to measure: never scale blind
        queue_mean = sig.get("queue_mean") or 0.0
        p95 = sig.get("p95_worst_s")
        over_queue = queue_mean >= self.cfg.queue_high
        over_latency = (
            self.cfg.p95_target_s > 0
            and p95 is not None
            and p95 > self.cfg.p95_target_s
        )
        if over_queue or over_latency:
            self._idle_evals = 0
            return min(n + 1, self.cfg.max_replicas)
        idle = (
            queue_mean == 0
            and sig.get("busy_total", 0) == 0
            and (
                self.cfg.p95_target_s <= 0
                or p95 is None
                or p95 < self.cfg.p95_target_s / 2
            )
        )
        if idle:
            self._idle_evals += 1
            if self._idle_evals >= self.SHRINK_AFTER:
                self._idle_evals = 0
                return max(n - 1, self.cfg.min_replicas)
        else:
            self._idle_evals = 0
        return n

    def step(self) -> Dict:
        """One evaluate→decide→apply round; returns the decision."""
        sig = self.signals()
        self.last_signals = sig
        self.evaluations += 1
        n = len(self.sup.replicas())
        target = self.decide(sig)
        if target > n:
            self.scale_ups += 1
            logger.info(
                "fleet autoscaler: %s -> %s (queue_mean=%.2f "
                "p95=%s)", n, target, sig["queue_mean"],
                sig["p95_worst_s"],
            )
            self.sup.scale_to(target)
        elif target < n:
            self.scale_downs += 1
            logger.info("fleet autoscaler: %s -> %s (idle)", n, target)
            self.sup.scale_to(target)
        return {"n": n, "target": target, **sig}

    def status(self) -> Dict:
        return {
            "evaluations": self.evaluations,
            "scale_ups": self.scale_ups,
            "scale_downs": self.scale_downs,
            "signals": self.last_signals,
            "bounds": [self.cfg.min_replicas, self.cfg.max_replicas],
        }

    # -- periodic driver ---------------------------------------------------

    def start(self) -> "FleetAutoscaler":
        """Periodic evaluation at ``autoscale_interval_s`` (a config of
        0 means manual ``step()`` only — start() is then a no-op)."""
        if self.cfg.autoscale_interval_s <= 0:
            return self
        self._thread = threading.Thread(
            target=self._loop, name="fleet-autoscaler", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.step()
            except Exception as e:  # noqa: BLE001 — scaler survives
                logger.exception("fleet autoscaler error: %s", e)
            self._stop.wait(self.cfg.autoscale_interval_s)
