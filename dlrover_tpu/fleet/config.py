"""Fleet configuration: the ``DLROVER_FLEET_*`` operator surface.

One typed dataclass consumed by every fleet component (supervisor,
gateway, rollout, autoscaler). Every field is overridable through a
registered env knob (``common/constants.py ENV_KNOBS`` — the
``tpurun-lint`` env-knobs pass enforces registered ⇔ documented ⇔
referenced from day one) and through ``tpurun-fleet`` flags; the env
path exists so a k8s Deployment tunes the fleet without re-templating
its command line, mirroring the trainer's ``DLROVER_*`` contract.
"""

from dataclasses import dataclass, fields

from ..common.constants import ENV_KNOBS

# field name -> env knob. Declared next to the dataclass so a new field
# and its knob land in the same diff (the lint staleness check fails on
# either half missing).
_FLEET_KNOBS = {
    "replicas": "DLROVER_FLEET_REPLICAS",
    "min_replicas": "DLROVER_FLEET_MIN_REPLICAS",
    "max_replicas": "DLROVER_FLEET_MAX_REPLICAS",
    "health_interval_s": "DLROVER_FLEET_HEALTH_INTERVAL_S",
    "health_timeout_s": "DLROVER_FLEET_HEALTH_TIMEOUT_S",
    "health_fails": "DLROVER_FLEET_HEALTH_FAILS",
    "start_timeout_s": "DLROVER_FLEET_START_TIMEOUT_S",
    "relaunch_budget": "DLROVER_FLEET_RELAUNCH_BUDGET",
    "queue_limit": "DLROVER_FLEET_QUEUE_LIMIT",
    "retry_after_s": "DLROVER_FLEET_RETRY_AFTER_S",
    "request_timeout_s": "DLROVER_FLEET_REQUEST_TIMEOUT_S",
    "drain_timeout_s": "DLROVER_FLEET_DRAIN_TIMEOUT_S",
    "autoscale_interval_s": "DLROVER_FLEET_AUTOSCALE_INTERVAL_S",
    "queue_high": "DLROVER_FLEET_QUEUE_HIGH",
    "p95_target_s": "DLROVER_FLEET_P95_TARGET_S",
    "prefix_capacity": "DLROVER_FLEET_PREFIX_CAPACITY",
    "prefill_replicas": "DLROVER_FLEET_PREFILL_REPLICAS",
    "disagg_min_prompt": "DLROVER_DISAGG_MIN_PROMPT",
}


@dataclass
class FleetConfig:
    """Knobs for one serving fleet (docs/serving_fleet.md table)."""

    # topology
    replicas: int = 2  # initial replica count
    min_replicas: int = 1  # autoscaler floor
    max_replicas: int = 4  # autoscaler ceiling

    # replica supervision (STARTING→READY→DRAINING→DEAD machine)
    health_interval_s: float = 0.5  # seconds between /healthz polls
    health_timeout_s: float = 5.0  # per-poll deadline
    health_fails: int = 3  # consecutive failures before DEAD
    start_timeout_s: float = 120.0  # STARTING deadline before relaunch
    relaunch_budget: int = 3  # relaunches per replica slot

    # gateway admission + proxying
    queue_limit: int = 64  # in-flight bound before 429
    retry_after_s: float = 1.0  # Retry-After hint on 429
    request_timeout_s: float = 300.0  # gateway→replica proxy deadline

    # staged weight rollout
    drain_timeout_s: float = 120.0  # per-replica drain deadline

    # autoscaler
    autoscale_interval_s: float = 0.0  # 0 = manual stepping only
    queue_high: float = 4.0  # mean queued/replica to grow
    p95_target_s: float = 0.0  # p95 latency target to grow (0 = off)

    # prefix registry + prefill/decode disaggregation
    prefix_capacity: int = 256  # gateway prefix-LRU bound
    prefill_replicas: int = 0  # lowest-rid slots run the prefill role
    disagg_min_prompt: int = 0  # prompt tokens before handing off

    def __post_init__(self):
        if self.replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {self.replicas}")
        if not (
            1 <= self.min_replicas <= self.replicas <= self.max_replicas
        ):
            raise ValueError(
                "need 1 <= min_replicas <= replicas <= max_replicas, got "
                f"{self.min_replicas}/{self.replicas}/{self.max_replicas}"
            )
        if self.health_fails < 1:
            raise ValueError("health_fails must be >= 1")
        if self.prefix_capacity < 1:
            raise ValueError("prefix_capacity must be >= 1")
        if self.prefill_replicas < 0:
            raise ValueError("prefill_replicas must be >= 0")
        # decode capacity must survive the autoscaler floor: prefill
        # replicas hold the lowest rids and never shrink away, so the
        # floor minus them is the guaranteed decode count
        if self.prefill_replicas and (
            self.prefill_replicas >= self.min_replicas
        ):
            raise ValueError(
                f"prefill_replicas {self.prefill_replicas} must stay "
                f"below min_replicas {self.min_replicas} (at least one "
                f"decode replica must survive scale-down)"
            )

    @classmethod
    def from_env(cls, **overrides) -> "FleetConfig":
        """Defaults ← ``DLROVER_FLEET_*`` env ← explicit overrides."""
        kwargs = {}
        for f in fields(cls):
            knob = ENV_KNOBS[_FLEET_KNOBS[f.name]]
            val = knob.get()
            if val is not None:
                kwargs[f.name] = val
        kwargs.update(overrides)
        return cls(**kwargs)
