"""Replica process backends for the fleet supervisor.

A replica is ONE ``tpurun-serve``-shaped HTTP serving daemon. The
supervisor only needs a tiny lifecycle protocol from it::

    start()      bind and begin serving (port resolved after start)
    alive()      process/thread still running
    terminate()  graceful stop (drain-friendly)
    kill()       hard stop — SIGKILL for subprocesses, an abrupt
                 socket+driver teardown in-process (mid-flight requests
                 fail with connection errors, exactly like a SIGKILL)
    port         the bound HTTP port (valid once start() returned)

Two implementations:

- :class:`SubprocessReplica` — production shape: one ``tpurun-serve``
  process per replica (own jax runtime, own device footprint, crash
  isolation; a replica SIGKILL cannot take the gateway down).
- :class:`InProcessReplica` — test/bench shape: a real
  ``ServingDaemon`` + HTTP server on a thread, so fleet semantics
  (routing, failover, rollout) are exercised over genuine HTTP without
  paying a jax interpreter boot per replica.
"""

import os
import signal
import subprocess
import sys
import threading
from typing import Callable, List, Optional

from ..common.log import logger

__all__ = ["SubprocessReplica", "InProcessReplica", "serve_command"]


def serve_command(
    port: int, replica_id: int, serve_args: Optional[List[str]] = None,
    role: Optional[str] = None,
) -> List[str]:
    """The ``tpurun-serve`` argv for one replica. ``serve_args`` carries
    the fleet-wide model/engine flags (``--cpu``, ``--ckpt-dir``,
    ``--config``, ...); port, replica id, and disaggregation role are
    per-replica."""
    return [
        sys.executable,
        "-m",
        "dlrover_tpu.launcher.serve",
        "--port",
        str(port),
        "--replica-id",
        str(replica_id),
        *(["--role", role] if role else []),
        *(serve_args or []),
    ]


class SubprocessReplica:
    """One ``tpurun-serve`` child process."""

    def __init__(
        self,
        replica_id: int,
        port: int,
        serve_args: Optional[List[str]] = None,
        env: Optional[dict] = None,
        role: Optional[str] = None,
    ):
        self.replica_id = replica_id
        self.port = port
        self._argv = serve_command(port, replica_id, serve_args, role=role)
        self._env = env
        self._proc: Optional[subprocess.Popen] = None

    @property
    def pid(self) -> Optional[int]:
        return self._proc.pid if self._proc is not None else None

    def start(self) -> None:
        env = dict(os.environ if self._env is None else self._env)
        # each replica gets a private IPC namespace: its checkpoint
        # restore engine must never unlink a sibling's (or a colocated
        # trainer's) shm segment
        env["DLROVER_IPC_NAMESPACE"] = (
            f"fleet_r{self.replica_id}_p{self.port}_{os.getpid()}"
        )
        self._proc = subprocess.Popen(
            self._argv,
            env=env,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
            start_new_session=True,  # our kill never signals the fleet
        )
        logger.info(
            "fleet replica %s: spawned pid %s on port %s",
            self.replica_id, self._proc.pid, self.port,
        )

    def alive(self) -> bool:
        return self._proc is not None and self._proc.poll() is None

    def terminate(self) -> None:
        if self.alive():
            self._proc.terminate()
            try:
                self._proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self.kill()

    def kill(self) -> None:
        if self._proc is None:
            return
        try:
            os.kill(self._proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
        try:
            self._proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            pass


class InProcessReplica:
    """A real serving daemon + HTTP server on a thread.

    ``engine_factory`` builds the ContinuousBatchingEngine (called on
    every (re)launch — a killed replica restarts with FRESH engine
    state, like a respawned process restoring from the checkpoint);
    ``reload_fn`` is the ``/v1/weights/reload`` source, ``() -> (step,
    params)``, so rollout tests/bench drive real weight swaps."""

    def __init__(
        self,
        replica_id: int,
        port: int = 0,
        engine_factory: Optional[Callable] = None,
        reload_fn: Optional[Callable] = None,
        role: str = "decode",
    ):
        if engine_factory is None:
            raise ValueError("InProcessReplica needs an engine_factory")
        self.replica_id = replica_id
        self.port = port  # rebound to the real port after start()
        self._engine_factory = engine_factory
        self._reload_fn = reload_fn
        self.role = role
        self._daemon = None
        self._httpd = None
        self._thread: Optional[threading.Thread] = None
        self._alive = False

    @property
    def pid(self) -> Optional[int]:
        return os.getpid()

    def start(self) -> None:
        from ..launcher.serve import ServingDaemon, serve

        engine = self._engine_factory()
        self._daemon = ServingDaemon(engine).start()
        self._httpd = serve(
            self._daemon,
            port=0,
            reload_fn=self._reload_fn,
            replica_id=self.replica_id,
            role=self.role,
        )
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name=f"fleet-replica-{self.replica_id}",
            daemon=True,
        )
        self._thread.start()
        self._alive = True

    def alive(self) -> bool:
        return self._alive

    def terminate(self) -> None:
        self._stop()

    def kill(self) -> None:
        # abrupt: close the listening socket first, then drop the
        # driver — in-flight gateway proxies see connection resets,
        # the same failure surface a SIGKILLed subprocess produces
        self._stop()

    def _stop(self) -> None:
        if not self._alive:
            return
        self._alive = False
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except OSError:
            pass
        self._daemon.stop()
        if self._thread is not None:
            self._thread.join(timeout=10)
