"""``tpurun-fleet`` — run a serving fleet on this host.

Spawns N ``tpurun-serve`` replica subprocesses, supervises them, and
serves the gateway API on ``--port``::

    tpurun-fleet --cpu --replicas 2 --port 8400 -- --max-new-tokens 64

Everything after ``--`` is forwarded verbatim to every replica's
``tpurun-serve`` command line (model family/config, ``--ckpt-dir``,
engine shape flags); ``--port``/``--replica-id`` are per-replica and
owned by the supervisor. Fleet shape and SLOs come from flags or their
``DLROVER_FLEET_*`` env twins (docs/serving_fleet.md knob table).
"""

import argparse
import signal
from typing import List, Optional

from ..common.log import logger
from .autoscaler import FleetAutoscaler
from .config import FleetConfig
from .gateway import Gateway
from .replica import SubprocessReplica
from .supervisor import ReplicaSupervisor

__all__ = ["main"]


def main(argv: Optional[List[str]] = None) -> int:
    from ..analysis.witness import maybe_install

    maybe_install()  # DLROVER_LOCK_WITNESS=1 -> sanitize lock order
    ap = argparse.ArgumentParser(
        prog="tpurun-fleet",
        description="elastic serving fleet: replica supervisor + "
        "slot-aware gateway",
    )
    ap.add_argument("--port", type=int, default=8400,
                    help="gateway bind port")
    ap.add_argument("--replicas", type=int, default=None,
                    help="initial replica count "
                    "(DLROVER_FLEET_REPLICAS)")
    ap.add_argument("--min-replicas", type=int, default=None)
    ap.add_argument("--max-replicas", type=int, default=None)
    ap.add_argument("--queue-limit", type=int, default=None,
                    help="gateway admission bound before 429 "
                    "(DLROVER_FLEET_QUEUE_LIMIT)")
    ap.add_argument("--autoscale-interval", type=float, default=None,
                    help="autoscaler period in seconds; 0 disables "
                    "(DLROVER_FLEET_AUTOSCALE_INTERVAL_S)")
    ap.add_argument("--cpu", action="store_true",
                    help="forward --cpu to every replica (local smoke)")
    ap.add_argument(
        "serve_args", nargs=argparse.REMAINDER,
        help="args after -- are forwarded to every tpurun-serve "
        "replica",
    )
    ns = ap.parse_args(argv)

    overrides = {}
    if ns.replicas is not None:
        overrides["replicas"] = ns.replicas
    if ns.min_replicas is not None:
        overrides["min_replicas"] = ns.min_replicas
    if ns.max_replicas is not None:
        overrides["max_replicas"] = ns.max_replicas
    if ns.queue_limit is not None:
        overrides["queue_limit"] = ns.queue_limit
    if ns.autoscale_interval is not None:
        overrides["autoscale_interval_s"] = ns.autoscale_interval
    cfg = FleetConfig.from_env(**overrides)

    serve_args = list(ns.serve_args)
    if serve_args and serve_args[0] == "--":
        serve_args = serve_args[1:]
    if ns.cpu and "--cpu" not in serve_args:
        serve_args.append("--cpu")

    def factory(rid: int, port: int) -> SubprocessReplica:
        # rid-derived role, mirroring ReplicaSupervisor.role_of: the
        # lowest slots run prefill in a disaggregated fleet
        role = (
            "prefill" if rid < cfg.prefill_replicas else "decode"
        )
        return SubprocessReplica(
            rid, port, serve_args=serve_args, role=role
        )

    # Replicas run in their own sessions (a replica SIGKILL must never
    # signal the fleet), so the DEFAULT SIGTERM action — immediate
    # death, no finally — would orphan every replica process. k8s
    # stops pods with SIGTERM: route it through KeyboardInterrupt so
    # the teardown below terminates the fleet.
    def _sigterm(signum, frame):
        raise KeyboardInterrupt

    signal.signal(signal.SIGTERM, _sigterm)

    supervisor = ReplicaSupervisor(factory, cfg).start()
    gateway = Gateway(supervisor, cfg)
    scaler = FleetAutoscaler(supervisor, cfg).start()
    httpd = gateway.serve(ns.port)
    logger.info(
        "tpurun-fleet gateway on :%s — %s replicas (bounds %s..%s), "
        "queue_limit %s",
        httpd.server_address[1], cfg.replicas, cfg.min_replicas,
        cfg.max_replicas, cfg.queue_limit,
    )
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        httpd.server_close()
        scaler.stop()
        supervisor.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
