"""endpoint-conformance: HTTP clients and handlers must agree on routes.

Incident (PR 7/8): three subsystems now speak HTTP to each other —
gateway→replica (``/v1/completions``, ``/v1/prefixes``,
``/v1/weights/reload``), supervisor health polls (``/healthz``), the
pool CLI's status plane (``/pool/status``/``journal``/``step``) — and
the route strings live as literals on both sides. A client path that
drifts from its handler 404s only at runtime, in exactly the
least-exercised code (a rollout, a drill); a handler nobody calls is
dead surface that still has to be security-reviewed.

Rule (repo-wide, over the linted tree):

- *Registered routes* are collected from request handlers: every
  string compared against ``self.path`` (``==``, ``in (tuple)``) and
  every ``self.path.startswith("...")`` prefix.
- *Client paths* are collected from in-repo HTTP clients: a string
  literal starting with ``/`` concatenated onto something named like a
  URL (``h.url + "/healthz"``), the trailing path of an
  ``http://...`` f-string, and the first route-like argument of
  helper calls named like ``_post``/``_post_replica``/``_get``/
  ``get_json`` (the path is not always the first positional).
- A client path with **no registered handler** (exact match, or under
  a registered ``startswith`` prefix) is an error at the client site.
- A registered route **no client or doc references** is an error at
  the handler site — docs (README.md, docs/*.md) count as a reference
  because operator-facing status endpoints are driven by curl, not by
  in-repo code.

Matching is by path string across the whole tree (the pass does not
model which server a client connects to); tests are excluded simply
because the lint gate only walks ``dlrover_tpu/``. Dynamic protocols
that build paths from variables (checkpoint replica peers, unified
payload store) contribute no literals on either side and are out of
scope — by design, this pass is exactly the literal-drift tripwire.
"""

import ast
import glob
import os
import re
from typing import Dict, Iterable, List, Tuple

from ..core import FileContext, Violation, dotted_name

PASS_ID = "endpoint-conformance"

_ROUTE_RE = re.compile(r"^/[A-Za-z0-9_\-./]*$")
_URLY = re.compile(r"(url|addr|base|endpoint)", re.I)
# HTTP helper methods: the gateway's _post_replica(h, "/v1/...", ...),
# the rpc client's _post("/get", ...) — the path may not be the first
# argument, so take the first route-like literal among the positionals
_HELPER_RE = re.compile(r"(^_?(post|request)|_post$|^_get$|^get_json$)")


def _is_self_path(expr: ast.AST) -> bool:
    return (
        isinstance(expr, ast.Attribute)
        and expr.attr == "path"
        and isinstance(expr.value, ast.Name)
        and expr.value.id == "self"
    )


def _route_like(s: object) -> bool:
    return (
        isinstance(s, str)
        and len(s) > 1
        and _ROUTE_RE.match(s) is not None
    )


def collect_routes(
    ctx: FileContext,
) -> List[Tuple[str, bool, int]]:
    """(path, is_prefix, line) registered by handlers in this file."""
    out: List[Tuple[str, bool, int]] = []
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Compare) and _is_self_path(node.left):
            for op, comp in zip(node.ops, node.comparators):
                if isinstance(op, ast.Eq) and isinstance(comp, ast.Constant):
                    if _route_like(comp.value):
                        out.append((comp.value, False, node.lineno))
                elif isinstance(op, ast.In) and isinstance(
                    comp, (ast.Tuple, ast.List, ast.Set)
                ):
                    for e in comp.elts:
                        if isinstance(e, ast.Constant) and _route_like(e.value):
                            out.append((e.value, False, node.lineno))
        elif (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "startswith"
            and _is_self_path(node.func.value)
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and _route_like(node.args[0].value)
        ):
            out.append((node.args[0].value, True, node.lineno))
    return out


def collect_client_paths(ctx: FileContext) -> List[Tuple[str, int]]:
    """(path, line) sent by HTTP clients in this file."""
    out: List[Tuple[str, int]] = []
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
            if (
                isinstance(node.right, ast.Constant)
                and _route_like(node.right.value)
                and _URLY.search(dotted_name(node.left) or "")
            ):
                out.append((node.right.value, node.lineno))
        elif isinstance(node, ast.JoinedStr):
            parts = node.values
            if (
                parts
                and isinstance(parts[0], ast.Constant)
                and str(parts[0].value).startswith("http")
                and isinstance(parts[-1], ast.Constant)
            ):
                tail = str(parts[-1].value)
                if _route_like(tail):
                    out.append((tail, node.lineno))
        elif isinstance(node, ast.Call):
            f = node.func
            name = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else ""
            )
            if name and _HELPER_RE.search(name):
                for a in node.args:
                    if isinstance(a, ast.Constant) and _route_like(a.value):
                        out.append((a.value, node.lineno))
                        break
    return out


def check_conformance(
    contexts: List[FileContext], docs_text: str
) -> Iterable[Violation]:
    routes: Dict[str, List[Tuple[bool, str, int]]] = {}
    clients: List[Tuple[str, str, int]] = []
    for ctx in contexts:
        for path, is_prefix, line in collect_routes(ctx):
            routes.setdefault(path, []).append((is_prefix, ctx.rel, line))
        for path, line in collect_client_paths(ctx):
            clients.append((path, ctx.rel, line))

    prefixes = [p for p, regs in routes.items() if any(r[0] for r in regs)]

    referenced: set = set()
    for path, rel, line in clients:
        hit = path in routes or any(path.startswith(p) for p in prefixes)
        if hit:
            referenced.add(path)
            for p in prefixes:
                if path.startswith(p):
                    referenced.add(p)
        else:
            yield Violation(
                PASS_ID,
                rel,
                line,
                f"client sends {path!r} but no handler registers that "
                "route — this 404s at runtime (the gateway/pool "
                "route-drift class); fix the path or register the "
                "handler",
                code=f"client:{path}",
            )

    for path, regs in sorted(routes.items()):
        if path in referenced or path in docs_text:
            continue
        _is_prefix, rel, line = regs[0]
        yield Violation(
            PASS_ID,
            rel,
            line,
            f"route {path!r} is registered but referenced by no in-repo "
            "client and no doc — dead (or drifted) surface; wire a "
            "client, document it, or delete the handler",
            code=f"route:{path}",
        )


def repo_check(
    root: str, contexts: List[FileContext]
) -> Iterable[Violation]:
    docs: List[str] = []
    for p in [os.path.join(root, "README.md")] + sorted(
        glob.glob(os.path.join(root, "docs", "*.md"))
    ):
        if os.path.exists(p):
            with open(p, encoding="utf-8") as f:
                docs.append(f.read())
    yield from check_conformance(contexts, "\n".join(docs))
