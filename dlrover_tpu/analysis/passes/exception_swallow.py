"""exception-swallow: broad handlers must log, re-raise, or record.

Incident (PR 8): the pool ledger only stayed honest because review
passes kept adding journaling by hand to ``except Exception`` bodies —
a poisoned grant that was silently swallowed would have left capacity
stranded with no trace, and the post-mortem would have had nothing to
read. The same class produced the "late cooperative confirm after
escalation" bug: the confirm was dropped on the floor instead of
journaled+ignored, and only a regression test caught it.

Rule: a broad handler — ``except:``, ``except Exception``, or
``except BaseException`` (alone or in a tuple) — must do at least one
of:

- re-raise (any ``raise`` in the body),
- log (a logging-verb call: ``logger.warning(...)``, ``print``, ...),
- record (bump a counter via ``+=``, or call something named like a
  journal/stats sink: ``journal``/``record``/``emit``/``note``/
  ``observe``/``mark``/``incr``/``stat``/``report``/``fail``),
- actually *use* the caught exception (``except Exception as e`` where
  ``e`` is referenced — stored, forwarded, formatted into a result).

A handler that does none of these erases the failure; suppress a
deliberate drop with ``# tpulint: ignore[exception-swallow] <why>`` on
the ``except`` line — the reason is the review trail. Narrow handlers
(``except OSError:``) are out of scope: naming the exception type is
already a statement of intent.

Nested ``def``/``lambda`` bodies inside the handler do not count as
handling — they run later, if ever.
"""

import ast
import re
from typing import Iterable

from ..core import FileContext, Violation, call_name, walk_skip_defs

PASS_ID = "exception-swallow"

_BROAD = {"Exception", "BaseException"}
_LOG_VERBS = {
    "debug",
    "info",
    "warning",
    "warn",
    "error",
    "exception",
    "critical",
    "log",
    "print",
}
_RECORDY = re.compile(
    r"(journal|record|emit|note|observe|mark|incr|stat|report|fail)", re.I
)


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    if isinstance(t, ast.Name):
        return t.id in _BROAD
    if isinstance(t, ast.Tuple):
        return any(
            isinstance(e, ast.Name) and e.id in _BROAD for e in t.elts
        )
    return False


def _handles(handler: ast.ExceptHandler) -> bool:
    exc_name = handler.name
    for st in handler.body:
        for node in walk_skip_defs(st):
            if isinstance(node, ast.Raise):
                return True
            if isinstance(node, ast.AugAssign):
                return True  # counter bump
            if exc_name and isinstance(node, ast.Name) and node.id == exc_name:
                return True  # the exception goes somewhere
            if isinstance(node, ast.Call):
                name = call_name(node)
                if name in _LOG_VERBS or _RECORDY.search(name):
                    return True
    return False


def check_file(ctx: FileContext) -> Iterable[Violation]:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if not _is_broad(node):
            continue
        if _handles(node):
            continue
        what = (
            "except:" if node.type is None
            else f"except {ast.unparse(node.type)}"  # py>=3.9
        )
        yield Violation(
            PASS_ID,
            ctx.rel,
            node.lineno,
            f"{what} swallows the failure — it neither re-raises, logs, "
            "records to a journal/counter, nor uses the exception; a "
            "dead component keeps looking healthy (the poisoned-grant "
            "class). Log/journal it, or suppress with the reason the "
            "drop is safe",
            code=ctx.code_at(node.lineno),
        )
