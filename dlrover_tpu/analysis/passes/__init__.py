"""Pass registry for tpurun-lint.

Each pass module exposes ``PASS_ID`` plus ``check_file(ctx)`` (per-file)
and/or ``repo_check(root, contexts)`` (whole-repo). The registry order
is the report order.
"""

from . import (
    blocking_under_lock,
    endpoint_conformance,
    env_knobs,
    epoch_fence,
    exception_swallow,
    host_sync,
    import_purity,
    injection_coverage,
    journal_conformance,
    lock_order,
    mesh_axes,
    reshard_coverage,
    rpc_deadline,
    thread_lifecycle,
)

ALL_PASSES = [
    import_purity,
    blocking_under_lock,
    lock_order,
    thread_lifecycle,
    exception_swallow,
    host_sync,
    rpc_deadline,
    env_knobs,
    injection_coverage,
    endpoint_conformance,
    mesh_axes,
    reshard_coverage,
    journal_conformance,
    epoch_fence,
]

PASS_BY_ID = {p.PASS_ID: p for p in ALL_PASSES}

__all__ = ["ALL_PASSES", "PASS_BY_ID"]
