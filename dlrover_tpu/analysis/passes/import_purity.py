"""import-purity: importing a runtime module must have no side effects.

Incident (PR 4): ``goodput_storm`` set the persistent XLA compile cache
by calling ``jax.config.update`` at module import ("STORM_CACHE_DIR
hack") — every process that merely *imported* the module got its jax
config mutated, and the fix had to re-plumb the knob through Context.
Import-time ``jax.distributed.initialize`` is worse (it binds sockets),
and an import-time ``os.environ`` write or thread/process start makes
import order load-bearing across the whole runtime.

Rule: at import time (module body, including module-level ``if``/
``try``/``with`` bodies and class bodies, which also execute at import)
a runtime module must not

- call ``jax.config.update`` / ``jax.distributed.initialize``,
- mutate ``os.environ`` (subscript assign, ``setdefault``, ``pop``,
  ``update``, ``putenv``),
- start a thread/process (``*.start()``, ``threading.Thread``,
  ``multiprocessing.Process``, ``subprocess.Popen``, ``os.fork``) or
  install signal handlers,
- call ``multiprocessing.set_start_method``.

A ``if __name__ == "__main__":`` block is exempt (that's a program, not
an import). Function and lambda bodies are exempt — they run when
called, not when imported.
"""

import ast
from typing import Iterable, List

from ..core import FileContext, Violation, call_name, dotted_name

PASS_ID = "import-purity"

_BANNED_DOTTED = {
    "jax.config.update": "jax config mutated at import",
    "jax.distributed.initialize": "jax.distributed.initialize at import",
    "multiprocessing.set_start_method": "start-method pinned at import",
    "os.fork": "process forked at import",
    "os.putenv": "environment mutated at import",
    "signal.signal": "signal handler installed at import",
}

_BANNED_CTORS = {
    "threading.Thread",
    "multiprocessing.Process",
    "subprocess.Popen",
}

_ENV_MUTATORS = {"setdefault", "pop", "update", "__setitem__"}


def _is_main_guard(node: ast.AST) -> bool:
    if not isinstance(node, ast.If):
        return False
    t = node.test
    return (
        isinstance(t, ast.Compare)
        and isinstance(t.left, ast.Name)
        and t.left.id == "__name__"
    )


def _import_time_nodes(tree: ast.Module) -> Iterable[ast.AST]:
    """Every node whose code executes at import: reachable from the
    module body WITHOUT entering function/lambda bodies (class bodies
    do execute at import and are included)."""
    stack: List[ast.AST] = list(tree.body)
    while stack:
        n = stack.pop()
        if isinstance(
            n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            continue
        if _is_main_guard(n):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


def check_file(ctx: FileContext) -> Iterable[Violation]:
    for node in _import_time_nodes(ctx.tree):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        elif isinstance(node, ast.Delete):
            targets = node.targets
        for t in targets:
            if isinstance(t, ast.Subscript) and dotted_name(t.value) in (
                "os.environ",
                "environ",
            ):
                yield Violation(
                    PASS_ID,
                    ctx.rel,
                    node.lineno,
                    "os.environ mutated at module import time — make it "
                    "a Context knob or move it under the caller",
                    code=ctx.code_at(node.lineno),
                )

        if not isinstance(node, ast.Call):
            continue
        dn = dotted_name(node.func)
        if dn in _BANNED_DOTTED:
            yield Violation(
                PASS_ID,
                ctx.rel,
                node.lineno,
                f"{_BANNED_DOTTED[dn]} ({dn!r}) — importing this "
                "module must be side-effect free",
                code=ctx.code_at(node.lineno),
            )
            continue
        if dn in _BANNED_CTORS:
            yield Violation(
                PASS_ID,
                ctx.rel,
                node.lineno,
                f"{dn} constructed at module import time",
                code=ctx.code_at(node.lineno),
            )
            continue
        name = call_name(node)
        recv = (
            dotted_name(node.func.value)
            if isinstance(node.func, ast.Attribute)
            else ""
        )
        if recv in ("os.environ", "environ") and name in _ENV_MUTATORS:
            yield Violation(
                PASS_ID,
                ctx.rel,
                node.lineno,
                f"os.environ.{name}() at module import time",
                code=ctx.code_at(node.lineno),
            )
        elif name == "start" and isinstance(node.func, ast.Attribute):
            yield Violation(
                PASS_ID,
                ctx.rel,
                node.lineno,
                "thread/process started at module import time",
                code=ctx.code_at(node.lineno),
            )
