"""blocking-under-lock: no unbounded blocking while holding a lock.

Incident (PR 3): the checkpoint saver's IPC wait and the serving weight
swap both held ``threading.Lock`` attributes across calls that chaos
storms wedged (a dead peer, a dropped RPC) — every other thread wanting
the lock then wedged behind them, turning one slow dependency into a
whole-process hang. PR 3's fixes (saver-IPC timeout → standalone saver,
swap abort paths) each started as exactly this pattern.

Rule: inside a ``with <lock>:`` body (any context-manager whose name
contains ``lock``/``mutex``/``cond``), the following are flagged:

- ``time.sleep(...)``
- untimed ``.join()`` (thread/process join with no timeout)
- untimed ``.wait()`` (Event/Condition wait with no timeout)
- untimed queue ``.get()``/``.put()`` (receiver named like a queue)
- untimed nested ``.acquire()`` (no ``timeout=``, classic ABBA setup)
- ``subprocess`` waits without ``timeout=`` (``run``, ``check_call``,
  ``check_output``, ``communicate``, ``wait``)
- network calls: ``urlopen``, and any call on a ``*client*`` receiver
  (the RPC clients' verbs — the master client retries with backoff
  *sleeps* internally, so holding a lock across it wedges for the whole
  retry budget)

Nested ``def``/``lambda`` bodies are skipped — they do not execute
under the lock (the saver factory's runner thread is *defined* under
the class lock but runs on its own thread).

The pass sees only syntactic locks (``with self._lock:``). Manual
``acquire()``/``release()`` spans are not tracked; keep those short or
convert them to ``with`` so the pass can see them.
"""

import ast
import re
from typing import Iterable

from ..core import (
    FileContext,
    Violation,
    call_name,
    keyword_map,
    receiver_name,
    walk_skip_defs,
)

PASS_ID = "blocking-under-lock"

_LOCKY = re.compile(r"(lock|mutex|cond)", re.I)
_QUEUEY = re.compile(r"(^q$|^_q$|queue|inbox|outbox)", re.I)
_CLIENTY = re.compile(r"client", re.I)
_SUBPROC_WAITS = {"run", "check_call", "check_output", "communicate", "wait_for"}


def _is_locky(expr: ast.expr) -> bool:
    if isinstance(expr, ast.Attribute):
        return bool(_LOCKY.search(expr.attr))
    if isinstance(expr, ast.Name):
        return bool(_LOCKY.search(expr.id))
    if isinstance(expr, ast.Call):
        # with self._lock_for(x): / with threading.Lock():
        return _is_locky(expr.func)
    return False


def check_file(ctx: FileContext) -> Iterable[Violation]:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        if not any(_is_locky(item.context_expr) for item in node.items):
            continue
        for st in node.body:
            for sub in walk_skip_defs(st):
                if not isinstance(sub, ast.Call):
                    continue
                v = _classify(ctx, sub)
                if v is not None:
                    yield v


def _classify(ctx: FileContext, call: ast.Call):
    name = call_name(call)
    recv = receiver_name(call)
    kw = keyword_map(call)
    timed = "timeout" in kw
    msg = None
    if name == "sleep":
        msg = "time.sleep while holding a lock"
    elif name == "join" and not timed and not call.args and recv:
        msg = f"untimed {recv}.join() while holding a lock"
    elif name == "wait" and not timed and not call.args:
        msg = f"untimed {recv}.wait() while holding a lock"
    elif name in ("get", "put") and not timed and _QUEUEY.search(recv or ""):
        # queue.get(False) / get_nowait are fine; only the blocking form
        # with no deadline wedges
        if not (
            call.args
            and isinstance(call.args[0], ast.Constant)
            and call.args[0].value is False
        ) and not (
            "block" in kw
            and isinstance(kw["block"], ast.Constant)
            and kw["block"].value is False
        ):
            msg = f"untimed {recv}.{name}() while holding a lock"
    elif name == "acquire" and not timed and recv:
        blocking = kw.get("blocking")
        if not (
            isinstance(blocking, ast.Constant) and blocking.value is False
        ):
            msg = f"untimed nested {recv}.acquire() while holding a lock"
    elif name in _SUBPROC_WAITS and not timed and recv in (
        "subprocess",
        "p",
        "proc",
        "popen",
    ):
        msg = f"{recv}.{name}() with no timeout while holding a lock"
    elif name == "urlopen":
        msg = "network call (urlopen) while holding a lock"
    elif recv and _CLIENTY.search(recv):
        msg = (
            f"RPC/API call {recv}.{name}() while holding a lock — the "
            "client blocks for its whole retry budget"
        )
    if msg is None:
        return None
    return Violation(
        PASS_ID, ctx.rel, call.lineno, msg, code=ctx.code_at(call.lineno)
    )
