"""journal-conformance: WAL record kinds and snapshot components agree.

Incident (PR 10): the master's crash tolerance rests on string-matched
dispatch — components journal ``self._record("kv.set", ...)`` literals
and ``master/persistence.py::apply_wal_record`` replays them through an
``elif kind == "kv.set"`` chain, with an ``else: logger.warning`` for
anything unknown. A record kind that drifts from its replay branch (or
a new component that journals a kind nobody applies) REPLAYS AS A
SILENT NO-OP: the master boots "successfully" and has lost state — the
exact failure mode the journal exists to prevent, detectable only by a
kill drill that happens to cover the lost component. The elastic
resharding refactor (ROADMAP items 1/4) will add record kinds to this
dispatcher.

Rule (repo-wide, two-sided — the endpoint-conformance idiom applied to
the journal protocol):

- *Recorded kinds* are collected from recorder calls — functions named
  ``record``/``_record``/``journal``/``_journal`` whose first argument
  is a dotted-kind string literal (``"kv.set"``).
- *Applied kinds* are collected from replay dispatchers — ``kind ==
  "..."`` / ``kind in (...)`` comparisons inside functions named
  ``apply_wal_record``/``apply_journal``.
- A recorded kind with **no replay branch** errors at the recorder site
  (the silent-no-op class); a replay branch for a kind **nothing
  records** errors at the comparison site (dead or drifted dispatch).
- Every class that implements one of ``export_state``/``import_state``
  must implement the other — a component captured into the snapshot
  but not restorable (or vice versa) loses state exactly once, on the
  boot that needed it.
- ``capture_master_state``'s snapshot keys must match
  ``restore_master_state``'s reads: a component added to capture but
  not restore is exported dead weight, one added to restore but not
  capture replays nothing.
"""

import ast
import re
from typing import Dict, Iterable, List, Set, Tuple

from ..core import FileContext, Violation, call_name

PASS_ID = "journal-conformance"

_RECORDER_NAMES = {"record", "_record", "journal", "_journal"}
_APPLIER_NAMES = {"apply_wal_record", "apply_journal"}
_KIND_RE = re.compile(r"^[a-z][a-z0-9_]*\.[a-z][a-z0-9_]*$")


def _dotted_kind(expr: ast.AST) -> str:
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        if _KIND_RE.match(expr.value):
            return expr.value
    return ""


def collect_recorded(ctx: FileContext) -> List[Tuple[str, int]]:
    """(kind, line) for every journal-recorder call in this file."""
    out: List[Tuple[str, int]] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        if call_name(node) not in _RECORDER_NAMES:
            continue
        kind = _dotted_kind(node.args[0])
        if kind:
            out.append((kind, node.lineno))
    return out


def collect_applied(ctx: FileContext) -> List[Tuple[str, int]]:
    """(kind, line) for every replay-dispatch comparison in this file."""
    out: List[Tuple[str, int]] = []
    for fn in ast.walk(ctx.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if fn.name not in _APPLIER_NAMES:
            continue
        for node in ast.walk(fn):
            if not isinstance(node, ast.Compare):
                continue
            for op, comp in zip(node.ops, node.comparators):
                if isinstance(op, ast.Eq):
                    kind = _dotted_kind(comp)
                    if kind:
                        out.append((kind, node.lineno))
                elif isinstance(op, ast.In) and isinstance(
                    comp, (ast.Tuple, ast.List, ast.Set)
                ):
                    for e in comp.elts:
                        kind = _dotted_kind(e)
                        if kind:
                            out.append((kind, node.lineno))
    return out


def _class_state_methods(ctx: FileContext) -> List[Tuple[str, int, Set[str]]]:
    """(class name, line, {state methods defined}) per class."""
    out = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        defined = {
            st.name
            for st in node.body
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef))
            and st.name in ("export_state", "import_state")
        }
        if defined:
            out.append((node.name, node.lineno, defined))
    return out


def _capture_keys(ctx: FileContext) -> Tuple[Set[str], int]:
    """Top-level keys of the dict ``capture_master_state`` returns."""
    for fn in ast.walk(ctx.tree):
        if isinstance(fn, ast.FunctionDef) and fn.name == "capture_master_state":
            for node in ast.walk(fn):
                if isinstance(node, ast.Return) and isinstance(
                    node.value, ast.Dict
                ):
                    keys = {
                        k.value
                        for k in node.value.keys
                        if isinstance(k, ast.Constant)
                        and isinstance(k.value, str)
                    }
                    return keys, fn.lineno
            return set(), fn.lineno
    return set(), 0


def _restore_keys(ctx: FileContext) -> Tuple[Set[str], int]:
    """String keys ``restore_master_state`` reads off its state arg."""
    for fn in ast.walk(ctx.tree):
        if isinstance(fn, ast.FunctionDef) and fn.name == "restore_master_state":
            if len(fn.args.args) < 2:
                return set(), fn.lineno
            state_name = fn.args.args[1].arg
            keys: Set[str] = set()
            for node in ast.walk(fn):
                if (
                    isinstance(node, ast.Call)
                    and call_name(node) == "get"
                    and isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == state_name
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                ):
                    keys.add(node.args[0].value)
                elif (
                    isinstance(node, ast.Subscript)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == state_name
                    and isinstance(node.slice, ast.Constant)
                    and isinstance(node.slice.value, str)
                ):
                    keys.add(node.slice.value)
            return keys, fn.lineno
    return set(), 0


def repo_check(
    root: str, contexts: List[FileContext]
) -> Iterable[Violation]:
    recorded: Dict[str, List[Tuple[str, int]]] = {}
    applied: Dict[str, List[Tuple[str, int]]] = {}
    for ctx in contexts:
        for kind, line in collect_recorded(ctx):
            recorded.setdefault(kind, []).append((ctx.rel, line))
        for kind, line in collect_applied(ctx):
            applied.setdefault(kind, []).append((ctx.rel, line))

    # Only meaningful when a replay dispatcher is in the linted tree:
    # a subset lint of, say, models/ sees recorder helpers but no
    # appliers — every kind would read as unreplayable.
    if applied:
        for kind in sorted(set(recorded) - set(applied)):
            rel, line = recorded[kind][0]
            yield Violation(
                PASS_ID, rel, line,
                f"journaled record kind {kind!r} has no branch in "
                "apply_wal_record/apply_journal — it replays as a "
                "silent no-op and the master loses this state on "
                "reboot; add the replay branch",
                code=f"recorded:{kind}",
            )
        for kind in sorted(set(applied) - set(recorded)):
            rel, line = applied[kind][0]
            yield Violation(
                PASS_ID, rel, line,
                f"replay branch for kind {kind!r} that no recorder "
                "journals — dead dispatch, or the recorder's literal "
                "drifted; fix the kind or delete the branch",
                code=f"applied:{kind}",
            )

    for ctx in contexts:
        for cls, line, defined in _class_state_methods(ctx):
            missing = {"export_state", "import_state"} - defined
            if missing:
                yield Violation(
                    PASS_ID, ctx.rel, line,
                    f"class {cls} defines {sorted(defined)[0]} but not "
                    f"{sorted(missing)[0]} — a snapshot component must "
                    "implement the export_state/import_state pair or "
                    "its state survives in only one direction",
                    code=f"pair:{cls}",
                )

    for ctx in contexts:
        cap, cap_line = _capture_keys(ctx)
        res, res_line = _restore_keys(ctx)
        if not cap_line or not res_line:
            continue
        for key in sorted(cap - res):
            yield Violation(
                PASS_ID, ctx.rel, res_line,
                f"snapshot captures component {key!r} but "
                "restore_master_state never reads it — the exported "
                "state is dead weight and the component boots empty",
                code=f"capture-only:{key}",
            )
        for key in sorted(res - cap):
            yield Violation(
                PASS_ID, ctx.rel, cap_line,
                f"restore_master_state reads component {key!r} that "
                "capture_master_state never writes — it always "
                "restores empty",
                code=f"restore-only:{key}",
            )
