"""mesh-axes: every SPMD axis-name literal names a registered axis.

Incident (ROADMAP item 1 prep): PartitionSpec/NamedSharding axis names,
``shard_map`` in/out specs, ``param_with_axes`` annotations and
collective axis names live as bare string literals across ~56 sites in
``parallel/``, ``models/``, ``ops/``, ``trainer/`` and
``checkpoint/meta.py``. A
typo'd or drifted name does not error — flax's logical-rules fallback
silently *stops constraining* (``RulesFallback.NO_CONSTRAINT``), so the
leaf quietly replicates and the job trains slower or OOMs at a bigger
scale, with nothing pointing at the one character that changed. The
elastic DP×TP×PP resharding refactor will rewrite exactly these sites.

Rule: ``parallel/mesh.py::MESH_AXIS_REGISTRY`` is the single source of
truth (the ENV_KNOBS idiom) — a pure-literal dict so this pass can read
it by AST without importing jax. Per file:

- every string literal inside a ``PartitionSpec``/``P(...)`` call
  (aliases resolved through the file's imports) must be a registered
  axis (mesh or logical — both legitimately appear in specs);
- ``param_with_axes(..., axes=...)`` and
  ``with_logical_constraint``/``_constrain`` string arguments must be
  registered *logical* axes (a mesh axis there is exactly the
  silent-no-constraint drift);
- ``axis_name=``/``*_axis`` keyword values and string parameter
  defaults, ``jax.lax`` collective axis arguments, and
  ``mesh.shape["..."]`` subscripts must be registered *mesh* axes;
- module-level ``*_AXES`` tuple constants must contain only registered
  names.

Repo-wide, the registry is cross-checked against the mesh construction
sites and the logical-rule table:

- ``MESH_AXES`` must equal the registry's kind-"mesh" entries, in
  order (``build_mesh``'s reshape order is load-bearing);
- every ``Mesh(...)`` construction must take ``MESH_AXES`` (or a
  literal tuple of registered mesh axes);
- ``sharding.DEFAULT_RULES`` keys must be registered logical axes and
  its targets registered mesh axes; every registered logical axis must
  be mapped by a rule;
- a registered axis referenced nowhere is a stale entry (the registry
  must not rot).
"""

import ast
import os
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ..core import FileContext, Violation, call_name, dotted_name, keyword_map

PASS_ID = "mesh-axes"

_MESH_REL = os.path.join("dlrover_tpu", "parallel", "mesh.py")
_MESH_POSIX = "dlrover_tpu/parallel/mesh.py"
_SHARDING_REL = os.path.join("dlrover_tpu", "parallel", "sharding.py")
_SHARDING_POSIX = "dlrover_tpu/parallel/sharding.py"

# dirs whose files carry spec literals (the staleness scan's scope)
_SCAN_DIRS = ("parallel", "models", "ops", "trainer")
_SCAN_FILES = ("checkpoint/meta.py",)

_LOGICAL_CALLS = {"param_with_axes", "with_logical_constraint", "_constrain"}
_COLLECTIVE_CALLS = {
    "psum", "pmean", "pmax", "pmin", "axis_index", "ppermute",
    "all_gather", "psum_scatter", "all_to_all",
}
_AXIS_KWARG_RE = re.compile(r"^(axis_name|seq_axis|[a-z_]*_axis)$")
_AXIS_PARAM_RE = re.compile(r"^(axis|axis_name|seq_axis|[a-z_]*_axis)$")
_AXES_CONST_RE = re.compile(r"^_?[A-Z0-9_]*AXES$")


def _stamp(path: str) -> Optional[Tuple[int, int]]:
    """(mtime_ns, size) cache key so a stateful pass re-parses its
    source tables when they are edited within one process (watch modes,
    harnesses looping over a tmp root)."""
    try:
        st = os.stat(path)
    except OSError:
        return None
    return st.st_mtime_ns, st.st_size


def _literal_assign(tree: ast.AST, name: str) -> Optional[ast.AST]:
    """The value node of a module-level ``name = <literal>`` (or
    annotated) assignment."""
    for node in tree.body if isinstance(tree, ast.Module) else []:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == name:
                    return node.value
        elif isinstance(node, ast.AnnAssign):
            if isinstance(node.target, ast.Name) and node.target.id == name:
                return node.value
    return None


def load_axis_registry(
    mesh_path: str,
) -> Tuple[Optional[Dict[str, str]], Optional[Tuple[str, ...]], str]:
    """(axis name -> kind, MESH_AXES tuple, error) parsed from
    ``parallel/mesh.py`` WITHOUT importing it (the module imports jax)."""
    try:
        tree = ast.parse(open(mesh_path, encoding="utf-8").read())
    except (OSError, SyntaxError) as e:
        return None, None, f"cannot parse {mesh_path}: {e}"
    reg_node = _literal_assign(tree, "MESH_AXIS_REGISTRY")
    if reg_node is None:
        return None, None, "MESH_AXIS_REGISTRY not assigned at module level"
    try:
        raw = ast.literal_eval(reg_node)
        registry = {
            str(name): str(entry[0]) for name, entry in raw.items()
        }
    except (ValueError, TypeError, IndexError, KeyError):
        return None, None, (
            "MESH_AXIS_REGISTRY is not a pure literal dict of "
            "name -> (kind, doc) — computed entries are invisible to "
            "the AST lint"
        )
    axes_node = _literal_assign(tree, "MESH_AXES")
    mesh_axes: Optional[Tuple[str, ...]] = None
    if axes_node is not None:
        try:
            mesh_axes = tuple(ast.literal_eval(axes_node))
        except (ValueError, TypeError):
            mesh_axes = None
    return registry, mesh_axes, ""


def _spec_call_names(tree: ast.AST) -> Set[str]:
    """Local names bound to ``jax.sharding.PartitionSpec`` in this file
    (``PartitionSpec``, ``P``, …) via imports or simple aliasing."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module and (
            node.module.startswith("jax")
        ):
            for alias in node.names:
                if alias.name == "PartitionSpec":
                    names.add(alias.asname or alias.name)
        elif isinstance(node, ast.Assign) and isinstance(
            node.value, (ast.Name, ast.Attribute)
        ):
            src = dotted_name(node.value)
            if src.split(".")[-1] == "PartitionSpec":
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        names.add(t.id)
    return names


def _str_entries(expr: ast.AST) -> Iterable[str]:
    """String literals in a spec entry: "dp", ("dp", "fsdp"), None…"""
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        yield expr.value
    elif isinstance(expr, (ast.Tuple, ast.List)):
        for e in expr.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                yield e.value


def iter_axis_sites(
    ctx: FileContext,
) -> Iterable[Tuple[str, str, int, str]]:
    """(axis_literal, required_kind, line, where) for every axis-name
    site in the file. ``required_kind`` is "mesh", "logical" or "any"."""
    spec_names = _spec_call_names(ctx.tree)
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call):
            name = call_name(node)
            dn = dotted_name(node.func)
            if name in spec_names:
                for arg in node.args:
                    for s in _str_entries(arg):
                        yield s, "any", node.lineno, f"{name}(...) spec"
            elif name in _LOGICAL_CALLS:
                for arg in node.args[1:] if name != "param_with_axes" else []:
                    for s in _str_entries(arg):
                        yield s, "logical", node.lineno, f"{name}(...)"
                axes_kw = keyword_map(node).get("axes")
                if axes_kw is not None:
                    for s in _str_entries(axes_kw):
                        yield s, "logical", node.lineno, f"{name}(axes=...)"
            elif name in _COLLECTIVE_CALLS and (
                dn.startswith("jax.lax.") or dn.startswith("lax.")
            ):
                for arg in node.args:
                    for s in _str_entries(arg):
                        yield s, "mesh", node.lineno, f"{name}(...) collective"
            # axis-name keywords on ANY call (shard_map wrappers,
            # partial(ring_attention, axis_name=...), …)
            for kw, val in keyword_map(node).items():
                if _AXIS_KWARG_RE.match(kw or ""):
                    if isinstance(val, ast.Constant) and isinstance(
                        val.value, str
                    ):
                        yield val.value, "mesh", node.lineno, f"{kw}= keyword"
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            a = node.args
            params = a.posonlyargs + a.args
            defaults = a.defaults
            for arg, default in zip(params[len(params) - len(defaults):], defaults):
                if _AXIS_PARAM_RE.match(arg.arg) and isinstance(
                    default, ast.Constant
                ) and isinstance(default.value, str):
                    yield (
                        default.value, "mesh", node.lineno,
                        f"default of parameter {arg.arg!r}",
                    )
            for arg, default in zip(a.kwonlyargs, a.kw_defaults):
                if default is not None and _AXIS_PARAM_RE.match(
                    arg.arg
                ) and isinstance(default, ast.Constant) and isinstance(
                    default.value, str
                ):
                    yield (
                        default.value, "mesh", node.lineno,
                        f"default of parameter {arg.arg!r}",
                    )
        elif isinstance(node, ast.Subscript):
            v = node.value
            if isinstance(v, ast.Attribute) and v.attr == "shape":
                sl = node.slice
                if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
                    yield sl.value, "mesh", node.lineno, ".shape[...] subscript"
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and _AXES_CONST_RE.match(t.id):
                    for s in _str_entries(node.value):
                        yield s, "any", node.lineno, f"{t.id} constant"


class MeshAxesPass:
    """Stateful so the registry is parsed once per run."""

    pass_id = PASS_ID

    def __init__(self):
        self._key = None
        self._registry: Optional[Dict[str, str]] = None
        self._mesh_axes: Optional[Tuple[str, ...]] = None
        self._error = ""

    def _ensure(self, root: str):
        mesh_path = os.path.join(root, _MESH_REL)
        key = (root, _stamp(mesh_path))
        if self._key == key:
            return
        self._key = key
        self._registry, self._mesh_axes, self._error = load_axis_registry(
            mesh_path
        )

    def _root_of(self, ctx: FileContext) -> Optional[str]:
        suffix = ctx.rel.replace("/", os.sep)
        if ctx.path.endswith(suffix):
            root = ctx.path[: -len(suffix) - 1]
            if os.path.exists(os.path.join(root, _MESH_REL)):
                return root
        return None

    # -- per-file ----------------------------------------------------------

    def check_file(self, ctx: FileContext) -> Iterable[Violation]:
        root = self._root_of(ctx)
        if root is None:
            return
        self._ensure(root)
        if self._registry is None:
            return  # the registry parse failure is reported repo-level
        for axis, required, line, where in iter_axis_sites(ctx):
            kind = self._registry.get(axis)
            if kind is None:
                yield Violation(
                    PASS_ID,
                    ctx.rel,
                    line,
                    f"axis name {axis!r} ({where}) is not in "
                    "parallel/mesh.py MESH_AXIS_REGISTRY — a typo'd axis "
                    "silently stops constraining (flax NO_CONSTRAINT "
                    "fallback); register it or fix the name",
                    code=ctx.code_at(line),
                )
            elif required != "any" and kind != required:
                yield Violation(
                    PASS_ID,
                    ctx.rel,
                    line,
                    f"axis {axis!r} ({where}) is registered as a {kind} "
                    f"axis but this site requires a {required} axis — "
                    + (
                        "a mesh axis in a logical annotation is exactly "
                        "the silent-no-constraint drift"
                        if required == "logical"
                        else "collectives/mesh lookups ride physical "
                        "mesh axes, not logical names"
                    ),
                    code=ctx.code_at(line),
                )

    # -- repo-level --------------------------------------------------------

    def repo_check(
        self, root: str, contexts: List[FileContext]
    ) -> Iterable[Violation]:
        mesh_path = os.path.join(root, _MESH_REL)
        if not os.path.exists(mesh_path):
            return
        self._ensure(root)
        if self._registry is None:
            yield Violation(
                PASS_ID, _MESH_POSIX, 0,
                f"mesh-axis registry unreadable: {self._error}",
                code="registry-parse",
            )
            return
        registry = self._registry
        mesh_kind = tuple(k for k, v in registry.items() if v == "mesh")
        logical_kind = {k for k, v in registry.items() if v == "logical"}

        # 1. MESH_AXES must equal the registry's mesh entries, in order
        if self._mesh_axes is None or self._mesh_axes != mesh_kind:
            yield Violation(
                PASS_ID, _MESH_POSIX, 0,
                f"MESH_AXES {self._mesh_axes!r} != registry mesh axes "
                f"{mesh_kind!r} — build_mesh's reshape order is "
                "load-bearing; keep the tuple and the registry in sync",
                code="mesh-axes-drift",
            )

        # collect sites + Mesh() constructions over the scanned tree —
        # reusing run_lint's already-parsed contexts; disk parses only
        # for scan files outside the lint scope (subset runs)
        by_rel = {ctx.rel: ctx for ctx in contexts}
        referenced: Set[str] = set()
        scan_paths: List[str] = []
        pkg = os.path.join(root, "dlrover_tpu")
        for d in _SCAN_DIRS:
            base = os.path.join(pkg, d)
            if os.path.isdir(base):
                for dirpath, dirnames, filenames in os.walk(base):
                    dirnames[:] = [x for x in dirnames if x != "__pycache__"]
                    scan_paths.extend(
                        os.path.join(dirpath, fn)
                        for fn in sorted(filenames)
                        if fn.endswith(".py")
                    )
        scan_paths.extend(
            p
            for f in _SCAN_FILES
            if os.path.exists(p := os.path.join(pkg, f.replace("/", os.sep)))
        )
        for path in scan_paths:
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            fctx = by_rel.get(rel) or FileContext.parse(path, rel)
            if fctx is None:
                continue
            for axis, _req, _line, _where in iter_axis_sites(fctx):
                referenced.add(axis)
            # 2. Mesh construction sites take MESH_AXES or registered
            #    literal tuples
            for node in ast.walk(fctx.tree):
                if not (
                    isinstance(node, ast.Call)
                    and call_name(node) == "Mesh"
                ):
                    continue
                # positional or keyword form: Mesh(devs, axes) /
                # Mesh(devs, axis_names=axes)
                axes_arg = (
                    node.args[1]
                    if len(node.args) >= 2
                    else keyword_map(node).get("axis_names")
                )
                if axes_arg is None:
                    continue  # not a jax Mesh construction
                if isinstance(axes_arg, ast.Name) and axes_arg.id == "MESH_AXES":
                    referenced.update(mesh_kind)
                    continue
                literals = list(_str_entries(axes_arg))
                if literals:
                    referenced.update(literals)
                    bad = [a for a in literals if a not in mesh_kind]
                    if bad:
                        yield Violation(
                            PASS_ID, rel, node.lineno,
                            f"Mesh(...) constructed with unregistered "
                            f"axes {bad!r} — mesh construction and the "
                            "registry must agree",
                            code=fctx.code_at(node.lineno),
                        )
                else:
                    yield Violation(
                        PASS_ID, rel, node.lineno,
                        "Mesh(...) constructed with axes that are "
                        "neither MESH_AXES nor a literal tuple — the "
                        "registry cross-check cannot see this mesh; "
                        "route it through MESH_AXES",
                        code=fctx.code_at(node.lineno),
                    )

        # 3. DEFAULT_RULES conformance
        rules_keys: Set[str] = set()
        sharding_path = os.path.join(root, _SHARDING_REL)
        if os.path.exists(sharding_path):
            sctx = by_rel.get(_SHARDING_POSIX)
            if sctx is not None:
                stree = sctx.tree
            else:
                try:
                    stree = ast.parse(
                        open(sharding_path, encoding="utf-8").read()
                    )
                except (OSError, SyntaxError):
                    stree = None
            rules_node = (
                _literal_assign(stree, "DEFAULT_RULES") if stree else None
            )
            rules = None
            if rules_node is not None:
                try:
                    rules = ast.literal_eval(rules_node)
                except (ValueError, TypeError):
                    rules = None
            if rules is None:
                yield Violation(
                    PASS_ID, _SHARDING_POSIX, 0,
                    "DEFAULT_RULES is not a pure-literal list — the "
                    "logical→mesh cross-check cannot see it",
                    code="rules-parse",
                )
            else:
                for entry in rules:
                    logical, target = entry[0], entry[1]
                    rules_keys.add(logical)
                    referenced.add(logical)
                    targets = (
                        tuple(target)
                        if isinstance(target, (tuple, list))
                        else (target,)
                    )
                    for t in targets:
                        if t is None:
                            continue
                        referenced.add(t)
                        if t not in mesh_kind:
                            yield Violation(
                                PASS_ID, _SHARDING_POSIX, 0,
                                f"DEFAULT_RULES maps {logical!r} onto "
                                f"{t!r}, which is not a registered mesh "
                                "axis",
                                code=f"rule-target:{logical}:{t}",
                            )
                    if logical not in logical_kind:
                        yield Violation(
                            PASS_ID, _SHARDING_POSIX, 0,
                            f"DEFAULT_RULES key {logical!r} is not a "
                            "registered logical axis",
                            code=f"rule-key:{logical}",
                        )
                for name in sorted(logical_kind - rules_keys):
                    yield Violation(
                        PASS_ID, _SHARDING_POSIX, 0,
                        f"logical axis {name!r} is registered but "
                        "DEFAULT_RULES does not map it — add a rule or "
                        "delete the entry",
                        code=f"unmapped:{name}",
                    )

        # 4. staleness: registered axes nobody references
        for name in sorted(set(registry) - referenced):
            yield Violation(
                PASS_ID, _MESH_POSIX, 0,
                f"registered axis {name!r} is referenced by no spec "
                "site, rule or mesh construction — delete the entry "
                "(the registry must not rot)",
                code=f"stale:{name}",
            )


PASS = MeshAxesPass()
check_file = PASS.check_file
repo_check = PASS.repo_check
