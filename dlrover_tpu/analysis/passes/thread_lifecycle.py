"""thread-lifecycle: every thread/child process has an owner that
reaps it.

Incident (PR 8): the profiler's stack-dump test could never pass once
the suite process leaked its 100th thread — faulthandler hard-truncates
the dump at 100 threads, newest-first, so the main thread fell off the
end. The leak came from exactly this class: threads started by an
owner whose stop path never joined them, and orphaned ``Popen``
children (the chip-watch reaper exists because of the same class one
level down).

Rule, per ``threading.Thread(...)`` creation:

- ``daemon=True`` at construction (or ``x.daemon = True`` before
  ``start``) is fine — the interpreter reaps it; OR
- the handle the thread is stored in (``self._t = Thread(...)``,
  ``t = Thread(...)``, ``threads.append(Thread(...))``, a
  comprehension assigned to a name) must be ``join``-ed **with a
  timeout** somewhere in the same file (the owner's stop/close path;
  an untimed join just moves the hang to the joiner — PR 3's
  blocking-under-lock incidents); OR
- a thread constructed and started with no handle at all is an error:
  nobody can ever join it.

Per ``subprocess.Popen(...)`` creation: the stored handle must have a
reachable ``wait``/``communicate``/``kill``/``terminate`` in the same
file — a Popen nobody reaps is a zombie on exit and an orphan on
crash (the chip-watch ``_reap_orphan_workers`` incident). Passing the
handle into a function named like a reaper
(``kill_process_group(proc)``) also counts — that is the scalers'
shared teardown idiom.

The check is per-file and name-based: a handle handed to another
module for reaping needs a ``# tpulint: ignore[thread-lifecycle]``
with the reason naming the reaper.
"""

import ast
import re
from typing import Iterable, List, Optional, Set, Tuple

from ..core import FileContext, Violation, dotted_name

PASS_ID = "thread-lifecycle"

_REAP_VERBS = {"wait", "communicate", "kill", "terminate"}
# a handle passed INTO a reaper function counts: the scalers hand their
# Popen to common.proc.kill_process_group, which waits and escalates
_REAPER_FN = re.compile(r"(kill|reap|stop|wait|terminate|shutdown|join)", re.I)


def _is_thread_ctor(call: ast.Call) -> bool:
    d = dotted_name(call.func)
    return d in ("threading.Thread", "Thread")


def _is_popen_ctor(call: ast.Call) -> bool:
    d = dotted_name(call.func)
    return d in ("subprocess.Popen", "Popen")


def _daemon_true(call: ast.Call) -> bool:
    for k in call.keywords:
        if k.arg == "daemon":
            return isinstance(k.value, ast.Constant) and k.value.value is True
    return False


def _leaf_name(expr: ast.expr) -> Optional[str]:
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    return None


def _timed_join(call: ast.Call) -> bool:
    if call.args and not isinstance(
        call.args[0], (ast.GeneratorExp, ast.ListComp)
    ):
        return True
    return any(k.arg == "timeout" for k in call.keywords)


class _FileFacts(ast.NodeVisitor):
    """One linear scan: creations with their handles, join/reap
    receivers, daemon-after-construction names, loop aliases."""

    def __init__(self) -> None:
        self.threads: List[Tuple[ast.Call, Optional[str]]] = []
        self.popens: List[Tuple[ast.Call, Optional[str]]] = []
        self.joined: Set[str] = set()  # timed-join receivers
        self.reaped: Set[str] = set()  # wait/kill/... receivers
        self.daemonized: Set[str] = set()  # x.daemon = True after ctor
        # for-loop variable -> names appearing in the iterable
        self.aliases: List[Tuple[str, Set[str]]] = []
        self._handle: List[Optional[str]] = [None]

    # -- handle tracking -------------------------------------------------
    def visit_Assign(self, node: ast.Assign) -> None:
        handle = _leaf_name(node.targets[0]) if len(node.targets) == 1 else None
        # x.daemon = True after construction
        if (
            isinstance(node.targets[0], ast.Attribute)
            and node.targets[0].attr == "daemon"
            and isinstance(node.value, ast.Constant)
            and node.value.value is True
        ):
            owner = _leaf_name(node.targets[0].value)
            if owner:
                self.daemonized.add(owner)
        self._handle.append(handle)
        self.generic_visit(node)
        self._handle.pop()

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        self._handle.append(_leaf_name(node.target))
        self.generic_visit(node)
        self._handle.pop()

    def visit_For(self, node: ast.For) -> None:
        var = _leaf_name(node.target)
        if var:
            src_names = {
                n for n in (
                    _leaf_name(sub)
                    for sub in ast.walk(node.iter)
                    if isinstance(sub, (ast.Name, ast.Attribute))
                )
                if n
            }
            self.aliases.append((var, src_names))
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        if _is_thread_ctor(node):
            self.threads.append((node, self._current_handle(node)))
        elif _is_popen_ctor(node):
            self.popens.append((node, self._current_handle(node)))
        else:
            f = node.func
            if isinstance(f, ast.Attribute):
                recv = _leaf_name(f.value)
                if recv:
                    if f.attr == "join" and _timed_join(node):
                        self.joined.add(recv)
                    elif f.attr in _REAP_VERBS:
                        self.reaped.add(recv)
                    elif f.attr == "setDaemon" and node.args and isinstance(
                        node.args[0], ast.Constant
                    ) and node.args[0].value is True:
                        self.daemonized.add(recv)
                if _REAPER_FN.search(f.attr):
                    self._note_reaper_args(node)
                # xs.append(Thread(...)) -> handle is the container
                if f.attr == "append":
                    recv = _leaf_name(f.value)
                    if recv:
                        self._handle.append(recv)
                        self.generic_visit(node)
                        self._handle.pop()
                        return
            elif isinstance(f, ast.Name) and _REAPER_FN.search(f.id):
                self._note_reaper_args(node)
        self.generic_visit(node)

    def _note_reaper_args(self, node: ast.Call) -> None:
        for a in node.args:
            n = _leaf_name(a)
            if n:
                self.reaped.add(n)
                self.joined.add(n)

    def _current_handle(self, node: ast.Call) -> Optional[str]:
        return self._handle[-1]


def _reachable(handle: str, receivers: Set[str], aliases) -> bool:
    if handle in receivers:
        return True
    # for t in self._threads: t.join(timeout=...) — the loop variable
    # stands for the container handle
    for var, src_names in aliases:
        if handle in src_names and var in receivers:
            return True
    return False


def check_file(ctx: FileContext) -> Iterable[Violation]:
    facts = _FileFacts()
    facts.visit(ctx.tree)

    for call, handle in facts.threads:
        if _daemon_true(call):
            continue
        if handle is None:
            yield Violation(
                PASS_ID,
                ctx.rel,
                call.lineno,
                "non-daemon Thread constructed without a handle — nobody "
                "can ever join it; store it on the owner and join "
                "(timeout=...) in the stop path, or pass daemon=True",
                code=ctx.code_at(call.lineno),
            )
            continue
        if handle in facts.daemonized:
            continue
        if not _reachable(handle, facts.joined, facts.aliases):
            yield Violation(
                PASS_ID,
                ctx.rel,
                call.lineno,
                f"non-daemon Thread stored in {handle!r} is never "
                "join(timeout=...)-ed in this file — the owner's "
                "stop/close path must reap it (the 100-thread "
                "faulthandler-truncation class), or pass daemon=True",
                code=ctx.code_at(call.lineno),
            )

    for call, handle in facts.popens:
        if handle is None:
            yield Violation(
                PASS_ID,
                ctx.rel,
                call.lineno,
                "Popen constructed without a handle — the child can "
                "never be waited or killed (zombie on exit, orphan on "
                "crash)",
                code=ctx.code_at(call.lineno),
            )
            continue
        if not _reachable(
            handle, facts.reaped | facts.joined, facts.aliases
        ):
            yield Violation(
                PASS_ID,
                ctx.rel,
                call.lineno,
                f"Popen stored in {handle!r} has no reachable "
                "wait/communicate/kill/terminate in this file — reap it "
                "in the owner's stop path (the orphan-worker class)",
                code=ctx.code_at(call.lineno),
            )
