"""lock-order: the static lock-acquisition graph must be acyclic.

Incident (PR 8): the pool arbiter's ``step()`` and the tenants' drain
threads each took the same two locks in opposite orders — ``step()``
held the step lock while touching the ledger, a drain-completion
callback held the ledger lock while re-entering arbiter bookkeeping.
The review pass serialized ``step()`` by hand; nothing stops the next
thread from reintroducing the inversion, and an ABBA pair only
deadlocks under exactly the interleaving chaos storms produce.

Rule: build the per-module lock-acquisition graph and error on cycles.

- A *lock* is any ``with``-acquired context manager whose name looks
  like a lock (``lock``/``mutex``/``cond``), identified by its
  qualified attribute path: ``self._ledger_lock`` inside ``class
  Arbiter`` is the node ``Arbiter.self._ledger_lock``; a module-global
  ``_lock`` is ``_lock``. Two instances of one class share the node —
  the *order discipline* is per-site, not per-object.
- An edge ``a -> b`` is recorded whenever ``with b:`` executes while
  ``a`` is held: direct syntactic nesting, and nesting through direct
  same-module calls (``with a: self.m()`` where ``m`` acquires ``b`` —
  transitively through the module's own call graph).
- A cycle means two threads can wait on each other forever. Self-edges
  (re-acquiring the same named lock) are ignored — that is the RLock
  re-entrancy pattern, and the non-reentrant variant is already flagged
  by blocking-under-lock's nested ``acquire`` rule.

The pass sees one module at a time: cross-module lock cycles (arbiter
lock -> tenant lock -> arbiter lock through an object reference) are
invisible to it — that is what the runtime lock-witness sanitizer
(``analysis/witness.py``, ``DLROVER_LOCK_WITNESS=1``) exists to catch.
"""

import ast
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ..core import FileContext, Violation, dotted_name

PASS_ID = "lock-order"

_LOCKY = re.compile(r"(lock|mutex|cond)", re.I)


def _lock_node(expr: ast.expr, cls: str) -> Optional[str]:
    """Qualified lock id for a with-item, or None if not lock-like."""
    d = dotted_name(expr)
    if isinstance(expr, ast.Call):
        d = dotted_name(expr.func)
    if not d:
        return None
    leaf = d.split(".")[-1]
    if not _LOCKY.search(leaf):
        return None
    if d.startswith("self."):
        return f"{cls}.{d}" if cls else d
    return d


class _Func:
    """One function's lock facts: edges it creates and locks it may
    acquire (directly; the transitive set is a later fixpoint)."""

    def __init__(self, key: Tuple[str, str]):
        self.key = key  # (class name or "", func name)
        self.acquires: Set[str] = set()
        # (held locks at the call site, callee key candidates)
        self.calls: List[Tuple[Tuple[str, ...], Tuple[str, str], int]] = []
        # direct nesting edges: (held, acquired, line)
        self.edges: List[Tuple[str, str, int]] = []

    def merge(self, other: "_Func") -> None:
        self.acquires |= other.acquires
        self.calls.extend(other.calls)
        self.edges.extend(other.edges)


def _collect_funcs(
    tree: ast.AST,
) -> List[Tuple[str, Tuple[str, str], ast.AST]]:
    """(lock class context, call key, fn) for EVERY function def —
    including closures: the PR 8 drain threads are nested ``def``s
    whose lock takes must participate in the graph. A method is
    callable as ``self.m()`` -> key (cls, m); module functions and
    closures are callable bare -> key ("", name). Closures keep the
    enclosing class as lock context (``self`` binds through the
    closure)."""
    out: List[Tuple[str, Tuple[str, str], ast.AST]] = []

    def walk(node: ast.AST, cls: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                walk(child, child.name)
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                is_method = isinstance(node, ast.ClassDef)
                key = (cls if is_method else "", child.name)
                out.append((cls, key, child))
                walk(child, cls)
            else:
                walk(child, cls)

    walk(tree, "")
    return out


def _analyze_func(cls: str, key: Tuple[str, str], fn: ast.AST) -> _Func:
    f = _Func(key)

    def visit(node: ast.AST, held: Tuple[str, ...]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return  # nested defs run on their own thread/time, not here
        if isinstance(node, (ast.With, ast.AsyncWith)):
            new_held = held
            for item in node.items:
                lock = _lock_node(item.context_expr, cls)
                if lock is None:
                    continue
                f.acquires.add(lock)
                for h in new_held:
                    if h != lock:
                        f.edges.append((h, lock, node.lineno))
                new_held = new_held + (lock,)
            for st in node.body:
                visit(st, new_held)
            return
        if isinstance(node, ast.Call):
            callee = _callee_key(node, cls)
            if callee is not None:
                # held may be empty: the call still feeds the fixpoint
                # (mid() holding nothing can reach leaf()'s locks)
                f.calls.append((held, callee, node.lineno))
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    for st in fn.body:
        visit(st, ())
    return f


def _callee_key(call: ast.Call, cls: str) -> Optional[Tuple[str, str]]:
    """Same-module callee candidate: ``self.m()`` -> (cls, m);
    bare ``f()`` -> ("", f). Anything else is opaque."""
    fc = call.func
    if (
        isinstance(fc, ast.Attribute)
        and isinstance(fc.value, ast.Name)
        and fc.value.id == "self"
        and cls
    ):
        return (cls, fc.attr)
    if isinstance(fc, ast.Name):
        return ("", fc.id)
    return None


def _transitive_acquires(funcs: Dict[Tuple[str, str], _Func]) -> Dict[
    Tuple[str, str], Set[str]
]:
    """Locks each function may acquire, through same-module calls
    (fixpoint over the module's own call graph)."""
    acq = {k: set(f.acquires) for k, f in funcs.items()}
    changed = True
    while changed:
        changed = False
        for k, f in funcs.items():
            for _held, callee, _line in f.calls:
                target = acq.get(callee)
                if target and not target.issubset(acq[k]):
                    acq[k] |= target
                    changed = True
    return acq


def _sccs(graph: Dict[str, Set[str]]) -> List[List[str]]:
    """Tarjan SCCs, iterative; only components of size >= 2 matter."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    out: List[List[str]] = []
    counter = [0]

    for root in sorted(graph):
        if root in index:
            continue
        work = [(root, iter(sorted(graph.get(root, ()))))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in index:
                    index[nxt] = low[nxt] = counter[0]
                    counter[0] += 1
                    stack.append(nxt)
                    on_stack.add(nxt)
                    work.append((nxt, iter(sorted(graph.get(nxt, ())))))
                    advanced = True
                    break
                if nxt in on_stack:
                    low[node] = min(low[node], index[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                if len(comp) >= 2:
                    out.append(sorted(comp))
    return out


def check_file(ctx: FileContext) -> Iterable[Violation]:
    funcs: Dict[Tuple[str, str], _Func] = {}
    for cls, key, fn in _collect_funcs(ctx.tree):
        f = _analyze_func(cls, key, fn)
        if key in funcs:
            funcs[key].merge(f)  # same-named closures share the key
        else:
            funcs[key] = f
    if not funcs:
        return
    acq = _transitive_acquires(funcs)

    # edge -> first (line) where it is created, for reporting
    edges: Dict[Tuple[str, str], int] = {}
    for f in funcs.values():
        for a, b, line in f.edges:
            edges.setdefault((a, b), line)
        for held, callee, line in f.calls:
            for b in acq.get(callee, ()):
                for a in held:
                    if a != b:
                        edges.setdefault((a, b), line)
    if not edges:
        return

    graph: Dict[str, Set[str]] = {}
    for (a, b), _line in edges.items():
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())

    for comp in _sccs(graph):
        comp_set = set(comp)
        cyc_edges = sorted(
            (line, a, b)
            for (a, b), line in edges.items()
            if a in comp_set and b in comp_set
        )
        line, _a, _b = cyc_edges[0]
        detail = ", ".join(f"{a}->{b} (line {ln})" for ln, a, b in cyc_edges)
        yield Violation(
            PASS_ID,
            ctx.rel,
            line,
            "lock-order cycle — two threads taking these locks in the "
            f"orders shown can deadlock: {detail}; pick one global order "
            "(or narrow a critical section) so the graph is acyclic",
            code="cycle:" + "->".join(comp),
        )
