"""injection-coverage: every chaos injection point is exercised.

Incident (PR 3): the fault plan grammar rejects unknown point names
precisely because a typo'd point would "pass" every recovery test by
never firing. The same failure mode exists one level up: an injection
point wired through the runtime but never *exercised* by any test is a
recovery path nobody has ever actually broken — new points can ship
untested and the first real exercise is production chaos.

Rule: every key of ``chaos/faults.INJECTION_POINTS`` must appear (as a
string) in at least one file under ``tests/`` — directly, or through a
named scenario: a point referenced by ``chaos/scenarios.py`` counts as
covered **because** the pass separately requires every registered
scenario name (``SCENARIOS`` keys) to be exercised by tests, so the
indirection cannot dangle. Both dicts are read from the AST, never by
importing the chaos package.
"""

import ast
import os
from typing import Iterable, List, Tuple

from ..core import FileContext, Violation

PASS_ID = "injection-coverage"

_FAULTS_REL = os.path.join("dlrover_tpu", "chaos", "faults.py")
_FAULTS_POSIX = "dlrover_tpu/chaos/faults.py"
_SCENARIOS_REL = os.path.join("dlrover_tpu", "chaos", "scenarios.py")
_SCENARIOS_POSIX = "dlrover_tpu/chaos/scenarios.py"


def scenario_names(scenarios_path: str) -> List[Tuple[str, int]]:
    """(name, line) for every SCENARIOS registry key, by AST."""
    if not os.path.exists(scenarios_path):
        return []
    tree = ast.parse(open(scenarios_path, encoding="utf-8").read())
    for node in ast.walk(tree):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.target is not None:
            targets = [node.target]
        for t in targets:
            if (
                isinstance(t, ast.Name)
                and t.id == "SCENARIOS"
                and isinstance(getattr(node, "value", None), ast.Dict)
            ):
                return [
                    (k.value, k.lineno)
                    for k in node.value.keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)
                ]
    return []


def injection_points(
    faults_path: str,
) -> List[Tuple[str, int]]:
    """(point_name, line) for every INJECTION_POINTS key, by AST."""
    if not os.path.exists(faults_path):
        return []
    tree = ast.parse(open(faults_path, encoding="utf-8").read())
    for node in ast.walk(tree):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.target is not None:
            targets = [node.target]
        for t in targets:
            if (
                isinstance(t, ast.Name)
                and t.id == "INJECTION_POINTS"
                and isinstance(getattr(node, "value", None), ast.Dict)
            ):
                return [
                    (k.value, k.lineno)
                    for k in node.value.keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)
                ]
    return []


def tests_corpus(tests_dir: str) -> str:
    texts = []
    for dirpath, dirnames, filenames in os.walk(tests_dir):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                texts.append(
                    open(
                        os.path.join(dirpath, fn), encoding="utf-8"
                    ).read()
                )
    return "\n".join(texts)


def check_coverage(
    faults_path: str,
    tests_dir: str,
    scenarios_path: str = "",
    rel: str = _FAULTS_POSIX,
    scenarios_rel: str = _SCENARIOS_POSIX,
) -> Iterable[Violation]:
    points = injection_points(faults_path)
    if not points:
        return
    corpus = tests_corpus(tests_dir) if os.path.isdir(tests_dir) else ""
    scenarios_src = ""
    if scenarios_path and os.path.exists(scenarios_path):
        scenarios_src = open(scenarios_path, encoding="utf-8").read()
        # a scenario only extends coverage if it is itself exercised
        for name, line in scenario_names(scenarios_path):
            if name not in corpus:
                yield Violation(
                    PASS_ID,
                    scenarios_rel,
                    line,
                    f"scenario {name!r} is registered but exercised by "
                    "no test under tests/ — its injection points would "
                    "count as covered through a drill nobody runs",
                    code=f"scenario:{name}",
                )
    for name, line in points:
        if name not in corpus and name not in scenarios_src:
            yield Violation(
                PASS_ID,
                rel,
                line,
                f"injection point {name!r} is exercised by no test under "
                "tests/ (directly or via a named scenario) — a recovery "
                "path nobody has ever broken; add a drill (see "
                "tests/test_faults.py)",
                code=name,
            )


def repo_check(
    root: str, contexts: List[FileContext]
) -> Iterable[Violation]:
    yield from check_coverage(
        os.path.join(root, _FAULTS_REL),
        os.path.join(root, "tests"),
        scenarios_path=os.path.join(root, _SCENARIOS_REL),
    )
