"""host-sync: no device→host synchronization inside a hot path.

Incident (PR 2 / PR 4): one stray ``float(logits)`` in the serving
round serializes host and device — the whole point of the overlapped
decode pipeline (PR 2) and the prefetched input pipeline (PR 4) is
that the device never waits for host bookkeeping. The same applies to
the train-loop step path (a per-step ``float(loss)`` halves step rate
on small models) and to jitted function bodies (where a host sync is a
tracer leak waiting to happen).

Hot paths are explicit, not guessed:

- any function decorated with ``jax.jit`` / ``pjit`` / ``jit`` (bare or
  via ``functools.partial``), and
- any function whose ``def`` carries a ``# tpulint: hotpath`` marker on
  the def line or the comment line directly above it.

Inside a hot function's own body (nested defs excluded — an inner
jitted fn is its own region) the pass flags:

- ``float(...)`` on non-literal arguments (the incident call),
  ``.item()``, ``.tolist()``,
- ``np.asarray`` / ``np.array`` / ``jax.device_get`` /
  ``block_until_ready``,
- per-call heavy imports (``import jax`` / ``import numpy`` inside the
  hot body — the importlib machinery is host work on every call).

Designed sync points (the pipeline drain, the sync A/B baseline, a
log-cadence scalar fetch) stay — with an inline
``# tpulint: ignore[host-sync] <reason>`` that documents *why* the
sync is intentional, which is exactly the review trail PR 2 had to
reconstruct by hand.
"""

import ast
from typing import Iterable, Set

from ..core import FileContext, Violation, call_name, dotted_name, walk_skip_defs

PASS_ID = "host-sync"

_JIT_NAMES = {"jit", "pjit"}
_SYNC_CALLS = {"item", "tolist", "block_until_ready"}
_SYNC_DOTTED = {
    "np.asarray",
    "np.array",
    "numpy.asarray",
    "numpy.array",
    "jax.device_get",
    "jax.block_until_ready",
}
_HEAVY_IMPORTS = {"jax", "numpy"}


def _is_jit_decorator(dec: ast.expr) -> bool:
    if isinstance(dec, ast.Call):
        # functools.partial(jax.jit, ...) or jax.jit(static_argnums=...)
        fn = dec.func
        if dotted_name(fn).endswith("partial") and dec.args:
            return _is_jit_decorator(dec.args[0])
        return _is_jit_decorator(fn)
    dn = dotted_name(dec)
    return dn.split(".")[-1] in _JIT_NAMES


def _hot_functions(ctx: FileContext) -> Iterable[ast.FunctionDef]:
    marker_lines: Set[int] = set(ctx.hotpath_lines)
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if any(_is_jit_decorator(d) for d in node.decorator_list):
            yield node
            continue
        # marker on the def line, or on the comment line(s) directly
        # above the def (skipping decorators)
        first = min(
            [node.lineno] + [d.lineno for d in node.decorator_list]
        )
        probe = {node.lineno, first - 1}
        probe.update(
            ln
            for ln in marker_lines
            if first - 3 <= ln <= node.lineno
        )
        if probe & marker_lines:
            yield node


def check_file(ctx: FileContext) -> Iterable[Violation]:
    for fn in _hot_functions(ctx):
        for st in fn.body:
            for node in walk_skip_defs(st):
                if isinstance(node, (ast.Import, ast.ImportFrom)):
                    mods = (
                        [a.name for a in node.names]
                        if isinstance(node, ast.Import)
                        else [node.module or ""]
                    )
                    for m in mods:
                        if m.split(".")[0] in _HEAVY_IMPORTS:
                            yield Violation(
                                PASS_ID,
                                ctx.rel,
                                node.lineno,
                                f"per-call import of {m!r} inside hot "
                                f"path {fn.name!r} — hoist to module "
                                "level (or a module-local memo)",
                                code=ctx.code_at(node.lineno),
                            )
                    continue
                if not isinstance(node, ast.Call):
                    continue
                name = call_name(node)
                dn = dotted_name(node.func)
                sync = None
                if dn in _SYNC_DOTTED:
                    sync = dn
                elif name in _SYNC_CALLS and isinstance(
                    node.func, ast.Attribute
                ):
                    sync = f".{name}()"
                elif (
                    # float() is the incident call (PR 2's serving
                    # round, PR 4's train loop); int()/bool() on
                    # non-array values are everywhere and would bury
                    # the signal in suppressions
                    name == "float"
                    and isinstance(node.func, ast.Name)
                    and node.args
                    and not isinstance(node.args[0], ast.Constant)
                ):
                    sync = f"{name}()"
                if sync is not None:
                    yield Violation(
                        PASS_ID,
                        ctx.rel,
                        node.lineno,
                        f"host sync {sync} inside hot path {fn.name!r} "
                        "— breaks the decode/input overlap; move it to "
                        "the drain point or suppress with the reason",
                        code=ctx.code_at(node.lineno),
                    )
