"""env-knobs: every ``DLROVER_*`` variable lives in the typed registry.

Incident (PR 4): env knobs kept drifting out of the docs — a knob wired
into the runtime but invisible to operators. PR 4 added an ad-hoc
doc-lint test with its own exemption list; this pass replaces it with a
single source of truth: ``common/constants.py::ENV_KNOBS``, a typed
registry every ``DLROVER_*`` name must be declared in. The invariant is
*documented ⇔ registered ⇔ referenced*:

- (per file) every ``os.environ`` / ``os.getenv`` access of a
  ``DLROVER_*`` name must name a registered knob — an unregistered
  knob is typo-prone, undocumented, and invisible to ``apply_env``
  tooling;
- (repo) every ``DLROVER_*`` token anywhere in runtime source must be a
  registered name or a prefix of one (prose like ``DLROVER_RPC_*``);
- (repo) every registered *operator-tunable* knob (``internal=False``)
  must appear in the docs corpus (README.md + docs/*.md);
- (repo) every registered knob must still be referenced — by a literal
  in source, or through its declared ``Context`` field
  (``context_field``) — a stale registry entry is an error, so the
  exemption list can never rot (the staleness check PR 4's test did by
  hand);
- (repo) every ``Context`` dataclass field of a scalar type must have
  its derived ``DLROVER_<UPPER>`` knob registered (``apply_env``
  accepts the env var whether or not anyone wrote it down — this makes
  writing it down mandatory);
- (repo) every ``DLROVER_*`` token in the docs corpus must be
  registered or a prefix of a registered name (no documenting knobs
  that do not exist).

Internal process-contract variables (agent→worker env contract, bench
plumbing) are registered with ``internal=True`` — exempt from the docs
requirement but still subject to every other rule.
"""

import ast
import importlib.util
import os
import re
from typing import Dict, Iterable, List, Set, Tuple

from ..core import FileContext, Violation, call_name, dotted_name

PASS_ID = "env-knobs"

_ENV_TOKEN = re.compile(r"DLROVER_[A-Z0-9]+(?:_[A-Z0-9]+)*")
_SCALAR_ANNOTATIONS = {"int", "float", "bool", "str"}

_CONSTANTS_REL = os.path.join("dlrover_tpu", "common", "constants.py")
_CONFIG_REL = os.path.join("dlrover_tpu", "common", "config.py")
_CONSTANTS_POSIX = "dlrover_tpu/common/constants.py"


def context_fields(root: str) -> List[Tuple[str, str]]:
    """(field_name, annotation) for Context's scalar dataclass fields,
    by AST so the runtime config module is never imported."""
    path = os.path.join(root, _CONFIG_REL)
    if not os.path.exists(path):
        return []
    tree = ast.parse(open(path, encoding="utf-8").read())
    out: List[Tuple[str, str]] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == "Context":
            for st in node.body:
                if isinstance(st, ast.AnnAssign) and isinstance(
                    st.target, ast.Name
                ):
                    ann = ""
                    if isinstance(st.annotation, ast.Name):
                        ann = st.annotation.id
                    out.append((st.target.id, ann))
    return out


def _env_access_name(call: ast.Call) -> object:
    """The name expression of an env access, or None.

    Matches ``os.getenv(X, ...)``, ``os.environ.get(X, ...)``,
    ``os.environ.setdefault(X, ...)``, ``os.environ.pop(X, ...)``."""
    dn = dotted_name(call.func)
    name = call_name(call)
    if dn in ("os.getenv", "getenv"):
        return call.args[0] if call.args else None
    if name in ("get", "setdefault", "pop") and isinstance(
        call.func, ast.Attribute
    ):
        recv = dotted_name(call.func.value)
        if recv in ("os.environ", "environ"):
            return call.args[0] if call.args else None
    return None


def _literal_knob(expr: ast.AST, constants) -> str:
    """Resolve an env-name expression to a DLROVER_* string: a literal,
    or a ``NodeEnv.X``-style attribute on a constants-module class."""
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return expr.value if expr.value.startswith("DLROVER_") else ""
    if constants is not None and isinstance(expr, ast.Attribute):
        dn = dotted_name(expr)
        parts = dn.split(".")
        obj = constants
        # e.g. NodeEnv.MASTER_ADDR (drop any leading module aliases)
        for p in parts:
            obj = getattr(obj, p, None)
            if obj is None:
                obj = constants
                continue
        if isinstance(obj, str) and obj.startswith("DLROVER_"):
            return obj
    return ""


class EnvKnobsPass:
    """Stateful so the registry is loaded once per run."""

    pass_id = PASS_ID

    def __init__(self):
        self._registry = None
        self._constants_mod = None
        self._root = None

    def _ensure(self, root: str):
        if self._root != root:
            self._root = root
            path = os.path.join(root, _CONSTANTS_REL)
            spec = importlib.util.spec_from_file_location(
                "_tpulint_constants", path
            )
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            self._constants_mod = mod
            self._registry = dict(getattr(mod, "ENV_KNOBS", {}))

    # -- per-file ----------------------------------------------------------

    def check_file(self, ctx: FileContext) -> Iterable[Violation]:
        # the file's own repo root: constants.py sits two levels up from
        # common/, three from deeper packages — derive from rel path
        root = ctx.path[: -len(ctx.rel) - 1] if ctx.path.endswith(ctx.rel.replace("/", os.sep)) else None
        if root is None or not os.path.exists(
            os.path.join(root, _CONSTANTS_REL)
        ):
            return
        self._ensure(root)
        if self._registry is None:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name_expr = _env_access_name(node)
            if name_expr is None:
                continue
            knob = _literal_knob(name_expr, self._constants_mod)
            if knob and knob not in self._registry:
                yield Violation(
                    PASS_ID,
                    ctx.rel,
                    node.lineno,
                    f"env access of unregistered knob {knob!r} — declare "
                    "it in common/constants.py ENV_KNOBS (type, default, "
                    "doc, internal flag)",
                    code=ctx.code_at(node.lineno),
                )

    # -- repo-level --------------------------------------------------------

    def repo_check(
        self, root: str, contexts: List[FileContext]
    ) -> Iterable[Violation]:
        if not os.path.exists(os.path.join(root, _CONSTANTS_REL)):
            return
        self._ensure(root)
        registry = self._registry or {}
        names = set(registry)

        def covered(token: str) -> bool:
            return token in names or any(
                n.startswith(token + "_") for n in names
            )

        # 1. every token in runtime source is registered (or a prefix).
        # Scanned from disk, not from the lint target set: staleness and
        # reference checks must see the whole package even when only a
        # subdirectory is being linted.
        seen_tokens: Dict[str, Tuple[str, int]] = {}
        # Reference set for the staleness rule (4): tokens OUTSIDE
        # constants.py — the registry's own declaration of a knob must
        # not count as a "reference" or the staleness check is vacuous.
        # Attribute-style usages (os.getenv(NodeEnv.MASTER_ADDR)) are
        # resolved through the loaded constants module: many contract
        # vars appear as a literal ONLY in the NodeEnv class.
        ref_tokens: Set[str] = set()
        attr_re = re.compile(r"\bNodeEnv\.([A-Z][A-Z0-9_]*)\b")
        node_env = getattr(self._constants_mod, "NodeEnv", None)
        pkg = os.path.join(root, "dlrover_tpu")
        for dirpath, dirnames, filenames in os.walk(pkg):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                fpath = os.path.join(dirpath, fn)
                rel = os.path.relpath(fpath, root).replace(os.sep, "/")
                try:
                    text = open(fpath, encoding="utf-8").read()
                except OSError:
                    continue
                is_registry = rel == _CONSTANTS_POSIX
                for i, line in enumerate(text.splitlines(), start=1):
                    for m in _ENV_TOKEN.finditer(line):
                        seen_tokens.setdefault(m.group(0), (rel, i))
                        if not is_registry:
                            ref_tokens.add(m.group(0))
                if not is_registry and node_env is not None:
                    for m in attr_re.finditer(text):
                        val = getattr(node_env, m.group(1), None)
                        if isinstance(val, str):
                            ref_tokens.add(val)
        for tok, (rel, line) in sorted(seen_tokens.items()):
            if not covered(tok):
                yield Violation(
                    PASS_ID,
                    rel,
                    line,
                    f"{tok!r} referenced in source but not registered in "
                    "ENV_KNOBS — register it (or fix the name)",
                    code=tok,
                )

        # 2. docs coverage for operator-tunable knobs; 3. docs tokens
        #    must be registered
        corpus, doc_tokens = _doc_corpus(root)
        for name in sorted(names):
            knob = registry[name]
            if getattr(knob, "internal", False):
                continue
            if name not in corpus:
                yield Violation(
                    PASS_ID,
                    _CONSTANTS_POSIX,
                    0,
                    f"operator-tunable knob {name!r} is registered but "
                    "undocumented — add it to README.md or docs/ (the "
                    "docs/analysis.md knob table)",
                    code=f"undocumented:{name}",
                )
        for tok, src in sorted(doc_tokens.items()):
            if not covered(tok):
                yield Violation(
                    PASS_ID,
                    src,
                    0,
                    f"{tok!r} appears in the docs but is not a registered "
                    "knob — fix the docs or register it",
                    code=f"doc-unknown:{tok}",
                )

        # 4. staleness: every registered knob must still be referenced
        # OUTSIDE its own registry entry (literal token, resolved
        # NodeEnv attribute, or its declared Context field)
        ctx_fields = {f for f, _ann in context_fields(root)}
        for name in sorted(names):
            knob = registry[name]
            cf = getattr(knob, "context_field", "")
            referenced = name in ref_tokens or (cf and cf in ctx_fields)
            if not referenced:
                yield Violation(
                    PASS_ID,
                    _CONSTANTS_POSIX,
                    0,
                    f"registered knob {name!r} is no longer referenced "
                    "anywhere in dlrover_tpu/ — delete the entry (the "
                    "registry must not rot)",
                    code=f"stale:{name}",
                )

        # 5. every scalar Context field has its derived knob registered
        for field, ann in context_fields(root):
            if ann not in _SCALAR_ANNOTATIONS:
                continue
            derived = "DLROVER_" + field.upper()
            if derived not in names:
                yield Violation(
                    PASS_ID,
                    _CONSTANTS_POSIX,
                    0,
                    f"Context.{field} is env-overridable as {derived!r} "
                    "but unregistered — apply_env accepts it whether or "
                    "not it is written down; register it",
                    code=f"context-unregistered:{derived}",
                )


def _doc_corpus(root: str) -> Tuple[str, Dict[str, str]]:
    texts: List[str] = []
    tokens: Dict[str, str] = {}
    candidates = [os.path.join(root, "README.md")]
    docs = os.path.join(root, "docs")
    if os.path.isdir(docs):
        candidates.extend(
            os.path.join(docs, n)
            for n in sorted(os.listdir(docs))
            if n.endswith(".md")
        )
    for path in candidates:
        if not os.path.exists(path):
            continue
        text = open(path, encoding="utf-8").read()
        texts.append(text)
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        for m in _ENV_TOKEN.finditer(text):
            tokens.setdefault(m.group(0), rel)
    return "\n".join(texts), tokens


# the runner instantiates stateless module-level passes via functions;
# this one is a singleton object
PASS = EnvKnobsPass()
check_file = PASS.check_file
repo_check = PASS.repo_check
