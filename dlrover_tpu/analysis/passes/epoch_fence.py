"""epoch-fence: every RPC response stamps the master epoch; every
client entry rides the fenced path.

Incident (PR 10): the master-kill drill works because BOTH sides of the
fence hold: every servicer response carries ``master_epoch`` (stamped
by the ``_respond`` helper) and every client RPC funnels through
``MasterClient._call``, whose ``_observe_epoch`` detects restarts,
fires the re-attach listeners exactly once per bump, and fences stale
in-flight answers from a dead incarnation. Nothing but convention stops
a NEW endpoint from constructing a bare ``BaseResponse`` (the bump is
invisible to its callers — agents poll a restarted master forever) or
a new client from calling a transport directly (stale responses from
the dead master are believed). The rail must hold through the
resharding refactor's new control-plane surface.

Rule (per file):

- every ``BaseResponse(...)`` construction must pass ``master_epoch=``
  explicitly — via the servicer's ``_respond`` stamping helper in
  practice. A journal-less service stamps 0 (= unfenced) as an
  explicit, greppable decision instead of an accidental default;
- a ``_transport`` verb access (``self._transport.get/report`` —
  called directly OR aliased to a bound method, the
  ``MasterClient._call`` idiom) may only appear in a function that
  also calls ``_observe_epoch`` — the fenced path;
- a ``*Transport`` class may only be instantiated inside
  ``MasterClient`` — anything else is a client-side RPC entry that
  bypasses the fence entirely.
"""

import ast
import re
from typing import Iterable, List, Optional, Tuple

from ..core import FileContext, Violation, call_name

PASS_ID = "epoch-fence"

_TRANSPORT_CLASS_RE = re.compile(r"^[A-Z]\w*Transport$")
_TRANSPORT_VERBS = {"get", "report"}


def _chain_attrs(expr: ast.AST) -> List[str]:
    """Attribute names along ``a.b.c`` (leftmost name excluded)."""
    out: List[str] = []
    while isinstance(expr, ast.Attribute):
        out.append(expr.attr)
        expr = expr.value
    return out


def _function_calls(fn: ast.AST) -> set:
    """Trailing names of every call inside ``fn`` (nested defs
    included: a listener closure calling _observe_epoch still fences)."""
    return {
        call_name(n)
        for n in ast.walk(fn)
        if isinstance(n, ast.Call)
    }


def check_file(ctx: FileContext) -> Iterable[Violation]:
    # enclosing-scope maps, innermost-first
    func_stack: List[ast.AST] = []
    class_stack: List[ast.ClassDef] = []

    def visit(node: ast.AST):
        is_func = isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
        is_class = isinstance(node, ast.ClassDef)
        if is_func:
            func_stack.append(node)
        if is_class:
            class_stack.append(node)
        try:
            if isinstance(node, ast.Call):
                yield from _check_call(node)
            elif isinstance(node, ast.Attribute):
                yield from _check_attribute(node)
            for child in ast.iter_child_nodes(node):
                yield from visit(child)
        finally:
            if is_func:
                func_stack.pop()
            if is_class:
                class_stack.pop()

    def _check_call(node: ast.Call):
        name = call_name(node)
        # 1. BaseResponse must stamp master_epoch
        if name == "BaseResponse":
            kwargs = {k.arg for k in node.keywords}
            if "master_epoch" not in kwargs:
                yield Violation(
                    PASS_ID,
                    ctx.rel,
                    node.lineno,
                    "BaseResponse constructed without master_epoch= — "
                    "an unstamped response is invisible to the client "
                    "fence (agents cannot detect this service's "
                    "restart); route it through a _respond helper that "
                    "stamps self._epoch (0 = journal-less, as an "
                    "explicit decision)",
                    code=ctx.code_at(node.lineno),
                )
        # 3. transports are only built inside MasterClient
        if _TRANSPORT_CLASS_RE.match(name):
            yield from _check_transport_ctor(node, name)

    def _check_attribute(node: ast.Attribute):
        # 2. raw transport verbs only on the fenced path — matched on
        # the ATTRIBUTE access so bound-method aliasing
        # (``fn = self._transport.get; fn(payload)``, the
        # MasterClient._call idiom) cannot evade the fence
        if (
            node.attr in _TRANSPORT_VERBS
            and "_transport" in _chain_attrs(node.value)
        ):
            fn = func_stack[-1] if func_stack else None
            if fn is None or "_observe_epoch" not in _function_calls(fn):
                yield Violation(
                    PASS_ID,
                    ctx.rel,
                    node.lineno,
                    "raw transport call bypasses the epoch fence — the "
                    "enclosing function never calls _observe_epoch, so "
                    "a stale response from a dead master incarnation "
                    "is believed; go through MasterClient._call",
                    code=ctx.code_at(node.lineno),
                )

    def _check_transport_ctor(node: ast.Call, name: str):
        owner: Optional[ast.ClassDef] = (
            class_stack[-1] if class_stack else None
        )
        if owner is None or owner.name != "MasterClient":
            yield Violation(
                PASS_ID,
                ctx.rel,
                node.lineno,
                f"{name} instantiated outside MasterClient — a "
                "client-side RPC entry that never observes the "
                "master epoch; use MasterClient (it owns the "
                "fence, retry and re-attach machinery)",
                code=ctx.code_at(node.lineno),
            )

    yield from visit(ctx.tree)
