"""rpc-deadline: every RPC carries a deadline sourced from Context.

Incident (PR 3): the master client shipped three hard-coded 30s
timeouts; under a chaos storm every retry path waited the same fixed
30s with no backoff, and tuning recovery SLOs meant editing source.
PR 3 re-plumbed them as ``Context.rpc_deadline_s``/``rpc_retries``/
``rpc_backoff_*`` — this pass keeps the next RPC surface from
regressing to a literal.

Rule, applied to RPC call surfaces (``urlopen``, gRPC channel/stub
calls, and any call on a ``channel``/``stub``/``transport`` receiver):

- the call must pass ``timeout=`` (an RPC with *no* deadline blocks
  forever on a dark master), and
- the value must not be a numeric literal — it must be a name/attribute
  ultimately sourced from ``Context`` (``ctx.rpc_deadline_s``, a
  constructor-injected ``self._deadline_s``, a parameter default
  resolved from ``get_context()``).

Additionally, inside the ``rpc/`` package, function parameter defaults
named ``deadline*``/``timeout*`` must not be numeric literals — default
``None`` and resolve from ``get_context()`` at call time, so one env
override (``DLROVER_RPC_DEADLINE_S``) reaches every transport.
"""

import ast
import re
from typing import Iterable

from ..core import (
    FileContext,
    Violation,
    call_name,
    is_number,
    keyword_map,
    receiver_name,
)

PASS_ID = "rpc-deadline"

_RPCISH_RECV = re.compile(r"(channel|stub|transport)", re.I)
_DEADLINE_PARAM = re.compile(r"^(deadline|timeout)", re.I)


def check_file(ctx: FileContext) -> Iterable[Violation]:
    in_rpc_pkg = "/rpc/" in f"/{ctx.rel}"
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call):
            yield from _check_call(ctx, node)
        elif in_rpc_pkg and isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef)
        ):
            yield from _check_defaults(ctx, node)


def _check_call(ctx: FileContext, call: ast.Call) -> Iterable[Violation]:
    name = call_name(call)
    recv = receiver_name(call)
    is_urlopen = name == "urlopen"
    is_rpcish = bool(recv and _RPCISH_RECV.search(recv)) or bool(
        _RPCISH_RECV.search(name)
    )
    if not (is_urlopen or is_rpcish):
        return
    kw = keyword_map(call)
    timeout = kw.get("timeout", kw.get("deadline", kw.get("deadline_s")))
    if timeout is None:
        # urlopen's positional timeout is arg 2
        if is_urlopen and len(call.args) >= 3:
            timeout = call.args[2]
    if timeout is None:
        # Only urlopen is REQUIRED to carry an explicit deadline: a
        # channel/stub receiver also matches setup/teardown calls
        # (unary_unary, close) whose deadline lives elsewhere.
        if is_urlopen:
            yield Violation(
                PASS_ID,
                ctx.rel,
                call.lineno,
                "urlopen() with no deadline — it blocks forever on a "
                "dark peer; pass timeout= from Context",
                code=ctx.code_at(call.lineno),
            )
    elif is_number(timeout):
        surface = "urlopen" if is_urlopen else f"{recv}.{name}"
        yield Violation(
            PASS_ID,
            ctx.rel,
            call.lineno,
            f"hard-coded deadline on RPC call {surface}() — source it "
            "from Context (rpc_deadline_s) so operators can tune "
            "recovery SLOs without editing source",
            code=ctx.code_at(call.lineno),
        )


def _check_defaults(
    ctx: FileContext, fn: ast.FunctionDef
) -> Iterable[Violation]:
    args = fn.args
    pos = args.posonlyargs + args.args
    defaults = list(args.defaults)
    # align defaults to the tail of positional args
    for arg, default in zip(pos[len(pos) - len(defaults):], defaults):
        if _DEADLINE_PARAM.match(arg.arg) and is_number(default):
            yield Violation(
                PASS_ID,
                ctx.rel,
                default.lineno,
                f"literal default for {fn.name}({arg.arg}=...) in the "
                "rpc package — default None and resolve from "
                "get_context() so DLROVER_RPC_* overrides reach it",
                code=ctx.code_at(default.lineno),
            )
    for arg, default in zip(args.kwonlyargs, args.kw_defaults):
        if (
            default is not None
            and _DEADLINE_PARAM.match(arg.arg)
            and is_number(default)
        ):
            yield Violation(
                PASS_ID,
                ctx.rel,
                default.lineno,
                f"literal default for {fn.name}({arg.arg}=...) in the "
                "rpc package — default None and resolve from "
                "get_context() so DLROVER_RPC_* overrides reach it",
                code=ctx.code_at(default.lineno),
            )
