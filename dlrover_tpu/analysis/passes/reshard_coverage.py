"""reshard-coverage: every saved state-tree category has a reshard rule.

Incident (ROADMAP items 1/4 prep): the flash-checkpoint image is what
carries live state between shardings on an elastic world change, and
the durable tier's whole point is "restore INTO a different DP×TP×PP
sharding than the save". Today the restore path reshards whatever the
template's shardings say — there is no table stating what SHOULD happen
to each category of saved state on a rung change, so a new category
(a LoRA adapter tree, EMA params, a new optimizer slot family) rides
along until the first real reshard silently replicates it or crashes
the restore. The dynamic reshard path will be built against
``parallel/sharding.py::RESHARD_RULES``; this pass makes the table
load-bearing before that code exists.

Rule:

- (repo) ``RESHARD_RULES`` must be a pure-literal table; every policy
  must be one of ``RESHARD_POLICIES``; every axis it references must
  be a registered mesh axis (``MESH_AXIS_REGISTRY``);
- (repo) every field of the ``TrainState`` the train loop saves must
  have a rule — a category on the save path with no restore/reshard
  rule is the silent-replication class; a rule for a category that no
  longer exists is stale (tables must not rot);
- (repo) every mesh axis ``DEFAULT_RULES`` can put on a saved leaf
  must be covered by every ``respec``/``mirror_params`` rule, and the
  world ladder's ``ELASTIC_AXES`` must be covered too — otherwise a
  rung change moves an axis the rule table never answered for;
- (per file) a dict-literal state tree handed to
  ``save_to_memory``/``save_to_storage`` may only use categories the
  table covers, and passing ``extra=`` requires the ``extra`` rule —
  new save-site categories fail lint at the call site, with a line to
  suppress on if the category is genuinely out of scope.
"""

import ast
import os
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ..core import FileContext, Violation, call_name, keyword_map
from .mesh_axes import _literal_assign, _stamp, load_axis_registry

PASS_ID = "reshard-coverage"

_SHARDING_REL = os.path.join("dlrover_tpu", "parallel", "sharding.py")
_SHARDING_POSIX = "dlrover_tpu/parallel/sharding.py"
_TRAIN_STEP_REL = os.path.join("dlrover_tpu", "parallel", "train_step.py")
_TRAIN_STEP_POSIX = "dlrover_tpu/parallel/train_step.py"
_MESH_REL = os.path.join("dlrover_tpu", "parallel", "mesh.py")

_SAVE_CALLS = {"save_to_memory", "save_to_storage"}
# policies whose axes set must cover everything a reshard can move
_SHARDED_POLICIES = {"respec", "mirror_params", "mirror_dp"}


def _literals_from(path: str, names: Tuple[str, ...]) -> Dict[str, object]:
    """One parse of ``path``, literal-eval of each requested
    module-level assignment (missing/computed names map to None)."""
    try:
        tree = ast.parse(open(path, encoding="utf-8").read())
    except (OSError, SyntaxError):
        return {n: None for n in names}
    out: Dict[str, object] = {}
    for n in names:
        node = _literal_assign(tree, n)
        try:
            out[n] = ast.literal_eval(node) if node is not None else None
        except (ValueError, TypeError):
            out[n] = None
    return out


def _parse_rules(
    raw: object,
) -> Optional[Dict[str, Tuple[str, Tuple[str, ...]]]]:
    if not isinstance(raw, dict):
        return None
    try:
        return {
            str(k): (str(v[0]), tuple(str(a) for a in v[1]))
            for k, v in raw.items()
        }
    except (TypeError, IndexError):
        return None


def load_tables(root: str) -> Tuple[
    Optional[Dict[str, Tuple[str, Tuple[str, ...]]]],
    Tuple[str, ...],
    Tuple[str, ...],
]:
    """(RESHARD_RULES, RESHARD_POLICIES, ELASTIC_AXES) parsed by AST."""
    lits = _literals_from(
        os.path.join(root, _SHARDING_REL),
        ("RESHARD_RULES", "RESHARD_POLICIES", "ELASTIC_AXES"),
    )
    return (
        _parse_rules(lits["RESHARD_RULES"]),
        tuple(lits["RESHARD_POLICIES"] or ()),
        tuple(lits["ELASTIC_AXES"] or ()),
    )


def train_state_fields(root: str) -> Optional[List[str]]:
    """Field names of parallel/train_step.py::TrainState, by AST.
    None when the file or class is unreadable — callers must NOT treat
    that as "zero fields" (it would misreport every rule as stale)."""
    path = os.path.join(root, _TRAIN_STEP_REL)
    try:
        tree = ast.parse(open(path, encoding="utf-8").read())
    except (OSError, SyntaxError):
        return None
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == "TrainState":
            return [
                st.target.id
                for st in node.body
                if isinstance(st, ast.AnnAssign)
                and isinstance(st.target, ast.Name)
            ]
    return None


def _default_rules_axes(rules: object) -> Set[str]:
    """Mesh axes a DEFAULT_RULES literal can place on a saved leaf."""
    axes: Set[str] = set()
    for entry in rules if isinstance(rules, list) else []:
        target = entry[1]
        targets = (
            tuple(target) if isinstance(target, (tuple, list)) else (target,)
        )
        axes.update(t for t in targets if isinstance(t, str))
    return axes


class ReshardCoveragePass:
    """Stateful so the tables are parsed once per run."""

    pass_id = PASS_ID

    def __init__(self):
        self._key = None
        self._rules = None
        self._policies: Tuple[str, ...] = ()
        self._elastic: Tuple[str, ...] = ()
        self._default_axes: Set[str] = set()

    def _ensure(self, root: str):
        sharding = os.path.join(root, _SHARDING_REL)
        key = (root, _stamp(sharding))
        if self._key == key:
            return
        self._key = key
        lits = _literals_from(
            sharding,
            (
                "RESHARD_RULES",
                "RESHARD_POLICIES",
                "ELASTIC_AXES",
                "DEFAULT_RULES",
            ),
        )
        self._rules = _parse_rules(lits["RESHARD_RULES"])
        self._policies = tuple(lits["RESHARD_POLICIES"] or ())
        self._elastic = tuple(lits["ELASTIC_AXES"] or ())
        self._default_axes = _default_rules_axes(lits["DEFAULT_RULES"])

    def _root_of(self, ctx: FileContext) -> Optional[str]:
        suffix = ctx.rel.replace("/", os.sep)
        if ctx.path.endswith(suffix):
            root = ctx.path[: -len(suffix) - 1]
            if os.path.exists(os.path.join(root, _SHARDING_REL)):
                return root
        return None

    # -- per-file ----------------------------------------------------------

    def check_file(self, ctx: FileContext) -> Iterable[Violation]:
        root = self._root_of(ctx)
        if root is None:
            return
        self._ensure(root)
        if self._rules is None:
            return  # table parse failure is reported repo-level
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if call_name(node) not in _SAVE_CALLS:
                continue
            state_arg = node.args[1] if len(node.args) > 1 else None
            if isinstance(state_arg, ast.Dict):
                for key in state_arg.keys:
                    if not (
                        isinstance(key, ast.Constant)
                        and isinstance(key.value, str)
                    ):
                        continue
                    if key.value not in self._rules:
                        yield Violation(
                            PASS_ID,
                            ctx.rel,
                            node.lineno,
                            f"state-tree category {key.value!r} is saved "
                            "here but parallel/sharding.py RESHARD_RULES "
                            "has no restore/reshard rule for it — on a "
                            "world-ladder rung change it silently "
                            "replicates or breaks the restore; add the "
                            "rule",
                            code=ctx.code_at(node.lineno),
                        )
            if "extra" in keyword_map(node) and "extra" not in self._rules:
                yield Violation(
                    PASS_ID,
                    ctx.rel,
                    node.lineno,
                    "save site passes extra= but RESHARD_RULES has no "
                    "'extra' rule — the side-channel payload has no "
                    "declared restore behavior across a reshard",
                    code=ctx.code_at(node.lineno),
                )

    # -- repo-level --------------------------------------------------------

    def repo_check(
        self, root: str, contexts: List[FileContext]
    ) -> Iterable[Violation]:
        if not os.path.exists(os.path.join(root, _SHARDING_REL)):
            return
        self._ensure(root)
        if self._rules is None:
            yield Violation(
                PASS_ID, _SHARDING_POSIX, 0,
                "RESHARD_RULES missing or not a pure-literal dict of "
                "category -> (policy, axes) — the reshard rail cannot "
                "be statically verified",
                code="table-parse",
            )
            return
        rules = self._rules
        registry, _mesh_axes, _err = load_axis_registry(
            os.path.join(root, _MESH_REL)
        )
        mesh_axes = {
            k for k, v in (registry or {}).items() if v == "mesh"
        }

        for cat in sorted(rules):
            policy, axes = rules[cat]
            if self._policies and policy not in self._policies:
                yield Violation(
                    PASS_ID, _SHARDING_POSIX, 0,
                    f"reshard rule {cat!r} uses unknown policy "
                    f"{policy!r} (known: {', '.join(self._policies)})",
                    code=f"policy:{cat}",
                )
            for a in axes:
                if registry is not None and a not in mesh_axes:
                    yield Violation(
                        PASS_ID, _SHARDING_POSIX, 0,
                        f"reshard rule {cat!r} references {a!r}, which "
                        "is not a registered mesh axis",
                        code=f"axis:{cat}:{a}",
                    )

        fields = train_state_fields(root)
        if fields is None:
            # NOT zero fields: reporting every rule as "stale; delete
            # it" against a mid-edit syntax error would be destructive
            # advice. One parse finding, coverage checks skipped.
            yield Violation(
                PASS_ID, _TRAIN_STEP_POSIX, 0,
                "TrainState unreadable (missing file, syntax error, or "
                "renamed class) — the reshard coverage/staleness "
                "checks cannot run; fix parallel/train_step.py",
                code="trainstate-parse",
            )
        else:
            for f in fields:
                if f not in rules:
                    yield Violation(
                        PASS_ID, _TRAIN_STEP_POSIX, 0,
                        f"TrainState.{f} rides the checkpoint save path "
                        "but RESHARD_RULES has no rule for it — "
                        "'restore into a different sharding' is "
                        "undefined for this category; add the rule",
                        code=f"uncovered:{f}",
                    )
            known = set(fields) | {"extra"}
            for cat in sorted(set(rules) - known):
                yield Violation(
                    PASS_ID, _SHARDING_POSIX, 0,
                    f"reshard rule {cat!r} matches no TrainState field "
                    "and no engine category — stale entry; delete it "
                    "(the table must not rot)",
                    code=f"stale:{cat}",
                )

        reachable = set(self._default_axes)
        if registry is not None:
            reachable &= mesh_axes  # unregistered targets are mesh-axes' finding
        for cat in sorted(rules):
            policy, axes = rules[cat]
            if policy not in _SHARDED_POLICIES:
                continue
            for a in sorted(reachable - set(axes)):
                yield Violation(
                    PASS_ID, _SHARDING_POSIX, 0,
                    f"DEFAULT_RULES can shard a saved leaf over {a!r} "
                    f"but reshard rule {cat!r} does not cover that axis "
                    "— a save under that sharding has no declared "
                    "restore behavior",
                    code=f"axis-gap:{cat}:{a}",
                )
            for a in self._elastic:
                if a not in axes:
                    yield Violation(
                        PASS_ID, _SHARDING_POSIX, 0,
                        f"world-ladder rung changes move {a!r} "
                        f"(ELASTIC_AXES) but reshard rule {cat!r} does "
                        "not cover it — the elastic path would hit an "
                        "unanswered reshard",
                        code=f"rung-gap:{cat}:{a}",
                    )


PASS = ReshardCoveragePass()
check_file = PASS.check_file
repo_check = PASS.repo_check
