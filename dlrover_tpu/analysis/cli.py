"""``tpurun-lint`` — run the runtime-invariant suite from the shell.

Exit status: 0 when clean (no unsuppressed violations, no stale
baseline entries, no malformed suppressions), 1 otherwise, 2 on usage
errors. Pure stdlib: safe in CI images without jax installed.
"""

import argparse
import json
import os
import subprocess
import sys
from typing import List, Optional

from .core import Baseline, LintResult, find_repo_root, iter_py_files, run_lint
from .passes import ALL_PASSES, PASS_BY_ID

DEFAULT_BASELINE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "baseline.json"
)

# --format json schema version. Findings are keyed for cross-commit
# diffing: ``rule`` is the same line-number-free identity the baseline
# matches on (stripped source line or stable token), so a gate can diff
# two commits' findings without every edit above a site reading as a
# new violation. Bump on any breaking key change.
JSON_SCHEMA = "tpurun-lint-findings/1"


def findings_json(result: LintResult) -> dict:
    """The stable machine-readable report: every finding —
    unsuppressed AND suppressed — as (pass, file, line, rule,
    suppression state), deterministically sorted."""
    findings = [
        {
            "pass": v.pass_id,
            "file": v.path,
            "line": v.line,
            "rule": v.code,
            "message": v.message,
            "suppressed": False,
            "reason": "",
        }
        for v in result.violations
    ] + [
        {
            "pass": v.pass_id,
            "file": v.path,
            "line": v.line,
            "rule": v.code,
            "message": v.message,
            "suppressed": True,
            "reason": s.reason,
        }
        for v, s in result.suppressed
    ]
    findings.sort(
        key=lambda f: (f["file"], f["line"], f["pass"], f["rule"], f["suppressed"])
    )
    return {
        "schema": JSON_SCHEMA,
        "findings": findings,
        "counts": {
            "violations": len(result.violations),
            "suppressed": len(result.suppressed),
            "baselined": result.baselined,
            "stale_baseline": len(result.stale_baseline),
            "errors": len(result.errors),
        },
        "stale_baseline": [
            {
                "pass": e.pass_id,
                "file": e.path,
                "rule": e.code,
                "reason": e.reason,
            }
            for e in result.stale_baseline
        ],
        "errors": list(result.errors),
        "clean": result.clean,
    }


def changed_files(root: str, ref: str) -> List[str]:
    """Absolute paths of files changed vs ``ref`` (tracked diffs plus
    untracked files, .gitignore respected). Raises CalledProcessError
    outside a git checkout."""
    diff = subprocess.run(
        ["git", "diff", "--name-only", ref, "--"],
        cwd=root,
        capture_output=True,
        text=True,
        check=True,
        timeout=60,
    ).stdout.splitlines()
    untracked = subprocess.run(
        ["git", "ls-files", "--others", "--exclude-standard"],
        cwd=root,
        capture_output=True,
        text=True,
        check=True,
        timeout=60,
    ).stdout.splitlines()
    out = []
    for rel in dict.fromkeys(diff + untracked):  # ordered de-dupe
        path = os.path.join(root, rel)
        if os.path.exists(path):  # deleted files have nothing to lint
            out.append(os.path.abspath(path))
    return out


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpurun-lint",
        description=(
            "AST lint suite encoding dlrover_tpu's runtime invariants "
            "(import purity, no blocking under locks, acyclic lock "
            "order, thread/Popen lifecycle, no swallowed exceptions, "
            "no host syncs in hot paths, Context-sourced RPC "
            "deadlines, the DLROVER_* knob registry, chaos injection "
            "coverage, HTTP endpoint conformance, the SPMD mesh-axis "
            "registry, checkpoint reshard-rule coverage, WAL "
            "record/replay conformance, the master-epoch fence). See "
            "docs/analysis.md."
        ),
    )
    p.add_argument(
        "paths",
        nargs="*",
        default=["dlrover_tpu"],
        help="files/directories to lint (default: dlrover_tpu)",
    )
    p.add_argument(
        "--select",
        metavar="PASS[,PASS...]",
        help="run only these passes (see --list-passes)",
    )
    p.add_argument(
        "--changed",
        metavar="REF",
        nargs="?",
        const="HEAD",
        default=None,
        help=(
            "lint only files changed vs REF (git diff --name-only, "
            "default HEAD, plus untracked) — the pre-commit fast path: "
            "repo-wide passes are skipped and baseline staleness is "
            "not assessed (the full gate is tests/test_lint_clean.py)"
        ),
    )
    p.add_argument(
        "--list-passes", action="store_true", help="list passes and exit"
    )
    p.add_argument(
        "--baseline",
        metavar="FILE",
        default=None,
        help=(
            "baseline file of grandfathered violations (default: the "
            "checked-in dlrover_tpu/analysis/baseline.json when present)"
        ),
    )
    p.add_argument(
        "--no-baseline",
        action="store_true",
        help="ignore any baseline file",
    )
    p.add_argument(
        "--write-baseline",
        metavar="FILE",
        nargs="?",
        const="",
        default=None,
        help=(
            "write current violations to FILE (default: the active "
            "baseline path) and exit 0; edit in the per-entry reasons"
        ),
    )
    p.add_argument(
        "--format",
        choices=["text", "json"],
        default="text",
        help="report format",
    )
    p.add_argument(
        "--show-suppressed",
        action="store_true",
        help="also list suppressed sites and their reasons",
    )
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    if args.list_passes:
        for lp in ALL_PASSES:
            doc = (lp.__doc__ or "").strip().splitlines()[0]
            print(f"{lp.PASS_ID:22s} {doc}")
        return 0

    passes = ALL_PASSES
    if args.select:
        wanted = [s.strip() for s in args.select.split(",") if s.strip()]
        unknown = [w for w in wanted if w not in PASS_BY_ID]
        if unknown:
            print(
                f"unknown pass(es): {', '.join(unknown)} "
                f"(known: {', '.join(sorted(PASS_BY_ID))})",
                file=sys.stderr,
            )
            return 2
        passes = [PASS_BY_ID[w] for w in wanted]

    # A typo'd path (or the relative default run from the wrong cwd)
    # must not green-light CI by linting zero files.
    missing = [p for p in args.paths if not os.path.exists(p)]
    if missing:
        print(
            f"path(s) do not exist: {', '.join(missing)}", file=sys.stderr
        )
        return 2
    if not any(True for _ in iter_py_files(args.paths)):
        print(
            f"no Python files under: {', '.join(args.paths)}",
            file=sys.stderr,
        )
        return 2

    baseline_path = args.baseline
    if baseline_path is None and os.path.exists(DEFAULT_BASELINE):
        baseline_path = DEFAULT_BASELINE
    baseline = None
    if baseline_path and not args.no_baseline and args.write_baseline is None:
        if os.path.exists(baseline_path):
            baseline = Baseline.load(baseline_path)

    root = find_repo_root(args.paths[0])
    lint_paths = list(args.paths)
    if args.changed is not None:
        if args.write_baseline is not None:
            # a subset run would silently truncate the repo-wide
            # baseline to the changed files' violations
            print(
                "--changed cannot be combined with --write-baseline: "
                "regenerate the baseline from a full run",
                file=sys.stderr,
            )
            return 2
        ref = args.changed
        # argparse ambiguity: `--changed dlrover_tpu` binds the PATH as
        # the ref. A "ref" that is not a rev but exists on disk is a
        # path — shift it back and diff against HEAD.
        if ref != "HEAD" and os.path.exists(ref):
            probe = subprocess.run(
                ["git", "rev-parse", "--verify", "--quiet", ref + "^{commit}"],
                cwd=root,
                capture_output=True,
                timeout=60,
            )
            if probe.returncode != 0:
                if ref not in args.paths:
                    args.paths.append(ref)
                ref = "HEAD"
        try:
            changed = changed_files(root, ref)
        except (subprocess.CalledProcessError, OSError) as e:
            print(f"--changed needs a git checkout: {e}", file=sys.stderr)
            return 2
        scope = [os.path.abspath(p) for p in args.paths]
        lint_paths = [
            f
            for f in changed
            if f.endswith(".py")
            and any(f == s or f.startswith(s + os.sep) for s in scope)
        ]
        if not lint_paths:
            # stderr: --format json owns stdout (the machine contract)
            print(
                f"tpurun-lint: no Python files changed vs {args.changed} "
                f"under {', '.join(args.paths)}",
                file=sys.stderr,
            )
            if args.format == "json":
                empty = LintResult([], [], 0, [], [])
                print(json.dumps(findings_json(empty), indent=2, sort_keys=True))
            return 0
        # repo-wide passes need the whole tree: meaningless on a subset
        skipped = [lp.PASS_ID for lp in passes if not hasattr(lp, "check_file")]
        passes = [lp for lp in passes if hasattr(lp, "check_file")]
        if not passes:
            # --select named only repo-wide passes: exiting 0 here
            # would report "clean" having checked nothing
            print(
                "--changed left no runnable pass (the selected passes "
                f"are all repo-wide: {', '.join(skipped)}); run without "
                "--changed",
                file=sys.stderr,
            )
            return 2
        if skipped:
            print(
                "tpurun-lint: --changed skips repo-wide passes: "
                + ", ".join(skipped),
                file=sys.stderr,
            )

    result = run_lint(
        lint_paths, passes=passes, baseline=baseline, repo_root=root
    )
    if args.changed is not None:
        # staleness cannot be assessed against a subset of the tree
        result.stale_baseline = []

    if args.write_baseline is not None:
        out = args.write_baseline or baseline_path or DEFAULT_BASELINE
        Baseline.from_violations(
            result.violations, reason="grandfathered — TODO: justify"
        ).save(out)
        print(
            f"wrote {len(result.violations)} baseline entr"
            f"{'y' if len(result.violations) == 1 else 'ies'} to {out}"
        )
        return 0

    if args.format == "json":
        print(json.dumps(findings_json(result), indent=2, sort_keys=True))
        return 0 if result.clean else 1

    for v in result.violations:
        print(v.render())
    for err in result.errors:
        print(f"ERROR: {err}")
    for e in result.stale_baseline:
        print(
            f"ERROR: stale baseline entry {e.key()} — the site was fixed "
            "or moved; delete the entry (baselines only shrink)"
        )
    if args.show_suppressed:
        for v, s in result.suppressed:
            print(f"suppressed {v.render()}  [reason: {s.reason}]")
    n = len(result.violations)
    print(
        f"tpurun-lint: {n} violation{'s' if n != 1 else ''}, "
        f"{len(result.suppressed)} suppressed, "
        f"{result.baselined} baselined, "
        f"{len(result.stale_baseline)} stale baseline entr"
        f"{'y' if len(result.stale_baseline) == 1 else 'ies'}, "
        f"{len(result.errors)} error{'s' if len(result.errors) != 1 else ''}"
    )
    return 0 if result.clean else 1


if __name__ == "__main__":
    sys.exit(main())
