"""tpurun-lint core: file model, suppressions, baseline, runner.

The suite encodes runtime invariants this repo has paid for in
incidents (docs/analysis.md tables each pass with the PR that motivated
it).  Everything here is pure ``ast`` + text — importing the analysis
package never imports jax, grpc, or any runtime module, so the suite
runs in milliseconds on any host (CI, a laptop without accelerators, a
pre-commit hook).

Vocabulary:

- A *pass* inspects one parsed file (``FileContext``) or the whole repo
  (``repo_check``) and yields :class:`Violation` records.
- An inline suppression ``# tpulint: ignore[<pass>] <reason>`` on the
  violating line (or the full-line comment directly above it) silences
  one site; the reason is mandatory — a bare ignore is itself reported.
- A *baseline* file grandfathers known sites so the suite can gate CI
  at zero new violations while old debt is paid down; every entry
  carries a written reason and stale entries (the site was fixed or
  moved) are reported as errors so the baseline can only shrink.
"""

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple

# ---------------------------------------------------------------------------
# violations
# ---------------------------------------------------------------------------


@dataclass
class Violation:
    pass_id: str
    path: str  # repo-relative, forward slashes
    line: int  # 1-based; 0 for repo-level findings
    message: str
    # What baseline matching keys on besides (pass, path): the stripped
    # source line for code findings, or a stable token (knob name,
    # injection point) for repo-level findings. Line numbers drift with
    # every edit, so they are display-only.
    code: str = ""

    def render(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        return f"{loc}: [{self.pass_id}] {self.message}"


# ---------------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------------

# `# tpulint: ignore[pass-a,pass-b] reason text`
_SUPPRESS_RE = re.compile(
    r"#\s*tpulint:\s*ignore\[([a-z0-9_,\s-]*)\]\s*(.*)$"
)
# `# tpulint: hotpath [reason]` — marks the NEXT (or same-line) `def` as
# a host-sync hot path (see passes/host_sync.py).
_HOTPATH_RE = re.compile(r"#\s*tpulint:\s*hotpath\b")


@dataclass
class Suppression:
    line: int
    passes: Set[str]
    reason: str
    full_line: bool  # comment-only line (applies to the line below)


@dataclass
class FileContext:
    """One parsed source file, shared by every per-file pass."""

    path: str  # absolute
    rel: str  # repo-relative, forward slashes
    source: str
    lines: List[str]
    tree: ast.AST
    suppressions: List[Suppression] = field(default_factory=list)
    hotpath_lines: Set[int] = field(default_factory=set)

    @classmethod
    def parse(cls, path: str, rel: str) -> Optional["FileContext"]:
        with open(path, encoding="utf-8") as f:
            source = f.read()
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError:
            return None
        ctx = cls(
            path=path,
            rel=rel,
            source=source,
            lines=source.splitlines(),
            tree=tree,
        )
        for i, text in enumerate(ctx.lines, start=1):
            m = _SUPPRESS_RE.search(text)
            if m:
                passes = {
                    p.strip() for p in m.group(1).split(",") if p.strip()
                }
                ctx.suppressions.append(
                    Suppression(
                        line=i,
                        passes=passes,
                        reason=m.group(2).strip(),
                        full_line=text.lstrip().startswith("#"),
                    )
                )
            if _HOTPATH_RE.search(text):
                ctx.hotpath_lines.add(i)
        return ctx

    def suppression_for(self, pass_id: str, line: int) -> Optional[Suppression]:
        """Suppression covering ``line``: same line, or a comment-only
        line directly above (stacked full-line comments chain up)."""
        by_line = {s.line: s for s in self.suppressions}
        s = by_line.get(line)
        if s is not None and pass_id in s.passes:
            return s
        # walk up through contiguous full-line comments
        probe = line - 1
        while probe >= 1 and self.lines[probe - 1].lstrip().startswith("#"):
            s = by_line.get(probe)
            if s is not None and s.full_line and pass_id in s.passes:
                return s
            probe -= 1
        return None

    def code_at(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""


def walk_skip_defs(node: ast.AST) -> Iterable[ast.AST]:
    """Walk ``node``'s subtree WITHOUT descending into nested function
    or lambda bodies — code inside a nested ``def`` does not execute in
    the enclosing region (the saver's factory runner is defined under
    the class lock but runs on its own thread)."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        # function/lambda nodes are opaque wherever they appear —
        # including as the walk root (a nested `def` statement in a
        # with-body is handed to this walker directly)
        if isinstance(
            n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            continue
        stack.extend(ast.iter_child_nodes(n))


def call_name(node: ast.Call) -> str:
    """Trailing name of the called function: ``a.b.c()`` → ``c``."""
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return ""


def receiver_name(node: ast.Call) -> str:
    """Name of the attribute-call receiver: ``self._q.get()`` → ``_q``."""
    f = node.func
    if isinstance(f, ast.Attribute):
        v = f.value
        if isinstance(v, ast.Attribute):
            return v.attr
        if isinstance(v, ast.Name):
            return v.id
    return ""


def dotted_name(expr: ast.AST) -> str:
    """``jax.config.update`` → "jax.config.update" (best effort)."""
    parts: List[str] = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if isinstance(expr, ast.Name):
        parts.append(expr.id)
        return ".".join(reversed(parts))
    return ""


def keyword_map(node: ast.Call) -> Dict[str, ast.expr]:
    return {k.arg: k.value for k in node.keywords if k.arg}


def is_number(expr: ast.AST) -> bool:
    if isinstance(expr, ast.Constant) and isinstance(
        expr.value, (int, float)
    ) and not isinstance(expr.value, bool):
        return True
    # -5, +2.5
    if isinstance(expr, ast.UnaryOp) and isinstance(
        expr.op, (ast.USub, ast.UAdd)
    ):
        return is_number(expr.operand)
    return False


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------


@dataclass
class BaselineEntry:
    pass_id: str
    path: str
    code: str
    reason: str

    def key(self) -> Tuple[str, str, str]:
        return (self.pass_id, self.path, self.code)


class Baseline:
    """Checked-in grandfather list. Matching ignores line numbers (they
    drift); a baselined site is keyed by (pass, file, stripped source
    line / stable token). Entries that no longer match anything are
    *stale* and reported as errors — the file can only shrink."""

    def __init__(self, entries: Optional[List[BaselineEntry]] = None):
        self.entries = entries or []

    @classmethod
    def load(cls, path: str) -> "Baseline":
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
        entries = [
            BaselineEntry(
                pass_id=e["pass"],
                path=e["path"],
                code=e.get("code", ""),
                reason=e.get("reason", ""),
            )
            for e in data.get("entries", [])
        ]
        return cls(entries)

    @classmethod
    def from_violations(
        cls, violations: List[Violation], reason: str = "grandfathered"
    ) -> "Baseline":
        return cls(
            [
                BaselineEntry(
                    pass_id=v.pass_id, path=v.path, code=v.code, reason=reason
                )
                for v in violations
            ]
        )

    def save(self, path: str) -> None:
        data = {
            "_comment": (
                "tpurun-lint baseline: grandfathered violations. Every "
                "entry MUST carry a reason; stale entries are reported "
                "as errors (the file can only shrink). Regenerate with "
                "tpurun-lint --write-baseline."
            ),
            "entries": [
                {
                    "pass": e.pass_id,
                    "path": e.path,
                    "code": e.code,
                    "reason": e.reason,
                }
                for e in self.entries
            ],
        }
        with open(path, "w", encoding="utf-8") as f:
            json.dump(data, f, indent=2, sort_keys=False)
            f.write("\n")

    def filter(
        self, violations: List[Violation]
    ) -> Tuple[List[Violation], List[BaselineEntry], List[str]]:
        """→ (surviving violations, stale entries, entry errors).

        Entry errors cover malformed entries (missing reason)."""
        errors = [
            f"baseline entry {e.key()} has no reason"
            for e in self.entries
            if not e.reason.strip()
        ]
        matched: Set[Tuple[str, str, str]] = set()
        keys = {e.key() for e in self.entries}
        surviving = []
        for v in violations:
            k = (v.pass_id, v.path, v.code)
            if k in keys:
                matched.add(k)
            else:
                surviving.append(v)
        stale = [e for e in self.entries if e.key() not in matched]
        return surviving, stale, errors


# ---------------------------------------------------------------------------
# runner
# ---------------------------------------------------------------------------


def find_repo_root(start: str) -> str:
    """Walk up from ``start`` to the directory holding pyproject.toml
    (falls back to ``start`` so the suite still runs on a bare tree)."""
    cur = os.path.abspath(start)
    if os.path.isfile(cur):
        cur = os.path.dirname(cur)
    while True:
        if os.path.exists(os.path.join(cur, "pyproject.toml")):
            return cur
        parent = os.path.dirname(cur)
        if parent == cur:
            return os.path.abspath(start if os.path.isdir(start) else os.path.dirname(start))
        cur = parent


def iter_py_files(paths: Iterable[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isfile(p) and p.endswith(".py"):
            yield p
        elif os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [
                    d for d in dirnames if d != "__pycache__"
                ]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        yield os.path.join(dirpath, fn)


@dataclass
class LintResult:
    violations: List[Violation]  # unsuppressed, unbaselined
    suppressed: List[Tuple[Violation, Suppression]]
    baselined: int
    stale_baseline: List[BaselineEntry]
    errors: List[str]  # bad suppressions, malformed baseline entries

    @property
    def clean(self) -> bool:
        return not self.violations and not self.stale_baseline and not self.errors


def run_lint(
    paths: List[str],
    passes: Optional[List] = None,
    baseline: Optional[Baseline] = None,
    repo_root: Optional[str] = None,
) -> LintResult:
    """Run ``passes`` (default: the full registry) over ``paths``."""
    from .passes import ALL_PASSES

    active = passes if passes is not None else list(ALL_PASSES)
    root = repo_root or find_repo_root(paths[0] if paths else ".")

    contexts: List[FileContext] = []
    for path in iter_py_files(paths):
        rel = os.path.relpath(os.path.abspath(path), root).replace(os.sep, "/")
        ctx = FileContext.parse(path, rel)
        if ctx is not None:
            contexts.append(ctx)

    raw: List[Violation] = []
    suppressed: List[Tuple[Violation, Suppression]] = []
    errors: List[str] = []

    for lp in active:
        check_file = getattr(lp, "check_file", None)
        if check_file is None:
            continue
        for ctx in contexts:
            for v in check_file(ctx):
                s = ctx.suppression_for(v.pass_id, v.line)
                if s is not None:
                    if not s.reason:
                        errors.append(
                            f"{ctx.rel}:{s.line}: tpulint: ignore"
                            f"[{v.pass_id}] needs a reason — a bare "
                            "ignore hides the incident the rule encodes"
                        )
                    suppressed.append((v, s))
                else:
                    raw.append(v)

    ctx_by_rel = {c.rel: c for c in contexts}
    for lp in active:
        repo_check = getattr(lp, "repo_check", None)
        if repo_check is None:
            continue
        for v in repo_check(root, contexts):
            c = ctx_by_rel.get(v.path)
            if c is None and v.line:
                # hybrid repo passes scan beyond the linted subset
                # (mesh-axes under --changed): a line-anchored finding
                # in an un-linted file must still honor that file's
                # inline suppressions, or the pre-commit fast path
                # reports sites the full gate accepts
                p = os.path.join(root, v.path.replace("/", os.sep))
                c = FileContext.parse(p, v.path)
                if c is not None:
                    ctx_by_rel[v.path] = c
            s = c.suppression_for(v.pass_id, v.line) if c and v.line else None
            if s is not None:
                if not s.reason:
                    errors.append(
                        f"{v.path}:{s.line}: tpulint: ignore"
                        f"[{v.pass_id}] needs a reason — a bare "
                        "ignore hides the incident the rule encodes"
                    )
                suppressed.append((v, s))
            else:
                raw.append(v)

    baselined = 0
    stale: List[BaselineEntry] = []
    if baseline is not None:
        before = len(raw)
        raw, stale, bl_errors = baseline.filter(raw)
        baselined = before - len(raw)
        errors.extend(bl_errors)

    raw.sort(key=lambda v: (v.path, v.line, v.pass_id))
    return LintResult(
        violations=raw,
        suppressed=suppressed,
        baselined=baselined,
        stale_baseline=stale,
        errors=errors,
    )
