"""tpurun-lint: runtime-invariant static analysis for dlrover_tpu.

Ten AST passes, each encoding a rule this repo learned from an incident
(docs/analysis.md): import-purity, blocking-under-lock, lock-order,
thread-lifecycle, exception-swallow, host-sync, rpc-deadline,
env-knobs, injection-coverage, endpoint-conformance — plus the runtime
lock-witness sanitizer (``analysis/witness.py``,
``DLROVER_LOCK_WITNESS=1``) for the inversions static analysis cannot
see. Pure stdlib — importing this package never imports jax or any
runtime module.

Run it::

    tpurun-lint dlrover_tpu            # or: python -m dlrover_tpu.analysis.cli

Suppress one site with a written reason::

    time.sleep(0.1)  # tpulint: ignore[blocking-under-lock] <why>
"""

from .core import Baseline, LintResult, Violation, run_lint

__all__ = ["Baseline", "LintResult", "Violation", "run_lint"]
