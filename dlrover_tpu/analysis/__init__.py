"""tpurun-lint: runtime-invariant static analysis for dlrover_tpu.

Six AST passes, each encoding a rule this repo learned from an incident
(docs/analysis.md): import-purity, blocking-under-lock, host-sync,
rpc-deadline, env-knobs, injection-coverage. Pure stdlib — importing
this package never imports jax or any runtime module.

Run it::

    tpurun-lint dlrover_tpu            # or: python -m dlrover_tpu.analysis.cli

Suppress one site with a written reason::

    time.sleep(0.1)  # tpulint: ignore[blocking-under-lock] <why>
"""

from .core import Baseline, LintResult, Violation, run_lint

__all__ = ["Baseline", "LintResult", "Violation", "run_lint"]
