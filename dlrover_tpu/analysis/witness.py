"""Runtime lock-witness sanitizer: observe real acquisition order.

The static ``lock-order`` pass proves the absence of cycles in what it
can *see* — one module at a time, ``with``-acquired locks, same-module
call edges. Cross-object cycles (arbiter lock → tenant lock → arbiter
lock through an object reference), manual ``acquire()`` spans, and
order decided by data are invisible to it. This module is the dynamic
half: when ``DLROVER_LOCK_WITNESS=1``, :func:`install` (or
:func:`maybe_install` at a runtime entry point) wraps
``threading.Lock``/``threading.RLock`` **creation** so every lock
minted by an instrumented package afterwards records, process-wide,
which locks were held when it was acquired. An observed edge ``A→B``
with an already-witnessed path ``B→…→A`` is an *inversion* — the
interleaving that deadlocks exists, whether or not this run hit it.

Pure stdlib, like the rest of the analysis package: importing (and
running) the witness never touches jax or any runtime module.

Knobs (registered in ``common/constants.py::ENV_KNOBS``):

- ``DLROVER_LOCK_WITNESS``      — truthy: ``maybe_install`` installs.
- ``DLROVER_LOCK_WITNESS_LOG``  — JSONL path: one line per new edge
  and per inversion (post-mortem food).
- ``DLROVER_LOCK_WITNESS_MODE`` — ``report`` (default: count, log) or
  ``raise`` (raise :class:`LockOrderInversion` in the acquiring
  thread — the sanitizer-under-test shape).

Locks are named by creation site (``module:lineno``): two instances
minted at the same site share a name, which is exactly the order
*discipline* the graph checks (same-site self-edges are ignored — the
per-instance order of sibling objects is the static pass's
blocking-under-lock territory). Locks created *before* install (module
globals of already-imported modules) stay raw: install early.
"""

import json
import os
import sys
import threading
import time
from typing import Dict, List, Optional, Set, Tuple

__all__ = [
    "LockOrderInversion",
    "install",
    "uninstall",
    "maybe_install",
    "reset",
    "stats",
    "installed",
]


class LockOrderInversion(RuntimeError):
    """Acquiring this lock creates a cycle in the observed order."""


_ORIG_LOCK = threading.Lock
_ORIG_RLOCK = threading.RLock

# witness-internal state guard: ALWAYS a raw lock (never witnessed)
_state_lock = _ORIG_LOCK()
_installed = False
_packages: Tuple[str, ...] = ()
_mode = "report"
_log_path: Optional[str] = None

# thread ident -> held _WitnessLocks, guarded by _state_lock. NOT a
# threading.local: threading.Lock permits cross-thread release (the
# gateway's async rollout acquires in the handler thread and releases
# in the rollout thread), so release must be able to clean up the
# ACQUIRER's stack from any thread.
_holds_by_thread: Dict[int, List["_WitnessLock"]] = {}
_edges: Dict[Tuple[str, str], int] = {}  # (a, b) -> observation count
_graph: Dict[str, Set[str]] = {}  # adjacency over lock names
_inversions: List[Dict] = []
_lock_count = 0


def _log_line(payload: Dict) -> None:
    if not _log_path:
        return
    try:
        with open(_log_path, "a", encoding="utf-8") as f:
            f.write(json.dumps(payload) + "\n")
    except OSError:
        pass  # the witness must never take the runtime down over a log


def _path_exists(src: str, dst: str) -> bool:
    """DFS: is there a witnessed path src -> ... -> dst? (graph is
    small: one node per lock creation site)"""
    seen = {src}
    stack = [src]
    while stack:
        n = stack.pop()
        if n == dst:
            return True
        for nxt in _graph.get(n, ()):
            if nxt not in seen:
                seen.add(nxt)
                stack.append(nxt)
    return False


def _on_acquired(lock: "_WitnessLock") -> None:
    tid = threading.get_ident()
    tname = threading.current_thread().name
    inversion: Optional[Dict] = None
    new_edges: List[Dict] = []
    with _state_lock:
        held = _holds_by_thread.setdefault(tid, [])
        for h in held:
            if h.name == lock.name:
                continue  # re-entrant / same-site sibling
            key = (h.name, lock.name)
            first = key not in _edges
            _edges[key] = _edges.get(key, 0) + 1
            if first:
                # inversion iff the REVERSE order was already witnessed
                if _path_exists(lock.name, h.name):
                    inversion = {
                        "type": "inversion",
                        "edge": [h.name, lock.name],
                        "thread": tname,
                        "ts": time.time(),
                    }
                    _inversions.append(inversion)
                _graph.setdefault(h.name, set()).add(lock.name)
                _graph.setdefault(lock.name, set())
                new_edges.append(
                    {
                        "type": "edge",
                        "edge": [h.name, lock.name],
                        "thread": tname,
                        "ts": time.time(),
                    }
                )
        held.append(lock)
        lock._owner_stack.append(tid)
    # file I/O OUTSIDE the state lock: the witness must not serialize
    # every acquisition process-wide behind a disk write
    for e in new_edges:
        _log_line(e)
    if inversion is not None:
        _log_line(inversion)
        if _mode == "raise":
            raise LockOrderInversion(
                f"lock-order inversion: acquired {lock.name} while "
                f"holding {inversion['edge'][0]}, but the reverse order "
                "was already witnessed — two threads interleaving these "
                "paths deadlock"
            )


def _on_released(lock: "_WitnessLock") -> None:
    with _state_lock:
        # cross-thread release: clean up the ACQUIRER's stack, not the
        # releasing thread's (threading.Lock permits handoff release)
        owner = (
            lock._owner_stack.pop()
            if lock._owner_stack
            else threading.get_ident()
        )
        held = _holds_by_thread.get(owner)
        if held:
            # release order may differ from acquire order
            # (Condition.wait): drop the LAST occurrence of this lock
            for i in range(len(held) - 1, -1, -1):
                if held[i] is lock:
                    del held[i]
                    break
            if not held:
                del _holds_by_thread[owner]


class _WitnessLock:
    """Order-witnessing wrapper over a real Lock/RLock."""

    # Condition must NOT find these on the wrapper: without them it
    # falls back to calling our acquire/release, which keeps the
    # witness's held-stack honest across cond.wait()
    _BLOCKED = ("_release_save", "_acquire_restore", "_is_owned")

    def __init__(self, inner, name: str):
        self._inner = inner
        self.name = name
        # thread idents that currently hold this lock, in acquire
        # order (guarded by _state_lock) — lets a cross-thread release
        # find the acquirer's held stack
        self._owner_stack: List[int] = []

    def acquire(self, *args, **kwargs):
        got = self._inner.acquire(*args, **kwargs)
        if got:
            try:
                _on_acquired(self)
            except LockOrderInversion:
                # raise-mode: hand the lock back before raising, or the
                # sanitizer's own report wedges every waiter behind us
                _on_released(self)
                self._inner.release()
                raise
        return got

    def release(self):
        _on_released(self)
        self._inner.release()

    def locked(self):
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __getattr__(self, item):
        if item in _WitnessLock._BLOCKED:
            raise AttributeError(item)
        return getattr(self._inner, item)

    def __repr__(self):
        return f"<WitnessLock {self.name} over {self._inner!r}>"


def _caller_site() -> Tuple[str, int]:
    f = sys._getframe(2)
    return f.f_globals.get("__name__", "?"), f.f_lineno


def _should_instrument(module: str) -> bool:
    if module.startswith("dlrover_tpu.analysis"):
        return False  # never witness the witness (or the lint suite)
    return any(
        module == p or module.startswith(p + ".") for p in _packages
    )


def _witness_lock_factory():
    module, lineno = _caller_site()
    inner = _ORIG_LOCK()
    if not _should_instrument(module):
        return inner
    global _lock_count
    with _state_lock:
        _lock_count += 1
    return _WitnessLock(inner, f"{module}:{lineno}")


def _witness_rlock_factory():
    module, lineno = _caller_site()
    inner = _ORIG_RLOCK()
    if not _should_instrument(module):
        return inner
    global _lock_count
    with _state_lock:
        _lock_count += 1
    return _WitnessLock(inner, f"{module}:{lineno}")


def install(
    packages: Tuple[str, ...] = ("dlrover_tpu",),
    mode: Optional[str] = None,
    log_path: Optional[str] = None,
) -> None:
    """Patch ``threading.Lock``/``RLock`` so locks created by
    ``packages`` from now on are witnessed. Idempotent."""
    global _installed, _packages, _mode, _log_path
    _packages = tuple(packages)
    _mode = (
        mode
        or os.environ.get("DLROVER_LOCK_WITNESS_MODE", "report").strip()
        or "report"
    )
    _log_path = log_path or os.environ.get("DLROVER_LOCK_WITNESS_LOG") or None
    if _installed:
        return
    threading.Lock = _witness_lock_factory
    threading.RLock = _witness_rlock_factory
    _installed = True


def uninstall() -> None:
    """Restore the real factories (already-wrapped locks stay wrapped
    and keep working — they delegate to real locks)."""
    global _installed
    threading.Lock = _ORIG_LOCK
    threading.RLock = _ORIG_RLOCK
    _installed = False


def maybe_install() -> bool:
    """Install iff ``DLROVER_LOCK_WITNESS`` is truthy. The runtime
    entry points (pool drill, fleet/pool CLIs) call this so an
    operator can turn the sanitizer on with one env var."""
    if os.environ.get("DLROVER_LOCK_WITNESS", "").strip().lower() in (
        "1",
        "true",
        "yes",
        "on",
    ):
        install()
        return True
    return False


def installed() -> bool:
    return _installed


def reset() -> None:
    """Clear observations (not the installation). Call quiescent —
    held-lock tracking is dropped too."""
    with _state_lock:
        _edges.clear()
        _graph.clear()
        _holds_by_thread.clear()
        del _inversions[:]
        global _lock_count
        _lock_count = 0


def stats() -> Dict:
    with _state_lock:
        return {
            "installed": _installed,
            "locks": _lock_count,
            "edges": len(_edges),
            "acquisitions_with_held": sum(_edges.values()),
            "inversions": list(_inversions),
        }
