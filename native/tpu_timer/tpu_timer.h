// tpu_timer — native profiling / hang-detection core for TPU training.
//
// TPU-native counterpart of the reference's xpu_timer C++ library
// (xpu_timer/xpu_timer/common/manager.h:106 GpuTimerManager,
// metrics.{h,cc} bucketed TFLOPS/latency, manager.cc:393 doHang,
// manager.h:50 KernelTraceManager 24B-trace ring buffer, server/
// hosting_service_server_client.cc Prometheus :18889).
//
// Where xpu_timer intercepts CUDA/NCCL symbols via LD_PRELOAD, XLA has
// no stable per-collective C ABI to hook, so events are *pushed* from
// the runtime layer (Python ctypes around jitted steps / PJRT events)
// and everything downstream of ingestion — aggregation, percentile
// buckets, the hang watchdog, compact timeline file, Prometheus text
// endpoint — is native, off the trainer's critical path.
//
// Threading model: lock-free-ish ingestion (per-call mutex on a small
// struct; events are O(μs) apart at training granularity), background
// poller thread computes aggregates and serves HTTP.

#ifndef DLROVER_TPU_TIMER_H_
#define DLROVER_TPU_TIMER_H_

#include <cstdint>

extern "C" {

// Lifecycle -----------------------------------------------------------------
// Start the core: aggregation thread + HTTP server on `port` (0 = pick a
// free port; returns the bound port, or -1 on failure).
int tt_init(int port);
void tt_shutdown();
int tt_http_port();

// Event ingestion -----------------------------------------------------------
// Kinds mirror the reference's metric families.
enum TTKind : int32_t {
  TT_KIND_MATMUL = 0,     // flops metric -> TFLOPS (op-granular)
  TT_KIND_COLLECTIVE = 1, // bytes metric -> bus GB/s (op-granular)
  TT_KIND_STEP = 2,       // training step
  TT_KIND_H2D = 3,
  TT_KIND_D2H = 4,
  TT_KIND_OTHER = 5,
  // Whole-step compiler-derived work (HLO cost analysis): separate
  // families so step-length durations never pollute the op-granular
  // matmul/collective latency gauges.
  TT_KIND_HLO_FLOPS = 6,
  TT_KIND_HLO_COMM = 7,
  // PJRT-interposer ground truth (pjrt_interposer.cc): device program
  // executions and compilations observed at the driver boundary.
  TT_KIND_EXECUTE = 8,
  TT_KIND_COMPILE = 9,
  TT_KIND_COUNT = 10
};

// Record one completed event. name_id: interned via tt_intern_name.
// dur_us: duration; flops/bytes: work for rate metrics (0 if n/a).
void tt_record(int32_t name_id, int32_t kind, int64_t start_us,
               int64_t dur_us, double flops, double bytes);

// Intern an event name, returning a dense id (stable for process life).
int32_t tt_intern_name(const char* name);

// Step watermarks (hang detection input).
void tt_step_begin(int64_t step);
void tt_step_end(int64_t step);

// Hang detection ------------------------------------------------------------
// A hang is flagged when a step stays open longer than
// max(min_timeout_ms, factor * rolling-median step time).
void tt_config_hang(double factor, int64_t min_timeout_ms);
// 1 if currently hung, else 0.
int tt_hang_status();
// Seconds the current step has been open (0 if none open).
double tt_current_step_open_s();

// Device launch/completion watermarks (fed by the PJRT interposer; the
// reference separates launch vs completion at the driver —
// xpu_timer/common/manager.cc:393-414). A launch marks device work
// enqueued; the matching completion fires when the device-side event
// resolves. The split lets the watchdog tell a wedged device program
// (work in flight, completions stopped) from a stalled host loop
// (step open, nothing in flight).
void tt_device_launch();
void tt_device_complete(int64_t dur_us);
int64_t tt_device_inflight();
// Seconds since the last device completion (-1 if none ever).
double tt_last_device_complete_age_s();
// 0 = no stall; 1 = DEVICE stall: the open step exceeded the hang
// threshold with work in flight and no recent completion; 2 = HOST
// stall: the open step exceeded the threshold with nothing in flight.
int tt_stall_verdict();

// Timeline ------------------------------------------------------------------
// Dump the trace ring buffer to `path` in the compact binary format
// (header "TPUTL001", then 24-byte records: name_id u32, kind u32,
// start_us i64, dur_us u32, step u32). Returns records written.
int64_t tt_dump_timeline(const char* path);

// Dump the interned-name table to `path` as "id\tname\n" lines, so a
// timeline file can be symbolized offline. Returns names written.
int64_t tt_dump_names(const char* path);

// Metrics (pull; also served as Prometheus text over HTTP /metrics) ---------
// Fill `out` with the Prometheus exposition text; returns bytes written
// (truncated to cap). Thread-safe snapshot.
int64_t tt_metrics_text(char* out, int64_t cap);

}  // extern "C"

#endif  // DLROVER_TPU_TIMER_H_
