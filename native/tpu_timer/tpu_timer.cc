// tpu_timer core implementation. See tpu_timer.h for the design notes
// and the reference mapping (xpu_timer manager/metrics/server).

#include "tpu_timer.h"

#include <arpa/inet.h>
#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <array>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdio>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

int64_t NowUs() {
  return std::chrono::duration_cast<std::chrono::microseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

// -- per-kind aggregation (reference metrics.h bucketed families) -----------

struct KindStats {
  int64_t count = 0;
  double sum_us = 0, min_us = 0, max_us = 0;
  double sum_flops = 0, sum_bytes = 0;
  // reservoir of recent durations for p99 (fixed window)
  std::deque<double> window;
  static constexpr size_t kWindow = 512;

  void Add(double dur_us, double flops, double bytes) {
    if (count == 0 || dur_us < min_us) min_us = dur_us;
    if (count == 0 || dur_us > max_us) max_us = dur_us;
    count++;
    sum_us += dur_us;
    sum_flops += flops;
    sum_bytes += bytes;
    window.push_back(dur_us);
    if (window.size() > kWindow) window.pop_front();
  }

  double WindowAvg() const {
    if (window.empty()) return 0;
    double sum = 0;
    for (double v : window) sum += v;
    return sum / window.size();
  }

  double P99() const {
    if (window.empty()) return 0;
    std::vector<double> v(window.begin(), window.end());
    size_t idx = static_cast<size_t>(v.size() * 0.99);
    if (idx >= v.size()) idx = v.size() - 1;
    std::nth_element(v.begin(), v.begin() + idx, v.end());
    return v[idx];
  }
};

// -- compact trace ring (reference KernelTraceManager, 24B/event) -----------

#pragma pack(push, 1)
struct TraceRecord {
  uint32_t name_id;
  uint32_t kind;
  int64_t start_us;
  uint32_t dur_us;
  uint32_t step;
};
#pragma pack(pop)
static_assert(sizeof(TraceRecord) == 24, "trace record must be 24 bytes");

constexpr size_t kTraceCapacity = 1 << 18;  // 256k events, 6 MB

struct Core {
  std::mutex mu;
  std::array<KindStats, TT_KIND_COUNT> stats;
  std::vector<TraceRecord> trace = std::vector<TraceRecord>(kTraceCapacity);
  std::atomic<uint64_t> trace_head{0};  // total records ever written

  std::vector<std::string> names;
  std::unordered_map<std::string, int32_t> name_ids;

  // step / hang state
  std::atomic<int64_t> current_step{-1};
  std::atomic<int64_t> step_open_since_us{0};
  std::atomic<int64_t> last_step_done{-1};
  std::deque<double> step_durs_ms;
  std::atomic<int> hang{0};
  std::atomic<double> hang_factor{5.0};
  std::atomic<int64_t> hang_min_timeout_ms{120000};

  // device launch/completion watermarks (PJRT interposer)
  std::atomic<int64_t> device_launches{0};
  std::atomic<int64_t> device_completes{0};
  std::atomic<int64_t> last_device_complete_us{0};

  // server
  std::atomic<bool> running{false};
  int listen_fd = -1;
  int port = 0;
  std::thread server_thread;
  std::thread watchdog_thread;
};

Core* g_core = nullptr;
std::mutex g_init_mu;

double StepMedianMs(Core& c) {
  std::lock_guard<std::mutex> lock(c.mu);
  if (c.step_durs_ms.empty()) return 0;
  std::vector<double> v(c.step_durs_ms.begin(), c.step_durs_ms.end());
  size_t mid = v.size() / 2;
  std::nth_element(v.begin(), v.begin() + mid, v.end());
  return v[mid];
}

// Hang threshold (ms): the watchdog and the stall verdict must agree.
double HangThresholdMs(Core& c) {
  double median = StepMedianMs(c);
  double factor = c.hang_factor.load();
  return std::max(static_cast<double>(c.hang_min_timeout_ms.load()),
                  median > 0 ? factor * median : 1e18);
}

// 0 none, 1 device-stall, 2 host-stall (see tt_stall_verdict).
int StallVerdict(Core& c) {
  int64_t open_since = c.step_open_since_us.load();
  if (open_since <= 0) return 0;
  double open_ms = (NowUs() - open_since) / 1e3;
  double threshold_ms = HangThresholdMs(c);
  if (open_ms <= threshold_ms) return 0;
  int64_t last = c.last_device_complete_us.load();
  double since_complete_ms = last > 0 ? (NowUs() - last) / 1e3 : open_ms;
  if (since_complete_ms <= threshold_ms) return 0;
  int64_t inflight = c.device_launches.load() - c.device_completes.load();
  return inflight > 0 ? 1 : 2;
}

std::string MetricsText(Core& c) {
  static const char* kKindNames[TT_KIND_COUNT] = {
      "matmul", "collective", "step", "h2d", "d2h", "other",
      "hlo_flops", "hlo_comm", "execute", "compile"};
  std::string out;
  out.reserve(4096);
  char buf[512];
  // BEFORE taking c.mu: StallVerdict -> HangThresholdMs -> StepMedianMs
  // re-locks the same non-recursive mutex (self-deadlock under lock).
  int stall_verdict = StallVerdict(c);
  std::lock_guard<std::mutex> lock(c.mu);
  for (int k = 0; k < TT_KIND_COUNT; k++) {
    const KindStats& s = c.stats[k];
    if (s.count == 0) continue;
    const char* kn = kKindNames[k];
    double avg = s.sum_us / s.count;
    snprintf(buf, sizeof(buf),
             "tpu_timer_latency_us{kind=\"%s\",agg=\"avg\"} %.3f\n"
             "tpu_timer_latency_us{kind=\"%s\",agg=\"win_avg\"} %.3f\n"
             "tpu_timer_latency_us{kind=\"%s\",agg=\"min\"} %.3f\n"
             "tpu_timer_latency_us{kind=\"%s\",agg=\"max\"} %.3f\n"
             "tpu_timer_latency_us{kind=\"%s\",agg=\"p99\"} %.3f\n"
             "tpu_timer_count{kind=\"%s\"} %lld\n",
             kn, avg, kn, s.WindowAvg(), kn, s.min_us, kn, s.max_us, kn,
             s.P99(), kn, static_cast<long long>(s.count));
    out += buf;
    if (s.sum_flops > 0 && s.sum_us > 0) {
      snprintf(buf, sizeof(buf),
               "tpu_timer_tflops{kind=\"%s\"} %.3f\n", kn,
               s.sum_flops / (s.sum_us * 1e6));  // flops/us -> TF/s
      out += buf;
    }
    if (s.sum_bytes > 0 && s.sum_us > 0) {
      snprintf(buf, sizeof(buf),
               "tpu_timer_gbps{kind=\"%s\"} %.3f\n", kn,
               s.sum_bytes / (s.sum_us * 1e3));  // bytes/us -> GB/s
      out += buf;
    }
  }
  snprintf(buf, sizeof(buf), "tpu_timer_hang %d\n", c.hang.load());
  out += buf;
  snprintf(buf, sizeof(buf), "tpu_timer_last_step %lld\n",
           static_cast<long long>(c.last_step_done.load()));
  out += buf;
  int64_t open_since = c.step_open_since_us.load();
  double open_s = open_since > 0 ? (NowUs() - open_since) / 1e6 : 0.0;
  snprintf(buf, sizeof(buf), "tpu_timer_step_open_seconds %.3f\n", open_s);
  out += buf;
  int64_t launches = c.device_launches.load();
  int64_t completes = c.device_completes.load();
  snprintf(buf, sizeof(buf),
           "tpu_timer_device_launches_total %lld\n"
           "tpu_timer_device_completes_total %lld\n"
           "tpu_timer_device_inflight %lld\n"
           "tpu_timer_stall_verdict %d\n",
           static_cast<long long>(launches),
           static_cast<long long>(completes),
           static_cast<long long>(launches - completes),
           stall_verdict);
  out += buf;
  return out;
}

// -- minimal HTTP server (GET /metrics, /status, /healthz) ------------------

void ServeClient(Core& c, int fd) {
  char req[1024];
  ssize_t n = recv(fd, req, sizeof(req) - 1, 0);
  if (n <= 0) {
    close(fd);
    return;
  }
  req[n] = 0;
  std::string body;
  if (strstr(req, "GET /metrics")) {
    body = MetricsText(c);
  } else if (strstr(req, "GET /status")) {
    char buf[256];
    snprintf(buf, sizeof(buf),
             "{\"hang\": %d, \"last_step\": %lld, \"median_step_ms\": %.1f}\n",
             c.hang.load(), static_cast<long long>(c.last_step_done.load()),
             StepMedianMs(c));
    body = buf;
  } else {
    body = "ok\n";
  }
  char header[256];
  snprintf(header, sizeof(header),
           "HTTP/1.1 200 OK\r\nContent-Type: text/plain\r\n"
           "Content-Length: %zu\r\nConnection: close\r\n\r\n",
           body.size());
  send(fd, header, strlen(header), MSG_NOSIGNAL);
  send(fd, body.data(), body.size(), MSG_NOSIGNAL);
  close(fd);
}

void ServerLoop(Core* c) {
  while (c->running.load()) {
    sockaddr_in addr;
    socklen_t len = sizeof(addr);
    int fd = accept(c->listen_fd, reinterpret_cast<sockaddr*>(&addr), &len);
    if (fd < 0) {
      if (!c->running.load()) break;
      continue;
    }
    ServeClient(*c, fd);
  }
}

// -- hang watchdog (reference manager.cc:393 doHang) ------------------------

void WatchdogLoop(Core* c) {
  while (c->running.load()) {
    std::this_thread::sleep_for(std::chrono::milliseconds(500));
    int64_t open_since = c->step_open_since_us.load();
    if (open_since <= 0) {
      c->hang.store(0);
      continue;
    }
    double open_ms = (NowUs() - open_since) / 1e3;
    c->hang.store(open_ms > HangThresholdMs(*c) ? 1 : 0);
  }
}

}  // namespace

extern "C" {

int tt_init(int port) {
  std::lock_guard<std::mutex> lock(g_init_mu);
  if (g_core != nullptr) return g_core->port;
  auto* c = new Core();
  c->listen_fd = socket(AF_INET, SOCK_STREAM, 0);
  if (c->listen_fd < 0) {
    delete c;
    return -1;
  }
  int one = 1;
  setsockopt(c->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (bind(c->listen_fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) <
          0 ||
      listen(c->listen_fd, 16) < 0) {
    close(c->listen_fd);
    delete c;
    return -1;
  }
  socklen_t len = sizeof(addr);
  getsockname(c->listen_fd, reinterpret_cast<sockaddr*>(&addr), &len);
  c->port = ntohs(addr.sin_port);
  c->running.store(true);
  c->server_thread = std::thread(ServerLoop, c);
  c->watchdog_thread = std::thread(WatchdogLoop, c);
  g_core = c;
  return c->port;
}

void tt_shutdown() {
  std::lock_guard<std::mutex> lock(g_init_mu);
  if (g_core == nullptr) return;
  Core* c = g_core;
  g_core = nullptr;
  c->running.store(false);
  shutdown(c->listen_fd, SHUT_RDWR);
  close(c->listen_fd);
  if (c->server_thread.joinable()) c->server_thread.join();
  if (c->watchdog_thread.joinable()) c->watchdog_thread.join();
  delete c;
}

int tt_http_port() { return g_core ? g_core->port : -1; }

int32_t tt_intern_name(const char* name) {
  if (g_core == nullptr) return -1;
  Core& c = *g_core;
  std::lock_guard<std::mutex> lock(c.mu);
  auto it = c.name_ids.find(name);
  if (it != c.name_ids.end()) return it->second;
  int32_t id = static_cast<int32_t>(c.names.size());
  c.names.emplace_back(name);
  c.name_ids.emplace(name, id);
  return id;
}

void tt_record(int32_t name_id, int32_t kind, int64_t start_us,
               int64_t dur_us, double flops, double bytes) {
  if (g_core == nullptr) return;
  Core& c = *g_core;
  if (kind < 0 || kind >= TT_KIND_COUNT) kind = TT_KIND_OTHER;
  TraceRecord rec;
  rec.name_id = static_cast<uint32_t>(name_id < 0 ? 0 : name_id);
  rec.kind = static_cast<uint32_t>(kind);
  rec.start_us = start_us;
  rec.dur_us = static_cast<uint32_t>(
      dur_us < 0 ? 0 : std::min<int64_t>(dur_us, UINT32_MAX));
  int64_t step = c.current_step.load();
  rec.step = static_cast<uint32_t>(step < 0 ? 0 : step);
  {
    // Single mutex covers stats and the trace ring slot, so a concurrent
    // tt_dump_timeline (which snapshots under the same lock) never reads
    // a torn record.
    std::lock_guard<std::mutex> lock(c.mu);
    c.stats[kind].Add(static_cast<double>(dur_us), flops, bytes);
    uint64_t slot = c.trace_head.fetch_add(1);
    c.trace[slot % kTraceCapacity] = rec;
  }
}

void tt_step_begin(int64_t step) {
  if (g_core == nullptr) return;
  g_core->current_step.store(step);
  g_core->step_open_since_us.store(NowUs());
}

void tt_step_end(int64_t step) {
  if (g_core == nullptr) return;
  Core& c = *g_core;
  int64_t open_since = c.step_open_since_us.exchange(0);
  c.last_step_done.store(step);
  if (open_since > 0) {
    // Only the watchdog's median window; step *stats* come from the
    // caller's tt_record (avoids double counting with the step hook).
    double dur_ms = (NowUs() - open_since) / 1e3;
    std::lock_guard<std::mutex> lock(c.mu);
    c.step_durs_ms.push_back(dur_ms);
    if (c.step_durs_ms.size() > 256) c.step_durs_ms.pop_front();
  }
  c.hang.store(0);
}

void tt_config_hang(double factor, int64_t min_timeout_ms) {
  if (g_core == nullptr) return;
  g_core->hang_factor.store(factor);
  g_core->hang_min_timeout_ms.store(min_timeout_ms);
}

int tt_hang_status() { return g_core ? g_core->hang.load() : 0; }

double tt_current_step_open_s() {
  if (g_core == nullptr) return 0;
  int64_t since = g_core->step_open_since_us.load();
  return since > 0 ? (NowUs() - since) / 1e6 : 0.0;
}

void tt_device_launch() {
  if (g_core == nullptr) return;
  g_core->device_launches.fetch_add(1);
}

void tt_device_complete(int64_t dur_us) {
  (void)dur_us;  // duration lands in stats via tt_record; this is the clock
  if (g_core == nullptr) return;
  g_core->device_completes.fetch_add(1);
  g_core->last_device_complete_us.store(NowUs());
}

int64_t tt_device_inflight() {
  if (g_core == nullptr) return 0;
  return g_core->device_launches.load() - g_core->device_completes.load();
}

double tt_last_device_complete_age_s() {
  if (g_core == nullptr) return -1;
  int64_t last = g_core->last_device_complete_us.load();
  return last > 0 ? (NowUs() - last) / 1e6 : -1;
}

int tt_stall_verdict() {
  // A completion newer than the threshold means the device is making
  // progress (or a synchronous launch/await loop is between launches) —
  // the step is just long; keep watching. The recency gate applies to
  // BOTH branches so the verdict can't flap 1<->2 with sample timing.
  // 1 = work handed to the device, completion stream quiet: the device
  // (or its program) is wedged. 2 = step open past threshold with
  // nothing in flight: the host stopped feeding the device
  // (dataloader stall, GC, deadlock).
  if (g_core == nullptr) return 0;
  return StallVerdict(*g_core);
}

int64_t tt_dump_timeline(const char* path) {
  if (g_core == nullptr) return -1;
  Core& c = *g_core;
  FILE* f = fopen(path, "wb");
  if (f == nullptr) return -1;
  // Snapshot the ring under the lock (see tt_record), then write the
  // copy outside it so slow IO never blocks recording.
  std::vector<TraceRecord> snapshot;
  {
    std::lock_guard<std::mutex> lock(c.mu);
    uint64_t head = c.trace_head.load();
    uint64_t count = std::min<uint64_t>(head, kTraceCapacity);
    snapshot.reserve(count);
    for (uint64_t i = head - count; i < head; i++) {
      snapshot.push_back(c.trace[i % kTraceCapacity]);
    }
  }
  fwrite("TPUTL001", 1, 8, f);
  fwrite(snapshot.data(), sizeof(TraceRecord), snapshot.size(), f);
  fclose(f);
  return static_cast<int64_t>(snapshot.size());
}

int64_t tt_dump_names(const char* path) {
  if (g_core == nullptr) return -1;
  Core& c = *g_core;
  std::vector<std::string> names;
  {
    std::lock_guard<std::mutex> lock(c.mu);
    names = c.names;
  }
  FILE* f = fopen(path, "w");
  if (f == nullptr) return -1;
  for (size_t i = 0; i < names.size(); i++) {
    fprintf(f, "%zu\t%s\n", i, names[i].c_str());
  }
  fclose(f);
  return static_cast<int64_t>(names.size());
}

int64_t tt_metrics_text(char* out, int64_t cap) {
  if (g_core == nullptr || cap <= 0) return 0;
  std::string text = MetricsText(*g_core);
  int64_t n = std::min<int64_t>(cap - 1, text.size());
  memcpy(out, text.data(), n);
  out[n] = 0;
  return n;
}

}  // extern "C"
