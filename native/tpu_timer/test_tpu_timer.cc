// Native smoke test for tpu_timer (reference model: xpu_timer/test/
// common_test.cc). Exercises ingestion from multiple threads, metrics
// text, the step watchdog, and the timeline dump format.

#include "tpu_timer.h"

#include <cassert>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

int main() {
  int port = tt_init(0);
  assert(port > 0);
  assert(tt_http_port() == port);

  int32_t mm = tt_intern_name("matmul_fwd");
  int32_t cc = tt_intern_name("psum_grads");
  assert(mm == tt_intern_name("matmul_fwd"));  // stable interning

  // concurrent ingestion
  std::vector<std::thread> threads;
  for (int t = 0; t < 4; t++) {
    threads.emplace_back([&, t]() {
      for (int i = 0; i < 1000; i++) {
        tt_record(mm, TT_KIND_MATMUL, i * 100, 50, 1e9, 0);
        tt_record(cc, TT_KIND_COLLECTIVE, i * 100, 20, 0, 1e6);
      }
    });
  }
  for (auto& th : threads) th.join();

  // steps + hang watchdog
  tt_config_hang(3.0, 50);  // 50ms min timeout for the test
  for (int64_t s = 0; s < 5; s++) {
    tt_step_begin(s);
    std::this_thread::sleep_for(std::chrono::milliseconds(2));
    tt_step_end(s);
  }
  assert(tt_hang_status() == 0);
  tt_step_begin(5);
  std::this_thread::sleep_for(std::chrono::milliseconds(700));
  assert(tt_hang_status() == 1);  // stuck step flagged
  {
    // Regression: metrics rendered WITH a step open past the hang
    // threshold (the stall-verdict path once re-locked the core mutex
    // from inside the locked section — a self-deadlock only this state
    // reaches). Host-stall expected: nothing was device-launched.
    char sbuf[16384];
    assert(tt_metrics_text(sbuf, sizeof(sbuf)) > 0);
    assert(std::string(sbuf).find("tpu_timer_stall_verdict 2") !=
           std::string::npos);
    assert(tt_stall_verdict() == 2);
  }
  tt_step_end(5);
  assert(tt_hang_status() == 0);
  assert(tt_stall_verdict() == 0);

  char buf[16384];
  int64_t n = tt_metrics_text(buf, sizeof(buf));
  assert(n > 0);
  std::string text(buf);
  assert(text.find("tpu_timer_tflops{kind=\"matmul\"}") != std::string::npos);
  assert(text.find("tpu_timer_gbps{kind=\"collective\"}") != std::string::npos);
  assert(text.find("tpu_timer_count{kind=\"matmul\"} 4000") !=
         std::string::npos);
  assert(text.find("tpu_timer_last_step 5") != std::string::npos);

  int64_t written = tt_dump_timeline("/tmp/tt_test.timeline");
  assert(written >= 8000);
  FILE* f = fopen("/tmp/tt_test.timeline", "rb");
  char magic[9] = {0};
  fread(magic, 1, 8, f);
  assert(strcmp(magic, "TPUTL001") == 0);
  fseek(f, 0, SEEK_END);
  long size = ftell(f);
  assert((size - 8) % 24 == 0);  // 24B records
  fclose(f);

  tt_shutdown();
  printf("tpu_timer native tests OK (%lld trace records)\n",
         static_cast<long long>(written));
  return 0;
}
