// Minimal fake PJRT plugin — a test double for the interposer.
//
// Implements just enough of the PJRT C API for test_driver.cc to push a
// compile / execute / H2D / D2H through the interposed table without
// hardware: events with deferred readiness (a background thread fires
// them after FAKE_EXEC_MS milliseconds), multiple OnReady callbacks per
// event (matching XLA's future semantics the interposer relies on), and
// a FAKE_EXEC_HANG=1 mode where execute events never fire — simulating
// a wedged device program for the stall-verdict test.

#include "pjrt_c_api.h"

#include <atomic>
#include <chrono>
#include <cstdlib>
#include <cstring>
#include <condition_variable>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

struct FakeError {
  std::string message;
};

struct FakeEvent {
  std::mutex mu;
  std::condition_variable cv;
  bool ready = false;
  std::vector<std::pair<PJRT_Event_OnReadyCallback, void*>> callbacks;
  // creator thread + owner each hold a ref; freed when both release
  std::atomic<int> refs{1};

  void Fire() {
    std::vector<std::pair<PJRT_Event_OnReadyCallback, void*>> cbs;
    {
      std::lock_guard<std::mutex> lock(mu);
      if (ready) return;
      ready = true;
      cbs.swap(callbacks);
    }
    cv.notify_all();
    for (auto& cb : cbs) cb.first(nullptr, cb.second);
  }

  void Unref() {
    if (refs.fetch_sub(1) == 1) delete this;
  }
};

PJRT_Event* MakeDeferredEvent(int delay_ms) {
  auto* ev = new FakeEvent();
  if (delay_ms < 0) {
    // hang mode: never fires; the extra creator ref is leaked on
    // purpose (the test process is short-lived)
    return reinterpret_cast<PJRT_Event*>(ev);
  }
  if (delay_ms == 0) {
    ev->Fire();
    return reinterpret_cast<PJRT_Event*>(ev);
  }
  ev->refs.fetch_add(1);
  std::thread([ev, delay_ms]() {
    std::this_thread::sleep_for(std::chrono::milliseconds(delay_ms));
    ev->Fire();
    ev->Unref();
  }).detach();
  return reinterpret_cast<PJRT_Event*>(ev);
}

int ExecDelayMs() {
  if (getenv("FAKE_EXEC_HANG") != nullptr) return -1;
  const char* ms = getenv("FAKE_EXEC_MS");
  return ms != nullptr ? atoi(ms) : 5;
}

// -- API impls --------------------------------------------------------------

void ErrorDestroy(PJRT_Error_Destroy_Args* args) {
  delete reinterpret_cast<FakeError*>(args->error);
}

void ErrorMessage(PJRT_Error_Message_Args* args) {
  auto* e = reinterpret_cast<const FakeError*>(args->error);
  args->message = e->message.c_str();
  args->message_size = e->message.size();
}

PJRT_Error* ErrorGetCode(PJRT_Error_GetCode_Args* args) {
  args->code = PJRT_Error_Code_UNKNOWN;
  return nullptr;
}

PJRT_Error* PluginInitialize(PJRT_Plugin_Initialize_Args*) { return nullptr; }

PJRT_Error* EventDestroy(PJRT_Event_Destroy_Args* args) {
  if (args->event != nullptr) {
    reinterpret_cast<FakeEvent*>(args->event)->Unref();
  }
  return nullptr;
}

PJRT_Error* EventIsReady(PJRT_Event_IsReady_Args* args) {
  auto* ev = reinterpret_cast<FakeEvent*>(args->event);
  std::lock_guard<std::mutex> lock(ev->mu);
  args->is_ready = ev->ready;
  return nullptr;
}

PJRT_Error* EventError(PJRT_Event_Error_Args*) { return nullptr; }

PJRT_Error* EventAwait(PJRT_Event_Await_Args* args) {
  auto* ev = reinterpret_cast<FakeEvent*>(args->event);
  std::unique_lock<std::mutex> lock(ev->mu);
  ev->cv.wait(lock, [ev] { return ev->ready; });
  return nullptr;
}

PJRT_Error* EventOnReady(PJRT_Event_OnReady_Args* args) {
  auto* ev = reinterpret_cast<FakeEvent*>(args->event);
  bool fire_now = false;
  {
    std::lock_guard<std::mutex> lock(ev->mu);
    if (ev->ready) {
      fire_now = true;
    } else {
      ev->callbacks.emplace_back(args->callback, args->user_arg);
    }
  }
  if (fire_now) args->callback(nullptr, args->user_arg);
  return nullptr;
}

int g_client_token, g_executable_token, g_buffer_token;
const char kProgramName[] = "fake_program";

PJRT_Error* ClientCreate(PJRT_Client_Create_Args* args) {
  args->client = reinterpret_cast<PJRT_Client*>(&g_client_token);
  return nullptr;
}

PJRT_Error* ClientDestroy(PJRT_Client_Destroy_Args*) { return nullptr; }

PJRT_Error* ClientCompile(PJRT_Client_Compile_Args* args) {
  std::this_thread::sleep_for(std::chrono::milliseconds(2));
  args->executable =
      reinterpret_cast<PJRT_LoadedExecutable*>(&g_executable_token);
  return nullptr;
}

PJRT_Error* LoadedExecutableGetExecutable(
    PJRT_LoadedExecutable_GetExecutable_Args* args) {
  args->executable = reinterpret_cast<PJRT_Executable*>(&g_executable_token);
  return nullptr;
}

PJRT_Error* ExecutableName(PJRT_Executable_Name_Args* args) {
  args->executable_name = kProgramName;
  args->executable_name_size = sizeof(kProgramName) - 1;
  return nullptr;
}

PJRT_Error* Execute(PJRT_LoadedExecutable_Execute_Args* args) {
  if (args->device_complete_events != nullptr) {
    int delay = ExecDelayMs();
    for (size_t i = 0; i < args->num_devices; i++) {
      args->device_complete_events[i] = MakeDeferredEvent(delay);
    }
  }
  return nullptr;
}

PJRT_Error* BufferFromHostBuffer(PJRT_Client_BufferFromHostBuffer_Args* args) {
  std::this_thread::sleep_for(std::chrono::microseconds(200));
  args->done_with_host_buffer = MakeDeferredEvent(0);
  args->buffer = reinterpret_cast<PJRT_Buffer*>(&g_buffer_token);
  return nullptr;
}

PJRT_Error* ToHostBuffer(PJRT_Buffer_ToHostBuffer_Args* args) {
  if (args->dst == nullptr) {
    args->dst_size = 64;
    return nullptr;
  }
  memset(args->dst, 0, args->dst_size);
  args->event = MakeDeferredEvent(2);
  return nullptr;
}

PJRT_Api g_api;
std::once_flag g_once;

}  // namespace

extern "C" {

const PJRT_Api* GetPjrtApi() {
  std::call_once(g_once, [] {
    memset(&g_api, 0, sizeof(g_api));
    g_api.struct_size = PJRT_Api_STRUCT_SIZE;
    g_api.pjrt_api_version.struct_size = PJRT_Api_Version_STRUCT_SIZE;
    g_api.pjrt_api_version.major_version = PJRT_API_MAJOR;
    g_api.pjrt_api_version.minor_version = PJRT_API_MINOR;
    g_api.PJRT_Error_Destroy = ErrorDestroy;
    g_api.PJRT_Error_Message = ErrorMessage;
    g_api.PJRT_Error_GetCode = ErrorGetCode;
    g_api.PJRT_Plugin_Initialize = PluginInitialize;
    g_api.PJRT_Event_Destroy = EventDestroy;
    g_api.PJRT_Event_IsReady = EventIsReady;
    g_api.PJRT_Event_Error = EventError;
    g_api.PJRT_Event_Await = EventAwait;
    g_api.PJRT_Event_OnReady = EventOnReady;
    g_api.PJRT_Client_Create = ClientCreate;
    g_api.PJRT_Client_Destroy = ClientDestroy;
    g_api.PJRT_Client_Compile = ClientCompile;
    g_api.PJRT_LoadedExecutable_GetExecutable = LoadedExecutableGetExecutable;
    g_api.PJRT_Executable_Name = ExecutableName;
    g_api.PJRT_LoadedExecutable_Execute = Execute;
    g_api.PJRT_Client_BufferFromHostBuffer = BufferFromHostBuffer;
    g_api.PJRT_Buffer_ToHostBuffer = ToHostBuffer;
  });
  return &g_api;
}

}  // extern "C"
