// PJRT C-API interposer — ground-truth device activity for tpu_timer.
//
// TPU-native counterpart of the reference's driver-boundary hooks
// (xpu_timer/xpu_timer/nvidia/hook.cc:54 intercepted cudaLaunchKernel,
// :323 NCCL collectives; completion timing via CUDA event pools,
// xpu_timer/common/manager.h:106). On TPU the driver boundary is the
// PJRT C API: jax loads a plugin shared object and calls through its
// PJRT_Api function table. This library IS a plugin — GetPjrtApi()
// loads the real one, copies its table, and patches the entries where
// device work is born:
//
//   PJRT_LoadedExecutable_Execute    -> launch + device-completion time
//   PJRT_Client_BufferFromHostBuffer -> H2D bytes + latency
//   PJRT_Buffer_ToHostBuffer         -> D2H bytes + event-completion time
//   PJRT_Client_Compile              -> compile wall time
//
// Everything lands in the tpu_timer core (bucketed stats, trace ring,
// Prometheus /metrics, hang watchdog) with NO Python cooperation: what
// the process actually executed is what gets recorded.
//
// ABI notes: the PJRT C ABI is append-only and struct_size-negotiated.
// The real table is copied at its full struct_size (heap buffer), so
// entries newer than this header pass through untouched; the patched
// entries live at offsets fixed since long before v0.72. Execute
// completion uses the per-device `device_complete_events`: when the
// caller passed none we request our own (and destroy them); when the
// caller did, we piggyback an extra OnReady — XLA's event is a future
// supporting multiple waiters.

#include "pjrt_c_api.h"

#include <dlfcn.h>

#include <atomic>
#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

#include "../tpu_timer/tpu_timer.h"

namespace {

int64_t NowUs() {
  return std::chrono::duration_cast<std::chrono::microseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

const PJRT_Api* g_real = nullptr;
PJRT_Api* g_wrapped = nullptr;
std::mutex g_mu;

int32_t g_name_execute = -1;
int32_t g_name_h2d = -1;
int32_t g_name_d2h = -1;
int32_t g_name_compile = -1;

// LoadedExecutable -> interned program name (one lookup per program).
std::mutex g_exe_mu;
std::unordered_map<PJRT_LoadedExecutable*, int32_t> g_exe_names;

void DestroyError(PJRT_Error* err) {
  if (err == nullptr || g_real == nullptr) return;
  PJRT_Error_Destroy_Args d;
  memset(&d, 0, sizeof(d));
  d.struct_size = PJRT_Error_Destroy_Args_STRUCT_SIZE;
  d.error = err;
  g_real->PJRT_Error_Destroy(&d);
}

int32_t ExecutableNameId(PJRT_LoadedExecutable* exe) {
  {
    std::lock_guard<std::mutex> lock(g_exe_mu);
    auto it = g_exe_names.find(exe);
    if (it != g_exe_names.end()) return it->second;
  }
  int32_t id = g_name_execute;
  if (g_real->PJRT_LoadedExecutable_GetExecutable != nullptr &&
      g_real->PJRT_Executable_Name != nullptr) {
    PJRT_LoadedExecutable_GetExecutable_Args ga;
    memset(&ga, 0, sizeof(ga));
    ga.struct_size = PJRT_LoadedExecutable_GetExecutable_Args_STRUCT_SIZE;
    ga.loaded_executable = exe;
    PJRT_Error* err = g_real->PJRT_LoadedExecutable_GetExecutable(&ga);
    if (err == nullptr && ga.executable != nullptr) {
      PJRT_Executable_Name_Args na;
      memset(&na, 0, sizeof(na));
      na.struct_size = PJRT_Executable_Name_Args_STRUCT_SIZE;
      na.executable = ga.executable;
      PJRT_Error* nerr = g_real->PJRT_Executable_Name(&na);
      if (nerr == nullptr && na.executable_name != nullptr) {
        std::string name(na.executable_name, na.executable_name_size);
        id = tt_intern_name(("exec:" + name).c_str());
      } else {
        DestroyError(nerr);
      }
      // NOTE: deliberately not destroying ga.executable — some plugins
      // hand back an owned reference; leaking one small handle per
      // distinct program is bounded by the number of compiled programs.
    } else {
      DestroyError(err);
    }
  }
  std::lock_guard<std::mutex> lock(g_exe_mu);
  g_exe_names.emplace(exe, id);
  return id;
}

// -- Execute ----------------------------------------------------------------

struct ExecCompletionCtx {
  int64_t start_us;
  int32_t name_id;
  PJRT_Event* event;  // owned iff we substituted our own events
  bool owns_event;
};

void OnExecReady(PJRT_Error* error, void* user_arg) {
  auto* ctx = static_cast<ExecCompletionCtx*>(user_arg);
  int64_t now = NowUs();
  tt_record(ctx->name_id, TT_KIND_EXECUTE, ctx->start_us,
            now - ctx->start_us, 0, 0);
  tt_device_complete(now - ctx->start_us);
  DestroyError(error);
  if (ctx->owns_event && ctx->event != nullptr &&
      g_real->PJRT_Event_Destroy != nullptr) {
    PJRT_Event_Destroy_Args d;
    memset(&d, 0, sizeof(d));
    d.struct_size = PJRT_Event_Destroy_Args_STRUCT_SIZE;
    d.event = ctx->event;
    DestroyError(g_real->PJRT_Event_Destroy(&d));
  }
  delete ctx;
}

PJRT_Error* WrapExecute(PJRT_LoadedExecutable_Execute_Args* args) {
  int64_t t0 = NowUs();
  bool substituted = false;
  std::vector<PJRT_Event*> our_events;
  if (args->device_complete_events == nullptr && args->num_devices > 0 &&
      g_real->PJRT_Event_OnReady != nullptr) {
    our_events.assign(args->num_devices, nullptr);
    args->device_complete_events = our_events.data();
    substituted = true;
  }
  PJRT_Error* err = g_real->PJRT_LoadedExecutable_Execute(args);
  if (err != nullptr) {
    if (substituted) args->device_complete_events = nullptr;
    return err;
  }
  int32_t name_id = ExecutableNameId(args->executable);
  PJRT_Event** events = args->device_complete_events;
  size_t n = events != nullptr ? args->num_devices : 0;
  bool any_event = false;
  for (size_t i = 0; i < n; i++) {
    if (events[i] == nullptr) continue;
    auto* ctx = new ExecCompletionCtx{t0, name_id, events[i], substituted};
    // Launch is counted BEFORE OnReady: an already-ready event invokes
    // the callback inline, and completion-before-launch would send
    // inflight negative (misreading a concurrent wedge as host-stall).
    tt_device_launch();
    PJRT_Event_OnReady_Args oa;
    memset(&oa, 0, sizeof(oa));
    oa.struct_size = PJRT_Event_OnReady_Args_STRUCT_SIZE;
    oa.event = events[i];
    oa.callback = OnExecReady;
    oa.user_arg = ctx;
    PJRT_Error* oerr = g_real->PJRT_Event_OnReady(&oa);
    if (oerr != nullptr) {
      DestroyError(oerr);
      delete ctx;
      tt_device_complete(0);  // never tracked; rebalance the watermark
      if (substituted && g_real->PJRT_Event_Destroy != nullptr) {
        PJRT_Event_Destroy_Args d;
        memset(&d, 0, sizeof(d));
        d.struct_size = PJRT_Event_Destroy_Args_STRUCT_SIZE;
        d.event = events[i];
        DestroyError(g_real->PJRT_Event_Destroy(&d));
      }
      continue;
    }
    any_event = true;
  }
  if (!any_event) {
    // No completion events available: record the host-side call as the
    // best evidence we have (still marks real device activity).
    tt_record(name_id, TT_KIND_EXECUTE, t0, NowUs() - t0, 0, 0);
  }
  if (substituted) args->device_complete_events = nullptr;
  return nullptr;
}

// -- Transfers --------------------------------------------------------------

int64_t BufferTypeBytes(PJRT_Buffer_Type t) {
  switch (t) {
    case PJRT_Buffer_Type_PRED:
    case PJRT_Buffer_Type_S8:
    case PJRT_Buffer_Type_U8:
    case PJRT_Buffer_Type_F8E5M2:
    case PJRT_Buffer_Type_F8E4M3FN:
    case PJRT_Buffer_Type_F8E4M3B11FNUZ:
    case PJRT_Buffer_Type_F8E5M2FNUZ:
    case PJRT_Buffer_Type_F8E4M3FNUZ:
      return 1;
    case PJRT_Buffer_Type_S16:
    case PJRT_Buffer_Type_U16:
    case PJRT_Buffer_Type_F16:
    case PJRT_Buffer_Type_BF16:
      return 2;
    case PJRT_Buffer_Type_S32:
    case PJRT_Buffer_Type_U32:
    case PJRT_Buffer_Type_F32:
      return 4;
    case PJRT_Buffer_Type_S64:
    case PJRT_Buffer_Type_U64:
    case PJRT_Buffer_Type_F64:
    case PJRT_Buffer_Type_C64:
      return 8;
    case PJRT_Buffer_Type_C128:
      return 16;
    default:
      return 1;
  }
}

PJRT_Error* WrapBufferFromHost(PJRT_Client_BufferFromHostBuffer_Args* args) {
  int64_t t0 = NowUs();
  double bytes = static_cast<double>(BufferTypeBytes(args->type));
  for (size_t i = 0; i < args->num_dims; i++) {
    bytes *= static_cast<double>(args->dims[i]);
  }
  PJRT_Error* err = g_real->PJRT_Client_BufferFromHostBuffer(args);
  if (err == nullptr) {
    // Host-call latency (the staging copy); the async device write is
    // covered by the buffer-ready event the runtime consumes.
    tt_record(g_name_h2d, TT_KIND_H2D, t0, NowUs() - t0, 0, bytes);
  }
  return err;
}

struct D2HCtx {
  int64_t start_us;
  double bytes;
};

void OnD2HReady(PJRT_Error* error, void* user_arg) {
  auto* ctx = static_cast<D2HCtx*>(user_arg);
  int64_t now = NowUs();
  tt_record(g_name_d2h, TT_KIND_D2H, ctx->start_us, now - ctx->start_us, 0,
            ctx->bytes);
  DestroyError(error);
  delete ctx;
}

PJRT_Error* WrapToHost(PJRT_Buffer_ToHostBuffer_Args* args) {
  if (args->dst == nullptr) {
    // size query, not a transfer
    return g_real->PJRT_Buffer_ToHostBuffer(args);
  }
  int64_t t0 = NowUs();
  double bytes = static_cast<double>(args->dst_size);
  PJRT_Error* err = g_real->PJRT_Buffer_ToHostBuffer(args);
  if (err != nullptr) return err;
  bool recorded = false;
  if (args->event != nullptr && g_real->PJRT_Event_OnReady != nullptr) {
    auto* ctx = new D2HCtx{t0, bytes};
    PJRT_Event_OnReady_Args oa;
    memset(&oa, 0, sizeof(oa));
    oa.struct_size = PJRT_Event_OnReady_Args_STRUCT_SIZE;
    oa.event = args->event;
    oa.callback = OnD2HReady;
    oa.user_arg = ctx;
    PJRT_Error* oerr = g_real->PJRT_Event_OnReady(&oa);
    if (oerr != nullptr) {
      DestroyError(oerr);
      delete ctx;
    } else {
      recorded = true;
    }
  }
  if (!recorded) {
    tt_record(g_name_d2h, TT_KIND_D2H, t0, NowUs() - t0, 0, bytes);
  }
  return nullptr;
}

// -- Compile ----------------------------------------------------------------

PJRT_Error* WrapCompile(PJRT_Client_Compile_Args* args) {
  int64_t t0 = NowUs();
  PJRT_Error* err = g_real->PJRT_Client_Compile(args);
  if (err == nullptr) {
    tt_record(g_name_compile, TT_KIND_COMPILE, t0, NowUs() - t0, 0, 0);
  }
  return err;
}

const char* RealPluginPath() {
  const char* p = getenv("DLROVER_PJRT_REAL_PLUGIN");
  if (p != nullptr && p[0] != 0) return p;
  return "libtpu.so";
}

}  // namespace

extern "C" {

// The PJRT plugin entry point. jax (or any PJRT client) dlopens this
// library and calls GetPjrtApi(); we hand back the real plugin's table
// with four entries replaced.
const PJRT_Api* GetPjrtApi() {
  std::lock_guard<std::mutex> lock(g_mu);
  if (g_wrapped != nullptr) return g_wrapped;

  void* handle = dlopen(RealPluginPath(), RTLD_NOW | RTLD_GLOBAL);
  if (handle == nullptr) {
    fprintf(stderr, "pjrt_interposer: cannot dlopen real plugin %s: %s\n",
            RealPluginPath(), dlerror());
    return nullptr;
  }
  auto get_api =
      reinterpret_cast<const PJRT_Api* (*)()>(dlsym(handle, "GetPjrtApi"));
  if (get_api == nullptr) {
    fprintf(stderr, "pjrt_interposer: %s has no GetPjrtApi\n",
            RealPluginPath());
    return nullptr;
  }
  g_real = get_api();
  if (g_real == nullptr) return nullptr;

  // Metrics core: port from env (0 -> auto-pick; the Python side reads
  // tt_http_port through this same library).
  const char* port_env = getenv("DLROVER_TT_PORT");
  int port = port_env != nullptr ? atoi(port_env) : 0;
  tt_init(port);
  g_name_execute = tt_intern_name("pjrt_execute");
  g_name_h2d = tt_intern_name("pjrt_h2d");
  g_name_d2h = tt_intern_name("pjrt_d2h");
  g_name_compile = tt_intern_name("pjrt_compile");

  // Full-size copy: fields beyond this header's knowledge pass through.
  size_t size = g_real->struct_size;
  if (size < sizeof(PJRT_Api)) size = sizeof(PJRT_Api);
  void* buf = calloc(1, size);
  memcpy(buf, g_real, g_real->struct_size);
  g_wrapped = static_cast<PJRT_Api*>(buf);
  g_wrapped->PJRT_LoadedExecutable_Execute = WrapExecute;
  g_wrapped->PJRT_Client_BufferFromHostBuffer = WrapBufferFromHost;
  g_wrapped->PJRT_Buffer_ToHostBuffer = WrapToHost;
  g_wrapped->PJRT_Client_Compile = WrapCompile;
  return g_wrapped;
}

}  // extern "C"
