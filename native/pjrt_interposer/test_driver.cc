// Drives the interposer against the fake plugin — no Python, no jax.
//
// Usage: test_driver <libpjrt_interposer.so> <mode>
//   mode "basic":     compile + execute + H2D + D2H, then print metrics
//   mode "devstall":  open a step, launch an execute whose completion
//                     never fires (FAKE_EXEC_HANG=1 set by the caller),
//                     then print the stall verdict (expect 1)
//   mode "hoststall": open a step and launch nothing (expect 2)
//
// The tt_* symbols are linked INTO the interposer library, so the same
// dlopen handle serves both the PJRT table and the metrics accessors —
// exactly how the Python side reads them in production.

#include <dlfcn.h>
#include <unistd.h>

#include <cstdio>
#include <cstdlib>
#include <cstring>

#include "pjrt_c_api.h"

typedef const PJRT_Api* (*GetApiFn)();
typedef void (*ConfigHangFn)(double, long long);
typedef void (*StepBeginFn)(long long);
typedef void (*StepEndFn)(long long);
typedef long long (*MetricsFn)(char*, long long);
typedef int (*VerdictFn)();
typedef long long (*InflightFn)();

#define CHECK(cond)                                               \
  do {                                                            \
    if (!(cond)) {                                                \
      fprintf(stderr, "CHECK failed at %d: %s\n", __LINE__, #cond); \
      exit(1);                                                    \
    }                                                             \
  } while (0)

int main(int argc, char** argv) {
  CHECK(argc >= 3);
  void* handle = dlopen(argv[1], RTLD_NOW);
  if (handle == nullptr) {
    fprintf(stderr, "dlopen %s: %s\n", argv[1], dlerror());
    return 1;
  }
  auto get_api = reinterpret_cast<GetApiFn>(dlsym(handle, "GetPjrtApi"));
  auto config_hang =
      reinterpret_cast<ConfigHangFn>(dlsym(handle, "tt_config_hang"));
  auto step_begin =
      reinterpret_cast<StepBeginFn>(dlsym(handle, "tt_step_begin"));
  auto step_end = reinterpret_cast<StepEndFn>(dlsym(handle, "tt_step_end"));
  auto metrics = reinterpret_cast<MetricsFn>(dlsym(handle, "tt_metrics_text"));
  auto verdict = reinterpret_cast<VerdictFn>(dlsym(handle, "tt_stall_verdict"));
  auto inflight =
      reinterpret_cast<InflightFn>(dlsym(handle, "tt_device_inflight"));
  CHECK(get_api && config_hang && step_begin && step_end && metrics &&
        verdict && inflight);

  const PJRT_Api* api = get_api();
  CHECK(api != nullptr);
  // Entries the interposer does not wrap pass through to the fake.
  PJRT_Plugin_Initialize_Args init_args;
  memset(&init_args, 0, sizeof(init_args));
  init_args.struct_size = PJRT_Plugin_Initialize_Args_STRUCT_SIZE;
  CHECK(api->PJRT_Plugin_Initialize(&init_args) == nullptr);

  PJRT_Client_Create_Args cc;
  memset(&cc, 0, sizeof(cc));
  cc.struct_size = PJRT_Client_Create_Args_STRUCT_SIZE;
  CHECK(api->PJRT_Client_Create(&cc) == nullptr);
  CHECK(cc.client != nullptr);

  const char* mode = argv[2];

  // The hang threshold stays infinite until a step-duration median
  // exists (no false hang during the first long compile), so the stall
  // modes record two quick steps first.
  if (strcmp(mode, "hoststall") == 0 || strcmp(mode, "devstall") == 0) {
    for (long long s = 0; s < 2; s++) {
      step_begin(s);
      usleep(20 * 1000);
      step_end(s);
    }
    config_hang(5.0, 150);
  }

  if (strcmp(mode, "hoststall") == 0) {
    step_begin(2);
    usleep(400 * 1000);
    printf("verdict=%d inflight=%lld\n", verdict(), inflight());
    return 0;
  }

  // compile
  char code[] = "dummy";
  PJRT_Program prog;
  memset(&prog, 0, sizeof(prog));
  prog.struct_size = PJRT_Program_STRUCT_SIZE;
  prog.code = code;
  prog.code_size = sizeof(code) - 1;
  prog.format = "mlir";
  prog.format_size = 4;
  PJRT_Client_Compile_Args comp;
  memset(&comp, 0, sizeof(comp));
  comp.struct_size = PJRT_Client_Compile_Args_STRUCT_SIZE;
  comp.client = cc.client;
  comp.program = &prog;
  CHECK(api->PJRT_Client_Compile(&comp) == nullptr);
  CHECK(comp.executable != nullptr);

  if (strcmp(mode, "devstall") == 0) {
    step_begin(2);
    PJRT_LoadedExecutable_Execute_Args ex;
    memset(&ex, 0, sizeof(ex));
    ex.struct_size = PJRT_LoadedExecutable_Execute_Args_STRUCT_SIZE;
    ex.executable = comp.executable;
    ex.num_devices = 1;
    ex.num_args = 0;
    CHECK(api->PJRT_LoadedExecutable_Execute(&ex) == nullptr);
    usleep(400 * 1000);
    printf("verdict=%d inflight=%lld\n", verdict(), inflight());
    return 0;
  }

  // basic: execute (interposer substitutes completion events)
  for (int i = 0; i < 3; i++) {
    PJRT_LoadedExecutable_Execute_Args ex;
    memset(&ex, 0, sizeof(ex));
    ex.struct_size = PJRT_LoadedExecutable_Execute_Args_STRUCT_SIZE;
    ex.executable = comp.executable;
    ex.num_devices = 1;
    ex.num_args = 0;
    CHECK(api->PJRT_LoadedExecutable_Execute(&ex) == nullptr);
    CHECK(ex.device_complete_events == nullptr);  // interposer reset it
  }

  // H2D: 128x128 f32 = 65536 bytes
  int64_t dims[2] = {128, 128};
  float host_data[4] = {0, 1, 2, 3};  // fake never reads past the pointer
  PJRT_Client_BufferFromHostBuffer_Args h2d;
  memset(&h2d, 0, sizeof(h2d));
  h2d.struct_size = PJRT_Client_BufferFromHostBuffer_Args_STRUCT_SIZE;
  h2d.client = cc.client;
  h2d.data = host_data;
  h2d.type = PJRT_Buffer_Type_F32;
  h2d.dims = dims;
  h2d.num_dims = 2;
  h2d.host_buffer_semantics = PJRT_HostBufferSemantics_kImmutableOnlyDuringCall;
  CHECK(api->PJRT_Client_BufferFromHostBuffer(&h2d) == nullptr);
  CHECK(h2d.buffer != nullptr);

  // D2H
  char dst[64];
  PJRT_Buffer_ToHostBuffer_Args d2h;
  memset(&d2h, 0, sizeof(d2h));
  d2h.struct_size = PJRT_Buffer_ToHostBuffer_Args_STRUCT_SIZE;
  d2h.src = h2d.buffer;
  d2h.dst = dst;
  d2h.dst_size = sizeof(dst);
  CHECK(api->PJRT_Buffer_ToHostBuffer(&d2h) == nullptr);

  usleep(100 * 1000);  // let deferred completion events fire

  char buf[16384];
  long long n = metrics(buf, sizeof(buf));
  CHECK(n > 0);
  fwrite(buf, 1, static_cast<size_t>(n), stdout);
  printf("inflight=%lld\n", inflight());
  fflush(stdout);
  // Hold the process (and its /metrics server) open on request so an
  // external scraper can poll without racing process exit.
  const char* linger = getenv("DRIVER_LINGER_MS");
  if (linger != nullptr) usleep(atoi(linger) * 1000);
  return 0;
}
