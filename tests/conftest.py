"""Test bootstrap: force a virtual 8-device CPU platform before jax imports.

Mirrors the reference's multi-node-without-cluster tricks (SURVEY.md §4):
control-plane tests run N simulated agents against an in-process master;
mesh/checkpoint tests run on 8 virtual CPU devices.
"""

import os

# Persistent XLA compile cache: the suite is compile-heavy (pipeline /
# MoE / sharded train steps) and repeated runs drop ~3x in wall time.
# Per-uid path: a world-shared /tmp dir would be unwritable for the
# second user on a shared machine.
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR", f"/tmp/dlrover_tpu_jax_cache_{os.getuid()}"
)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")

from dlrover_tpu.common.platform import force_virtual_cpu

force_virtual_cpu(8)
os.environ.setdefault("DLROVER_JOB_NAME", f"test_{os.getpid()}")

import pytest  # noqa: E402


@pytest.fixture()
def tmp_ipc_dir(tmp_path, monkeypatch):
    import dlrover_tpu.common.multi_process as mp

    monkeypatch.setattr(mp, "SOCKET_TMP_DIR", str(tmp_path / "sockets"))
    return tmp_path
