"""Test bootstrap: force a virtual 8-device CPU platform before jax imports.

Mirrors the reference's multi-node-without-cluster tricks (SURVEY.md §4):
control-plane tests run N simulated agents against an in-process master;
mesh/checkpoint tests run on 8 virtual CPU devices.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("DLROVER_JOB_NAME", f"test_{os.getpid()}")

# The environment's sitecustomize registers a TPU backend and overrides
# jax_platforms after env-var resolution; force CPU back explicitly.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture()
def tmp_ipc_dir(tmp_path, monkeypatch):
    import dlrover_tpu.common.multi_process as mp

    monkeypatch.setattr(mp, "SOCKET_TMP_DIR", str(tmp_path / "sockets"))
    return tmp_path
