"""Test bootstrap: force a virtual 8-device CPU platform before jax imports.

Mirrors the reference's multi-node-without-cluster tricks (SURVEY.md §4):
control-plane tests run N simulated agents against an in-process master;
mesh/checkpoint tests run on 8 virtual CPU devices.
"""

import os

# Persistent XLA compile cache: the suite is compile-heavy (pipeline /
# MoE / sharded train steps) and repeated runs drop ~3x in wall time.
# Per-uid path: a world-shared /tmp dir would be unwritable for the
# second user on a shared machine.
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR", f"/tmp/dlrover_tpu_jax_cache_{os.getuid()}"
)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")

from dlrover_tpu.common.platform import force_virtual_cpu

force_virtual_cpu(8)
os.environ.setdefault("DLROVER_JOB_NAME", f"test_{os.getpid()}")

import pytest  # noqa: E402


@pytest.fixture()
def tmp_ipc_dir(tmp_path, monkeypatch):
    import dlrover_tpu.common.multi_process as mp

    monkeypatch.setattr(mp, "SOCKET_TMP_DIR", str(tmp_path / "sockets"))
    return tmp_path


def pytest_collection_modifyitems(session, config, items):
    """Hoist test_train_loop to the FRONT of the session.

    This container's jaxlib segfaults the whole pytest process (C++
    stack, no repo frames — pre-existing at seed HEAD, stash-verified)
    when an in-process ElasticTrainLoop test runs AFTER any
    engine-heavy module (test_generation/test_serving/...) in the same
    process with the persistent compile cache warm; at its alphabetical
    slot the crash killed every test sorting after test_train_loop.
    Run FIRST — paired with the module's own cache-off fixture — the
    same tests pass 100%. Ordering is otherwise preserved."""
    front = [
        it for it in items if it.fspath.basename == "test_train_loop.py"
    ]
    if front:
        rest = [
            it
            for it in items
            if it.fspath.basename != "test_train_loop.py"
        ]
        items[:] = front + rest
