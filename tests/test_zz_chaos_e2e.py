"""End-to-end chaos scenarios (chaos/scenarios.py) — run LAST (zz):
each injects a deterministic fault into the real runtime path, asserts
the injection demonstrably fired (injection records/log), and asserts
the runtime recovered. The slow production-shaped storms live in
tests/test_goodput_storm.py; this file carries the non-slow storm
smoke plus the in-process/subprocess scenario drills the
``tpurun-chaos`` CLI ships.
"""

import os

import jax
import numpy as np
import pytest

from dlrover_tpu.chaos import faults


@pytest.fixture(autouse=True)
def _clean_injector():
    faults.deactivate()
    yield
    faults.deactivate()


def test_storm_smoke_compressed(tmp_path):
    """Non-slow storm smoke (satellite): 1 kill, ~20 steps, relaxed
    bounds — the harness (real master + agents + trainers + SIGKILL +
    recovery) is exercised on every full tier-1 run, not only by the
    8-minute slow test. Doubles as the env-activation e2e for the
    fault-injection layer: the plan rides DLROVER_FAULT_PLAN into the
    REAL agent processes and must demonstrably fire there."""
    from dlrover_tpu.chaos import run_goodput_storm

    log = tmp_path / "faults.jsonl"
    result = run_goodput_storm(
        str(tmp_path / "storm"),
        num_workers=2,
        kills=1,
        kill_interval_steps=10,
        settle_steps=5,
        first_kill_step=5,
        step_sleep=0.2,
        storage_every=5,
        timeout_s=240.0,
        job_name=f"storm_smoke_{os.getpid()}",
        extra_env={
            "DLROVER_FAULT_PLAN": (
                f"log={log};agent.worker_start:delay:0.2@once"
            ),
        },
    )
    assert result is not None, "smoke storm timed out"
    assert result["kills"] == 1
    assert result["steps"] >= 15
    # Relaxed bounds: the machinery must RECOVER (watermark reaches the
    # budget, MTTR bounded); the >=0.90 goodput north star stays with
    # the slow production-shaped test where MTBF >> MTTR holds.
    assert result["training_goodput"] > 0.2, result
    assert result["mttr_s"] <= 90.0, result
    fired = [
        r
        for r in faults.read_log(str(log))
        if r["point"] == "agent.worker_start"
    ]
    assert fired, "fault plan never fired inside the agent processes"


def test_flaky_rpc_scenario(tmp_path):
    from dlrover_tpu.chaos.scenarios import flaky_rpc

    result = flaky_rpc(str(tmp_path))
    assert result["fired"] >= 2, result
    assert result["recovered"], result


def test_rdzv_retry_scenario(tmp_path):
    from dlrover_tpu.chaos.scenarios import rdzv_retry

    result = rdzv_retry(str(tmp_path))
    assert result["fired"] >= 1, result
    assert result["recovered"], result


def test_peer_replica_loss_scenario(tmp_path):
    from dlrover_tpu.chaos.scenarios import peer_replica_loss

    result = peer_replica_loss(str(tmp_path))
    assert result["fired"] >= 1, result
    assert result["recovered"], result


def test_saver_wedge_scenario(tmp_path):
    from dlrover_tpu.chaos.scenarios import saver_wedge

    result = saver_wedge(str(tmp_path))
    assert result["fired"] >= 1, result
    assert result["recovered"], result


def test_poisoned_swap_scenario(tmp_path):
    from dlrover_tpu.chaos.scenarios import poisoned_swap

    result = poisoned_swap(str(tmp_path))
    assert result["fired"] >= 1, result
    assert result["recovered"], result


class TestSwapFailureMidOverlap:
    """Satellite regression: an injected device-transfer failure during
    ``set_params_async`` MID-OVERLAP surfaces in ``stats()`` and leaves
    the pipeline serving the old weights — no wedge, ``swap_pending``
    cleared, streams bit-identical with the never-swapped baseline."""

    def _engine(self):
        import jax.numpy as jnp

        from dlrover_tpu.models.generation import SamplingConfig
        from dlrover_tpu.models.gpt import GPT, GPTConfig
        from dlrover_tpu.models.serving import ContinuousBatchingEngine

        model = GPT(
            GPTConfig(
                vocab_size=64,
                max_seq_len=128,
                num_layers=2,
                num_heads=2,
                head_dim=8,
                embed_dim=16,
                use_remat=False,
            )
        )
        params = model.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
        )["params"]
        sampling = SamplingConfig(max_new_tokens=8, temperature=0.0)
        eng = ContinuousBatchingEngine(
            model, params, sampling, batch_size=2, prompt_width=16,
            decode_chunk=4, overlap=True,
        )
        return eng, params

    def test_poisoned_swap_mid_stream(self):
        eng, params = self._engine()
        r = np.random.default_rng(3)
        prompts = [
            [int(x) for x in r.integers(1, 64, 6)] for _ in range(4)
        ]
        baseline = {c.uid: c.tokens for c in eng.run(prompts)}
        base_by_prompt = [
            baseline[uid] for uid in sorted(baseline)
        ]

        # Re-stream the same prompts; poison a swap while chunks are in
        # flight. The attempted push is ZEROED weights — if the aborted
        # swap leaked through, the greedy stream would change.
        faults.activate(
            faults.FaultPlan.parse("serving.swap:error:poisoned@once")
        )
        uids = [eng.submit(p) for p in prompts]
        rng = jax.random.PRNGKey(0)
        poisoned = False
        rounds = 0
        while eng.pending:
            rng, key = jax.random.split(rng)
            eng.step(key)
            rounds += 1
            if not poisoned and rounds >= 1:
                poisoned_params = jax.tree_util.tree_map(
                    lambda x: x * 0, params
                )
                eng.set_params_async(poisoned_params)
                poisoned = True
            assert rounds < 500, "pipeline wedged after poisoned swap"
        stats = eng.stats()
        assert stats["swap_pending"] is False
        assert stats["swap_failures"] == 1
        assert "poisoned" in stats["last_swap_error"]
        got = {c.uid: c.tokens for c in eng.drain_completions()}
        assert [got[u] for u in uids] == base_by_prompt
        assert [r["point"] for r in faults.records()] == ["serving.swap"]

    def test_blocking_set_params_survives_abort(self):
        """The blocking wrapper must not wedge on an aborted swap."""
        eng, params = self._engine()
        faults.activate(
            faults.FaultPlan.parse("serving.swap:error:poisoned@once")
        )
        eng.set_params(params)  # aborted inside; must return, not raise
        assert eng.stats()["swap_failures"] == 1
        assert eng.stats()["swap_pending"] is False

    def test_spec_target_abort_in_flight_drops_draft_too(self, monkeypatch):
        """Regression: a target transfer that fails IN FLIGHT (readiness
        probe raises mid-overlap) must abort the draft with it — an
        orphaned pending draft would adopt against a later target-only
        swap, serving the mismatched pair atomic adoption forbids."""
        import dataclasses

        from dlrover_tpu.models import serving
        from dlrover_tpu.models.generation import SamplingConfig
        from dlrover_tpu.models.gpt import GPT, GPTConfig
        from dlrover_tpu.models.serving import SpeculativeBatchingEngine

        model = GPT(
            GPTConfig(
                vocab_size=64,
                max_seq_len=256,
                num_layers=2,
                num_heads=2,
                head_dim=8,
                embed_dim=16,
                use_remat=False,
            )
        )
        import jax.numpy as jnp

        params = model.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
        )["params"]
        draft = GPT(dataclasses.replace(model.config, num_layers=1))
        d_params = draft.init(
            jax.random.PRNGKey(7), jnp.zeros((1, 8), jnp.int32)
        )["params"]
        eng = SpeculativeBatchingEngine(
            model,
            params,
            SamplingConfig(max_new_tokens=4, temperature=0.0),
            batch_size=2,
            prompt_width=16,
            draft_model=draft,
            draft_params=d_params,
            num_draft=2,
        )
        old_draft = eng.draft_params

        # Stage a paired swap whose TARGET dies in flight: the draft's
        # readiness probe (checked first) passes, the target's raises.
        eng.set_params_async(params, draft_params=d_params)
        probes = {"n": 0}

        def flaky_ready(tree):
            probes["n"] += 1
            if probes["n"] == 1:
                return True  # draft landed
            raise RuntimeError("target transfer died in flight")

        monkeypatch.setattr(serving, "_tree_ready", flaky_ready)
        assert eng._maybe_adopt_pending() is False
        monkeypatch.undo()
        assert eng._pending_params is None
        assert eng._pending_draft is None  # no orphan
        assert eng.stats()["swap_failures"] == 1

        # A later target-only swap adopts cleanly: the draft keeps
        # self-following semantics of its CURRENT pair, not the corpse
        # of the aborted push.
        eng.set_params_async(params)
        assert eng._maybe_adopt_pending() is True
        assert eng.draft_params is old_draft


@pytest.mark.slow
def test_host_kill_scenario(tmp_path):
    from dlrover_tpu.chaos.scenarios import host_kill

    result = host_kill(str(tmp_path))
    assert result["fired"] >= 1, result
    assert result["recovered"], result


def test_kv_alloc_pressure_scenario(tmp_path):
    """Paged-KV allocator under injected block-pool exhaustion: bursts
    queue at admission, nothing OOMs or wedges, and every request
    completes with the pool fully recovered."""
    from dlrover_tpu.chaos.scenarios import kv_alloc_pressure

    result = kv_alloc_pressure(str(tmp_path))
    assert result["fired"] >= 3, result
    assert result["recovered"], result


@pytest.mark.slow
def test_prefill_handoff_drop_scenario(tmp_path):
    """Full disaggregated-fleet drill (real engines; the fast
    synthetic twin lives in test_fleet.py): a dropped prefill handoff
    falls back to the decode replica's direct path, never a client
    error."""
    from dlrover_tpu.chaos.scenarios import prefill_handoff_drop

    result = prefill_handoff_drop(str(tmp_path))
    assert result["fired"] >= 1, result
    assert result["recovered"], result


def test_dp_pp_trade_storm_scenario(tmp_path):
    """Fast synthetic twin of the DP↔PP trade drill
    (docs/elastic_parallelism.md): an injected replan blip mid-shrink,
    then the retry picks the dp2·pp2 rung over accum-only and the
    staged flash image reshards onto the new mesh bit-exact."""
    from dlrover_tpu.chaos.scenarios import dp_pp_trade_storm

    result = dp_pp_trade_storm(str(tmp_path))
    assert result["fired"] >= 1, result
    assert result["recovered"], result
    assert result["transition"] == "dp8 → dp2·pp2", result
    assert result["hybrid_vs_accum_goodput_x"] > 1.0, result
    assert result["retries"] >= 1, result


@pytest.mark.slow
def test_dp_pp_trade_storm_via_cli(tmp_path, capsys):
    """The same drill the operator runs: ``tpurun-chaos run
    dp_pp_trade_storm`` exits 0 only when the trade recovered."""
    import json as _json

    from dlrover_tpu.chaos.cli import main

    assert main(
        ["run", "dp_pp_trade_storm", "--workdir", str(tmp_path)]
    ) == 0
    result = _json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert result["recovered"] and result["fired"] >= 1, result
    assert result["transition"] == "dp8 → dp2·pp2", result
