"""Serving fleet (dlrover_tpu/fleet/): supervisor state machine,
slot-aware gateway, staged rollout, autoscaler, chaos drills.

Mechanics tests run over STUB replicas — a tiny HTTP server speaking
the tpurun-serve surface with scripted stats/failures — so routing,
failover, admission, and rollout staging are pinned without paying an
engine compile per case. Engine-backed correctness (gateway completion
== direct engine greedy output, prefix serving) runs over in-process
replicas with the real ContinuousBatchingEngine.
"""

import json
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from dlrover_tpu.chaos import faults
from dlrover_tpu.fleet import (
    FleetAutoscaler,
    FleetConfig,
    Gateway,
    InProcessReplica,
    ReplicaState,
    ReplicaSupervisor,
    staged_rollout,
)

# ---------------------------------------------------------------------------
# Stub replica: the tpurun-serve HTTP surface, scripted.
# ---------------------------------------------------------------------------


class StubReplica:
    """Protocol-compatible replica whose behavior is scripted per test:
    canned /healthz stats, per-request completion delay, reload
    success/failure, and an abrupt kill."""

    def __init__(self, replica_id: int, port: int = 0, script=None):
        self.replica_id = replica_id
        self.port = port
        self.script = script or {}
        self.served = 0
        self.reloads = 0
        self.prefills = 0
        self.prefix_deletes = 0
        self._uid = 0
        self._prefixes = {}
        self._next_pid = 0
        self._swap_failures = 0
        self._httpd = None
        self._thread = None
        self._alive = False
        self._busy = 0
        self._mu = threading.Lock()

    # -- lifecycle (supervisor protocol) ----------------------------

    @property
    def pid(self):
        return None

    def start(self):
        stub = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def _send(self, code, payload):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    with stub._mu:
                        busy = stub._busy
                    self._send(200, {
                        "replica_id": stub.replica_id,
                        "busy_slots": stub.script.get(
                            "busy_slots", busy
                        ),
                        "queue_depth": stub.script.get(
                            "queue_depth", 0
                        ),
                        "inflight_chunks": 0,
                        "latency_p95_s": stub.script.get(
                            "latency_p95_s"
                        ),
                        "tokens_per_s": stub.script.get("tokens_per_s"),
                        "swap_failures": stub._swap_failures,
                        "swap_pending": False,
                        "last_swap_error": None,
                        # scripted warmth override, else every
                        # registered pid is resident (engine stats
                        # surface, gateway affinity input)
                        "resident_prefixes": stub.script.get(
                            "resident_prefixes",
                            sorted(stub._prefixes),
                        ),
                        "blocks_total": stub.script.get("blocks_total"),
                        "blocks_free": stub.script.get("blocks_free"),
                        "prefix_hits": stub.script.get(
                            "prefix_hits", 0
                        ),
                        "alloc_failures": 0,
                    })
                else:
                    self._send(404, {"error": "nope"})

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0) or 0)
                body = json.loads(self.rfile.read(n)) if n else {}
                if self.path == "/v1/completions":
                    delay = stub.script.get("delay_s", 0.0)
                    with stub._mu:
                        stub._busy += 1
                    try:
                        if delay:
                            time.sleep(delay)
                        if not stub._alive:
                            # killed mid-request: die like a SIGKILL —
                            # drop the socket, never answer
                            self.connection.close()
                            return
                        if stub.script.get("fail_completions"):
                            self._send(500, {"error": "scripted"})
                            return
                        pid = body.get("prefix_id")
                        if pid is not None and (
                            pid not in stub._prefixes
                        ):
                            self._send(
                                400,
                                {"error": f"unknown prefix_id {pid}"},
                            )
                            return
                        with stub._mu:
                            stub._uid += 1
                            stub.served += 1
                            uid = stub._uid
                        # tokens encode WHO served (replica id) — the
                        # tests read routing off the response
                        self._send(200, {
                            "uid": uid,
                            "tokens": [stub.replica_id] * 3,
                            "logprobs": [0.0] * 3,
                            "queue_s": 0.0, "ttft_s": 0.001,
                            "total_s": 0.002,
                        })
                    finally:
                        with stub._mu:
                            stub._busy -= 1
                elif self.path == "/v1/prefixes":
                    with stub._mu:
                        pid = stub._next_pid
                        stub._next_pid += 1
                        stub._prefixes[pid] = body["tokens"]
                    self._send(200, {"prefix_id": pid})
                elif self.path == "/v1/prefill":
                    if stub.script.get("fail_prefill"):
                        self._send(500, {"error": "scripted"})
                        return
                    with stub._mu:
                        stub.prefills += 1
                    self._send(200, {"prefilled": {
                        "stub": True, "tokens": body["tokens"],
                    }})
                elif self.path == "/v1/weights/reload":
                    stub.reloads += 1
                    if stub.script.get("fail_reload"):
                        stub._swap_failures += 1
                        self._send(500, {"error": "poisoned ckpt"})
                        return
                    self._send(200, {
                        "step": stub.script.get("reload_step", 1),
                        "swap_latency_s": 0.01,
                    })
                else:
                    self._send(404, {"error": "nope"})

            def do_DELETE(self):
                n = int(self.headers.get("Content-Length", 0) or 0)
                body = json.loads(self.rfile.read(n)) if n else {}
                if self.path == "/v1/prefixes":
                    pid = body.get("prefix_id")
                    with stub._mu:
                        known = pid in stub._prefixes
                        if known:
                            del stub._prefixes[pid]
                            stub.prefix_deletes += 1
                    if not known:
                        self._send(
                            404, {"error": f"unknown prefix_id {pid}"}
                        )
                        return
                    self._send(200, {"removed": pid})
                else:
                    self._send(404, {"error": "nope"})

        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()
        self._alive = True

    def alive(self):
        return self._alive

    def terminate(self):
        self.kill()

    def kill(self):
        if not self._alive:
            return
        self._alive = False
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=10)


def _stub_fleet(n=2, script=None, scripts=None, **cfg_kwargs):
    """(supervisor, gateway) over stub replicas, started and READY."""
    made = {}

    def factory(rid, port):
        s = (scripts or {}).get(rid, script)
        rep = StubReplica(rid, port, script=dict(s) if s else None)
        made[rid] = rep
        return rep

    defaults = dict(
        replicas=n, max_replicas=max(n, 4),
        health_interval_s=0.05, health_timeout_s=5.0,
        health_fails=3, relaunch_budget=2, start_timeout_s=30.0,
        drain_timeout_s=10.0, request_timeout_s=30.0,
    )
    defaults.update(cfg_kwargs)
    cfg = FleetConfig(**defaults)
    sup = ReplicaSupervisor(factory, cfg).start()
    gw = Gateway(sup, cfg)
    assert sup.wait_ready(n, timeout=30.0), "stub fleet never READY"
    return sup, gw, made


# ---------------------------------------------------------------------------
# Supervisor state machine
# ---------------------------------------------------------------------------


class TestSupervisor:
    def test_starting_to_ready_and_status(self):
        sup, gw, _ = _stub_fleet(2)
        try:
            st = sup.status()
            assert st["ready"] == 2 and st["target"] == 2
            states = {r["state"] for r in st["replicas"]}
            assert states == {ReplicaState.READY}
        finally:
            sup.stop()

    def test_kill_declares_dead_and_relaunches(self):
        sup, gw, _ = _stub_fleet(2)
        try:
            h = sup.get(0)
            assert sup.kill_replica(0)
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline and h.generation < 1:
                time.sleep(0.02)
            assert h.generation == 1 and h.relaunches == 1
            assert sup.wait_ready(2, timeout=30.0)
        finally:
            sup.stop()

    def test_relaunch_budget_exhaustion_leaves_dead(self):
        sup, gw, made = _stub_fleet(2, relaunch_budget=0)
        try:
            sup.kill_replica(1)
            deadline = time.monotonic() + 30
            h = sup.get(1)
            while (
                time.monotonic() < deadline
                and h.state != ReplicaState.DEAD
            ):
                time.sleep(0.02)
            assert h.state == ReplicaState.DEAD
            time.sleep(0.3)  # would-be relaunch window
            assert h.relaunches == 0  # budget 0: never relaunched
            # the fleet degrades but the survivor still serves
            out = gw.complete({"prompt": [1, 2]})
            assert out["replica"] == 0
        finally:
            sup.stop()

    def test_drain_readmit_cycle(self):
        sup, gw, _ = _stub_fleet(2)
        try:
            assert sup.drain(0)
            assert sup.get(0).state == ReplicaState.DRAINING
            # DRAINING is out of rotation: every request lands on 1
            for _ in range(4):
                assert gw.complete({"prompt": [1]})["replica"] == 1
            assert sup.readmit(0)
            assert sup.get(0).state == ReplicaState.READY
            # can't readmit a READY replica or drain a DRAINING one
            assert not sup.readmit(0)
            assert sup.drain(0) and not sup.drain(0)
            sup.readmit(0)
        finally:
            sup.stop()

    def test_health_fail_streak_kills_replica(self):
        """consecutive failed polls (here: the stub's socket closed
        behind the supervisor's back) drive READY -> DEAD."""
        sup, gw, made = _stub_fleet(1, relaunch_budget=0)
        try:
            # close the HTTP server without flipping alive(): polls
            # now fail while the "process" looks alive
            rep = made[0]
            rep._httpd.shutdown()
            rep._httpd.server_close()
            h = sup.get(0)
            deadline = time.monotonic() + 30
            while (
                time.monotonic() < deadline
                and h.state != ReplicaState.DEAD
            ):
                time.sleep(0.02)
            assert h.state == ReplicaState.DEAD
            assert "failed health polls" in h.last_error
        finally:
            rep._alive = False
            sup.stop()

    def test_scale_to_grows_and_shrinks_within_bounds(self):
        sup, gw, _ = _stub_fleet(2, max_replicas=3, min_replicas=1)
        try:
            assert sup.scale_to(5) == 3  # clamped to max
            assert sup.wait_ready(3, timeout=30.0)
            assert sup.scale_to(0) == 1  # clamped to min
            deadline = time.monotonic() + 30
            while (
                time.monotonic() < deadline
                and len(sup.replicas()) != 1
            ):
                time.sleep(0.02)
            assert len(sup.replicas()) == 1
            # shrink removed the NEWEST rids; rid 0 survives
            assert sup.replicas()[0].rid == 0
        finally:
            sup.stop()


# ---------------------------------------------------------------------------
# Gateway: routing, failover, admission
# ---------------------------------------------------------------------------


class TestHttpErrorDetail:
    """PR 9: the 4 copies of the error-body parser folded into
    gateway._http_error_detail — the replica's JSON verdict passes
    through, and an unreadable body keeps BOTH failures."""

    def _err(self, code, body):
        import io
        import urllib.error

        return urllib.error.HTTPError(
            "http://x/v1/completions", code, "nope", {}, io.BytesIO(body)
        )

    def test_json_verdict_passes_through(self):
        from dlrover_tpu.fleet.gateway import _http_error_detail

        d = _http_error_detail(self._err(400, b'{"error": "bad prompt"}'))
        assert d == {"error": "bad prompt"}

    def test_unreadable_body_keeps_both_failures(self):
        from dlrover_tpu.fleet.gateway import _http_error_detail

        d = _http_error_detail(self._err(502, b"<html>oops</html>"))
        assert "502" in d["error"]
        assert "detail_unreadable" in d


class TestGatewayRouting:
    def test_least_loaded_routing_spreads_load(self):
        sup, gw, _ = _stub_fleet(2)
        try:
            for _ in range(8):
                gw.complete({"prompt": [1, 2]})
            # every request saw idle stats on both → the in-flight
            # term decides; serial requests alternate via rid
            # tie-break + routed counters must cover both replicas
            assert set(gw.routed) == {0, 1}
        finally:
            sup.stop()

    def test_routing_prefers_unloaded_replica(self):
        # replica 0 reports all slots busy + a deep queue; replica 1
        # idle: everything routes to 1
        sup, gw, _ = _stub_fleet(
            2, scripts={0: {"busy_slots": 8, "queue_depth": 9},
                        1: {}},
        )
        try:
            time.sleep(0.2)  # let the monitor pick up the stats
            for _ in range(5):
                assert gw.complete({"prompt": [1]})["replica"] == 1
        finally:
            sup.stop()

    def test_redispatch_on_dead_replica_zero_failures(self):
        """Kill one of two replicas while requests are in flight
        against it: every non-streamed request still succeeds."""
        sup, gw, made = _stub_fleet(
            2, scripts={0: {"delay_s": 0.3}, 1: {}},
        )
        try:
            results = {"ok": 0, "failed": 0}
            mu = threading.Lock()

            def hit(i):
                try:
                    out = gw.complete({"prompt": [1, i]})
                    assert out["tokens"]
                    with mu:
                        results["ok"] += 1
                except Exception:  # noqa: BLE001 — counted below
                    with mu:
                        results["failed"] += 1

            threads = [
                threading.Thread(target=hit, args=(i,))
                for i in range(10)
            ]
            for t in threads:
                t.start()
            time.sleep(0.1)  # some requests now parked on replica 0
            made[0].kill()
            for t in threads:
                t.join(timeout=30)
            assert results == {"ok": 10, "failed": 0}
            assert gw.redispatches >= 1
        finally:
            sup.stop()

    def test_replica_400_forwards_without_redispatch(self):
        sup, gw, _ = _stub_fleet(2)
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                # stub 400s unknown prefix ids; the gateway must not
                # mask a client error as a failover
                gw._post_replica(
                    sup.ready_replicas()[0], "/v1/completions",
                    {"prompt": [1], "prefix_id": 404},
                    timeout=10.0,
                )
            assert ei.value.code == 400
            assert gw.redispatches == 0
        finally:
            sup.stop()

    def test_admission_control_429_with_retry_after(self):
        sup, gw, made = _stub_fleet(
            2, scripts={0: {"delay_s": 1.0}, 1: {"delay_s": 1.0}},
            queue_limit=2,
        )
        port = gw.start_http(0)
        base = f"http://127.0.0.1:{port}"
        try:
            codes = []
            retry_after = []
            mu = threading.Lock()

            def hit():
                req = urllib.request.Request(
                    base + "/v1/completions",
                    data=json.dumps({"prompt": [1]}).encode(),
                    headers={"Content-Type": "application/json"},
                    method="POST",
                )
                try:
                    with urllib.request.urlopen(req, timeout=30) as r:
                        with mu:
                            codes.append(r.status)
                except urllib.error.HTTPError as e:
                    with mu:
                        codes.append(e.code)
                        if e.code == 429:
                            retry_after.append(
                                e.headers.get("Retry-After")
                            )

            threads = [
                threading.Thread(target=hit) for _ in range(6)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30)
            # 2 slots of admission + 4 rejects (scripted 1s service
            # time ensures overlap)
            assert codes.count(429) >= 1
            assert codes.count(200) >= 2
            assert retry_after and float(retry_after[0]) > 0
            assert gw.rejected >= 1
        finally:
            sup.stop()

    def test_no_ready_replica_is_503(self):
        sup, gw, made = _stub_fleet(1, relaunch_budget=0)
        port = gw.start_http(0)
        base = f"http://127.0.0.1:{port}"
        try:
            sup.kill_replica(0)
            h = sup.get(0)
            deadline = time.monotonic() + 30
            while (
                time.monotonic() < deadline
                and h.state != ReplicaState.DEAD
            ):
                time.sleep(0.02)
            req = urllib.request.Request(
                base + "/v1/completions",
                data=json.dumps({"prompt": [1]}).encode(),
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=30)
            assert ei.value.code == 503
        finally:
            sup.stop()

    def test_fleet_status_endpoint(self):
        sup, gw, _ = _stub_fleet(2)
        port = gw.start_http(0)
        base = f"http://127.0.0.1:{port}"
        try:
            gw.complete({"prompt": [1]})
            with urllib.request.urlopen(
                base + "/fleet/status", timeout=30
            ) as r:
                st = json.loads(r.read())
            assert st["ready"] == 2
            assert st["gateway"]["served"] == 1
            assert st["gateway"]["queue_limit"] == gw.cfg.queue_limit
            # the gateway's own attribution phases ride the status
            assert "serving_host_frac" in st["phase_split"]
            assert "route_ms" in st["phase_split"]
            assert "proxy_ms" in st["phase_split"]
            # /fleet/scale over HTTP
            req = urllib.request.Request(
                base + "/fleet/scale",
                data=json.dumps({"replicas": 3}).encode(),
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            with urllib.request.urlopen(req, timeout=30) as r:
                assert json.loads(r.read())["replicas"] == 3
            assert sup.wait_ready(3, timeout=30.0)
        finally:
            sup.stop()


# ---------------------------------------------------------------------------
# Prefix fan-out
# ---------------------------------------------------------------------------


class TestGatewayPrefixes:
    def test_prefix_registers_everywhere_and_replays_on_relaunch(self):
        sup, gw, made = _stub_fleet(2)
        try:
            pid = gw.register_prefix([4, 5, 6])
            assert made[0]._prefixes and made[1]._prefixes
            out = gw.complete({"prompt": [7], "prefix_id": pid})
            assert out["tokens"]
            # kill + relaunch replica 0: the fresh stub has NO
            # prefixes until the READY replay re-registers
            sup.kill_replica(0)
            h = sup.get(0)
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline and h.generation < 1:
                time.sleep(0.02)
            assert sup.wait_ready(2, timeout=30.0)
            fresh = made[0]  # factory re-made rid 0
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and not fresh._prefixes:
                time.sleep(0.02)
            assert fresh._prefixes, "replay never reached the relaunch"
            # a prefix completion pinned to the fresh replica works
            sup.drain(1)
            out = gw.complete({"prompt": [7], "prefix_id": pid})
            assert out["replica"] == 0
            sup.readmit(1)
        finally:
            sup.stop()

    def test_unknown_fleet_prefix_rejected_without_redispatch(self):
        """A bad prefix_id is the CLIENT's error: 400 over HTTP, no
        burned replicas, no inflated redispatch counter (pre-fix it
        exhausted every replica and surfaced as 503)."""
        from dlrover_tpu.fleet import UnknownPrefix

        sup, gw, _ = _stub_fleet(2)
        port = gw.start_http(0)
        base = f"http://127.0.0.1:{port}"
        try:
            with pytest.raises(UnknownPrefix):
                gw.complete({"prompt": [1], "prefix_id": 99})
            assert gw.redispatches == 0
            for stream in (False, True):
                req = urllib.request.Request(
                    base + "/v1/completions",
                    data=json.dumps({
                        "prompt": [1], "prefix_id": 99,
                        "stream": stream,
                    }).encode(),
                    headers={"Content-Type": "application/json"},
                    method="POST",
                )
                with pytest.raises(urllib.error.HTTPError) as ei:
                    urllib.request.urlopen(req, timeout=30)
                assert ei.value.code == 400
            assert gw.redispatches == 0
            # the gateway still serves normally afterwards
            assert gw.complete({"prompt": [1]})["tokens"]
        finally:
            sup.stop()


def _poll(gw, n=1):
    """Wait out >= n health-poll intervals so scripted /healthz stats
    land in the supervisor handles the gateway routes off."""
    time.sleep(max(0.2, n * gw.cfg.health_interval_s * 3))


class TestPrefixAffinity:
    def test_prefix_requests_prefer_warm_replica(self):
        """Replica 0 scripts an empty resident set (cold cache), so
        every prefix-id completion should land on warm replica 1 and
        bump the affinity counter; plain completions still spread."""
        sup, gw, made = _stub_fleet(
            2, scripts={0: {"resident_prefixes": []}}
        )
        try:
            pid = gw.register_prefix([4, 5, 6])
            _poll(gw)  # warmth is read off the last health poll
            for _ in range(4):
                out = gw.complete({"prompt": [7], "prefix_id": pid})
                assert out["tokens"] == [1, 1, 1], out
            assert gw.affinity_hits >= 4
            assert gw.status()["gateway"]["affinity_hits"] >= 4
            # affinity is a preference, not a pin: plain traffic still
            # reaches the cold replica
            for _ in range(4):
                gw.complete({"prompt": [7]})
            assert made[0].served > 0
        finally:
            sup.stop()

    def test_kv_aggregate_sums_paged_replicas(self):
        """/fleet/status "kv" sums block occupancy over the replicas
        that report a paged pool and stays None-total when none do."""
        sup, gw, _ = _stub_fleet(2)
        try:
            _poll(gw)
            kv = gw.status()["kv"]
            assert kv["blocks_total"] is None
            assert kv["blocks_free"] is None
        finally:
            sup.stop()
        sup, gw, _ = _stub_fleet(2, scripts={
            0: {"blocks_total": 64, "blocks_free": 10,
                "prefix_hits": 3},
            1: {"prefix_hits": 2},  # dense replica: no pool
        })
        try:
            _poll(gw)
            kv = gw.status()["kv"]
            assert kv["blocks_total"] == 64
            assert kv["blocks_free"] == 10
            assert kv["prefix_hits"] == 5
        finally:
            sup.stop()


class TestPrefixGC:
    def test_registry_bounded_no_leak(self):
        """Leak regression: registering far past prefix_capacity keeps
        the fleet registry, the replica-pid map, AND the replica-side
        prefix stores bounded — evicted ids are forgotten everywhere."""
        sup, gw, made = _stub_fleet(2, prefix_capacity=4)
        try:
            pids = [gw.register_prefix([i]) for i in range(50)]
            assert len(gw._prefixes) <= 4
            assert gw.prefix_evictions == 46
            # replica-pid translations for evicted ids are gone too
            assert all(
                k[3] in gw._prefixes for k in gw._replica_pids
            ), "evicted prefix left a dangling replica-pid entry"
            # replica-side forget fan-out freed the stub stores
            for rep in made.values():
                assert len(rep._prefixes) <= 4
                assert rep.prefix_deletes >= 46
            # survivors are the MRU tail and still usable
            out = gw.complete({"prompt": [7], "prefix_id": pids[-1]})
            assert out["tokens"]
            with pytest.raises(Exception):
                gw.complete({"prompt": [7], "prefix_id": pids[0]})
        finally:
            sup.stop()

    def test_unregister_blocked_while_referenced_then_ok(self):
        """DELETE of a prefix a request is still decoding against is a
        retryable conflict; it succeeds once the request drains."""
        sup, gw, made = _stub_fleet(2, script={"delay_s": 0.4})
        try:
            pid = gw.register_prefix([1, 2, 3])
            t = threading.Thread(
                target=gw.complete,
                args=({"prompt": [7], "prefix_id": pid},),
            )
            t.start()
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline and not gw._prefix_refs:
                time.sleep(0.01)
            assert gw._prefix_refs, "request never pinned its prefix"
            with pytest.raises(ValueError, match="in-flight"):
                gw.unregister_prefix(pid)
            t.join(timeout=30)
            gw.unregister_prefix(pid)
            assert not gw._prefixes
            for rep in made.values():
                assert not rep._prefixes
            with pytest.raises(KeyError):
                gw.unregister_prefix(999)
        finally:
            sup.stop()

    def test_delete_prefix_over_http(self):
        sup, gw, _ = _stub_fleet(2)
        port = gw.start_http(0)
        base = f"http://127.0.0.1:{port}"

        def delete(pid):
            req = urllib.request.Request(
                base + "/v1/prefixes",
                data=json.dumps({"prefix_id": pid}).encode(),
                headers={"Content-Type": "application/json"},
                method="DELETE",
            )
            with urllib.request.urlopen(req, timeout=30) as r:
                return r.status, json.loads(r.read())

        try:
            pid = gw.register_prefix([1, 2, 3])
            code, out = delete(pid)
            assert code == 200 and out["removed"] == pid
            with pytest.raises(urllib.error.HTTPError) as ei:
                delete(pid)
            assert ei.value.code == 404
            with pytest.raises(urllib.error.HTTPError) as ei:
                delete("not-an-int")
            assert ei.value.code == 400
        finally:
            gw.stop_http()
            sup.stop()


class TestDisaggregatedStubFleet:
    """Fast synthetic twin of the engine-backed disaggregation drill
    (chaos scenario ``prefill_handoff_drop`` exercises the real
    engines): handoff routing, short-prompt bypass, and the
    failure->direct-path fallback over scripted stubs."""

    def _fleet(self, **kw):
        return _stub_fleet(
            2, min_replicas=2, prefill_replicas=1,
            disagg_min_prompt=2, **kw
        )

    def test_long_prompt_hands_off_then_decodes(self):
        sup, gw, made = self._fleet()
        try:
            out = gw.complete({"prompt": [1, 2, 3]})
            # rid 0 is the prefill replica; completions must land on
            # the decode replica (tokens encode who served)
            assert out["tokens"] == [1, 1, 1], out
            assert made[0].prefills == 1
            assert made[0].served == 0
            assert gw.handoffs == 1 and gw.handoff_fallbacks == 0
            st = sup.status()
            assert st["ready_prefill"] == 1
            assert st["ready_decode"] == 1
        finally:
            sup.stop()

    def test_short_prompt_skips_handoff(self):
        sup, gw, made = self._fleet()
        try:
            out = gw.complete({"prompt": [7]})
            assert out["tokens"] == [1, 1, 1], out
            assert made[0].prefills == 0 and gw.handoffs == 0
        finally:
            sup.stop()

    def test_prefill_failure_falls_back_to_direct_path(self):
        sup, gw, made = self._fleet(
            scripts={0: {"fail_prefill": True}}
        )
        try:
            out = gw.complete({"prompt": [1, 2, 3]})
            assert out["tokens"] == [1, 1, 1], out
            assert gw.handoffs == 0 and gw.handoff_fallbacks == 1
        finally:
            sup.stop()


# ---------------------------------------------------------------------------
# Staged rollout (stub mechanics; engine-backed e2e in
# tests/test_zz_fleet_e2e.py)
# ---------------------------------------------------------------------------


class TestStagedRollout:
    def test_rollout_one_at_a_time_bumps_versions(self):
        sup, gw, made = _stub_fleet(2, script={"reload_step": 7})
        try:
            report = staged_rollout(sup, gw)
            assert not report["aborted"]
            assert report["max_unready"] == 1  # never below N-1 READY
            assert report["steps"] == [7, 7]
            assert report["version_consistent"] is True
            assert [h.weight_version for h in sup.replicas()] == [1, 1]
            assert made[0].reloads == 1 and made[1].reloads == 1
            assert sup.status()["ready"] == 2
            assert gw.last_rollout is report
        finally:
            sup.stop()

    def test_swap_failure_aborts_and_rolls_back(self):
        """Replica 0's reload 500s: the rollout readmits it un-swapped
        (old weights keep serving at full fleet strength) and aborts
        instead of marching on to replica 1."""
        sup, gw, made = _stub_fleet(
            2, scripts={0: {"fail_reload": True}, 1: {}},
        )
        try:
            report = staged_rollout(sup, gw)
            assert report["aborted"] is True
            assert "swap failed" in report["replicas"][0]["error"]
            # replica 1 was never touched
            assert made[1].reloads == 0
            assert [h.weight_version for h in sup.replicas()] == [0, 0]
            # full strength restored
            assert sup.status()["ready"] == 2
            out = gw.complete({"prompt": [1]})
            assert out["tokens"]
        finally:
            sup.stop()

    def test_rollout_waits_for_inflight_work(self):
        """A request in flight on the draining replica holds the swap
        until it retires (the gateway's in-flight counter is part of
        the drain condition)."""
        sup, gw, made = _stub_fleet(
            2, scripts={0: {"delay_s": 0.8}, 1: {}},
        )
        try:
            done = {}

            def slow_hit():
                done["out"] = gw.complete({"prompt": [1]})

            sup.drain(1)  # force the request onto replica 0
            t = threading.Thread(target=slow_hit)
            t.start()
            time.sleep(0.2)
            sup.readmit(1)
            report = staged_rollout(sup, gw)
            t.join(timeout=30)
            assert done["out"]["replica"] == 0
            assert not report["aborted"]
            # the drain on rid 0 waited for the slow request
            assert report["replicas"][0]["drain_s"] >= 0.4
        finally:
            sup.stop()

    def test_rollout_over_http(self):
        sup, gw, _ = _stub_fleet(2, script={"reload_step": 3})
        port = gw.start_http(0)
        base = f"http://127.0.0.1:{port}"
        try:
            req = urllib.request.Request(
                base + "/fleet/rollout",
                data=json.dumps({"wait": True}).encode(),
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            with urllib.request.urlopen(req, timeout=60) as r:
                report = json.loads(r.read())
            assert report["steps"] == [3, 3]
            with urllib.request.urlopen(
                base + "/fleet/status", timeout=30
            ) as r:
                st = json.loads(r.read())
            assert st["rollout"]["version_consistent"] is True
        finally:
            sup.stop()


# ---------------------------------------------------------------------------
# Autoscaler policy
# ---------------------------------------------------------------------------


class TestAutoscaler:
    def _scaler(self, sup, **cfg_kwargs):
        cfg_kwargs.setdefault("queue_high", 4.0)
        cfg = FleetConfig(
            replicas=len(sup.replicas()),
            min_replicas=1, max_replicas=4, **cfg_kwargs,
        )
        return FleetAutoscaler(sup, cfg)

    def test_grows_on_queue_pressure(self):
        sup, gw, _ = _stub_fleet(
            2, script={"queue_depth": 9, "busy_slots": 8},
        )
        try:
            time.sleep(0.2)
            scaler = self._scaler(sup)
            decision = scaler.step()
            assert decision["target"] == 3
            assert sup.wait_ready(3, timeout=30.0)
        finally:
            sup.stop()

    def test_grows_on_p95_latency(self):
        sup, gw, _ = _stub_fleet(2, script={"latency_p95_s": 9.0})
        try:
            time.sleep(0.2)
            scaler = self._scaler(sup, p95_target_s=1.0)
            assert scaler.step()["target"] == 3
        finally:
            sup.stop()

    def test_shrinks_only_after_sustained_idle(self):
        sup, gw, _ = _stub_fleet(2)
        try:
            time.sleep(0.2)
            scaler = self._scaler(sup)
            # hysteresis: the first SHRINK_AFTER-1 idle evals hold N
            for _ in range(scaler.SHRINK_AFTER - 1):
                assert scaler.step()["target"] == 2
            assert scaler.step()["target"] == 1
            deadline = time.monotonic() + 30
            while (
                time.monotonic() < deadline
                and len(sup.replicas()) != 1
            ):
                time.sleep(0.02)
            assert len(sup.replicas()) == 1
        finally:
            sup.stop()

    def test_never_scales_blind(self):
        sup, gw, _ = _stub_fleet(1, relaunch_budget=0)
        try:
            sup.kill_replica(0)
            h = sup.get(0)
            deadline = time.monotonic() + 30
            while (
                time.monotonic() < deadline
                and h.state != ReplicaState.DEAD
            ):
                time.sleep(0.02)
            scaler = self._scaler(sup)
            # 0 READY: no signal, no scaling decision
            assert scaler.step()["target"] == 1
        finally:
            sup.stop()

    def test_decide_is_pure_policy(self):
        sup, gw, _ = _stub_fleet(2)
        try:
            scaler = self._scaler(sup, p95_target_s=2.0)
            grow = {"ready": 2, "queue_mean": 10.0, "busy_total": 4,
                    "p95_worst_s": 0.1}
            assert scaler.decide(grow) == 3
            hold = {"ready": 2, "queue_mean": 1.0, "busy_total": 2,
                    "p95_worst_s": 0.5}
            assert scaler.decide(hold) == 2
        finally:
            sup.stop()


# ---------------------------------------------------------------------------
# Chaos drills: the three fleet injection points fire and recovery
# holds (the injection-coverage lint pass requires each point drilled)
# ---------------------------------------------------------------------------


class TestFleetInjectionDrills:
    def teardown_method(self):
        faults.deactivate()

    def test_fleet_route_error_is_503_then_recovers(self):
        sup, gw, _ = _stub_fleet(2)
        port = gw.start_http(0)
        base = f"http://127.0.0.1:{port}"
        try:
            faults.activate(
                faults.FaultPlan.parse("fleet.route:error:routing@at=1")
            )
            req = urllib.request.Request(
                base + "/v1/completions",
                data=json.dumps({"prompt": [1]}).encode(),
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=30)
            assert ei.value.code == 500
            fired = [
                r for r in faults.records()
                if r["point"] == "fleet.route"
            ]
            assert fired
            # the next request routes fine (the fault was once)
            out = gw.complete({"prompt": [1, 2]})
            assert out["tokens"]
        finally:
            sup.stop()

    def test_fleet_replica_health_error_drives_death(self):
        """Injected health-poll errors count toward the failure streak
        exactly like network failures — enough of them declare the
        replica dead and the budgeted relaunch takes over."""
        sup, gw, _ = _stub_fleet(2, health_fails=2)
        try:
            faults.activate(
                faults.FaultPlan.parse(
                    "fleet.replica_health:error:poisoned-poll@times=8"
                )
            )
            h0, h1 = sup.get(0), sup.get(1)
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline and not (
                h0.relaunches or h1.relaunches
            ):
                time.sleep(0.02)
            assert h0.relaunches or h1.relaunches
            fired = [
                r for r in faults.records()
                if r["point"] == "fleet.replica_health"
            ]
            assert len(fired) >= 2
            faults.deactivate()
            assert sup.wait_ready(2, timeout=30.0)
        finally:
            sup.stop()

    def test_fleet_replica_kill_point_fires_on_kill(self):
        sup, gw, _ = _stub_fleet(2)
        try:
            faults.activate(
                faults.FaultPlan.parse(
                    "fleet.replica_kill:delay:0.01@once"
                )
            )
            sup.kill_replica(1)
            fired = [
                r for r in faults.records()
                if r["point"] == "fleet.replica_kill"
            ]
            assert fired and fired[0]["ctx"]["replica"] == "1"
            assert sup.wait_ready(2, timeout=30.0)
        finally:
            sup.stop()


# ---------------------------------------------------------------------------
# Engine-backed correctness: the gateway serves EXACT engine output
# ---------------------------------------------------------------------------


def _small_model():
    import jax.numpy as jnp  # noqa: F401 — jax present iff engines run

    from dlrover_tpu.models.gpt import GPT, GPTConfig

    return GPT(
        GPTConfig(
            vocab_size=64, max_seq_len=128, num_layers=2, num_heads=2,
            head_dim=8, embed_dim=16, use_remat=False,
        )
    )


@pytest.fixture(scope="module")
def engine_fleet():
    import jax
    import jax.numpy as jnp

    from dlrover_tpu.models.generation import SamplingConfig
    from dlrover_tpu.models.serving import ContinuousBatchingEngine

    model = _small_model()
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    sampling = SamplingConfig(max_new_tokens=6, temperature=0.0)

    def engine_factory():
        return ContinuousBatchingEngine(
            model, params, sampling, batch_size=2, prompt_width=16,
            decode_chunk=4,
        )

    cfg = FleetConfig(
        replicas=2, max_replicas=2,
        health_interval_s=0.1, health_fails=50,
        health_timeout_s=15.0, relaunch_budget=2, start_timeout_s=60.0,
    )
    sup = ReplicaSupervisor(
        lambda rid, port: InProcessReplica(
            rid, port, engine_factory=engine_factory
        ),
        cfg,
    ).start()
    gw = Gateway(sup, cfg)
    assert sup.wait_ready(2, timeout=60.0)
    yield sup, gw, model, params, sampling
    sup.stop()


class TestEngineFleet:
    def test_gateway_completions_are_greedy_exact(self, engine_fleet):
        import jax
        import numpy as np

        from dlrover_tpu.models.generation import (
            generate,
            left_pad_prompts,
        )

        sup, gw, model, params, sampling = engine_fleet
        prompts = [[5, 9, 2], [3], [7, 7], [1, 2, 3, 4]]
        results = {}
        mu = threading.Lock()

        def hit(i):
            out = gw.complete({"prompt": prompts[i]})
            with mu:
                results[i] = out

        threads = [
            threading.Thread(target=hit, args=(i,))
            for i in range(len(prompts))
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        for i, p in enumerate(prompts):
            toks, mask = left_pad_prompts([p], pad_id=0)
            want, _, _ = generate(
                model, params, toks, mask, jax.random.PRNGKey(0),
                sampling,
            )
            assert results[i]["tokens"] == [
                int(t) for t in np.asarray(want)[0]
            ]
        # both replicas took part across the module's traffic or the
        # routing counter at least saw every request
        assert sum(gw.routed.values()) >= len(prompts)

    def test_stream_via_gateway_matches_plain(self, engine_fleet):
        sup, gw, model, params, sampling = engine_fleet
        port = gw.start_http(0)
        base = f"http://127.0.0.1:{port}"
        try:
            plain = gw.complete({"prompt": [5, 9, 2]})
            req = urllib.request.Request(
                base + "/v1/completions",
                data=json.dumps(
                    {"prompt": [5, 9, 2], "stream": True}
                ).encode(),
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            with urllib.request.urlopen(req, timeout=120) as r:
                assert r.headers.get("X-Fleet-Replica") is not None
                lines = [json.loads(x) for x in r if x.strip()]
            assert lines[-1]["done"] is True
            assert lines[-1]["tokens"] == plain["tokens"]
            streamed = [
                t for ln in lines[:-1] for t in ln.get("tokens", [])
            ]
            assert streamed == lines[-1]["tokens"][: len(streamed)]
        finally:
            gw.stop_http()

    def test_prefix_via_gateway_exact(self, engine_fleet):
        import jax
        import numpy as np

        from dlrover_tpu.models.generation import (
            generate,
            left_pad_prompts,
        )

        sup, gw, model, params, sampling = engine_fleet
        prefix, suffix = [11, 23, 5], [7, 1]
        pid = gw.register_prefix(prefix)
        got = gw.complete({"prompt": suffix, "prefix_id": pid})
        toks, mask = left_pad_prompts([prefix + suffix])
        want_t, want_m, _ = generate(
            model, params, toks, mask, jax.random.PRNGKey(0), sampling
        )
        want = [
            int(x)
            for x, keep in zip(
                np.asarray(want_t)[0], np.asarray(want_m)[0]
            )
            if keep
        ]
        assert got["tokens"] == want


# ---------------------------------------------------------------------------
# Engine latency stats (the routing/autoscaler signal — satellite)
# ---------------------------------------------------------------------------


class TestEngineLatencyStats:
    def test_latency_percentiles_and_rate_in_stats(self):
        import jax
        import jax.numpy as jnp

        from dlrover_tpu.models.generation import SamplingConfig
        from dlrover_tpu.models.serving import ContinuousBatchingEngine

        model = _small_model()
        params = model.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
        )["params"]
        eng = ContinuousBatchingEngine(
            model, params,
            SamplingConfig(max_new_tokens=6, temperature=0.0),
            batch_size=2, prompt_width=16, decode_chunk=4,
        )
        before = eng.stats()
        assert before["latency_p50_s"] is None
        assert before["tokens_per_s"] is None
        assert before["completed_total"] == 0
        out = eng.run([[5, 9, 2], [3], [7, 7]])
        stats = eng.stats()
        assert stats["completed_total"] == 3
        assert 0 < stats["latency_p50_s"] <= stats["latency_p95_s"]
        assert stats["tokens_per_s"] > 0
        # the latency window matches the actual completions (stats
        # rounds to 4 decimals — compare at that grain)
        totals = sorted(c.total_s for c in out)
        assert stats["latency_p95_s"] <= totals[-1] + 1e-3


# ---------------------------------------------------------------------------
# Config: env knobs round-trip
# ---------------------------------------------------------------------------


class TestFleetConfig:
    def test_from_env_reads_fleet_knobs(self, monkeypatch):
        monkeypatch.setenv("DLROVER_FLEET_REPLICAS", "3")
        monkeypatch.setenv("DLROVER_FLEET_MAX_REPLICAS", "5")
        monkeypatch.setenv("DLROVER_FLEET_QUEUE_LIMIT", "7")
        monkeypatch.setenv("DLROVER_FLEET_P95_TARGET_S", "1.5")
        cfg = FleetConfig.from_env()
        assert cfg.replicas == 3
        assert cfg.max_replicas == 5
        assert cfg.queue_limit == 7
        assert cfg.p95_target_s == 1.5

    def test_overrides_beat_env(self, monkeypatch):
        monkeypatch.setenv("DLROVER_FLEET_REPLICAS", "3")
        monkeypatch.setenv("DLROVER_FLEET_MAX_REPLICAS", "4")
        cfg = FleetConfig.from_env(replicas=2)
        assert cfg.replicas == 2 and cfg.max_replicas == 4

    def test_bounds_validated(self):
        with pytest.raises(ValueError, match="min_replicas"):
            FleetConfig(replicas=2, min_replicas=3, max_replicas=4)
        with pytest.raises(ValueError, match="replicas"):
            FleetConfig(replicas=0)

    def test_every_fleet_knob_is_registered(self):
        from dlrover_tpu.common.constants import ENV_KNOBS
        from dlrover_tpu.fleet.config import _FLEET_KNOBS

        for field, knob in _FLEET_KNOBS.items():
            assert knob in ENV_KNOBS, knob
            # disaggregation knobs share the serve-side DISAGG family
            assert knob.startswith(
                ("DLROVER_FLEET_", "DLROVER_DISAGG_")
            ), knob
