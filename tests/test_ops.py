"""Pallas flash attention + ring attention kernels.

Kernel logic runs in Pallas interpret mode on the CPU backend (identical
code path to TPU modulo codegen); ring attention runs under shard_map on
the virtual 8-device mesh (SURVEY §4 trick #2).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dlrover_tpu.ops.flash_attention import (
    flash_attention,
    reference_attention,
)
from dlrover_tpu.ops.ring_attention import ring_attention

try:
    from jax import shard_map as _shard_map_mod  # jax >= 0.7 style

    shard_map = _shard_map_mod
except ImportError:
    from jax.experimental.shard_map import shard_map


def _qkv(b=2, t=32, h=2, d=16, dtype=jnp.float32, seed=0):
    keys = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (b, t, h, d)
    return tuple(jax.random.normal(k, shape, dtype) for k in keys)


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_reference(self, causal):
        q, k, v = _qkv()
        out = flash_attention(q, k, v, causal, None, 16, 16)
        ref = reference_attention(q, k, v, causal)
        np.testing.assert_allclose(out, ref, atol=2e-5)

    def test_non_divisible_seq_padding(self):
        q, k, v = _qkv(t=40)
        out = flash_attention(q, k, v, True, None, 16, 16)
        ref = reference_attention(q, k, v, True)
        np.testing.assert_allclose(out, ref, atol=2e-5)

    def test_gradients_match_reference(self):
        q, k, v = _qkv(t=32)

        def loss_fa(q, k, v):
            return (flash_attention(q, k, v, True, None, 16, 16) ** 2).sum()

        def loss_ref(q, k, v):
            return (reference_attention(q, k, v, True) ** 2).sum()

        g_fa = jax.grad(loss_fa, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_fa, g_ref):
            np.testing.assert_allclose(a, b, atol=5e-5)

    def test_bf16_inputs(self):
        q, k, v = _qkv(dtype=jnp.bfloat16)
        out = flash_attention(q, k, v, True, None, 16, 16)
        ref = reference_attention(q, k, v, True)
        np.testing.assert_allclose(
            out.astype(jnp.float32), ref.astype(jnp.float32), atol=3e-2
        )


class TestRingAttention:
    def _mesh(self, sp):
        devices = np.array(jax.devices()[:sp]).reshape(sp)
        return Mesh(devices, ("sp",))

    @pytest.mark.parametrize("causal", [True, False])
    @pytest.mark.parametrize("sp", [2, 4, 8])
    def test_matches_full_attention(self, causal, sp):
        t_global = 8 * sp
        q, k, v = _qkv(b=2, t=t_global, h=2, d=8)
        mesh = self._mesh(sp)
        spec = P(None, "sp", None, None)
        fn = shard_map(
            functools.partial(ring_attention, causal=causal),
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
        )
        out = fn(q, k, v)
        ref = reference_attention(q, k, v, causal)
        np.testing.assert_allclose(out, ref, atol=2e-5)

    def test_gradients_flow_through_ring(self):
        sp = 4
        t_global = 8 * sp
        q, k, v = _qkv(b=1, t=t_global, h=2, d=8)
        mesh = self._mesh(sp)
        spec = P(None, "sp", None, None)
        fn = shard_map(
            functools.partial(ring_attention, causal=True),
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
        )

        def loss_ring(q, k, v):
            return (fn(q, k, v) ** 2).sum()

        def loss_ref(q, k, v):
            return (reference_attention(q, k, v, True) ** 2).sum()

        g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_ring, g_ref):
            np.testing.assert_allclose(a, b, atol=5e-5)

    def test_long_context_memory_shape(self):
        """The per-device intermediate stays O(T/sp): run a sequence that
        would be a (T, T) = (256, 256) logits matrix per head densely,
        sharded 8 ways."""
        sp = 8
        q, k, v = _qkv(b=1, t=256, h=1, d=8)
        mesh = self._mesh(sp)
        spec = P(None, "sp", None, None)
        fn = jax.jit(
            shard_map(
                functools.partial(ring_attention, causal=True),
                mesh=mesh,
                in_specs=(spec, spec, spec),
                out_specs=spec,
            )
        )
        out = fn(q, k, v)
        assert out.shape == q.shape
        ref = reference_attention(q, k, v, True)
        np.testing.assert_allclose(out, ref, atol=2e-5)


class TestRingAttentionInModel:
    def test_sp_train_step_matches_dense(self):
        """A full sharded train step with ring attention (sp=4) produces
        the same loss as the dense-attention step on identical weights."""
        from dlrover_tpu.models.gpt import GPT, GPTConfig, cross_entropy_loss
        from dlrover_tpu.parallel.mesh import MeshConfig, build_mesh
        from dlrover_tpu.parallel.train_step import (
            build_train_step,
            default_optimizer,
            init_train_state,
        )

        def make(attn_impl, mesh_cfg):
            cfg = GPTConfig(
                vocab_size=128,
                max_seq_len=32,
                num_layers=2,
                num_heads=2,
                head_dim=8,
                embed_dim=16,
                use_remat=False,
                attention_impl=attn_impl,
            )
            model = GPT(cfg)
            mesh = build_mesh(mesh_cfg, jax.devices()[:8])
            tx = default_optimizer(learning_rate=1e-3)
            state, shardings = init_train_state(
                model, jnp.zeros((4, 32), jnp.int32), mesh, tx
            )
            step = build_train_step(
                model,
                tx,
                cross_entropy_loss,
                mesh,
                shardings,
                example_data=(
                    jnp.zeros((4, 32), jnp.int32),
                    jnp.zeros((4, 32), jnp.int32),
                ),
                donate=False,
            )
            return step, state

        tokens = jax.random.randint(
            jax.random.PRNGKey(3), (4, 32), 0, 128, jnp.int32
        )
        targets = jnp.roll(tokens, -1, axis=1)

        step_ring, state_ring = make("ring", MeshConfig(dp=2, sp=4))
        step_dense, state_dense = make("dense", MeshConfig(dp=2, sp=4))
        _, loss_ring = step_ring(state_ring, tokens, targets)
        _, loss_dense = step_dense(state_dense, tokens, targets)
        np.testing.assert_allclose(
            np.asarray(loss_ring), np.asarray(loss_dense), rtol=2e-3
        )


class TestCrossLengthCausal:
    def test_kv_cache_decode_shape(self):
        """t_kv > t_q (decode with cache): the causal mask is end-aligned,
        matching the reference oracle."""
        q, _, _ = _qkv(b=1, t=8, h=2, d=16, seed=5)
        _, k, v = _qkv(b=1, t=24, h=2, d=16, seed=6)
        out = flash_attention(q, k, v, True, None, 8, 8)
        ref = reference_attention(q, k, v, True)
        np.testing.assert_allclose(out, ref, atol=2e-5)
