"""Mesh, sharding, and train-step tests on the virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.models.gpt import GPT, GPTConfig, cross_entropy_loss
from dlrover_tpu.models.mnist import MlpConfig, MnistMlp, classification_loss
from dlrover_tpu.parallel.mesh import (
    MeshConfig,
    build_mesh,
    choose_mesh_shape,
    local_batch_slice,
)
from dlrover_tpu.parallel.train_step import (
    build_eval_step,
    build_train_step,
    default_optimizer,
    init_train_state,
)


class TestMeshConfig:
    def test_resolve_free_axis(self):
        cfg = MeshConfig(dp=-1, fsdp=1, tp=2)
        assert cfg.resolve(8).as_dict() == {
            "dp": 4, "fsdp": 1, "ep": 1, "tp": 2, "sp": 1, "pp": 1,
        }

    def test_resolve_exact(self):
        cfg = MeshConfig(dp=2, fsdp=2, tp=2)
        assert cfg.resolve(8).sizes == (2, 2, 1, 2, 1, 1)

    def test_resolve_mismatch_raises(self):
        with pytest.raises(ValueError):
            MeshConfig(dp=3, fsdp=1, tp=1).resolve(8)
        with pytest.raises(ValueError):
            MeshConfig(dp=-1, tp=3).resolve(8)

    def test_choose_mesh_shape_elastic(self):
        # Elastic world change: 8 → 6 devices with tp=2 keeps tp, shrinks data
        cfg8 = choose_mesh_shape(8, tp=2)
        cfg6 = choose_mesh_shape(6, tp=2)
        assert cfg8.fsdp == 4 and cfg6.fsdp == 3
        with pytest.raises(ValueError):
            choose_mesh_shape(7, tp=2)

    def test_local_batch_slice(self):
        mesh = build_mesh(MeshConfig(dp=2, fsdp=2, tp=2))
        assert local_batch_slice(32, mesh) == 8
        with pytest.raises(ValueError):
            local_batch_slice(30, mesh)

    def test_build_mesh_axis_order(self):
        mesh = build_mesh(MeshConfig(dp=2, fsdp=1, tp=4))
        assert mesh.shape["dp"] == 2
        assert mesh.shape["tp"] == 4


@pytest.fixture(scope="module")
def tiny_gpt_setup():
    cfg = GPTConfig.tiny()
    model = GPT(cfg)
    mesh = build_mesh(MeshConfig(dp=2, fsdp=2, tp=2))
    tx = default_optimizer()
    tokens = jnp.zeros((8, 32), jnp.int32)
    state, shardings = init_train_state(model, tokens, mesh, tx)
    return cfg, model, mesh, tx, state, shardings


class TestGptTrainStep:
    def test_params_are_sharded(self, tiny_gpt_setup):
        _, _, mesh, _, state, _ = tiny_gpt_setup
        wqkv = state.params["block_0"]["CausalSelfAttention_0"]["wqkv"]
        assert "tp" in tuple(wqkv.sharding.spec)
        assert "fsdp" in tuple(wqkv.sharding.spec)
        w1 = state.params["block_0"]["Mlp_0"]["w1"]
        assert tuple(w1.sharding.spec) == ("fsdp", "tp")

    def test_loss_decreases(self, tiny_gpt_setup):
        cfg, model, mesh, tx, state, shardings = tiny_gpt_setup
        # donate=False: the module-scoped fixture state is reused by other
        # tests; donation would delete its buffers.
        step = build_train_step(
            model, tx, cross_entropy_loss, mesh, shardings, donate=False
        )
        r = np.random.default_rng(0)
        x = jnp.asarray(r.integers(0, cfg.vocab_size, (8, 32)), jnp.int32)
        y = jnp.roll(x, -1, axis=1)
        state0_loss = None
        for i in range(8):
            state, loss = step(state, x, y)
            state0_loss = state0_loss if state0_loss is not None else float(loss)
        assert float(loss) < state0_loss
        assert int(state.step) == 8

    def test_sharded_matches_single_device(self):
        """The same model/optimizer on a 1-device mesh and an 8-device mesh
        must produce (numerically close) identical losses — sharding is an
        implementation detail, not a semantics change."""
        cfg = GPTConfig.tiny()
        model = GPT(cfg)
        tx = default_optimizer()
        r = np.random.default_rng(1)
        x = jnp.asarray(r.integers(0, cfg.vocab_size, (8, 32)), jnp.int32)
        y = jnp.roll(x, -1, axis=1)
        losses = {}
        for name, mcfg, devs in [
            ("single", MeshConfig(dp=1), jax.devices()[:1]),
            ("sharded", MeshConfig(dp=2, fsdp=2, tp=2), jax.devices()),
        ]:
            mesh = build_mesh(mcfg, devs)
            tokens = jnp.zeros((8, 32), jnp.int32)
            state, shardings = init_train_state(
                model, tokens, mesh, tx, rng=jax.random.PRNGKey(7)
            )
            step = build_train_step(model, tx, cross_entropy_loss, mesh, shardings)
            run = []
            for _ in range(3):
                state, loss = step(state, x, y)
                run.append(float(loss))
            losses[name] = run
        np.testing.assert_allclose(losses["single"], losses["sharded"], rtol=2e-2)

    def test_eval_step(self, tiny_gpt_setup):
        cfg, model, mesh, tx, state, shardings = tiny_gpt_setup
        eval_step = build_eval_step(model, cross_entropy_loss, mesh, shardings)
        r = np.random.default_rng(2)
        x = jnp.asarray(r.integers(0, cfg.vocab_size, (8, 32)), jnp.int32)
        loss = eval_step(state.params, x, jnp.roll(x, -1, axis=1))
        assert np.isfinite(float(loss))


class TestMnist:
    def test_train_decreases_loss(self):
        model = MnistMlp(MlpConfig(input_dim=64, hidden_dim=32))
        mesh = build_mesh(MeshConfig(dp=4, fsdp=2))
        tx = default_optimizer(learning_rate=1e-2)
        x_example = jnp.zeros((8, 64))
        state, shardings = init_train_state(model, x_example, mesh, tx)
        step = build_train_step(
            model, tx, classification_loss, mesh, shardings, example_data=(x_example, jnp.zeros((8,), jnp.int32))
        )
        r = np.random.default_rng(0)
        x = jnp.asarray(r.normal(size=(8, 64)), jnp.float32)
        y = jnp.asarray(r.integers(0, 10, (8,)), jnp.int32)
        first = None
        for _ in range(20):
            state, loss = step(state, x, y)
            first = first if first is not None else float(loss)
        assert float(loss) < first * 0.8


class TestOptDpShard:
    """Cross-replica weight-update sharding (arXiv:2004.13336, the
    RESHARD_RULES ``mirror_dp`` policy): ``state_shardings(
    shard_opt_over_dp=True)`` shards optimizer moments dim 0 over
    ``dp``; GSPMD inserts the gather at ``tx.update`` from the
    annotations alone, so the update math is unchanged."""

    def test_moments_shard_over_dp_and_update_matches(self):
        model = MnistMlp(MlpConfig(input_dim=64, hidden_dim=32))
        mesh = build_mesh(MeshConfig(dp=4), devices=jax.devices()[:4])
        tx = default_optimizer(learning_rate=1e-2)
        x_example = jnp.zeros((8, 64))
        r = np.random.default_rng(0)
        x = jnp.asarray(r.normal(size=(8, 64)), jnp.float32)
        y = jnp.asarray(r.integers(0, 10, (8,)), jnp.int32)
        runs = {}
        for flag in (False, True):
            state, shardings = init_train_state(
                model, x_example, mesh, tx, shard_opt_over_dp=flag
            )
            step = build_train_step(
                model,
                tx,
                classification_loss,
                mesh,
                shardings,
                example_data=(x_example, jnp.zeros((8,), jnp.int32)),
                donate=False,
            )
            losses = []
            for _ in range(3):
                state, loss = step(state, x, y)
                losses.append(float(loss))
            runs[flag] = (losses, state)
        # Annotations move placement, not math.
        np.testing.assert_allclose(
            runs[True][0], runs[False][0], rtol=1e-4, atol=1e-5
        )
        # dp-divisible moment leaves actually shard: 1/4 per device.
        hits = 0
        for leaf in jax.tree.leaves(runs[True][1].opt_state):
            shape = getattr(leaf, "shape", ())
            if not shape or shape[0] % 4 or not hasattr(leaf, "sharding"):
                continue
            head = (tuple(leaf.sharding.spec) or (None,))[0]
            axes = head if isinstance(head, tuple) else (head,)
            if "dp" in axes:
                hits += 1
                assert (
                    leaf.addressable_shards[0].data.shape[0]
                    == shape[0] // 4
                )
        assert hits > 0, "no moment leaf picked up the dp factor"
        # The un-sharded run's moments never reference dp.
        for leaf in jax.tree.leaves(runs[False][1].opt_state):
            if hasattr(leaf, "sharding"):
                spec = tuple(getattr(leaf.sharding, "spec", ()) or ())
                flat = [
                    a
                    for e in spec
                    for a in (e if isinstance(e, tuple) else (e,))
                ]
                assert "dp" not in flat
