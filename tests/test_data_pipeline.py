"""Dynamic sharding client + elastic dataloader/sampler.

Reference test model: test_sharding_client.py + sampler tests — real
client↔master RPC against an in-process LocalJobMaster (SURVEY §4).
"""

import numpy as np
import pytest

from dlrover_tpu.agent.sharding import IndexShardingClient, ShardingClient
from dlrover_tpu.common import comm
from dlrover_tpu.master.local_master import LocalJobMaster
from dlrover_tpu.rpc.client import MasterClient
from dlrover_tpu.trainer.config_tuner import ParalConfigTuner
from dlrover_tpu.trainer.dataloader import (
    ElasticDistributedSampler,
    ElasticShardLoader,
)


@pytest.fixture()
def master():
    MasterClient.reset_singleton()
    m = LocalJobMaster(num_workers=2, fresh_context=True)
    m.prepare()
    yield m
    m.stop()
    MasterClient.reset_singleton()


def _client(master, node_id=0):
    return MasterClient(master_addr=master.addr, node_id=node_id)


class TestShardingClient:
    def test_pull_and_complete_all_shards(self, master):
        c = ShardingClient(
            "ds", client=_client(master), batch_size=4, dataset_size=32
        )
        seen = []
        while True:
            task = c.fetch_task()
            if task is None:
                break
            seen.extend(range(task.shard.start, task.shard.end))
            c.report_task_done(task)
        assert sorted(seen) == list(range(32))
        assert master.task_manager.finished()

    def test_dead_worker_shards_requeued(self, master):
        c0 = ShardingClient("ds", client=_client(master, 0), batch_size=4, dataset_size=16)
        c1 = ShardingClient("ds", client=_client(master, 1), batch_size=4, dataset_size=16)
        t0 = c0.fetch_task()
        assert t0 is not None
        # worker 0 dies without reporting; master recovers its tasks
        master.task_manager.recover_tasks(0)
        seen = []
        while True:
            task = c1.fetch_task()
            if task is None:
                break
            seen.extend(range(task.shard.start, task.shard.end))
            c1.report_task_done(task)
        assert sorted(seen) == list(range(16))  # includes re-queued shard

    def test_index_client_streams_all_samples(self, master):
        c = IndexShardingClient(
            "ds", client=_client(master), batch_size=2, dataset_size=10
        )
        indices = []
        while True:
            i = c.fetch_sample_index()
            if i is None:
                break
            indices.append(i)
        assert sorted(indices) == list(range(10))
        assert master.task_manager.finished()


class TestElasticShardLoader:
    def test_batches_and_completion(self, master):
        c = ShardingClient(
            "ds", client=_client(master), batch_size=4, dataset_size=24
        )
        loader = ElasticShardLoader(
            c, fetch_fn=lambda idx: np.array(idx), batch_size=4
        )
        batches = list(loader)
        assert all(b.shape == (4,) for b in batches)
        assert sorted(np.concatenate(batches).tolist()) == list(range(24))
        assert master.task_manager.finished()

    def test_shard_reported_only_after_consumed(self, master):
        c = ShardingClient(
            "ds", client=_client(master), batch_size=2, dataset_size=8,
            num_minibatches_per_shard=4,  # one shard = 8 samples
        )
        loader = ElasticShardLoader(
            c, fetch_fn=lambda idx: idx, batch_size=2
        )
        it = iter(loader)
        next(it)
        ds = master.task_manager.get_dataset("ds")
        assert not ds.completed()  # shard open until last sample yielded
        for _ in range(3):
            next(it)
        assert ds.completed()


class TestElasticDistributedSampler:
    def test_partition_and_coverage(self):
        s0 = ElasticDistributedSampler(10, num_replicas=2, rank=0, shuffle=False)
        s1 = ElasticDistributedSampler(10, num_replicas=2, rank=1, shuffle=False)
        a, b = list(s0), list(s1)
        assert sorted(a + b) == list(range(10))
        assert len(a) == len(b) == 5

    def test_resume_after_remesh(self):
        """Consume 6 samples with 2 replicas, resume with 3 replicas: the
        remaining samples are exactly the unconsumed ones."""
        s0 = ElasticDistributedSampler(12, num_replicas=2, rank=0, shuffle=False)
        it = iter(s0)
        first = [next(it) for _ in range(3)]  # rank0 consumed 3 → global 6
        state = s0.state_dict()
        assert state["completed_num"] == 6
        resumed = [
            ElasticDistributedSampler(12, num_replicas=3, rank=r, shuffle=False)
            for r in range(3)
        ]
        rest = []
        for r in resumed:
            r.load_state_dict(state)
            rest.extend(list(r))
        assert sorted(rest) == list(range(6, 12))

    def test_shuffle_deterministic_per_epoch(self):
        s = ElasticDistributedSampler(16, num_replicas=1, rank=0, shuffle=True, seed=7)
        s.set_epoch(1)
        a = list(s)
        s.set_epoch(1)
        b = list(s)
        assert a == b
        s.set_epoch(2)
        assert list(s) != a


class TestParalConfigTuner:
    def test_pushes_batch_size_to_loader(self, master):
        client = _client(master)
        shard_client = ShardingClient(
            "ds", client=client, batch_size=4, dataset_size=16
        )
        loader = ElasticShardLoader(
            shard_client, fetch_fn=lambda i: i, batch_size=4
        )
        tuner = ParalConfigTuner(client=client, poll_interval_s=0.05)
        tuner.attach_dataloader(loader)
        master.servicer._job_ctx.paral_config = comm.ParallelConfig(
            dataloader_batch_size=8, version=1
        )
        assert tuner.poll_once() is not None
        assert loader.batch_size == 8
        # same version: no-op
        assert tuner.poll_once() is None
