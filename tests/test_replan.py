"""Elastic hybrid-parallelism replanner (``parallel/replan.py``): the
DP×TP×PP rung ladder, the analytic cost model behind the DP↔PP trade,
and the planner the loop + compile-ahead service drive on world change
(docs/elastic_parallelism.md)."""

import dataclasses

import pytest

from dlrover_tpu.chaos import faults
from dlrover_tpu.parallel.replan import (
    CostModel,
    ElasticReplanner,
    Rung,
    default_replanner,
    enumerate_rungs,
)

MiB = 1 << 20


def _capped_planner(**overrides):
    """Planner in the regime the ladder exists for: full world dp8, the
    HBM cap sized so the accum-only shrink rung is memory-bound while
    the dp→pp trade (params + dp-sharded moments split over pp) fits."""
    kwargs = dict(
        param_bytes=1 * MiB,
        opt_bytes=2 * MiB,
        hbm_bytes_per_device=1_200_000,
        step_time_s=1.0,
        reference=Rung(dp=8),
        opt_dp_shard=True,
    )
    kwargs.update(overrides)
    return ElasticReplanner(
        CostModel(**kwargs), full_dp=8, current=Rung(dp=8), max_pp=2
    )


class TestRungLadder:
    def test_accum_only_ladder_is_the_tp1_pp1_column(self):
        # Default caps (max_tp=max_pp=1) reproduce the 1D ladder: one
        # rung per world, accum by the same round-up rule as
        # gradient_accumulation_steps.
        assert enumerate_rungs(4, full_dp=8) == [Rung(dp=4, accum=2)]
        assert enumerate_rungs(3, full_dp=8) == [Rung(dp=3, accum=3)]
        assert enumerate_rungs(8, full_dp=8) == [Rung(dp=8, accum=1)]

    def test_2d_enumeration_covers_the_factorings(self):
        rungs = enumerate_rungs(4, full_dp=8, max_tp=2, max_pp=2)
        assert Rung(dp=4, accum=2) in rungs
        assert Rung(dp=2, tp=2, accum=4) in rungs
        assert Rung(dp=2, pp=2, accum=4) in rungs
        assert Rung(dp=1, tp=2, pp=2, accum=8) in rungs
        assert all(r.devices == 4 for r in rungs)

    def test_pp_must_divide_the_layer_count(self):
        rungs = enumerate_rungs(8, full_dp=8, max_pp=8, num_layers=6)
        assert {r.pp for r in rungs} == {1, 2}  # 4 and 8 do not divide 6

    def test_labels_are_mesh_axes_only(self):
        # accum stays out: tpurun-trace attributes reshard_s by these
        assert Rung(dp=4, accum=2).label() == "dp4"
        assert Rung(dp=2, pp=2, accum=4).label() == "dp2·pp2"
        assert Rung(dp=1, tp=2, pp=2).label() == "dp1·tp2·pp2"

    def test_mesh_config_and_program_key(self):
        r = Rung(dp=2, pp=2, accum=4)
        mc = r.mesh_config()
        assert (mc.dp, mc.tp, mc.pp) == (2, 1, 2)
        assert r.program_key() == (2, 1, 2, 4)


class TestCostModel:
    def test_opt_dp_shard_moves_the_memory_floor(self):
        base = CostModel(param_bytes=1 * MiB, opt_bytes=2 * MiB)
        rung = Rung(dp=4, accum=2)
        unsharded = base.mem_bytes_per_device(rung)
        sharded = dataclasses.replace(
            base, opt_dp_shard=True
        ).mem_bytes_per_device(rung)
        assert unsharded == 3 * MiB
        assert sharded == 1 * MiB + (2 * MiB) // 4  # moments /dp

    def test_pipeline_pays_the_gpipe_bubble(self):
        cm = CostModel(
            param_bytes=MiB, opt_bytes=MiB, microbatches=8,
            reference=Rung(dp=8),
        )
        # same device count: pp2 pays (M + pp - 1)/M over dp's accum
        flat = cm.est_step_s(Rung(dp=4, accum=2))
        piped = cm.est_step_s(Rung(dp=2, pp=2, accum=4))
        assert piped == pytest.approx(flat * (4 / 2) * (9 / 8) / 1)

    def test_infeasible_rung_pays_spill_not_exclusion(self):
        cm = CostModel(
            param_bytes=4 * MiB,
            opt_bytes=0,
            hbm_bytes_per_device=1,
            spill_penalty_x=4.0,
            reference=Rung(dp=8),
        )
        rung = Rung(dp=8)
        assert not cm.feasible(rung)
        free = dataclasses.replace(cm, hbm_bytes_per_device=0)
        assert cm.est_step_s(rung) == pytest.approx(
            4.0 * free.est_step_s(rung)
        )


class TestPlanner:
    def test_shrink_trades_dp_for_pp_under_the_memory_cap(self):
        plan = _capped_planner().plan(4)
        assert plan.rung == Rung(dp=2, pp=2, accum=4)
        assert plan.is_trade
        assert plan.accum_rung == Rung(dp=4, accum=2)
        assert plan.hybrid_vs_accum_goodput_x > 1.0

    def test_unconstrained_shrink_keeps_the_accum_rung(self):
        plan = _capped_planner(hbm_bytes_per_device=0).plan(4)
        assert plan.rung == Rung(dp=4, accum=2)
        assert not plan.is_trade
        assert plan.hybrid_vs_accum_goodput_x == pytest.approx(1.0)

    def test_plan_fires_the_injection_point_then_retries_clean(self):
        planner = _capped_planner()
        faults.activate(
            faults.FaultPlan.parse(
                "seed=7;remesh.replan:error:replan-blip@at=1"
            )
        )
        try:
            with pytest.raises(faults.FaultInjectedError):
                planner.plan(4)
            plan = planner.plan(4)  # the loop's catch-and-retry
            assert plan.rung == Rung(dp=2, pp=2, accum=4)
            assert [
                r["point"] for r in faults.records()
            ] == ["remesh.replan"]
        finally:
            faults.deactivate()

    def test_zero_devices_raises(self):
        with pytest.raises(ValueError):
            _capped_planner().plan(0)

    def test_observe_step_time_reanchors_at_the_current_rung(self):
        planner = _capped_planner()
        planner.adopt(Rung(dp=2, pp=2, accum=4))
        planner.observe_step_time(9.0)  # first sample on a NEW rung
        assert planner.cost_model.reference == Rung(dp=2, pp=2, accum=4)
        assert planner.cost_model.step_time_s == pytest.approx(9.0)
        planner.observe_step_time(11.0)  # same rung: EMA, not replace
        assert 9.0 < planner.cost_model.step_time_s < 11.0
        planner.observe_step_time(-1.0)  # garbage sample ignored
        assert 9.0 < planner.cost_model.step_time_s < 11.0

    def test_anticipate_plans_each_world_and_dedupes_programs(self):
        planner = _capped_planner()
        rungs = planner.anticipate(8, max_devices=8, unit_devices=4)
        # one likely world (8 - 4 = 4); its PLAN is the pp trade, and
        # the shrink-ladder revisit of the same world dedupes away
        assert rungs == [Rung(dp=2, pp=2, accum=4)]
        keys = [r.program_key() for r in rungs]
        assert len(keys) == len(set(keys))
        assert planner.current.program_key() not in keys

    def test_anticipate_unit_ladder(self):
        planner = ElasticReplanner(
            CostModel(param_bytes=MiB, opt_bytes=MiB, reference=Rung(dp=8)),
            full_dp=8,
            current=Rung(dp=8),
        )
        rungs = planner.anticipate(8, max_devices=16, unit_devices=2)
        # nearest worlds first (grow 10 before shrink 6 on the tie),
        # then the shrink ladder (4, 2)
        assert rungs[0] == Rung(dp=10, accum=1)
        assert rungs[1] == Rung(dp=6, accum=2)
        assert Rung(dp=4, accum=2) in rungs
        assert Rung(dp=2, accum=4) in rungs


class TestDefaultReplanner:
    def test_gated_off_by_default(self):
        cm = CostModel(param_bytes=MiB, opt_bytes=MiB)
        assert default_replanner(cm, full_dp=8, current=Rung(dp=8)) is None

    def test_context_knobs_configure_the_planner(self, monkeypatch):
        from dlrover_tpu.common.config import get_context

        ctx = get_context()
        monkeypatch.setattr(ctx, "elastic_replan", True)
        monkeypatch.setattr(ctx, "elastic_max_pp", 2)
        monkeypatch.setattr(ctx, "elastic_hbm_gb", 1_200_000 / (1 << 30))
        cm = CostModel(
            param_bytes=MiB, opt_bytes=2 * MiB,
            reference=Rung(dp=8), opt_dp_shard=True,
        )
        planner = default_replanner(cm, full_dp=8, current=Rung(dp=8))
        assert planner is not None
        assert planner.max_pp == 2
        assert planner.cost_model.hbm_bytes_per_device == 1_200_000
        assert planner.plan(4).rung == Rung(dp=2, pp=2, accum=4)
