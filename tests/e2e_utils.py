"""Shared harness for process-backed chaos/e2e tests — now product code
(dlrover_tpu.chaos.harness) so the benchmark drives the same wiring;
re-exported here for the existing tests."""

from dlrover_tpu.chaos.harness import (  # noqa: F401
    cleanup_namespaces,
    make_process_master,
)
