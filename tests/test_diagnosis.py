"""Diagnosis subsystem tests: collectors, inference chain, operators,
diagnosticians (reference dlrover/python/diagnosis family)."""

import os

import pytest

from dlrover_tpu.diagnosis import (
    Inference,
    InferenceAttribution,
    InferenceChain,
    InferenceName,
    FailureNodeDiagnostician,
    ResourceCollector,
    TrainingLogCollector,
)
from dlrover_tpu.diagnosis.operators import (
    CheckFailureNodeOperator,
    CheckTrainingHangOperator,
    ResolveFailureNodeOperator,
    ResolveTrainingHangOperator,
)
from dlrover_tpu.master.diagnosis.action import DiagnosisActionType


class TestCollectors:
    def test_training_log_collector_extracts_errors(self, tmp_path):
        log = tmp_path / "worker.log"
        log.write_text(
            "step 1 loss 3.2\n"
            "step 2 loss 3.1\n"
            "E0730 something RESOURCE_EXHAUSTED: out of memory\n"
            "Traceback (most recent call last):\n"
            "  File train.py line 10\n"
            "ValueError: bad value\n"
        )
        got = TrainingLogCollector(str(log)).collect()
        assert "loss 3.2" in got.tail
        assert any("out of memory" in line for line in got.error_lines)
        assert any("Traceback" in line for line in got.error_lines)
        assert not any("loss" in line for line in got.error_lines)

    def test_training_log_collector_missing_file(self):
        collector = TrainingLogCollector("/nonexistent/x.log")
        assert not collector.is_enabled()
        assert collector.collect().tail == ""

    def test_resource_collector_reads_proc(self):
        usage = ResourceCollector(pid=os.getpid()).collect()
        assert usage.host_memory_total_mb > 0
        assert usage.memory_mb > 0


class TestFailureChain:
    def _decide(self, log, restart_count=0, max_restarts=3):
        return FailureNodeDiagnostician(max_restarts=max_restarts).decide(
            log_tail=log, restart_count=restart_count
        )

    def test_node_fatal_relaunches(self):
        assert (
            self._decide("E: failed to initialize TPU system")
            == DiagnosisActionType.RELAUNCH_WORKER
        )
        assert (
            self._decide("uncorrectable ECC error encountered")
            == DiagnosisActionType.RELAUNCH_WORKER
        )

    def test_retryable_restarts(self):
        assert (
            self._decide("grpc: connection refused while dialing master")
            == DiagnosisActionType.RESTART_WORKER
        )

    def test_oom_restarts_with_budget(self):
        assert (
            self._decide("RESOURCE_EXHAUSTED: out of memory on device")
            == DiagnosisActionType.RESTART_WORKER
        )

    def test_budget_exhausted_relaunches(self):
        assert (
            self._decide("connection refused", restart_count=3)
            == DiagnosisActionType.RELAUNCH_WORKER
        )
        # node-fatal wins regardless of budget
        assert (
            self._decide("pjrt internal error", restart_count=0)
            == DiagnosisActionType.RELAUNCH_WORKER
        )

    def test_unknown_restarts(self):
        assert self._decide("") == DiagnosisActionType.RESTART_WORKER

    def test_attribution_surfaces(self):
        diag = FailureNodeDiagnostician()
        facts = diag.observe(log_tail="out of memory on chip 0")
        resolved = InferenceChain(
            [CheckFailureNodeOperator(), ResolveFailureNodeOperator()]
        ).infer(facts)
        attributed = [
            f for f in resolved if f.name == InferenceName.WORKER_FAILURE
        ]
        assert attributed[0].attribution == InferenceAttribution.OOM


class TestHangChain:
    def _chain(self, downtime=10.0):
        return InferenceChain(
            [CheckTrainingHangOperator(downtime), ResolveTrainingHangOperator()]
        )

    def test_confirmed_hang_dumps_then_restarts(self):
        actions = self._chain().resolved_actions(
            [
                Inference(
                    name=InferenceName.TRAINING_HANG,
                    data={"stalled_for_s": 60.0, "profiler_hung_nodes": []},
                )
            ]
        )
        assert actions == [
            DiagnosisActionType.STACK_DUMP,
            DiagnosisActionType.RESTART_WORKER,
        ]

    def test_profiler_hang_alone_confirms(self):
        actions = self._chain().resolved_actions(
            [
                Inference(
                    name=InferenceName.TRAINING_HANG,
                    data={"stalled_for_s": 0.0, "profiler_hung_nodes": [2]},
                )
            ]
        )
        assert DiagnosisActionType.STACK_DUMP in actions

    def test_below_threshold_no_actions(self):
        actions = self._chain().resolved_actions(
            [
                Inference(
                    name=InferenceName.TRAINING_HANG,
                    data={"stalled_for_s": 2.0, "profiler_hung_nodes": []},
                )
            ]
        )
        assert actions == []


class TestChainMechanics:
    def test_chain_terminates_without_compatible_operator(self):
        chain = InferenceChain([CheckFailureNodeOperator()])
        facts = [Inference(name="unrelated")]
        assert chain.infer(facts) == facts
