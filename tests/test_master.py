"""Master components + full RPC round-trips against a live LocalJobMaster."""

import time

import pytest

from dlrover_tpu.common import comm
from dlrover_tpu.common.constants import (
    CommsType,
    JobStage,
    NodeStatus,
    RendezvousName,
)
from dlrover_tpu.master.diagnosis.action import (
    DiagnosisActionType,
    NodeAction,
)
from dlrover_tpu.master.job_context import JobContext
from dlrover_tpu.master.local_master import LocalJobMaster
from dlrover_tpu.master.shard.dataset_splitter import (
    TableDatasetSplitter,
    TextDatasetSplitter,
)
from dlrover_tpu.master.shard.task_manager import DatasetManager, TaskManager
from dlrover_tpu.rpc.client import MasterClient


class TestDatasetSplitting:
    def test_table_splitter(self):
        splitter = TableDatasetSplitter("ds", dataset_size=103, shard_size=10)
        shards = splitter.create_shards()
        assert len(shards) == 11
        assert shards[-1].size == 3
        assert sum(s.size for s in shards) == 103

    def test_text_splitter_shuffle(self):
        splitter = TextDatasetSplitter(
            "ds", dataset_size=20, shard_size=5, shuffle=True, seed=42
        )
        shards = splitter.create_shards()
        all_indices = [i for s in shards for i in s.record_indices]
        assert sorted(all_indices) == list(range(20))
        assert all_indices != list(range(20))  # actually shuffled

    def test_task_redelivery_on_node_death(self):
        splitter = TableDatasetSplitter("ds", dataset_size=40, shard_size=10)
        mgr = DatasetManager("ds", splitter)
        t1 = mgr.get_task(node_id=0)
        t2 = mgr.get_task(node_id=1)
        assert t1.task_id != t2.task_id
        mgr.report_task_status(t1.task_id, success=True)
        # node 1 dies with t2 in flight → t2 requeued first
        assert mgr.recover_tasks_of_node(1) == 1
        t3 = mgr.get_task(node_id=0)
        assert t3.shard.start == t2.shard.start

    def test_completion_after_epochs(self):
        splitter = TableDatasetSplitter("ds", dataset_size=10, shard_size=10, num_epochs=2)
        mgr = DatasetManager("ds", splitter)
        for _ in range(2):
            task = mgr.get_task(0)
            mgr.report_task_status(task.task_id, success=True)
        assert mgr.get_task(0).task_id == -1
        assert mgr.completed()

    def test_shard_checkpoint_roundtrip(self):
        splitter = TableDatasetSplitter("ds", dataset_size=30, shard_size=10)
        mgr = DatasetManager("ds", splitter)
        t = mgr.get_task(0)  # in-flight
        content = mgr.checkpoint()
        # Fresh manager restores: the in-flight shard must come back
        splitter2 = TableDatasetSplitter("ds", dataset_size=30, shard_size=10)
        mgr2 = DatasetManager("ds", splitter2)
        mgr2.restore_checkpoint(content)
        restored_first = mgr2.get_task(0)
        assert restored_first.shard.start == t.shard.start
        starts = {restored_first.shard.start}
        while True:
            task = mgr2.get_task(0)
            if task.task_id == -1:
                break
            starts.add(task.shard.start)
        assert starts == {0, 10, 20}


@pytest.fixture(params=[CommsType.GRPC, CommsType.HTTP])
def live_master(request):
    master = LocalJobMaster(
        num_workers=2, service_type=request.param, fresh_context=True
    )
    master.prepare()
    yield master
    master.stop()
    JobContext.reset()


def _client(master, node_id):
    return MasterClient(
        master_addr=master.addr,
        node_id=node_id,
        service_type=(
            CommsType.HTTP if "Http" in type(master._server).__name__ else CommsType.GRPC
        ),
    )


class TestMasterRpcRoundtrip:
    def test_kv_store(self, live_master):
        c = _client(live_master, 0)
        c.kv_store_set("k1", b"v1")
        assert c.kv_store_get("k1") == b"v1"
        assert c.kv_store_get("missing") == b""
        assert c.kv_store_add("cnt", 3) == 3
        assert c.kv_store_add("cnt", 2) == 5
        c.kv_store_multi_set({"a": b"1", "b": b"2"})
        assert c.kv_store_multi_get(["a", "b"]) == {"a": b"1", "b": b"2"}

    def test_two_agents_complete_rendezvous(self, live_master):
        c0, c1 = _client(live_master, 0), _client(live_master, 1)
        c0.join_rendezvous(0, 4, RendezvousName.TRAINING, node_ip="10.0.0.1")
        resp = c0.get_comm_world(RendezvousName.TRAINING)
        assert resp.world == {}
        c1.join_rendezvous(1, 4, RendezvousName.TRAINING, node_ip="10.0.0.2")
        resp = c0.get_comm_world(RendezvousName.TRAINING)
        assert len(resp.world) == 2
        assert resp.world[0].addr == "10.0.0.1"
        assert resp.world[1].addr == "10.0.0.2"

    def test_node_status_and_heartbeat_actions(self, live_master):
        c0 = _client(live_master, 0)
        c0.report_node_status(NodeStatus.RUNNING)
        # Master queues a restart action for this node
        live_master.servicer._job_ctx.node_actions.add_action(
            NodeAction(node_id=0, action_type=DiagnosisActionType.RESTART_WORKER)
        )
        actions = c0.report_heartbeat()
        assert len(actions) == 1
        assert actions[0].config["action_type"] == DiagnosisActionType.RESTART_WORKER
        # Drained: next heartbeat is empty
        assert c0.report_heartbeat() == []

    def test_failed_worker_triggers_relaunch_action(self, live_master):
        c0 = _client(live_master, 0)
        c0.report_node_status(NodeStatus.RUNNING)
        c0.report_node_status(NodeStatus.FAILED, exit_reason="killed")
        actions = c0.report_heartbeat()
        assert any(
            a.config["action_type"] == DiagnosisActionType.RELAUNCH_WORKER
            for a in actions
        )

    def test_task_flow_over_rpc(self, live_master):
        c = _client(live_master, 0)
        c.report_dataset_params(
            comm.DatasetShardParams(
                batch_size=5,
                num_minibatches_per_shard=2,
                dataset_size=30,
                dataset_name="train",
            )
        )
        task = c.get_task("train")
        assert task.task_id >= 0
        assert task.shard.end - task.shard.start == 10
        c.report_task_result("train", task.task_id, success=True)
        ckpt = c.get_shard_checkpoint("train")
        assert "train" in ckpt

    def test_pre_check_and_job_status(self, live_master):
        c = _client(live_master, 0)
        assert c.get_pre_check_result().status == "passed"
        assert c.get_job_status().stage == JobStage.RUNNING

    def test_sync_barrier(self, live_master):
        c0, c1 = _client(live_master, 0), _client(live_master, 1)
        # Barrier of 2 (num_workers): incomplete until both join
        assert not c0.join_sync("mesh_build")
        assert not c0.sync_finished("mesh_build")
        assert c1.join_sync("mesh_build")
        assert c0.sync_finished("mesh_build")

    def test_training_step_report_feeds_perf_monitor(self, live_master):
        c = _client(live_master, 0)
        c.report_training_step(step=10)
        time.sleep(0.05)
        c.report_training_step(step=20)
        step, _ = live_master.perf_monitor.last_step()
        assert step == 20
        assert live_master.perf_monitor.steps_per_second() > 0
        status = c.get_job_status()
        assert status.last_step == 20
        assert status.steps_per_second > 0
        assert 0.0 <= status.goodput <= 1.0

    def test_goodput_accounting(self):
        """Measured, not assumed (reference headline: 69%→95% goodput):
        steady step intervals count productive; a long stall counts one
        median step against productive time."""
        from dlrover_tpu.master.monitor.perf_monitor import PerfMonitor

        mon = PerfMonitor()
        t0 = mon._start_time
        # 10 steady steps of 1s each
        for i in range(11):
            mon.collect_global_step(i, timestamp=t0 + i)
        assert mon._productive_s == pytest.approx(10.0)
        # a 30s stall (re-rendezvous), then training resumes
        mon.collect_global_step(11, timestamp=t0 + 40)
        assert mon._productive_s == pytest.approx(11.0)  # +1 median step
        for i in range(12, 15):
            mon.collect_global_step(i, timestamp=t0 + 40 + (i - 11))
        # productive 14s over 43s elapsed-at-last-report; goodput uses
        # time.time() so just bound it loosely
        g = mon.goodput()
        assert 0.2 < g < 0.5

    def test_goodput_first_interval_stall_capped(self):
        """An hour-long gap before the SECOND report must not count as
        an hour of productive training or poison the median."""
        from dlrover_tpu.master.monitor.perf_monitor import PerfMonitor

        mon = PerfMonitor()
        t0 = mon._start_time
        mon.collect_global_step(1, timestamp=t0)
        mon.collect_global_step(2, timestamp=t0 + 3600)  # crash recovery
        assert mon._productive_s <= 120.0
        # subsequent normal steps restore a sane median quickly
        for i in range(3, 10):
            mon.collect_global_step(i, timestamp=t0 + 3600 + (i - 2))
        import statistics as _st

        assert _st.median(mon._step_dts) < 5.0

    def test_goodput_backward_timestamp_clamped(self):
        """A lagging host clock must not rewind the baseline and
        double-count wall time as productive."""
        from dlrover_tpu.master.monitor.perf_monitor import PerfMonitor

        mon = PerfMonitor()
        t0 = mon._start_time
        for i in range(5):
            mon.collect_global_step(i, timestamp=t0 + i)
        before = mon._productive_s
        mon.collect_global_step(5, timestamp=t0 - 50)  # skewed clock
        mon.collect_global_step(6, timestamp=t0 + 5)
        # the rewound window is not re-credited
        assert mon._productive_s == pytest.approx(before + 1.0)


class TestMasterSupervision:
    def test_job_exits_when_all_workers_succeed(self):
        master = LocalJobMaster(num_workers=1, fresh_context=True)
        master.prepare()
        master.run_in_background()
        try:
            c = _client(master, 0)
            c.report_node_status(NodeStatus.RUNNING)
            c.report_node_status(NodeStatus.SUCCEEDED)
            deadline = time.time() + 10
            while time.time() < deadline and not master.exit_reason:
                time.sleep(0.2)
            assert master.exit_reason == "succeeded"
        finally:
            master.stop()
            JobContext.reset()
