"""tpurun-lint unit tests: every pass fires on its planted fixture,
both suppression forms work (same-line and line-above), bare ignores
are errors, and the baseline round-trips (stale entries reported).

The repo-wide zero-violation gate lives in tests/test_lint_clean.py;
this file exercises the machinery against tests/lint_fixtures/.
"""

import json
import os

import pytest

from dlrover_tpu.analysis import Baseline, run_lint
from dlrover_tpu.analysis.cli import main as lint_main
from dlrover_tpu.analysis.passes import (
    ALL_PASSES,
    PASS_BY_ID,
    blocking_under_lock,
    env_knobs,
    host_sync,
    import_purity,
    injection_coverage,
    rpc_deadline,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_FIXTURES = os.path.join(_REPO, "tests", "lint_fixtures")


def _fx(name):
    return os.path.join(_FIXTURES, name)


def _run(path, lint_pass):
    return run_lint([path], passes=[lint_pass], repo_root=_REPO)


class TestPassesFireOnFixtures:
    def test_import_purity_fires(self):
        r = _run(_fx("fx_import_purity.py"), import_purity)
        assert len(r.violations) == 1, r.violations
        v = r.violations[0]
        assert v.pass_id == "import-purity"
        assert "jax_compilation_cache_dir" in v.code
        # the suppressed twin (line-above form) and only it
        assert len(r.suppressed) == 1
        assert not r.errors

    def test_import_purity_main_guard_and_functions_exempt(self):
        r = _run(_fx("fx_import_purity.py"), import_purity)
        flagged_lines = {v.line for v in r.violations} | {
            v.line for v, _s in r.suppressed
        }
        src = open(_fx("fx_import_purity.py")).readlines()
        for i, text in enumerate(src, start=1):
            if "fine_inside_a_function" in text or "__main__" in text:
                assert i not in flagged_lines

    def test_blocking_under_lock_fires(self):
        r = _run(_fx("fx_blocking_under_lock.py"), blocking_under_lock)
        assert len(r.violations) == 1, r.violations
        assert "sleep" in r.violations[0].message
        # same-line suppression on the untimed join
        assert len(r.suppressed) == 1
        assert "join" in r.suppressed[0][0].message
        assert not r.errors

    def test_host_sync_fires_on_marker_and_jit(self):
        r = _run(_fx("fx_host_sync.py"), host_sync)
        msgs = [v.message for v in r.violations]
        assert any("float()" in m and "dispatch_round" in m for m in msgs)
        assert any(".item()" in m and "jitted_body" in m for m in msgs)
        assert len(r.violations) == 2, r.violations
        # the drain point is suppressed; the cold path is not hot
        assert len(r.suppressed) == 1
        assert "device_get" in r.suppressed[0][0].message

    def test_host_sync_flags_per_call_heavy_import(self, tmp_path):
        p = tmp_path / "fx.py"
        p.write_text(
            "# tpulint: hotpath\n"
            "def step(state):\n"
            "    import jax\n"
            "    return state\n"
        )
        r = _run(str(p), host_sync)
        assert len(r.violations) == 1
        assert "per-call import" in r.violations[0].message

    def test_rpc_deadline_fires(self):
        r = _run(_fx("fx_rpc_deadline.py"), rpc_deadline)
        assert len(r.violations) == 1, r.violations
        assert "hard-coded deadline" in r.violations[0].message
        # urlopen with NO deadline is also a violation — suppressed here
        assert len(r.suppressed) == 1
        assert "no deadline" in r.suppressed[0][0].message

    def test_env_knobs_fires_on_unregistered_access(self):
        r = _run(_fx("fx_env_knobs.py"), env_knobs)
        assert len(r.violations) == 1, r.violations
        assert "DLROVER_NOT_A_REGISTERED_KNOB" in r.violations[0].message
        assert len(r.suppressed) == 1

    def test_bare_ignore_is_an_error(self):
        r = _run(_fx("fx_bad_suppression.py"), blocking_under_lock)
        assert not r.violations  # the site IS suppressed...
        assert r.errors and "needs a reason" in r.errors[0]
        assert not r.clean  # ...but the bare ignore fails the run


class TestInjectionCoveragePass:
    def _tree(self, tmp_path, tests_text):
        faults = tmp_path / "faults.py"
        faults.write_text(
            "INJECTION_POINTS = {\n"
            '    "covered.point": "a point with a drill",\n'
            '    "uncovered.point": "a point nobody exercises",\n'
            "}\n"
        )
        scenarios = tmp_path / "scenarios.py"
        scenarios.write_text(
            "def drill(w=None):\n    return {}\n\n"
            'SCENARIOS = {"my_drill": drill, "dusty_drill": drill}\n'
        )
        tests = tmp_path / "tests"
        tests.mkdir()
        (tests / "test_x.py").write_text(tests_text)
        return str(faults), str(tests), str(scenarios)

    def test_uncovered_point_and_unexercised_scenario_flagged(
        self, tmp_path
    ):
        faults, tests, scenarios = self._tree(
            tmp_path, 'def test_a():\n    fire("covered.point")\n'
        )
        got = list(
            injection_coverage.check_coverage(
                faults, tests, scenarios_path=scenarios
            )
        )
        codes = {v.code for v in got}
        assert "uncovered.point" in codes
        assert "scenario:my_drill" in codes
        assert "scenario:dusty_drill" in codes
        assert "covered.point" not in codes

    def test_point_covered_through_exercised_scenario(self, tmp_path):
        faults, tests, scenarios = self._tree(
            tmp_path,
            "def test_a():\n"
            '    run("my_drill"); run("dusty_drill")\n'
            '    fire("covered.point")\n',
        )
        # point the scenario file at the uncovered point: the scenario
        # is exercised, so the point counts as covered
        open(scenarios, "a").write('PLAN = "uncovered.point:error"\n')
        got = list(
            injection_coverage.check_coverage(
                faults, tests, scenarios_path=scenarios
            )
        )
        assert not got, [v.render() for v in got]


class TestBaseline:
    def _fixture_violations(self):
        return run_lint(
            [_FIXTURES], passes=list(ALL_PASSES), repo_root=_REPO
        )

    def test_round_trip(self, tmp_path):
        first = self._fixture_violations()
        assert first.violations  # the planted set
        path = str(tmp_path / "baseline.json")
        Baseline.from_violations(
            first.violations, reason="fixture grandfather"
        ).save(path)
        again = run_lint(
            [_FIXTURES],
            passes=list(ALL_PASSES),
            baseline=Baseline.load(path),
            repo_root=_REPO,
        )
        assert not again.violations
        assert again.baselined == len(first.violations)
        assert not again.stale_baseline
        # the bare-ignore error is NOT baselineable
        assert again.errors

    def test_stale_entry_reported(self, tmp_path):
        first = self._fixture_violations()
        bl = Baseline.from_violations(first.violations, reason="ok")
        bl.entries.append(
            type(bl.entries[0])(
                pass_id="host-sync",
                path="tests/lint_fixtures/fx_host_sync.py",
                code="this_line_was_fixed_long_ago()",
                reason="ghost of a fixed site",
            )
        )
        path = str(tmp_path / "baseline.json")
        bl.save(path)
        again = run_lint(
            [_FIXTURES],
            passes=list(ALL_PASSES),
            baseline=Baseline.load(path),
            repo_root=_REPO,
        )
        assert len(again.stale_baseline) == 1
        assert again.stale_baseline[0].code == "this_line_was_fixed_long_ago()"
        assert not again.clean

    def test_entry_without_reason_is_an_error(self, tmp_path):
        first = self._fixture_violations()
        bl = Baseline.from_violations(first.violations, reason="")
        path = str(tmp_path / "baseline.json")
        bl.save(path)
        again = run_lint(
            [_FIXTURES],
            passes=list(ALL_PASSES),
            baseline=Baseline.load(path),
            repo_root=_REPO,
        )
        assert any("no reason" in e for e in again.errors)


class TestCli:
    def test_list_passes(self, capsys):
        assert lint_main(["--list-passes"]) == 0
        out = capsys.readouterr().out
        for pid in PASS_BY_ID:
            assert pid in out

    def test_unknown_pass_is_usage_error(self, capsys):
        assert lint_main(["--select", "no-such-pass", _FIXTURES]) == 2

    def test_fixtures_fail_and_json_format(self, capsys):
        rc = lint_main(
            ["--no-baseline", "--format", "json", _FIXTURES]
        )
        assert rc == 1
        data = json.loads(capsys.readouterr().out)
        assert data["violations"] and not data["clean"]

    def test_write_baseline_then_clean(self, tmp_path, capsys):
        path = str(tmp_path / "bl.json")
        assert lint_main([_FIXTURES, "--write-baseline", path]) == 0
        capsys.readouterr()
        # violations are baselined now, but the bare ignore still fails
        rc = lint_main([_FIXTURES, "--baseline", path])
        out = capsys.readouterr().out
        assert "0 violations" in out
        assert rc == 1 and "needs a reason" in out


class TestSuppressionForms:
    def test_stacked_comment_lines_chain_up(self, tmp_path):
        p = tmp_path / "fx.py"
        p.write_text(
            "import time, threading\n"
            "_lock = threading.Lock()\n"
            "def f():\n"
            "    with _lock:\n"
            "        # tpulint: ignore[blocking-under-lock] long reason\n"
            "        # that wraps onto a second comment line\n"
            "        time.sleep(1)\n"
        )
        r = _run(str(p), blocking_under_lock)
        assert not r.violations and len(r.suppressed) == 1

    def test_suppression_for_other_pass_does_not_apply(self, tmp_path):
        p = tmp_path / "fx.py"
        p.write_text(
            "import time, threading\n"
            "_lock = threading.Lock()\n"
            "def f():\n"
            "    with _lock:\n"
            "        time.sleep(1)  # tpulint: ignore[host-sync] wrong pass\n"
        )
        r = _run(str(p), blocking_under_lock)
        assert len(r.violations) == 1 and not r.suppressed


class TestReviewRegressions:
    """Review findings on PR 6 itself: the staleness rule must not be
    satisfied by the registry's own declaration, bare ignores on
    repo-level violations are errors too, and the CLI refuses to
    green-light a typo'd path."""

    def _fake_tree(self, tmp_path, mod_source):
        (tmp_path / "pyproject.toml").write_text("[project]\n")
        common = tmp_path / "dlrover_tpu" / "common"
        common.mkdir(parents=True)
        (common / "constants.py").write_text(
            "class K:\n"
            "    def __init__(self, name, internal=False,"
            " context_field=''):\n"
            "        self.name = name\n"
            "        self.internal = internal\n"
            "        self.context_field = context_field\n"
            "\n"
            "class NodeEnv:\n"
            "    ATTR_ONLY = 'DLROVER_ATTR_ONLY'\n"
            "\n"
            "ENV_KNOBS = {k.name: k for k in [\n"
            "    K('DLROVER_USED', internal=True),\n"
            "    K('DLROVER_GHOST', internal=True),\n"
            "    K('DLROVER_ATTR_ONLY', internal=True),\n"
            "]}\n"
        )
        (tmp_path / "dlrover_tpu" / "mod.py").write_text(mod_source)
        return tmp_path

    def test_registry_self_reference_does_not_hide_staleness(
        self, tmp_path
    ):
        root = self._fake_tree(
            tmp_path,
            "import os\n"
            "A = os.getenv('DLROVER_USED')\n"
            "from .common.constants import NodeEnv\n"
            "B = os.getenv(NodeEnv.ATTR_ONLY)\n",
        )
        r = run_lint(
            [str(root / "dlrover_tpu")],
            passes=[env_knobs],
            repo_root=str(root),
        )
        codes = {v.code for v in r.violations}
        # GHOST appears ONLY in ENV_KNOBS itself -> stale; USED is
        # referenced by literal, ATTR_ONLY through the NodeEnv attr
        assert "stale:DLROVER_GHOST" in codes, [
            v.render() for v in r.violations
        ]
        assert "stale:DLROVER_USED" not in codes
        assert "stale:DLROVER_ATTR_ONLY" not in codes

    def test_bare_ignore_on_repo_level_violation_is_an_error(
        self, tmp_path
    ):
        root = self._fake_tree(
            tmp_path,
            "X = 'DLROVER_TYPO_KNOB'  # tpulint: ignore[env-knobs]\n"
            "import os\n"
            "A = os.getenv('DLROVER_USED')\n"
            "B = 'DLROVER_ATTR_ONLY'\n",
        )
        r = run_lint(
            [str(root / "dlrover_tpu")],
            passes=[env_knobs],
            repo_root=str(root),
        )
        assert any(
            v.pass_id == "env-knobs" for v, _s in r.suppressed
        ), [v.render() for v in r.violations]
        assert any("needs a reason" in e for e in r.errors)
        assert not r.clean

    def test_cli_rejects_nonexistent_path(self, capsys):
        assert lint_main(["definitely_no_such_dir_xyz"]) == 2
        assert "do not exist" in capsys.readouterr().err

    def test_cli_rejects_pathless_lint(self, tmp_path, capsys):
        empty = tmp_path / "empty"
        empty.mkdir()
        assert lint_main([str(empty)]) == 2
        assert "no Python files" in capsys.readouterr().err
