"""tpurun-lint unit tests: every pass fires on its planted fixture,
both suppression forms work (same-line and line-above), bare ignores
are errors, and the baseline round-trips (stale entries reported).

The repo-wide zero-violation gate lives in tests/test_lint_clean.py;
this file exercises the machinery against tests/lint_fixtures/.
"""

import json
import os

import pytest

from dlrover_tpu.analysis import Baseline, run_lint
from dlrover_tpu.analysis.cli import main as lint_main
from dlrover_tpu.analysis.passes import (
    ALL_PASSES,
    PASS_BY_ID,
    blocking_under_lock,
    endpoint_conformance,
    env_knobs,
    epoch_fence,
    exception_swallow,
    host_sync,
    import_purity,
    injection_coverage,
    journal_conformance,
    lock_order,
    mesh_axes,
    reshard_coverage,
    rpc_deadline,
    thread_lifecycle,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_FIXTURES = os.path.join(_REPO, "tests", "lint_fixtures")


def _fx(name):
    return os.path.join(_FIXTURES, name)


def _run(path, lint_pass):
    return run_lint([path], passes=[lint_pass], repo_root=_REPO)


class TestPassesFireOnFixtures:
    def test_import_purity_fires(self):
        r = _run(_fx("fx_import_purity.py"), import_purity)
        assert len(r.violations) == 1, r.violations
        v = r.violations[0]
        assert v.pass_id == "import-purity"
        assert "jax_compilation_cache_dir" in v.code
        # the suppressed twin (line-above form) and only it
        assert len(r.suppressed) == 1
        assert not r.errors

    def test_import_purity_main_guard_and_functions_exempt(self):
        r = _run(_fx("fx_import_purity.py"), import_purity)
        flagged_lines = {v.line for v in r.violations} | {
            v.line for v, _s in r.suppressed
        }
        src = open(_fx("fx_import_purity.py")).readlines()
        for i, text in enumerate(src, start=1):
            if "fine_inside_a_function" in text or "__main__" in text:
                assert i not in flagged_lines

    def test_blocking_under_lock_fires(self):
        r = _run(_fx("fx_blocking_under_lock.py"), blocking_under_lock)
        assert len(r.violations) == 1, r.violations
        assert "sleep" in r.violations[0].message
        # same-line suppression on the untimed join
        assert len(r.suppressed) == 1
        assert "join" in r.suppressed[0][0].message
        assert not r.errors

    def test_host_sync_fires_on_marker_and_jit(self):
        r = _run(_fx("fx_host_sync.py"), host_sync)
        msgs = [v.message for v in r.violations]
        assert any("float()" in m and "dispatch_round" in m for m in msgs)
        assert any(".item()" in m and "jitted_body" in m for m in msgs)
        assert len(r.violations) == 2, r.violations
        # the drain point is suppressed; the cold path is not hot
        assert len(r.suppressed) == 1
        assert "device_get" in r.suppressed[0][0].message

    def test_host_sync_flags_per_call_heavy_import(self, tmp_path):
        p = tmp_path / "fx.py"
        p.write_text(
            "# tpulint: hotpath\n"
            "def step(state):\n"
            "    import jax\n"
            "    return state\n"
        )
        r = _run(str(p), host_sync)
        assert len(r.violations) == 1
        assert "per-call import" in r.violations[0].message

    def test_rpc_deadline_fires(self):
        r = _run(_fx("fx_rpc_deadline.py"), rpc_deadline)
        assert len(r.violations) == 1, r.violations
        assert "hard-coded deadline" in r.violations[0].message
        # urlopen with NO deadline is also a violation — suppressed here
        assert len(r.suppressed) == 1
        assert "no deadline" in r.suppressed[0][0].message

    def test_env_knobs_fires_on_unregistered_access(self):
        r = _run(_fx("fx_env_knobs.py"), env_knobs)
        assert len(r.violations) == 1, r.violations
        assert "DLROVER_NOT_A_REGISTERED_KNOB" in r.violations[0].message
        assert len(r.suppressed) == 1

    def test_bare_ignore_is_an_error(self):
        r = _run(_fx("fx_bad_suppression.py"), blocking_under_lock)
        assert not r.violations  # the site IS suppressed...
        assert r.errors and "needs a reason" in r.errors[0]
        assert not r.clean  # ...but the bare ignore fails the run

    def test_lock_order_fires_through_call_edge(self):
        r = _run(_fx("fx_lock_order.py"), lock_order)
        assert len(r.violations) == 1, [v.render() for v in r.violations]
        v = r.violations[0]
        assert v.pass_id == "lock-order"
        assert v.code.startswith("cycle:")
        # one arm of the planted cycle goes through self._touch_ledger()
        assert "_step_lock" in v.message and "_ledger_lock" in v.message
        # the suppressed-twin cycle (journal/ring) and only it
        assert len(r.suppressed) == 1
        assert "_journal_lock" in r.suppressed[0][0].message
        assert not r.errors

    def test_thread_lifecycle_fires(self):
        r = _run(_fx("fx_thread_lifecycle.py"), thread_lifecycle)
        assert len(r.violations) == 1, [v.render() for v in r.violations]
        assert "_leaked" in r.violations[0].message
        # the suppressed twin is the handed-off Popen
        assert len(r.suppressed) == 1
        assert "Popen" in r.suppressed[0][0].message
        assert not r.errors

    def test_exception_swallow_fires(self):
        r = _run(_fx("fx_exception_swallow.py"), exception_swallow)
        assert len(r.violations) == 1, [v.render() for v in r.violations]
        assert "swallows" in r.violations[0].message
        assert len(r.suppressed) == 1
        assert not r.errors

    def test_endpoint_conformance_fires(self):
        r = _run(_fx("fx_endpoint_conformance.py"), endpoint_conformance)
        assert len(r.violations) == 1, [v.render() for v in r.violations]
        assert r.violations[0].code == "client:/fx/drifted"
        # the dead route is the suppressed twin; the exact and
        # under-prefix clients are conformant
        assert len(r.suppressed) == 1
        assert r.suppressed[0][0].code == "route:/fx/dead-route"
        assert not r.errors

    def test_mesh_axes_fires(self):
        r = _run(_fx("fx_mesh_axes.py"), mesh_axes)
        assert len(r.violations) == 1, [v.render() for v in r.violations]
        v = r.violations[0]
        assert v.pass_id == "mesh-axes" and "zz_bogus" in v.message
        # the suppressed twin; registered axes (batch/seq, shape["dp"])
        # are conformant
        assert len(r.suppressed) == 1
        assert "zz_experiment" in r.suppressed[0][0].message
        assert not r.errors

    def test_reshard_coverage_fires(self):
        r = _run(_fx("fx_reshard_coverage.py"), reshard_coverage)
        assert len(r.violations) == 1, [v.render() for v in r.violations]
        v = r.violations[0]
        assert v.pass_id == "reshard-coverage" and "zz_lora" in v.message
        # covered categories (params/opt_state) and the suppressed twin
        assert len(r.suppressed) == 1
        assert "zz_probe" in r.suppressed[0][0].message
        assert not r.errors

    def test_journal_conformance_fires(self):
        r = _run(_fx("fx_journal_conformance.py"), journal_conformance)
        codes = {v.code for v in r.violations}
        # the drifted record kind AND the dead replay branch
        assert codes == {"recorded:fx.sett", "applied:fx.ghost"}, [
            v.render() for v in r.violations
        ]
        # the one-way component is the suppressed twin
        assert len(r.suppressed) == 1
        assert r.suppressed[0][0].code == "pair:FxHalfComponent"
        assert not r.errors

    def test_epoch_fence_fires(self):
        r = _run(_fx("fx_epoch_fence.py"), epoch_fence)
        assert len(r.violations) == 2, [v.render() for v in r.violations]
        msgs = [v.message for v in r.violations]
        # the unstamped servicer response AND the raw transport client
        assert any("master_epoch" in m for m in msgs)
        assert any("bypasses the epoch fence" in m for m in msgs)
        assert len(r.suppressed) == 1
        assert not r.errors


class TestInjectionCoveragePass:
    def _tree(self, tmp_path, tests_text):
        faults = tmp_path / "faults.py"
        faults.write_text(
            "INJECTION_POINTS = {\n"
            '    "covered.point": "a point with a drill",\n'
            '    "uncovered.point": "a point nobody exercises",\n'
            "}\n"
        )
        scenarios = tmp_path / "scenarios.py"
        scenarios.write_text(
            "def drill(w=None):\n    return {}\n\n"
            'SCENARIOS = {"my_drill": drill, "dusty_drill": drill}\n'
        )
        tests = tmp_path / "tests"
        tests.mkdir()
        (tests / "test_x.py").write_text(tests_text)
        return str(faults), str(tests), str(scenarios)

    def test_uncovered_point_and_unexercised_scenario_flagged(
        self, tmp_path
    ):
        faults, tests, scenarios = self._tree(
            tmp_path, 'def test_a():\n    fire("covered.point")\n'
        )
        got = list(
            injection_coverage.check_coverage(
                faults, tests, scenarios_path=scenarios
            )
        )
        codes = {v.code for v in got}
        assert "uncovered.point" in codes
        assert "scenario:my_drill" in codes
        assert "scenario:dusty_drill" in codes
        assert "covered.point" not in codes

    def test_point_covered_through_exercised_scenario(self, tmp_path):
        faults, tests, scenarios = self._tree(
            tmp_path,
            "def test_a():\n"
            '    run("my_drill"); run("dusty_drill")\n'
            '    fire("covered.point")\n',
        )
        # point the scenario file at the uncovered point: the scenario
        # is exercised, so the point counts as covered
        open(scenarios, "a").write('PLAN = "uncovered.point:error"\n')
        got = list(
            injection_coverage.check_coverage(
                faults, tests, scenarios_path=scenarios
            )
        )
        assert not got, [v.render() for v in got]


class TestBaseline:
    def _fixture_violations(self):
        return run_lint(
            [_FIXTURES], passes=list(ALL_PASSES), repo_root=_REPO
        )

    def test_round_trip(self, tmp_path):
        first = self._fixture_violations()
        assert first.violations  # the planted set
        path = str(tmp_path / "baseline.json")
        Baseline.from_violations(
            first.violations, reason="fixture grandfather"
        ).save(path)
        again = run_lint(
            [_FIXTURES],
            passes=list(ALL_PASSES),
            baseline=Baseline.load(path),
            repo_root=_REPO,
        )
        assert not again.violations
        assert again.baselined == len(first.violations)
        assert not again.stale_baseline
        # the bare-ignore error is NOT baselineable
        assert again.errors

    def test_stale_entry_reported(self, tmp_path):
        first = self._fixture_violations()
        bl = Baseline.from_violations(first.violations, reason="ok")
        bl.entries.append(
            type(bl.entries[0])(
                pass_id="host-sync",
                path="tests/lint_fixtures/fx_host_sync.py",
                code="this_line_was_fixed_long_ago()",
                reason="ghost of a fixed site",
            )
        )
        path = str(tmp_path / "baseline.json")
        bl.save(path)
        again = run_lint(
            [_FIXTURES],
            passes=list(ALL_PASSES),
            baseline=Baseline.load(path),
            repo_root=_REPO,
        )
        assert len(again.stale_baseline) == 1
        assert again.stale_baseline[0].code == "this_line_was_fixed_long_ago()"
        assert not again.clean

    def test_entry_without_reason_is_an_error(self, tmp_path):
        first = self._fixture_violations()
        bl = Baseline.from_violations(first.violations, reason="")
        path = str(tmp_path / "baseline.json")
        bl.save(path)
        again = run_lint(
            [_FIXTURES],
            passes=list(ALL_PASSES),
            baseline=Baseline.load(path),
            repo_root=_REPO,
        )
        assert any("no reason" in e for e in again.errors)


class TestCli:
    def test_list_passes(self, capsys):
        assert lint_main(["--list-passes"]) == 0
        out = capsys.readouterr().out
        for pid in PASS_BY_ID:
            assert pid in out

    def test_unknown_pass_is_usage_error(self, capsys):
        assert lint_main(["--select", "no-such-pass", _FIXTURES]) == 2

    def test_fixtures_fail_and_json_format(self, capsys):
        rc = lint_main(
            ["--no-baseline", "--format", "json", _FIXTURES]
        )
        assert rc == 1
        data = json.loads(capsys.readouterr().out)
        assert data["findings"] and not data["clean"]

    def test_json_schema_round_trips(self, capsys):
        """The --format json report is the machine contract the lint
        gate diffs across commits: schema-stamped, deterministically
        sorted, and exactly reconstructable from a direct run_lint —
        including suppressed findings and their reasons."""
        from dlrover_tpu.analysis.cli import JSON_SCHEMA, findings_json

        rc = lint_main(["--no-baseline", "--format", "json", _FIXTURES])
        assert rc == 1
        data = json.loads(capsys.readouterr().out)
        assert data["schema"] == JSON_SCHEMA

        direct = run_lint(
            [_FIXTURES], passes=list(ALL_PASSES), repo_root=_REPO
        )
        expect = findings_json(direct)
        # byte-for-byte identical after a JSON round trip: the report
        # is diffable across commits with no run-order noise
        assert json.loads(json.dumps(expect)) == data

        # every finding carries the full key tuple; rules are the
        # line-number-free identities the baseline also matches on
        for f in data["findings"]:
            assert set(f) == {
                "pass", "file", "line", "rule", "message",
                "suppressed", "reason",
            }
            if f["suppressed"]:
                assert f["reason"].strip() or f["file"].endswith(
                    "fx_bad_suppression.py"
                )
        keys = [
            (f["file"], f["line"], f["pass"], f["rule"], f["suppressed"])
            for f in data["findings"]
        ]
        assert keys == sorted(keys)
        assert data["counts"]["violations"] == len(direct.violations)
        assert data["counts"]["suppressed"] == len(direct.suppressed)
        # the bare-ignore fixture keeps the errors channel non-empty
        assert data["counts"]["errors"] == len(direct.errors) > 0

    def test_write_baseline_then_clean(self, tmp_path, capsys):
        path = str(tmp_path / "bl.json")
        assert lint_main([_FIXTURES, "--write-baseline", path]) == 0
        capsys.readouterr()
        # violations are baselined now, but the bare ignore still fails
        rc = lint_main([_FIXTURES, "--baseline", path])
        out = capsys.readouterr().out
        assert "0 violations" in out
        assert rc == 1 and "needs a reason" in out


class TestSuppressionForms:
    def test_stacked_comment_lines_chain_up(self, tmp_path):
        p = tmp_path / "fx.py"
        p.write_text(
            "import time, threading\n"
            "_lock = threading.Lock()\n"
            "def f():\n"
            "    with _lock:\n"
            "        # tpulint: ignore[blocking-under-lock] long reason\n"
            "        # that wraps onto a second comment line\n"
            "        time.sleep(1)\n"
        )
        r = _run(str(p), blocking_under_lock)
        assert not r.violations and len(r.suppressed) == 1

    def test_suppression_for_other_pass_does_not_apply(self, tmp_path):
        p = tmp_path / "fx.py"
        p.write_text(
            "import time, threading\n"
            "_lock = threading.Lock()\n"
            "def f():\n"
            "    with _lock:\n"
            "        time.sleep(1)  # tpulint: ignore[host-sync] wrong pass\n"
        )
        r = _run(str(p), blocking_under_lock)
        assert len(r.violations) == 1 and not r.suppressed


class TestLockOrderMachinery:
    def test_closure_edges_participate(self, tmp_path):
        """The PR 8 drain threads are nested defs: a cycle whose second
        arm lives in a closure must still be found."""
        p = tmp_path / "fx.py"
        p.write_text(
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._a_lock = threading.Lock()\n"
            "        self._b_lock = threading.Lock()\n"
            "    def fwd(self):\n"
            "        with self._a_lock:\n"
            "            with self._b_lock:\n"
            "                pass\n"
            "    def spawn(self):\n"
            "        def drain():\n"
            "            with self._b_lock:\n"
            "                with self._a_lock:\n"
            "                    pass\n"
            "        threading.Thread(target=drain, daemon=True).start()\n"
        )
        r = _run(str(p), lock_order)
        assert len(r.violations) == 1, [v.render() for v in r.violations]
        assert "cycle" in r.violations[0].message

    def test_consistent_order_is_clean(self, tmp_path):
        p = tmp_path / "fx.py"
        p.write_text(
            "import threading\n"
            "_a_lock = threading.Lock()\n"
            "_b_lock = threading.Lock()\n"
            "def f():\n"
            "    with _a_lock:\n"
            "        with _b_lock:\n"
            "            pass\n"
            "def g():\n"
            "    with _a_lock:\n"
            "        with _b_lock:\n"
            "            pass\n"
        )
        r = _run(str(p), lock_order)
        assert not r.violations

    def test_reentrant_same_lock_is_not_a_cycle(self, tmp_path):
        p = tmp_path / "fx.py"
        p.write_text(
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._rlock = threading.RLock()\n"
            "    def outer(self):\n"
            "        with self._rlock:\n"
            "            self.inner()\n"
            "    def inner(self):\n"
            "        with self._rlock:\n"
            "            pass\n"
        )
        r = _run(str(p), lock_order)
        assert not r.violations

    def test_transitive_call_chain_closes_cycle(self, tmp_path):
        """a held -> call f -> call g -> acquires b; elsewhere b->a."""
        p = tmp_path / "fx.py"
        p.write_text(
            "import threading\n"
            "_a_lock = threading.Lock()\n"
            "_b_lock = threading.Lock()\n"
            "def top():\n"
            "    with _a_lock:\n"
            "        mid()\n"
            "def mid():\n"
            "    leaf()\n"
            "def leaf():\n"
            "    with _b_lock:\n"
            "        pass\n"
            "def reverse():\n"
            "    with _b_lock:\n"
            "        with _a_lock:\n"
            "            pass\n"
        )
        r = _run(str(p), lock_order)
        assert len(r.violations) == 1, [v.render() for v in r.violations]


class TestThreadLifecycleMachinery:
    def test_handle_passed_to_reaper_counts(self, tmp_path):
        p = tmp_path / "fx.py"
        p.write_text(
            "import subprocess\n"
            "class C:\n"
            "    def launch(self):\n"
            "        self._proc = subprocess.Popen(['true'])\n"
            "    def stop(self):\n"
            "        kill_process_group(self._proc, grace_s=5)\n"
        )
        r = _run(str(p), thread_lifecycle)
        assert not r.violations

    def test_killpg_on_pid_is_not_a_reap(self, tmp_path):
        """The warm-spare bug shape: os.killpg(getpgid(pid)) never
        waits — the handle itself is unreaped."""
        p = tmp_path / "fx.py"
        p.write_text(
            "import os, signal, subprocess\n"
            "class C:\n"
            "    def launch(self):\n"
            "        self._proc = subprocess.Popen(['true'])\n"
            "    def stop(self):\n"
            "        os.killpg(os.getpgid(self._proc.pid), signal.SIGKILL)\n"
        )
        r = _run(str(p), thread_lifecycle)
        assert len(r.violations) == 1
        assert "_proc" in r.violations[0].message

    def test_loop_over_container_join_counts(self, tmp_path):
        p = tmp_path / "fx.py"
        p.write_text(
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._threads = []\n"
            "    def go(self):\n"
            "        self._threads.append(threading.Thread(target=int))\n"
            "    def stop(self):\n"
            "        for t in self._threads:\n"
            "            t.join(timeout=5)\n"
        )
        r = _run(str(p), thread_lifecycle)
        assert not r.violations

    def test_untimed_join_does_not_satisfy(self, tmp_path):
        p = tmp_path / "fx.py"
        p.write_text(
            "import threading\n"
            "class C:\n"
            "    def go(self):\n"
            "        self._t = threading.Thread(target=int)\n"
            "    def stop(self):\n"
            "        self._t.join()\n"
        )
        r = _run(str(p), thread_lifecycle)
        assert len(r.violations) == 1


class TestExceptionSwallowMachinery:
    def test_broad_in_tuple_is_flagged(self, tmp_path):
        p = tmp_path / "fx.py"
        p.write_text(
            "def f():\n"
            "    try:\n"
            "        g()\n"
            "    except (ValueError, Exception):\n"
            "        pass\n"
        )
        r = _run(str(p), exception_swallow)
        assert len(r.violations) == 1

    def test_handler_in_nested_def_does_not_count(self, tmp_path):
        """A log call inside a nested def runs later, if ever — the
        handler still swallows."""
        p = tmp_path / "fx.py"
        p.write_text(
            "import logging\n"
            "def f():\n"
            "    try:\n"
            "        g()\n"
            "    except Exception:\n"
            "        def later():\n"
            "            logging.warning('x')\n"
            "        keep = later\n"
        )
        r = _run(str(p), exception_swallow)
        assert len(r.violations) == 1

    def test_counter_bump_counts(self, tmp_path):
        p = tmp_path / "fx.py"
        p.write_text(
            "def f(stats):\n"
            "    try:\n"
            "        g()\n"
            "    except Exception:\n"
            "        stats['fail'] += 1\n"
        )
        r = _run(str(p), exception_swallow)
        assert not r.violations


class TestEndpointConformanceMachinery:
    def _ctx(self, tmp_path, name, source):
        from dlrover_tpu.analysis.core import FileContext

        p = tmp_path / name
        p.write_text(source)
        return FileContext.parse(str(p), name)

    def test_route_referenced_only_by_docs_is_clean(self, tmp_path):
        server = self._ctx(
            tmp_path,
            "server.py",
            "class H:\n"
            "    def do_GET(self):\n"
            "        if self.path == '/fx/status':\n"
            "            pass\n",
        )
        got = list(
            endpoint_conformance.check_conformance(
                [server], "curl the `/fx/status` endpoint"
            )
        )
        assert not got
        got = list(endpoint_conformance.check_conformance([server], ""))
        assert len(got) == 1 and got[0].code == "route:/fx/status"

    def test_helper_call_path_not_first_arg(self, tmp_path):
        """The gateway shape: _post_replica(h, '/v1/x', payload)."""
        client = self._ctx(
            tmp_path,
            "client.py",
            "class C:\n"
            "    def go(self, h):\n"
            "        self._post_replica(h, '/fx/x', {})\n",
        )
        got = list(endpoint_conformance.check_conformance([client], ""))
        assert len(got) == 1 and got[0].code == "client:/fx/x"

    def test_fstring_url_tail_collected(self, tmp_path):
        client = self._ctx(
            tmp_path,
            "client.py",
            "def go(host, port):\n"
            "    url = f'http://{host}:{port}/fx/poll'\n"
            "    return url\n",
        )
        got = list(endpoint_conformance.check_conformance([client], ""))
        assert len(got) == 1 and got[0].code == "client:/fx/poll"

    def test_filesystem_paths_are_not_clients(self, tmp_path):
        client = self._ctx(
            tmp_path,
            "client.py",
            "import os\n"
            "def go(base_dir):\n"
            "    return os.path.join(base_dir, '/tmp/x.json')\n",
        )
        got = list(endpoint_conformance.check_conformance([client], ""))
        assert not got


class TestChangedMode:
    def _git_repo(self, tmp_path):
        import subprocess

        def git(*args):
            subprocess.run(
                ["git", "-c", "user.email=t@t", "-c", "user.name=t"]
                + list(args),
                cwd=tmp_path,
                check=True,
                capture_output=True,
            )

        (tmp_path / "pyproject.toml").write_text("[project]\n")
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        violation = (
            "import threading, time\n"
            "_lock = threading.Lock()\n"
            "def f():\n"
            "    with _lock:\n"
            "        time.sleep(1)\n"
        )
        (pkg / "old.py").write_text(violation)
        (pkg / "other.py").write_text("X = 1\n")
        git("init", "-q")
        git("add", "-A")
        git("commit", "-qm", "seed")
        return pkg, violation

    def test_changed_lints_only_changed_files(self, tmp_path, capsys):
        pkg, violation = self._git_repo(tmp_path)
        # old.py's committed violation must NOT be reported; the fresh
        # edit to other.py must be
        (pkg / "other.py").write_text(violation)
        rc = lint_main(["--changed", "--no-baseline", str(pkg)])
        captured = capsys.readouterr()
        assert rc == 1
        assert "other.py" in captured.out and "old.py" not in captured.out
        # the notice rides stderr: stdout belongs to --format json
        assert "skips repo-wide passes" in captured.err

    def test_changed_with_no_edits_is_clean(self, tmp_path, capsys):
        pkg, _ = self._git_repo(tmp_path)
        rc = lint_main(["--changed", "--no-baseline", str(pkg)])
        captured = capsys.readouterr()
        assert rc == 0
        assert "no Python files changed" in captured.err

    def test_changed_json_stdout_is_pure(self, tmp_path, capsys):
        """Review regression: the --changed notices must not corrupt the
        --format json machine contract — stdout parses as the schema
        document, notices go to stderr."""
        pkg, violation = self._git_repo(tmp_path)
        (pkg / "other.py").write_text(violation)
        rc = lint_main(
            ["--changed", "--no-baseline", "--format", "json", str(pkg)]
        )
        captured = capsys.readouterr()
        doc = json.loads(captured.out)
        assert rc == 1
        assert doc["schema"] == "tpurun-lint-findings/1"
        assert doc["counts"]["violations"] == 1
        assert "skips repo-wide passes" in captured.err

    def test_changed_json_no_edits_emits_empty_document(
        self, tmp_path, capsys
    ):
        """A gate diffing findings across commits always gets a
        document, even when nothing changed."""
        pkg, _ = self._git_repo(tmp_path)
        rc = lint_main(
            ["--changed", "--no-baseline", "--format", "json", str(pkg)]
        )
        captured = capsys.readouterr()
        doc = json.loads(captured.out)
        assert rc == 0
        assert doc["clean"] is True and doc["findings"] == []
        assert "no Python files changed" in captured.err

    def test_changed_sees_untracked_files(self, tmp_path, capsys):
        pkg, violation = self._git_repo(tmp_path)
        (pkg / "fresh.py").write_text(violation)
        rc = lint_main(["--changed", "--no-baseline", str(pkg)])
        out = capsys.readouterr().out
        assert rc == 1 and "fresh.py" in out

    def test_changed_rejects_write_baseline(self, tmp_path, capsys):
        """A subset run must not silently truncate the repo-wide
        baseline file."""
        pkg, _ = self._git_repo(tmp_path)
        rc = lint_main(
            [
                "--changed",
                "--write-baseline",
                str(tmp_path / "bl.json"),
                str(pkg),
            ]
        )
        assert rc == 2
        assert "cannot be combined" in capsys.readouterr().err

    def test_changed_with_only_repo_passes_is_usage_error(
        self, tmp_path, capsys
    ):
        """--select naming only repo-wide passes + --changed must not
        exit 0 having checked nothing."""
        pkg, violation = self._git_repo(tmp_path)
        (pkg / "other.py").write_text(violation)
        rc = lint_main(
            ["--changed", "--select", "endpoint-conformance", str(pkg)]
        )
        assert rc == 2
        assert "no runnable pass" in capsys.readouterr().err


class TestReviewRegressions:
    """Review findings on PR 6 itself: the staleness rule must not be
    satisfied by the registry's own declaration, bare ignores on
    repo-level violations are errors too, and the CLI refuses to
    green-light a typo'd path."""

    def _fake_tree(self, tmp_path, mod_source):
        (tmp_path / "pyproject.toml").write_text("[project]\n")
        common = tmp_path / "dlrover_tpu" / "common"
        common.mkdir(parents=True)
        (common / "constants.py").write_text(
            "class K:\n"
            "    def __init__(self, name, internal=False,"
            " context_field=''):\n"
            "        self.name = name\n"
            "        self.internal = internal\n"
            "        self.context_field = context_field\n"
            "\n"
            "class NodeEnv:\n"
            "    ATTR_ONLY = 'DLROVER_ATTR_ONLY'\n"
            "\n"
            "ENV_KNOBS = {k.name: k for k in [\n"
            "    K('DLROVER_USED', internal=True),\n"
            "    K('DLROVER_GHOST', internal=True),\n"
            "    K('DLROVER_ATTR_ONLY', internal=True),\n"
            "]}\n"
        )
        (tmp_path / "dlrover_tpu" / "mod.py").write_text(mod_source)
        return tmp_path

    def test_registry_self_reference_does_not_hide_staleness(
        self, tmp_path
    ):
        root = self._fake_tree(
            tmp_path,
            "import os\n"
            "A = os.getenv('DLROVER_USED')\n"
            "from .common.constants import NodeEnv\n"
            "B = os.getenv(NodeEnv.ATTR_ONLY)\n",
        )
        r = run_lint(
            [str(root / "dlrover_tpu")],
            passes=[env_knobs],
            repo_root=str(root),
        )
        codes = {v.code for v in r.violations}
        # GHOST appears ONLY in ENV_KNOBS itself -> stale; USED is
        # referenced by literal, ATTR_ONLY through the NodeEnv attr
        assert "stale:DLROVER_GHOST" in codes, [
            v.render() for v in r.violations
        ]
        assert "stale:DLROVER_USED" not in codes
        assert "stale:DLROVER_ATTR_ONLY" not in codes

    def test_bare_ignore_on_repo_level_violation_is_an_error(
        self, tmp_path
    ):
        root = self._fake_tree(
            tmp_path,
            "X = 'DLROVER_TYPO_KNOB'  # tpulint: ignore[env-knobs]\n"
            "import os\n"
            "A = os.getenv('DLROVER_USED')\n"
            "B = 'DLROVER_ATTR_ONLY'\n",
        )
        r = run_lint(
            [str(root / "dlrover_tpu")],
            passes=[env_knobs],
            repo_root=str(root),
        )
        assert any(
            v.pass_id == "env-knobs" for v, _s in r.suppressed
        ), [v.render() for v in r.violations]
        assert any("needs a reason" in e for e in r.errors)
        assert not r.clean

    def test_cli_rejects_nonexistent_path(self, capsys):
        assert lint_main(["definitely_no_such_dir_xyz"]) == 2
        assert "do not exist" in capsys.readouterr().err

    def test_cli_rejects_pathless_lint(self, tmp_path, capsys):
        empty = tmp_path / "empty"
        empty.mkdir()
        assert lint_main([str(empty)]) == 2
        assert "no Python files" in capsys.readouterr().err


class TestMeshAxesMachinery:
    """Fake-tree cases: the registry cross-checks must catch drift in
    every direction, not just unknown literals."""

    def _tree(self, tmp_path, mesh_src, sharding_src="", probe_src=""):
        tmp_path.mkdir(parents=True, exist_ok=True)
        (tmp_path / "pyproject.toml").write_text("[project]\n")
        par = tmp_path / "dlrover_tpu" / "parallel"
        par.mkdir(parents=True)
        (par / "mesh.py").write_text(mesh_src)
        if sharding_src:
            (par / "sharding.py").write_text(sharding_src)
        if probe_src:
            (tmp_path / "dlrover_tpu" / "probe.py").write_text(probe_src)
        return tmp_path

    _REGISTRY = (
        "MESH_AXIS_REGISTRY = {\n"
        '    "dp": ("mesh", "data"),\n'
        '    "tp": ("mesh", "tensor"),\n'
        '    "batch": ("logical", "batch"),\n'
        "}\n"
        'MESH_AXES = ("dp", "tp")\n'
    )
    _RULES = 'DEFAULT_RULES = [("batch", ("dp",))]\n'

    def _lint(self, root):
        return run_lint(
            [str(root / "dlrover_tpu")],
            passes=[mesh_axes],
            repo_root=str(root),
        )

    def test_conformant_fake_tree_is_clean(self, tmp_path):
        root = self._tree(
            tmp_path,
            self._REGISTRY,
            self._RULES,
            "from jax.sharding import PartitionSpec\n"
            "def f(mesh):\n"
            '    return PartitionSpec("batch"), mesh.shape["dp"]\n',
        )
        r = self._lint(root)
        assert not r.violations, [v.render() for v in r.violations]

    def test_mesh_axis_in_logical_annotation_flagged(self, tmp_path):
        """A mesh axis in param_with_axes is the silent-no-constraint
        drift even though the name is registered."""
        root = self._tree(
            tmp_path,
            self._REGISTRY,
            self._RULES,
            "def f(init):\n"
            '    return param_with_axes("w", init, (4,), axes=("dp",))\n',
        )
        r = self._lint(root)
        assert len(r.violations) == 1
        assert "requires a logical axis" in r.violations[0].message

    def test_logical_axis_in_collective_flagged(self, tmp_path):
        root = self._tree(
            tmp_path,
            self._REGISTRY,
            self._RULES,
            "import jax\n"
            "def f(x):\n"
            '    return jax.lax.psum(x, "batch")\n',
        )
        r = self._lint(root)
        assert len(r.violations) == 1
        assert "requires a mesh axis" in r.violations[0].message

    def test_mesh_axes_tuple_drift_flagged(self, tmp_path):
        registry = self._REGISTRY.replace(
            'MESH_AXES = ("dp", "tp")', 'MESH_AXES = ("dp",)'
        )
        root = self._tree(tmp_path, registry, self._RULES)
        r = self._lint(root)
        codes = {v.code for v in r.violations}
        assert "mesh-axes-drift" in codes, [
            v.render() for v in r.violations
        ]

    def test_mesh_construction_with_unregistered_axes_flagged(
        self, tmp_path
    ):
        registry = self._REGISTRY + (
            "def build(devs):\n"
            '    return Mesh(devs, ("dp", "zz_rogue"))\n'
        )
        root = self._tree(tmp_path, registry, self._RULES)
        r = self._lint(root)
        assert any(
            "Mesh(...)" in v.message and "zz_rogue" in v.message
            for v in r.violations
        ), [v.render() for v in r.violations]

    def test_suppressed_site_outside_lint_subset_honored(self, tmp_path):
        """Review regression: the hybrid repo_check scans the whole
        tree even when run_lint's subset (--changed) excludes the
        suppressed file — its inline suppression must still be
        honored, or the pre-commit fast path blocks commits the full
        gate accepts."""
        registry = self._REGISTRY + (
            "def build(devs):\n"
            '    return Mesh(devs, ("dp", "zz_probe"))'
            "  # tpulint: ignore[mesh-axes] drill mesh, not a training axis\n"
        )
        root = self._tree(tmp_path, registry, self._RULES, "X = 1\n")
        r = run_lint(
            [str(root / "dlrover_tpu" / "probe.py")],
            passes=[mesh_axes],
            repo_root=str(root),
        )
        assert not r.violations, [v.render() for v in r.violations]
        assert any(v.pass_id == "mesh-axes" for v, _s in r.suppressed)

    def test_mesh_construction_keyword_form_checked(self, tmp_path):
        """Review regression: jax's Mesh accepts axis_names as a
        keyword — the cross-check must not skip that form."""
        registry = self._REGISTRY + (
            "def build(devs):\n"
            '    return Mesh(devs, axis_names=("dp", "zz_kwrogue"))\n'
        )
        root = self._tree(tmp_path, registry, self._RULES)
        r = self._lint(root)
        assert any(
            "zz_kwrogue" in v.message for v in r.violations
        ), [v.render() for v in r.violations]

    def test_default_rules_unregistered_target_flagged(self, tmp_path):
        root = self._tree(
            tmp_path,
            self._REGISTRY,
            'DEFAULT_RULES = [("batch", ("zz_ghost_mesh",))]\n',
        )
        r = self._lint(root)
        codes = {v.code for v in r.violations}
        assert "rule-target:batch:zz_ghost_mesh" in codes

    def test_unmapped_logical_axis_flagged(self, tmp_path):
        registry = self._REGISTRY.replace(
            '    "batch": ("logical", "batch"),\n',
            '    "batch": ("logical", "batch"),\n'
            '    "seq": ("logical", "sequence"),\n',
        )
        # seq registered + referenced by a spec, but DEFAULT_RULES
        # never maps it
        root = self._tree(
            tmp_path,
            registry,
            self._RULES,
            "from jax.sharding import PartitionSpec as P\n"
            'S = P("seq")\n',
        )
        r = self._lint(root)
        codes = {v.code for v in r.violations}
        assert "unmapped:seq" in codes, [v.render() for v in r.violations]

    def test_stale_registry_entry_flagged(self, tmp_path):
        registry = self._REGISTRY.replace(
            '    "batch": ("logical", "batch"),\n',
            '    "batch": ("logical", "batch"),\n'
            '    "zz_unused": ("logical", "nobody references this"),\n',
        )
        rules = (
            'DEFAULT_RULES = [("batch", ("dp",)), ("zz_unused", None)]\n'
        )
        root = self._tree(tmp_path, registry, rules)
        r = self._lint(root)
        # mapped by DEFAULT_RULES -> referenced -> NOT stale
        assert not any("stale" in v.code for v in r.violations)
        root2 = self._tree(
            tmp_path / "two", registry, self._RULES
        )
        r2 = self._lint(root2)
        codes = {v.code for v in r2.violations}
        assert "stale:zz_unused" in codes
        # registered-but-unmapped also fires for it
        assert "unmapped:zz_unused" in codes

    def test_computed_registry_is_a_parse_violation(self, tmp_path):
        root = self._tree(
            tmp_path,
            "MESH_AXIS_REGISTRY = dict(dp=(\"mesh\", \"d\"))\n"
            "MESH_AXES = tuple(MESH_AXIS_REGISTRY)\n",
        )
        r = self._lint(root)
        assert any(v.code == "registry-parse" for v in r.violations)

    def test_registry_edit_reparsed_within_one_process(self, tmp_path):
        """Review regression: the pass singleton caches the parsed
        registry keyed by (root, mtime/size) — registering the axis and
        re-running run_lint in the SAME process must go clean (watch
        modes, harnesses looping over one tmp root)."""
        probe = (
            "from jax.sharding import PartitionSpec\n"
            'SPEC = PartitionSpec("zz_new")\n'
        )
        root = self._tree(tmp_path, self._REGISTRY, self._RULES, probe)
        r = self._lint(root)
        assert any("zz_new" in v.message for v in r.violations)
        (root / "dlrover_tpu" / "parallel" / "mesh.py").write_text(
            self._REGISTRY.replace(
                '    "batch": ("logical", "batch"),\n',
                '    "batch": ("logical", "batch"),\n'
                '    "zz_new": ("logical", "fresh"),\n',
            )
        )
        (root / "dlrover_tpu" / "parallel" / "sharding.py").write_text(
            'DEFAULT_RULES = [("batch", ("dp",)), ("zz_new", ("tp",))]\n'
        )
        r2 = self._lint(root)
        assert not r2.violations, [v.render() for v in r2.violations]


class TestReshardCoverageMachinery:
    """Fake-tree cases over the rule-table cross-checks."""

    _MESH = (
        "MESH_AXIS_REGISTRY = {\n"
        '    "dp": ("mesh", "d"),\n'
        '    "tp": ("mesh", "t"),\n'
        '    "batch": ("logical", "b"),\n'
        "}\n"
        'MESH_AXES = ("dp", "tp")\n'
    )

    def _tree(self, tmp_path, sharding_src, train_state_fields=("step",)):
        (tmp_path / "pyproject.toml").write_text("[project]\n")
        par = tmp_path / "dlrover_tpu" / "parallel"
        par.mkdir(parents=True)
        (par / "mesh.py").write_text(self._MESH)
        (par / "sharding.py").write_text(sharding_src)
        fields = "".join(f"    {f}: int\n" for f in train_state_fields)
        (par / "train_step.py").write_text(
            "class TrainState:\n" + fields
        )
        return tmp_path

    def _lint(self, root):
        return run_lint(
            [str(root / "dlrover_tpu")],
            passes=[reshard_coverage],
            repo_root=str(root),
        )

    _BASE = (
        'DEFAULT_RULES = [("batch", ("dp",))]\n'
        'ELASTIC_AXES = ("dp",)\n'
        'RESHARD_POLICIES = ("replicate", "respec")\n'
    )

    def test_conformant_table_is_clean(self, tmp_path):
        root = self._tree(
            tmp_path,
            self._BASE
            + 'RESHARD_RULES = {"step": ("replicate", ()),'
            ' "params": ("respec", ("dp", "tp"))}\n',
            train_state_fields=("step", "params"),
        )
        r = self._lint(root)
        assert not r.violations, [v.render() for v in r.violations]

    def test_train_state_field_without_rule_flagged(self, tmp_path):
        root = self._tree(
            tmp_path,
            self._BASE + 'RESHARD_RULES = {"step": ("replicate", ())}\n',
            train_state_fields=("step", "ema_params"),
        )
        r = self._lint(root)
        codes = {v.code for v in r.violations}
        assert "uncovered:ema_params" in codes, [
            v.render() for v in r.violations
        ]

    def test_stale_rule_flagged(self, tmp_path):
        root = self._tree(
            tmp_path,
            self._BASE
            + 'RESHARD_RULES = {"step": ("replicate", ()),'
            ' "zz_gone": ("replicate", ())}\n',
        )
        r = self._lint(root)
        assert any("stale:zz_gone" == v.code for v in r.violations)

    def test_unknown_policy_flagged(self, tmp_path):
        root = self._tree(
            tmp_path,
            self._BASE + 'RESHARD_RULES = {"step": ("teleport", ())}\n',
        )
        r = self._lint(root)
        assert any("policy:step" == v.code for v in r.violations)

    def test_axis_gap_vs_default_rules_flagged(self, tmp_path):
        """DEFAULT_RULES can shard over tp, but the respec rule only
        covers dp — the save path can produce a sharding the table
        never answers for."""
        root = self._tree(
            tmp_path,
            'DEFAULT_RULES = [("batch", ("dp", "tp"))]\n'
            'ELASTIC_AXES = ("dp",)\n'
            'RESHARD_POLICIES = ("replicate", "respec")\n'
            'RESHARD_RULES = {"step": ("replicate", ()),'
            ' "params": ("respec", ("dp",))}\n',
            train_state_fields=("step", "params"),
        )
        r = self._lint(root)
        assert any(
            v.code == "axis-gap:params:tp" for v in r.violations
        ), [v.render() for v in r.violations]

    def test_rung_gap_vs_elastic_axes_flagged(self, tmp_path):
        root = self._tree(
            tmp_path,
            'DEFAULT_RULES = [("batch", ("dp",))]\n'
            'ELASTIC_AXES = ("dp", "tp")\n'
            'RESHARD_POLICIES = ("replicate", "respec")\n'
            'RESHARD_RULES = {"step": ("replicate", ()),'
            ' "params": ("respec", ("dp",))}\n',
            train_state_fields=("step", "params"),
        )
        r = self._lint(root)
        assert any(
            v.code == "rung-gap:params:tp" for v in r.violations
        ), [v.render() for v in r.violations]

    def test_rung_gap_on_pp_axis_flagged(self, tmp_path):
        """The 2D rung ladder's axes (docs/elastic_parallelism.md):
        ELASTIC_AXES carries pp, so a respec rule that only answers for
        (dp, tp) cannot survive a dp→pp trade — the planner would pick
        a rung the reshard table never covers."""
        root = self._tree(
            tmp_path,
            'DEFAULT_RULES = [("batch", ("dp",))]\n'
            'ELASTIC_AXES = ("dp", "tp", "pp")\n'
            'RESHARD_POLICIES = ("replicate", "respec")\n'
            'RESHARD_RULES = {"step": ("replicate", ()),'
            ' "params": ("respec", ("dp", "tp"))}\n',
            train_state_fields=("step", "params"),
        )
        r = self._lint(root)
        codes = {v.code for v in r.violations}
        assert "rung-gap:params:pp" in codes, [
            v.render() for v in r.violations
        ]
        assert "rung-gap:params:tp" not in codes  # tp IS covered

    def test_missing_table_flagged(self, tmp_path):
        root = self._tree(tmp_path, "DEFAULT_RULES = []\n")
        r = self._lint(root)
        assert any(v.code == "table-parse" for v in r.violations)

    def test_unreadable_train_state_is_parse_finding_not_stale(
        self, tmp_path
    ):
        """Review regression: a mid-edit syntax error in train_step.py
        must NOT misreport every rule as 'stale entry; delete it' —
        one parse finding, coverage checks skipped."""
        root = self._tree(
            tmp_path,
            self._BASE + 'RESHARD_RULES = {"step": ("replicate", ())}\n',
        )
        (root / "dlrover_tpu" / "parallel" / "train_step.py").write_text(
            "def broken(:\n"
        )
        r = self._lint(root)
        codes = {v.code for v in r.violations}
        assert "trainstate-parse" in codes, [
            v.render() for v in r.violations
        ]
        assert not any(c.startswith("stale:") for c in codes)

    def test_rule_table_edit_reparsed_within_one_process(self, tmp_path):
        """Review regression: same (root, mtime/size)-keyed cache as
        mesh-axes — adding the missing rule and re-running run_lint in
        the SAME process must go clean."""
        root = self._tree(
            tmp_path, self._BASE + "RESHARD_RULES = {}\n"
        )
        r = self._lint(root)
        assert any(v.code == "uncovered:step" for v in r.violations)
        (root / "dlrover_tpu" / "parallel" / "sharding.py").write_text(
            self._BASE + 'RESHARD_RULES = {"step": ("replicate", ())}\n'
        )
        r2 = self._lint(root)
        assert not r2.violations, [v.render() for v in r2.violations]

    def test_extra_kwarg_without_rule_flagged(self, tmp_path):
        root = self._tree(
            tmp_path,
            self._BASE + 'RESHARD_RULES = {"step": ("replicate", ())}\n',
        )
        (tmp_path / "dlrover_tpu" / "probe.py").write_text(
            "def f(engine, step, tree, cursors):\n"
            "    return engine.save_to_memory(step, tree, extra=cursors)\n"
        )
        r = self._lint(root)
        assert any(
            "extra" in v.message and v.path.endswith("probe.py")
            for v in r.violations
        ), [v.render() for v in r.violations]

    def test_real_repo_tables_are_loadable_and_match_runtime(self):
        """The AST-parsed tables must agree with what the runtime
        imports — a computed entry would silently vanish from lint."""
        jax = pytest.importorskip("jax")  # noqa: F841 — sharding imports jax
        from dlrover_tpu.analysis.passes.reshard_coverage import (
            load_tables,
            train_state_fields,
        )
        from dlrover_tpu.parallel import sharding as runtime

        rules, policies, elastic = load_tables(_REPO)
        assert rules == runtime.RESHARD_RULES
        assert policies == runtime.RESHARD_POLICIES
        assert elastic == runtime.ELASTIC_AXES
        assert set(train_state_fields(_REPO)) == {
            "step", "params", "opt_state",
        }

    def test_real_repo_registry_matches_runtime(self):
        jax = pytest.importorskip("jax")  # noqa: F841 — mesh imports jax
        from dlrover_tpu.analysis.passes.mesh_axes import load_axis_registry
        from dlrover_tpu.parallel import mesh as runtime

        registry, axes, err = load_axis_registry(
            os.path.join(_REPO, "dlrover_tpu", "parallel", "mesh.py")
        )
        assert not err
        assert axes == runtime.MESH_AXES
        assert registry == {
            k: v[0] for k, v in runtime.MESH_AXIS_REGISTRY.items()
        }


class TestJournalConformanceMachinery:
    def _ctx(self, tmp_path, name, source):
        from dlrover_tpu.analysis.core import FileContext

        p = tmp_path / name
        p.write_text(source)
        return FileContext.parse(str(p), name)

    def test_capture_restore_key_mismatch_flagged(self, tmp_path):
        ctx = self._ctx(
            tmp_path,
            "persistence.py",
            "def capture_master_state(master):\n"
            '    return {"job": 1, "kv": 2}\n'
            "def restore_master_state(master, state):\n"
            '    use(state.get("job"))\n'
            '    use(state.get("phantom"))\n',
        )
        got = list(journal_conformance.repo_check(str(tmp_path), [ctx]))
        codes = {v.code for v in got}
        assert "capture-only:kv" in codes
        assert "restore-only:phantom" in codes

    def test_subscript_restore_read_counts(self, tmp_path):
        ctx = self._ctx(
            tmp_path,
            "persistence.py",
            "def capture_master_state(master):\n"
            '    return {"job": 1}\n'
            "def restore_master_state(master, state):\n"
            '    use(state["job"])\n',
        )
        got = list(journal_conformance.repo_check(str(tmp_path), [ctx]))
        assert not got, [v.render() for v in got]

    def test_direct_journal_call_is_a_recorder(self, tmp_path):
        """The rdzv manager journals via self.journal(...) directly —
        no _record wrapper."""
        ctx = self._ctx(
            tmp_path,
            "mgr.py",
            "class M:\n"
            "    def complete(self):\n"
            '        self.journal("fx.complete", {})\n',
        )
        got = list(journal_conformance.repo_check(str(tmp_path), [ctx]))
        # no applier in the tree -> recorder conformance is skipped
        # (a subset lint must not read every kind as unreplayable)
        assert not got
        applier = self._ctx(
            tmp_path,
            "persist.py",
            "def apply_wal_record(m, record):\n"
            '    kind = record.get("kind")\n'
            '    if kind == "fx.other":\n'
            "        pass\n",
        )
        got = list(
            journal_conformance.repo_check(str(tmp_path), [ctx, applier])
        )
        codes = {v.code for v in got}
        assert "recorded:fx.complete" in codes
        assert "applied:fx.other" in codes

    def test_non_dotted_literals_ignored(self, tmp_path):
        """Profiler timers call .record("train_step", ...) — not a WAL
        kind; the dotted-kind shape keeps them out of scope."""
        ctx = self._ctx(
            tmp_path,
            "timer.py",
            "class T:\n"
            "    def hit(self):\n"
            '        self.timer.record("train_step", 1, 2)\n'
            "def apply_wal_record(m, r):\n"
            '    kind = r.get("kind")\n'
            '    if kind == "fx.x":\n'
            "        pass\n",
        )
        got = list(journal_conformance.repo_check(str(tmp_path), [ctx]))
        assert not any("train_step" in v.code for v in got)

    def test_repo_kinds_conform_both_ways(self):
        """The real WAL protocol: every recorded kind has a branch and
        vice versa (the invariant the pass rails)."""
        from dlrover_tpu.analysis.core import FileContext, iter_py_files
        from dlrover_tpu.analysis.passes.journal_conformance import (
            collect_applied,
            collect_recorded,
        )

        rec, app = set(), set()
        for p in iter_py_files([os.path.join(_REPO, "dlrover_tpu")]):
            ctx = FileContext.parse(p, os.path.relpath(p, _REPO))
            if ctx is None:
                continue
            rec |= {k for k, _l in collect_recorded(ctx)}
            app |= {k for k, _l in collect_applied(ctx)}
        assert rec and rec == app, (rec - app, app - rec)


class TestEpochFenceMachinery:
    def _run_src(self, tmp_path, source):
        p = tmp_path / "fx.py"
        p.write_text(source)
        return _run(str(p), epoch_fence)

    def test_transport_built_outside_masterclient_flagged(self, tmp_path):
        r = self._run_src(
            tmp_path,
            "class SideChannel:\n"
            "    def __init__(self, addr):\n"
            "        self._t = HttpTransport(addr)\n",
        )
        assert len(r.violations) == 1
        assert "outside MasterClient" in r.violations[0].message

    def test_transport_built_inside_masterclient_clean(self, tmp_path):
        r = self._run_src(
            tmp_path,
            "class MasterClient:\n"
            "    def __init__(self, addr):\n"
            "        self._transport = HttpTransport(addr)\n",
        )
        assert not r.violations

    def test_kwargs_splat_does_not_count_as_stamp(self, tmp_path):
        r = self._run_src(
            tmp_path,
            "def respond(**kw):\n"
            "    return dumps(BaseResponse(**kw))\n",
        )
        assert len(r.violations) == 1
        assert "master_epoch" in r.violations[0].message

    def test_observe_epoch_in_nested_def_counts(self, tmp_path):
        """A retry closure that observes the epoch still fences the
        enclosing call path."""
        r = self._run_src(
            tmp_path,
            "class C:\n"
            "    def call(self, payload):\n"
            "        def once():\n"
            "            raw = self._transport.get(payload)\n"
            "            self._observe_epoch(raw)\n"
            "            return raw\n"
            "        return once()\n",
        )
        assert not r.violations

    def test_module_level_transport_call_flagged(self, tmp_path):
        r = self._run_src(
            tmp_path,
            "RAW = CLIENT._transport.report(b'x')\n",
        )
        assert len(r.violations) == 1

    def test_aliased_transport_method_flagged(self, tmp_path):
        """Review regression: the fence matches the ATTRIBUTE access,
        so the repo's own bound-method idiom
        (``fn = self._transport.get; fn(payload)``) cannot evade it in
        an unfenced function."""
        r = self._run_src(
            tmp_path,
            "class Rogue:\n"
            "    def fetch(self, verb, payload):\n"
            "        fn = (self._transport.get if verb == 'get'\n"
            "              else self._transport.report)\n"
            "        return fn(payload)\n",
        )
        assert len(r.violations) == 2, [
            v.render() for v in r.violations
        ]
        assert all("epoch fence" in v.message for v in r.violations)

    def test_aliased_transport_method_fenced_clean(self, tmp_path):
        """MasterClient._call's real shape: aliasing inside a function
        that observes the epoch is the fenced path."""
        r = self._run_src(
            tmp_path,
            "class C:\n"
            "    def _call(self, verb, payload):\n"
            "        fn = (self._transport.get if verb == 'get'\n"
            "              else self._transport.report)\n"
            "        raw = fn(payload)\n"
            "        self._observe_epoch(raw)\n"
            "        return raw\n",
        )
        assert not r.violations, [v.render() for v in r.violations]


class TestPrecommitHook:
    """The checked-in pre-commit fast path: scripts/precommit-lint on a
    throwaway git repo catches a planted violation in a CHANGED file
    and skips clean/committed files entirely."""

    _SCRIPT = os.path.join(_REPO, "scripts", "precommit-lint")

    def _git_repo(self, tmp_path):
        import subprocess

        def git(*args):
            subprocess.run(
                ["git", "-c", "user.email=t@t", "-c", "user.name=t"]
                + list(args),
                cwd=tmp_path,
                check=True,
                capture_output=True,
            )

        (tmp_path / "pyproject.toml").write_text("[project]\n")
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        violation = (
            "import threading, time\n"
            "_lock = threading.Lock()\n"
            "def f():\n"
            "    with _lock:\n"
            "        time.sleep(1)\n"
        )
        # a COMMITTED violation: the fast path must not report it
        (pkg / "old.py").write_text(violation)
        (pkg / "clean.py").write_text("X = 1\n")
        git("init", "-q")
        git("add", "-A")
        git("commit", "-qm", "seed")
        return pkg, violation

    def _hook(self, tmp_path, lint_path="pkg"):
        import subprocess
        import sys

        env = dict(os.environ)
        env["PRECOMMIT_ROOT"] = str(tmp_path)
        env["PYTHON"] = sys.executable
        env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
        return subprocess.run(
            ["sh", self._SCRIPT, lint_path],
            capture_output=True,
            text=True,
            timeout=120,
            env=env,
        )

    def test_catches_planted_violation_in_changed_file(self, tmp_path):
        pkg, violation = self._git_repo(tmp_path)
        (pkg / "fresh.py").write_text(violation)
        proc = self._hook(tmp_path)
        assert proc.returncode == 1, proc.stdout + proc.stderr
        assert "fresh.py" in proc.stdout
        assert "blocking-under-lock" in proc.stdout
        # the committed twin is skipped — the hook is a fast path, not
        # the repo gate
        assert "old.py" not in proc.stdout

    def test_skips_clean_tree(self, tmp_path):
        self._git_repo(tmp_path)
        proc = self._hook(tmp_path)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "no Python files changed" in proc.stderr

    def test_clean_edit_passes(self, tmp_path):
        pkg, _ = self._git_repo(tmp_path)
        (pkg / "clean.py").write_text("X = 2\n")
        proc = self._hook(tmp_path)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "0 violations" in proc.stdout

    def test_config_wires_the_script(self):
        cfg = open(os.path.join(_REPO, ".pre-commit-config.yaml")).read()
        assert "scripts/precommit-lint" in cfg
        assert os.access(self._SCRIPT, os.X_OK), (
            "scripts/precommit-lint must be executable"
        )

    def test_documented_symlink_install(self, tmp_path):
        """Review regression: the documented
        ``ln -s ../../scripts/precommit-lint .git/hooks/pre-commit``
        install runs the hook as .git/hooks/pre-commit, where the old
        script-relative cd landed in .git/ and rejected every commit.
        Git runs hooks with cwd = repo toplevel; drill exactly that."""
        import shutil
        import subprocess
        import sys

        pkg, violation = self._git_repo(tmp_path)
        scripts = tmp_path / "scripts"
        scripts.mkdir()
        shutil.copy(self._SCRIPT, scripts / "precommit-lint")
        hook = tmp_path / ".git" / "hooks" / "pre-commit"
        hook.symlink_to("../../scripts/precommit-lint")

        def run_hook():
            env = dict(os.environ)
            env.pop("PRECOMMIT_ROOT", None)  # the real install has none
            env["PYTHON"] = sys.executable
            env["PYTHONPATH"] = _REPO + os.pathsep + env.get(
                "PYTHONPATH", ""
            )
            return subprocess.run(
                ["sh", str(hook), "pkg"],
                cwd=tmp_path,
                capture_output=True,
                text=True,
                timeout=120,
                env=env,
            )

        (pkg / "fresh.py").write_text(violation)
        proc = run_hook()
        assert proc.returncode == 1, proc.stdout + proc.stderr
        assert "fresh.py" in proc.stdout
        (pkg / "fresh.py").unlink()
        proc = run_hook()
        assert proc.returncode == 0, proc.stdout + proc.stderr
