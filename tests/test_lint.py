"""tpurun-lint unit tests: every pass fires on its planted fixture,
both suppression forms work (same-line and line-above), bare ignores
are errors, and the baseline round-trips (stale entries reported).

The repo-wide zero-violation gate lives in tests/test_lint_clean.py;
this file exercises the machinery against tests/lint_fixtures/.
"""

import json
import os

import pytest

from dlrover_tpu.analysis import Baseline, run_lint
from dlrover_tpu.analysis.cli import main as lint_main
from dlrover_tpu.analysis.passes import (
    ALL_PASSES,
    PASS_BY_ID,
    blocking_under_lock,
    endpoint_conformance,
    env_knobs,
    exception_swallow,
    host_sync,
    import_purity,
    injection_coverage,
    lock_order,
    rpc_deadline,
    thread_lifecycle,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_FIXTURES = os.path.join(_REPO, "tests", "lint_fixtures")


def _fx(name):
    return os.path.join(_FIXTURES, name)


def _run(path, lint_pass):
    return run_lint([path], passes=[lint_pass], repo_root=_REPO)


class TestPassesFireOnFixtures:
    def test_import_purity_fires(self):
        r = _run(_fx("fx_import_purity.py"), import_purity)
        assert len(r.violations) == 1, r.violations
        v = r.violations[0]
        assert v.pass_id == "import-purity"
        assert "jax_compilation_cache_dir" in v.code
        # the suppressed twin (line-above form) and only it
        assert len(r.suppressed) == 1
        assert not r.errors

    def test_import_purity_main_guard_and_functions_exempt(self):
        r = _run(_fx("fx_import_purity.py"), import_purity)
        flagged_lines = {v.line for v in r.violations} | {
            v.line for v, _s in r.suppressed
        }
        src = open(_fx("fx_import_purity.py")).readlines()
        for i, text in enumerate(src, start=1):
            if "fine_inside_a_function" in text or "__main__" in text:
                assert i not in flagged_lines

    def test_blocking_under_lock_fires(self):
        r = _run(_fx("fx_blocking_under_lock.py"), blocking_under_lock)
        assert len(r.violations) == 1, r.violations
        assert "sleep" in r.violations[0].message
        # same-line suppression on the untimed join
        assert len(r.suppressed) == 1
        assert "join" in r.suppressed[0][0].message
        assert not r.errors

    def test_host_sync_fires_on_marker_and_jit(self):
        r = _run(_fx("fx_host_sync.py"), host_sync)
        msgs = [v.message for v in r.violations]
        assert any("float()" in m and "dispatch_round" in m for m in msgs)
        assert any(".item()" in m and "jitted_body" in m for m in msgs)
        assert len(r.violations) == 2, r.violations
        # the drain point is suppressed; the cold path is not hot
        assert len(r.suppressed) == 1
        assert "device_get" in r.suppressed[0][0].message

    def test_host_sync_flags_per_call_heavy_import(self, tmp_path):
        p = tmp_path / "fx.py"
        p.write_text(
            "# tpulint: hotpath\n"
            "def step(state):\n"
            "    import jax\n"
            "    return state\n"
        )
        r = _run(str(p), host_sync)
        assert len(r.violations) == 1
        assert "per-call import" in r.violations[0].message

    def test_rpc_deadline_fires(self):
        r = _run(_fx("fx_rpc_deadline.py"), rpc_deadline)
        assert len(r.violations) == 1, r.violations
        assert "hard-coded deadline" in r.violations[0].message
        # urlopen with NO deadline is also a violation — suppressed here
        assert len(r.suppressed) == 1
        assert "no deadline" in r.suppressed[0][0].message

    def test_env_knobs_fires_on_unregistered_access(self):
        r = _run(_fx("fx_env_knobs.py"), env_knobs)
        assert len(r.violations) == 1, r.violations
        assert "DLROVER_NOT_A_REGISTERED_KNOB" in r.violations[0].message
        assert len(r.suppressed) == 1

    def test_bare_ignore_is_an_error(self):
        r = _run(_fx("fx_bad_suppression.py"), blocking_under_lock)
        assert not r.violations  # the site IS suppressed...
        assert r.errors and "needs a reason" in r.errors[0]
        assert not r.clean  # ...but the bare ignore fails the run

    def test_lock_order_fires_through_call_edge(self):
        r = _run(_fx("fx_lock_order.py"), lock_order)
        assert len(r.violations) == 1, [v.render() for v in r.violations]
        v = r.violations[0]
        assert v.pass_id == "lock-order"
        assert v.code.startswith("cycle:")
        # one arm of the planted cycle goes through self._touch_ledger()
        assert "_step_lock" in v.message and "_ledger_lock" in v.message
        # the suppressed-twin cycle (journal/ring) and only it
        assert len(r.suppressed) == 1
        assert "_journal_lock" in r.suppressed[0][0].message
        assert not r.errors

    def test_thread_lifecycle_fires(self):
        r = _run(_fx("fx_thread_lifecycle.py"), thread_lifecycle)
        assert len(r.violations) == 1, [v.render() for v in r.violations]
        assert "_leaked" in r.violations[0].message
        # the suppressed twin is the handed-off Popen
        assert len(r.suppressed) == 1
        assert "Popen" in r.suppressed[0][0].message
        assert not r.errors

    def test_exception_swallow_fires(self):
        r = _run(_fx("fx_exception_swallow.py"), exception_swallow)
        assert len(r.violations) == 1, [v.render() for v in r.violations]
        assert "swallows" in r.violations[0].message
        assert len(r.suppressed) == 1
        assert not r.errors

    def test_endpoint_conformance_fires(self):
        r = _run(_fx("fx_endpoint_conformance.py"), endpoint_conformance)
        assert len(r.violations) == 1, [v.render() for v in r.violations]
        assert r.violations[0].code == "client:/fx/drifted"
        # the dead route is the suppressed twin; the exact and
        # under-prefix clients are conformant
        assert len(r.suppressed) == 1
        assert r.suppressed[0][0].code == "route:/fx/dead-route"
        assert not r.errors


class TestInjectionCoveragePass:
    def _tree(self, tmp_path, tests_text):
        faults = tmp_path / "faults.py"
        faults.write_text(
            "INJECTION_POINTS = {\n"
            '    "covered.point": "a point with a drill",\n'
            '    "uncovered.point": "a point nobody exercises",\n'
            "}\n"
        )
        scenarios = tmp_path / "scenarios.py"
        scenarios.write_text(
            "def drill(w=None):\n    return {}\n\n"
            'SCENARIOS = {"my_drill": drill, "dusty_drill": drill}\n'
        )
        tests = tmp_path / "tests"
        tests.mkdir()
        (tests / "test_x.py").write_text(tests_text)
        return str(faults), str(tests), str(scenarios)

    def test_uncovered_point_and_unexercised_scenario_flagged(
        self, tmp_path
    ):
        faults, tests, scenarios = self._tree(
            tmp_path, 'def test_a():\n    fire("covered.point")\n'
        )
        got = list(
            injection_coverage.check_coverage(
                faults, tests, scenarios_path=scenarios
            )
        )
        codes = {v.code for v in got}
        assert "uncovered.point" in codes
        assert "scenario:my_drill" in codes
        assert "scenario:dusty_drill" in codes
        assert "covered.point" not in codes

    def test_point_covered_through_exercised_scenario(self, tmp_path):
        faults, tests, scenarios = self._tree(
            tmp_path,
            "def test_a():\n"
            '    run("my_drill"); run("dusty_drill")\n'
            '    fire("covered.point")\n',
        )
        # point the scenario file at the uncovered point: the scenario
        # is exercised, so the point counts as covered
        open(scenarios, "a").write('PLAN = "uncovered.point:error"\n')
        got = list(
            injection_coverage.check_coverage(
                faults, tests, scenarios_path=scenarios
            )
        )
        assert not got, [v.render() for v in got]


class TestBaseline:
    def _fixture_violations(self):
        return run_lint(
            [_FIXTURES], passes=list(ALL_PASSES), repo_root=_REPO
        )

    def test_round_trip(self, tmp_path):
        first = self._fixture_violations()
        assert first.violations  # the planted set
        path = str(tmp_path / "baseline.json")
        Baseline.from_violations(
            first.violations, reason="fixture grandfather"
        ).save(path)
        again = run_lint(
            [_FIXTURES],
            passes=list(ALL_PASSES),
            baseline=Baseline.load(path),
            repo_root=_REPO,
        )
        assert not again.violations
        assert again.baselined == len(first.violations)
        assert not again.stale_baseline
        # the bare-ignore error is NOT baselineable
        assert again.errors

    def test_stale_entry_reported(self, tmp_path):
        first = self._fixture_violations()
        bl = Baseline.from_violations(first.violations, reason="ok")
        bl.entries.append(
            type(bl.entries[0])(
                pass_id="host-sync",
                path="tests/lint_fixtures/fx_host_sync.py",
                code="this_line_was_fixed_long_ago()",
                reason="ghost of a fixed site",
            )
        )
        path = str(tmp_path / "baseline.json")
        bl.save(path)
        again = run_lint(
            [_FIXTURES],
            passes=list(ALL_PASSES),
            baseline=Baseline.load(path),
            repo_root=_REPO,
        )
        assert len(again.stale_baseline) == 1
        assert again.stale_baseline[0].code == "this_line_was_fixed_long_ago()"
        assert not again.clean

    def test_entry_without_reason_is_an_error(self, tmp_path):
        first = self._fixture_violations()
        bl = Baseline.from_violations(first.violations, reason="")
        path = str(tmp_path / "baseline.json")
        bl.save(path)
        again = run_lint(
            [_FIXTURES],
            passes=list(ALL_PASSES),
            baseline=Baseline.load(path),
            repo_root=_REPO,
        )
        assert any("no reason" in e for e in again.errors)


class TestCli:
    def test_list_passes(self, capsys):
        assert lint_main(["--list-passes"]) == 0
        out = capsys.readouterr().out
        for pid in PASS_BY_ID:
            assert pid in out

    def test_unknown_pass_is_usage_error(self, capsys):
        assert lint_main(["--select", "no-such-pass", _FIXTURES]) == 2

    def test_fixtures_fail_and_json_format(self, capsys):
        rc = lint_main(
            ["--no-baseline", "--format", "json", _FIXTURES]
        )
        assert rc == 1
        data = json.loads(capsys.readouterr().out)
        assert data["violations"] and not data["clean"]

    def test_write_baseline_then_clean(self, tmp_path, capsys):
        path = str(tmp_path / "bl.json")
        assert lint_main([_FIXTURES, "--write-baseline", path]) == 0
        capsys.readouterr()
        # violations are baselined now, but the bare ignore still fails
        rc = lint_main([_FIXTURES, "--baseline", path])
        out = capsys.readouterr().out
        assert "0 violations" in out
        assert rc == 1 and "needs a reason" in out


class TestSuppressionForms:
    def test_stacked_comment_lines_chain_up(self, tmp_path):
        p = tmp_path / "fx.py"
        p.write_text(
            "import time, threading\n"
            "_lock = threading.Lock()\n"
            "def f():\n"
            "    with _lock:\n"
            "        # tpulint: ignore[blocking-under-lock] long reason\n"
            "        # that wraps onto a second comment line\n"
            "        time.sleep(1)\n"
        )
        r = _run(str(p), blocking_under_lock)
        assert not r.violations and len(r.suppressed) == 1

    def test_suppression_for_other_pass_does_not_apply(self, tmp_path):
        p = tmp_path / "fx.py"
        p.write_text(
            "import time, threading\n"
            "_lock = threading.Lock()\n"
            "def f():\n"
            "    with _lock:\n"
            "        time.sleep(1)  # tpulint: ignore[host-sync] wrong pass\n"
        )
        r = _run(str(p), blocking_under_lock)
        assert len(r.violations) == 1 and not r.suppressed


class TestLockOrderMachinery:
    def test_closure_edges_participate(self, tmp_path):
        """The PR 8 drain threads are nested defs: a cycle whose second
        arm lives in a closure must still be found."""
        p = tmp_path / "fx.py"
        p.write_text(
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._a_lock = threading.Lock()\n"
            "        self._b_lock = threading.Lock()\n"
            "    def fwd(self):\n"
            "        with self._a_lock:\n"
            "            with self._b_lock:\n"
            "                pass\n"
            "    def spawn(self):\n"
            "        def drain():\n"
            "            with self._b_lock:\n"
            "                with self._a_lock:\n"
            "                    pass\n"
            "        threading.Thread(target=drain, daemon=True).start()\n"
        )
        r = _run(str(p), lock_order)
        assert len(r.violations) == 1, [v.render() for v in r.violations]
        assert "cycle" in r.violations[0].message

    def test_consistent_order_is_clean(self, tmp_path):
        p = tmp_path / "fx.py"
        p.write_text(
            "import threading\n"
            "_a_lock = threading.Lock()\n"
            "_b_lock = threading.Lock()\n"
            "def f():\n"
            "    with _a_lock:\n"
            "        with _b_lock:\n"
            "            pass\n"
            "def g():\n"
            "    with _a_lock:\n"
            "        with _b_lock:\n"
            "            pass\n"
        )
        r = _run(str(p), lock_order)
        assert not r.violations

    def test_reentrant_same_lock_is_not_a_cycle(self, tmp_path):
        p = tmp_path / "fx.py"
        p.write_text(
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._rlock = threading.RLock()\n"
            "    def outer(self):\n"
            "        with self._rlock:\n"
            "            self.inner()\n"
            "    def inner(self):\n"
            "        with self._rlock:\n"
            "            pass\n"
        )
        r = _run(str(p), lock_order)
        assert not r.violations

    def test_transitive_call_chain_closes_cycle(self, tmp_path):
        """a held -> call f -> call g -> acquires b; elsewhere b->a."""
        p = tmp_path / "fx.py"
        p.write_text(
            "import threading\n"
            "_a_lock = threading.Lock()\n"
            "_b_lock = threading.Lock()\n"
            "def top():\n"
            "    with _a_lock:\n"
            "        mid()\n"
            "def mid():\n"
            "    leaf()\n"
            "def leaf():\n"
            "    with _b_lock:\n"
            "        pass\n"
            "def reverse():\n"
            "    with _b_lock:\n"
            "        with _a_lock:\n"
            "            pass\n"
        )
        r = _run(str(p), lock_order)
        assert len(r.violations) == 1, [v.render() for v in r.violations]


class TestThreadLifecycleMachinery:
    def test_handle_passed_to_reaper_counts(self, tmp_path):
        p = tmp_path / "fx.py"
        p.write_text(
            "import subprocess\n"
            "class C:\n"
            "    def launch(self):\n"
            "        self._proc = subprocess.Popen(['true'])\n"
            "    def stop(self):\n"
            "        kill_process_group(self._proc, grace_s=5)\n"
        )
        r = _run(str(p), thread_lifecycle)
        assert not r.violations

    def test_killpg_on_pid_is_not_a_reap(self, tmp_path):
        """The warm-spare bug shape: os.killpg(getpgid(pid)) never
        waits — the handle itself is unreaped."""
        p = tmp_path / "fx.py"
        p.write_text(
            "import os, signal, subprocess\n"
            "class C:\n"
            "    def launch(self):\n"
            "        self._proc = subprocess.Popen(['true'])\n"
            "    def stop(self):\n"
            "        os.killpg(os.getpgid(self._proc.pid), signal.SIGKILL)\n"
        )
        r = _run(str(p), thread_lifecycle)
        assert len(r.violations) == 1
        assert "_proc" in r.violations[0].message

    def test_loop_over_container_join_counts(self, tmp_path):
        p = tmp_path / "fx.py"
        p.write_text(
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._threads = []\n"
            "    def go(self):\n"
            "        self._threads.append(threading.Thread(target=int))\n"
            "    def stop(self):\n"
            "        for t in self._threads:\n"
            "            t.join(timeout=5)\n"
        )
        r = _run(str(p), thread_lifecycle)
        assert not r.violations

    def test_untimed_join_does_not_satisfy(self, tmp_path):
        p = tmp_path / "fx.py"
        p.write_text(
            "import threading\n"
            "class C:\n"
            "    def go(self):\n"
            "        self._t = threading.Thread(target=int)\n"
            "    def stop(self):\n"
            "        self._t.join()\n"
        )
        r = _run(str(p), thread_lifecycle)
        assert len(r.violations) == 1


class TestExceptionSwallowMachinery:
    def test_broad_in_tuple_is_flagged(self, tmp_path):
        p = tmp_path / "fx.py"
        p.write_text(
            "def f():\n"
            "    try:\n"
            "        g()\n"
            "    except (ValueError, Exception):\n"
            "        pass\n"
        )
        r = _run(str(p), exception_swallow)
        assert len(r.violations) == 1

    def test_handler_in_nested_def_does_not_count(self, tmp_path):
        """A log call inside a nested def runs later, if ever — the
        handler still swallows."""
        p = tmp_path / "fx.py"
        p.write_text(
            "import logging\n"
            "def f():\n"
            "    try:\n"
            "        g()\n"
            "    except Exception:\n"
            "        def later():\n"
            "            logging.warning('x')\n"
            "        keep = later\n"
        )
        r = _run(str(p), exception_swallow)
        assert len(r.violations) == 1

    def test_counter_bump_counts(self, tmp_path):
        p = tmp_path / "fx.py"
        p.write_text(
            "def f(stats):\n"
            "    try:\n"
            "        g()\n"
            "    except Exception:\n"
            "        stats['fail'] += 1\n"
        )
        r = _run(str(p), exception_swallow)
        assert not r.violations


class TestEndpointConformanceMachinery:
    def _ctx(self, tmp_path, name, source):
        from dlrover_tpu.analysis.core import FileContext

        p = tmp_path / name
        p.write_text(source)
        return FileContext.parse(str(p), name)

    def test_route_referenced_only_by_docs_is_clean(self, tmp_path):
        server = self._ctx(
            tmp_path,
            "server.py",
            "class H:\n"
            "    def do_GET(self):\n"
            "        if self.path == '/fx/status':\n"
            "            pass\n",
        )
        got = list(
            endpoint_conformance.check_conformance(
                [server], "curl the `/fx/status` endpoint"
            )
        )
        assert not got
        got = list(endpoint_conformance.check_conformance([server], ""))
        assert len(got) == 1 and got[0].code == "route:/fx/status"

    def test_helper_call_path_not_first_arg(self, tmp_path):
        """The gateway shape: _post_replica(h, '/v1/x', payload)."""
        client = self._ctx(
            tmp_path,
            "client.py",
            "class C:\n"
            "    def go(self, h):\n"
            "        self._post_replica(h, '/fx/x', {})\n",
        )
        got = list(endpoint_conformance.check_conformance([client], ""))
        assert len(got) == 1 and got[0].code == "client:/fx/x"

    def test_fstring_url_tail_collected(self, tmp_path):
        client = self._ctx(
            tmp_path,
            "client.py",
            "def go(host, port):\n"
            "    url = f'http://{host}:{port}/fx/poll'\n"
            "    return url\n",
        )
        got = list(endpoint_conformance.check_conformance([client], ""))
        assert len(got) == 1 and got[0].code == "client:/fx/poll"

    def test_filesystem_paths_are_not_clients(self, tmp_path):
        client = self._ctx(
            tmp_path,
            "client.py",
            "import os\n"
            "def go(base_dir):\n"
            "    return os.path.join(base_dir, '/tmp/x.json')\n",
        )
        got = list(endpoint_conformance.check_conformance([client], ""))
        assert not got


class TestChangedMode:
    def _git_repo(self, tmp_path):
        import subprocess

        def git(*args):
            subprocess.run(
                ["git", "-c", "user.email=t@t", "-c", "user.name=t"]
                + list(args),
                cwd=tmp_path,
                check=True,
                capture_output=True,
            )

        (tmp_path / "pyproject.toml").write_text("[project]\n")
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        violation = (
            "import threading, time\n"
            "_lock = threading.Lock()\n"
            "def f():\n"
            "    with _lock:\n"
            "        time.sleep(1)\n"
        )
        (pkg / "old.py").write_text(violation)
        (pkg / "other.py").write_text("X = 1\n")
        git("init", "-q")
        git("add", "-A")
        git("commit", "-qm", "seed")
        return pkg, violation

    def test_changed_lints_only_changed_files(self, tmp_path, capsys):
        pkg, violation = self._git_repo(tmp_path)
        # old.py's committed violation must NOT be reported; the fresh
        # edit to other.py must be
        (pkg / "other.py").write_text(violation)
        rc = lint_main(["--changed", "--no-baseline", str(pkg)])
        out = capsys.readouterr().out
        assert rc == 1
        assert "other.py" in out and "old.py" not in out
        assert "skips repo-wide passes" in out

    def test_changed_with_no_edits_is_clean(self, tmp_path, capsys):
        pkg, _ = self._git_repo(tmp_path)
        rc = lint_main(["--changed", "--no-baseline", str(pkg)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "no Python files changed" in out

    def test_changed_sees_untracked_files(self, tmp_path, capsys):
        pkg, violation = self._git_repo(tmp_path)
        (pkg / "fresh.py").write_text(violation)
        rc = lint_main(["--changed", "--no-baseline", str(pkg)])
        out = capsys.readouterr().out
        assert rc == 1 and "fresh.py" in out

    def test_changed_rejects_write_baseline(self, tmp_path, capsys):
        """A subset run must not silently truncate the repo-wide
        baseline file."""
        pkg, _ = self._git_repo(tmp_path)
        rc = lint_main(
            [
                "--changed",
                "--write-baseline",
                str(tmp_path / "bl.json"),
                str(pkg),
            ]
        )
        assert rc == 2
        assert "cannot be combined" in capsys.readouterr().err

    def test_changed_with_only_repo_passes_is_usage_error(
        self, tmp_path, capsys
    ):
        """--select naming only repo-wide passes + --changed must not
        exit 0 having checked nothing."""
        pkg, violation = self._git_repo(tmp_path)
        (pkg / "other.py").write_text(violation)
        rc = lint_main(
            ["--changed", "--select", "endpoint-conformance", str(pkg)]
        )
        assert rc == 2
        assert "no runnable pass" in capsys.readouterr().err


class TestReviewRegressions:
    """Review findings on PR 6 itself: the staleness rule must not be
    satisfied by the registry's own declaration, bare ignores on
    repo-level violations are errors too, and the CLI refuses to
    green-light a typo'd path."""

    def _fake_tree(self, tmp_path, mod_source):
        (tmp_path / "pyproject.toml").write_text("[project]\n")
        common = tmp_path / "dlrover_tpu" / "common"
        common.mkdir(parents=True)
        (common / "constants.py").write_text(
            "class K:\n"
            "    def __init__(self, name, internal=False,"
            " context_field=''):\n"
            "        self.name = name\n"
            "        self.internal = internal\n"
            "        self.context_field = context_field\n"
            "\n"
            "class NodeEnv:\n"
            "    ATTR_ONLY = 'DLROVER_ATTR_ONLY'\n"
            "\n"
            "ENV_KNOBS = {k.name: k for k in [\n"
            "    K('DLROVER_USED', internal=True),\n"
            "    K('DLROVER_GHOST', internal=True),\n"
            "    K('DLROVER_ATTR_ONLY', internal=True),\n"
            "]}\n"
        )
        (tmp_path / "dlrover_tpu" / "mod.py").write_text(mod_source)
        return tmp_path

    def test_registry_self_reference_does_not_hide_staleness(
        self, tmp_path
    ):
        root = self._fake_tree(
            tmp_path,
            "import os\n"
            "A = os.getenv('DLROVER_USED')\n"
            "from .common.constants import NodeEnv\n"
            "B = os.getenv(NodeEnv.ATTR_ONLY)\n",
        )
        r = run_lint(
            [str(root / "dlrover_tpu")],
            passes=[env_knobs],
            repo_root=str(root),
        )
        codes = {v.code for v in r.violations}
        # GHOST appears ONLY in ENV_KNOBS itself -> stale; USED is
        # referenced by literal, ATTR_ONLY through the NodeEnv attr
        assert "stale:DLROVER_GHOST" in codes, [
            v.render() for v in r.violations
        ]
        assert "stale:DLROVER_USED" not in codes
        assert "stale:DLROVER_ATTR_ONLY" not in codes

    def test_bare_ignore_on_repo_level_violation_is_an_error(
        self, tmp_path
    ):
        root = self._fake_tree(
            tmp_path,
            "X = 'DLROVER_TYPO_KNOB'  # tpulint: ignore[env-knobs]\n"
            "import os\n"
            "A = os.getenv('DLROVER_USED')\n"
            "B = 'DLROVER_ATTR_ONLY'\n",
        )
        r = run_lint(
            [str(root / "dlrover_tpu")],
            passes=[env_knobs],
            repo_root=str(root),
        )
        assert any(
            v.pass_id == "env-knobs" for v, _s in r.suppressed
        ), [v.render() for v in r.violations]
        assert any("needs a reason" in e for e in r.errors)
        assert not r.clean

    def test_cli_rejects_nonexistent_path(self, capsys):
        assert lint_main(["definitely_no_such_dir_xyz"]) == 2
        assert "do not exist" in capsys.readouterr().err

    def test_cli_rejects_pathless_lint(self, tmp_path, capsys):
        empty = tmp_path / "empty"
        empty.mkdir()
        assert lint_main([str(empty)]) == 2
        assert "no Python files" in capsys.readouterr().err
