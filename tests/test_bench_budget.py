"""Bench orchestrator budget accounting (r5).

The chip watcher kills bench at --bench-timeout; bench must therefore
never START a TPU attempt it cannot finish inside the shared budget
(DLROVER_BENCH_TOTAL_BUDGET_S) — a worker killed mid-run emits no JSON
line, producing the unparseable artifact r4 was dinged for. These
tests pin the attempt-gating arithmetic with fake worker commands.
"""

import json
import sys
import time


import os

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _bench():
    sys.path.insert(0, _REPO)
    import bench

    return bench


def test_exhausted_budget_skips_all_attempts():
    bench = _bench()
    history = []
    # deadline leaves less than MIN_TPU_ATTEMPT_S after the CPU
    # reserve: every attempt must be skipped without spawning anything
    deadline = (
        time.time() + bench.CPU_WORKER_TIMEOUT_S + 180.0
        + bench.MIN_TPU_ATTEMPT_S / 2
    )
    t0 = time.time()
    parsed = bench._try_tpu_worker(
        [sys.executable, "-c", "import time; time.sleep(60)"],
        {},
        history,
        deadline,
    )
    assert parsed is None
    assert time.time() - t0 < 5.0  # nothing was spawned
    notes = [h.get("note", "") for h in history]
    assert any("budget exhausted" in n for n in notes)


def test_ample_budget_runs_attempt_and_parses():
    bench = _bench()
    history = []
    line = json.dumps(
        {"metric": "m", "value": 1.0, "unit": "u", "vs_baseline": 1.0}
    )
    deadline = time.time() + 10_000.0
    parsed = bench._try_tpu_worker(
        [sys.executable, "-c", f"print({line!r})"], {}, history, deadline
    )
    assert parsed is not None and parsed["value"] == 1.0
    assert parsed["extra"]["tpu_attempt"] == "plain"


def test_concurrent_reserve_allows_late_attempt():
    """Once the CPU fallback runs concurrently, only a finishing
    margin is held back — a deadline too tight for the serial reserve
    still admits a silicon attempt."""
    bench = _bench()
    line = json.dumps(
        {"metric": "m", "value": 3.0, "unit": "u", "vs_baseline": 1.0}
    )
    deadline = time.time() + bench.MIN_TPU_ATTEMPT_S + 120.0
    cmd = [sys.executable, "-c", f"print({line!r})"]
    # serial default reserve: gated off
    hist = []
    assert bench._try_tpu_worker(cmd, {}, hist, deadline) is None
    assert any("budget exhausted" in h.get("note", "") for h in hist)
    # concurrent margin: admitted
    parsed = bench._try_tpu_worker(cmd, {}, [], deadline, cpu_reserve=60.0)
    assert parsed is not None and parsed["value"] == 3.0


def test_no_deadline_is_unbounded():
    bench = _bench()
    line = json.dumps(
        {"metric": "m", "value": 2.0, "unit": "u", "vs_baseline": 1.0}
    )
    parsed = bench._try_tpu_worker(
        [sys.executable, "-c", f"print({line!r})"], {}, [], None
    )
    assert parsed is not None and parsed["value"] == 2.0
