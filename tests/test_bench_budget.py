"""Bench orchestrator budget accounting (r5).

The chip watcher kills bench at --bench-timeout; bench must therefore
never START a TPU attempt it cannot finish inside the shared budget
(DLROVER_BENCH_TOTAL_BUDGET_S) — a worker killed mid-run emits no JSON
line, producing the unparseable artifact r4 was dinged for. These
tests pin the attempt-gating arithmetic with fake worker commands.
"""

import json
import sys
import time


import os

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _bench():
    sys.path.insert(0, _REPO)
    import bench

    return bench


def test_exhausted_budget_skips_all_attempts():
    bench = _bench()
    history = []
    # deadline leaves less than MIN_TPU_ATTEMPT_S after the CPU
    # reserve: every attempt must be skipped without spawning anything
    deadline = (
        time.time() + bench.CPU_WORKER_TIMEOUT_S + 180.0
        + bench.MIN_TPU_ATTEMPT_S / 2
    )
    t0 = time.time()
    parsed = bench._try_tpu_worker(
        [sys.executable, "-c", "import time; time.sleep(60)"],
        {},
        history,
        deadline,
    )
    assert parsed is None
    assert time.time() - t0 < 5.0  # nothing was spawned
    notes = [h.get("note", "") for h in history]
    assert any("budget exhausted" in n for n in notes)


def test_ample_budget_runs_attempt_and_parses():
    bench = _bench()
    history = []
    line = json.dumps(
        {"metric": "m", "value": 1.0, "unit": "u", "vs_baseline": 1.0}
    )
    deadline = time.time() + 10_000.0
    parsed = bench._try_tpu_worker(
        [sys.executable, "-c", f"print({line!r})"], {}, history, deadline
    )
    assert parsed is not None and parsed["value"] == 1.0
    assert parsed["extra"]["tpu_attempt"] == "plain"


def test_concurrent_reserve_allows_late_attempt():
    """Once the CPU fallback runs concurrently, only a finishing
    margin is held back — a deadline too tight for the serial reserve
    still admits a silicon attempt."""
    bench = _bench()
    line = json.dumps(
        {"metric": "m", "value": 3.0, "unit": "u", "vs_baseline": 1.0}
    )
    deadline = time.time() + bench.MIN_TPU_ATTEMPT_S + 120.0
    cmd = [sys.executable, "-c", f"print({line!r})"]
    # serial default reserve: gated off
    hist = []
    assert bench._try_tpu_worker(cmd, {}, hist, deadline) is None
    assert any("budget exhausted" in h.get("note", "") for h in hist)
    # concurrent margin: admitted
    parsed = bench._try_tpu_worker(cmd, {}, [], deadline, cpu_reserve=60.0)
    assert parsed is not None and parsed["value"] == 3.0


def test_no_deadline_is_unbounded():
    bench = _bench()
    line = json.dumps(
        {"metric": "m", "value": 2.0, "unit": "u", "vs_baseline": 1.0}
    )
    parsed = bench._try_tpu_worker(
        [sys.executable, "-c", f"print({line!r})"], {}, [], None
    )
    assert parsed is not None and parsed["value"] == 2.0


# ---------------------------------------------------------------------------
# Byte budget (VERDICT r5 #2): the ONE emitted line must stay parseable
# inside the driver's ~2,000-char window. The shrink is exercised on the
# WORST case: both LATEST artifacts merged, 10 probe entries, every
# bench section populated.
# ---------------------------------------------------------------------------


def _worst_case_extra(bench, tmp_path, monkeypatch):
    monkeypatch.setattr(bench, "_REPO_DIR", str(tmp_path))
    # realistic committed artifacts (shapes from the r5 round)
    with open(tmp_path / "SILICON_LATEST.json", "w") as f:
        json.dump(
            {
                "ts": 1785575775, "git_sha": "6e56865",
                "artifact": "SILICON_r05_1785575775.json",
                "metric": "gpt2s_train_tokens_per_s", "value": 114100.0,
                "unit": "tokens/s", "vs_baseline": 1.58,
                "device": "TPU_v5e(chip=0)",
                "headline": {
                    k: 0.1 * i
                    for i, k in enumerate(
                        (
                            "mfu", "flash_step_s", "flash_batch",
                            "seq_len", "flash_seq4096_tflops",
                            "decode_tokens_per_s",
                            "generate_tokens_per_s",
                            "llama_tokens_per_s", "moe_tokens_per_s",
                            "spec_tokens_per_s", "spec_acceptance",
                            "longseq_train_tokens_per_s",
                            "ckpt_async_stage_block_s",
                            "goodput_ckpt_every_10_steps",
                            "serving_per_row_tokens_per_s",
                            "serving_host_frac",
                        )
                    )
                },
            },
            f,
        )
    with open(tmp_path / "HANG_DIAGNOSIS_LATEST.json", "w") as f:
        json.dump(
            {
                "ts": 1785692011, "git_sha": "01f7eac",
                "artifact": "HANG_DIAGNOSIS_r05_1785692011.json",
                "phase": "reg",
                "classification": (
                    "pjrt_client_init_hang (zero device activity; host "
                    "wedged creating the PJRT client — tunnel dial "
                    "never completed)"
                ),
                "wedge_frame": 'File "axon/register.py", line 88',
                "stall_verdict": None,
                "stall_verdict_name": "unknown",
                "interposer_metrics": {
                    "tpu_timer_device_launches_total": 0.0
                },
                "stack_excerpt": "x" * 600,
            },
            f,
        )
    # every section populated: ~90 keys the real worker can emit
    extra = {"device": "TPU_v5e(chip=0) at tunnel", "model": "gpt2-small-124M"}
    sections = (
        "flash_step_s flash_batch seq_len mfu dense_step_s dense_batch "
        "dense_tokens_per_s flash_vs_dense headline_config ckpt_bytes "
        "flash_ckpt_save_block_s ckpt_save_block_s ckpt_async_stage_block_s "
        "ckpt_save_vs_target restore_s h2d_floor_s restore_overhead_x "
        "goodput_ckpt_every_10_steps durable_save_block_s "
        "durable_restore_s durable_block_vs_flash_x "
        "flash_seq4096_ms flash_seq4096_tflops "
        "flash_seq4096_dispatch_floor_ms generate_tokens_per_s decode_batch "
        "decode_prompt_len decode_new_tokens decode_ms_per_step "
        "decode_tokens_per_s prefill_ms decode_int8_ms_per_step "
        "decode_int8_tokens_per_s decode_int8_vs_bf16 spec_tokens_per_s "
        "spec_acceptance spec_self_acceptance spec_self_acceptance_f32 "
        "spec_vs_plain serving_weight_adopt_s serving_stream_tokens_per_s "
        "serving_homogeneous_tokens_per_s serving_mixed_vs_homogeneous "
        "serving_weight_swap_s serving_batch_slots serving_requests "
        "serving_per_row_tokens_per_s serving_per_row_vs_frontier "
        "serving_sync_tokens_per_s serving_overlap_tokens_per_s "
        "serving_overlap_vs_sync serving_overlap_hidden_ms "
        "serving_overlap_slots serving_auto_chunk_final "
        "serving_auto_chunk_retunes interposer_overhead_pct "
        "interposer_plain_step_s flash_base_step_s "
        "serving_spec_tokens_per_s serving_spec_acceptance "
        "serving_spec_vs_per_row serving_int8_2x_slots_tokens_per_s "
        "serving_int8_2x_vs_per_row serving_host_frac "
        "attr_top_residual_frac attr_matmul_frac llama_tokens_per_s "
        "llama_step_s moe_tokens_per_s moe_step_s longseq_train_tokens_per_s "
        "longseq_train_mfu fused_ce_b32_step_s fused_ce_b32_tokens_per_s "
        "fused_ce_b64_step_s fused_ce_b64_tokens_per_s remat_dots_step_s "
        "remat_dots_tokens_per_s no_remat_step_s no_remat_tokens_per_s "
        "batch48_step_s batch48_tokens_per_s batch64_step_s "
        "batch64_tokens_per_s worker_rc"
    ).split()
    for i, k in enumerate(sections):
        extra[k] = round(1234.5678 + i, 4)
    extra["serving_overlap_exact"] = True
    extra["ckpt_note"] = "c" * 220  # the artifact-note string rides extra
    extra["section_retry"] = {
        "sections": ["ckpt", "serving"], "cleared": ["ckpt_error"],
        "retry_on_tpu": True, "elapsed_s": 812.4,
    }
    extra["headline_config"] = "flash+fused_ce+remat_dots+b64"
    extra["tpu_attempt"] = "interposed"
    extra["attr_report"] = "BENCH_attr_1785575775_1234.json"
    extra["attr_ring"] = "BENCH_attr_ring_1785575775_1234.timeline"
    extra["attr_top_residual"] = "optimizer_hbm"
    extra["hbm_live_mb"] = {
        n: 1234.5 for n in (
            "post_dense", "post_ckpt", "post_serving", "post_llama",
            "post_longseq",
        )
    }
    extra["interposed"] = {
        "execute_count": 50000.0, "execute_avg_us": 3300.0,
        "execute_max_us": 410000.0, "h2d_count": 900.0,
        "compile_count": 44.0, "device_completes": 50000.0,
        "stall_verdict": 0.0,
    }
    # slice-storm recovery-SLO matrix (full dict incl. stall forensics
    # rides extra/sidecar; the storm_* scalars must survive in-line)
    extra["goodput_storm"] = {
        "goodput": 0.83, "training_goodput": 0.95, "steps": 520,
        "kills": 4, "elapsed_s": 812.2, "steps_per_second": 0.71,
        "boot_s": 24.3, "mttr_s": 11.4, "slice_mttr_s": 17.9,
        "slice_goodput": 0.88, "slice_relaunches": 3,
        "rdzv_s": 2.1, "restore_s": 0.4, "compile_s": 6.2,
        "first_step_s": 7.0, "recovery_samples": 4,
        # incident-trace phase breakdown (docs/observability.md)
        "mttd_s": 0.8, "detect_s": 0.8, "rendezvous_s": 2.0,
        "reshard_s": 0.5, "recompile_s": 6.1, "trace_mttr_s": 9.4,
        "trace_incidents": 4,
        "stalls": [
            {"at_step": 100 + 30 * i, "gap_s": 12.5, "kill": True,
             "kind": "slice" if i % 2 else "host"}
            for i in range(8)
        ],
    }
    extra["storm_goodput"] = 0.83
    extra["storm_mttr_s"] = 11.4
    extra["storm_slice_mttr_s"] = 17.9
    extra["storm_slice_goodput"] = 0.88
    # MTTR phase breakdown + warm-vs-cold recovery A/B (docs/recovery.md):
    # the full two-leg dict is sidecar-class; the scalars ride the line
    extra["storm_rdzv_s"] = 2.1
    extra["storm_restore_s"] = 0.4
    extra["storm_compile_s"] = 6.2
    extra["storm_first_step_s"] = 7.0
    # trace-derived detection SLOs (docs/observability.md): MTTD + the
    # detect phase share ride the line; the remaining trace phase
    # scalars stay inside the sidecar's goodput_storm dict
    extra["storm_mttd_s"] = 0.8
    extra["storm_detect_s"] = 0.8
    extra["recovery_ab"] = {
        "cold": dict(extra["goodput_storm"], compile_s=12.1),
        "warm": dict(extra["goodput_storm"], compile_s=0.3),
        "mttr_delta_s": 11.8, "cold_compile_s": 12.1,
        "warm_compile_s": 0.3,
    }
    extra["recovery_cold_mttr_s"] = 22.9
    extra["recovery_warm_mttr_s"] = 11.1
    extra["recovery_mttr_delta_s"] = 11.8
    extra["recovery_cold_compile_s"] = 12.1
    extra["recovery_warm_compile_s"] = 0.3
    # master crash tolerance (docs/recovery.md master failover): the
    # MTTR + goodput scalars must survive in-line; the full drill dict
    # (epoch, replay_s, restart audit) is sidecar-class
    extra["master_kill"] = {
        "master_mttr_s": 3.4, "master_kill_goodput": 0.91,
        "steps": 42, "epoch": 2, "worker_restarts": 0,
        "kv_survived": True, "master_replay_s": 0.012,
        "master_boot_samples": 1, "reattach_s": 0.05,
        "rdzv_s": 0.0, "restore_s": 0.0, "compile_s": 0.0,
        "first_step_s": 0.0, "recovery_samples": 0,
    }
    extra["master_mttr_s"] = 3.4
    extra["master_kill_goodput"] = 0.91
    extra["master_kill_worker_restarts"] = 0
    # serving-fleet section (docs/serving_fleet.md): the SLO trio must
    # survive in-line; the supporting scalars may shrink to the sidecar
    extra["fleet_requests_per_s"] = 8.42
    extra["fleet_1rep_requests_per_s"] = 4.91
    extra["fleet_2v1_x"] = 1.715
    extra["fleet_kill_availability"] = 1.0
    extra["fleet_kill_redispatches"] = 3
    extra["fleet_rollout_max_unready"] = 1
    extra["fleet_rollout_aborted"] = False
    extra["fleet_rollout_load_failed"] = 0
    extra["fleet_ready"] = 2
    # paged-KV serving section (docs/serving_fleet.md paged memory):
    # the throughput/p95/hit-rate trio must survive in-line; the dense
    # leg and occupancy scalars may shrink to the sidecar
    extra["fleet_paged_tokens_per_s"] = 1613.5
    extra["fleet_paged_p95_s"] = 0.0559
    extra["prefix_hit_rate"] = 0.792
    extra["fleet_dense_tokens_per_s"] = 390.0
    extra["fleet_dense_p95_s"] = 0.2392
    extra["fleet_paged_vs_dense_x"] = 4.138
    extra["fleet_affinity_hits"] = 9
    extra["fleet_blocks_total"] = 30
    extra["fleet_blocks_free"] = 30
    # chip-pool section (docs/pool.md): the SLO trio must survive
    # in-line; the supporting scalars may shrink to the sidecar
    extra["pool_preempt_to_ready_s"] = 0.54
    extra["pool_spike_availability"] = 1.0
    extra["pool_train_goodput"] = 0.62
    extra["pool_handback"] = True
    extra["pool_requests_ok"] = 212
    extra["pool_revokes"] = 2
    extra["pool_escalations"] = 0
    extra["pool_recovered_vs_baseline"] = 0.98
    extra["pool_window_s"] = 10.4
    # multi-tenant cluster section (docs/cluster.md): the SLO trio must
    # survive in-line; the supporting scalars may shrink to the sidecar
    extra["cluster_inversion_avail"] = 1.0
    extra["cluster_preempt_cascade_s"] = 0.41
    extra["cluster_brain_adopt_s"] = 0.22
    extra["cluster_first_victim"] = "train_lo"
    extra["cluster_adoptions"] = 2
    extra["cluster_revokes"] = 2
    extra["cluster_escalations"] = 0
    extra["cluster_handback"] = True
    extra["cluster_one_trace"] = True
    # elastic hybrid-parallelism section (docs/elastic_parallelism.md):
    # the DP↔PP trade trio must survive in-line; the transition label
    # and the rung's accum may shrink to the sidecar
    extra["dp_pp_trade_mttr_s"] = 0.327
    extra["reshard_s"] = 0.311
    extra["hybrid_vs_accum_goodput_x"] = 1.7778
    extra["elastic_transition"] = "dp8 -> dp2·pp2"
    extra["elastic_rung_accum"] = 4
    bench._merge_committed_artifacts(extra)
    extra["probe_history"] = [
        {
            "ts": 1785575700 + i, "rc": -9, "duration_s": 180.0,
            "phase": "none", "platform": "",
            "last_stderr": "y" * bench.STDERR_MAX,
        }
        for i in range(10)
    ]
    extra["probe_sidecar"] = "BENCH_probe_sidecar_1785575775_1234.json"
    extra["probe_history_watcher"] = {
        "attempts": 120, "ok": 3, "first_ts": 1785500000,
        "last_ts": 1785575775, "span_s": 75775,
        "last": {"ts": 1785575775, "rc": -9, "phase": "none"},
    }
    return extra


def test_merge_committed_artifacts_is_pointers_not_payloads(
    tmp_path, monkeypatch
):
    bench = _bench()
    extra = _worst_case_extra(bench, tmp_path, monkeypatch)
    # the merged records are POINTERS: artifact + sha + a handful of
    # floats, bounded regardless of what the LATEST files hold
    assert extra["last_silicon"]["artifact"].startswith("SILICON_r05")
    assert extra["last_silicon"]["git_sha"] == "6e56865"
    assert len(json.dumps(extra["last_silicon"])) < 400
    assert extra["hang_diagnosis"]["artifact"].startswith("HANG_")
    assert "stack_excerpt" not in extra["hang_diagnosis"]
    assert len(json.dumps(extra["hang_diagnosis"])) < 300


def test_line_budget_worst_case(tmp_path, monkeypatch):
    """Both LATEST artifacts merged + 10 probe entries + every section
    populated: the emitted line must stay ≤ 1,800 bytes with the vital
    keys in-line and the complete extra in the sidecar."""
    bench = _bench()
    extra = _worst_case_extra(bench, tmp_path, monkeypatch)
    result = {
        "metric": bench.METRIC, "value": 114100.0, "unit": "tokens/s",
        "vs_baseline": 1.58, "extra": extra,
    }
    assert len(json.dumps(result)) > bench.LINE_BUDGET_BYTES  # truly worst
    line = bench._shrink_to_budget(result)
    s = json.dumps(line)
    assert len(s) <= bench.LINE_BUDGET_BYTES, len(s)
    # the driver's contract fields are intact
    assert line["metric"] == bench.METRIC and line["value"] == 114100.0
    assert line["vs_baseline"] == 1.58
    # the vital keys survived in-line
    slim = line["extra"]
    assert slim["line_truncated"] is True
    assert slim["mfu"] == extra["mfu"]
    assert slim["serving_host_frac"] == extra["serving_host_frac"]
    # the overlap A/B verdict (PR 2 headline rung) must ride the line
    assert slim["serving_overlap_vs_sync"] == (
        extra["serving_overlap_vs_sync"]
    )
    assert slim["serving_overlap_exact"] is True
    assert slim["interposer_overhead_pct"] == (
        extra["interposer_overhead_pct"]
    )
    # the host-fault recovery headline rides the line as pointer-style
    # scalars (the full storm dict with its stall list stays
    # sidecar-only)
    assert slim["storm_mttr_s"] == extra["storm_mttr_s"]
    assert slim["storm_goodput"] == extra["storm_goodput"]
    # the MTTR phase breakdown, the detect phase share, and the
    # warm-vs-cold A/B verdict pair moved sidecar-only to seat the
    # paged-KV trio (the first three re-derive from the sidecar's
    # goodput_storm dict — same class as storm_restore_s /
    # storm_first_step_s before them — the A/B pair from recovery_ab);
    # the slice row of the matrix (storm_slice_mttr_s /
    # storm_slice_goodput) and the flash_step_s / headline_config pair
    # moved sidecar-only to seat the cluster trio (slice row from
    # goodput_storm, the pair from the SILICON headline dict)
    for key in (
        "storm_rdzv_s", "storm_compile_s", "storm_detect_s",
        "recovery_mttr_delta_s", "recovery_warm_compile_s",
        "storm_slice_mttr_s", "storm_slice_goodput",
        "flash_step_s", "headline_config",
    ):
        assert key not in slim, key
    assert "recovery_ab" not in slim
    # the detection headline still rides the line
    assert slim["storm_mttd_s"] == extra["storm_mttd_s"]
    # the master-kill SLO pair rides the line; the full drill dict is
    # sidecar-only
    assert slim["master_mttr_s"] == extra["master_mttr_s"]
    assert slim["master_kill_goodput"] == extra["master_kill_goodput"]
    assert "master_kill" not in slim
    # the durable-tier SLO pair rides the line; the supporting ratio
    # (durable_block_vs_flash_x) is sidecar-recoverable
    assert slim["durable_save_block_s"] == extra["durable_save_block_s"]
    assert slim["durable_restore_s"] == extra["durable_restore_s"]
    # the fleet SLO trio rides the line (fleet_2v1_x and the per-rep
    # rate are sidecar-recoverable, like the A/B per-leg scalars)
    for key in (
        "fleet_requests_per_s", "fleet_kill_availability",
        "fleet_rollout_max_unready",
    ):
        assert slim[key] == extra[key], key
    # the paged-KV trio rides the line (the dense leg, the speedup
    # ratio, and block occupancy are sidecar-recoverable)
    for key in (
        "fleet_paged_tokens_per_s", "fleet_paged_p95_s",
        "prefix_hit_rate",
    ):
        assert slim[key] == extra[key], key
    # the chip-pool SLO trio rides the line (supporting pool scalars
    # are sidecar-recoverable)
    for key in (
        "pool_preempt_to_ready_s", "pool_spike_availability",
        "pool_train_goodput",
    ):
        assert slim[key] == extra[key], key
    # the multi-tenant cluster SLO trio rides the line (first victim,
    # counters, and the one-trace flag are sidecar-recoverable)
    for key in (
        "cluster_inversion_avail", "cluster_preempt_cascade_s",
        "cluster_brain_adopt_s",
    ):
        assert slim[key] == extra[key], key
    # the elastic DP↔PP trade trio rides the line (the transition label
    # and the rung accum are sidecar-recoverable)
    for key in (
        "dp_pp_trade_mttr_s", "reshard_s", "hybrid_vs_accum_goodput_x",
    ):
        assert slim[key] == extra[key], key
    assert slim["attr_report"] == extra["attr_report"]
    assert slim["last_silicon"]["artifact"] == (
        extra["last_silicon"]["artifact"]
    )
    # the COMPLETE extra is recoverable from the sidecar
    sidecar = tmp_path / slim["extra_sidecar"]
    full = json.load(open(sidecar))
    assert set(extra) == set(full)
    assert full["probe_history"] == extra["probe_history"]


# ---------------------------------------------------------------------------
# Section filter + interposer-overhead A/B (PR 2): the worker's
# DLROVER_BENCH_SECTIONS contract and the orchestrator-side plain
# headline child are pinned with fake workers (no jax).
# ---------------------------------------------------------------------------


def test_section_filter_parsing(monkeypatch):
    bench = _bench()
    monkeypatch.delenv("DLROVER_BENCH_SECTIONS", raising=False)
    want, filtered = bench._section_filter()
    assert not filtered and want("ckpt") and want("anything")
    monkeypatch.setenv("DLROVER_BENCH_SECTIONS", "ckpt, serving")
    want, filtered = bench._section_filter()
    assert filtered
    assert want("ckpt") and want("serving")
    assert not want("decode") and not want("ladder")
    # "headline" names no optional section: everything optional skips
    monkeypatch.setenv("DLROVER_BENCH_SECTIONS", "headline")
    want, filtered = bench._section_filter()
    assert filtered and not any(
        want(s) for s in set(bench.SECTION_OF_ERROR.values())
    )


def test_section_of_error_maps_into_headline_errors():
    bench = _bench()
    # every retryable error key is a headline-section error, and the
    # run-scoped markers stay non-retryable
    assert set(bench.SECTION_OF_ERROR) <= bench.HEADLINE_SECTION_ERRORS
    assert "tpu_error" not in bench.SECTION_OF_ERROR
    assert "fatal_error" not in bench.SECTION_OF_ERROR


def _interposed_parsed(step=0.05):
    return {
        "metric": "m", "value": 1.0, "unit": "u", "vs_baseline": 1.0,
        "extra": {
            "tpu_attempt": "interposed", "flash_base_step_s": step,
        },
    }


def test_interposer_ab_computes_overhead_pct():
    bench = _bench()
    line = json.dumps({
        "metric": "m", "value": 2.0, "unit": "u", "vs_baseline": 1.0,
        "extra": {"flash_base_step_s": 0.04},
    })
    parsed = _interposed_parsed(step=0.05)
    bench._interposer_overhead_rung(
        parsed, {}, [sys.executable, "-c", f"print({line!r})"], [],
    )
    extra = parsed["extra"]
    assert extra["interposer_plain_step_s"] == 0.04
    # 0.05 / 0.04 - 1 = 25%
    assert extra["interposer_overhead_pct"] == 25.0


def test_interposer_ab_skips_plain_attempt_and_budget():
    bench = _bench()
    # a plain main attempt never spawns the child
    parsed = _interposed_parsed()
    parsed["extra"]["tpu_attempt"] = "plain"
    t0 = time.time()
    bench._interposer_overhead_rung(
        parsed, {}, [sys.executable, "-c", "import time; time.sleep(60)"],
        [],
    )
    assert time.time() - t0 < 5.0
    assert "interposer_overhead_pct" not in parsed["extra"]
    # an exhausted budget records the skip instead of spawning
    parsed = _interposed_parsed()
    history = []
    bench._interposer_overhead_rung(
        parsed, {}, [sys.executable, "-c", "import time; time.sleep(60)"],
        history, deadline=time.time() + 60.0,
    )
    assert "interposer_overhead_pct" not in parsed["extra"]
    assert any("skipped" in h.get("note", "") for h in history)


def test_interposer_ab_failed_child_records_history():
    bench = _bench()
    parsed = _interposed_parsed()
    history = []
    bench._interposer_overhead_rung(
        parsed, {}, [sys.executable, "-c", "raise SystemExit(3)"],
        history,
    )
    assert "interposer_overhead_pct" not in parsed["extra"]
    assert any(
        h.get("worker_attempt") == "interposer_ab_plain" for h in history
    )


def test_under_budget_line_passes_through_untouched(tmp_path, monkeypatch):
    bench = _bench()
    monkeypatch.setattr(bench, "_REPO_DIR", str(tmp_path))
    result = {
        "metric": "m", "value": 1.0, "unit": "u", "vs_baseline": 1.0,
        "extra": {"device": "cpu"},
    }
    assert bench._shrink_to_budget(result) is result
    assert not list(tmp_path.glob("BENCH_extra_*"))  # no sidecar spam
