"""Tier-1 gate: ``tpurun-lint`` over ``dlrover_tpu/`` is CLEAN.

The whole point of the suite (docs/analysis.md): the invariants PRs 1-4
paid for are machine-enforced from PR 6 forward. Pure AST — no jax
import — so this runs in milliseconds anywhere.
"""

import json
import os

from dlrover_tpu.analysis import Baseline, run_lint
from dlrover_tpu.analysis.cli import DEFAULT_BASELINE, main as lint_main

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_PKG = os.path.join(_REPO, "dlrover_tpu")

# The full-repo AST scan costs seconds on a loaded tier-1 box; every
# in-process test below asserts against the SAME run — one scan, not
# one per test (the CLI test keeps its own invocation for the main()
# wiring, scoped to a subpackage).
_SHARED = {}


def _repo_lint_result():
    if "result" not in _SHARED:
        baseline = (
            Baseline.load(DEFAULT_BASELINE)
            if os.path.exists(DEFAULT_BASELINE)
            else None
        )
        _SHARED["result"] = run_lint(
            [_PKG], baseline=baseline, repo_root=_REPO
        )
    return _SHARED["result"]


def test_repo_is_lint_clean():
    result = _repo_lint_result()
    assert result.clean, "tpurun-lint is not clean:\n" + "\n".join(
        [v.render() for v in result.violations]
        + result.errors
        + [f"stale baseline entry: {e.key()}" for e in result.stale_baseline]
    )


def test_cli_exits_zero_and_reports(capsys):
    """main() wiring: exit status + the summary line. Scoped to the
    analysis package — full-repo cleanliness is already asserted by
    test_repo_is_lint_clean against the same engine and baseline."""
    assert lint_main([os.path.join(_PKG, "analysis")]) == 0
    out = capsys.readouterr().out
    assert "0 violations" in out


def test_every_suppression_carries_a_reason():
    """Redundant with run_lint's own error channel, but kept explicit:
    the reasons ARE the documentation of every intentional exception."""
    result = _repo_lint_result()
    for v, s in result.suppressed:
        assert s.reason.strip(), f"bare suppression at {v.path}:{s.line}"


def test_checked_in_baseline_is_empty_or_reasoned():
    data = json.load(open(DEFAULT_BASELINE))
    for entry in data["entries"]:
        assert entry.get("reason", "").strip(), entry
    # PR 6 fixed everything it found; keep the count pinned so additions
    # are a conscious choice (update docs/analysis.md when this moves)
    assert len(data["entries"]) == 0


def test_console_script_registered():
    pyproject = open(os.path.join(_REPO, "pyproject.toml")).read()
    assert 'tpurun-lint = "dlrover_tpu.analysis.cli:main"' in pyproject


def test_analysis_doc_linked():
    assert os.path.exists(os.path.join(_REPO, "docs", "analysis.md"))
    for rel in ("README.md", "docs/chaos.md"):
        text = open(os.path.join(_REPO, rel)).read()
        assert "analysis.md" in text, f"{rel} does not link docs/analysis.md"


def test_analysis_package_is_jax_free():
    """The suite must import (and run) without jax: no runtime module
    creep into the analysis package."""
    import sys
    import subprocess

    # linting the analysis package itself is enough to prove the
    # import graph is jax-free — the full-repo scan (same engine) runs
    # in-process above, and one per-test repeat of it costs real
    # seconds inside the tier-1 wall-clock budget
    code = (
        "import sys\n"
        "sys.modules['jax'] = None  # poison: any import attempt dies\n"
        "from dlrover_tpu.analysis import run_lint\n"
        "r = run_lint([r'%s'], repo_root=r'%s')\n"
        "sys.exit(0 if r is not None else 1)\n"
        % (os.path.join(_PKG, "analysis"), _REPO)
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        cwd=_REPO,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stderr


def test_semantic_passes_are_jax_free_and_non_vacuous():
    """The v3 passes read jax-adjacent source (mesh registry, sharding
    rules, spec literals) but must do it by AST: with jax poisoned they
    still load the real tables AND still fire on their fixtures — the
    poison must not degrade them into silent no-ops."""
    import sys
    import subprocess

    fx = os.path.join(_REPO, "tests", "lint_fixtures")
    code = (
        "import sys\n"
        "sys.modules['jax'] = None  # poison: any import attempt dies\n"
        "from dlrover_tpu.analysis import run_lint\n"
        "from dlrover_tpu.analysis.passes import (\n"
        "    epoch_fence, journal_conformance, mesh_axes, reshard_coverage)\n"
        "from dlrover_tpu.analysis.passes.mesh_axes import load_axis_registry\n"
        "from dlrover_tpu.analysis.passes.reshard_coverage import load_tables\n"
        "import os\n"
        "registry, axes, err = load_axis_registry(\n"
        "    os.path.join(r'%(repo)s', 'dlrover_tpu', 'parallel', 'mesh.py'))\n"
        "assert registry and not err, err\n"
        "rules, policies, elastic = load_tables(r'%(repo)s')\n"
        "assert rules and policies and elastic\n"
        "for pass_mod, fixture, needle in [\n"
        "    (mesh_axes, 'fx_mesh_axes.py', 'zz_bogus'),\n"
        "    (reshard_coverage, 'fx_reshard_coverage.py', 'zz_lora'),\n"
        "    (journal_conformance, 'fx_journal_conformance.py', 'fx.sett'),\n"
        "    (epoch_fence, 'fx_epoch_fence.py', 'master_epoch'),\n"
        "]:\n"
        "    r = run_lint([os.path.join(r'%(fx)s', fixture)],\n"
        "                 passes=[pass_mod], repo_root=r'%(repo)s')\n"
        "    assert any(needle in v.message for v in r.violations), (\n"
        "        fixture, [v.render() for v in r.violations])\n"
        "r = run_lint([r'%(pkg)s'],\n"
        "             passes=[mesh_axes, reshard_coverage,\n"
        "                     journal_conformance, epoch_fence],\n"
        "             repo_root=r'%(repo)s')\n"
        "assert not r.violations, [v.render() for v in r.violations]\n"
        "assert r.suppressed  # node_check probe-axis suppressions seen\n"
    ) % {"repo": _REPO, "pkg": _PKG, "fx": fx}
    proc = subprocess.run(
        [sys.executable, "-c", code],
        cwd=_REPO,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stderr


def test_lock_witness_is_jax_free():
    """The runtime sanitizer must install and witness locks with jax
    poisoned — it runs inside arbitrary runtime processes, including
    ones that must never import jax (the agent, the lint CI image)."""
    import sys
    import subprocess

    code = (
        "import sys, threading, types\n"
        "sys.modules['jax'] = None  # poison: any import attempt dies\n"
        "from dlrover_tpu.analysis import witness\n"
        "witness.install()\n"
        "mod = types.ModuleType('dlrover_tpu._poison_probe')\n"
        "sys.modules[mod.__name__] = mod\n"
        "src = ('import threading\\n'\n"
        "       'def make():\\n'\n"
        "       '    a = threading.Lock()\\n'\n"
        "       '    b = threading.Lock()\\n'\n"
        "       '    return a, b\\n')\n"
        "exec(compile(src, 'probe.py', 'exec'), mod.__dict__)\n"
        "a, b = mod.make()\n"
        "assert type(a).__name__ == '_WitnessLock', type(a)\n"
        "with a:\n"
        "    with b:\n"
        "        pass\n"
        "s = witness.stats()\n"
        "assert s['edges'] == 1 and not s['inversions'], s\n"
        "witness.uninstall()\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        cwd=_REPO,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stderr


def test_no_new_timestamped_artifacts_tracked():
    """Repo hygiene: generated probe/diagnosis artifacts are gitignored
    from PR 9 on — only the ``*_LATEST`` pointers and the numbered
    ``BENCH_r0*.json`` trajectory files the bench reads stay tracked."""
    import re
    import subprocess

    proc = subprocess.run(
        ["git", "ls-files"],
        cwd=_REPO,
        capture_output=True,
        text=True,
        timeout=60,
    )
    if proc.returncode != 0:
        import pytest

        pytest.skip("not a git checkout")
    timestamped = re.compile(
        r"^(BENCH_probe_sidecar_\d|SILICON_r\d+_\d|HANG_DIAGNOSIS_r\d+_\d)"
    )
    offenders = [
        f for f in proc.stdout.splitlines() if timestamped.match(f)
    ]
    assert not offenders, (
        "timestamped artifacts tracked (add to .gitignore, git rm "
        f"--cached): {offenders}"
    )
