"""SPMD generation over a device mesh (rollout at scale).

The sharded rollout capability: ``build_generate_fn(mesh=...)`` runs
prefill + the decode scan over a tp/fsdp/dp mesh with the params held
exactly as the trainer shards them — XLA inserts the decode
collectives. The reference can only do this by deploying a separate
vLLM instance per rollout (SURVEY.md §2.13); here it is the same
compiled path as single-chip generation, so the test's keystone is
bit-identical greedy output between the two.

8 virtual CPU devices (conftest), mirroring the multichip dryrun.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.models.generation import (
    SamplingConfig,
    build_generate_fn,
    left_pad_prompts,
)
from dlrover_tpu.models.gpt import GPT, GPTConfig
from dlrover_tpu.models.llama import Llama, LlamaConfig
from dlrover_tpu.parallel.mesh import MeshConfig, build_mesh
from dlrover_tpu.parallel.train_step import (
    default_optimizer,
    init_train_state,
)


def _sharded_params(model, mesh, batch=4, width=8):
    """Params initialized INTO their mesh shards, trainer-style."""
    tokens = jnp.zeros((batch, width), jnp.int32)
    state, shardings = init_train_state(
        model, tokens, mesh, default_optimizer()
    )
    return state.params, shardings.params


class TestShardedGeneration:
    @pytest.mark.parametrize(
        "mesh_cfg",
        [
            MeshConfig(dp=2, fsdp=2, tp=2),
            MeshConfig(dp=4, tp=2),
            MeshConfig(dp=8),
        ],
        ids=["dp2_fsdp2_tp2", "dp4_tp2", "dp8"],
    )
    def test_greedy_matches_single_device(self, mesh_cfg):
        model = Llama(LlamaConfig.tiny())
        mesh = build_mesh(mesh_cfg, jax.devices()[:8])
        params, param_sh = _sharded_params(model, mesh)

        # 8 rows: divisible by the data extent of every mesh case
        toks, mask = left_pad_prompts(
            [
                [3, 7, 11],
                [9],
                [5, 5],
                [1, 2, 3, 4],
                [8],
                [2, 4, 6],
                [10, 11],
                [7, 7, 7, 7],
            ],
            pad_id=0,
        )
        sampling = SamplingConfig(max_new_tokens=4, temperature=0.0)
        fn = build_generate_fn(
            model,
            sampling,
            prompt_width=toks.shape[1],
            mesh=mesh,
            param_shardings=param_sh,
        )
        out_s, mask_s, logp_s = fn(params, toks, mask, jax.random.PRNGKey(0))

        # single-device reference on the SAME parameter values
        host_params = jax.device_get(params)
        fn1 = build_generate_fn(model, sampling, prompt_width=toks.shape[1])
        out_1, mask_1, logp_1 = fn1(
            jax.tree.map(jnp.asarray, host_params),
            toks,
            mask,
            jax.random.PRNGKey(0),
        )
        np.testing.assert_array_equal(np.asarray(out_s), np.asarray(out_1))
        np.testing.assert_array_equal(np.asarray(mask_s), np.asarray(mask_1))
        np.testing.assert_allclose(
            np.asarray(logp_s), np.asarray(logp_1), rtol=2e-2, atol=2e-2
        )

    def test_gpt_tp_sharded_generation(self):
        model = GPT(GPTConfig.tiny())
        mesh = build_mesh(MeshConfig(dp=2, tp=2), jax.devices()[:4])
        params, param_sh = _sharded_params(model, mesh)
        toks, mask = left_pad_prompts([[3, 7], [9, 1]], pad_id=0)
        fn = build_generate_fn(
            model,
            SamplingConfig(max_new_tokens=3, temperature=0.0),
            prompt_width=2,
            mesh=mesh,
            param_shardings=param_sh,
        )
        out, omask, _ = fn(params, toks, mask, jax.random.PRNGKey(0))
        assert out.shape == (2, 3) and bool(omask.all())
        # teacher-forced check through the sharded TRAINING forward
        from dlrover_tpu.parallel.sharding import apply_rules

        full = jnp.concatenate([toks, out[:, :2]], axis=1)
        with mesh, apply_rules():
            logits = jax.jit(
                lambda p, t: model.apply({"params": p}, t)
            )(params, full)
        pred = jnp.argmax(np.asarray(logits)[:, 1:], axis=-1)
        np.testing.assert_array_equal(np.asarray(pred), np.asarray(out))

    def test_sampled_path_runs_sharded(self):
        """Temperature/top-k/top-p over a tp-sharded vocab compiles and
        executes (the filters argsort the vocab dim — XLA must gather)."""
        model = Llama(LlamaConfig.tiny())
        mesh = build_mesh(MeshConfig(dp=2, tp=2), jax.devices()[:4])
        params, param_sh = _sharded_params(model, mesh)
        toks, mask = left_pad_prompts([[3], [9]], pad_id=0)
        fn = build_generate_fn(
            model,
            SamplingConfig(
                max_new_tokens=3, temperature=0.9, top_k=16, top_p=0.9
            ),
            prompt_width=1,
            mesh=mesh,
            param_shardings=param_sh,
        )
        out, omask, logp = fn(params, toks, mask, jax.random.PRNGKey(1))
        assert out.shape == (2, 3)
        assert np.isfinite(np.asarray(logp)).all()
