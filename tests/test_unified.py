"""Unified multi-role control plane tests (reference:
dlrover/python/unified/tests — builder validation, placement,
supervision, failover lineage, state recovery — run with real local
processes like the reference's local-Ray integration tests)."""

import os
import sys
import time

import pytest

from dlrover_tpu.unified import (
    DLExecutionGraph,
    DLJobBuilder,
    FileStateBackend,
    MemoryStateBackend,
    PrimeManager,
    RLJobBuilder,
    place,
)
from dlrover_tpu.unified.graph import VertexState
from dlrover_tpu.unified.manager import JobStatus


class TestBuilder:
    def test_rl_roles_and_validation(self):
        job = (
            RLJobBuilder("ppo")
            .node_num(2)
            .device_per_node(4)
            .trainer(["python", "t.py"], num=2, device=2.0)
            .rollout(["python", "r.py"], num=2, device=1.0)
            .reward(["python", "w.py"], num=1, device=0.5)
            .with_collocation("trainer", "rollout")
            .build()
        )
        assert set(job.roles) == {"trainer", "rollout", "reward"}
        # rollout failure lineage defaults to the trainer
        assert job.roles["rollout"].restart_dependents == ["trainer"]

    def test_rl_requires_trainer(self):
        with pytest.raises(ValueError, match="trainer"):
            RLJobBuilder("x").rollout(["python", "r.py"]).build()

    def test_duplicate_and_unknown_roles_rejected(self):
        builder = DLJobBuilder("j").role("a", ["cmd"])
        with pytest.raises(ValueError, match="twice"):
            builder.role("a", ["cmd"])
        with pytest.raises(ValueError, match="unknown role"):
            DLJobBuilder("j").role("a", ["cmd"]).with_collocation(
                "a", "ghost"
            ).build()
        with pytest.raises(ValueError, match="unknown dependent"):
            DLJobBuilder("j").role(
                "a", ["cmd"], restart_dependents=["ghost"]
            ).build()


class TestPlacement:
    def _job(self, **kw):
        builder = (
            DLJobBuilder("place")
            .node_num(kw.get("nodes", 2))
            .device_per_node(kw.get("devices", 4))
        )
        return builder

    def test_collocated_roles_share_nodes(self):
        job = (
            self._job()
            .role("actor", ["c"], num=2, device=2.0)
            .role("rollout", ["c"], num=2, device=2.0)
            .with_collocation("actor", "rollout")
            .build()
        )
        graph = DLExecutionGraph.from_job(job)
        placement = place(graph)
        for index in range(2):
            assert placement.node_of(f"actor-{index}") == placement.node_of(
                f"rollout-{index}"
            )

    def test_capacity_enforced(self):
        job = (
            self._job(nodes=1, devices=2)
            .role("big", ["c"], num=3, device=1.0)
            .build()
        )
        with pytest.raises(ValueError, match="insufficient capacity"):
            place(DLExecutionGraph.from_job(job))

    def test_collocation_requires_equal_counts(self):
        job = (
            self._job()
            .role("a", ["c"], num=2, device=1.0)
            .role("b", ["c"], num=1, device=1.0)
            .with_collocation("a", "b")
            .build()
        )
        with pytest.raises(ValueError, match="equal instance counts"):
            place(DLExecutionGraph.from_job(job))


def _script(tmp_path, name, body):
    path = tmp_path / name
    path.write_text(body)
    return [sys.executable, str(path)]


class TestSupervision:
    def test_job_runs_to_success(self, tmp_path):
        marker = tmp_path / "out"
        marker.mkdir()
        cmd = _script(
            tmp_path,
            "ok.py",
            "import os, pathlib\n"
            "role = os.environ['DLROVER_ROLE']\n"
            "idx = os.environ['DLROVER_ROLE_INDEX']\n"
            f"pathlib.Path(r'{marker}', f'{{role}}_{{idx}}').write_text(\n"
            "    os.environ['DLROVER_ROLE_WORLD'])\n",
        )
        job = (
            DLJobBuilder("ok")
            .node_num(1)
            .device_per_node(4)
            .role("trainer", cmd, num=2, device=1.0)
            .role("reward", cmd, num=1, device=1.0)
            .build()
        )
        manager = PrimeManager(job, log_dir=str(tmp_path / "logs"))
        manager.start()
        assert manager.wait(timeout=30) == JobStatus.SUCCEEDED
        assert sorted(p.name for p in marker.iterdir()) == [
            "reward_0",
            "trainer_0",
            "trainer_1",
        ]
        assert (marker / "trainer_0").read_text() == "2"

    @pytest.mark.slow
    def test_elastic_role_runs_under_tpurun(self, tmp_path):
        """elastic=True wraps the role in the tpurun launcher against a
        role-scoped sub-master (reference ElasticMaster sub-master):
        both instances must rendezvous into ONE world of size 2."""
        marker = tmp_path / "world"
        marker.mkdir()
        script = tmp_path / "train.py"
        script.write_text(
            "import os, pathlib\n"
            "rank = os.environ['DLROVER_NODE_RANK']\n"
            f"pathlib.Path(r'{marker}', f'r{{rank}}').write_text(\n"
            "    os.environ['DLROVER_NUM_PROCESSES'])\n"
        )
        job = (
            DLJobBuilder("eljob")
            .node_num(1)
            .device_per_node(2)
            .role("trainer", [str(script)], num=2, device=1.0, elastic=True)
            .build()
        )
        manager = PrimeManager(job, log_dir=str(tmp_path / "logs"))
        env_backup = dict(os.environ)
        os.environ["PYTHONPATH"] = os.pathsep.join(sys.path)
        try:
            manager.start()
            assert manager._sub_masters  # sub-master actually spawned
            assert manager.wait(timeout=90) == JobStatus.SUCCEEDED
        finally:
            manager.stop(manager.status)
            os.environ.clear()
            os.environ.update(env_backup)
        assert sorted(p.name for p in marker.iterdir()) == ["r0", "r1"]
        # one elastic world of both instances, not two worlds of one
        assert (marker / "r0").read_text() == "2"
        assert (marker / "r1").read_text() == "2"

    def test_elastic_role_requires_command(self):
        with pytest.raises(ValueError, match="no command"):
            (
                DLJobBuilder("bad")
                .node_num(1)
                .device_per_node(1)
                .role("t", [], elastic=True)
                .build()
            )

    def test_failed_role_restarts_with_lineage(self, tmp_path):
        marker = tmp_path / "runs"
        marker.mkdir()
        # rollout fails once, then succeeds; each start drops a marker
        rollout_cmd = _script(
            tmp_path,
            "rollout.py",
            "import os, pathlib, sys, time\n"
            f"d = pathlib.Path(r'{marker}')\n"
            "n = len(list(d.glob('rollout_*')))\n"
            "(d / f'rollout_{n}').write_text('')\n"
            "time.sleep(0.3)\n"
            "sys.exit(1 if n == 0 else 0)\n",
        )
        trainer_cmd = _script(
            tmp_path,
            "trainer.py",
            "import pathlib, time\n"
            f"d = pathlib.Path(r'{marker}')\n"
            "n = len(list(d.glob('trainer_*')))\n"
            "(d / f'trainer_{n}').write_text('')\n"
            "time.sleep(1.2)\n",
        )
        job = (
            RLJobBuilder("lineage")
            .node_num(1)
            .device_per_node(4)
            .trainer(trainer_cmd, num=1, device=1.0)
            .rollout(rollout_cmd, num=1, device=1.0)
            .build()
        )
        manager = PrimeManager(
            job, log_dir=str(tmp_path / "logs"), monitor_interval=0.1
        )
        manager.start()
        status = manager.wait(timeout=30)
        assert status == JobStatus.SUCCEEDED, status
        # rollout ran twice (failure + retry); the trainer was restarted
        # by lineage even though it never failed itself
        assert len(list(marker.glob("rollout_*"))) == 2
        assert len(list(marker.glob("trainer_*"))) >= 2

    def test_budget_exhaustion_fails_job(self, tmp_path):
        cmd = _script(tmp_path, "bad.py", "import sys; sys.exit(1)\n")
        job = (
            DLJobBuilder("doomed")
            .node_num(1)
            .device_per_node(2)
            .role("trainer", cmd, num=1, device=1.0, max_restarts=1)
            .build()
        )
        manager = PrimeManager(
            job,
            log_dir=str(tmp_path / "logs"),
            monitor_interval=0.1,
            max_job_restarts=0,
        )
        manager.start()
        assert manager.wait(timeout=30) == JobStatus.FAILED


class TestStateRecovery:
    def test_file_backend_roundtrip(self, tmp_path):
        backend = FileStateBackend(str(tmp_path / "state.json"))
        backend.save({"a": 1})
        assert backend.load() == {"a": 1}
        backend.clear()
        assert backend.load() is None

    def test_manager_recovers_budgets(self, tmp_path):
        backend = FileStateBackend(str(tmp_path / "state.json"))
        cmd = [sys.executable, "-c", "pass"]
        job = (
            DLJobBuilder("recover")
            .node_num(1)
            .device_per_node(1)
            .role("trainer", cmd, num=1, device=1.0)
            .build()
        )
        first = PrimeManager(job, state_backend=backend)
        first.graph.vertices["trainer-0"].restart_count = 2
        first._job_restarts = 1
        first._save_state()

        # a NEW master process resumes the budgets instead of resetting
        second = PrimeManager(job, state_backend=backend)
        assert second.graph.vertices["trainer-0"].restart_count == 2
        assert second._job_restarts == 1


class TestOrphanReaping:
    def test_recovered_master_reaps_orphan_roles(self, tmp_path):
        backend = FileStateBackend(str(tmp_path / "state.json"))
        cmd = _script(tmp_path, "sleepy.py", "import time; time.sleep(60)\n")
        job = (
            DLJobBuilder("orphans")
            .node_num(1)
            .device_per_node(1)
            .role("trainer", cmd, num=1, device=1.0)
            .build()
        )
        first = PrimeManager(job, state_backend=backend, monitor_interval=0.1)
        first.start()
        pid = first._workers["trainer-0"].pid
        assert pid is not None
        # simulate the master process dying: supervision stops, the role
        # process (own session) survives as an orphan
        first._stopped.set()
        time.sleep(0.3)
        assert os.path.exists(f"/proc/{pid}")

        def alive(p):
            # in THIS test the orphan stays our child, so a killed orphan
            # lingers as a zombie (state Z) until reaped — dead either way
            try:
                with open(f"/proc/{p}/stat", "rb") as f:
                    stat = f.read()
                return stat[stat.rindex(b")") + 2 :].split()[0] != b"Z"
            except OSError:
                return False

        second = PrimeManager(job, state_backend=backend)
        deadline = time.time() + 10
        while time.time() < deadline and alive(pid):
            time.sleep(0.1)
        try:
            assert not alive(pid), "orphan role survived master recovery"
        finally:
            second.stop()
            try:
                os.kill(pid, 9)
            except OSError:
                pass
