"""Unified multi-role control plane tests (reference:
dlrover/python/unified/tests — builder validation, placement,
supervision, failover lineage, state recovery — run with real local
processes like the reference's local-Ray integration tests)."""

import os
import sys
import time

import pytest

from dlrover_tpu.unified import (
    DLExecutionGraph,
    DLJobBuilder,
    FileStateBackend,
    MemoryStateBackend,
    PrimeManager,
    RLJobBuilder,
    place,
)
from dlrover_tpu.unified.graph import VertexState
from dlrover_tpu.unified.manager import JobStatus


class TestBuilder:
    def test_rl_roles_and_validation(self):
        job = (
            RLJobBuilder("ppo")
            .node_num(2)
            .device_per_node(4)
            .trainer(["python", "t.py"], num=2, device=2.0)
            .rollout(["python", "r.py"], num=2, device=1.0)
            .reward(["python", "w.py"], num=1, device=0.5)
            .with_collocation("trainer", "rollout")
            .build()
        )
        assert set(job.roles) == {"trainer", "rollout", "reward"}
        # rollout failure lineage defaults to the trainer
        assert job.roles["rollout"].restart_dependents == ["trainer"]

    def test_rl_requires_trainer(self):
        with pytest.raises(ValueError, match="trainer"):
            RLJobBuilder("x").rollout(["python", "r.py"]).build()

    def test_duplicate_and_unknown_roles_rejected(self):
        builder = DLJobBuilder("j").role("a", ["cmd"])
        with pytest.raises(ValueError, match="twice"):
            builder.role("a", ["cmd"])
        with pytest.raises(ValueError, match="unknown role"):
            DLJobBuilder("j").role("a", ["cmd"]).with_collocation(
                "a", "ghost"
            ).build()
        with pytest.raises(ValueError, match="unknown dependent"):
            DLJobBuilder("j").role(
                "a", ["cmd"], restart_dependents=["ghost"]
            ).build()


class TestPlacement:
    def _job(self, **kw):
        builder = (
            DLJobBuilder("place")
            .node_num(kw.get("nodes", 2))
            .device_per_node(kw.get("devices", 4))
        )
        return builder

    def test_collocated_roles_share_nodes(self):
        job = (
            self._job()
            .role("actor", ["c"], num=2, device=2.0)
            .role("rollout", ["c"], num=2, device=2.0)
            .with_collocation("actor", "rollout")
            .build()
        )
        graph = DLExecutionGraph.from_job(job)
        placement = place(graph)
        for index in range(2):
            assert placement.node_of(f"actor-{index}") == placement.node_of(
                f"rollout-{index}"
            )

    def test_capacity_enforced(self):
        job = (
            self._job(nodes=1, devices=2)
            .role("big", ["c"], num=3, device=1.0)
            .build()
        )
        with pytest.raises(ValueError, match="insufficient capacity"):
            place(DLExecutionGraph.from_job(job))

    def test_collocation_requires_equal_counts(self):
        job = (
            self._job()
            .role("a", ["c"], num=2, device=1.0)
            .role("b", ["c"], num=1, device=1.0)
            .with_collocation("a", "b")
            .build()
        )
        with pytest.raises(ValueError, match="equal instance counts"):
            place(DLExecutionGraph.from_job(job))


def _script(tmp_path, name, body):
    path = tmp_path / name
    path.write_text(body)
    return [sys.executable, str(path)]


class TestSupervision:
    def test_job_runs_to_success(self, tmp_path):
        marker = tmp_path / "out"
        marker.mkdir()
        cmd = _script(
            tmp_path,
            "ok.py",
            "import os, pathlib\n"
            "role = os.environ['DLROVER_ROLE']\n"
            "idx = os.environ['DLROVER_ROLE_INDEX']\n"
            f"pathlib.Path(r'{marker}', f'{{role}}_{{idx}}').write_text(\n"
            "    os.environ['DLROVER_ROLE_WORLD'])\n",
        )
        job = (
            DLJobBuilder("ok")
            .node_num(1)
            .device_per_node(4)
            .role("trainer", cmd, num=2, device=1.0)
            .role("reward", cmd, num=1, device=1.0)
            .build()
        )
        manager = PrimeManager(job, log_dir=str(tmp_path / "logs"))
        manager.start()
        assert manager.wait(timeout=30) == JobStatus.SUCCEEDED
        assert sorted(p.name for p in marker.iterdir()) == [
            "reward_0",
            "trainer_0",
            "trainer_1",
        ]
        assert (marker / "trainer_0").read_text() == "2"

    @pytest.mark.slow
    def test_elastic_role_runs_under_tpurun(self, tmp_path):
        """elastic=True wraps the role in the tpurun launcher against a
        role-scoped sub-master (reference ElasticMaster sub-master):
        both instances must rendezvous into ONE world of size 2."""
        marker = tmp_path / "world"
        marker.mkdir()
        script = tmp_path / "train.py"
        script.write_text(
            "import os, pathlib\n"
            "rank = os.environ['DLROVER_NODE_RANK']\n"
            f"pathlib.Path(r'{marker}', f'r{{rank}}').write_text(\n"
            "    os.environ['DLROVER_NUM_PROCESSES'])\n"
        )
        job = (
            DLJobBuilder("eljob")
            .node_num(1)
            .device_per_node(2)
            .role("trainer", [str(script)], num=2, device=1.0, elastic=True)
            .build()
        )
        manager = PrimeManager(job, log_dir=str(tmp_path / "logs"))
        env_backup = dict(os.environ)
        os.environ["PYTHONPATH"] = os.pathsep.join(sys.path)
        try:
            manager.start()
            assert manager._sub_masters  # sub-master actually spawned
            assert manager.wait(timeout=90) == JobStatus.SUCCEEDED
        finally:
            manager.stop(manager.status)
            os.environ.clear()
            os.environ.update(env_backup)
        assert sorted(p.name for p in marker.iterdir()) == ["r0", "r1"]
        # one elastic world of both instances, not two worlds of one
        assert (marker / "r0").read_text() == "2"
        assert (marker / "r1").read_text() == "2"

    def test_elastic_role_requires_command(self):
        with pytest.raises(ValueError, match="no command"):
            (
                DLJobBuilder("bad")
                .node_num(1)
                .device_per_node(1)
                .role("t", [], elastic=True)
                .build()
            )

    def test_failed_role_restarts_with_lineage(self, tmp_path):
        marker = tmp_path / "runs"
        marker.mkdir()
        # rollout fails once, then succeeds; each start drops a marker
        rollout_cmd = _script(
            tmp_path,
            "rollout.py",
            "import os, pathlib, sys, time\n"
            f"d = pathlib.Path(r'{marker}')\n"
            "n = len(list(d.glob('rollout_*')))\n"
            "(d / f'rollout_{n}').write_text('')\n"
            "time.sleep(0.3)\n"
            "sys.exit(1 if n == 0 else 0)\n",
        )
        trainer_cmd = _script(
            tmp_path,
            "trainer.py",
            "import pathlib, time\n"
            f"d = pathlib.Path(r'{marker}')\n"
            "n = len(list(d.glob('trainer_*')))\n"
            "(d / f'trainer_{n}').write_text('')\n"
            "time.sleep(1.2)\n",
        )
        job = (
            RLJobBuilder("lineage")
            .node_num(1)
            .device_per_node(4)
            .trainer(trainer_cmd, num=1, device=1.0)
            .rollout(rollout_cmd, num=1, device=1.0)
            .build()
        )
        manager = PrimeManager(
            job, log_dir=str(tmp_path / "logs"), monitor_interval=0.1
        )
        manager.start()
        status = manager.wait(timeout=30)
        assert status == JobStatus.SUCCEEDED, status
        # rollout ran twice (failure + retry); the trainer was restarted
        # by lineage even though it never failed itself
        assert len(list(marker.glob("rollout_*"))) == 2
        assert len(list(marker.glob("trainer_*"))) >= 2

    def test_budget_exhaustion_fails_job(self, tmp_path):
        cmd = _script(tmp_path, "bad.py", "import sys; sys.exit(1)\n")
        job = (
            DLJobBuilder("doomed")
            .node_num(1)
            .device_per_node(2)
            .role("trainer", cmd, num=1, device=1.0, max_restarts=1)
            .build()
        )
        manager = PrimeManager(
            job,
            log_dir=str(tmp_path / "logs"),
            monitor_interval=0.1,
            max_job_restarts=0,
        )
        manager.start()
        assert manager.wait(timeout=30) == JobStatus.FAILED


class TestStateRecovery:
    def test_file_backend_roundtrip(self, tmp_path):
        backend = FileStateBackend(str(tmp_path / "state.json"))
        backend.save({"a": 1})
        assert backend.load() == {"a": 1}
        backend.clear()
        assert backend.load() is None

    def test_manager_recovers_budgets(self, tmp_path):
        backend = FileStateBackend(str(tmp_path / "state.json"))
        cmd = [sys.executable, "-c", "pass"]
        job = (
            DLJobBuilder("recover")
            .node_num(1)
            .device_per_node(1)
            .role("trainer", cmd, num=1, device=1.0)
            .build()
        )
        first = PrimeManager(job, state_backend=backend)
        first.graph.vertices["trainer-0"].restart_count = 2
        first._job_restarts = 1
        first._save_state()

        # a NEW master process resumes the budgets instead of resetting
        second = PrimeManager(job, state_backend=backend)
        assert second.graph.vertices["trainer-0"].restart_count == 2
        assert second._job_restarts == 1


class TestOrphanReaping:
    def test_recovered_master_reaps_orphan_roles(self, tmp_path):
        backend = FileStateBackend(str(tmp_path / "state.json"))
        cmd = _script(tmp_path, "sleepy.py", "import time; time.sleep(60)\n")
        job = (
            DLJobBuilder("orphans")
            .node_num(1)
            .device_per_node(1)
            .role("trainer", cmd, num=1, device=1.0)
            .build()
        )
        first = PrimeManager(job, state_backend=backend, monitor_interval=0.1)
        first.start()
        pid = first._workers["trainer-0"].pid
        assert pid is not None
        # simulate the master process dying: supervision stops, the role
        # process (own session) survives as an orphan
        first._stopped.set()
        time.sleep(0.3)
        assert os.path.exists(f"/proc/{pid}")

        def alive(p):
            # in THIS test the orphan stays our child, so a killed orphan
            # lingers as a zombie (state Z) until reaped — dead either way
            try:
                with open(f"/proc/{p}/stat", "rb") as f:
                    stat = f.read()
                return stat[stat.rindex(b")") + 2 :].split()[0] != b"Z"
            except OSError:
                return False

        second = PrimeManager(job, state_backend=backend)
        deadline = time.time() + 10
        while time.time() < deadline and alive(pid):
            time.sleep(0.1)
        try:
            assert not alive(pid), "orphan role survived master recovery"
        finally:
            second.stop()
            try:
                os.kill(pid, 9)
            except OSError:
                pass


class TestRoleComm:
    """Role-to-role RPC + queue helpers (VERDICT r2 #4; reference
    unified/api/runtime/ rpc_helper.py + queue.py)."""

    def _role_env(self, role, index=0, world=1, job="commjob"):
        return {
            "DLROVER_ROLE": role,
            "DLROVER_ROLE_INDEX": str(index),
            "DLROVER_ROLE_WORLD": str(world),
            "DLROVER_UNIFIED_JOB": job,
        }

    def test_rpc_export_and_call(self, tmp_ipc_dir, monkeypatch):
        import dlrover_tpu.unified.comm as comm

        for k, v in self._role_env("rollout").items():
            monkeypatch.setenv(k, v)
        monkeypatch.setattr(comm, "_rpc_server", None)
        calls = []
        comm.export_rpc_method("ping", lambda x: calls.append(x) or x + 1)
        try:
            # a "peer" (same process, different client) calls by name
            assert comm.call_role("rollout", "ping", 41) == 42
            assert calls == [41]
            with pytest.raises(RuntimeError, match="exports no rpc"):
                comm.call_role("rollout", "nope")
        finally:
            comm._server().stop()
            monkeypatch.setattr(comm, "_rpc_server", None)

    def test_rpc_instance_export_and_group(self, tmp_ipc_dir, monkeypatch):
        import dlrover_tpu.unified.comm as comm

        for k, v in self._role_env("actor").items():
            monkeypatch.setenv(k, v)
        monkeypatch.setattr(comm, "_rpc_server", None)

        class Policy:
            @comm.rpc()
            def version(self):
                return 7

            @comm.rpc("rename")
            def other(self):
                return "renamed"

        comm.export_rpc_instance("policy", Policy())
        try:
            assert comm.call_role("actor", "policy.version") == 7
            assert comm.call_role("actor", "policy.rename") == "renamed"
            group = comm.RoleGroup("actor", world=1)
            assert group.call("policy.version") == [7]
        finally:
            comm._server().stop()
            monkeypatch.setattr(comm, "_rpc_server", None)

    def test_data_queue_batches_and_array_codec(self, tmp_ipc_dir):
        import numpy as np

        from dlrover_tpu.unified.comm import (
            DataQueue,
            pack_array,
            queue_batches,
            unpack_array,
        )

        owner = DataQueue("exp_test", is_master=True, size=8)
        client = DataQueue("exp_test")
        try:
            arr = np.arange(6, dtype=np.float32).reshape(2, 3)
            client.put({"a": pack_array(arr)}, {"a": pack_array(arr * 2)})
            batches = list(
                queue_batches(owner, batch_size=2, max_batches=1, timeout=5)
            )
            assert len(batches) == 1 and len(batches[0]) == 2
            np.testing.assert_array_equal(
                unpack_array(batches[0][0]["a"]), arr
            )
            np.testing.assert_array_equal(
                unpack_array(batches[0][1]["a"]), arr * 2
            )
            assert owner.qsize() == 0
        finally:
            client.close()
            owner.close()

    def test_queue_backpressure(self, tmp_ipc_dir):
        from dlrover_tpu.unified.comm import DataQueue

        owner = DataQueue("bp_test", is_master=True, size=2)
        try:
            owner.put(1, 2)
            with pytest.raises(TimeoutError):
                owner.put(3, timeout=0.2)
            assert owner.get(2, timeout=1) == [1, 2]
        finally:
            owner.close()


PPO_SCRIPT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "examples",
    "unified",
    "ppo_toy.py",
)


class TestPpoE2E:
    """The toy PPO loop: rollout -> queue -> trainer, weights -> rollout
    (reference examples/unified/rl/openrlhf/ppo/main.py:26-60)."""

    def _job(self, tmp_path, name, updates=30):
        out = tmp_path / "out"
        env = {
            "PPO_OUT_DIR": str(out),
            "PPO_UPDATES": str(updates),
            "PPO_ROLLOUTS": "1",
            "PPO_SYNC_EVERY": "5",
        }
        job = (
            RLJobBuilder(name)
            .node_num(1)
            .device_per_node(4)
            .trainer([sys.executable, PPO_SCRIPT], num=1, device=1.0, env=env)
            .rollout(
                [sys.executable, PPO_SCRIPT],
                num=1,
                device=1.0,
                env=env,
                restart_dependents=[],  # trainer survives rollout kills
            )
            .build()
        )
        return job, out

    def test_data_flows_and_weights_sync(self, tmp_path):
        import json
        import uuid

        job, out = self._job(tmp_path, f"ppo_{uuid.uuid4().hex[:8]}")
        manager = PrimeManager(
            job, log_dir=str(tmp_path / "logs"), monitor_interval=0.2
        )
        manager.start()
        try:
            assert manager.wait(timeout=120) == JobStatus.SUCCEEDED
        finally:
            manager.stop(status=manager.status)
        result = json.loads((out / "trainer_result.json").read_text())
        assert result["updates"] == 30
        # trainer learned the target through the experience stream
        assert abs(result["w"] - 3.0) < 0.5, result

    @pytest.mark.slow  # ~3 min: full PPO loop + mid-loop kill; the
    # tier-1 representative is test_data_flows_and_weights_sync, and
    # kill-recovery stays drilled by test_zz_chaos_e2e's storm smoke
    # and the fleet failover e2e
    def test_mid_loop_rollout_kill_recovers(self, tmp_path):
        """SIGKILL the rollout mid-loop: the manager restarts it, the
        re-bound RPC/queue endpoints pick the flow back up, and the job
        still completes with the trainer uninterrupted."""
        import json
        import signal
        import uuid

        job, out = self._job(
            tmp_path, f"ppo_{uuid.uuid4().hex[:8]}", updates=60
        )
        manager = PrimeManager(
            job, log_dir=str(tmp_path / "logs"), monitor_interval=0.2
        )
        manager.start()
        try:
            # let the pipeline flow, then kill the rollout process
            deadline = time.time() + 30
            rollout = manager._workers.get("rollout-0")
            while time.time() < deadline and (
                rollout is None or rollout.pid is None
            ):
                time.sleep(0.1)
                rollout = manager._workers.get("rollout-0")
            assert rollout is not None and rollout.pid is not None
            time.sleep(1.0)  # mid-loop
            os.kill(rollout.pid, signal.SIGKILL)
            assert manager.wait(timeout=180) == JobStatus.SUCCEEDED
        finally:
            manager.stop(status=manager.status)
        restarted = manager.graph.vertices["rollout-0"].restart_count
        assert restarted >= 1, "rollout was never restarted"
        result = json.loads((out / "trainer_result.json").read_text())
        assert result["updates"] == 60
        assert abs(result["w"] - 3.0) < 0.5, result


class TestMasterCommService:
    """Cluster-wide role comm over the DCN RPC (reference: Ray queues
    reach any actor in the cluster; the unix-socket DataQueue is the
    same-host fast path only)."""

    @pytest.fixture()
    def service(self):
        from dlrover_tpu.unified.comm_service import UnifiedCommService

        svc = UnifiedCommService()
        yield svc
        svc.stop()

    def test_responses_stamp_master_epoch(self):
        """epoch-fence regression: every unified comm response carries
        the master_epoch stamp (0 = journal-less, an explicit decision)
        on the success, unknown-message and handler-error paths."""
        from dlrover_tpu.common import comm
        from dlrover_tpu.common.serialize import dumps, loads
        from dlrover_tpu.unified.comm_service import (
            UKvSet,
            UnifiedCommServicer,
        )

        servicer = UnifiedCommServicer()
        for msg, ok in (
            (UKvSet(key="k", value=1), True),
            (comm.HeartbeatRequest(node_id=0), False),  # unknown here
        ):
            resp = loads(servicer.get(dumps(msg)))
            assert isinstance(resp, comm.BaseResponse)
            assert resp.master_epoch == 0
            assert resp.success is ok

    def test_queue_roundtrip_across_clients(self, service):
        from dlrover_tpu.unified.comm_service import MasterDataQueue

        producer = MasterDataQueue("exp", addr=service.local_addr)
        consumer = MasterDataQueue("exp", addr=service.local_addr)
        producer.put({"x": 1.0}, {"x": 2.0}, [1, 2, 3])
        assert consumer.qsize() == 3
        batch = consumer.get(batch_size=3, timeout=10)
        assert batch == [{"x": 1.0}, {"x": 2.0}, [1, 2, 3]]
        assert consumer.get(batch_size=1, timeout=0.2) == []

    def test_queue_backpressure_and_timeout(self, service):
        from dlrover_tpu.unified.comm_service import MasterDataQueue

        service._servicer._default_size = 2
        q = MasterDataQueue("small", addr=service.local_addr)
        q.put(1, 2)
        import pytest as _pytest

        with _pytest.raises(TimeoutError):
            q.put(3, timeout=0.5)
        assert q.get(2, timeout=5) == [1, 2]

    def test_kv_roundtrip(self, service):
        from dlrover_tpu.unified.comm_service import MasterKV

        kv = MasterKV(addr=service.local_addr)
        assert kv.get("w", default="none") == "none"
        kv.set("w", {"version": 3, "data": [0.5, 0.25]})
        assert kv.get("w")["version"] == 3

    def test_missing_addr_raises_clearly(self, monkeypatch):
        from dlrover_tpu.unified.comm_service import (
            ADDR_ENV,
            MasterDataQueue,
        )

        monkeypatch.delenv(ADDR_ENV, raising=False)
        with pytest.raises(RuntimeError, match="DLROVER_UNIFIED_COMM_ADDR"):
            MasterDataQueue("q")

    def test_roles_receive_comm_addr(self, tmp_path):
        """Every role process (plain AND elastic) gets the service
        address in its env contract."""
        from dlrover_tpu.unified.comm_service import ADDR_ENV

        marker = tmp_path / "out"
        marker.mkdir()
        cmd = _script(
            tmp_path,
            "addr.py",
            "import os, pathlib\n"
            f"pathlib.Path(r'{marker}', os.environ['DLROVER_ROLE'])"
            ".write_text(os.environ.get('DLROVER_UNIFIED_COMM_ADDR', ''))\n",
        )
        job = (
            DLJobBuilder("commaddr")
            .node_num(1)
            .device_per_node(2)
            .role("trainer", cmd, num=1, device=1.0)
            .build()
        )
        manager = PrimeManager(job, log_dir=str(tmp_path / "logs"))
        manager.start()
        try:
            assert manager.wait(timeout=30) == JobStatus.SUCCEEDED
        finally:
            manager.stop(status=manager.status)
        addr = (marker / "trainer").read_text()
        # routable export (loopback only as a resolution fallback)
        assert addr == manager.comm_service.addr
        assert addr.endswith(f":{manager.comm_service.port}")


@pytest.mark.slow
def test_elastic_role_consumes_master_queue(tmp_path):
    """The cluster comm path closes the elastic-role gap: a plain
    producer role feeds MasterDataQueue; the consumer is an elastic=True
    role (own tpurun world + isolated IPC namespace, where the
    unix-socket helpers refuse) reading the SAME queue through
    DLROVER_UNIFIED_COMM_ADDR."""
    out = tmp_path / "out"
    out.mkdir()
    producer = _script(
        tmp_path,
        "producer.py",
        "import os, sys\n"
        f"sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(__file__)))!r})\n"
        "from dlrover_tpu.unified.comm_service import MasterDataQueue\n"
        "q = MasterDataQueue('eq')\n"
        "for v in range(1, 11):\n"
        "    q.put(v, timeout=30)\n"
        "print('produced 10')\n",
    )
    trainer = tmp_path / "train.py"
    trainer.write_text(
        "import os, sys, pathlib\n"
        f"sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(__file__)))!r})\n"
        "from dlrover_tpu.unified.comm_service import MasterDataQueue\n"
        "from dlrover_tpu.unified.comm import DataQueue\n"
        "# the process-local helper must refuse inside an elastic role\n"
        "try:\n"
        "    DataQueue('eq')\n"
        "    refused = False\n"
        "except RuntimeError as e:\n"
        "    refused = 'MasterDataQueue' in str(e)\n"
        "q = MasterDataQueue('eq')\n"
        "total, got = 0, 0\n"
        "while got < 10:\n"
        "    for v in q.get(batch_size=10, timeout=30, retry_for=30):\n"
        "        total += v; got += 1\n"
        f"pathlib.Path(r'{out}', 'sum').write_text(f'{{total}},{{refused}}')\n",
    )
    job = (
        DLJobBuilder("elq")
        .node_num(1)
        .device_per_node(2)
        .role("rollout", producer, num=1, device=0.5)
        .role(
            "trainer", [str(trainer)], num=1, device=1.0, elastic=True
        )
        .build()
    )
    manager = PrimeManager(job, log_dir=str(tmp_path / "logs"))
    env_backup = dict(os.environ)
    os.environ["PYTHONPATH"] = os.pathsep.join(sys.path)
    try:
        manager.start()
        assert manager.wait(timeout=120) == JobStatus.SUCCEEDED
    finally:
        manager.stop(manager.status)
        os.environ.clear()
        os.environ.update(env_backup)
    total, refused = (out / "sum").read_text().split(",")
    assert int(total) == sum(range(1, 11))
    assert refused == "True", "local DataQueue did not refuse in elastic role"


class TestP2PPayloadPath:
    """VERDICT r3 #6: payload bytes go producer→consumer directly; the
    master brokers only tiny envelopes (Ray-object-store shape,
    reference unified/api/runtime/queue.py:123)."""

    @pytest.fixture()
    def service(self):
        from dlrover_tpu.unified.comm_service import UnifiedCommService
        from dlrover_tpu.unified.payload import PayloadServer

        svc = UnifiedCommService()
        yield svc
        svc.stop()
        PayloadServer.reset_singleton()

    def _big_item(self, nbytes, seed=0):
        import numpy as np

        from dlrover_tpu.unified.comm import pack_array

        return {
            "obs": pack_array(
                np.full(nbytes // 4, seed, dtype=np.float32)
            ),
            "seed": seed,
        }

    def test_payload_bytes_bypass_master(self, service):
        from dlrover_tpu.unified.comm import unpack_array
        from dlrover_tpu.unified.comm_service import MasterDataQueue

        producer = MasterDataQueue("p2p", addr=service.local_addr)
        consumer = MasterDataQueue("p2p", addr=service.local_addr)
        payload = 512 * 1024  # 512 KB, far above INLINE_MAX
        before = producer.comm_stats()["bytes_in"]
        producer.put(*[self._big_item(payload, i) for i in range(4)])
        master_bytes = producer.comm_stats()["bytes_in"] - before
        assert master_bytes < payload, (
            f"puts moved {master_bytes} bytes through the master for "
            f"4x{payload}B items — payloads are transiting the master"
        )
        batch = consumer.get(batch_size=4, timeout=20)
        assert len(batch) == 4
        for item in batch:
            arr = unpack_array(item["obs"])
            assert arr.shape == (payload // 4,)
            assert float(arr[0]) == item["seed"]

    def test_master_load_flat_in_payload_size(self, service):
        """10x the payload must not 10x the master's byte load."""
        from dlrover_tpu.unified.comm_service import MasterDataQueue

        q = MasterDataQueue("flat", addr=service.local_addr)
        c = MasterDataQueue("flat", addr=service.local_addr)

        def master_cost(nbytes):
            s0 = q.comm_stats()
            q.put(self._big_item(nbytes))
            assert len(c.get(1, timeout=20)) == 1
            s1 = q.comm_stats()
            return (s1["bytes_in"] - s0["bytes_in"]) + (
                s1["bytes_out"] - s0["bytes_out"]
            )

        small = master_cost(128 * 1024)
        big = master_cost(1280 * 1024)
        assert big < small * 3, (small, big)

    def test_small_items_stay_inline(self, service):
        from dlrover_tpu.unified import payload as p
        from dlrover_tpu.unified.comm_service import MasterDataQueue

        q = MasterDataQueue("inline", addr=service.local_addr)
        c = MasterDataQueue("inline", addr=service.local_addr)
        q.put({"tiny": 1})
        # no payload server should have been spun up for a tiny item
        assert p.PayloadServer._instance is None
        assert c.get(1, timeout=10) == [{"tiny": 1}]

    def test_dead_producer_item_dropped_not_wedged(self, service):
        from dlrover_tpu.unified import payload as p
        from dlrover_tpu.unified.comm_service import MasterDataQueue

        q = MasterDataQueue("dead", addr=service.local_addr)
        c = MasterDataQueue("dead", addr=service.local_addr)
        q.put(self._big_item(256 * 1024))
        p.PayloadServer.reset_singleton()  # producer dies
        assert c.get(1, timeout=1.5) == []  # dropped, no hang
        # queue stays usable for inline traffic afterwards
        q.put({"ok": True})
        assert c.get(1, timeout=10) == [{"ok": True}]

    def test_store_cap_refuses_and_ttl_expires(self):
        """Overflow REFUSES (caller falls back to inline, master queue
        back-pressures) — never evicts a live enqueued ticket, which
        would be guaranteed data loss. Only TTL-expired tickets are
        reclaimed."""
        from dlrover_tpu.unified.payload import PayloadStore

        store = PayloadStore(cap_bytes=100, ttl_s=1000)
        t1 = store.put(b"x" * 60)
        assert store.put(b"y" * 60) is None  # no room: refused
        assert store.get(t1) == b"x" * 60  # t1 untouched
        store.ack(t1)
        assert store.get(t1) is None and store.nbytes == 0
        assert store.put(b"y" * 60) is not None  # room again

        store = PayloadStore(cap_bytes=10_000, ttl_s=0.05)
        t3 = store.put(b"z" * 10)
        time.sleep(0.1)
        assert store.put(b"w") is not None  # triggers the TTL sweep
        assert store.get(t3) is None

    def test_fetch_requires_token(self, service):
        import urllib.error
        import urllib.request

        from dlrover_tpu.unified.payload import PayloadServer, fetch

        server = PayloadServer.singleton()
        ticket = server.store.put(b"secret" * 100)
        addr = f"127.0.0.1:{server._httpd.server_address[1]}"
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            urllib.request.urlopen(
                f"http://{addr}/payload/{ticket}", timeout=5
            )
        assert exc_info.value.code == 403
        assert fetch(addr, ticket) == b"secret" * 100
        PayloadServer.reset_singleton()


class TestRpcHelperDepth:
    """Round-4 unified runtime depth (VERDICT r3 missing #4; reference
    rpc_helper.py futures/typed proxies + ray_dataloader_iter.py
    prefetching)."""

    def _role_env(self, role, index=0, world=1, job="rpcdepth"):
        return {
            "DLROVER_ROLE": role,
            "DLROVER_ROLE_INDEX": str(index),
            "DLROVER_ROLE_WORLD": str(world),
            "DLROVER_UNIFIED_JOB": job,
        }

    @pytest.fixture()
    def rollout_role(self, tmp_ipc_dir, monkeypatch):
        import dlrover_tpu.unified.comm as comm

        for k, v in self._role_env("rollout").items():
            monkeypatch.setenv(k, v)
        monkeypatch.setattr(comm, "_rpc_server", None)
        yield comm
        comm._server().stop()
        monkeypatch.setattr(comm, "_rpc_server", None)

    def test_async_call_and_future_group(self, rollout_role):
        import time as _time

        from dlrover_tpu.unified.rpc_helper import call_role_async

        comm = rollout_role

        def slow_double(x):
            _time.sleep(0.2)
            return x * 2

        comm.export_rpc_method("slow_double", slow_double)
        t0 = _time.time()
        futures = [call_role_async("rollout", "slow_double", i) for i in range(3)]
        assert [f.result(timeout=10) for f in futures] == [0, 2, 4]
        # concurrent, not serial: 3 x 0.2s overlapped
        assert _time.time() - t0 < 0.55

        group = comm.RoleGroup("rollout", world=1)
        fg = group.call_async("slow_double", 21)
        assert fg.wait(timeout=10) == [42]
        assert len(fg) == 1

    def test_call_rank0_and_call_batch(self, rollout_role):
        comm = rollout_role
        seen = []

        def record(tag, extra=None):
            seen.append((tag, extra))
            return tag

        comm.export_rpc_method("record", record)
        group = comm.RoleGroup("rollout", world=1)
        # rank0: exactly one call, to instance 0
        assert group.call_rank0("record", "only0").result(timeout=10) == "only0"
        # scatter: per-instance args (tuple form and bare form)
        fg = group.call_batch("record", [("shard0", 7)])
        assert fg.wait(timeout=10) == ["shard0"]
        fg2 = group.call_batch("record", ["bare"])
        assert fg2.wait(timeout=10) == ["bare"]
        assert ("shard0", 7) in seen and ("bare", None) in seen
        # scatter length must match the role world
        import pytest as _pytest

        with _pytest.raises(ValueError, match="args_list has 2 items"):
            group.call_batch("record", ["a", "b"])

    def test_typed_proxy_follows_rpc_contract(self, rollout_role):
        from dlrover_tpu.unified.rpc_helper import create_rpc_proxy

        comm = rollout_role

        class Policy:
            @comm.rpc()
            def version(self):
                return 9

            @comm.rpc("score")
            def compute_score(self, x):
                return x + 0.5

            def not_exported(self):  # undecorated: NOT on the wire
                raise AssertionError

        comm.export_rpc_instance("policy", Policy())
        proxy = create_rpc_proxy("rollout", Policy, ns="policy")
        assert proxy.version() == 9
        # renamed method: attribute keeps the PYTHON name, wire uses
        # the exported one
        assert proxy.compute_score(2) == 2.5
        assert not hasattr(proxy, "not_exported")
        # async variant rides the same wire name
        assert proxy.version.async_call().result(timeout=10) == 9

    def test_remote_batch_iterator_prefetches_and_ends(self, rollout_role):
        import time as _time

        from dlrover_tpu.unified.dataloader_iter import RemoteBatchIterator

        comm = rollout_role
        served = list(range(6))
        fetch_times = []

        def fetch(i):
            fetch_times.append(_time.time())
            _time.sleep(0.05)
            if i >= len(served):
                raise StopIteration
            return {"batch": served[i]}

        comm.export_rpc_method("fetch", fetch)
        it = RemoteBatchIterator(
            "rollout", "fetch", prefetch=2, index_fn=lambda i: i
        )
        got = [b["batch"] for b in it]
        assert got == served
        with pytest.raises(StopIteration):
            next(it)

    def test_remote_iterator_streaming_none_terminates(self, rollout_role):
        from dlrover_tpu.unified.dataloader_iter import RemoteBatchIterator

        comm = rollout_role
        remaining = [3, 2, 1]

        def next_batch():
            return remaining.pop() if remaining else None

        comm.export_rpc_method("next_batch", next_batch)
        it = RemoteBatchIterator("rollout", "next_batch", prefetch=1)
        assert sorted(list(it)) == [1, 2, 3]


class TestGrpoE2E:
    """GRPO with real arrays across the cluster-wide runtime
    (examples/unified/grpo_jax.py): typed reward proxy + async futures,
    MasterDataQueue batches (p2p-eligible packed arrays), MasterKV
    weight sync, real jax grads in the learner. Convergence proves every
    hop carried faithful data."""

    @pytest.mark.slow
    def test_grpo_converges_across_roles(self, tmp_path):
        import json

        script = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "examples",
            "unified",
            "grpo_jax.py",
        )
        out = tmp_path / "grpo"
        env = {
            "GRPO_OUT_DIR": str(out),
            "GRPO_UPDATES": "30",
            "GRPO_PROMPTS": "48",
            # batches are a few KB; force them onto the REAL p2p
            # payload path so this e2e exercises producer-served bytes
            "DLROVER_UNIFIED_P2P_INLINE_MAX": "2048",
            "PYTHONPATH": os.pathsep.join(sys.path),
        }
        job = (
            RLJobBuilder("grpo-e2e")
            .node_num(1)
            .device_per_node(4)
            .trainer([sys.executable, script], num=1, device=1.5, env=env)
            .rollout([sys.executable, script], num=2, device=0.5, env=env)
            .reward([sys.executable, script], num=1, device=0.5, env=env)
            .role("dataset", [sys.executable, script], num=1, device=0.5,
                  env=env)
            .build()
        )
        manager = PrimeManager(job, log_dir=str(tmp_path / "logs"))
        manager.start()
        try:
            assert manager.wait(timeout=240) == JobStatus.SUCCEEDED
        finally:
            manager.stop(manager.status)
        result = json.loads((out / "learner_result.json").read_text())
        assert result["updates"] == 30
        # uniform policy emits the target 12.5% of the time; a learned
        # one must be far beyond noise
        assert result["p_target"] >= 0.5, result


class TestPayloadServerConcurrency:
    """The producer's payload server under concurrent consumers — the
    load pattern a real RL job creates (many learner threads fetching
    tickets from one rollout)."""

    def test_parallel_fetches_and_acks(self):
        import concurrent.futures

        from dlrover_tpu.unified.payload import PayloadServer, fetch

        server = PayloadServer.singleton()
        try:
            addr = f"127.0.0.1:{server._httpd.server_address[1]}"
            blobs = {
                server.store.put(bytes([i]) * 50_000): bytes([i]) * 50_000
                for i in range(8)
            }
            with concurrent.futures.ThreadPoolExecutor(8) as pool:
                results = list(
                    pool.map(
                        lambda t: (t, fetch(addr, t)), list(blobs)
                    )
                )
            for ticket, data in results:
                assert data == blobs[ticket]
            # store drains fully once every consumer acks
            from dlrover_tpu.unified.payload import ack

            for ticket in blobs:
                ack(addr, ticket)
            assert server.store.nbytes == 0
        finally:
            PayloadServer.reset_singleton()


@pytest.mark.skipif(
    sys.version_info < (3, 12),
    reason="sys.monitoring (PEP 669) needs Python 3.12",
)
class TestTracerThreadSafety:
    def test_traced_function_from_multiple_threads(self):
        """Per-thread timing stacks: concurrent traced calls must not
        cross-pollinate durations."""
        import threading as _threading
        import time as _time

        from dlrover_tpu.profiler.py_tracer import FunctionTracer

        tracer = FunctionTracer()

        def work30():
            _time.sleep(0.03)

        def work60():
            _time.sleep(0.06)

        assert tracer.add_target(work30, name="w30")
        assert tracer.add_target(work60, name="w60")
        assert tracer.install()
        try:
            threads = [
                _threading.Thread(target=fn)
                for fn in (work30, work60, work30, work60)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert tracer.calls == 4
            import tempfile

            from dlrover_tpu.profiler.timeline import (
                read_names,
                read_timeline,
            )

            path = tempfile.mktemp(suffix=".timeline")
            assert tracer.timer.dump_timeline(path) > 0
            names = read_names(path + ".names")
            by_name = {}
            for e in read_timeline(path):
                by_name.setdefault(names.get(e.name_id), []).append(e.dur_us)
            # Cross-thread stack smearing would pop the WRONG t0 and
            # record a duration shorter than the function's own sleep;
            # the lower bounds are load-immune (sleeps only stretch
            # under contention, never shrink).
            assert len(by_name.get("host_py_w30", [])) == 2, by_name
            assert len(by_name.get("host_py_w60", [])) == 2, by_name
            assert all(d >= 25_000 for d in by_name["host_py_w30"]), by_name
            assert all(d >= 50_000 for d in by_name["host_py_w60"]), by_name
        finally:
            tracer.uninstall()


class TestPytreeCodec:
    """pack_pytree/unpack_pytree — the learner→rollout weight-sync
    primitive (examples/unified/grpo_llm.py publishes params this way;
    reference ships torch state dicts through Ray's object store)."""

    def test_roundtrip_preserves_values_and_structure(self):
        import jax.numpy as jnp
        import numpy as np

        from dlrover_tpu.unified.comm import pack_pytree, unpack_pytree

        tree = {
            "layer": {"w": jnp.arange(6.0).reshape(2, 3), "b": jnp.ones(3)},
            "scale": jnp.asarray(2.5),
        }
        blob = pack_pytree(tree)
        # wire dict is msgpack-able primitives only
        for leaf in blob["leaves"]:
            assert isinstance(leaf["data"], bytes)
        out = unpack_pytree(blob, tree)
        assert set(out) == {"layer", "scale"}
        np.testing.assert_array_equal(out["layer"]["w"], np.asarray(tree["layer"]["w"]))
        np.testing.assert_array_equal(out["layer"]["b"], np.asarray(tree["layer"]["b"]))
        assert float(out["scale"]) == 2.5

    def test_leaf_count_mismatch_fails_loudly(self):
        import jax.numpy as jnp
        import pytest as _pytest

        from dlrover_tpu.unified.comm import pack_pytree, unpack_pytree

        blob = pack_pytree({"a": jnp.ones(2)})
        with _pytest.raises(ValueError, match="leaf count mismatch"):
            unpack_pytree(blob, {"a": jnp.ones(2), "b": jnp.ones(2)})


class TestWeightBus:
    """Versioned weight publication: the blob crosses the wire only
    when the version advanced (the probe-key protocol grpo_llm.py
    established, now a comm primitive)."""

    class _CountingKV:
        def __init__(self):
            self.store = {}
            self.gets = []

        def set(self, key, value):
            self.store[key] = value

        def get(self, key, default=None):
            self.gets.append(key)
            return self.store.get(key, default)

    def test_poll_fetches_blob_only_on_new_version(self):
        import jax.numpy as jnp

        from dlrover_tpu.unified.comm import WeightBus

        kv = self._CountingKV()
        template = {"w": jnp.zeros(3), "b": jnp.zeros(())}
        producer = WeightBus(kv, name="policy")
        consumer = WeightBus(kv, name="policy")

        # nothing published yet
        tree, ver = consumer.poll(template)
        assert tree is None and ver == -1
        assert kv.gets == ["policy_version"]  # no blob fetch

        producer.publish({"w": jnp.ones(3), "b": jnp.asarray(2.0)}, 0)
        tree, ver = consumer.poll(template)
        assert ver == 0 and float(tree["b"]) == 2.0
        assert kv.gets.count("policy") == 1

        # same version: only the probe key is read again
        tree, ver = consumer.poll(template)
        assert tree is None and ver == 0
        assert kv.gets.count("policy") == 1

        producer.publish({"w": jnp.full(3, 5.0), "b": jnp.asarray(7.0)}, 1)
        tree, ver = consumer.poll(template)
        assert ver == 1 and float(tree["w"][0]) == 5.0
        assert kv.gets.count("policy") == 2

    def test_publish_orders_probe_key_last(self):
        from dlrover_tpu.unified.comm import WeightBus

        order = []

        class _KV(self._CountingKV):
            def set(inner, key, value):
                order.append(key)
                super().set(key, value)

        import jax.numpy as jnp

        WeightBus(_KV(), name="policy").publish({"w": jnp.ones(2)}, 3)
        assert order == ["policy", "policy_version"]
