"""Chaos e2e with a REAL trainer: kill -> re-mesh -> resume-from-memory.

The round-1 chaos test proved node replacement with sleep-script
workers; this one closes the loop on the product's core scenario
(reference call stack §3.4: training.py:1216 -> engine.py:375-409): a
tiny GPT trains under the elastic agents, flash-checkpoints every step
into host shm, a node is SIGKILLed, the master replaces it, and BOTH
workers resume from their staged shm step — step sequences stay
strictly increasing (no step re-trained, none skipped past a gap of
one) and the loss keeps improving across the kill.

The trainer runs far longer than the test needs (TOTAL_STEPS=600) so
the surviving rank can never finish before the replacement re-joins the
rendezvous — job COMPLETION under elasticity is covered separately by
test_elastic_e2e.py; this test is about checkpoint/resume continuity.
"""

import os
import signal
import subprocess
import sys
import time

import pytest

from dlrover_tpu.common.constants import JobExitReason

TRAINER = r'''
import os, sys, time, pathlib
from dlrover_tpu.common.platform import force_virtual_cpu
force_virtual_cpu(1)
import numpy as np
import jax
import jax.numpy as jnp

from dlrover_tpu.checkpoint.engine import CheckpointEngine
from dlrover_tpu.models.gpt import GPT, GPTConfig, cross_entropy_loss
from dlrover_tpu.parallel.mesh import MeshConfig, build_mesh
from dlrover_tpu.parallel.train_step import (
    build_train_step, default_optimizer, init_train_state,
)

TOTAL_STEPS = 600
rank = int(os.environ["DLROVER_NODE_RANK"])
out_dir = pathlib.Path(os.environ["PROGRESS_DIR"])
ckpt_dir = pathlib.Path(os.environ["CKPT_DIR"]) / f"rank{rank}"
ckpt_dir.mkdir(parents=True, exist_ok=True)
progress = out_dir / f"progress_{rank}.txt"

cfg = GPTConfig.tiny()
model = GPT(cfg)
mesh = build_mesh(MeshConfig(dp=-1), jax.devices()[:1])
tx = default_optimizer(learning_rate=1e-2, warmup_steps=2)
tokens = jnp.zeros((2, cfg.max_seq_len), jnp.int32)
state, shardings = init_train_state(model, tokens, mesh, tx)
step_fn = build_train_step(model, tx, cross_entropy_loss, mesh, shardings)

r = np.random.default_rng(rank)
x = jnp.asarray(r.integers(0, cfg.vocab_size, (2, cfg.max_seq_len)), jnp.int32)
y = jnp.roll(x, -1, axis=1)

engine = CheckpointEngine(
    str(ckpt_dir), mesh=mesh, host_rank=rank, num_hosts=1, replicate=False
)
start = 0
loaded_step, restored = engine.load(state)
if loaded_step >= 0 and restored is not None:
    state = restored
    start = loaded_step + 1
    with open(out_dir / f"resumed_{rank}_{loaded_step}", "w") as f:
        f.write(str(os.getpid()))

for step in range(start, TOTAL_STEPS):
    state, loss = step_fn(state, x, y)
    loss_val = float(loss)
    assert np.isfinite(loss_val), loss_val
    if not engine.save_to_memory(step, state):
        # persister briefly held the lock; acceptable skip
        pass
    with open(progress, "a") as f:
        f.write(f"{step} {loss_val:.6f}\n")
    time.sleep(0.35)

print(f"rank {rank} finished at step {TOTAL_STEPS - 1}", flush=True)
'''


def _read_progress(path):
    rows = []
    if not path.exists():
        return rows
    for line in path.read_text().splitlines():
        step, loss = line.split()
        rows.append((int(step), float(loss)))
    return rows


def _cleanup_namespaces():
    from dlrover_tpu.agent.worker import kill_worker_by_pidfile

    for job in ("chaos_train_e2e_n0", "chaos_train_e2e_n1"):
        kill_worker_by_pidfile(job)
        for name in os.listdir("/dev/shm"):
            if name.startswith(f"dlrover_{job}_"):
                try:
                    os.unlink(os.path.join("/dev/shm", name))
                except OSError:
                    pass


@pytest.mark.slow
def test_kill_node_resumes_training_from_memory(tmp_path):
    _cleanup_namespaces()  # a previously aborted run must not leak state
    progress_dir = tmp_path / "progress"
    ckpt_dir = tmp_path / "ckpt"
    progress_dir.mkdir()
    ckpt_dir.mkdir()
    script = tmp_path / "train_gpt.py"
    script.write_text(TRAINER)

    from e2e_utils import make_process_master

    master, scaler, watcher = make_process_master(
        "chaos_train_e2e",
        command=[
            sys.executable,
            "-m",
            "dlrover_tpu.launcher.elastic_run",
            # CPU host simulation: also keeps profile-auto (TPU-only) off
            "--accelerator",
            "cpu",
            "--nnodes",
            "2",
            "--max_restarts",
            "3",
            str(script),
        ],
        env={
            "PROGRESS_DIR": str(progress_dir),
            "CKPT_DIR": str(ckpt_dir),
            "DLROVER_LOCAL_DEVICES": "1",
            "PYTHONPATH": os.pathsep.join(sys.path),
        },
        num_workers=2,
    )
    p0 = progress_dir / "progress_0.txt"
    p1 = progress_dir / "progress_1.txt"
    try:
        master.prepare()
        master.run_in_background()

        # let both ranks train a few real steps
        deadline = time.time() + 120
        while time.time() < deadline:
            if len(_read_progress(p0)) >= 4 and len(_read_progress(p1)) >= 4:
                break
            time.sleep(0.5)
        assert len(_read_progress(p0)) >= 4, "rank 0 never trained"
        assert len(_read_progress(p1)) >= 4, "rank 1 never trained"

        # chaos: SIGKILL node 0's agent (whole process group)
        steps_before_kill = len(_read_progress(p0))
        handle = scaler._procs[0]
        os.killpg(handle.proc.pid, signal.SIGKILL)

        # the replacement must RESUME, not restart: a resumed_0_* marker
        # appears and training continues past the staged step
        deadline = time.time() + 180
        while time.time() < deadline:
            if list(progress_dir.glob("resumed_0_*")):
                break
            time.sleep(0.5)
        markers = list(progress_dir.glob("resumed_0_*"))
        assert markers, "rank 0 never resumed from its shm checkpoint"
        resumed_step = int(markers[0].name.rsplit("_", 1)[-1])
        assert resumed_step >= steps_before_kill - 2, (
            f"resumed from step {resumed_step}, but ~{steps_before_kill} "
            "steps were staged — memory checkpoint was not used"
        )

        # both ranks must make post-resume progress (rank 1 is restarted
        # by the membership change and resumes from ITS shm step too)
        resumed_len = {0: None, 1: None}
        deadline = time.time() + 120
        while time.time() < deadline:
            m1 = list(progress_dir.glob("resumed_1_*"))
            if m1 and resumed_len[1] is None:
                resumed_len[1] = len(_read_progress(p1))
            if resumed_len[0] is None:
                resumed_len[0] = len(_read_progress(p0))
            if (
                m1
                and len(_read_progress(p0)) >= resumed_len[0] + 6
                and len(_read_progress(p1)) >= (resumed_len[1] or 0) + 6
            ):
                break
            time.sleep(0.5)
        assert list(progress_dir.glob("resumed_1_*")), (
            "rank 1 was never re-meshed/resumed"
        )

        for path, rank in ((p0, 0), (p1, 1)):
            rows = _read_progress(path)
            steps = [s for s, _ in rows]
            # strictly increasing: no step was ever re-trained after the
            # kill (the staged shm step is the resume watermark)
            assert steps == sorted(set(steps)), f"rank {rank} re-trained: {steps}"
            # gaps of at most one step (save landed, append did not)
            for a, b in zip(steps, steps[1:]):
                assert b - a <= 2, f"rank {rank} skipped steps: {a}->{b}"
            # learning survived the kill: loss improved end-to-end
            assert rows[-1][1] < rows[0][1], f"rank {rank} loss did not drop"
    finally:
        master.stop()
        scaler.stop()
        _cleanup_namespaces()
