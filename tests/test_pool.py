"""Chip-pool arbiter (dlrover_tpu/pool/): ledger/lease mechanics, the
pure policy, tenant adapters, escalation, the decision journal, the
status endpoint, chaos drills, and the synthetic end-to-end
arbitration drill.

Mechanics run over FAKE tenants (scripted report/grant/revoke) so
every ledger transition is pinned without an engine; adapter tests run
over scripted HTTP replicas (drill.ScriptedReplica) and a numpy-backed
real ElasticTrainLoop; the synthetic drill exercises the whole
breach → revoke → drain → grant → READY → handback arc in-process.
The real-engine twin lives in tests/test_zz_pool_e2e.py (subprocess,
via the ``traffic_spike_preempt`` scenario).
"""

import json
import os
import threading
import time
import urllib.request

import pytest

from dlrover_tpu.chaos import faults
from dlrover_tpu.pool import (
    ChipPoolArbiter,
    LoopTrainingController,
    MasterTrainingController,
    PoolConfig,
    ServingTenant,
    TrainingTenant,
    decide,
)
from dlrover_tpu.pool.arbiter import SERVING, TRAINING, LeaseState


@pytest.fixture(autouse=True)
def fresh_saver(tmp_ipc_dir, monkeypatch):
    from dlrover_tpu.checkpoint.saver import AsyncCheckpointSaver
    from dlrover_tpu.checkpoint.shm_handler import SharedMemoryHandler

    job = f"pool_{os.getpid()}_{id(tmp_ipc_dir)}"
    monkeypatch.setenv("DLROVER_JOB_NAME", job)
    AsyncCheckpointSaver.reset()
    yield
    AsyncCheckpointSaver.reset()
    for name in os.listdir("/dev/shm"):
        if name.startswith(f"dlrover_{job}_"):
            SharedMemoryHandler(
                0, name=name.split(f"dlrover_{job}_", 1)[1]
            ).unlink()


class FakeTenant:
    """Scripted tenant: instant (or timed/stubborn) drains, recorded
    grant/revoke/escalate calls, canned signals."""

    def __init__(
        self,
        name,
        units,
        signals=None,
        drain_s=0.0,
        stubborn=False,
        escalate_frees=True,
        grant_error=False,
        report_error=False,
    ):
        self.name = name
        self.initial_units = units
        self.signals = dict(signals or {})
        self.drain_s = drain_s
        self.stubborn = stubborn
        self.escalate_frees = escalate_frees
        self.grant_error = grant_error
        self.report_error = report_error
        self.granted = []
        self.revoked = []
        self.escalated = []

    def report(self):
        if self.report_error:
            raise RuntimeError("control plane dark")
        return dict(self.signals)

    def grant(self, units):
        if self.grant_error:
            raise RuntimeError("grant failed")
        self.granted.append(units)

    def revoke(self, units, deadline_s, on_released):
        self.revoked.append(units)
        if self.stubborn:
            return  # never confirms: the arbiter must escalate
        if self.drain_s:
            threading.Timer(
                self.drain_s, lambda: on_released(units)
            ).start()
        else:
            on_released(units)

    def escalate(self, units):
        self.escalated.append(units)
        return units if self.escalate_frees else 0


def _cfg(**kw):
    base = dict(
        total_units=4,
        train_floor=1,
        serve_floor=1,
        queue_high=2.0,
        handback_evals=2,
        revoke_deadline_s=5.0,
    )
    base.update(kw)
    return PoolConfig(**base)


BREACH = {"ready": 1, "queue_mean": 5.0, "busy_total": 2, "p95_worst_s": None}
CALM = {"ready": 1, "queue_mean": 0.0, "busy_total": 0, "p95_worst_s": None}
ACTIVE = {"ready": 1, "queue_mean": 1.0, "busy_total": 1, "p95_worst_s": None}


def _arbiter(serving, training, **cfg_kw):
    return ChipPoolArbiter(serving, training, config=_cfg(**cfg_kw))


class TestPoolConfig:
    def test_ceilings_default_to_pool(self):
        cfg = PoolConfig(total_units=6)
        assert cfg.train_ceiling == 6 and cfg.serve_ceiling == 6

    def test_floor_sum_must_fit(self):
        with pytest.raises(ValueError, match="exceed the pool"):
            PoolConfig(total_units=4, train_floor=3, serve_floor=2)

    def test_floor_above_ceiling_rejected(self):
        with pytest.raises(ValueError, match="above train_ceiling"):
            PoolConfig(total_units=8, train_floor=5, train_ceiling=4)

    def test_from_env_reads_knobs(self, monkeypatch):
        monkeypatch.setenv("DLROVER_POOL_TOTAL_UNITS", "8")
        monkeypatch.setenv("DLROVER_POOL_QUEUE_HIGH", "7.5")
        monkeypatch.setenv("DLROVER_POOL_HANDBACK_EVALS", "5")
        cfg = PoolConfig.from_env()
        assert cfg.total_units == 8
        assert cfg.queue_high == 7.5
        assert cfg.handback_evals == 5

    def test_overrides_beat_env(self, monkeypatch):
        monkeypatch.setenv("DLROVER_POOL_TOTAL_UNITS", "8")
        assert PoolConfig.from_env(total_units=5).total_units == 5


class TestDecidePolicy:
    def test_no_signal_never_arbitrates(self):
        cfg = _cfg()
        out = decide(None, {SERVING: 1, TRAINING: 3}, 0, cfg, 0, 1)
        assert out["action"] is None
        out = decide(
            {"ready": 0}, {SERVING: 1, TRAINING: 3}, 0, cfg, 0, 1
        )
        assert out["action"] is None

    def test_queue_breach_preempts(self):
        out = decide(BREACH, {SERVING: 1, TRAINING: 3}, 0, _cfg(), 0, 1)
        assert out["action"] == "preempt" and out["units"] == 1

    def test_p95_breach_preempts(self):
        sig = dict(CALM, p95_worst_s=0.9, busy_total=1)
        out = decide(
            sig,
            {SERVING: 1, TRAINING: 3},
            0,
            _cfg(p95_target_s=0.5),
            0,
            1,
        )
        assert out["action"] == "preempt"
        assert "p95" in out["reason"]

    def test_breach_respects_training_floor(self):
        # training at its floor and nothing free: the breach cannot move
        out = decide(BREACH, {SERVING: 3, TRAINING: 1}, 0, _cfg(), 0, 1)
        assert out["action"] is None
        assert "no capacity movable" in out["reason"]

    def test_breach_respects_serve_ceiling(self):
        # serving is capped; the free unit it cannot take returns to
        # training instead of stranding
        out = decide(
            BREACH,
            {SERVING: 2, TRAINING: 1},
            1,
            _cfg(serve_ceiling=2),
            0,
            1,
        )
        assert out["action"] == "reclaim" and out["units"] == 1
        # and with nothing free, the breach-but-stuck verdict stands
        out = decide(
            BREACH,
            {SERVING: 2, TRAINING: 2},
            0,
            _cfg(serve_ceiling=2),
            0,
            1,
        )
        assert out["action"] is None

    def test_free_units_reclaimed_to_training(self):
        # unowned units (grid overshoot, rolled-back grants) go back
        # to training without waiting for hysteresis — they need no
        # revocation
        out = decide(CALM, {SERVING: 1, TRAINING: 2}, 1, _cfg(), 5, 1)
        assert out["action"] == "reclaim" and out["units"] == 1
        assert out["calm_streak"] == 5  # surge hysteresis undisturbed
        # disabled without a training adapter (serving-only pools)
        out = decide(
            CALM, {SERVING: 1, TRAINING: 0}, 3, _cfg(train_floor=0),
            0, 1, trainable=False,
        )
        assert out["action"] is None

    def test_handback_needs_consecutive_calm(self):
        cfg = _cfg(handback_evals=3)
        alloc = {SERVING: 2, TRAINING: 2}
        out = decide(CALM, alloc, 0, cfg, 0, 1)
        assert out["action"] is None and out["calm_streak"] == 1
        out = decide(CALM, alloc, 0, cfg, 1, 1)
        assert out["action"] is None and out["calm_streak"] == 2
        out = decide(CALM, alloc, 0, cfg, 2, 1)
        assert out["action"] == "handback" and out["units"] == 1

    def test_activity_resets_calm_streak(self):
        out = decide(ACTIVE, {SERVING: 2, TRAINING: 2}, 0, _cfg(), 5, 1)
        assert out["action"] is None and out["calm_streak"] == 0

    def test_handback_stops_at_serve_baseline(self):
        # serving at its calm baseline: nothing to hand back
        out = decide(
            CALM, {SERVING: 2, TRAINING: 2}, 0, _cfg(), 9, 2
        )
        assert out["action"] is None

    def test_handback_capped_by_train_ceiling(self):
        out = decide(
            CALM,
            {SERVING: 3, TRAINING: 1},
            0,
            _cfg(train_ceiling=1),
            9,
            1,
        )
        assert out["action"] is None


class TestArbiterLedger:
    def test_breach_takes_free_pool_first(self):
        serving = FakeTenant("serving", 1, signals=BREACH)
        training = FakeTenant("training", 2, signals={})
        arb = _arbiter(serving, training)  # 1 + 2 of 4: 1 free
        out = arb.step()
        assert out["action"] == "preempt"
        assert serving.granted == [1]
        assert training.revoked == []  # the free unit covered it
        assert arb.allocations() == {SERVING: 2, TRAINING: 2}
        assert arb.free_units() == 0
        events = [e["event"] for e in arb.journal()]
        assert events == ["breach", "grant"]

    def test_breach_revokes_training_when_pool_empty(self):
        serving = FakeTenant("serving", 1, signals=BREACH)
        training = FakeTenant("training", 3, signals={})
        arb = _arbiter(serving, training)
        arb.step()
        assert training.revoked == [1]
        assert serving.granted == [1]
        assert arb.allocations() == {SERVING: 2, TRAINING: 2}
        events = [e["event"] for e in arb.journal()]
        assert events == ["breach", "revoke", "release", "grant"]
        release = [e for e in arb.journal() if e["event"] == "release"][0]
        assert release["drain_s"] >= 0

    def test_handback_after_hysteresis(self):
        serving = FakeTenant("serving", 2, signals=CALM)
        training = FakeTenant("training", 1, signals={})
        arb = _arbiter(serving, training)
        # baseline is serving's initial 2 — drop it so surge exists
        arb._serve_baseline = 1
        # eval 1: the pool's 1 unowned free unit reclaims to training
        out = arb.step()
        assert out["action"] == "reclaim"
        assert training.granted == [1]
        assert arb.free_units() == 0
        # evals 2-3: calm hysteresis, then the surge hands back
        assert arb.step()["action"] is None
        out = arb.step()
        assert out["action"] == "handback"
        assert serving.revoked == [1]
        assert training.granted == [1, 1]
        assert arb.allocations() == {SERVING: 1, TRAINING: 3}

    def test_inflight_revocation_blocks_decisions(self):
        serving = FakeTenant("serving", 1, signals=BREACH)
        training = FakeTenant("training", 3, stubborn=True)
        arb = _arbiter(serving, training, revoke_deadline_s=30.0)
        arb.step()
        assert len(arb.pending_leases()) == 1
        out = arb.step()
        assert out["action"] is None
        assert out["reason"] == "revocation in flight"
        assert training.revoked == [1]  # not re-issued

    def test_deadline_escalates_and_regrants(self):
        serving = FakeTenant("serving", 1, signals=BREACH)
        training = FakeTenant("training", 3, stubborn=True)
        arb = _arbiter(serving, training, revoke_deadline_s=0.1)
        arb.step()
        time.sleep(0.15)
        arb.step()  # past the deadline: escalation fires
        assert training.escalated == [1]
        assert arb.escalations == 1
        assert serving.granted == [1]
        assert arb.allocations() == {SERVING: 2, TRAINING: 2}
        events = [e["event"] for e in arb.journal()]
        assert "escalate" in events and "escalate_freed" in events

    def test_failed_escalation_keeps_ledger_honest(self):
        serving = FakeTenant("serving", 1, signals=BREACH)
        training = FakeTenant(
            "training", 3, stubborn=True, escalate_frees=False
        )
        arb = _arbiter(serving, training, revoke_deadline_s=0.1)
        arb.step()
        first = arb.pending_leases()[0]
        time.sleep(0.15)
        arb.step()
        # nothing actually freed: the ledger must not claim capacity
        assert arb.allocations() == {SERVING: 1, TRAINING: 3}
        assert serving.granted == []
        # the failed lease is closed; the persisting breach is allowed
        # to open a RETRY lease (new id) — it must not be the old one
        assert first.state == LeaseState.ESCALATED
        assert all(
            l.lease_id != first.lease_id for l in arb.pending_leases()
        )

    def test_late_release_after_escalation_is_ignored(self):
        serving = FakeTenant("serving", 1, signals=BREACH)
        training = FakeTenant("training", 3, stubborn=True)
        arb = _arbiter(serving, training, revoke_deadline_s=0.1)
        arb.step()
        lease = arb.pending_leases()[0]
        time.sleep(0.15)
        arb.step()  # escalated; ledger moved once
        alloc = arb.allocations()
        arb._on_released(lease, 1)  # the tardy cooperative confirm
        assert arb.allocations() == alloc  # no double move
        assert lease.state == LeaseState.ESCALATED
        assert any(
            e["event"] == "late_release" for e in arb.journal()
        )

    def test_grant_failure_rolls_back_to_free(self):
        serving = FakeTenant(
            "serving", 1, signals=BREACH, grant_error=True
        )
        training = FakeTenant("training", 2)
        arb = _arbiter(serving, training)
        arb.step()
        assert arb.allocations() == {SERVING: 1, TRAINING: 2}
        assert arb.free_units() == 1  # rolled back, retryable
        assert any(
            e["event"] == "grant_error" for e in arb.journal()
        )
        # the breach persists: the next eval retries the move
        serving.grant_error = False
        arb.step()
        assert serving.granted == [1]
        assert arb.allocations() == {SERVING: 2, TRAINING: 2}

    def test_report_error_skips_eval(self):
        serving = FakeTenant(
            "serving", 1, signals=BREACH, report_error=True
        )
        training = FakeTenant("training", 3)
        arb = _arbiter(serving, training)
        out = arb.step()
        assert out["action"] is None
        assert training.revoked == []
        assert any(
            e["event"] == "report_error" for e in arb.journal()
        )

    def test_journal_file_is_jsonl(self, tmp_path):
        path = str(tmp_path / "journal.jsonl")
        serving = FakeTenant("serving", 1, signals=BREACH)
        training = FakeTenant("training", 3)
        arb = _arbiter(serving, training, journal_path=path)
        arb.step()
        lines = [
            json.loads(l)
            for l in open(path).read().splitlines()
            if l.strip()
        ]
        assert [e["event"] for e in lines] == [
            "breach", "revoke", "release", "grant",
        ]
        assert all("alloc" in e and "seq" in e for e in lines)

    def test_status_shape(self):
        serving = FakeTenant("serving", 1, signals=CALM)
        training = FakeTenant("training", 3)
        arb = _arbiter(serving, training)
        arb.step()
        st = arb.status()
        assert st["total_units"] == 4
        assert st["allocations"] == {SERVING: 1, TRAINING: 3}
        assert st["counters"]["evaluations"] == 1
        assert "phase_split" in st and "journal_tail" in st
        assert st["bounds"]["train"] == [1, 4]

    def test_serving_only_pool_uses_free_ledger(self):
        # no training adapter (the tpurun-pool serve shape): spikes
        # draw from free, handback returns there
        serving = FakeTenant("serving", 1, signals=BREACH)
        arb = ChipPoolArbiter(
            serving, config=_cfg(train_floor=0)
        )
        arb.step()
        assert serving.granted == [1]
        assert arb.allocations()[SERVING] == 2
        assert arb.free_units() == 2
        serving.signals = dict(CALM)
        arb.step()
        arb.step()  # hysteresis: second calm eval hands back
        arb.wait_idle(5.0)
        assert arb.allocations()[SERVING] == 1
        assert arb.free_units() == 3


class TestPoolInjectionDrills:
    """The three pool injection points, fired deterministically against
    a live arbiter (chaos/faults.py): the arbitration loop must ride
    through a dark tenant report, a delayed revoke dispatch, and a
    poisoned grant — and every fire must be visible in the records."""

    def teardown_method(self):
        faults.deactivate()

    def test_tenant_report_error_rides_through(self):
        faults.activate(
            faults.FaultPlan.parse(
                "seed=7;pool.tenant_report:error:dark@at=1"
            )
        )
        serving = FakeTenant("serving", 1, signals=BREACH)
        training = FakeTenant("training", 3)
        arb = _arbiter(serving, training)
        out = arb.step()  # first collection dies injected
        assert out["action"] is None
        assert any(
            e["event"] == "report_error" for e in arb.journal()
        )
        arb.step()  # next eval proceeds normally
        assert serving.granted == [1]
        fired = [
            r for r in faults.records()
            if r["point"] == "pool.tenant_report"
        ]
        assert len(fired) == 1

    def test_revoke_delay_injection_fires(self):
        faults.activate(
            faults.FaultPlan.parse("seed=7;pool.revoke:delay:0.01@once")
        )
        serving = FakeTenant("serving", 1, signals=BREACH)
        training = FakeTenant("training", 3)
        arb = _arbiter(serving, training)
        arb.step()
        assert training.revoked == [1]
        assert [
            r["point"] for r in faults.records()
        ] == ["pool.revoke"]

    def test_poisoned_grant_rolls_back_then_retries(self):
        faults.activate(
            faults.FaultPlan.parse("seed=7;pool.grant:error:poisoned@at=1")
        )
        serving = FakeTenant("serving", 1, signals=BREACH)
        training = FakeTenant("training", 3)
        arb = _arbiter(serving, training)
        arb.step()  # grant dies injected -> rollback to free
        assert arb.free_units() == 1
        assert any(
            e["event"] == "grant_error" for e in arb.journal()
        )
        arb.step()  # breach persists: retried from the free pool
        assert serving.granted == [1]
        assert arb.allocations() == {SERVING: 2, TRAINING: 2}
        assert any(
            r["point"] == "pool.grant" for r in faults.records()
        )


class TestServingTenant:
    def _fleet(self, n=2, max_replicas=4):
        from dlrover_tpu.fleet import FleetConfig, ReplicaSupervisor
        from dlrover_tpu.pool.drill import ScriptedReplica

        script = {}
        cfg = FleetConfig(
            replicas=n,
            min_replicas=1,
            max_replicas=max_replicas,
            health_interval_s=0.05,
            health_timeout_s=5.0,
            drain_timeout_s=5.0,
        )
        sup = ReplicaSupervisor(
            lambda rid, port: ScriptedReplica(rid, port, script=script),
            cfg,
        ).start()
        assert sup.wait_ready(n, timeout=30.0)
        return sup, script

    def test_report_units_and_signals(self):
        sup, script = self._fleet(2)
        try:
            tenant = ServingTenant(sup)
            assert tenant.initial_units == 2
            script["queue_depth"] = 6
            time.sleep(0.2)  # two poll intervals
            rep = tenant.report()
            assert rep["units_held"] == 2
            assert rep["ready"] == 2
            assert rep["queue_mean"] == 6.0
        finally:
            sup.stop()

    def test_grant_adds_replicas(self):
        sup, _ = self._fleet(1)
        try:
            tenant = ServingTenant(sup)
            tenant.grant(2)
            assert len(sup.replicas()) == 3
            assert sup.wait_ready(3, timeout=30.0)
        finally:
            sup.stop()

    def test_revoke_drains_newest_and_confirms(self):
        sup, _ = self._fleet(3)
        try:
            tenant = ServingTenant(sup)
            released = []
            tenant.revoke(2, 10.0, released.append)
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and not released:
                time.sleep(0.05)
            assert released == [2]
            rids = sorted(h.rid for h in sup.replicas())
            assert rids == [0]  # newest (1, 2) drained away
        finally:
            sup.stop()

    def test_escalate_terminates_without_drain(self):
        sup, _ = self._fleet(2)
        try:
            tenant = ServingTenant(sup)
            assert tenant.escalate(1) == 1
            assert len(sup.replicas()) == 1
        finally:
            sup.stop()


class TestLoopTrainingController:
    """The in-process training tenant: a REAL ElasticTrainLoop over a
    numpy step program — the flash-checkpoint reconfigure machinery
    without an XLA compile in sight."""

    def _controller(self, tmp_path, max_units=3, step_s=0.01):
        from dlrover_tpu.pool.drill import _synthetic_training

        engine, build, state, data = _synthetic_training(
            str(tmp_path), max_units, step_s=step_s
        )
        ctl = LoopTrainingController(
            engine,
            build,
            state,
            data,
            max_units=max_units,
            start_world=max_units,
            storage_every=10_000,
        )
        return engine, ctl

    def _wait_steps(self, ctl, n, timeout=30.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if ctl.steps_total >= n:
                return True
            time.sleep(0.02)
        return False

    def test_shrink_is_checkpoint_backed_and_lossless(self, tmp_path):
        engine, ctl = self._controller(tmp_path)
        try:
            ctl.start()
            assert self._wait_steps(ctl, 5)
            assert ctl.reconfigure(2, timeout_s=30.0)
            assert ctl.world() == 2
            before = ctl.steps_total
            assert self._wait_steps(ctl, before + 5)
            assert ctl.reconfigure(3, timeout_s=30.0)  # grow back
            assert self._wait_steps(ctl, ctl.steps_total + 3)
        finally:
            ctl.stop()
            engine.shm.unlink()
            engine.close()
        # every step applied exactly once across both reconfigs: the
        # state's own counter equals the observed step count — a lossy
        # or replayed resume would break the equality
        assert int(ctl.state()["step"]) == ctl.steps_total
        assert ctl.reconfigs == 2

    def test_report_and_rate(self, tmp_path):
        engine, ctl = self._controller(tmp_path)
        try:
            ctl.start()
            assert self._wait_steps(ctl, 8)
            rep = ctl.report()
            assert rep["world"] == 3
            assert rep["units_held"] == 3
            assert rep["steps_per_s"] > 0
        finally:
            ctl.stop()
            engine.shm.unlink()
            engine.close()

    def test_tenant_shrink_ladder_respects_node_unit(self, tmp_path):
        engine, ctl = self._controller(tmp_path, max_units=4)
        try:
            tenant = TrainingTenant(ctl, node_unit=2)
            assert tenant._shrink_target(1) == 2  # 4-1=3 -> 2 (unit=2)
            assert tenant._shrink_target(2) == 2
            assert tenant._shrink_target(3) == 0
        finally:
            engine.shm.unlink()
            engine.close()

    def test_escalate_to_uses_grace(self, tmp_path):
        engine, ctl = self._controller(tmp_path)
        try:
            ctl.start()
            assert self._wait_steps(ctl, 3)
            freed = ctl.escalate_to(1, grace_s=30.0)
            assert freed == 2
            assert ctl.world() == 1
        finally:
            ctl.stop()
            engine.shm.unlink()
            engine.close()


class _FakeController:
    """Scripted training controller for tenant-arithmetic tests where
    the live loop's timing would hide the race being pinned."""

    def __init__(self, world=3):
        self.world_val = world
        self.pending = None
        self.reconfig_calls = []
        self.escalate_calls = []
        self.complete_reconfigs = True

    def world(self):
        return self.world_val

    def target_world(self):
        return self.pending if self.pending is not None else self.world_val

    def reconfigure(self, target, timeout_s=None):
        self.reconfig_calls.append(target)
        self.pending = target
        if self.complete_reconfigs:
            self.world_val = target
            self.pending = None
            return True
        return False

    def escalate_to(self, target, grace_s=5.0):
        self.escalate_calls.append(target)
        before = self.world_val
        self.world_val = target
        self.pending = None
        return max(0, before - target)

    def report(self):
        return {"world": self.world_val}


class TestTenantLedgerConsistency:
    """Regression pins for the review findings: stale-world targets,
    revoke/escalate double-reclaim, and node_unit grid mismatches —
    each of which silently drifted the pool ledger from real
    capacity."""

    def test_revoke_after_pending_grant_sees_granted_world(self):
        # a grant's reconfigure is dispatched but not yet applied; the
        # next revoke must compute against the GRANTED world, not
        # clobber the grant with a stale-world target
        ctl = _FakeController(world=2)
        ctl.complete_reconfigs = False  # grant stays pending
        tenant = TrainingTenant(ctl)
        tenant.grant(1)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and not ctl.reconfig_calls:
            time.sleep(0.01)
        assert ctl.reconfig_calls == [3]  # the pending grant
        released = []
        ctl.complete_reconfigs = True
        tenant.revoke(1, 10.0, released.append)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and not released:
            time.sleep(0.01)
        # 3 (granted) - 1 = 2 — NOT 2 - 1 = 1 (the stale-world bug)
        assert released == [1]
        assert ctl.reconfig_calls[-1] == 2
        assert ctl.world() == 2

    def test_escalate_finishes_stored_target_not_a_fresh_delta(self):
        # the cooperative drain already reached the revoke's target
        # when the deadline fired: escalation must drive to the SAME
        # absolute world (a no-op here) and report the freed delta —
        # never re-derive a delta from the already-shrunk world
        ctl = _FakeController(world=3)
        ctl.complete_reconfigs = False  # coop "hangs"
        tenant = TrainingTenant(ctl)
        tenant.revoke(1, 10.0, lambda n: None)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and not ctl.reconfig_calls:
            time.sleep(0.01)
        # the coop drain lands just as the deadline fires
        ctl.world_val = 2
        ctl.pending = None
        freed = tenant.escalate(1)
        assert freed == 1  # from the pre-revoke world 3, not 2-1
        assert ctl.escalate_calls == []  # already at target: no force
        assert ctl.world() == 2  # NEVER shrunk twice

    def test_serving_escalate_finishes_stored_victims(self):
        from dlrover_tpu.fleet import FleetConfig, ReplicaSupervisor
        from dlrover_tpu.pool.drill import ScriptedReplica

        script = {}
        cfg = FleetConfig(
            replicas=3, min_replicas=1, max_replicas=4,
            health_interval_s=0.05, health_timeout_s=5.0,
            drain_timeout_s=5.0,
        )
        sup = ReplicaSupervisor(
            lambda rid, port: ScriptedReplica(rid, port, script=script),
            cfg,
        ).start()
        try:
            assert sup.wait_ready(3, timeout=30.0)
            tenant = ServingTenant(sup)
            # busy replicas: the cooperative drain blocks on queue>0
            script["queue_depth"] = 5
            released = []
            tenant.revoke(2, 20.0, released.append)
            time.sleep(0.3)  # drain is stuck mid-victim
            freed = tenant.escalate(2)
            assert freed == 2
            # the STORED victims (newest rids 1, 2) went; replica 0 —
            # which a fresh victim pick over the survivors would have
            # cut — is untouched
            assert sorted(h.rid for h in sup.replicas()) == [0]
            # and the context is consumed: a later lease's escalation
            # must never recount these rids as freshly freed
            assert tenant._revoke_victims is None
        finally:
            script["queue_depth"] = 0
            sup.stop()

    def test_escalation_consumes_context_for_next_lease(self):
        # lease A times out cooperatively and is escalated; lease B's
        # dispatch then fails (the pool.revoke error-injection path)
        # and B escalates too. B must compute from the LIVE world —
        # replaying A's consumed context would report phantom freed
        # units and leave the world untouched
        ctl = _FakeController(world=4)
        ctl.complete_reconfigs = False  # A's coop drain hangs
        tenant = TrainingTenant(ctl)
        tenant.revoke(1, 10.0, lambda n: None)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and not ctl.reconfig_calls:
            time.sleep(0.01)
        assert tenant.escalate(1) == 1  # A forced 4 -> 3
        assert ctl.world() == 3
        freed = tenant.escalate(1)  # B: no stored context
        assert freed == 1
        assert ctl.world() == 2  # really moved — not A's replay

    def test_escalate_after_failed_dispatch_uses_fresh_world(self):
        # revoke #1 completed and was released (its stored context is
        # consumed); revoke #2's dispatch failed before the tenant
        # stored anything. Escalation must compute from the LIVE
        # world — stale context would re-report revoke #1's units as
        # freshly freed (phantom capacity in the ledger)
        ctl = _FakeController(world=4)
        tenant = TrainingTenant(ctl)
        released = []
        tenant.revoke(2, 10.0, released.append)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and not released:
            time.sleep(0.01)
        assert released == [2] and ctl.world() == 2
        # dispatch of revoke #2 "failed": escalate fires with no
        # stored context
        freed = tenant.escalate(1)
        assert freed == 1  # 2 -> 1, NOT the stale 4-2=2
        assert ctl.world() == 1

    def test_serving_escalate_after_consumed_release_is_fresh(self):
        from dlrover_tpu.fleet import FleetConfig, ReplicaSupervisor
        from dlrover_tpu.pool.drill import ScriptedReplica

        cfg = FleetConfig(
            replicas=3, min_replicas=1, max_replicas=4,
            health_interval_s=0.05, health_timeout_s=5.0,
            drain_timeout_s=5.0,
        )
        sup = ReplicaSupervisor(
            lambda rid, port: ScriptedReplica(rid, port, script={}),
            cfg,
        ).start()
        try:
            assert sup.wait_ready(3, timeout=30.0)
            tenant = ServingTenant(sup)
            released = []
            tenant.revoke(1, 10.0, released.append)
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and not released:
                time.sleep(0.05)
            assert released == [1]
            # escalation for a LATER failed-dispatch revoke must pick
            # fresh victims, not recount the consumed set as "gone"
            assert tenant.escalate(1) == 1
            assert len(sup.replicas()) == 1
        finally:
            sup.stop()

    def test_grant_clamped_to_free_ledger(self):
        # the double-spend race: a release's deferred grant and a
        # concurrent step() both placing the same freed units — the
        # second grant must find them spent, never drive free negative
        serving = FakeTenant("serving", 1, signals=CALM)
        training = FakeTenant("training", 3)
        arb = _arbiter(serving, training)  # free = 0
        arb._grant(SERVING, 1, reason="race-loser")
        assert arb.free_units() == 0
        assert arb.allocations() == {SERVING: 1, TRAINING: 3}
        assert serving.granted == []
        assert any(
            e["event"] == "grant_skipped" for e in arb.journal()
        )

    def test_shrink_ladder_respects_floor_on_grid(self, tmp_path):
        # node_unit=4, floor 2: the only grid worlds are 0/4/8 — a
        # 1-unit revoke must be REFUSED (released 0), not shut
        # training down to world 0 past its floor
        ctl = _FakeController(world=4)
        tenant = TrainingTenant(ctl, node_unit=4, floor_units=2)
        assert tenant._shrink_target(1) == 4  # no valid world
        released = []
        tenant.revoke(1, 5.0, released.append)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and not released:
            time.sleep(0.01)
        assert released == [0]
        assert ctl.world() == 4  # untouched
        assert ctl.reconfig_calls == []
        # a grid that CAN satisfy the floor still shrinks to it
        tenant2 = TrainingTenant(
            _FakeController(world=4), node_unit=2, floor_units=2
        )
        assert tenant2._shrink_target(3) == 2  # clamped at the floor

    def test_grant_off_node_unit_grid_raises_for_rollback(self):
        ctl = _FakeController(world=2)
        tenant = TrainingTenant(ctl, node_unit=2)
        with pytest.raises(ValueError, match="node_unit"):
            tenant.grant(1)
        assert ctl.reconfig_calls == []  # nothing dispatched

    def test_node_unit_deep_shrink_overfree_reaches_ledger(self):
        # node_unit grids can force freeing MORE than the leased
        # units; the arbiter must ledger the actual freed count (the
        # excess lands in the free pool via the ceiling clamp)
        class _DeepTenant(FakeTenant):
            def revoke(self, units, deadline_s, on_released):
                self.revoked.append(units)
                on_released(units + 1)  # the ladder skipped a rung

        serving = FakeTenant("serving", 1, signals=BREACH)
        training = _DeepTenant("training", 4, signals={})
        arb = ChipPoolArbiter(
            serving, training, config=_cfg(total_units=5)
        )
        arb.step()
        # 2 freed: 1 granted to serving (spike_units), 1 left free
        assert arb.allocations() == {SERVING: 2, TRAINING: 2}
        assert arb.free_units() == 1


class TestMasterTrainingController:
    class _Scaler:
        def __init__(self):
            self.plans = []

        def scale(self, plan):
            self.plans.append(plan)

    def test_grow_issues_scale_plan(self):
        scaler = self._Scaler()
        world = {"n": 2}
        ctl = MasterTrainingController(
            scaler, lambda: world["n"], max_units=4
        )
        assert ctl.reconfigure(4) is True
        assert scaler.plans[-1].worker_num == 4

    def test_shrink_prefers_drain_handler(self):
        scaler = self._Scaler()
        drained = []
        ctl = MasterTrainingController(
            scaler,
            lambda: 4,
            max_units=4,
            shrink_handler=drained.append,
        )
        ctl.reconfigure(2)
        assert drained == [2]
        assert scaler.plans == []  # never a bare kill for a shrink

    def test_blocking_reconfigure_polls_world(self):
        scaler = self._Scaler()
        world = {"n": 2}

        def grow_soon():
            time.sleep(0.2)
            world["n"] = 3

        threading.Thread(target=grow_soon, daemon=True).start()
        ctl = MasterTrainingController(
            scaler, lambda: world["n"], max_units=4,
            poll_interval_s=0.05,
        )
        assert ctl.reconfigure(3, timeout_s=5.0) is True
        assert (
            ctl.reconfigure(8, timeout_s=0.2) is False
        )  # never forms

    def test_escalate_forces_plan_and_counts_actual(self):
        scaler = self._Scaler()
        world = {"n": 4}

        # the platform applies forced plans promptly in this fake
        class _ApplyingScaler(self._Scaler):
            def scale(self, plan):
                super().scale(plan)
                if plan.worker_num >= 0:
                    world["n"] = plan.worker_num

        scaler = _ApplyingScaler()
        ctl = MasterTrainingController(
            scaler, lambda: world["n"], max_units=4,
            poll_interval_s=0.01,
        )
        assert ctl.escalate_to(2) == 2
        assert scaler.plans[-1].worker_num == 2

    def test_escalate_frees_nothing_until_world_drops(self):
        # a plan still converging frees nothing yet (ledger honesty)
        scaler = self._Scaler()
        ctl = MasterTrainingController(
            scaler, lambda: 4, max_units=4, poll_interval_s=0.02
        )
        assert ctl.escalate_to(2, grace_s=0.1) == 0
        assert scaler.plans[-1].worker_num == 2


class TestStatusEndpoint:
    def test_status_journal_and_step_over_http(self):
        from dlrover_tpu.pool.cli import serve_status

        serving = FakeTenant("serving", 1, signals=BREACH)
        training = FakeTenant("training", 3)
        arb = _arbiter(serving, training)
        httpd = serve_status(arb, 0)
        port = httpd.server_address[1]
        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()
        try:
            deadline = arb.cfg.status_timeout_s
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/pool/status", timeout=deadline
            ) as r:
                st = json.loads(r.read())
            assert st["total_units"] == 4
            assert st["allocations"] == {SERVING: 1, TRAINING: 3}
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/pool/step", method="POST"
            )
            with urllib.request.urlopen(req, timeout=deadline) as r:
                out = json.loads(r.read())
            assert out["action"] == "preempt"
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/pool/journal",
                timeout=deadline,
            ) as r:
                j = json.loads(r.read())["journal"]
            assert [e["event"] for e in j] == [
                "breach", "revoke", "release", "grant",
            ]
        finally:
            httpd.shutdown()
            httpd.server_close()
            t.join(timeout=10)


class TestSyntheticDrill:
    def test_full_arbitration_arc(self, tmp_path):
        """The whole breach → revoke (checkpointed shrink) → grant →
        READY → hysteresis handback arc over scripted replicas and a
        numpy ElasticTrainLoop — the tier-1 twin of the real-engine
        ``traffic_spike_preempt`` scenario (test_zz_pool_e2e.py)."""
        from dlrover_tpu.pool.drill import run_traffic_spike_drill

        result = run_traffic_spike_drill(
            workdir=str(tmp_path),
            real_engines=False,
            calibration_window_s=0.5,
            spike_hold_s=0.3,
            eval_interval_s=0.1,
            timeout_s=90.0,
        )
        assert result["ok"], result
        assert result["drill"] == "traffic_spike_preempt"
        assert result["requests_failed"] == 0
        assert result["availability"] == 1.0
        assert result["preempt_to_ready_s"] >= 0
        assert result["handback"] is True
        assert result["escalations"] == 0
        assert result["train_goodput"] > 0
        events = [e["event"] for e in result["journal"]]
        assert "breach" in events and "grant" in events
        # the shrink genuinely moved the training world
        assert result["world_during_spike"] < 3
