"""Elastic agent: rendezvous handler, worker supervision, failure handling.

Mirrors the reference's agent test approach (SURVEY.md §4): real
rendezvous against an in-process LocalJobMaster, real subprocess workers
(tiny scripts written to tmp_path), no cluster.
"""

import os
import signal
import threading
import time

import pytest

from dlrover_tpu.agent.config import ElasticLaunchConfig
from dlrover_tpu.agent.diagnosis_agent import DiagnosisAgent, WorkerFailure
from dlrover_tpu.agent.rendezvous import MasterRendezvousHandler
from dlrover_tpu.agent.training_agent import (
    AGENT_EXIT_OK,
    AGENT_EXIT_RELAUNCH,
    ElasticTrainingAgent,
)
from dlrover_tpu.agent.worker import WorkerProcess, WorkerSpec, WorkerState
from dlrover_tpu.common.constants import RendezvousName
from dlrover_tpu.master.diagnosis.action import DiagnosisActionType
from dlrover_tpu.master.local_master import LocalJobMaster
from dlrover_tpu.rpc.client import MasterClient


@pytest.fixture()
def master2():
    m = LocalJobMaster(num_workers=2, fresh_context=True)
    m.prepare()
    yield m
    m.stop()


@pytest.fixture()
def master1():
    m = LocalJobMaster(num_workers=1, fresh_context=True)
    m.prepare()
    yield m
    m.stop()


def _client(master, node_id):
    return MasterClient(
        master_addr=master.addr, node_id=node_id, service_type="grpc"
    )


class TestRendezvousHandler:
    def test_two_nodes_assemble_world(self, master2):
        results = {}

        def join(rank):
            handler = MasterRendezvousHandler(
                RendezvousName.TRAINING,
                node_rank=rank,
                client=_client(master2, rank),
                rdzv_timeout=30,
            )
            results[rank] = handler.next_rendezvous()

        threads = [threading.Thread(target=join, args=(r,)) for r in (0, 1)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert sorted(results) == [0, 1]
        w0, w1 = results[0], results[1]
        assert w0.world_size == w1.world_size == 2
        assert {w0.rank, w1.rank} == {0, 1}
        assert w0.coordinator == w1.coordinator
        assert ":" in w0.coordinator

    def test_rank_is_topology_position(self, master1):
        handler = MasterRendezvousHandler(
            RendezvousName.TRAINING,
            node_rank=7,
            client=_client(master1, 7),
            rdzv_timeout=30,
        )
        world = handler.next_rendezvous()
        # Single node: process_id 0 regardless of its node_rank.
        assert world.rank == 0
        assert world.world_size == 1
        assert world.world[0].node_rank == 7


def _write_script(tmp_path, name, body):
    path = tmp_path / name
    path.write_text(body)
    return str(path)


class TestWorkerProcess:
    def test_success_lifecycle(self, tmp_path):
        script = _write_script(tmp_path, "ok.py", "print('hello')\n")
        w = WorkerProcess(WorkerSpec(entrypoint=script, log_dir=str(tmp_path)))
        w.start()
        result = w.wait(timeout=30)
        assert result.state == WorkerState.SUCCEEDED
        assert "hello" in w.tail_log()

    def test_failure_captures_log(self, tmp_path):
        script = _write_script(
            tmp_path, "bad.py", "raise RuntimeError('boom-xyz')\n"
        )
        w = WorkerProcess(WorkerSpec(entrypoint=script, log_dir=str(tmp_path)))
        w.start()
        result = w.wait(timeout=30)
        assert result.state == WorkerState.FAILED
        assert result.returncode == 1
        assert "boom-xyz" in w.tail_log()

    def test_stop_kills_process_group(self, tmp_path):
        script = _write_script(
            tmp_path,
            "sleep.py",
            "import time\nprint('up', flush=True)\ntime.sleep(600)\n",
        )
        spec = WorkerSpec(entrypoint=script, log_dir=str(tmp_path), kill_grace_s=2)
        w = WorkerProcess(spec)
        w.start()
        deadline = time.time() + 20
        while "up" not in w.tail_log() and time.time() < deadline:
            time.sleep(0.1)
        assert w.poll().state == WorkerState.RUNNING
        pid = w.pid
        w.stop()
        assert w.poll().state in (WorkerState.FAILED, WorkerState.SUCCEEDED)
        with pytest.raises(ProcessLookupError):
            os.kill(pid, 0)

    def test_env_contract_passed(self, tmp_path):
        script = _write_script(
            tmp_path,
            "env.py",
            "import os\nprint('PID=' + os.environ['DLROVER_PROCESS_ID'])\n",
        )
        w = WorkerProcess(WorkerSpec(entrypoint=script, log_dir=str(tmp_path)))
        w.start(dynamic_env={"DLROVER_PROCESS_ID": "3"})
        w.wait(timeout=30)
        assert "PID=3" in w.tail_log()


class TestDiagnosisClassification:
    def _agent(self, master, max_restarts=3):
        return DiagnosisAgent(
            0, client=_client(master, 0), max_restarts=max_restarts
        )

    def test_retryable_restarts(self, master1):
        d = self._agent(master1)
        f = WorkerFailure(0, 0, 1, None, log_tail="Connection refused by peer")
        assert (
            d.diagnose_training_failure(f) == DiagnosisActionType.RESTART_WORKER
        )

    def test_node_fatal_relaunches(self, master1):
        d = self._agent(master1)
        f = WorkerFailure(0, 0, 1, None, log_tail="Failed to initialize TPU system")
        assert (
            d.diagnose_training_failure(f) == DiagnosisActionType.RELAUNCH_WORKER
        )

    def test_budget_exhaustion_relaunches(self, master1):
        d = self._agent(master1, max_restarts=2)
        f = WorkerFailure(0, 2, 1, None, log_tail="whatever")
        assert (
            d.diagnose_training_failure(f) == DiagnosisActionType.RELAUNCH_WORKER
        )


def _make_agent(master, tmp_path, script, node_rank=0, **cfg_kw):
    cfg = ElasticLaunchConfig(
        min_nodes=1,
        max_nodes=cfg_kw.pop("max_nodes", 1),
        node_id=node_rank,
        node_rank=node_rank,
        entrypoint=script,
        master_addr=master.addr,
        monitor_interval=0.2,
        rdzv_timeout=30,
        save_at_breakpoint=False,
        log_dir=str(tmp_path / f"logs{node_rank}"),
        **cfg_kw,
    )
    return ElasticTrainingAgent(
        cfg, client=_client(master, node_rank), start_ckpt_saver=False
    )


class TestElasticTrainingAgent:
    def test_successful_run(self, master1, tmp_path):
        script = _write_script(tmp_path, "ok.py", "print('done')\n")
        agent = _make_agent(master1, tmp_path, script)
        assert agent.run() == AGENT_EXIT_OK

    def test_restart_then_success(self, master1, tmp_path):
        # Fails on first run, succeeds once the marker file exists.
        marker = tmp_path / "marker"
        script = _write_script(
            tmp_path,
            "flaky.py",
            f"""
import os, sys
marker = {str(marker)!r}
if not os.path.exists(marker):
    open(marker, 'w').close()
    sys.exit(3)
print('recovered')
""",
        )
        agent = _make_agent(master1, tmp_path, script, max_restarts=2)
        assert agent.run() == AGENT_EXIT_OK
        assert agent._restart_count == 1

    def test_relaunch_when_budget_exhausted(self, master1, tmp_path):
        script = _write_script(tmp_path, "bad.py", "import sys\nsys.exit(5)\n")
        agent = _make_agent(master1, tmp_path, script, max_restarts=0)
        assert agent.run() == AGENT_EXIT_RELAUNCH

    def test_membership_change_triggers_re_rendezvous(self, master2, tmp_path):
        """Two agents; kill one worker → both re-rendezvous into round 1.

        This is the core elastic scenario (reference training.py:1262):
        a healthy agent notices waiters and restarts its worker group so
        the whole world re-meshes.
        """
        script = _write_script(
            tmp_path,
            "sleep.py",
            "import time\nprint('up', flush=True)\ntime.sleep(120)\n",
        )
        agents = [
            _make_agent(master2, tmp_path, script, node_rank=r, max_nodes=2)
            for r in (0, 1)
        ]
        codes = {}
        threads = [
            threading.Thread(target=lambda r=r: codes.update({r: agents[r].run()}))
            for r in (0, 1)
        ]
        for t in threads:
            t.start()

        def wait_for(cond, timeout=30):
            deadline = time.time() + timeout
            while time.time() < deadline:
                if cond():
                    return True
                time.sleep(0.1)
            return False

        # Both workers up in round 0.
        assert wait_for(
            lambda: all(
                a._worker is not None
                and a._worker.poll().state == WorkerState.RUNNING
                for a in agents
            )
        )
        assert agents[0]._world.round == agents[1]._world.round == 0
        victim_pid = agents[1]._worker.pid

        os.kill(victim_pid, signal.SIGKILL)

        # Both agents must land in a new world (round 1) with live workers.
        assert wait_for(
            lambda: all(
                a._world is not None
                and a._world.round == 1
                and a._worker.poll().state == WorkerState.RUNNING
                for a in agents
            ),
            timeout=60,
        ), f"worlds: {[a._world and a._world.round for a in agents]}"
        assert agents[0]._world.world_size == 2

        for a in agents:
            a.stop()
        for t in threads:
            t.join(timeout=30)


class TestWarmSpare:
    """Warm-spare workers (round 4): restarts skip the interpreter +
    jax/flax import tax — the dominant term in elastic MTTR."""

    def test_spare_adopted_and_env_contract_applied(self, tmp_path):
        from dlrover_tpu.agent.worker import WarmSpare, WorkerProcess

        out = tmp_path / "out.txt"
        script = tmp_path / "train.py"
        script.write_text(
            "import os, pathlib, sys\n"
            f"pathlib.Path(r'{out}').write_text(\n"
            "    os.environ['DLROVER_COORDINATOR_ADDRESS'] + ' '\n"
            "    + os.environ['DLROVER_RESTART_COUNT']\n"
            "    + ' ' + (sys.argv[1] if len(sys.argv) > 1 else ''))\n"
        )
        spec = WorkerSpec(
            entrypoint=str(script),
            args=["argA"],
            log_dir=str(tmp_path / "logs"),
        )
        spare = WarmSpare(spec, tag="t")
        assert spare.wait_ready(timeout=30), "spare never became ready"
        worker = WorkerProcess(spec, restart_count=3)
        t0 = time.time()
        how = worker.start(
            dynamic_env={"DLROVER_COORDINATOR_ADDRESS": "1.2.3.4:5"},
            spare=spare,
        )
        assert how == "warm"
        result = worker.wait(timeout=30)
        warm_latency = time.time() - t0
        assert result.state == WorkerState.SUCCEEDED, worker.tail_log()
        assert out.read_text() == "1.2.3.4:5 3 argA"
        # the whole point: handoff->exit must beat a cold python start
        assert warm_latency < 5.0, warm_latency

    def test_unready_spare_falls_back_cold(self, tmp_path):
        from dlrover_tpu.agent.worker import WarmSpare, WorkerProcess

        script = tmp_path / "ok.py"
        script.write_text("print('ran')\n")
        spec = WorkerSpec(entrypoint=str(script))

        class NeverReady(WarmSpare):
            def wait_ready(self, timeout=0.0):
                return False

        spare = NeverReady(spec, tag="n")
        try:
            worker = WorkerProcess(spec)
            how = worker.start(spare=spare)
            assert how == "cold"
            assert worker.wait(timeout=30).state == WorkerState.SUCCEEDED
            assert spare.proc.poll() is None  # untouched, still warm-ing
        finally:
            spare.kill()

    def test_agent_keeps_one_spare_and_cleans_up(self, master1, tmp_path):
        script = tmp_path / "train.py"
        # outlives the (shortened) spare-spawn delay: the timer only
        # fires while the agent is still running
        script.write_text("import time\ntime.sleep(4.0)\n")
        config = ElasticLaunchConfig(
            min_nodes=1,
            max_nodes=1,
            entrypoint=str(script),
            master_addr=master1.addr,
            monitor_interval=0.3,
            warm_spare=True,
        )
        agent = ElasticTrainingAgent(
            config,
            client=_client(master1, 0),
            start_ckpt_saver=False,
        )
        agent.SPARE_SPAWN_DELAY_S = 0.5
        rc = {}
        t = threading.Thread(target=lambda: rc.update(v=agent.run()))
        t.start()
        deadline = time.time() + 30
        saw_spare = False
        while time.time() < deadline and not saw_spare:
            saw_spare = agent._spare is not None
            time.sleep(0.1)
        assert saw_spare, "agent never spawned a warm spare"
        spare_proc = agent._spare.proc
        t.join(timeout=60)
        assert rc.get("v") == AGENT_EXIT_OK
        assert agent._spare is None
        deadline = time.time() + 10
        while time.time() < deadline and spare_proc.poll() is None:
            time.sleep(0.1)
        assert spare_proc.poll() is not None, "spare leaked after agent exit"
