"""Elastic agent: rendezvous handler, worker supervision, failure handling.

Mirrors the reference's agent test approach (SURVEY.md §4): real
rendezvous against an in-process LocalJobMaster, real subprocess workers
(tiny scripts written to tmp_path), no cluster.
"""

import os
import signal
import threading
import time

import pytest

from dlrover_tpu.agent.config import ElasticLaunchConfig
from dlrover_tpu.agent.diagnosis_agent import DiagnosisAgent, WorkerFailure
from dlrover_tpu.agent.rendezvous import MasterRendezvousHandler
from dlrover_tpu.agent.training_agent import (
    AGENT_EXIT_FATAL,
    AGENT_EXIT_OK,
    AGENT_EXIT_RELAUNCH,
    ElasticTrainingAgent,
)
from dlrover_tpu.agent.worker import WorkerProcess, WorkerSpec, WorkerState
from dlrover_tpu.common.constants import RendezvousName
from dlrover_tpu.master.diagnosis.action import DiagnosisActionType
from dlrover_tpu.master.local_master import LocalJobMaster
from dlrover_tpu.rpc.client import MasterClient


@pytest.fixture()
def master2():
    m = LocalJobMaster(num_workers=2, fresh_context=True)
    m.prepare()
    yield m
    m.stop()


@pytest.fixture()
def master1():
    m = LocalJobMaster(num_workers=1, fresh_context=True)
    m.prepare()
    yield m
    m.stop()


def _client(master, node_id):
    return MasterClient(
        master_addr=master.addr, node_id=node_id, service_type="grpc"
    )


class TestRendezvousHandler:
    def test_two_nodes_assemble_world(self, master2):
        results = {}

        def join(rank):
            handler = MasterRendezvousHandler(
                RendezvousName.TRAINING,
                node_rank=rank,
                client=_client(master2, rank),
                rdzv_timeout=30,
            )
            results[rank] = handler.next_rendezvous()

        threads = [threading.Thread(target=join, args=(r,)) for r in (0, 1)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert sorted(results) == [0, 1]
        w0, w1 = results[0], results[1]
        assert w0.world_size == w1.world_size == 2
        assert {w0.rank, w1.rank} == {0, 1}
        assert w0.coordinator == w1.coordinator
        assert ":" in w0.coordinator

    def test_rank_is_topology_position(self, master1):
        handler = MasterRendezvousHandler(
            RendezvousName.TRAINING,
            node_rank=7,
            client=_client(master1, 7),
            rdzv_timeout=30,
        )
        world = handler.next_rendezvous()
        # Single node: process_id 0 regardless of its node_rank.
        assert world.rank == 0
        assert world.world_size == 1
        assert world.world[0].node_rank == 7


def _write_script(tmp_path, name, body):
    path = tmp_path / name
    path.write_text(body)
    return str(path)


class TestWorkerProcess:
    def test_success_lifecycle(self, tmp_path):
        script = _write_script(tmp_path, "ok.py", "print('hello')\n")
        w = WorkerProcess(WorkerSpec(entrypoint=script, log_dir=str(tmp_path)))
        w.start()
        result = w.wait(timeout=30)
        assert result.state == WorkerState.SUCCEEDED
        assert "hello" in w.tail_log()

    def test_failure_captures_log(self, tmp_path):
        script = _write_script(
            tmp_path, "bad.py", "raise RuntimeError('boom-xyz')\n"
        )
        w = WorkerProcess(WorkerSpec(entrypoint=script, log_dir=str(tmp_path)))
        w.start()
        result = w.wait(timeout=30)
        assert result.state == WorkerState.FAILED
        assert result.returncode == 1
        assert "boom-xyz" in w.tail_log()

    def test_stop_kills_process_group(self, tmp_path):
        script = _write_script(
            tmp_path,
            "sleep.py",
            "import time\nprint('up', flush=True)\ntime.sleep(600)\n",
        )
        spec = WorkerSpec(entrypoint=script, log_dir=str(tmp_path), kill_grace_s=2)
        w = WorkerProcess(spec)
        w.start()
        deadline = time.time() + 20
        while "up" not in w.tail_log() and time.time() < deadline:
            time.sleep(0.1)
        assert w.poll().state == WorkerState.RUNNING
        pid = w.pid
        w.stop()
        assert w.poll().state in (WorkerState.FAILED, WorkerState.SUCCEEDED)
        with pytest.raises(ProcessLookupError):
            os.kill(pid, 0)

    def test_env_contract_passed(self, tmp_path):
        script = _write_script(
            tmp_path,
            "env.py",
            "import os\nprint('PID=' + os.environ['DLROVER_PROCESS_ID'])\n",
        )
        w = WorkerProcess(WorkerSpec(entrypoint=script, log_dir=str(tmp_path)))
        w.start(dynamic_env={"DLROVER_PROCESS_ID": "3"})
        w.wait(timeout=30)
        assert "PID=3" in w.tail_log()


class TestDiagnosisClassification:
    def _agent(self, master, max_restarts=3):
        return DiagnosisAgent(
            0, client=_client(master, 0), max_restarts=max_restarts
        )

    def test_retryable_restarts(self, master1):
        d = self._agent(master1)
        f = WorkerFailure(0, 0, 1, None, log_tail="Connection refused by peer")
        assert (
            d.diagnose_training_failure(f) == DiagnosisActionType.RESTART_WORKER
        )

    def test_node_fatal_relaunches(self, master1):
        d = self._agent(master1)
        f = WorkerFailure(0, 0, 1, None, log_tail="Failed to initialize TPU system")
        assert (
            d.diagnose_training_failure(f) == DiagnosisActionType.RELAUNCH_WORKER
        )

    def test_budget_exhaustion_relaunches(self, master1):
        d = self._agent(master1, max_restarts=2)
        f = WorkerFailure(0, 2, 1, None, log_tail="whatever")
        assert (
            d.diagnose_training_failure(f) == DiagnosisActionType.RELAUNCH_WORKER
        )

    def test_orphan_guard_aborts_when_master_lost(self, monkeypatch):
        """Agents whose master is GONE must self-abort, not supervise
        forever (observed live: agents from a SIGTERMed run lingered
        over an hour respawning warm spares)."""
        import threading as _threading

        from dlrover_tpu.common.config import get_context

        class DeadClient:
            def report_heartbeat(self):
                raise ConnectionError("master gone")

        monkeypatch.setattr(
            get_context(), "master_lost_timeout_s", 0.3, raising=True
        )
        d = DiagnosisAgent(0, client=DeadClient(), heartbeat_interval=0.05)
        aborted = _threading.Event()

        def on_action(action_type, config):
            if action_type == DiagnosisActionType.JOB_ABORTION:
                assert config.get("reason") == "master_unreachable"
                aborted.set()

        d.register_action_handler(on_action)
        d.start_heartbeat()
        assert aborted.wait(5.0), "orphan guard never fired"
        d._hb_thread.join(5.0)
        assert not d._hb_thread.is_alive()
        d.stop()


def _make_agent(master, tmp_path, script, node_rank=0, **cfg_kw):
    cfg = ElasticLaunchConfig(
        min_nodes=1,
        max_nodes=cfg_kw.pop("max_nodes", 1),
        node_id=node_rank,
        node_rank=node_rank,
        entrypoint=script,
        master_addr=master.addr,
        monitor_interval=0.2,
        rdzv_timeout=30,
        save_at_breakpoint=False,
        log_dir=str(tmp_path / f"logs{node_rank}"),
        **cfg_kw,
    )
    return ElasticTrainingAgent(
        cfg, client=_client(master, node_rank), start_ckpt_saver=False
    )


class TestElasticTrainingAgent:
    def test_successful_run(self, master1, tmp_path):
        script = _write_script(tmp_path, "ok.py", "print('done')\n")
        agent = _make_agent(master1, tmp_path, script)
        assert agent.run() == AGENT_EXIT_OK

    def test_restart_then_success(self, master1, tmp_path):
        # Fails on first run, succeeds once the marker file exists.
        marker = tmp_path / "marker"
        script = _write_script(
            tmp_path,
            "flaky.py",
            f"""
import os, sys
marker = {str(marker)!r}
if not os.path.exists(marker):
    open(marker, 'w').close()
    sys.exit(3)
print('recovered')
""",
        )
        agent = _make_agent(master1, tmp_path, script, max_restarts=2)
        assert agent.run() == AGENT_EXIT_OK
        assert agent._restart_count == 1

    def test_relaunch_when_budget_exhausted(self, master1, tmp_path):
        script = _write_script(tmp_path, "bad.py", "import sys\nsys.exit(5)\n")
        agent = _make_agent(master1, tmp_path, script, max_restarts=0)
        assert agent.run() == AGENT_EXIT_RELAUNCH

    @pytest.mark.slow  # ~34 s: waits out the master-lost deadline for
    # real; the fast orphan-guard case (TestDiagnosisClassification::
    # test_orphan_guard_aborts_when_master_lost) keeps the master-dark
    # abort path in tier-1
    def test_agent_exits_when_master_dies_mid_training(
        self, master1, tmp_path, monkeypatch
    ):
        """The orphan guard END TO END: a training agent whose master
        disappears must tear down (worker + spare reaped) instead of
        supervising forever — the exact state observed live after a
        killed test run."""
        import threading as _threading

        from dlrover_tpu.common.config import get_context

        monkeypatch.setattr(get_context(), "master_lost_timeout_s", 2.0)
        monkeypatch.setattr(get_context(), "heartbeat_interval_s", 0.2)
        script = _write_script(
            tmp_path,
            "sleep.py",
            "import time\nprint('up', flush=True)\ntime.sleep(300)\n",
        )
        agent = _make_agent(master1, tmp_path, script)
        rc = {}
        # daemon: a guard regression must fail THIS test, not wedge the
        # whole pytest process behind a non-daemon supervisor thread.
        t = _threading.Thread(
            target=lambda: rc.update(v=agent.run()), daemon=True
        )
        t.start()
        try:
            # Let the worker come up, then kill the master.
            deadline = time.time() + 30
            while time.time() < deadline and agent._worker is None:
                time.sleep(0.1)
            time.sleep(1.0)
            master1.stop()
            t.join(60)
            assert not t.is_alive(), (
                "agent kept supervising a masterless world"
            )
            assert rc.get("v") == AGENT_EXIT_FATAL
        finally:
            agent.stop()
        # Worker and warm spare both reaped.
        if agent._worker is not None and agent._worker._proc is not None:
            assert agent._worker._proc.poll() is not None
        assert agent._spare is None

    def test_membership_change_triggers_re_rendezvous(self, master2, tmp_path):
        """Two agents; kill one worker → both re-rendezvous into round 1.

        This is the core elastic scenario (reference training.py:1262):
        a healthy agent notices waiters and restarts its worker group so
        the whole world re-meshes.
        """
        script = _write_script(
            tmp_path,
            "sleep.py",
            "import time\nprint('up', flush=True)\ntime.sleep(120)\n",
        )
        agents = [
            _make_agent(master2, tmp_path, script, node_rank=r, max_nodes=2)
            for r in (0, 1)
        ]
        codes = {}
        threads = [
            threading.Thread(target=lambda r=r: codes.update({r: agents[r].run()}))
            for r in (0, 1)
        ]
        for t in threads:
            t.start()

        def wait_for(cond, timeout=30):
            deadline = time.time() + timeout
            while time.time() < deadline:
                if cond():
                    return True
                time.sleep(0.1)
            return False

        # Both workers up in round 0.
        assert wait_for(
            lambda: all(
                a._worker is not None
                and a._worker.poll().state == WorkerState.RUNNING
                for a in agents
            )
        )
        assert agents[0]._world.round == agents[1]._world.round == 0
        victim_pid = agents[1]._worker.pid

        os.kill(victim_pid, signal.SIGKILL)

        # Both agents must land in a new world (round 1) with live workers.
        assert wait_for(
            lambda: all(
                a._world is not None
                and a._world.round == 1
                and a._worker.poll().state == WorkerState.RUNNING
                for a in agents
            ),
            timeout=60,
        ), f"worlds: {[a._world and a._world.round for a in agents]}"
        assert agents[0]._world.world_size == 2

        for a in agents:
            a.stop()
        for t in threads:
            t.join(timeout=30)


class TestNumaAffinity:
    def _fake_sysfs(self, tmp_path, vendor="0x1ae0", node="1",
                    cpulist="4-7,12"):
        pci = tmp_path / "pci"
        dev = pci / "0000:00:05.0"
        dev.mkdir(parents=True)
        (dev.joinpath("vendor")).write_text(vendor + "\n")
        (dev.joinpath("numa_node")).write_text(node + "\n")
        nodes = tmp_path / "node"
        n1 = nodes / f"node{node}"
        n1.mkdir(parents=True)
        (n1.joinpath("cpulist")).write_text(cpulist + "\n")
        return str(pci), str(nodes)

    def test_parse_cpulist_ranges(self):
        from dlrover_tpu.agent.numa import parse_cpulist

        assert parse_cpulist("0-3,8,10-11") == [0, 1, 2, 3, 8, 10, 11]
        assert parse_cpulist("") == []

    def test_detects_tpu_node_and_pins(self, tmp_path, monkeypatch):
        import os as _os

        from dlrover_tpu.agent.numa import apply_numa_affinity

        pci, nodes = self._fake_sysfs(tmp_path)
        allowed = _os.sched_getaffinity(0)
        # Pin to fake-node CPUs intersected with reality would fail on
        # small CI hosts — monkeypatch the syscall and assert the set.
        pinned = {}
        monkeypatch.setattr(
            _os, "sched_setaffinity", lambda pid, cpus: pinned.update(c=set(cpus))
        )
        got = apply_numa_affinity(0, pci_root=pci, node_root=nodes)
        assert got == {4, 5, 6, 7, 12}
        assert pinned["c"] == {4, 5, 6, 7, 12}
        assert allowed == _os.sched_getaffinity(0)  # untouched for real

    def test_non_tpu_host_is_noop(self, tmp_path):
        from dlrover_tpu.agent.numa import apply_numa_affinity

        pci, nodes = self._fake_sysfs(tmp_path, vendor="0x8086")
        assert apply_numa_affinity(0, pci_root=pci, node_root=nodes) is None

    def test_unknown_node_is_noop(self, tmp_path):
        from dlrover_tpu.agent.numa import apply_numa_affinity

        pci, nodes = self._fake_sysfs(tmp_path, node="-1")
        assert apply_numa_affinity(0, pci_root=pci, node_root=nodes) is None


class TestWarmSpare:
    """Warm-spare workers (round 4): restarts skip the interpreter +
    jax/flax import tax — the dominant term in elastic MTTR."""

    def test_spare_adopted_and_env_contract_applied(self, tmp_path):
        from dlrover_tpu.agent.worker import WarmSpare, WorkerProcess

        out = tmp_path / "out.txt"
        script = tmp_path / "train.py"
        script.write_text(
            "import os, pathlib, sys\n"
            f"pathlib.Path(r'{out}').write_text(\n"
            "    os.environ['DLROVER_COORDINATOR_ADDRESS'] + ' '\n"
            "    + os.environ['DLROVER_RESTART_COUNT']\n"
            "    + ' ' + (sys.argv[1] if len(sys.argv) > 1 else ''))\n"
        )
        spec = WorkerSpec(
            entrypoint=str(script),
            args=["argA"],
            log_dir=str(tmp_path / "logs"),
        )
        spare = WarmSpare(spec, tag="t")
        assert spare.wait_ready(timeout=30), "spare never became ready"
        worker = WorkerProcess(spec, restart_count=3)
        t0 = time.time()
        how = worker.start(
            dynamic_env={"DLROVER_COORDINATOR_ADDRESS": "1.2.3.4:5"},
            spare=spare,
        )
        assert how == "warm"
        result = worker.wait(timeout=30)
        warm_latency = time.time() - t0
        assert result.state == WorkerState.SUCCEEDED, worker.tail_log()
        assert out.read_text() == "1.2.3.4:5 3 argA"
        # the whole point: handoff->exit must beat a cold python start
        assert warm_latency < 5.0, warm_latency

    def test_kill_reaps_the_spare_no_zombie(self, tmp_path):
        """PR 9 thread-lifecycle finding: kill() SIGKILLed the group
        but never wait()ed — every killed spare left a zombie holding
        its pid-table slot for the agent's lifetime."""
        from dlrover_tpu.agent.worker import WarmSpare

        script = tmp_path / "train.py"
        script.write_text("print('ok')\n")
        spec = WorkerSpec(entrypoint=str(script))
        spare = WarmSpare(spec, tag="z")
        assert spare.wait_ready(timeout=30), "spare never became ready"
        spare.kill()
        # reaped: returncode collected, and /proc no longer shows a
        # zombie ('Z') for the pid
        assert spare.proc.returncode is not None
        stat = f"/proc/{spare.proc.pid}/stat"
        if os.path.exists(stat):  # pid not reused yet
            with open(stat, "rb") as f:
                data = f.read()
            state = data[data.rindex(b")") + 2 :].split()[0]
            assert state != b"Z", "killed spare left a zombie"

    def test_unready_spare_falls_back_cold(self, tmp_path):
        from dlrover_tpu.agent.worker import WarmSpare, WorkerProcess

        script = tmp_path / "ok.py"
        script.write_text("print('ran')\n")
        spec = WorkerSpec(entrypoint=str(script))

        class NeverReady(WarmSpare):
            def wait_ready(self, timeout=0.0):
                return False

        spare = NeverReady(spec, tag="n")
        try:
            worker = WorkerProcess(spec)
            how = worker.start(spare=spare)
            assert how == "cold"
            assert worker.wait(timeout=30).state == WorkerState.SUCCEEDED
            assert spare.proc.poll() is None  # untouched, still warm-ing
        finally:
            spare.kill()

    def test_agent_keeps_one_spare_and_cleans_up(self, master1, tmp_path):
        script = tmp_path / "train.py"
        # outlives the (shortened) spare-spawn delay: the timer only
        # fires while the agent is still running
        script.write_text("import time\ntime.sleep(4.0)\n")
        config = ElasticLaunchConfig(
            min_nodes=1,
            max_nodes=1,
            entrypoint=str(script),
            master_addr=master1.addr,
            monitor_interval=0.3,
            warm_spare=True,
        )
        agent = ElasticTrainingAgent(
            config,
            client=_client(master1, 0),
            start_ckpt_saver=False,
        )
        agent.SPARE_SPAWN_DELAY_S = 0.5
        rc = {}
        t = threading.Thread(target=lambda: rc.update(v=agent.run()))
        t.start()
        deadline = time.time() + 30
        saw_spare = False
        while time.time() < deadline and not saw_spare:
            saw_spare = agent._spare is not None
            time.sleep(0.1)
        assert saw_spare, "agent never spawned a warm spare"
        spare_proc = agent._spare.proc
        t.join(timeout=60)
        assert rc.get("v") == AGENT_EXIT_OK
        assert agent._spare is None
        deadline = time.time() + 10
        while time.time() < deadline and spare_proc.poll() is None:
            time.sleep(0.1)
        assert spare_proc.poll() is not None, "spare leaked after agent exit"


class TestSoftRemesh:
    """Soft re-mesh (round 4): survivors of a membership change keep
    their PROCESS — the agent runs the new rendezvous while the worker
    trains, offers the world at a step boundary, and only restarts on
    refusal/timeout. The reference restarts worker processes on every
    membership change (training.py:1262)."""

    def test_worker_side_accept_and_refuse(self, tmp_path, monkeypatch):
        import json

        from dlrover_tpu.trainer.elastic import ElasticContext
        from dlrover_tpu.trainer.remesh import REMESH_DIR_ENV, SoftRemesh

        monkeypatch.setenv(REMESH_DIR_ENV, str(tmp_path))
        ctx = ElasticContext(num_processes=2, process_id=1, coordinator="a:1")
        sr = SoftRemesh(ctx)
        assert sr.install()
        try:
            pid = os.getpid()
            assert (tmp_path / f"ready_{pid}").exists()

            def offer(world):
                (tmp_path / f"world_{pid}").write_text(json.dumps(world))
                os.kill(pid, signal.SIGUSR1)
                deadline = time.time() + 5
                while not sr.requested and time.time() < deadline:
                    time.sleep(0.01)
                assert sr.requested
                ok = sr.apply()
                ack = json.loads((tmp_path / f"ack_{pid}").read_text())
                assert ack["accepted"] == ok
                return ok

            # same shape, new coordinator, no live jax.distributed: ride
            assert offer(
                {"coordinator": "b:2", "num_processes": 2, "process_id": 1,
                 "round": 3}
            )
            assert ctx.coordinator == "b:2" and sr.applied == 1
            # shape change: refuse (agent will hard-restart)
            assert not offer(
                {"coordinator": "b:2", "num_processes": 3, "process_id": 1,
                 "round": 4}
            )
            # live distributed runtime + coordinator change: refuse
            import dlrover_tpu.trainer.remesh as remesh_mod

            monkeypatch.setattr(
                remesh_mod, "_jax_distributed_initialized", lambda: True
            )
            assert not offer(
                {"coordinator": "c:3", "num_processes": 2, "process_id": 1,
                 "round": 5}
            )
        finally:
            sr.uninstall()

    def test_agent_offers_world_to_live_worker(self, master2, tmp_path):
        """Two agents; when a waiter appears, the protocol-speaking
        worker adopts the new world and its PID never changes."""
        import json

        script = tmp_path / "protocol_worker.py"
        script.write_text(
            "import json, os, signal, sys, time\n"
            "d = os.environ['DLROVER_REMESH_DIR']\n"
            "os.makedirs(d, exist_ok=True)\n"
            "pid = os.getpid()\n"
            "flag = []\n"
            "signal.signal(signal.SIGUSR1, lambda *a: flag.append(1))\n"
            "open(f'{d}/ready_{pid}', 'w').write(str(pid))\n"
            "t0 = time.time()\n"
            "while time.time() - t0 < 60:\n"
            "    if flag:\n"
            "        flag.clear()\n"
            "        world = json.load(open(f'{d}/world_{pid}'))\n"
            "        json.dump({'accepted': True},\n"
            "                  open(f'{d}/ack_{pid}', 'w'))\n"
            "        open(os.environ['ADOPTED_FILE'], 'w').write(\n"
            "            str(world['round']))\n"
            "    time.sleep(0.05)\n"
            "sys.exit(0)\n"
        )
        adopted = tmp_path / "adopted"
        config = ElasticLaunchConfig(
            min_nodes=2,
            max_nodes=2,
            node_rank=0,
            entrypoint=str(script),
            master_addr=master2.addr,
            monitor_interval=0.3,
            warm_spare=False,
            extra_env={"ADOPTED_FILE": str(adopted)},
        )
        agent = ElasticTrainingAgent(
            config,
            client=_client(master2, 0),
            start_ckpt_saver=False,
        )

        def peer_join():
            handler = MasterRendezvousHandler(
                RendezvousName.TRAINING,
                node_rank=1,
                client=_client(master2, 1),
                rdzv_timeout=60,
            )
            return handler.next_rendezvous()

        rc = {}
        t = threading.Thread(target=lambda: rc.update(v=agent.run()))
        t.start()
        # node 1 joins round 0 alongside the agent so it forms instantly
        t_first = threading.Thread(target=peer_join)
        t_first.start()
        try:
            t_first.join(timeout=60)
            # wait for the worker to come up and publish its ready file
            deadline = time.time() + 60
            while time.time() < deadline and (
                agent._worker is None
                or agent._worker.pid is None
                or not os.path.exists(
                    os.path.join(
                        agent._remesh_dir, f"ready_{agent._worker.pid}"
                    )
                )
            ):
                time.sleep(0.1)
            pid_before = agent._worker.pid
            assert pid_before and os.path.exists(
                os.path.join(agent._remesh_dir, f"ready_{pid_before}")
            ), "worker never published its soft-remesh ready file"

            # node 1 re-joins (its own restart): membership change
            joiner = {}
            t2 = threading.Thread(
                target=lambda: joiner.update(w=peer_join())
            )
            t2.start()
            deadline = time.time() + 60
            while time.time() < deadline and not adopted.exists():
                time.sleep(0.2)
            assert adopted.exists(), "worker never adopted the new world"
            assert agent._worker.pid == pid_before, (
                "survivor was restarted despite accepting the soft remesh"
            )
            t2.join(timeout=30)
            assert joiner["w"].world_size == 2
        finally:
            agent.stop()
            t.join(timeout=30)

    def test_loop_rides_membership_change_in_process(
        self, tmp_path, monkeypatch
    ):
        """ElasticTrainLoop + SoftRemesh end-to-end in one process: the
        loop keeps stepping across an adopted world."""
        import json

        import jax.numpy as jnp

        from dlrover_tpu.checkpoint.engine import CheckpointEngine
        from dlrover_tpu.trainer.elastic import ElasticContext
        from dlrover_tpu.trainer.loop import ElasticTrainLoop
        from dlrover_tpu.trainer.remesh import REMESH_DIR_ENV

        monkeypatch.setenv(REMESH_DIR_ENV, str(tmp_path / "remesh"))
        ctx = ElasticContext(num_processes=1, process_id=0)
        steps_done = []

        def step_fn(state, x):
            return state + jnp.sum(x), jnp.sum(x)

        def data():
            while True:
                time.sleep(0.03)
                yield (jnp.ones(()),)

        engine = CheckpointEngine(
            str(tmp_path / "ckpt"), standalone=True, replicate=False
        )
        loop = ElasticTrainLoop(
            engine,
            step_fn,
            ctx=ctx,
            max_steps=40,
            storage_every=1000,
            device_monitor=False,
            trace_host=False,
            on_step=lambda s, l: steps_done.append(s),
        )

        # The loop must run on the MAIN thread (signal handlers); the
        # agent-side offer comes from a helper thread, as in production
        # (where it is a different PROCESS).
        def offer():
            deadline = time.time() + 30
            while time.time() < deadline and len(steps_done) < 5:
                time.sleep(0.05)
            pid = os.getpid()
            d = tmp_path / "remesh"
            (d / f"world_{pid}").write_text(
                json.dumps(
                    {"coordinator": "new:1", "num_processes": 1,
                     "process_id": 0, "round": 9}
                )
            )
            os.kill(pid, signal.SIGUSR1)

        t = threading.Thread(target=offer)
        t.start()
        try:
            final = loop.run(jnp.zeros(()), data())
            t.join(timeout=30)
            assert loop._remesh is not None
            assert loop._remesh.applied == 1
            assert ctx.coordinator == "new:1"
            assert float(final) == 40.0  # no step lost or repeated
        finally:
            engine.shm.unlink()
            engine.close()

    def test_refused_offer_restarts_into_same_round(self, master2, tmp_path):
        """A worker that refuses the offered world is restarted INTO
        that world — no second global rendezvous round is formed."""
        script = tmp_path / "refusing_worker.py"
        script.write_text(
            "import json, os, signal, sys, time\n"
            "d = os.environ['DLROVER_REMESH_DIR']\n"
            "os.makedirs(d, exist_ok=True)\n"
            "pid = os.getpid()\n"
            "flag = []\n"
            "signal.signal(signal.SIGUSR1, lambda *a: flag.append(1))\n"
            "open(f'{d}/ready_{pid}', 'w').write(str(pid))\n"
            "# record every incarnation so the test sees the restart\n"
            "runs = os.environ['RUNS_DIR']\n"
            "open(f'{runs}/run_{pid}', 'w').write(\n"
            "    os.environ.get('DLROVER_NUM_PROCESSES', '?'))\n"
            "t0 = time.time()\n"
            "while time.time() - t0 < 60:\n"
            "    if flag:\n"
            "        flag.clear()\n"
            "        json.dump({'accepted': False},\n"
            "                  open(f'{d}/ack_{pid}', 'w'))\n"
            "    time.sleep(0.05)\n"
            "sys.exit(0)\n"
        )
        runs = tmp_path / "runs"
        runs.mkdir()
        config = ElasticLaunchConfig(
            min_nodes=2,
            max_nodes=2,
            node_rank=0,
            entrypoint=str(script),
            master_addr=master2.addr,
            monitor_interval=0.3,
            warm_spare=False,
            extra_env={"RUNS_DIR": str(runs)},
        )
        agent = ElasticTrainingAgent(
            config,
            client=_client(master2, 0),
            start_ckpt_saver=False,
        )

        def peer_join():
            handler = MasterRendezvousHandler(
                RendezvousName.TRAINING,
                node_rank=1,
                client=_client(master2, 1),
                rdzv_timeout=60,
            )
            return handler.next_rendezvous()

        rc = {}
        t = threading.Thread(target=lambda: rc.update(v=agent.run()))
        t.start()
        t_first = threading.Thread(target=peer_join)
        t_first.start()
        try:
            t_first.join(timeout=60)
            deadline = time.time() + 60
            while time.time() < deadline and (
                agent._worker is None
                or agent._worker.pid is None
                or not os.path.exists(
                    os.path.join(
                        agent._remesh_dir, f"ready_{agent._worker.pid}"
                    )
                )
            ):
                time.sleep(0.1)
            pid_before = agent._worker.pid
            assert pid_before

            joiner = {}
            t2 = threading.Thread(
                target=lambda: joiner.update(w=peer_join())
            )
            t2.start()
            t2.join(timeout=60)
            new_round = joiner["w"].round

            # refusal must RESTART the worker (new pid) into the SAME
            # round the refusal consumed
            deadline = time.time() + 60
            while time.time() < deadline and (
                agent._worker.pid == pid_before
                or len(list(runs.iterdir())) < 2
            ):
                time.sleep(0.2)
            assert agent._worker.pid != pid_before, (
                "refusing worker was never restarted"
            )
            assert agent._world.round == new_round, (
                "restart formed an extra rendezvous round instead of "
                "reusing the refused offer's"
            )
        finally:
            agent.stop()
            t.join(timeout=30)
