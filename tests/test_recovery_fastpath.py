"""Warm-restart fast path (docs/recovery.md): compile-ahead remesh,
overlapped restore, double-buffered input, and MTTR phase attribution.

Everything here is deliberately cheap — tiny jitted steps, no model
compiles — because tier-1 is a time-boxed run and the production-shaped
proof (warm-vs-cold A/B at equal fault plans) lives in the bench's
``recovery_ab`` section and the storm harness.
"""

import json
import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.attribution import recovery
from dlrover_tpu.checkpoint.engine import CheckpointEngine
from dlrover_tpu.checkpoint.saver import AsyncCheckpointSaver
from dlrover_tpu.checkpoint.shm_handler import SharedMemoryHandler
from dlrover_tpu.trainer.dataloader import PrefetchIterator
from dlrover_tpu.trainer.loop import (
    ElasticTrainLoop,
    gradient_accumulation_steps,
)
from dlrover_tpu.trainer.precompile import (
    CompileAheadService,
    anticipated_worlds,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def fresh_saver(tmp_ipc_dir, monkeypatch):
    job = f"recfp_{os.getpid()}_{id(tmp_ipc_dir)}"
    monkeypatch.setenv("DLROVER_JOB_NAME", job)
    AsyncCheckpointSaver.reset()
    yield
    AsyncCheckpointSaver.reset()
    for name in os.listdir("/dev/shm"):
        if name.startswith(f"dlrover_{job}_"):
            SharedMemoryHandler(
                0, name=name.split(f"dlrover_{job}_", 1)[1]
            ).unlink()


# ---------------------------------------------------------------------------
# PrefetchIterator: the double-buffered input pipeline
# ---------------------------------------------------------------------------


class TestPrefetchIterator:
    def test_order_and_values_identical_to_source(self):
        src = [np.full((2, 2), i, np.int32) for i in range(20)]
        got = list(PrefetchIterator(iter(src)))
        assert len(got) == 20
        for want, have in zip(src, got):
            np.testing.assert_array_equal(want, have)

    def test_stage_fn_applied_in_order(self):
        got = list(PrefetchIterator(iter(range(10)), stage_fn=lambda x: x * 2))
        assert got == [i * 2 for i in range(10)]

    def test_producer_error_reraises_on_consumer(self):
        def src():
            yield 1
            raise RuntimeError("boom in producer")

        it = PrefetchIterator(src())
        assert next(it) == 1
        with pytest.raises(RuntimeError, match="boom in producer"):
            for _ in range(5):
                next(it)

    def test_stage_fn_error_reraises(self):
        def bad_stage(x):
            raise ValueError("stage failed")

        it = PrefetchIterator(iter([1, 2]), stage_fn=bad_stage)
        with pytest.raises(ValueError, match="stage failed"):
            next(it)

    def test_lazy_start_consumes_nothing_before_first_draw(self):
        drawn = []

        def src():
            for i in range(5):
                drawn.append(i)
                yield i

        it = PrefetchIterator(src())
        time.sleep(0.1)
        assert drawn == []  # no thread until the first __next__
        assert next(it) == 0
        it.close()

    def test_exhaustion_raises_stop_iteration_then_stays_stopped(self):
        it = PrefetchIterator(iter([7]))
        assert next(it) == 7
        with pytest.raises(StopIteration):
            next(it)
        with pytest.raises(StopIteration):
            next(it)

    def test_close_is_idempotent_and_unblocks_producer(self):
        def endless():
            i = 0
            while True:
                yield i
                i += 1

        it = PrefetchIterator(endless())
        assert next(it) == 0
        it.close()
        it.close()
        # the producer thread exited (did not wedge on a full queue)
        assert it._thread is None or not it._thread.is_alive()


class TestLoopPrefetchBitExact:
    """The acceptance contract: the prefetch loop is bit-exact with the
    synchronous loop under JAX_PLATFORMS=cpu — same steps, same final
    state bits."""

    def _run(self, tmp_path, tag, prefetch):
        @jax.jit
        def step(state, x, y):
            w = state["w"] * 0.99 + jnp.asarray(x).sum() * 1e-3
            b = state["b"] + jnp.asarray(y).mean()
            return {"w": w, "b": b}, w.sum()

        r = np.random.default_rng(7)

        def data():
            # host numpy: the prefetch producer thread must not
            # dispatch jax computations
            while True:
                x = r.integers(0, 100, (4, 8)).astype(np.int32)
                yield x, np.roll(x, 1, axis=1)

        engine = CheckpointEngine(
            str(tmp_path / f"ckpt_{tag}"), standalone=True, replicate=False
        )
        state = {
            "w": jnp.arange(16, dtype=jnp.float32).reshape(4, 4),
            "b": jnp.float32(0.0),
        }
        loop = ElasticTrainLoop(
            engine,
            step,
            max_steps=6,
            storage_every=100,
            prefetch_input=prefetch,
        )
        try:
            final = loop.run(state, data())
        finally:
            engine.shm.unlink()
            engine.close()
        return final

    def test_prefetch_loop_bit_exact_with_sync_loop(self, tmp_path):
        sync = self._run(tmp_path, "sync", prefetch=False)
        pre = self._run(tmp_path, "pre", prefetch=True)
        for a, b in zip(jax.tree.leaves(sync), jax.tree.leaves(pre)):
            # bitwise, not allclose: staging a draw early must not
            # change the bytes
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_sync_escape_hatch_still_applies_stage_fn(self, tmp_path):
        staged = []

        def stage(batch):
            staged.append(1)
            return batch

        @jax.jit
        def step(state, x):
            return {"v": state["v"] + jnp.asarray(x).sum()}, state["v"].sum()

        engine = CheckpointEngine(
            str(tmp_path / "ckpt_hatch"), standalone=True, replicate=False
        )
        try:
            loop = ElasticTrainLoop(
                engine,
                step,
                max_steps=3,
                storage_every=100,
                prefetch_input=False,
                input_stage_fn=stage,
            )
            loop.run(
                {"v": jnp.zeros(2)},
                ((np.ones((2, 2), np.float32),) for _ in range(10)),
            )
        finally:
            engine.shm.unlink()
            engine.close()
        assert len(staged) == 3


# ---------------------------------------------------------------------------
# Fixed-global-batch accumulation rounding (trainer/loop.py)
# ---------------------------------------------------------------------------


class TestGradAccumRounding:
    def test_divisible_worlds(self):
        assert gradient_accumulation_steps(8, 8) == 1
        assert gradient_accumulation_steps(8, 4) == 2
        assert gradient_accumulation_steps(8, 2) == 4
        assert gradient_accumulation_steps(8, 1) == 8

    def test_non_divisible_rounds_up(self):
        # round UP: the global batch grows slightly rather than
        # silently shrinking (documented in trainer/loop.py)
        assert gradient_accumulation_steps(8, 3) == 3  # ceil(8/3)
        assert gradient_accumulation_steps(8, 5) == 2  # ceil(8/5)
        assert gradient_accumulation_steps(7, 2) == 4  # ceil(7/2)
        assert gradient_accumulation_steps(10, 4) == 3  # ceil(10/4)

    def test_grown_or_degenerate_worlds(self):
        assert gradient_accumulation_steps(4, 8) == 1  # grown past max
        assert gradient_accumulation_steps(4, 4) == 1
        assert gradient_accumulation_steps(4, 0) == 1  # guard
        assert gradient_accumulation_steps(0, 4) == 1


# ---------------------------------------------------------------------------
# Compile-ahead remesh (trainer/precompile.py)
# ---------------------------------------------------------------------------


class TestAnticipatedWorlds:
    def test_adjacent_worlds_first(self):
        worlds = anticipated_worlds(4, max_workers=8, node_unit=1)
        assert worlds[0] in (3, 5) and worlds[1] in (3, 5)
        assert 4 not in worlds

    def test_shrink_ladder_covers_distinct_accum_factors(self):
        worlds = anticipated_worlds(8, max_workers=8, node_unit=1)
        # every distinct accumulation factor below 8 compiles a
        # distinct program; each must appear exactly once
        factors = {gradient_accumulation_steps(8, w) for w in worlds}
        assert {2, 3, 4} <= factors
        assert len(worlds) == len(set(worlds))

    def test_node_unit_granularity(self):
        worlds = anticipated_worlds(4, max_workers=8, node_unit=2)
        assert all(w % 2 == 0 for w in worlds)
        assert 6 in worlds and 2 in worlds

    def test_bounds_and_degenerate(self):
        assert anticipated_worlds(0) == []
        assert anticipated_worlds(1, max_workers=1) == []
        worlds = anticipated_worlds(8, max_workers=8)
        assert all(1 <= w <= 8 for w in worlds)


class TestCompileAheadService:
    def test_compiles_anticipated_set_and_records_timing(self):
        built = []
        svc = CompileAheadService(
            lambda w: built.append(w), current_world=4, max_workers=8
        )
        svc.start()
        assert svc.wait(timeout=10)
        svc.stop()
        stats = svc.stats()
        assert set(built) == set(stats["compiled"])
        assert set(built) == set(anticipated_worlds(4, 8))
        assert all(t >= 0 for t in stats["compiled"].values())
        assert stats["errors"] == {}

    def test_build_errors_recorded_not_raised(self):
        def build(w):
            if w == 3:
                raise RuntimeError("mesh mismatch")

        svc = CompileAheadService(build, current_world=4, max_workers=8)
        svc.start()
        assert svc.wait(timeout=10)
        svc.stop()
        stats = svc.stats()
        assert "mesh mismatch" in stats["errors"][3]
        assert 3 not in stats["compiled"]

    def test_reanticipate_dedups_compiled_worlds(self):
        built = []
        svc = CompileAheadService(
            lambda w: built.append(w), current_world=4, max_workers=8
        )
        svc.start()
        assert svc.wait(timeout=10)
        first = list(built)
        fresh = svc.anticipate(5)
        assert svc.wait(timeout=10)
        svc.stop()
        # worlds already compiled for current=4 are not re-built
        assert not (set(first) & set(fresh))
        assert len(built) == len(set(built))


class TestPlannerRungLadder:
    """``anticipated_worlds``/``CompileAheadService`` driven by the 2D
    replanner (docs/elastic_parallelism.md): entries are the Rungs each
    anticipated world would actually be replanned onto, not bare ints —
    the accum-only int ladder under-reports distinct programs once a
    shrink can trade DP for PP."""

    @staticmethod
    def _planner():
        from dlrover_tpu.parallel.replan import (
            CostModel,
            ElasticReplanner,
            Rung,
        )

        return ElasticReplanner(
            CostModel(
                param_bytes=1 << 20,
                opt_bytes=2 << 20,
                hbm_bytes_per_device=1_200_000,
                reference=Rung(dp=8),
                opt_dp_shard=True,
            ),
            full_dp=8,
            current=Rung(dp=8),
            max_pp=2,
        )

    def test_planner_ladder_is_the_planned_rungs(self):
        from dlrover_tpu.parallel.replan import Rung

        rungs = anticipated_worlds(
            8, max_workers=8, node_unit=4, planner=self._planner()
        )
        # one likely world (8 - 4 devices): under the HBM cap its PLAN
        # is the dp→pp trade, so the anticipation set carries the 2D
        # rung — the int ladder would have said "world 4" and the
        # compile-ahead cache would be warm for the wrong program
        assert rungs == [Rung(dp=2, pp=2, accum=4)]

    def test_int_ladder_unchanged_without_planner(self):
        assert anticipated_worlds(
            4, max_workers=8, node_unit=1, planner=None
        ) == anticipated_worlds(4, max_workers=8, node_unit=1)
        assert anticipated_worlds(0, planner=None) == []

    def test_service_compiles_rung_keys(self):
        from dlrover_tpu.parallel.replan import Rung

        built = []
        svc = CompileAheadService(
            lambda r: built.append(r),
            current_world=8,
            max_workers=8,
            node_unit=4,
            planner=self._planner(),
        )
        svc.start()
        assert svc.wait(timeout=10)
        svc.stop()
        assert built == [Rung(dp=2, pp=2, accum=4)]
        stats = svc.stats()
        assert set(stats["compiled"]) == {Rung(dp=2, pp=2, accum=4)}
        assert stats["errors"] == {}

    def test_stage_build_fn_compiles_per_stage_programs(self):
        from dlrover_tpu.parallel.replan import Rung
        from dlrover_tpu.trainer.precompile import make_stage_build_fn

        layers = {
            "w": jax.ShapeDtypeStruct((4, 8, 8), jnp.float32),
            "b": jax.ShapeDtypeStruct((4, 8), jnp.float32),
        }

        def stage_fn(params, x):
            def body(h, layer):
                return jnp.tanh(h @ layer["w"] + layer["b"]), None

            out, _ = jax.lax.scan(body, x, params)
            return out

        build = make_stage_build_fn(
            stage_fn, layers, np.zeros((2, 8), np.float32)
        )
        # a Rung's pp picks the stage depth; a bare int works too
        compiled = build(Rung(dp=2, pp=2, accum=4))
        assert compiled is not None
        assert build(1) is not None
        # depth that does not divide the layer count is a recorded error
        with pytest.raises(ValueError):
            build(3)


class TestCompileCacheKnob:
    def test_enable_disable_and_idempotence(self, tmp_path, monkeypatch):
        import dlrover_tpu.common.compile_cache as cc
        from dlrover_tpu.common.config import get_context

        prev = jax.config.jax_compilation_cache_dir
        monkeypatch.setattr(cc, "_applied_dir", None)
        monkeypatch.setattr(get_context(), "compile_cache_dir", "")
        try:
            # knob unset -> disabled, no config touch
            assert cc.enable_compile_cache() is None
            target = str(tmp_path / "xla_cache")
            assert cc.enable_compile_cache(target) == target
            assert jax.config.jax_compilation_cache_dir == target
            assert os.path.isdir(target)
            assert cc.active_cache_dir() == target
            # idempotent re-apply
            assert cc.enable_compile_cache(target) == target
        finally:
            jax.config.update("jax_compilation_cache_dir", prev)

    def test_context_env_wiring(self, monkeypatch):
        from dlrover_tpu.common.config import Context

        monkeypatch.setenv("DLROVER_COMPILE_CACHE_DIR", "/tmp/cc_env")
        monkeypatch.setenv("DLROVER_COMPILE_CACHE_MIN_COMPILE_S", "2.5")
        monkeypatch.setenv("DLROVER_INPUT_PREFETCH", "0")
        monkeypatch.setenv("DLROVER_CKPT_PREFETCH_RESTORE", "false")
        ctx = Context()
        ctx.apply_env()
        assert ctx.compile_cache_dir == "/tmp/cc_env"
        assert ctx.compile_cache_min_compile_s == 2.5
        assert ctx.input_prefetch is False
        assert ctx.ckpt_prefetch_restore is False

    def test_launcher_flags(self):
        from dlrover_tpu.launcher.elastic_run import (
            config_from_args,
            parse_args,
        )

        ns = parse_args(
            [
                "--nnodes", "1",
                "--compile-cache-dir", "/tmp/job_cache",
                "--sync-input",
                "train.py",
            ]
        )
        cfg = config_from_args(ns)
        env = cfg.worker_env()
        assert env["DLROVER_COMPILE_CACHE_DIR"] == "/tmp/job_cache"
        assert env["DLROVER_INPUT_PREFETCH"] == "0"
        # default: prefetch on -> no override exported
        ns2 = parse_args(["--nnodes", "1", "train.py"])
        assert "DLROVER_INPUT_PREFETCH" not in config_from_args(
            ns2
        ).worker_env()


# ---------------------------------------------------------------------------
# MTTR phase attribution (attribution/recovery.py)
# ---------------------------------------------------------------------------


class TestRecoverySpool:
    def test_noop_without_env(self, monkeypatch):
        monkeypatch.delenv(recovery.RECOVERY_DIR_ENV, raising=False)
        assert recovery.record_phase_file("worker", {"x": 1}) is None

    def test_record_and_aggregate_excludes_first_boot(
        self, tmp_path, monkeypatch
    ):
        root = str(tmp_path / "spool")
        monkeypatch.setenv(recovery.RECOVERY_DIR_ENV, root)
        # round 0 = first boot: excluded from the rdzv mean
        recovery.record_phase_file("rdzv", {"rdzv_s": 9.0, "round": 0})
        recovery.record_phase_file("rdzv", {"rdzv_s": 2.0, "round": 1})
        recovery.record_phase_file("rdzv", {"rdzv_s": 4.0, "round": 2})
        # non-resumed worker = first boot: excluded from phase means
        recovery.record_phase_file(
            "worker",
            {"resumed": False, "restore_s": 0.1, "compile_s": 30.0,
             "first_step_s": 31.0},
        )
        recovery.record_phase_file(
            "worker",
            {"resumed": True, "restore_s": 0.4, "compile_s": 6.0,
             "first_step_s": 7.0},
        )
        recovery.record_phase_file(
            "worker",
            {"resumed": True, "restore_s": 0.6, "compile_s": 8.0,
             "first_step_s": 9.0},
        )
        agg = recovery.aggregate(root)
        assert agg["rdzv_s"] == 3.0
        assert agg["restore_s"] == 0.5
        assert agg["compile_s"] == 7.0
        assert agg["first_step_s"] == 8.0
        assert agg["recovery_samples"] == 2

    def test_aggregate_empty_and_torn_records(self, tmp_path):
        root = str(tmp_path / "spool2")
        agg = recovery.aggregate(root)  # missing dir
        assert agg["recovery_samples"] == 0
        os.makedirs(root)
        # a half-written temp file (dot-prefixed) and junk are ignored
        with open(os.path.join(root, ".worker_tmp.json"), "w") as f:
            f.write('{"resumed": true')
        with open(os.path.join(root, "worker_1_2.json"), "w") as f:
            f.write("not json")
        agg = recovery.aggregate(root)
        assert agg["recovery_samples"] == 0

    def test_loop_writes_worker_record(self, tmp_path, monkeypatch):
        spool = str(tmp_path / "rec")
        monkeypatch.setenv(recovery.RECOVERY_DIR_ENV, spool)

        @jax.jit
        def step(state, x):
            return {"v": state["v"] + jnp.asarray(x).sum()}, state["v"].sum()

        engine = CheckpointEngine(
            str(tmp_path / "ckpt"), standalone=True, replicate=False
        )
        try:
            loop = ElasticTrainLoop(
                engine, step, max_steps=3, storage_every=100
            )
            loop.run(
                {"v": jnp.zeros(3)},
                ((np.ones((2,), np.float32),) for _ in range(10)),
            )
        finally:
            engine.shm.unlink()
            engine.close()
        recs = [r for r in recovery.read_records(spool)
                if r["_kind"] == "worker"]
        assert len(recs) == 1
        rec = recs[0]
        assert rec["resumed"] is False  # first boot
        assert rec["first_step_s"] > 0
        assert "compile_s" in rec  # steady step observed -> split done

    def test_report_carries_recovery_section(self):
        from dlrover_tpu.attribution.report import Report, build_report

        rc = {"rdzv_s": 2.0, "restore_s": 0.4, "compile_s": 6.0,
              "first_step_s": 7.0, "recovery_samples": 3}
        rep = build_report(recovery=rc, meta={"job": "t"})
        again = Report.from_dict(json.loads(rep.to_json()))
        assert again.recovery == rc
        text = again.format()
        for key in recovery.PHASES:
            assert key in text
        assert "3 per-host recovery records" in text


# ---------------------------------------------------------------------------
# Overlapped restore (checkpoint/engine.py + saver.py)
# ---------------------------------------------------------------------------


class TestOverlappedRestore:
    def _tree(self):
        return {
            "w": jnp.arange(32, dtype=jnp.float32).reshape(8, 4),
            "step": np.int64(4),
        }

    def test_prefetched_restore_consumed(self, tmp_path):
        tree = self._tree()
        stage = CheckpointEngine(
            str(tmp_path / "ckpt"), standalone=True, replicate=False,
            prefetch_restore=False,
        )
        assert stage.save_to_memory(4, tree)
        stage.close()  # shm image survives the engine
        # a fresh engine (the restarted worker): its constructor starts
        # the host read in the background; load() consumes it
        engine = CheckpointEngine(
            str(tmp_path / "ckpt"), standalone=True, replicate=False,
            prefetch_restore=True,
        )
        try:
            step, restored = engine.load(
                jax.tree.map(jnp.zeros_like, tree)
            )
            assert step == 4
            assert engine.prefetch_used
            for a, b in zip(
                jax.tree.leaves(tree), jax.tree.leaves(restored)
            ):
                np.testing.assert_array_equal(
                    np.asarray(a), np.asarray(b)
                )
        finally:
            engine.shm.unlink()
            engine.close()

    def test_save_supersedes_prefetched_image(self, tmp_path):
        old = self._tree()
        stage = CheckpointEngine(
            str(tmp_path / "ckpt"), standalone=True, replicate=False,
            prefetch_restore=False,
        )
        assert stage.save_to_memory(4, old)
        stage.close()
        engine = CheckpointEngine(
            str(tmp_path / "ckpt"), standalone=True, replicate=False,
            prefetch_restore=True,
        )
        try:
            new = {"w": old["w"] * 2.0, "step": np.int64(9)}
            assert engine.save_to_memory(9, new)
            # the save invalidated the init-time prefetch: load must
            # see step 9, never the stale prefetched step 4
            step, restored = engine.load(
                jax.tree.map(jnp.zeros_like, new)
            )
            assert step == 9
            assert not engine.prefetch_used
            np.testing.assert_array_equal(
                np.asarray(restored["w"]), np.asarray(new["w"])
            )
        finally:
            engine.shm.unlink()
            engine.close()

    def test_saver_prefetch_restore_outcomes(self, tmp_path):
        # no saver instance yet: nothing to prefetch, never raises
        AsyncCheckpointSaver.reset()
        assert AsyncCheckpointSaver.prefetch_restore_async() is None
        engine = CheckpointEngine(
            str(tmp_path / "ckpt"), standalone=True, replicate=False,
            prefetch_restore=False,
        )
        try:
            # The engine ctor returns once the saver's shard-lock
            # server answers, but the runner thread assigns _instance
            # moments later — poll briefly on loaded boxes.
            deadline = time.time() + 10
            inst = AsyncCheckpointSaver._instance
            while inst is None and time.time() < deadline:
                time.sleep(0.05)
                inst = AsyncCheckpointSaver._instance
            assert inst is not None
            # no staged image, no replica manager -> unavailable
            assert inst.prefetch_restore() == "unavailable"
            assert engine.save_to_memory(2, self._tree())
            assert inst.prefetch_restore() == "staged"
            t = AsyncCheckpointSaver.prefetch_restore_async()
            assert t is not None
            t.join(10)
        finally:
            engine.shm.unlink()
            engine.close()


# ---------------------------------------------------------------------------
# Doc lint, folded into tpurun-lint (PR 6): the ad-hoc DLROVER_* doc
# test this file carried (its own exemption list + staleness check)
# now lives in the env-knobs pass of dlrover_tpu/analysis — one typed
# registry in common/constants.py (ENV_KNOBS) enforcing documented <=>
# registered <=> referenced. The assertions stay green through the
# pass; only the duplicate logic is gone.
# ---------------------------------------------------------------------------


def test_env_knob_registry_enforced_by_lint():
    """Every DLROVER_* knob is registered, documented (unless an
    internal process-contract var), still referenced, and every env
    access names a registered knob — via the env-knobs pass."""
    from dlrover_tpu.analysis import run_lint
    from dlrover_tpu.analysis.passes import env_knobs

    result = run_lint(
        [os.path.join(_REPO, "dlrover_tpu")],
        passes=[env_knobs],
        repo_root=_REPO,
    )
    assert result.clean, "\n".join(
        [v.render() for v in result.violations] + result.errors
    )


def test_recovery_doc_linked():
    assert os.path.exists(os.path.join(_REPO, "docs", "recovery.md"))
    for rel in ("README.md", "docs/chaos.md", "docs/deploy.md"):
        text = open(os.path.join(_REPO, rel)).read()
        assert "recovery.md" in text, f"{rel} does not link docs/recovery.md"


def test_storm_result_contract_mentions_phases():
    """The storm docstring/result contract carries the breakdown keys
    (the result dict itself is exercised by the slow storm tests and
    the smoke in test_zz_chaos_e2e)."""
    from dlrover_tpu.chaos import goodput_storm

    doc = goodput_storm.run_goodput_storm.__doc__
    for key in ("rdzv_s", "restore_s", "compile_s", "first_step_s"):
        assert key in doc
