"""Train → checkpoint → rollout handoff (the serve side of the loop).

A user trains with the elastic runtime, flash-checkpoints, and then
stands up a rollout/serving role from the SAME artifacts: the params
restore from the engine's storage (or the Orbax export) into the
generation engine with zero format conversion. The reference cannot
close this loop in one stack — training checkpoints are torch state
dicts, serving is vLLM's own weight loader. Greedy continuity is the
proof: the restored policy generates exactly what the live policy
generated before the round trip.
"""

import jax
import jax.numpy as jnp
import numpy as np

from dlrover_tpu.checkpoint.engine import CheckpointEngine
from dlrover_tpu.models.generation import (
    SamplingConfig,
    generate,
    left_pad_prompts,
)
from dlrover_tpu.models.gpt import GPT, GPTConfig, token_loss_mean
from dlrover_tpu.parallel.mesh import MeshConfig, build_mesh
from dlrover_tpu.parallel.train_step import (
    build_train_step,
    default_optimizer,
    init_train_state,
)


def _train_some(tmp_path, steps=3):
    cfg = GPTConfig(
        vocab_size=128,
        max_seq_len=64,
        num_layers=1,
        num_heads=2,
        head_dim=8,
        embed_dim=16,
        use_remat=False,
        ce_chunk=16,
    )
    model = GPT(cfg)
    mesh = build_mesh(MeshConfig(dp=-1), jax.devices()[:1])
    tx = default_optimizer(learning_rate=1e-2, warmup_steps=1)
    x = jnp.zeros((2, cfg.max_seq_len), jnp.int32)
    state, shardings = init_train_state(model, x, mesh, tx)
    step = build_train_step(model, tx, token_loss_mean, mesh, shardings)
    r = np.random.default_rng(0)
    for _ in range(steps):
        xb = jnp.asarray(
            r.integers(0, cfg.vocab_size, (2, cfg.max_seq_len)), jnp.int32
        )
        state, _ = step(state, xb, jnp.roll(xb, -1, axis=1))
    return model, mesh, state


class TestTrainToServe:
    def test_engine_checkpoint_feeds_generation(self, tmp_path):
        model, mesh, state = _train_some(tmp_path)
        prompts, mask = left_pad_prompts([[5, 9], [3]], pad_id=0)
        sampling = SamplingConfig(max_new_tokens=5, temperature=0.0)
        live, _, _ = generate(
            model, state.params, prompts, mask, jax.random.PRNGKey(0),
            sampling,
        )

        ckpt_dir = str(tmp_path / "ckpt")
        engine = CheckpointEngine(ckpt_dir, mesh=mesh, standalone=True)
        try:
            assert engine.save_to_storage(int(state.step), state)
            assert engine.wait_saving(timeout=120)
        finally:
            engine.shm.unlink()
            engine.close()

        # fresh "rollout role": restore into a template built from the
        # shared model definition — no trainer objects carried over
        model2, mesh2, template = _train_some(tmp_path, steps=0)
        engine2 = CheckpointEngine(ckpt_dir, mesh=mesh2, standalone=True)
        try:
            step, restored = engine2.load(template)
            assert restored is not None and step == int(state.step)
        finally:
            engine2.shm.unlink()
            engine2.close()
        served, _, _ = generate(
            model2, restored.params, prompts, mask, jax.random.PRNGKey(0),
            sampling,
        )
        np.testing.assert_array_equal(np.asarray(served), np.asarray(live))

        # ... and through the continuous-batching scheduler: the same
        # restored params serve a request stream, and each greedy
        # completion matches the one-shot engine's output row
        from dlrover_tpu.models.serving import ContinuousBatchingEngine

        eng = ContinuousBatchingEngine(
            model2, restored.params, sampling, batch_size=2,
            prompt_width=8, decode_chunk=4,
        )
        comps = eng.run([[5, 9], [3]])
        assert [c.uid for c in comps] == [0, 1]  # nothing dropped
        live_np = np.asarray(live)
        for i, c in enumerate(comps):
            assert c.tokens == [int(t) for t in live_np[i]], (
                i, c.tokens, live_np[i]
            )

    def test_orbax_export_feeds_generation(self, tmp_path):
        """The Orbax-interop artifact serves too: a consumer with only
        stock orbax (no dlrover_tpu checkpoint engine) restores the
        exported tree and generates identically."""
        import orbax.checkpoint as ocp

        from dlrover_tpu.checkpoint.orbax_interop import export_to_orbax

        model, mesh, state = _train_some(tmp_path)
        prompts, mask = left_pad_prompts([[7, 2, 4]], pad_id=0)
        sampling = SamplingConfig(max_new_tokens=4, temperature=0.0)
        live, _, _ = generate(
            model, state.params, prompts, mask, jax.random.PRNGKey(0),
            sampling,
        )

        ckpt_dir = str(tmp_path / "ckpt")
        engine = CheckpointEngine(ckpt_dir, mesh=mesh, standalone=True)
        try:
            assert engine.save_to_storage(int(state.step), state)
            assert engine.wait_saving(timeout=120)
        finally:
            engine.shm.unlink()
            engine.close()
        orbax_dir = str(tmp_path / "orbax")
        assert export_to_orbax(ckpt_dir, orbax_dir) == int(state.step)

        # external-consumer path: stock orbax restore, params subtree
        tree = ocp.StandardCheckpointer().restore(orbax_dir)
        served, _, _ = generate(
            model,
            tree["params"],
            prompts,
            mask,
            jax.random.PRNGKey(0),
            sampling,
        )
        np.testing.assert_array_equal(np.asarray(served), np.asarray(live))
