"""Distributed incident tracing, the unified metrics plane, and the
flight recorder (docs/observability.md).

Everything here is a fast synthetic — no JAX, no master/agent
processes except the one real subprocess in the acceptance drill,
which proves the spawn contract (``trace.child_env()``) carries a
trace id across a process boundary through the real event SDK.
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

import pytest

from dlrover_tpu.agent.metric_collector import parse_prometheus
from dlrover_tpu.common import comm, events
from dlrover_tpu.observability import (
    flight_recorder,
    metrics,
    trace,
    trace_merge,
)


@pytest.fixture(autouse=True)
def _clean_observability_state(monkeypatch):
    """Every test starts with no trace, a fresh registry/recorder, and
    no inherited env contract."""
    for var in (
        trace.TRACE_ID_ENV,
        trace.PARENT_SPAN_ENV,
        flight_recorder.TRACE_DIR_ENV,
        flight_recorder.RING_CAP_ENV,
        "DLROVER_EVENT_DIR",
        "DLROVER_METRICS_PORT",
        "DLROVER_METRICS_AGENT_PORT",
    ):
        monkeypatch.delenv(var, raising=False)
    trace.reset()
    metrics.reset_registry()
    flight_recorder.reset_recorder()
    yield
    trace.reset()
    metrics.reset_registry()
    flight_recorder.reset_recorder()
    events.flush_default_exporter()


# ---------------------------------------------------------------------------
# Trace context
# ---------------------------------------------------------------------------


class TestTraceContext:
    def test_no_trace_by_default(self):
        assert trace.current() is None
        assert trace.current_ids() == ("", "")
        assert trace.child_env() == {}

    def test_start_incident_sets_process_context(self):
        ctx = trace.start_incident()
        assert len(ctx.trace_id) == 16 and len(ctx.span_id) == 16
        assert trace.current_ids() == (ctx.trace_id, ctx.span_id)
        # every thread of the process shares the incident
        seen = {}
        t = threading.Thread(target=lambda: seen.update(ids=trace.current_ids()))
        t.start()
        t.join()
        assert seen["ids"] == (ctx.trace_id, ctx.span_id)

    def test_child_env_round_trips_through_env_adoption(self, monkeypatch):
        ctx = trace.start_incident()
        env = trace.child_env()
        assert env[trace.TRACE_ID_ENV] == ctx.trace_id
        assert env[trace.PARENT_SPAN_ENV] == ctx.span_id
        # simulate the spawned process: fresh module state + contract env
        trace.reset()
        for k, v in env.items():
            monkeypatch.setenv(k, v)
        adopted = trace.current()
        assert adopted is not None
        assert adopted.trace_id == ctx.trace_id
        assert adopted.parent_id == ctx.span_id
        assert adopted.span_id != ctx.span_id  # own span in the child

    def test_adopt_release_overlay_scopes_servicer_requests(self):
        trace.start_incident()
        base = trace.current_ids()
        req = comm.BaseRequest(node_id=1, data="{}")
        req.trace_id, req.span_id = "a" * 16, "b" * 16
        token = trace.adopt_request(req)
        assert trace.current_ids()[0] == "a" * 16
        trace.release(token)
        assert trace.current_ids() == base
        # untraced requests are a no-op
        assert trace.adopt_request(comm.BaseRequest()) is None
        trace.release(None)

    def test_push_child_nests_under_current(self):
        ctx = trace.start_incident()
        token = trace.push_child()
        child = trace.current()
        assert child.trace_id == ctx.trace_id
        assert child.parent_id == ctx.span_id
        assert child.span_id != ctx.span_id
        trace.release(token)
        assert trace.current_ids() == (ctx.trace_id, ctx.span_id)
        # no active trace → no token, no crash
        trace.reset()
        assert trace.push_child() is None

    def test_master_clock_offset_ewma(self):
        assert trace.master_clock_offset() is None
        trace.note_master_offset(1.0)
        assert trace.master_clock_offset() == 1.0
        trace.note_master_offset(2.0)
        # EWMA, alpha 0.2: 1.0 + 0.2 * (2.0 - 1.0)
        assert abs(trace.master_clock_offset() - 1.2) < 1e-9

    def test_request_and_response_carry_trace_fields(self):
        # the epoch-fenced RPC envelope grew the correlation fields
        req = comm.BaseRequest()
        assert req.trace_id == "" and req.span_id == ""
        resp = comm.BaseResponse(master_epoch=3, trace_id="t" * 16, server_ts=5.0)
        assert resp.trace_id == "t" * 16 and resp.server_ts == 5.0


# ---------------------------------------------------------------------------
# Event SDK integration
# ---------------------------------------------------------------------------


class TestEventTraceStamping:
    def test_untraced_event_keeps_pre_trace_shape(self):
        e = events.Event("t", "n", events.EventType.INSTANT, {})
        d = e.to_dict()
        assert "trace_id" not in d and "span_id" not in d
        assert "trace_id" not in e.to_json()

    def test_traced_event_is_stamped(self):
        ctx = trace.start_incident()
        e = events.Event("t", "n", events.EventType.INSTANT, {})
        d = e.to_dict()
        assert d["trace_id"] == ctx.trace_id
        assert d["span_id"] == ctx.span_id

    def test_duration_span_pushes_child_span(self):
        ctx = trace.start_incident()
        sink = []

        class _ListExporter(events.Exporter):
            def export(self, event):
                sink.append(event)

        em = events.EventEmitter("agent", exporter=_ListExporter())
        with em.duration("rendezvous", round=1):
            pass
        begin, end = sink
        assert begin.trace_id == end.trace_id == ctx.trace_id
        # begin/end share the child span, nested under the incident span
        assert begin.span_id == end.span_id
        assert begin.span_id != ctx.span_id
        # the overlay was released
        assert trace.current_ids() == (ctx.trace_id, ctx.span_id)

    def test_emitted_events_land_in_flight_ring(self):
        class _Null(events.Exporter):
            def export(self, event):
                pass

        em = events.EventEmitter("agent", exporter=_Null())
        em.instant("incident_detected", kind="test")
        ring = flight_recorder.get_recorder().snapshot()
        assert any(e["name"] == "incident_detected" for e in ring)


class TestAsyncExporterDropAccounting:
    def test_full_queue_drop_is_counted_and_summarized(self):
        """Satellite (a): drops are observable three ways — the
        ``dropped`` property, the registry counter, and a close-time
        ``events_dropped`` summary event written through the sink."""
        gate = threading.Event()
        inner_events = []

        class _GatedExporter(events.Exporter):
            def export(self, event):
                gate.wait(timeout=10)
                inner_events.append(event)

        async_exp = events.AsyncExporter(_GatedExporter(), max_queue=1)
        e1 = events.Event("t", "first", events.EventType.INSTANT, {})
        async_exp.export(e1)
        # wait until the worker thread is inside export (queue empty)
        for _ in range(100):
            if async_exp._queue.empty():
                break
            time.sleep(0.01)
        async_exp.export(events.Event("t", "queued", events.EventType.INSTANT, {}))
        async_exp.export(events.Event("t", "drop1", events.EventType.INSTANT, {}))
        async_exp.export(events.Event("t", "drop2", events.EventType.INSTANT, {}))
        assert async_exp.dropped == 2
        assert (
            metrics.get_registry()
            .counter("dlrover_events_dropped_total")
            .value()
            == 2
        )
        gate.set()
        async_exp.close()
        # both real events drained, then the synchronous drop summary
        names = [e.name for e in inner_events]
        assert names[:2] == ["first", "queued"]
        assert names[-1] == "events_dropped"
        assert inner_events[-1].content == {"dropped": 2}

    def test_no_drops_no_summary(self):
        sink = []

        class _ListExporter(events.Exporter):
            def export(self, event):
                sink.append(event)

        async_exp = events.AsyncExporter(_ListExporter())
        async_exp.export(events.Event("t", "only", events.EventType.INSTANT, {}))
        async_exp.close()
        assert [e.name for e in sink] == ["only"]


# ---------------------------------------------------------------------------
# parse_prometheus flattening (satellite b)
# ---------------------------------------------------------------------------


class TestParsePrometheus:
    def test_labeled_sample_keeps_full_key_and_bare_alias(self):
        gauges = parse_prometheus('tpu_timer_lat{kind="execute"} 3.5\n')
        assert gauges['tpu_timer_lat{kind="execute"}'] == 3.5
        assert gauges["tpu_timer_lat"] == 3.5

    def test_duplicate_family_bare_key_is_last_in_file_order(self):
        text = (
            'lat{kind="a"} 1.0\n'
            'lat{kind="b"} 2.0\n'
        )
        gauges = parse_prometheus(text)
        assert gauges['lat{kind="a"}'] == 1.0
        assert gauges['lat{kind="b"}'] == 2.0
        assert gauges["lat"] == 2.0  # LAST sample wins, documented

    def test_unlabeled_sample_has_one_key(self):
        gauges = parse_prometheus("tpu_timer_hang 1\n")
        assert gauges == {"tpu_timer_hang": 1.0}

    def test_comments_blanks_and_malformed_are_skipped(self):
        text = (
            "# HELP lat latency\n"
            "# TYPE lat gauge\n"
            "\n"
            "lat 1.5\n"
            "9bad_name 2.0\n"
            "no_value_here\n"
            "not_a_number nan-garbage\n"
        )
        assert parse_prometheus(text) == {"lat": 1.5}

    def test_registry_render_is_parseable(self):
        reg = metrics.MetricsRegistry()
        reg.counter("c_total").inc(2)
        reg.gauge("g").set(1.5, node="0")
        gauges = parse_prometheus(reg.render())
        assert gauges["c_total"] == 2.0
        assert gauges['g{node="0"}'] == 1.5


# ---------------------------------------------------------------------------
# Metrics registry + /metrics endpoint
# ---------------------------------------------------------------------------


class TestMetricsRegistry:
    def test_counter_gauge_histogram_render(self):
        reg = metrics.MetricsRegistry()
        reg.counter("req_total", help_="requests").inc()
        reg.counter("req_total").inc(2, code="500")
        reg.gauge("world_size").set(4)
        h = reg.histogram("step_s", buckets=(0.1, 1.0))
        h.observe(0.05)
        h.observe(5.0)
        text = reg.render()
        assert "# HELP req_total requests" in text
        assert "# TYPE req_total counter" in text
        assert "req_total 1.0" in text
        assert 'req_total{code="500"} 2.0' in text
        assert "world_size 4.0" in text
        assert 'step_s_bucket{le="0.1"} 1' in text
        assert 'step_s_bucket{le="+Inf"} 2' in text
        assert "step_s_count 2" in text

    def test_family_type_conflict_raises(self):
        reg = metrics.MetricsRegistry()
        reg.counter("x")
        with pytest.raises(ValueError):
            reg.gauge("x")

    def test_get_or_create_returns_same_family(self):
        reg = metrics.MetricsRegistry()
        assert reg.counter("x") is reg.counter("x")

    def test_gauge_fn_collector_and_ingest(self):
        reg = metrics.MetricsRegistry()
        reg.gauge_fn("sps", lambda: 2.5)
        reg.gauge_fn("boom", lambda: 1 / 0)  # skipped, not fatal
        reg.collector(lambda: {'node_metric{node="0",name="hang"}': 0.0})
        reg.ingest({'tpu_timer_lat{kind="execute"}': 3.25})
        text = reg.render()
        assert "sps 2.5" in text
        assert "boom" not in text
        assert 'node_metric{node="0",name="hang"} 0.0' in text
        assert 'tpu_timer_lat{kind="execute"} 3.25' in text

    def test_snapshot_is_flat_scalars(self):
        reg = metrics.MetricsRegistry()
        reg.counter("c").inc(3)
        reg.gauge("g").set(7)
        reg.histogram("h").observe(1.0)
        reg.gauge_fn("fn", lambda: 9.0)
        snap = reg.snapshot()
        assert snap["c"] == 3.0 and snap["g"] == 7.0 and snap["fn"] == 9.0
        assert snap["h_count"] == 1.0 and snap["h_sum"] == 1.0

    def test_drop_counter_preregistered(self):
        reg = metrics.MetricsRegistry()
        assert "dlrover_events_dropped_total 0.0" in reg.render()


class TestMetricsServer:
    def test_serves_prometheus_text(self):
        reg = metrics.MetricsRegistry()
        reg.gauge("dlrover_job_steps_per_second").set(1.25)
        server = metrics.MetricsServer(registry=reg, port=0).start()
        try:
            url = f"http://127.0.0.1:{server.port}/metrics"
            with urllib.request.urlopen(url, timeout=5) as resp:
                assert resp.status == 200
                assert "text/plain" in resp.headers["Content-Type"]
                text = resp.read().decode()
            gauges = parse_prometheus(text)
            assert gauges["dlrover_job_steps_per_second"] == 1.25
            assert gauges["dlrover_events_dropped_total"] == 0.0
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(
                    f"http://127.0.0.1:{server.port}/other", timeout=5
                )
        finally:
            server.stop()

    def test_maybe_start_respects_knob(self, monkeypatch):
        assert metrics.maybe_start_metrics_server("DLROVER_METRICS_PORT") is None
        monkeypatch.setenv("DLROVER_METRICS_PORT", "0")
        server = metrics.maybe_start_metrics_server("DLROVER_METRICS_PORT")
        try:
            assert server is not None and server.port > 0
        finally:
            server.stop()

    def test_stop_never_started_is_safe(self):
        metrics.MetricsServer(registry=metrics.MetricsRegistry(), port=0).stop()


# ---------------------------------------------------------------------------
# Flight recorder
# ---------------------------------------------------------------------------


class TestFlightRecorder:
    def test_ring_is_bounded(self):
        rec = flight_recorder.FlightRecorder(capacity=3, role="agent")
        for i in range(10):
            rec.record({"name": f"e{i}"})
        names = [e["name"] for e in rec.snapshot()]
        assert names == ["e7", "e8", "e9"]

    def test_dump_writes_atomic_json(self, tmp_path):
        trace.start_incident()
        trace.note_master_offset(0.25)
        rec = flight_recorder.FlightRecorder(capacity=8, role="trainer")
        rec.record({"name": "train_step", "id": "x1"})
        path = rec.dump("chaos kill!", out_dir=str(tmp_path))
        assert path is not None and os.path.exists(path)
        assert "chaos_kill_" in os.path.basename(path)  # sanitized reason
        dump = json.load(open(path))
        assert dump["pid"] == os.getpid()
        assert dump["role"] == "trainer"
        assert dump["clock_offset_s"] == 0.25
        assert dump["trace_id"] == trace.current_ids()[0]
        assert dump["events"] == [{"name": "train_step", "id": "x1"}]
        assert not list(tmp_path.glob("*.tmp"))

    def test_dump_without_dir_is_noop(self):
        rec = flight_recorder.FlightRecorder()
        rec.record({"name": "e"})
        assert rec.dump("fault") is None

    def test_ring_cap_knob(self, monkeypatch):
        monkeypatch.setenv(flight_recorder.RING_CAP_ENV, "5")
        assert flight_recorder.get_recorder("agent").capacity == 5

    def test_dump_on_fault_without_recorder_is_none(self):
        assert flight_recorder.dump_on_fault() is None

    def test_dump_on_fault_dumps_existing_recorder(self, tmp_path, monkeypatch):
        monkeypatch.setenv(flight_recorder.TRACE_DIR_ENV, str(tmp_path))
        flight_recorder.get_recorder("agent").record({"name": "crash"})
        path = flight_recorder.dump_on_fault("fatal_signal")
        assert path is not None
        assert json.load(open(path))["events"] == [{"name": "crash"}]


# ---------------------------------------------------------------------------
# tpurun-trace merge
# ---------------------------------------------------------------------------


def _evt(eid, ts, pid, target, name, etype="instant", trace_id="", **content):
    e = {
        "id": eid, "ts": ts, "pid": pid, "target": target,
        "name": name, "type": etype, "content": content,
    }
    if trace_id:
        e["trace_id"] = trace_id
        e["span_id"] = "s" + eid
    return e


def _write_jsonl(path, evts):
    with open(path, "w") as f:
        for e in evts:
            f.write(json.dumps(e) + "\n")


class TestTraceMerge:
    def _skewed_dir(self, tmp_path):
        """Two processes, one clock 5 s fast. Master-clock truth:
        fault 999 → detect 1000 → rdzv end 1002 → restore end 1003.5
        → resume 1004."""
        tid = "deadbeef00000000"
        master = [
            _evt("m1", 999.0, 100, "chaos", "chaos_kill", victims=[200]),
            _evt("m2", 1000.0, 100, "agent", "incident_detected",
                 trace_id=tid, kind="worker_failure"),
            _evt("m3", 1001.0, 100, "agent", "rendezvous", etype="begin",
                 trace_id=tid),
            _evt("m4", 1002.0, 100, "agent", "rendezvous", etype="end",
                 trace_id=tid),
        ]
        # trainer clock runs 5 s AHEAD of the master's
        trainer = [
            _evt("t1", 1008.5, 200, "trainer", "train_restore",
                 etype="end", trace_id=tid),
            _evt("t2", 1009.0, 200, "trainer", "train_resume",
                 trace_id=tid),
        ]
        _write_jsonl(tmp_path / "events_100_1.jsonl", master)
        _write_jsonl(tmp_path / "events_200_1.jsonl", trainer)
        # the flight dump carries the offset estimate AND repeats a
        # ring event (dedup by id must keep one copy)
        with open(tmp_path / "flight_200_fault_1.json", "w") as f:
            json.dump(
                {"pid": 200, "role": "trainer", "clock_offset_s": 5.0,
                 "events": [trainer[0]]},
                f,
            )
        return tid

    def test_clock_skew_alignment_and_phases(self, tmp_path):
        tid = self._skewed_dir(tmp_path)
        summary = trace_merge.summarize(str(tmp_path))
        assert summary["events"] == 6  # deduped: t1 counted once
        assert summary["processes"] == [100, 200]
        assert summary["clock_offsets"] == {200: 5.0}
        (inc,) = summary["incidents"]
        assert inc["trace_id"] == tid
        assert inc["pids"] == [100, 200]  # ≥2 processes, one trace
        # aligned phases: without the −5 s correction reshard_s would
        # be 6.5 and the breakdown nonsense
        assert abs(inc["mttd_s"] - 1.0) < 1e-6
        assert abs(inc["detect_s"] - 1.0) < 1e-6
        assert abs(inc["rendezvous_s"] - 2.0) < 1e-6
        assert abs(inc["reshard_s"] - 1.5) < 1e-6
        assert abs(inc["recompile_s"] - 0.5) < 1e-6
        assert abs(inc["mttr_s"] - 5.0) < 1e-6
        # the tiling invariant: phases sum to MTTR exactly
        phases = (
            inc["detect_s"] + inc["rendezvous_s"]
            + inc["reshard_s"] + inc["recompile_s"]
        )
        assert abs(phases - inc["mttr_s"]) < 1e-6
        # headline keys mirror the worst incident
        assert summary["mttr_s"] == inc["mttr_s"]
        assert summary["mttd_s"] == inc["mttd_s"]

    def test_missing_milestone_collapses_phase(self, tmp_path):
        tid = "feedface00000000"
        _write_jsonl(
            tmp_path / "events_1_1.jsonl",
            [
                _evt("a", 10.0, 1, "agent", "incident_detected", trace_id=tid),
                # no rendezvous / restore events at all
                _evt("b", 14.0, 1, "trainer", "train_resume", trace_id=tid),
            ],
        )
        (inc,) = trace_merge.summarize(str(tmp_path))["incidents"]
        assert inc["rendezvous_s"] == 0.0 and inc["reshard_s"] == 0.0
        assert inc["recompile_s"] == 4.0  # the gap folded forward
        assert inc["mttd_s"] == 0.0  # no fault event → undetectable
        assert inc["mttr_s"] == 4.0

    def test_train_step_is_resume_fallback(self, tmp_path):
        tid = "cafebabe00000000"
        _write_jsonl(
            tmp_path / "events_1_1.jsonl",
            [
                _evt("a", 10.0, 1, "agent", "incident_detected", trace_id=tid),
                _evt("b", 12.0, 1, "trainer", "train_step", trace_id=tid, step=7),
            ],
        )
        (inc,) = trace_merge.summarize(str(tmp_path))["incidents"]
        assert inc["mttr_s"] == 2.0

    def test_stale_fault_outside_window_not_attributed(self, tmp_path):
        tid = "0123456789abcdef"
        _write_jsonl(
            tmp_path / "events_1_1.jsonl",
            [
                _evt("a", 100.0, 1, "chaos", "chaos_kill"),
                _evt("b", 100.0 + trace_merge.FAULT_WINDOW_S + 60.0, 1,
                     "agent", "incident_detected", trace_id=tid),
            ],
        )
        (inc,) = trace_merge.summarize(str(tmp_path))["incidents"]
        assert inc["mttd_s"] == 0.0  # the old kill is someone else's

    def test_live_reshard_transition_attribution(self, tmp_path):
        # the elastic replanner's live_reshard span carries the from→to
        # rung; tpurun-trace labels the reshard leg with it
        # (docs/elastic_parallelism.md)
        tid = "abad1dea00000000"
        begin = _evt(
            "r1", 11.0, 1, "trainer", "live_reshard", etype="begin",
            trace_id=tid, from_rung="dp4", to_rung="dp2·pp2",
        )
        end = _evt(
            "r2", 13.5, 1, "trainer", "live_reshard", etype="end",
            trace_id=tid, applied=True,
        )
        end["span_id"] = begin["span_id"]  # one span, two events
        _write_jsonl(
            tmp_path / "events_1_1.jsonl",
            [
                _evt("a", 10.0, 1, "agent", "incident_detected",
                     trace_id=tid),
                begin,
                end,
                _evt("b", 14.0, 1, "trainer", "train_resume",
                     trace_id=tid),
            ],
        )
        (inc,) = trace_merge.summarize(str(tmp_path))["incidents"]
        (tr,) = inc["reshard_transitions"]
        assert tr["name"] == "live_reshard"
        assert tr["from_rung"] == "dp4" and tr["to_rung"] == "dp2·pp2"
        assert tr["transition"] == "dp4 → dp2·pp2"
        assert abs(tr["reshard_s"] - 2.5) < 1e-6
        assert tr["applied"] is True

    def test_plain_restore_span_reported_unlabeled(self, tmp_path):
        # a restore with no rung labels still accounts its seconds —
        # just without a transition label
        tid = "face0ff000000000"
        begin = _evt(
            "r1", 11.0, 1, "trainer", "ckpt_load", etype="begin",
            trace_id=tid,
        )
        end = _evt(
            "r2", 12.0, 1, "trainer", "ckpt_load", etype="end",
            trace_id=tid,
        )
        end["span_id"] = begin["span_id"]
        _write_jsonl(
            tmp_path / "events_1_1.jsonl",
            [
                _evt("a", 10.0, 1, "agent", "incident_detected",
                     trace_id=tid),
                begin,
                end,
                _evt("b", 13.0, 1, "trainer", "train_resume",
                     trace_id=tid),
            ],
        )
        (inc,) = trace_merge.summarize(str(tmp_path))["incidents"]
        (tr,) = inc["reshard_transitions"]
        assert tr["name"] == "ckpt_load"
        assert abs(tr["reshard_s"] - 1.0) < 1e-6
        assert "transition" not in tr and "from_rung" not in tr

    def test_cli_writes_chrome_trace(self, tmp_path, capsys):
        self._skewed_dir(tmp_path)
        assert trace_merge.main([str(tmp_path)]) == 0
        summary = json.loads(capsys.readouterr().out)
        assert summary["events"] == 6
        chrome = json.load(open(tmp_path / "trace.json"))
        phases = {e["ph"] for e in chrome["traceEvents"]}
        assert phases == {"B", "E", "i"}
        # µs timeline starts at the (aligned) first event
        assert chrome["traceEvents"][0]["ts"] == 0
        named = {e["name"] for e in chrome["traceEvents"]}
        assert "agent.rendezvous" in named and "chaos.chaos_kill" in named

    def test_cli_empty_dir_fails(self, tmp_path):
        assert trace_merge.main([str(tmp_path), "--summary-only"]) == 1

    def test_torn_tail_line_is_skipped(self, tmp_path):
        with open(tmp_path / "events_1_1.jsonl", "w") as f:
            f.write(json.dumps(_evt("a", 1.0, 1, "t", "train_step")) + "\n")
            f.write('{"id": "torn", "ts": 2.0, "pi')  # killed mid-write
        evts, _ = trace_merge.load_dir(str(tmp_path))
        assert [e["id"] for e in evts] == ["a"]


# ---------------------------------------------------------------------------
# Acceptance drill: one incident, two real processes, one trace_id
# ---------------------------------------------------------------------------


_CHILD_SCRIPT = """
import sys, time
sys.path.insert(0, {repo!r})
from dlrover_tpu.common import events

em = events.EventEmitter("trainer")
with em.duration("train_restore") as span:
    time.sleep(0.02)
    span.end({{"loaded_step": 7}})
em.instant("train_resume", restore_s=0.02)
events.flush_default_exporter()
"""


class TestSyntheticTwinDrill:
    def test_cross_process_incident_trace(self, tmp_path, monkeypatch):
        """The ISSUE acceptance drill, synthetic-twin form: an agent-role
        parent detects a chaos kill and runs rendezvous; a REAL trainer
        subprocess (env contract from ``trace.child_env()``) restores and
        resumes. The merged trace must show one trace_id spanning ≥2
        pids with MTTD + phase breakdown summing to the measured MTTR
        (within 10%)."""
        trace_dir = tmp_path / "trace"
        trace_dir.mkdir()
        monkeypatch.setenv("DLROVER_EVENT_DIR", str(trace_dir))
        events.flush_default_exporter()  # rebuild from the redirected env
        try:
            chaos_evt = events.EventEmitter("chaos")
            agent_evt = events.EventEmitter("agent")

            # fault (untraced: the killer cannot know the detector's
            # future trace), then detection opens the incident
            chaos_evt.instant("chaos_kill", kind="host_kill", victims=[1])
            time.sleep(0.03)
            ctx = trace.start_incident()
            agent_evt.instant("incident_detected", kind="worker_failure")
            with agent_evt.duration("rendezvous", round=1):
                time.sleep(0.03)

            # the worker env contract carries the trace to the child
            env = dict(os.environ)
            env.update(trace.child_env())
            env["DLROVER_EVENT_DIR"] = str(trace_dir)
            repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
            proc = subprocess.run(
                [sys.executable, "-c", _CHILD_SCRIPT.format(repo=repo)],
                env=env,
                capture_output=True,
                text=True,
                timeout=60,
            )
            assert proc.returncode == 0, proc.stderr
        finally:
            events.flush_default_exporter()

        summary = trace_merge.summarize(str(trace_dir))
        (inc,) = summary["incidents"]
        assert inc["trace_id"] == ctx.trace_id
        assert len(inc["pids"]) >= 2  # parent + real subprocess
        assert os.getpid() in inc["pids"]
        # the full chain fired: every phase has real width
        assert inc["mttd_s"] > 0  # chaos_kill → incident_detected
        assert inc["rendezvous_s"] > 0
        assert inc["reshard_s"] > 0  # → child's train_restore end
        assert inc["mttr_s"] > 0
        phases = (
            inc["detect_s"] + inc["rendezvous_s"]
            + inc["reshard_s"] + inc["recompile_s"]
        )
        assert abs(phases - inc["mttr_s"]) <= 0.1 * inc["mttr_s"]
        # both targets visible in one incident
        assert "agent" in inc["targets"] and "trainer" in inc["targets"]
