"""Tests for dlrover_tpu.common: serialization, node model, config, events."""

import dataclasses
import os

import pytest

from dlrover_tpu.common import comm
from dlrover_tpu.common.config import Context
from dlrover_tpu.common.constants import NodeExitReason, NodeStatus
from dlrover_tpu.common.events import (
    AsyncExporter,
    EventEmitter,
    Exporter,
)
from dlrover_tpu.common.node import Node, NodeResource, is_allowed_transition
from dlrover_tpu.common.serialize import dumps, loads, register_message


class TestSerialize:
    def test_roundtrip_simple(self):
        msg = comm.JoinRendezvousRequest(
            node_id=3, node_rank=1, local_world_size=4, rdzv_name="training"
        )
        assert loads(dumps(msg)) == msg

    def test_roundtrip_nested(self):
        world = {
            0: comm.NodeMeta(node_id=0, node_rank=0, addr="10.0.0.1"),
            1: comm.NodeMeta(node_id=1, node_rank=1, addr="10.0.0.2"),
        }
        msg = comm.CommWorldResponse(rdzv_name="training", round=2, world=world)
        out = loads(dumps(msg))
        assert out.world[1].addr == "10.0.0.2"
        assert isinstance(out.world[0], comm.NodeMeta)

    def test_roundtrip_bytes_and_lists(self):
        msg = comm.KeyValuePair(key="k", value=b"\x00\x01binary")
        assert loads(dumps(msg)).value == b"\x00\x01binary"
        msg2 = comm.FaultNodesResponse(fault_nodes=[1, 5, 9])
        assert loads(dumps(msg2)).fault_nodes == [1, 5, 9]

    def test_unknown_type_rejected(self):
        class NotRegistered:
            pass

        with pytest.raises(TypeError):
            dumps(NotRegistered())

    def test_register_duplicate_rejected(self):
        @register_message
        @dataclasses.dataclass
        class UniqueMsg1234:
            x: int = 0

        with pytest.raises(ValueError):

            @register_message
            @dataclasses.dataclass
            class UniqueMsg1234:  # noqa: F811
                y: int = 0

    def test_empty_payload(self):
        assert loads(b"") is None


class TestNode:
    def test_status_flow(self):
        node = Node(node_type="worker", node_id=0)
        assert node.update_status(NodeStatus.PENDING)
        assert node.update_status(NodeStatus.RUNNING)
        assert node.start_time is not None
        # Illegal transition back to pending
        assert not node.update_status(NodeStatus.PENDING)
        assert node.update_status(NodeStatus.FAILED)
        assert node.exited()

    def test_transition_table(self):
        assert is_allowed_transition(NodeStatus.RUNNING, NodeStatus.SUCCEEDED)
        assert not is_allowed_transition(NodeStatus.SUCCEEDED, NodeStatus.RUNNING)
        assert not is_allowed_transition(NodeStatus.RUNNING, NodeStatus.RUNNING)

    def test_should_relaunch_budget(self):
        node = Node(node_type="worker", node_id=0, max_relaunch_count=2)
        assert node.should_relaunch()
        node.relaunch_count = 2
        assert not node.should_relaunch()

    def test_fatal_error_not_relaunched(self):
        node = Node(node_type="worker", node_id=0)
        node.exit_reason = NodeExitReason.FATAL_ERROR
        assert not node.should_relaunch()

    def test_get_relaunch_node(self):
        node = Node(node_type="worker", node_id=0, rank_index=3)
        node.update_status(NodeStatus.RUNNING)
        new = node.get_relaunch_node(new_id=7)
        assert new.node_id == 7
        assert new.rank_index == 3
        assert new.status == NodeStatus.INITIAL
        assert new.relaunch_count == 1

    def test_resource_parse(self):
        res = NodeResource.resource_str_to_node_resource("cpu=4,memory=8192Mi,tpu=8")
        assert res.cpu == 4
        assert res.memory_mb == 8192
        assert res.device_count == 8


class TestConfig:
    def test_env_override(self, monkeypatch):
        monkeypatch.setenv("DLROVER_MAX_RELAUNCH_COUNT", "7")
        monkeypatch.setenv("DLROVER_HANG_DETECTION_ENABLED", "false")
        ctx = Context()
        ctx.apply_env()
        assert ctx.max_relaunch_count == 7
        assert ctx.hang_detection_enabled is False

    def test_singleton(self):
        assert Context.singleton_instance() is Context.singleton_instance()


class _ListExporter(Exporter):
    def __init__(self):
        self.events = []

    def export(self, event):
        self.events.append(event)


class TestEvents:
    def test_instant_and_span(self):
        exp = _ListExporter()
        em = EventEmitter("test", exporter=exp)
        em.instant("hello", a=1)
        with em.duration("work", step=3):
            pass
        assert [e.name for e in exp.events] == ["hello", "work", "work"]
        end = exp.events[-1]
        assert end.event_type == "end"
        assert "duration_s" in end.content

    def test_span_failure(self):
        exp = _ListExporter()
        em = EventEmitter("test", exporter=exp)
        with pytest.raises(RuntimeError):
            with em.duration("work"):
                raise RuntimeError("boom")
        assert exp.events[-1].content["success"] is False

    def test_async_exporter_drains(self):
        exp = _ListExporter()
        async_exp = AsyncExporter(exp)
        em = EventEmitter("test", exporter=async_exp)
        for i in range(100):
            em.instant("e", i=i)
        async_exp.close()
        assert len(exp.events) == 100

    def test_async_exporter_counts_inner_export_failures(self):
        """PR 9 exception-swallow finding: a sink that throws silently
        ate events — now they count as dropped (the exporter still
        outlives the sink)."""

        class BoomExporter(_ListExporter):
            def export(self, event):
                if len(self.events) >= 2:
                    raise RuntimeError("sink died")
                super().export(event)

        exp = BoomExporter()
        async_exp = AsyncExporter(exp)
        em = EventEmitter("test", exporter=async_exp)
        for i in range(5):
            em.instant("e", i=i)
        async_exp.close()
        assert len(exp.events) == 2
        assert async_exp._dropped == 3


class TestSerializeEscaping:
    def test_plain_dict_with_reserved_key(self):
        msg = comm.ElasticRunConfigResponse(configs={"_t": "oops", "x": "1"})
        out = loads(dumps(msg))
        assert out.configs == {"_t": "oops", "x": "1"}

    def test_memory_units(self):
        res = NodeResource.resource_str_to_node_resource("memory=8Gi")
        assert res.memory_mb == 8192
        res = NodeResource.resource_str_to_node_resource("memory=2G")
        assert res.memory_mb == 2000


class TestErrorHandler:
    """Crash-safe event flushing (reference error_handler.py:26)."""

    def test_excepthook_flushes_and_chains(self):
        import sys

        from dlrover_tpu.common.error_handler import ErrorHandler

        handler = ErrorHandler()
        flushed = []
        chained = []
        handler.register_flushable("x", lambda: flushed.append(1))
        orig = sys.excepthook
        sys.excepthook = lambda *a: chained.append(a)
        try:
            handler.register()
            try:
                raise ValueError("boom")
            except ValueError:
                sys.excepthook(*sys.exc_info())
            assert flushed == [1]
            assert chained and chained[0][0] is ValueError
        finally:
            handler.unregister()
            sys.excepthook = orig

    def test_flush_failure_does_not_block_others(self):
        from dlrover_tpu.common.error_handler import ErrorHandler

        handler = ErrorHandler()
        ran = []
        handler.register_flushable("bad", lambda: 1 / 0)
        handler.register_flushable("good", lambda: ran.append(1))
        assert "good" in handler.flush_all()
        assert ran == [1]

    def test_fatal_signal_flushes_then_dies_with_signal(self, tmp_path):
        """SIGTERM: the flushable lands on disk, then the ORIGINAL
        disposition kills the process (exit -15), in a real child."""
        import signal
        import subprocess
        import sys
        import time

        marker = tmp_path / "flushed"
        child = subprocess.Popen(
            [
                sys.executable,
                "-c",
                (
                    "import sys, time, pathlib\n"
                    "sys.path.insert(0, %r)\n"
                    "from dlrover_tpu.common.error_handler import "
                    "init_error_handler\n"
                    "h = init_error_handler()\n"
                    "h.register_flushable('m', lambda: pathlib.Path(%r)"
                    ".write_text('flushed'))\n"
                    "print('READY', flush=True)\n"
                    "time.sleep(60)\n"
                )
                % (os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   str(marker)),
            ],
            stdout=subprocess.PIPE,
            text=True,
        )
        try:
            assert child.stdout.readline().strip() == "READY"
            child.send_signal(signal.SIGTERM)
            rc = child.wait(timeout=15)
            assert rc == -signal.SIGTERM  # true disposition preserved
            deadline = time.time() + 5
            while time.time() < deadline and not marker.exists():
                time.sleep(0.1)
            assert marker.read_text() == "flushed"
        finally:
            if child.poll() is None:
                child.kill()

    def test_crash_event_written_to_event_dir(self, tmp_path):
        """An unhandled exception leaves a 'crash' event on disk."""
        import subprocess
        import sys

        env = dict(os.environ, DLROVER_EVENT_DIR=str(tmp_path))
        code = (
            "import sys\n"
            "sys.path.insert(0, %r)\n"
            "from dlrover_tpu.common.error_handler import init_error_handler\n"
            "init_error_handler()\n"
            "raise RuntimeError('the crash reason')\n"
        ) % os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        r = subprocess.run(
            [sys.executable, "-c", code],
            env=env, capture_output=True, text=True, timeout=60,
        )
        assert r.returncode != 0
        assert "the crash reason" in r.stderr  # original hook chained
        contents = "".join(
            p.read_text() for p in tmp_path.glob("events*")
        )
        assert '"crash"' in contents and "the crash reason" in contents
