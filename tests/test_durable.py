"""Durable checkpoint tier tests: layout + two-phase commit, checksum
verification, generation GC, reshard-on-read restore for every
RESHARD_RULES policy class, the engine's durable fallback rung, the
cross-job warm pool, and the durable_loss chaos drill. The full
train-state whole-pool drill (different world sizes, block-cost budget)
is slow-marked; everything else is fast synthetics."""

import os
import shutil
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec

from dlrover_tpu.chaos import faults
from dlrover_tpu.checkpoint.durable import (
    DurableLayout,
    DurableShardError,
    DurableWriter,
    collect_generations,
    commit_generation,
    list_lineages,
    read_generation,
    warm_start,
)
from dlrover_tpu.checkpoint.engine import CheckpointEngine
from dlrover_tpu.checkpoint.meta import CheckpointMeta, ShardRecord
from dlrover_tpu.checkpoint.saver import AsyncCheckpointSaver
from dlrover_tpu.checkpoint.shm_handler import SharedMemoryHandler
from dlrover_tpu.checkpoint.storage import PosixCheckpointStorage
from dlrover_tpu.parallel.mesh import MeshConfig, build_mesh
from dlrover_tpu.parallel.sharding import (
    respec_spec,
    validate_saved_spec,
)


@pytest.fixture(autouse=True)
def fresh_saver(tmp_ipc_dir, monkeypatch):
    job = f"dur_{os.getpid()}_{id(tmp_ipc_dir)}"
    monkeypatch.setenv("DLROVER_JOB_NAME", job)
    AsyncCheckpointSaver.reset()
    yield
    AsyncCheckpointSaver.reset()
    for name in os.listdir("/dev/shm"):
        if name.startswith(f"dlrover_{job}_"):
            SharedMemoryHandler(
                0, name=name.split(f"dlrover_{job}_", 1)[1]
            ).unlink()


def _fabricate_gen(layout, step, value, num_hosts=1, commit=True):
    """A committed generation without shm/jax: one replicated leaf."""
    arr = np.full((4,), value, np.float32)
    payload = arr.tobytes()
    for rank in range(num_hosts):
        rec = ShardRecord(
            path="params/w",
            global_shape=[4],
            local_shape=[4],
            dtype="float32",
            index=[],
            offset=0,
            nbytes=arr.nbytes,
            spec=[],
        )
        meta = CheckpointMeta(
            step=step,
            host_rank=rank,
            num_hosts=num_hosts,
            records=[rec],
            total_bytes=arr.nbytes,
        )
        layout.write_shard(meta, lambda off, n: payload[off : off + n])
    if not commit:
        return False
    return commit_generation(layout, step, num_hosts)


def _commit_flash_step(storage, step):
    meta = CheckpointMeta(step=step, host_rank=0, num_hosts=1)
    storage.write_shard(meta, b"")
    assert storage.commit(step, 1)


class TestTornFlashTracker:
    """Satellite: flash latest_step() must skip a tracker pointing at a
    step whose commit marker is missing (crash in the commit window)."""

    def test_torn_tracker_falls_back_to_newest_committed(self, tmp_path):
        storage = PosixCheckpointStorage(str(tmp_path))
        _commit_flash_step(storage, 3)
        _commit_flash_step(storage, 5)
        # Crash window: tracker advanced to 7 but step 7 never committed.
        storage._atomic_write(storage.tracker_path(), b"7")
        assert storage.latest_step() == 5

    def test_valid_tracker_wins(self, tmp_path):
        storage = PosixCheckpointStorage(str(tmp_path))
        _commit_flash_step(storage, 3)
        _commit_flash_step(storage, 5)
        # A tracker legitimately behind (e.g. step 5's tracker write
        # lost) still resolves to its committed target, not the max.
        storage._atomic_write(storage.tracker_path(), b"3")
        assert storage.latest_step() == 3

    def test_torn_tracker_with_nothing_committed(self, tmp_path):
        storage = PosixCheckpointStorage(str(tmp_path))
        storage._atomic_write(storage.tracker_path(), b"7")
        assert storage.latest_step() is None


class TestDurableLayout:
    def test_two_phase_visibility(self, tmp_path):
        layout = DurableLayout(str(tmp_path), "jobA")
        _fabricate_gen(layout, 5, 1.0, commit=False)
        # Phase 1 done, phase 2 not run: invisible to readers.
        assert layout.all_shards_done(5, 1)
        assert not layout.committed(5)
        assert layout.latest_committed() is None
        assert commit_generation(layout, 5, 1)
        assert layout.committed(5)
        assert layout.latest_committed() == 5
        manifest = layout.read_manifest(5)
        assert manifest.step == 5
        assert manifest.lineage == "jobA"
        assert manifest.shards["0"]["nbytes"] == 16
        assert "params" in manifest.category_specs
        assert manifest.reshard_rules["params"][0] == "respec"

    def test_torn_durable_tracker(self, tmp_path):
        layout = DurableLayout(str(tmp_path), "jobA")
        _fabricate_gen(layout, 3, 1.0)
        _fabricate_gen(layout, 5, 2.0)
        layout.atomic_write(layout.tracker_path(), b"9")
        assert layout.latest_committed() == 5

    def test_checksum_verification_rejects_corruption(self, tmp_path):
        layout = DurableLayout(str(tmp_path), "jobA")
        _fabricate_gen(layout, 5, 1.0)
        with open(layout.shard_bin_path(5, 0), "r+b") as f:
            f.seek(3)
            f.write(b"\xff")
        with pytest.raises(DurableShardError):
            read_generation(str(tmp_path), "jobA")

    def test_commit_fault_leaves_previous_generation(self, tmp_path):
        """Crash in the commit window: the new generation stays
        invisible, the tracker stays on the old one, and a re-driven
        commit converges."""
        layout = DurableLayout(str(tmp_path), "jobA")
        _fabricate_gen(layout, 3, 1.0)
        _fabricate_gen(layout, 5, 2.0, commit=False)
        faults.activate(
            faults.FaultPlan.parse(
                "seed=7;ckpt.durable_commit:error:crash-window@once"
            )
        )
        try:
            with pytest.raises(faults.FaultInjectedError):
                commit_generation(layout, 5, 1)
        finally:
            faults.deactivate()
        assert not layout.committed(5)
        assert layout.latest_committed() == 3
        # retry after the "restart"
        assert commit_generation(layout, 5, 1)
        assert layout.latest_committed() == 5

    def test_commit_barrier_timeout(self, tmp_path):
        layout = DurableLayout(str(tmp_path), "jobA")
        # 2-host generation with only one shard landed: no commit.
        arr = np.ones((4,), np.float32)
        rec = ShardRecord(
            path="params/w",
            global_shape=[4],
            local_shape=[4],
            dtype="float32",
            index=[],
            offset=0,
            nbytes=arr.nbytes,
            spec=[],
        )
        meta = CheckpointMeta(
            step=5, host_rank=0, num_hosts=2, records=[rec], total_bytes=16
        )
        payload = arr.tobytes()
        layout.write_shard(meta, lambda off, n: payload[off : off + n])
        assert not commit_generation(layout, 5, 2, timeout_s=0.3)
        assert not layout.committed(5)


class TestGenerationGC:
    def test_keep_policy_with_pins_and_leases(self, tmp_path):
        layout = DurableLayout(str(tmp_path), "jobA")
        for step in (1, 2, 3, 4, 5):
            _fabricate_gen(layout, step, float(step))
        layout.pin(1)
        token = layout.take_lease(2)
        removed = collect_generations(layout, keep=2)
        # newest two (4, 5) + pinned 1 + leased 2 survive; 3 swept
        assert removed == [3]
        assert layout.list_committed() == [1, 2, 4, 5]
        layout.release_lease(2, token)
        assert collect_generations(layout, keep=2) == [2]
        layout.unpin(1)
        assert collect_generations(layout, keep=2) == [1]
        assert layout.list_committed() == [4, 5]

    def test_gc_never_removes_tracker_target(self, tmp_path):
        layout = DurableLayout(str(tmp_path), "jobA")
        _fabricate_gen(layout, 1, 1.0)
        assert collect_generations(layout, keep=1) == []
        assert layout.latest_committed() == 1


class TestReshardOnRead:
    """Round-trip every RESHARD_RULES policy class across meshes: save
    under (world 1, fsdp=4 x tp=2), restore under dp=2 x fsdp=2 x tp=2."""

    def _save_gen(self, root, lineage, mesh, extra=None):
        w = jnp.arange(64, dtype=jnp.float32).reshape(8, 8)
        tree = {
            # respec: genuinely sharded over fsdp x tp
            "params": {
                "w": jax.device_put(
                    w, NamedSharding(mesh, PartitionSpec("fsdp", "tp"))
                )
            },
            # mirror_params: optimizer slot shaped+sharded like its param
            "opt_state": {
                "mu": {
                    "w": jax.device_put(
                        w * 0.5,
                        NamedSharding(mesh, PartitionSpec("fsdp", "tp")),
                    )
                }
            },
            # replicate: scalar step
            "step": np.int64(3),
        }
        shm = SharedMemoryHandler(0, name=f"reshard_{lineage}")
        try:
            shm.save_pytree(3, tree, num_hosts=1, mesh=mesh, extra=extra)
            writer = DurableWriter(root, lineage, 0, 1, shm)
            assert writer.drain(3)
            writer.stop()
        finally:
            shm.unlink()
        return np.asarray(w)

    def test_all_policy_classes_roundtrip(self, tmp_path):
        root = str(tmp_path / "durable")
        mesh_a = build_mesh(MeshConfig(dp=1, fsdp=4, tp=2))
        # host_local: the extra side channel rides the shard meta
        w_np = self._save_gen(root, "jobA", mesh_a, extra={"cursor": 7})
        assert list_lineages(root) == ["jobA"]

        mesh_b = build_mesh(MeshConfig(dp=2, fsdp=2, tp=2))
        step, placed, extra = warm_start(root, "jobA", mesh_b)
        assert step == 3
        # respec: byte-exact logical values, current-mesh sharding with
        # the saved axes re-applied where they still fit
        got_w = placed["params/w"]
        np.testing.assert_array_equal(np.asarray(got_w), w_np)
        assert got_w.sharding.mesh.shape == mesh_b.shape
        assert tuple(got_w.sharding.spec) == ("fsdp", "tp")
        # mirror_params: slot values survive with the param's placement
        np.testing.assert_array_equal(
            np.asarray(placed["opt_state/mu/w"]), w_np * 0.5
        )
        # replicate: scalar restored replicated
        assert int(placed["step"]) == 3
        assert placed["step"].sharding.is_fully_replicated
        # host_local: extra restored verbatim for this host
        assert extra == {"cursor": 7}

    def test_host_local_beyond_saved_world_is_empty(self, tmp_path):
        root = str(tmp_path / "durable")
        mesh_a = build_mesh(MeshConfig(dp=1, fsdp=4, tp=2))
        self._save_gen(root, "jobB", mesh_a, extra={"cursor": 7})
        # a host rank the saved world never had gets no host_local state
        _, _, _, extra = read_generation(root, "jobB", host_rank=5)
        assert extra == {}

    def test_respec_drops_axes_that_stop_dividing(self):
        mesh = build_mesh(MeshConfig(dp=8))
        # dim 4 can't shard over dp=8 → replicated; dim 8 keeps dp
        assert respec_spec(["dp"], mesh, (4,)) == PartitionSpec(None)
        assert respec_spec(["dp"], mesh, (8,)) == PartitionSpec("dp")
        # axes absent from the target mesh are dropped
        dp_only = jax.sharding.Mesh(np.array(jax.devices()[:8]), ("dp",))
        assert respec_spec([["fsdp", "tp"]], dp_only, (8,)) == PartitionSpec(
            None
        )

    def test_saved_spec_outside_rule_coverage_rejected(self):
        with pytest.raises(ValueError):
            validate_saved_spec("step", ["dp"])
        validate_saved_spec("params", ["fsdp", "tp"])  # covered: no raise


class TestEngineDurableRung:
    def test_whole_pool_loss_falls_back_to_durable(self, tmp_path):
        """Engine-driven end to end at world 1: save_to_storage commits
        flash, the saver's writer drains to durable off-thread; after
        flash + shm are wiped a fresh engine restores from durable."""
        ckpt_dir = str(tmp_path / "ckpt")
        durable_dir = str(tmp_path / "durable")
        tree = {
            "params": {"w": jnp.arange(16, dtype=jnp.float32)},
            "step": jnp.int32(7),
        }
        engine = CheckpointEngine(
            ckpt_dir,
            standalone=True,
            durable_dir=durable_dir,
            durable_lineage="jobA",
        )
        try:
            assert engine.save_to_storage(7, tree)
            assert engine.wait_saving(timeout=60)
            layout = DurableLayout(durable_dir, "jobA")
            deadline = time.monotonic() + 60
            while layout.latest_committed() != 7:
                assert time.monotonic() < deadline, "durable drain timed out"
                time.sleep(0.05)
            # the drain ran on the writer's own thread, not the persist
            # loop (the non-blocking hand-off contract)
            writer = AsyncCheckpointSaver._instance._durable_writer
            assert writer is not None
            assert writer.drained_steps >= 1
            assert writer._thread is not None
            assert writer._thread.name == "durable-writer-0"
            engine.shm.invalidate()
        finally:
            engine.shm.unlink()
            engine.close()
        shutil.rmtree(ckpt_dir)  # flash tier gone too: whole-pool loss

        engine2 = CheckpointEngine(
            ckpt_dir,
            standalone=True,
            prefetch_restore=False,
            durable_dir=durable_dir,
            durable_lineage="jobA",
        )
        try:
            template = jax.tree.map(jnp.zeros_like, tree)
            step, restored = engine2.load_consistent(template)
            assert step == 7
            np.testing.assert_array_equal(
                np.asarray(restored["params"]["w"]),
                np.arange(16, dtype=np.float32),
            )
            assert int(restored["step"]) == 7
        finally:
            engine2.shm.unlink()
            engine2.close()

    def test_durable_off_changes_nothing(self, tmp_path):
        engine = CheckpointEngine(str(tmp_path / "ckpt"), standalone=True)
        try:
            assert engine.durable_dir == ""
            assert engine._load_from_durable({"w": jnp.zeros(4)}) is None
            assert engine._durable_latest() == -1
        finally:
            engine.shm.unlink()
            engine.close()


class TestDurableLossScenario:
    def test_durable_loss_scenario(self, tmp_path):
        from dlrover_tpu.chaos.scenarios import run_scenario

        result = run_scenario("durable_loss", str(tmp_path))
        assert result["recovered"], result
        assert result["fired"] >= 2
        assert result["saved_world"] == 2
        assert result["restored_world"] == 1


@pytest.mark.slow
class TestWholePoolDrill:
    def test_durable_whole_pool_drill(self, tmp_path):
        """Full acceptance drill: a real train state saved under one
        mesh, whole-pool loss, restart at a DIFFERENT world layout
        restoring logically exact state from durable — with the train
        loop's blocking cost per durable save within 2x the flash
        tier's stage block."""
        from dlrover_tpu.models.gpt import GPT, GPTConfig
        from dlrover_tpu.parallel.train_step import (
            default_optimizer,
            init_train_state,
        )

        cfg = GPTConfig.tiny()
        model = GPT(cfg)
        tx = default_optimizer()
        tokens = jnp.zeros((8, 32), jnp.int32)
        mesh_a = build_mesh(MeshConfig(dp=1, fsdp=4, tp=2))
        state_a, _ = init_train_state(
            model, tokens, mesh_a, tx, rng=jax.random.PRNGKey(1)
        )
        ckpt_dir = str(tmp_path / "ckpt")
        durable_dir = str(tmp_path / "durable")

        def timed_async_saves(engine, first_step):
            # warm the async staging path (snapshot compile), then take
            # the best of 3 — the same min-of discipline bench uses.
            engine.save_to_memory(first_step, state_a, block=False)
            assert engine.wait_staged(60)
            blocks = []
            for i in range(3):
                t0 = time.perf_counter()
                engine.save_to_memory(first_step + 1 + i, state_a, block=False)
                blocks.append(time.perf_counter() - t0)
                assert engine.wait_staged(60)
            return min(blocks)

        flash_engine = CheckpointEngine(
            ckpt_dir, mesh=mesh_a, standalone=True, durable_dir=""
        )
        try:
            flash_block = timed_async_saves(flash_engine, 1)
        finally:
            flash_engine.shm.unlink()
            flash_engine.close()
        AsyncCheckpointSaver.reset()

        engine_a = CheckpointEngine(
            ckpt_dir,
            mesh=mesh_a,
            standalone=True,
            durable_dir=durable_dir,
            durable_lineage="drill",
        )
        try:
            durable_block = timed_async_saves(engine_a, 11)
            assert engine_a.save_to_storage(20, state_a)
            assert engine_a.wait_saving(timeout=120)
            layout = DurableLayout(durable_dir, "drill")
            deadline = time.monotonic() + 120
            while layout.latest_committed() != 20:
                assert time.monotonic() < deadline, "durable drain timed out"
                time.sleep(0.1)
            engine_a.shm.invalidate()
        finally:
            engine_a.shm.unlink()
            engine_a.close()
        # Non-blocking discipline: the durable tier must not grow the
        # train loop's hand-off beyond 2x the flash stage block (+25 ms
        # absolute floor for CPU-container timer noise).
        assert durable_block <= 2.0 * flash_block + 0.025, (
            durable_block,
            flash_block,
        )

        shutil.rmtree(ckpt_dir)  # whole-pool loss
        mesh_b = build_mesh(MeshConfig(dp=2, fsdp=2, tp=2))
        state_b, _ = init_train_state(
            model, tokens, mesh_b, tx, rng=jax.random.PRNGKey(2)
        )
        engine_b = CheckpointEngine(
            ckpt_dir,
            mesh=mesh_b,
            standalone=True,
            prefetch_restore=False,
            durable_dir=durable_dir,
            durable_lineage="drill",
        )
        try:
            step, restored = engine_b.load_consistent(state_b)
            assert step == 20
            for a, b in zip(
                jax.tree.leaves(state_a.params),
                jax.tree.leaves(restored.params),
            ):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b))
            wqkv = restored.params["block_0"]["CausalSelfAttention_0"]["wqkv"]
            assert wqkv.sharding.mesh.shape == mesh_b.shape
        finally:
            engine_b.shm.unlink()
            engine_b.close()
