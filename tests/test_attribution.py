"""Performance attribution subsystem (dlrover_tpu/attribution/).

Pins the three pillars without a device: op-bucket classification +
per-step accounting on synthetic ring events, the serving host/device
phase-split math on synthetic timestamps, and Report serialization
(the bench-line contract: pointers + ≤5 floats, payload in the
artifact). The CLI is driven against a hand-written TPUTL001 ring.
"""

import json
import struct
from dataclasses import dataclass

import pytest

from dlrover_tpu.attribution import (
    BUCKETS,
    PhaseAccumulator,
    Report,
    account_events,
    build_report,
    classify_op,
)
from dlrover_tpu.attribution import ops as attr_ops
from dlrover_tpu.attribution.phases import (
    DEVICE_PHASES,
    HOST_PHASES,
    OVERLAP_PHASES,
    PHASES,
)


@dataclass
class _Ev:  # TimelineEvent-shaped
    name_id: int
    kind: int
    start_us: int
    dur_us: int
    step: int


class TestClassification:
    def test_native_kind_wins_over_name(self):
        # a collective whose fused name mentions "add" stays collective
        assert classify_op("add.fusion", attr_ops.KIND_COLLECTIVE) == (
            "collective"
        )
        assert classify_op("whatever", attr_ops.KIND_MATMUL) == "matmul"
        assert classify_op("x", attr_ops.KIND_H2D) == "transfer"
        assert classify_op("x", attr_ops.KIND_D2H) == "transfer"

    @pytest.mark.parametrize(
        "name,bucket",
        [
            ("fusion.123.dot_general.1", "matmul"),
            ("jit_matmul", "matmul"),
            ("custom-call.flash_attention_fwd", "attention"),
            ("fusion.softmax.add", "attention"),
            ("layer_norm.fusion", "vpu"),
            ("rms_norm_bwd", "vpu"),
            ("fusion.add.multiply.reduce", "vpu"),
            ("adamw_update.fusion", "optimizer_hbm"),
            ("convert_element_type.42", "optimizer_hbm"),
            ("all-reduce.7", "collective"),
            ("reduce-scatter.1", "collective"),
            ("jit__psum", "collective"),
            ("opaque_program_xyz", "other"),
        ],
    )
    def test_fingerprints(self, name, bucket):
        assert classify_op(name, attr_ops.KIND_EXECUTE) == bucket

    def test_ordering_collective_beats_vpu_tokens(self):
        # fused all-reduce-of-gradients contains "add": must stay
        # collective (the table is ordered most-specific-first)
        assert (
            classify_op("all_reduce.add.fusion", attr_ops.KIND_EXECUTE)
            == "collective"
        )


class TestAccounting:
    def test_per_step_table_with_step_markers_and_gap(self):
        names = {1: "dot_general.0", 2: "layer_norm.0", 3: "all-reduce.0"}
        events = [
            # step 0: span 1000us via step marker; ops cover 700us
            _Ev(0, attr_ops.KIND_STEP, 0, 1000, 0),
            _Ev(1, attr_ops.KIND_EXECUTE, 0, 400, 0),
            _Ev(2, attr_ops.KIND_EXECUTE, 400, 200, 0),
            _Ev(3, attr_ops.KIND_COLLECTIVE, 600, 100, 0),
            # step 1: no marker → envelope span 500us, ops 500us, gap 0
            _Ev(1, attr_ops.KIND_EXECUTE, 2000, 500, 1),
        ]
        table = account_events(events, names)
        assert len(table.steps) == 2
        s0 = table.steps[0]
        assert s0.span_us == 1000 and s0.busy_us == 700
        assert s0.buckets["matmul"] == 400
        assert s0.buckets["vpu"] == 200
        assert s0.buckets["collective"] == 100
        assert s0.buckets["gap_dispatch"] == 300
        s1 = table.steps[1]
        assert s1.span_us == 500 and "gap_dispatch" not in s1.buckets
        # aggregate fractions are over the summed spans (1500us)
        assert table.total_span_us == 1500
        assert table.buckets["matmul"].time_us == 900
        assert table.buckets["matmul"].frac == pytest.approx(0.6)
        assert table.buckets["gap_dispatch"].frac == pytest.approx(0.2)

    def test_top_residual_excludes_matmul_and_recommends(self):
        names = {1: "dot_general", 2: "adam_update"}
        events = [
            _Ev(0, attr_ops.KIND_STEP, 0, 1000, 0),
            _Ev(1, attr_ops.KIND_EXECUTE, 0, 600, 0),
            _Ev(2, attr_ops.KIND_EXECUTE, 600, 400, 0),
        ]
        res = account_events(events, names).top_residual()
        # matmul is the biggest bucket but never the residual
        assert res["bucket"] == "optimizer_hbm"
        assert res["frac"] == pytest.approx(0.4)
        assert "optimizer" in res["recommendation"] or "donate" in (
            res["recommendation"]
        )

    def test_empty_ring(self):
        table = account_events([], {})
        assert table.total_span_us == 0 and table.events == 0
        assert table.top_residual()["bucket"] is None

    def test_marker_only_step_is_pure_gap(self):
        """A step marker whose device ops were lost (ring overflow) or
        that genuinely stalled in dispatch must still be accounted —
        its whole span is gap_dispatch, not silently dropped."""
        events = [
            _Ev(0, attr_ops.KIND_STEP, 0, 50000, 7),
            # a normal step alongside proves fractions stay honest
            _Ev(0, attr_ops.KIND_STEP, 60000, 1000, 8),
            _Ev(1, attr_ops.KIND_EXECUTE, 60000, 1000, 8),
        ]
        table = account_events(events, {1: "dot_general"})
        assert [r.step for r in table.steps] == [7, 8]
        assert table.steps[0].buckets == {"gap_dispatch": 50000}
        assert table.total_span_us == 51000
        assert table.buckets["gap_dispatch"].frac == pytest.approx(
            50000 / 51000
        )
        assert table.top_residual()["bucket"] == "gap_dispatch"

    def test_busy_exceeding_span_clamps_gap(self):
        # concurrent streams: summed op time > marker span — gap must
        # clamp at zero, not go negative
        events = [
            _Ev(0, attr_ops.KIND_STEP, 0, 100, 0),
            _Ev(1, attr_ops.KIND_EXECUTE, 0, 90, 0),
            _Ev(1, attr_ops.KIND_EXECUTE, 10, 90, 0),
        ]
        table = account_events(events, {1: "dot_general"})
        assert "gap_dispatch" not in table.steps[0].buckets
        assert table.steps[0].span_us == 180  # busy floor

    def test_to_dict_bounded(self):
        events = [
            _Ev(i, attr_ops.KIND_EXECUTE, i * 10, 5, i) for i in range(100)
        ]
        d = account_events(events, {}).to_dict(max_steps=8, max_top_ops=3)
        assert len(d["steps"]) == 8 and len(d["top_ops"]) <= 3
        assert set(d["buckets"]) <= set(BUCKETS)


class TestPhaseSplit:
    def test_split_math_on_synthetic_timestamps(self):
        acc = PhaseAccumulator()
        # 3 rounds of known spans: host = admission+dispatch+retire
        for _ in range(3):
            acc.add_round(
                [
                    ("admission", 0.010),
                    ("prefill", 0.020),
                    ("decode_dispatch", 0.005),
                    ("host_sync", 0.060),
                    ("retirement", 0.005),
                ]
            )
        split = acc.split()
        assert split.rounds == 3
        assert split.host_s == pytest.approx(0.060)
        assert split.device_s == pytest.approx(0.240)
        assert split.serving_host_frac == pytest.approx(0.2)
        assert split.phases["host_sync"]["count"] == 3
        assert split.phases["host_sync"]["mean_ms"] == pytest.approx(60.0)
        assert split.phases["admission"]["host"] is True
        assert split.phases["prefill"]["host"] is False
        # 10ms = 10000us → log2 bucket 13
        assert split.phases["admission"]["hist_log2us"][13] == 3

    def test_phase_name_partition(self):
        from dlrover_tpu.attribution.phases import (
            GATEWAY_PHASES,
            POOL_PHASES,
        )

        # engine + gateway + pool phase names jointly partition into
        # host / device / overlap — split() classifies by these sets
        assert set(PHASES) | set(GATEWAY_PHASES) | set(POOL_PHASES) == (
            HOST_PHASES | DEVICE_PHASES | OVERLAP_PHASES
        )
        assert not (set(PHASES) & set(GATEWAY_PHASES))
        assert not (set(PHASES) & set(POOL_PHASES))
        assert not (set(GATEWAY_PHASES) & set(POOL_PHASES))
        assert not (HOST_PHASES & DEVICE_PHASES)
        assert not (OVERLAP_PHASES & (HOST_PHASES | DEVICE_PHASES))

    def test_overlap_hidden_counts_toward_total_not_host(self):
        """The pipelined scheduler's hidden host work: in total_s (it
        is real wall time inside rounds), in neither host_s nor
        device_s — serving_host_frac must DROP when the same host work
        moves from retirement to overlap_hidden."""
        serial = PhaseAccumulator()
        serial.add_round(
            [("decode_dispatch", 0.01), ("host_sync", 0.05),
             ("retirement", 0.04)]
        )
        piped = PhaseAccumulator()
        piped.add_round(
            [("decode_dispatch", 0.01), ("host_sync", 0.05),
             ("overlap_hidden", 0.04)]
        )
        s, p = serial.split(), piped.split()
        assert s.serving_host_frac == pytest.approx(0.5)
        assert p.overlap_s == pytest.approx(0.04)
        assert p.host_s == pytest.approx(0.01)
        assert p.total_s == pytest.approx(s.total_s)
        assert p.serving_host_frac == pytest.approx(0.1)
        assert p.summary()["overlap_hidden_s"] == pytest.approx(0.04)
        # a split with no overlap keeps the compact summary unchanged
        assert "overlap_hidden_s" not in s.summary()

    def test_empty_and_reset(self):
        acc = PhaseAccumulator()
        assert acc.split().serving_host_frac == 0.0
        acc.add("admission", 1.0)
        acc.rounds += 1
        acc.reset()
        split = acc.split()
        assert split.total_s == 0.0 and split.rounds == 0

    def test_negative_duration_clamps(self):
        acc = PhaseAccumulator()
        acc.add("admission", -0.5)  # clock skew must not go negative
        assert acc.split().host_s == 0.0

    def test_summary_is_compact_floats(self):
        acc = PhaseAccumulator()
        acc.add_round([(p, 0.001) for p in PHASES])
        s = acc.split().summary()
        # 3 host / 6 total (2 device + 1 overlap-hidden)
        assert s["serving_host_frac"] == pytest.approx(0.5)
        assert s["rounds"] == 1
        for p in PHASES:
            assert isinstance(s[f"{p}_ms"], float)
        # bounded: the 1,800-byte bench line must fit this whole
        assert len(json.dumps(s)) < 350


class TestReport:
    def _report(self):
        acc = PhaseAccumulator()
        acc.add_round(
            [("admission", 0.01), ("host_sync", 0.03),
             ("decode_dispatch", 0.01)]
        )
        events = [
            _Ev(0, attr_ops.KIND_STEP, 0, 100, 0),
            _Ev(1, attr_ops.KIND_EXECUTE, 0, 60, 0),
            _Ev(2, attr_ops.KIND_EXECUTE, 60, 30, 0),
        ]
        table = account_events(
            events, {1: "dot_general", 2: "layer_norm"}
        )
        return build_report(
            op_table=table, serving=acc.split(), meta={"device": "test"}
        )

    def test_round_trip(self, tmp_path):
        rep = self._report()
        path = str(tmp_path / "report.json")
        rep.save(path)
        back = Report.load(path)
        assert back.meta["device"] == "test"
        assert back.op_table["buckets"]["matmul"]["time_us"] == 60
        assert back.serving["serving_host_frac"] == pytest.approx(0.4)
        # the file is plain JSON with the schema tag
        raw = json.load(open(path))
        assert raw["schema"].startswith("dlrover_tpu.attribution")

    def test_rejects_foreign_json(self):
        with pytest.raises(ValueError, match="not an attribution"):
            Report.from_json(json.dumps({"schema": "nope"}))

    def test_headline_is_at_most_five_floats(self):
        head = self._report().headline()
        assert 0 < len(head) <= 5
        assert head["serving_host_frac"] == pytest.approx(0.4)
        assert head["matmul_frac"] == pytest.approx(0.6)
        for v in head.values():
            assert isinstance(v, (int, float))
        assert len(json.dumps(head)) < 200

    def test_top_residual_falls_back_to_serving(self):
        acc = PhaseAccumulator()
        acc.add_round([("admission", 0.03), ("host_sync", 0.01)])
        rep = build_report(serving=acc.split())
        res = rep.top_residual()
        assert res["bucket"] == "serving_host"
        assert res["frac"] == pytest.approx(0.75)

    def test_format_renders_both_pillars(self):
        text = self._report().format()
        assert "top residual" in text
        assert "serving_host_frac" in text


def _write_ring(path, events, names):
    """Hand-write a TPUTL001 ring + names sidecar (the native dump
    format timeline.py reads)."""
    rec = struct.Struct("<IIqII")
    with open(path, "wb") as f:
        f.write(b"TPUTL001")
        for ev in events:
            f.write(
                rec.pack(ev.name_id, ev.kind, ev.start_us, ev.dur_us,
                         ev.step)
            )
    with open(str(path) + ".names", "w") as f:
        for ident, name in names.items():
            f.write(f"{ident}\t{name}\n")


class TestCli:
    def _ring(self, tmp_path):
        ring = tmp_path / "run.timeline"
        _write_ring(
            ring,
            [
                _Ev(0, attr_ops.KIND_STEP, 0, 1000, 0),
                _Ev(1, attr_ops.KIND_EXECUTE, 0, 700, 0),
                _Ev(2, attr_ops.KIND_EXECUTE, 700, 200, 0),
            ],
            {1: "dot_general.3", 2: "adam_update"},
        )
        return str(ring)

    def test_json_table_from_saved_ring(self, tmp_path, capsys):
        from dlrover_tpu.attribution.cli import main

        assert main([self._ring(tmp_path), "--json"]) == 0
        out = json.loads(capsys.readouterr().out)
        assert out["buckets"]["matmul"]["time_us"] == 700
        assert out["buckets"]["optimizer_hbm"]["time_us"] == 200
        assert out["top_residual"]["bucket"] == "optimizer_hbm"

    def test_human_table_and_report_artifact(self, tmp_path, capsys):
        from dlrover_tpu.attribution.cli import main

        out_path = str(tmp_path / "rep.json")
        assert main([self._ring(tmp_path), "--out", out_path]) == 0
        text = capsys.readouterr().out
        assert "matmul" in text and "top residual" in text
        rep = Report.load(out_path)
        assert rep.op_table["buckets"]["matmul"]["count"] == 1

    def test_missing_ring_fails_cleanly(self, tmp_path, capsys):
        from dlrover_tpu.attribution.cli import main

        assert main([str(tmp_path / "absent.timeline")]) == 2
        assert "tpurun-attr" in capsys.readouterr().err


class TestEngineIntegration:
    """The serving engine stamps real phases: one tiny CPU stream must
    populate the split and expose it through stats() — the classic
    five phases in the synchronous round, plus ``overlap_hidden`` in
    the pipelined round."""

    def _engine(self, overlap):
        import jax
        import jax.numpy as jnp

        from dlrover_tpu.models.generation import SamplingConfig
        from dlrover_tpu.models.gpt import GPT, GPTConfig
        from dlrover_tpu.models.serving import ContinuousBatchingEngine

        model = GPT(
            GPTConfig(
                vocab_size=64, max_seq_len=128, num_layers=1,
                num_heads=2, head_dim=8, embed_dim=16, use_remat=False,
            )
        )
        params = model.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
        )["params"]
        return ContinuousBatchingEngine(
            model, params,
            SamplingConfig(max_new_tokens=4, temperature=0.0),
            batch_size=2, prompt_width=8, decode_chunk=2,
            cache_layout="per_row", overlap=overlap,
        )

    def test_sync_engine_stamps_classic_phases(self):
        eng = self._engine(overlap=False)
        eng.run([[5, 9, 2], [7, 1]])
        split = eng.phases.split()
        assert split.rounds > 0
        for phase in set(PHASES) - OVERLAP_PHASES:
            assert phase in split.phases, phase
        assert "overlap_hidden" not in split.phases
        assert split.overlap_s == 0.0
        assert 0.0 < split.serving_host_frac < 1.0
        stats = eng.stats()
        assert stats["phase_split"]["rounds"] == split.rounds
        assert "serving_host_frac" in stats["phase_split"]

    def test_overlapped_engine_hides_host_time(self):
        """The pipelined round must report nonzero overlap_hidden —
        host work that ran under an in-flight chunk — and the split
        accounting must balance."""
        eng = self._engine(overlap=True)
        # enough requests that the pipeline is warm across rounds
        eng.run([[5, 9, 2], [7, 1], [3, 3, 8], [9], [2, 4], [6, 1, 1]])
        split = eng.phases.split()
        assert split.rounds > 0
        assert "overlap_hidden" in split.phases
        assert split.overlap_s > 0.0
        assert split.total_s == pytest.approx(
            split.host_s + split.device_s + split.overlap_s
        )
        assert 0.0 <= split.serving_host_frac < 1.0
        assert eng.stats()["phase_split"]["overlap_hidden_s"] > 0.0
