"""Elastic chaos e2e: DistributedJobMaster + ProcessScaler node processes.

The TPU build's equivalent of the reference's chaosblade experiments
(docs/tech_report/fault_tolerance_exps.md): a 2-"host" job where each
host is a real agent process supervising a real worker process; SIGKILL
one host mid-run and assert the master replaces it, the survivor
re-rendezvouses, and the job runs to completion.
"""

import os
import signal
import subprocess
import sys
import time

import pytest

from dlrover_tpu.common.constants import JobExitReason, NodeEnv


def _worker_script(tmp_path):
    script = tmp_path / "train.py"
    script.write_text(
        "import os, time, pathlib\n"
        "md = pathlib.Path(os.environ['MARKER_DIR'])\n"
        "rank = os.environ['DLROVER_NODE_RANK']\n"
        "runs = len(list(md.glob(f'run_{rank}_*')))\n"
        "(md / f'run_{rank}_{os.getpid()}').write_text(\n"
        "    os.environ['DLROVER_NUM_PROCESSES'])\n"
        "time.sleep(25 if runs == 0 else 6)\n"
        "print('worker', rank, 'done after', runs + 1, 'runs')\n"
    )
    return script


@pytest.mark.slow
def test_kill_node_master_relaunches(tmp_path):
    markers = tmp_path / "markers"
    markers.mkdir()
    script = _worker_script(tmp_path)
    from e2e_utils import make_process_master

    master, scaler, watcher = make_process_master(
        "chaos_e2e",
        command=[
            sys.executable,
            "-m",
            "dlrover_tpu.launcher.elastic_run",
            # CPU host simulation: also keeps profile-auto (TPU-only) off
            "--accelerator",
            "cpu",
            "--nnodes",
            "2",
            "--max_restarts",
            "3",
            str(script),
        ],
        env={
            "MARKER_DIR": str(markers),
            "DLROVER_LOCAL_DEVICES": "1",
            "PYTHONPATH": os.pathsep.join(sys.path),
        },
        num_workers=2,
    )
    try:
        master.prepare()
        master.run_in_background()
        # wait until both first-incarnation workers are running
        deadline = time.time() + 90
        while time.time() < deadline:
            if len(list(markers.glob("run_*"))) >= 2:
                break
            time.sleep(0.5)
        assert len(list(markers.glob("run_*"))) >= 2, "workers never started"

        # chaos: SIGKILL node 0's agent process (kills its process group)
        handle = scaler._procs[0]
        os.killpg(handle.proc.pid, signal.SIGKILL)

        # master must replace it: a second run marker for rank 0 appears
        deadline = time.time() + 120
        while time.time() < deadline:
            if len(list(markers.glob("run_0_*"))) >= 2:
                break
            time.sleep(0.5)
        assert len(list(markers.glob("run_0_*"))) >= 2, "node 0 not relaunched"

        # and the whole job completes successfully
        deadline = time.time() + 120
        while time.time() < deadline and not master._stopped.is_set():
            time.sleep(0.5)
        assert master.exit_reason == JobExitReason.SUCCEEDED
        # the re-rendezvoused world was full-size again
        final_runs = sorted(markers.glob("run_0_*"))
        assert final_runs[-1].read_text() == "2"
    finally:
        master.stop()
        scaler.stop()


@pytest.mark.slow
def test_scale_down_releases_host_and_training_continues(tmp_path):
    """VERDICT r2 #6 e2e: a saturated job releases a host through the
    drain path (auto-scaler -> job_manager.scale_down -> ProcessScaler)
    and the survivors re-rendezvous into a SMALLER world — no relaunch
    of the released node, job still succeeds."""
    markers = tmp_path / "markers"
    markers.mkdir()
    script = _worker_script(tmp_path)
    from e2e_utils import make_process_master

    master, scaler, watcher = make_process_master(
        "shrink_e2e",
        command=[
            sys.executable,
            "-m",
            "dlrover_tpu.launcher.elastic_run",
            # CPU host simulation: also keeps profile-auto (TPU-only) off
            "--accelerator",
            "cpu",
            "--nnodes",
            "3",
            "--max_restarts",
            "3",
            str(script),
        ],
        env={
            "MARKER_DIR": str(markers),
            "DLROVER_LOCAL_DEVICES": "1",
            "PYTHONPATH": os.pathsep.join(sys.path),
        },
        num_workers=3,
    )
    try:
        master.prepare()
        master.run_in_background()
        deadline = time.time() + 90
        while time.time() < deadline:
            if len(list(markers.glob("run_*"))) >= 3:
                break
            time.sleep(0.5)
        assert len(list(markers.glob("run_*"))) >= 3, "workers never started"

        # the optimizer decided 3 hosts don't pay: execute a shrink to 2
        from dlrover_tpu.master.resource.optimizer import ResourcePlan

        released_pid = scaler._procs[2].proc.pid
        master.auto_scaler.execute_job_optimization_plan(
            ResourcePlan(worker_num=2)
        )

        # released host's process group goes away and STAYS away
        deadline = time.time() + 60
        while time.time() < deadline and scaler._procs.get(2) is not None:
            if not scaler._procs[2].alive():
                break
            time.sleep(0.5)
        handle2 = scaler._procs.get(2)
        assert handle2 is None or not handle2.alive(), "node 2 not removed"

        # survivors re-rendezvous at world size 2 (second-run markers)
        deadline = time.time() + 120
        while time.time() < deadline:
            reruns = [
                p
                for rank in (0, 1)
                for p in markers.glob(f"run_{rank}_*")
            ]
            worlds = {p.read_text() for p in reruns}
            if "2" in worlds:
                break
            time.sleep(0.5)
        assert "2" in worlds, f"no re-mesh at world 2; saw {worlds}"

        # completes successfully, and node 2 was never resurrected
        deadline = time.time() + 120
        while time.time() < deadline and not master._stopped.is_set():
            time.sleep(0.5)
        assert master.exit_reason == JobExitReason.SUCCEEDED
        assert len(list(markers.glob("run_2_*"))) == 1, "node 2 relaunched"
        # its pid is gone
        try:
            os.kill(released_pid, 0)
            alive = True
        except OSError:
            alive = False
        assert not alive
    finally:
        master.stop()
        scaler.stop()
