"""ElasticJob operator reconcile tests with a fake client (reference:
controller-runtime envtest suites, go/elasticjob/pkg/controllers/
elasticjob_controller_test.go — here the reconciler is Python, so a
fake client covers the same create-master-pod-from-CR behavior)."""

import pytest

from dlrover_tpu.operator.controller import (
    ElasticJobController,
    JobPhase,
    build_master_pod,
    build_master_service,
    master_pod_name,
)
from dlrover_tpu.scheduler.kubernetes import (
    CRD_GROUP,
    ELASTIC_JOB_LABEL,
    ELASTICJOB_PLURAL,
    pod_name,
)


def _cr(name="gpt", replicas=4, **spec_overrides):
    spec = {
        "distributionStrategy": "spmd",
        "nodeUnit": 2,
        "masterImage": "dlrover-tpu:latest",
        "workerImage": "dlrover-tpu:latest",
        "workerCommand": ["python", "-m", "train"],
        "replicaSpecs": {
            "worker": {"replicas": replicas, "maxReplicas": 8, "tpuChips": 4}
        },
    }
    spec.update(spec_overrides)
    return {
        "metadata": {"name": name, "uid": "uid-1"},
        "spec": spec,
    }


class FakeClient:
    def __init__(self):
        self.pods = {}
        self.services = {}
        self.custom = {ELASTICJOB_PLURAL: {}}
        self.statuses = {}

    def create_service(self, svc):
        self.services[svc["metadata"]["name"]] = svc
        return True

    def get_service(self, name):
        return self.services.get(name)

    def delete_service(self, name):
        self.services.pop(name, None)
        return True

    def create_pod(self, pod):
        self.pods[pod_name(pod)] = pod
        return True

    def delete_pod(self, name):
        self.pods.pop(name, None)
        return True

    def get_pod(self, name):
        return self.pods.get(name)

    def list_pods(self, label_selector):
        key, _, val = label_selector.partition("=")
        return [
            p
            for p in self.pods.values()
            if p["metadata"]["labels"].get(key) == val
        ]

    def list_custom_objects(self, group, version, plural, label_selector=""):
        return list(self.custom.get(plural, {}).values())

    def get_custom_object(self, group, version, plural, name):
        obj = self.custom.get(plural, {}).get(name)
        if obj is not None and name in self.statuses:
            obj = dict(obj, status=self.statuses[name])
        return obj

    def update_custom_object_status(self, group, version, plural, name, status):
        self.statuses[name] = status
        return True

    def watch_custom_objects(self, *a, **k):
        return iter(())


@pytest.fixture()
def controller(monkeypatch):
    client = FakeClient()
    import dlrover_tpu.operator.controller as mod

    monkeypatch.setattr(
        mod.k8sClient, "singleton", staticmethod(lambda ns="default": client)
    )
    ctl = ElasticJobController(namespace="ns1")
    return ctl, client


class TestMasterPodManifest:
    def test_shape(self):
        pod = build_master_pod(_cr(), "ns1")
        assert pod["metadata"]["name"] == "gpt-master"
        assert pod["metadata"]["labels"][ELASTIC_JOB_LABEL] == "gpt"
        owner = pod["metadata"]["ownerReferences"][0]
        assert owner["kind"] == "ElasticJob"
        assert owner["name"] == "gpt"
        assert CRD_GROUP in owner["apiVersion"]
        container = pod["spec"]["containers"][0]
        assert "--num_workers" in container["command"]
        idx = container["command"].index("--num_workers")
        assert container["command"][idx + 1] == "4"
        idx = container["command"].index("--max_workers")
        assert container["command"][idx + 1] == "8"
        env = {e["name"]: e["value"] for e in container["env"]}
        assert env["DLROVER_WORKER_IMAGE"] == "dlrover-tpu:latest"
        assert env["DLROVER_WORKER_COMMAND"] == "python -m train"


class TestReconcile:
    def test_creates_master_pod_from_cr(self, controller):
        ctl, client = controller
        cr = _cr()
        client.custom[ELASTICJOB_PLURAL]["gpt"] = cr
        ctl.reconcile_all()
        assert master_pod_name("gpt") in client.pods
        # workers resolve the master through a Service, not a bare pod
        assert master_pod_name("gpt") in client.services
        assert client.statuses["gpt"]["phase"] == JobPhase.PENDING
        assert client.pods["gpt-master"]["spec"]["restartPolicy"] == "Never"

    def test_idempotent_and_status_follows_pod(self, controller):
        ctl, client = controller
        cr = _cr()
        client.custom[ELASTICJOB_PLURAL]["gpt"] = cr
        ctl.reconcile(cr)
        ctl.reconcile(cr)
        assert len(client.pods) == 1
        client.pods["gpt-master"]["status"] = {"phase": "Running"}
        ctl.reconcile(dict(cr, status=client.statuses.get("gpt", {})))
        assert client.statuses["gpt"]["phase"] == JobPhase.RUNNING
        client.pods["gpt-master"]["status"] = {"phase": "Succeeded"}
        ctl.reconcile(dict(cr, status=client.statuses.get("gpt", {})))
        assert client.statuses["gpt"]["phase"] == JobPhase.SUCCEEDED

    def test_suspend_keeps_master_and_reports(self, controller):
        ctl, client = controller
        cr = _cr(suspend=True)
        client.custom[ELASTICJOB_PLURAL]["gpt"] = cr
        ctl.reconcile(cr)
        client.pods["gpt-master"]["status"] = {"phase": "Running"}
        ctl.reconcile(cr)
        # the master stays (it orchestrates worker teardown + resume)
        assert "gpt-master" in client.pods
        assert client.statuses["gpt"]["phase"] == JobPhase.SUSPENDED

    def test_deletion_removes_master_and_workers(self, controller):
        ctl, client = controller
        cr = _cr()
        client.custom[ELASTICJOB_PLURAL]["gpt"] = cr
        ctl.reconcile(cr)
        # master created a worker pod meanwhile
        client.pods["gpt-worker-0"] = {
            "metadata": {
                "name": "gpt-worker-0",
                "labels": {ELASTIC_JOB_LABEL: "gpt"},
            }
        }
        deleted = dict(cr, metadata=dict(cr["metadata"], deletionTimestamp="t"))
        ctl.reconcile(deleted)
        assert "gpt-master" not in client.pods
        assert "gpt-worker-0" not in client.pods
        assert "gpt-master" not in client.services

    def test_failed_master_retried_then_reported(self, controller):
        ctl, client = controller
        cr = _cr(masterRestartCount=1)
        client.custom[ELASTICJOB_PLURAL]["gpt"] = cr
        ctl.reconcile(cr)
        # transient crash: pod deleted + budget consumed, job stays live
        client.pods["gpt-master"]["status"] = {"phase": "Failed"}
        ctl.reconcile(cr)
        assert "gpt-master" not in client.pods
        assert client.statuses["gpt"]["masterRestarts"] == 1
        # operator recreates it on the next pass
        cr_live = dict(cr, status=client.statuses["gpt"])
        ctl.reconcile(cr_live)
        assert "gpt-master" in client.pods
        # second crash exhausts the budget -> FAILED
        client.pods["gpt-master"]["status"] = {"phase": "Failed"}
        ctl.reconcile(dict(cr, status=client.statuses["gpt"]))
        assert client.statuses["gpt"]["phase"] == JobPhase.FAILED

    def test_terminal_job_not_resurrected(self, controller):
        ctl, client = controller
        cr = _cr()
        client.custom[ELASTICJOB_PLURAL]["gpt"] = cr
        ctl.reconcile(cr)
        client.pods["gpt-master"]["status"] = {"phase": "Succeeded"}
        ctl.reconcile(cr)
        assert client.statuses["gpt"]["phase"] == JobPhase.SUCCEEDED
        # kubelet GC removes the terminated pod later
        del client.pods["gpt-master"]
        ctl.reconcile(dict(cr, status=client.statuses["gpt"]))
        assert "gpt-master" not in client.pods, "finished job re-ran!"

    def test_worker_command_shlex_roundtrip(self):
        import shlex

        cr = _cr(workerCommand=["python", "train.py", "--name", "my run"])
        pod = build_master_pod(cr, "ns1")
        env = {
            e["name"]: e["value"]
            for e in pod["spec"]["containers"][0]["env"]
        }
        assert shlex.split(env["DLROVER_WORKER_COMMAND"]) == [
            "python", "train.py", "--name", "my run",
        ]
