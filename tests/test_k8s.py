"""K8s path tests without a cluster (reference style: mock_k8s_client,
dlrover/python/tests/test_utils.py:321-341 — every k8s verb faked,
watch → NodeEvent → relaunch → scaler CRUD exercised in-process)."""

import time
from unittest import mock

import pytest

from dlrover_tpu.common.constants import (
    NodeEventType,
    NodeExitReason,
    NodeStatus,
)
from dlrover_tpu.common.node import Node, NodeEvent
from dlrover_tpu.master.scaler.base_scaler import ScalePlan, Scaler
from dlrover_tpu.master.watcher.k8s_watcher import (
    ElasticJobWatcher,
    PodWatcher,
    ScalePlanWatcher,
    _pod_to_node,
    scale_plan_from_cr,
)
from dlrover_tpu.scheduler.kubernetes import (
    ELASTIC_JOB_LABEL,
    REPLICA_INDEX_LABEL,
    build_worker_pod,
    job_args_from_crd,
    pod_name,
    pod_terminating,
)


class FakeK8sClient:
    """In-memory stand-in for k8sClient (reference mock_k8s_client)."""

    def __init__(self):
        self.pods = {}
        self.fail_names = set()
        self.custom_objects = {}
        self.watch_events = []

    # pods
    def create_pod(self, pod):
        name = pod_name(pod)
        if name in self.fail_names:
            return False
        self.pods[name] = pod
        return True

    def delete_pod(self, name):
        self.pods.pop(name, None)
        return True

    def get_pod(self, name):
        return self.pods.get(name)

    def list_pods(self, label_selector):
        key, _, val = label_selector.partition("=")
        return [
            p
            for p in self.pods.values()
            if p["metadata"]["labels"].get(key) == val
        ]

    def watch_pods(self, label_selector, timeout_s=60):
        yield from self.watch_events

    # custom objects
    def list_custom_objects(self, group, version, plural, label_selector=""):
        return list(self.custom_objects.get(plural, {}).values())

    def watch_custom_objects(
        self, group, version, plural, label_selector="", timeout_s=60
    ):
        yield from self.watch_events

    def delete_custom_object(self, group, version, plural, name):
        self.custom_objects.get(plural, {}).pop(name, None)
        self.deleted_crs = getattr(self, "deleted_crs", [])
        self.deleted_crs.append((plural, name))
        return True


@pytest.fixture(autouse=True)
def fresh_job_context():
    """Tests here build managers on the GLOBAL job context; stale nodes
    from earlier (e2e) tests must not leak into suspend/scale plans."""
    from dlrover_tpu.master.job_context import JobContext

    JobContext.reset()
    yield
    JobContext.reset()


@pytest.fixture()
def fake_client(monkeypatch):
    client = FakeK8sClient()
    import dlrover_tpu.master.scaler.pod_scaler as ps_mod
    import dlrover_tpu.master.watcher.k8s_watcher as kw_mod

    for mod in (ps_mod, kw_mod):
        monkeypatch.setattr(
            mod.k8sClient, "singleton", staticmethod(lambda ns="default": client)
        )
    return client


def _make_scaler(client, **kwargs):
    from dlrover_tpu.master.scaler.pod_scaler import PodScaler

    return PodScaler(
        "job", "img:v1", ["python", "train.py"], "master:50001", **kwargs
    )


class TestPodManifest:
    def test_worker_pod_shape(self):
        pod = build_worker_pod(
            job_name="gpt",
            node_id=3,
            node_rank=5,
            image="img",
            command=["run"],
            master_addr="m:1",
            tpu_chips=4,
            tpu_topology="4x4",
            slice_index=1,
            env={"EXTRA": "1"},
        )
        assert pod["metadata"]["name"] == "gpt-worker-3"
        labels = pod["metadata"]["labels"]
        assert labels[ELASTIC_JOB_LABEL] == "gpt"
        assert labels[REPLICA_INDEX_LABEL] == "5"
        container = pod["spec"]["containers"][0]
        assert container["resources"]["limits"]["google.com/tpu"] == "4"
        assert (
            pod["spec"]["nodeSelector"]["cloud.google.com/gke-tpu-topology"]
            == "4x4"
        )
        env = {e["name"]: e["value"] for e in container["env"]}
        assert env["DLROVER_MASTER_ADDR"] == "m:1"
        assert env["DLROVER_NODE_RANK"] == "5"
        assert env["EXTRA"] == "1"

    def test_pod_terminating(self):
        pod = build_worker_pod("j", 0, 0, "i", ["c"], "m:1")
        assert not pod_terminating(pod)
        pod["metadata"]["deletionTimestamp"] = "2026-07-29T00:00:00Z"
        assert pod_terminating(pod)


class TestPodToNode:
    def _pod(self, name="j-worker-2", phase="Running", **status):
        return {
            "metadata": {"name": name, "labels": {REPLICA_INDEX_LABEL: "2"}},
            "status": {"phase": phase, **status},
        }

    def test_phases(self):
        assert _pod_to_node(self._pod()).status == NodeStatus.RUNNING
        assert (
            _pod_to_node(self._pod(phase="Pending")).status
            == NodeStatus.PENDING
        )
        assert (
            _pod_to_node(self._pod(phase="Succeeded")).status
            == NodeStatus.SUCCEEDED
        )

    def test_exit_reasons(self):
        oom = self._pod(
            phase="Failed",
            containerStatuses=[
                {"state": {"terminated": {"reason": "OOMKilled", "exitCode": 137}}}
            ],
        )
        assert _pod_to_node(oom).exit_reason == NodeExitReason.OOM
        killed = self._pod(
            phase="Failed",
            containerStatuses=[
                {"state": {"terminated": {"exitCode": 137}}}
            ],
        )
        assert _pod_to_node(killed).exit_reason == NodeExitReason.KILLED
        fatal = self._pod(
            phase="Failed",
            containerStatuses=[{"state": {"terminated": {"exitCode": 1}}}],
        )
        assert _pod_to_node(fatal).exit_reason == NodeExitReason.FATAL_ERROR

    def test_non_worker_name_skipped(self):
        assert _pod_to_node({"metadata": {"name": "whatever"}}) is None


class TestPodWatcher:
    def test_list_and_watch_events(self, fake_client):
        scaler = _make_scaler(fake_client)
        scaler.scale(ScalePlan(worker_num=2))
        watcher = PodWatcher("job")
        nodes = watcher.list()
        assert sorted(n.node_id for n in nodes) == [0, 1]

        dead = dict(fake_client.pods["job-worker-1"])
        dead["status"] = {
            "phase": "Failed",
            "containerStatuses": [
                {"state": {"terminated": {"exitCode": 137}}}
            ],
        }
        fake_client.watch_events = [{"type": "DELETED", "object": dead}]
        events = []
        for ev in fake_client.watch_pods(""):
            node = _pod_to_node(ev["object"])
            events.append(
                NodeEvent(event_type=NodeEventType.DELETED, node=node)
            )
        assert events[0].node.node_id == 1
        assert events[0].node.exit_reason == NodeExitReason.KILLED


class TestPodScaler:
    def test_scale_up_and_reconcile(self, fake_client):
        scaler = _make_scaler(fake_client)
        scaler.scale(ScalePlan(worker_num=3))
        assert len(fake_client.pods) == 3
        # a pod vanishes outside a plan -> reconcile recreates it
        fake_client.pods.pop("job-worker-1")
        scaler._reconcile()
        assert "job-worker-1" in fake_client.pods

    def test_remove_only_plan_not_resurrected(self, fake_client):
        scaler = _make_scaler(fake_client)
        scaler.scale(ScalePlan(worker_num=3))
        scaler.scale(ScalePlan(worker_num=-1, remove_nodes=[1]))
        assert "job-worker-1" not in fake_client.pods
        scaler._reconcile()
        assert "job-worker-1" not in fake_client.pods

    def test_terminating_409_retry_keeps_rank(self, fake_client):
        scaler = _make_scaler(fake_client, reconcile_interval=0.1)
        scaler.scale(ScalePlan(worker_num=3))
        old = fake_client.pods["job-worker-2"]
        old["metadata"]["deletionTimestamp"] = "2026-01-01T00:00:00Z"
        fake_client.fail_names.add("job-worker-2")
        scaler.scale(ScalePlan(worker_num=-1, remove_nodes=[2]))
        fake_client.pods["job-worker-2"] = old  # graceful delete: lingers
        scaler.scale(
            ScalePlan(
                worker_num=-1,
                launch_nodes=[Node(node_id=2, rank_index=7)],
            )
        )
        scaler._reconcile()
        assert 2 in scaler._retry, "Terminating pod cancelled the retry"
        # old pod finally goes; retry loop heals with the planned rank
        del fake_client.pods["job-worker-2"]
        fake_client.fail_names.clear()
        scaler.start()
        deadline = time.time() + 5
        while time.time() < deadline:
            pod = fake_client.pods.get("job-worker-2")
            if pod is not None:
                break
            time.sleep(0.05)
        scaler.stop()
        assert pod is not None, "retry loop never healed the 409"
        assert pod["metadata"]["labels"][REPLICA_INDEX_LABEL] == "7"


class TestCrdParsing:
    def test_job_args_from_crd(self):
        crd = {
            "metadata": {"name": "gpt-job", "uid": "u1"},
            "spec": {
                "distributionStrategy": "spmd",
                "nodeUnit": 4,
                "tpuTopology": "4x4",
                "replicaSpecs": {
                    "worker": {"replicas": 16, "restartCount": 5}
                },
            },
        }
        args = job_args_from_crd(crd, "ns1")
        assert args.job_name == "gpt-job"
        group = args.node_args["worker"]
        assert group.count == 16
        assert group.restart_count == 5
        assert group.node_unit == 4
        assert group.accelerator_topology == "4x4"

    def test_scale_plan_from_cr(self):
        obj = {
            "metadata": {"name": "sp1"},
            "spec": {
                "replicaResourceSpecs": {"worker": {"replicas": 8}},
                "removeNodes": [3, 5],
            },
        }
        plan = scale_plan_from_cr(obj)
        assert plan.worker_num == 8
        assert plan.remove_nodes == [3, 5]
        assert scale_plan_from_cr({"spec": {}}) is None


class RecordingScaler(Scaler):
    def __init__(self):
        super().__init__("job")
        self.plans = []

    def scale(self, plan):
        self.plans.append(plan)


class TestScalePlanWatcher:
    def test_plan_cr_dispatch_and_dedup(self, fake_client):
        scaler = RecordingScaler()
        watcher = ScalePlanWatcher("job", scaler.scale)
        cr = {
            "metadata": {"name": "sp1", "resourceVersion": "1"},
            "spec": {"replicaResourceSpecs": {"worker": {"replicas": 5}}},
        }
        watcher._handle(cr)
        watcher._handle(cr)  # same resourceVersion: no double-execute
        assert len(scaler.plans) == 1
        assert scaler.plans[0].worker_num == 5
        # executed CRs are deleted so they can't replay on master restart
        assert ("scaleplans", "sp1") in fake_client.deleted_crs
        cr2 = dict(cr, metadata={"name": "sp1", "resourceVersion": "2"})
        watcher._handle(cr2)
        assert len(scaler.plans) == 2


class TestSuspendResume:
    def _manager(self):
        from dlrover_tpu.master.node.dist_job_manager import (
            DistributedJobManager,
        )

        scaler = RecordingScaler()
        mgr = DistributedJobManager(num_workers=2, scaler=scaler)
        from dlrover_tpu.common.constants import NodeType

        for node_id in range(2):
            node = Node(
                node_type=NodeType.WORKER, node_id=node_id, rank_index=node_id
            )
            node.update_status(NodeStatus.RUNNING)
            mgr._job_ctx.update_node(node)
        return mgr, scaler

    def test_suspend_removes_and_suppresses_relaunch(self):
        mgr, scaler = self._manager()
        mgr.suspend()
        assert mgr.is_suspended
        assert scaler.plans[-1].worker_num == 0
        assert sorted(scaler.plans[-1].remove_nodes) == [0, 1]
        # deletions while suspended are not failures
        from dlrover_tpu.common.constants import NodeType

        dead = Node(node_type=NodeType.WORKER, node_id=0, rank_index=0)
        dead.update_status(NodeStatus.FAILED)
        mgr.process_event(
            NodeEvent(event_type=NodeEventType.DELETED, node=dead)
        )
        assert len(scaler.plans) == 1, "suspended deletion triggered relaunch"

        mgr.resume()
        assert not mgr.is_suspended
        assert scaler.plans[-1].worker_num == 2

    def test_elasticjob_watcher_apply(self, fake_client):
        mgr, scaler = self._manager()
        watcher = ElasticJobWatcher("job", mgr)
        watcher._apply({"metadata": {"name": "job"}, "spec": {"suspend": True}})
        assert mgr.is_suspended
        watcher._apply({"metadata": {"name": "job"}, "spec": {"suspend": False}})
        assert not mgr.is_suspended
