"""Multi-tenant cluster scheduler (dlrover_tpu/cluster/, PR 20).

Tier-1 fast synthetics: the pure ``schedule()`` policy on plain dicts
(priority ordering, preemption cascades, floors/ceilings, gang grids,
busy exclusion, idle placement), the ``ClusterScheduler`` lease machine
over scripted tenants, brain-target adoption (``BrainFeedback`` over a
seeded datastore — targets come from measured scaling curves, not
static knobs), journal replay of a mid-cascade crash, the chaos
injection points (``cluster.schedule`` / ``cluster.brain_target``) and
the ``priority_inversion_storm`` scenario twin. The real-engine
4-tenant drill is slow-marked at the bottom.
"""

import json
import os
import time

import pytest

from dlrover_tpu.cluster.config import ClusterConfig
from dlrover_tpu.cluster.registry import (
    SERVE,
    TRAIN,
    TenantRegistry,
    TenantSpec,
    parse_priority_classes,
)
from dlrover_tpu.cluster.scheduler import ClusterScheduler, schedule


# ---------------------------------------------------------------------------
# policy-table helpers: plain tenant-view dicts, no scheduler state
# ---------------------------------------------------------------------------


def view(
    name,
    kind=TRAIN,
    priority=20,
    floor=0,
    ceiling=8,
    node_unit=1,
    held=0,
    target=None,
    signals=None,
    calm_streak=0,
    baseline=0,
    busy=False,
    expandable=None,
    **extra,
):
    v = {
        "name": name,
        "kind": kind,
        "priority": priority,
        "floor": floor,
        "ceiling": ceiling,
        "node_unit": node_unit,
        "held": held,
        "target": target,
        "signals": signals,
        "calm_streak": calm_streak,
        "baseline": baseline,
        "busy": busy,
        "expandable": kind == TRAIN if expandable is None else expandable,
    }
    v.update(extra)
    return v


def breach_sig(queue_mean=8.0, ready=1, busy_total=1, p95=None):
    return {
        "ready": ready,
        "queue_mean": queue_mean,
        "busy_total": busy_total,
        "p95_worst_s": p95,
    }


def calm_sig(ready=1):
    return {
        "ready": ready,
        "queue_mean": 0.0,
        "busy_total": 0,
        "p95_worst_s": 0.0,
    }


CFG = ClusterConfig(total_units=8, queue_high=2.0)


class TestSchedulePolicy:
    def test_no_demand_no_move(self):
        out = schedule(
            [view("a", held=2), view("b", held=2)], free=0, cfg=CFG
        )
        assert out["action"] is None
        assert out["reason"] == "all tenants at target"

    def test_breach_claims_free_pool_first(self):
        out = schedule(
            [
                view(
                    "svc",
                    kind=SERVE,
                    priority=0,
                    floor=1,
                    held=1,
                    signals=breach_sig(),
                ),
                view("bulk", priority=30, floor=1, held=3),
            ],
            free=2,
            cfg=CFG,
        )
        assert out["action"] == "grant"
        assert out["tenant"] == "svc"
        assert out["from_free"] == 1  # one spike step, not the pool
        assert out["victims"] == []

    def test_involuntary_victim_is_lowest_priority_above_floor(self):
        out = schedule(
            [
                view(
                    "svc",
                    kind=SERVE,
                    priority=0,
                    floor=1,
                    held=1,
                    signals=breach_sig(),
                ),
                view("mid", priority=20, floor=1, held=3),
                view("low", priority=30, floor=1, held=3),
            ],
            free=0,
            cfg=CFG,
        )
        assert out["action"] == "grant" and out["tenant"] == "svc"
        assert out["victims"] == [{"tenant": "low", "units": 1}]

    def test_victim_at_floor_is_skipped(self):
        out = schedule(
            [
                view(
                    "svc",
                    kind=SERVE,
                    priority=0,
                    floor=1,
                    held=1,
                    signals=breach_sig(),
                ),
                view("mid", priority=20, floor=1, held=3),
                view("low", priority=30, floor=1, held=1),  # at floor
            ],
            free=0,
            cfg=CFG,
        )
        # low is untouchable; the cascade moves up the priority order
        assert out["victims"] == [{"tenant": "mid", "units": 1}]

    def test_never_involuntarily_preempts_equal_or_higher(self):
        out = schedule(
            [
                view(
                    "svc",
                    kind=SERVE,
                    priority=10,
                    floor=1,
                    held=1,
                    signals=breach_sig(),
                ),
                view("peer", priority=10, floor=1, held=4),
                view("boss", priority=0, floor=1, held=3),
            ],
            free=0,
            cfg=CFG,
        )
        # no strictly-lower-priority capacity above floor, no
        # volunteers: the breach is stuck, not stolen
        assert out["action"] is None
        assert "no capacity movable" in out["reason"]

    def test_equal_priority_voluntary_surplus_moves(self):
        out = schedule(
            [
                view(
                    "svc",
                    kind=SERVE,
                    priority=10,
                    floor=1,
                    held=1,
                    signals=breach_sig(),
                ),
                # a peer whose own brain target is below its holding
                # volunteers the surplus even at equal priority
                view("peer", priority=10, floor=1, held=4, target=3),
            ],
            free=0,
            cfg=CFG,
        )
        assert out["action"] == "grant" and out["tenant"] == "svc"
        assert out["victims"] == [{"tenant": "peer", "units": 1}]

    def test_voluntary_before_involuntary_among_equals(self):
        out = schedule(
            [
                view(
                    "svc",
                    kind=SERVE,
                    priority=0,
                    floor=1,
                    held=1,
                    signals=breach_sig(),
                ),
                view("a", priority=30, floor=1, held=3),  # involuntary
                view("b", priority=30, floor=1, held=3, target=2),
            ],
            free=0,
            cfg=CFG,
        )
        # same rank: the volunteer pays before the conscript
        assert out["victims"] == [{"tenant": "b", "units": 1}]

    def test_priority_orders_competing_claimants(self):
        out = schedule(
            [
                view("hi", priority=0, floor=0, held=0, target=2),
                view("lo", priority=30, floor=0, held=0, target=2),
            ],
            free=2,
            cfg=CFG,
        )
        assert out["tenant"] == "hi"

    def test_registration_order_breaks_priority_ties(self):
        out = schedule(
            [
                view("first", priority=10, held=0, target=1),
                view("second", priority=10, held=0, target=1),
            ],
            free=1,
            cfg=CFG,
        )
        assert out["tenant"] == "first"

    def test_ceiling_clamps_demand(self):
        out = schedule(
            [view("t", held=4, ceiling=4, target=6)], free=4, cfg=CFG
        )
        assert out["action"] is None  # already at ceiling

    def test_floor_clamps_shrink_target(self):
        # a brain target below floor is lifted to the floor: no
        # voluntary surplus below the reserved capacity
        out = schedule(
            [
                view("hungry", priority=0, held=0, target=4),
                view("t", priority=30, floor=2, held=2, target=0),
            ],
            free=0,
            cfg=CFG,
        )
        assert out["action"] is None

    def test_gang_claimant_snaps_demand_down_to_grid(self):
        out = schedule(
            [view("gang", node_unit=2, held=2, target=5)],
            free=4,
            cfg=CFG,
        )
        # demand 5 → 4 on the grid; one move = one node_unit slice
        assert out["action"] == "grant"
        assert out["units"] == 2 and out["from_free"] == 2

    def test_gang_victim_revocation_snaps_up_to_grid(self):
        out = schedule(
            [
                view(
                    "svc",
                    kind=SERVE,
                    priority=0,
                    floor=1,
                    held=1,
                    signals=breach_sig(),
                ),
                view("gang", priority=30, floor=0, node_unit=2, held=4),
            ],
            free=0,
            cfg=CFG,
        )
        # svc needs 1 but the gang tenant can only shrink by whole
        # slices: the revoke is 2, the excess lands in the free pool
        assert out["victims"] == [{"tenant": "gang", "units": 2}]

    def test_gang_claimant_refuses_partial_slice(self):
        out = schedule(
            [
                view("gang", node_unit=4, held=0, floor=0, target=4),
                view(
                    "donor",
                    priority=30,
                    floor=0,
                    held=1,
                    expandable=False,
                ),
            ],
            free=1,
            cfg=CFG,
        )
        # only 2 units reachable < one node_unit=4 slice: no move
        assert out["action"] is None

    def test_busy_claimant_excluded(self):
        out = schedule(
            [view("t", held=0, target=2, busy=True)], free=2, cfg=CFG
        )
        assert out["action"] is None

    def test_busy_victim_excluded(self):
        out = schedule(
            [
                view(
                    "svc",
                    kind=SERVE,
                    priority=0,
                    floor=1,
                    held=1,
                    signals=breach_sig(),
                ),
                view("low", priority=30, floor=1, held=3, busy=True),
                view("mid", priority=20, floor=1, held=3),
            ],
            free=0,
            cfg=CFG,
        )
        # the busy tenant's lease is in flight: one move per tenant
        assert out["victims"] == [{"tenant": "mid", "units": 1}]

    def test_serve_breach_needs_a_ready_replica(self):
        out = schedule(
            [
                view(
                    "svc",
                    kind=SERVE,
                    priority=0,
                    floor=1,
                    held=1,
                    signals=breach_sig(ready=0),
                ),
            ],
            free=2,
            cfg=CFG,
        )
        assert out["action"] is None  # never arbitrate blind

    def test_serve_p95_breach(self):
        out = schedule(
            [
                view(
                    "svc",
                    kind=SERVE,
                    priority=0,
                    floor=1,
                    held=1,
                    signals=breach_sig(queue_mean=0.0, p95=1.0),
                    p95_target_s=0.5,
                ),
            ],
            free=2,
            cfg=CFG,
        )
        assert out["action"] == "grant" and out["tenant"] == "svc"
        assert "p95" in out["reason"]

    def test_serve_calm_streak_hands_surge_back(self):
        views = [
            view(
                "svc",
                kind=SERVE,
                priority=0,
                floor=1,
                held=3,
                baseline=1,
                signals=calm_sig(),
                calm_streak=CFG.handback_evals - 1,
            ),
            view("train", priority=30, floor=1, held=5),
        ]
        out = schedule(views, free=0, cfg=CFG)
        # svc's demand drops below held → voluntary surplus flows to
        # the expandable trainer through idle placement
        assert out["action"] == "grant" and out["tenant"] == "train"
        assert out["victims"] == [{"tenant": "svc", "units": 1}]
        assert out["calm"]["svc"] == 0  # streak consumed by the move

    def test_calm_streak_below_hysteresis_holds(self):
        out = schedule(
            [
                view(
                    "svc",
                    kind=SERVE,
                    priority=0,
                    floor=1,
                    held=3,
                    baseline=1,
                    signals=calm_sig(),
                    calm_streak=0,
                ),
                view("train", priority=30, floor=1, held=5),
            ],
            free=0,
            cfg=CFG,
        )
        assert out["action"] is None
        assert out["calm"]["svc"] == 1  # the streak advances

    def test_idle_free_units_reclaimed_by_expandable(self):
        out = schedule(
            [
                view("svc", kind=SERVE, priority=0, floor=1, held=1),
                view("train", priority=30, floor=1, held=3, ceiling=6),
            ],
            free=2,
            cfg=CFG,
        )
        assert out["action"] == "grant" and out["tenant"] == "train"
        assert out["from_free"] == 2 and out["victims"] == []
        assert "reclaim" in out["reason"]

    def test_targeted_tenants_never_reclaim_past_target(self):
        # two brain-targeted trainers sitting AT target with a free
        # unit: idle placement must leave the unit in the free ledger.
        # Lifting either above its target would make it a voluntary
        # victim next round and the pair would trade the unit forever
        # (grant↔handback livelock).
        out = schedule(
            [
                view("a", held=4, target=4),
                view("b", priority=30, held=1, target=1),
            ],
            free=1,
            cfg=CFG,
        )
        assert out["action"] is None

    def test_idle_placement_skips_unattached_tenants(self):
        # a declared-but-unattached trainer can only ever produce
        # grant_skipped — idle placement must not pick it (it would
        # retry forever and starve the release branch); with no other
        # recipient the calm surge releases to the free ledger instead
        out = schedule(
            [
                view(
                    "svc",
                    kind=SERVE,
                    priority=0,
                    floor=1,
                    held=2,
                    baseline=1,
                    signals=calm_sig(),
                    calm_streak=CFG.handback_evals - 1,
                ),
                view("t", priority=30, held=0, attached=False),
            ],
            free=6,
            cfg=CFG,
        )
        assert out["action"] == "release"
        assert out["tenant"] == "svc" and out["units"] == 1

    def test_surplus_with_no_recipient_releases_to_free(self):
        # calm serve surge while every trainer is brain-capped: no
        # idle-placement recipient exists, so the surge must release
        # back to the free ledger instead of sticking to the fleet
        out = schedule(
            [
                view(
                    "svc",
                    kind=SERVE,
                    priority=0,
                    floor=1,
                    held=2,
                    baseline=1,
                    signals=calm_sig(),
                    calm_streak=CFG.handback_evals - 1,
                ),
                view("t", held=4, target=4),
            ],
            free=0,
            cfg=CFG,
        )
        assert out["action"] == "release"
        assert out["tenant"] == "svc" and out["units"] == 1
        assert out["calm"]["svc"] == 0

    def test_brain_target_replaces_static_hold(self):
        # without a target a trainer holds; with one, it claims
        # (expandable off isolates demand from idle reclaim)
        assert (
            schedule(
                [view("t", held=2, expandable=False)], free=4, cfg=CFG
            )["action"]
            is None
        )
        out = schedule(
            [view("t", held=2, target=4, expandable=False)],
            free=4,
            cfg=CFG,
        )
        assert out["action"] == "grant" and out["tenant"] == "t"

    def test_demand_map_reports_effective_targets(self):
        out = schedule(
            [
                view("t", floor=1, ceiling=4, held=2, target=9),
                view("u", floor=2, held=2, target=0),
            ],
            free=0,
            cfg=CFG,
        )
        assert out["demand"] == {"t": 4, "u": 2}  # clamped both ways


# ---------------------------------------------------------------------------
# scheduler lease machine over scripted tenants
# ---------------------------------------------------------------------------


class Scripted:
    """Pool tenant protocol with scriptable drain behaviour."""

    def __init__(
        self,
        initial_units=0,
        signals=None,
        drain="instant",
        grant_error=False,
        escalate_frees=True,
    ):
        self.initial_units = initial_units
        self.signals = signals
        self.drain = drain  # "instant" | "never"
        self.grant_error = grant_error
        self.escalate_frees = escalate_frees
        self.granted = []
        self.revoked = []
        self.escalated = []
        self.pending_release = []

    def report(self):
        return self.signals

    def grant(self, units):
        if self.grant_error:
            raise RuntimeError("no capacity applied")
        self.granted.append(units)

    def revoke(self, units, deadline_s, on_released):
        self.revoked.append(units)
        if self.drain == "instant":
            on_released(units)
        else:
            self.pending_release.append((units, on_released))

    def release_all(self):
        for units, cb in self.pending_release:
            cb(units)
        self.pending_release = []

    def escalate(self, units):
        self.escalated.append(units)
        return units if self.escalate_frees else 0


def two_tenant(svc_sig, cfg=None, drain="instant", **svc_kw):
    reg = TenantRegistry()
    svc = Scripted(initial_units=1, signals=svc_sig, **svc_kw)
    bulk = Scripted(initial_units=3, drain=drain)
    reg.register(
        TenantSpec("svc", SERVE, priority=0, floor=1, ceiling=4), svc
    )
    reg.register(
        TenantSpec("bulk", TRAIN, priority=30, floor=1), bulk
    )
    sched = ClusterScheduler(
        reg, cfg or ClusterConfig(total_units=4, queue_high=2.0)
    )
    return sched, svc, bulk


class TestClusterScheduler:
    def test_breach_revokes_then_grants(self):
        sched, svc, bulk = two_tenant(breach_sig())
        verdict = sched.step()
        assert verdict["action"] == "grant"
        assert sched.allocations() == {"svc": 2, "bulk": 2}
        assert bulk.revoked == [1] and svc.granted == [1]
        assert sched.revokes == 1 and sched.grants == 1
        events = [e["event"] for e in sched.journal()]
        assert events == ["decision", "revoke", "release", "grant"]

    def test_one_move_in_flight_per_tenant(self):
        sched, svc, bulk = two_tenant(breach_sig(), drain="never")
        sched.step()
        # ledger honesty: nothing moved until the drain confirms
        assert sched.allocations() == {"svc": 1, "bulk": 3}
        # lease open: both the victim and the claimant are busy, the
        # breach cannot issue a second overlapping move
        verdict = sched.step()
        assert verdict["action"] is None
        assert bulk.revoked == [1]
        bulk.release_all()
        assert sched.allocations() == {"svc": 2, "bulk": 2}
        assert sched.wait_idle(timeout=1.0)

    def test_deadline_escalation_reclaims(self):
        cfg = ClusterConfig(
            total_units=4, queue_high=2.0, revoke_deadline_s=0.05
        )
        sched, svc, bulk = two_tenant(breach_sig(), cfg=cfg, drain="never")
        sched.step()
        svc.signals["queue_mean"] = 0.0  # breach quiets; lease hangs
        time.sleep(0.08)
        sched.step()  # deadline check escalates the overdue lease
        assert bulk.escalated == [1]
        assert sched.escalations == 1
        assert sched.allocations() == {"svc": 2, "bulk": 2}
        events = [e["event"] for e in sched.journal()]
        assert "escalate" in events and "escalate_freed" in events

    def test_late_release_after_escalation_is_ignored(self):
        cfg = ClusterConfig(
            total_units=4, queue_high=2.0, revoke_deadline_s=0.05
        )
        sched, svc, bulk = two_tenant(breach_sig(), cfg=cfg, drain="never")
        sched.step()
        svc.signals["queue_mean"] = 0.0
        time.sleep(0.08)
        sched.step()
        alloc = sched.allocations()
        bulk.release_all()  # the cooperative drain finally answers
        assert sched.allocations() == alloc  # ledger moved exactly once
        assert any(
            e["event"] == "late_release" for e in sched.journal()
        )

    def test_failed_grant_rolls_ledger_back(self):
        sched, svc, bulk = two_tenant(breach_sig(), grant_error=True)
        sched.step()
        # the unit was freed but could not be applied: it sits in the
        # free pool for a later round, never vanishes
        assert sched.allocations() == {"svc": 1, "bulk": 2}
        assert sched.free_units() == 1
        assert any(
            e["event"] == "grant_error" for e in sched.journal()
        )

    def test_shrink_target_adopts_immediately(self):
        sched, svc, bulk = two_tenant(None)
        sched.set_target("bulk", 2, source="brain")
        st = sched.status()["targets"]["bulk"]
        assert st["adopted"] and st["source"] == "brain"
        assert sched.adoptions == 1 and sched.last_adopt_s == 0.0

    def test_grow_target_adopts_at_the_lifting_grant(self):
        # a calm serving tenant with a brain GROW target: the target
        # itself is the demand; bulk's shrink target volunteers the
        # capacity, and adoption closes at the lifting grant
        sched, svc, bulk = two_tenant(calm_sig())
        sched.set_target("svc", 2, source="brain")
        assert not sched.status()["targets"]["svc"]["adopted"]
        sched.set_target("bulk", 2, source="brain")
        sched.step()
        assert sched.allocations() == {"svc": 2, "bulk": 2}
        assert sched.status()["targets"]["svc"]["adopted"]
        assert sched.last_adopt_s is not None
        assert sched.last_adopt_s > 0.0
        assert any(
            e["event"] == "target_adopted" and e["tenant"] == "svc"
            for e in sched.journal()
        )

    def test_unknown_tenant_target_raises(self):
        sched, _, _ = two_tenant(None)
        with pytest.raises(KeyError):
            sched.set_target("ghost", 2)

    def test_roster_overcommit_rejected(self):
        reg = TenantRegistry()
        reg.register(
            TenantSpec("a", TRAIN, floor=1), Scripted(initial_units=3)
        )
        reg.register(
            TenantSpec("b", TRAIN, floor=1), Scripted(initial_units=3)
        )
        with pytest.raises(ValueError):
            ClusterScheduler(reg, ClusterConfig(total_units=4))

    def test_status_shape(self):
        sched, _, _ = two_tenant(calm_sig())
        sched.step()
        st = sched.status()
        assert st["total_units"] == 4
        assert st["allocations"] == {"svc": 1, "bulk": 3}
        assert st["counters"]["evaluations"] == 1
        assert st["tenants"]["svc"]["priority"] == 0
        assert st["tenants"]["bulk"]["ceiling"] == 4  # 0 = whole pool


# ---------------------------------------------------------------------------
# journal replay: a scheduler crash mid-cascade
# ---------------------------------------------------------------------------


class TestJournalReplay:
    def test_mid_cascade_crash_surfaces_open_lease(self, tmp_path):
        from dlrover_tpu.common.journal import replay

        path = str(tmp_path / "journal.jsonl")
        cfg = ClusterConfig(
            total_units=4, queue_high=2.0, journal_path=path
        )
        sched, svc, bulk = two_tenant(breach_sig(), cfg=cfg, drain="never")
        sched.step()
        # "crash": the process dies with the drain in flight. The
        # journal file is all that survives.
        state = replay(path)
        # the ledger never moved — capacity is still the victim's
        assert state["alloc"] == {"svc": 1, "bulk": 3}
        assert state["free"] == 0
        assert state["open_leases"] == [
            {
                "lease_id": 0,
                "tenant": "bulk",
                "units": 1,
                "grant_to": "svc",
                "reason": state["open_leases"][0]["reason"],
            }
        ]

    def test_completed_cascade_replays_closed(self, tmp_path):
        from dlrover_tpu.common.journal import replay

        path = str(tmp_path / "journal.jsonl")
        cfg = ClusterConfig(
            total_units=4, queue_high=2.0, journal_path=path
        )
        sched, svc, bulk = two_tenant(breach_sig(), cfg=cfg)
        sched.step()
        state = replay(path)
        assert state["alloc"] == {"svc": 2, "bulk": 2}
        assert state["open_leases"] == []
        assert state["last_seq"] == len(sched.journal()) - 1

    def test_escalated_lease_is_terminal(self, tmp_path):
        from dlrover_tpu.common.journal import replay

        path = str(tmp_path / "journal.jsonl")
        cfg = ClusterConfig(
            total_units=4,
            queue_high=2.0,
            revoke_deadline_s=0.05,
            journal_path=path,
        )
        sched, svc, bulk = two_tenant(breach_sig(), cfg=cfg, drain="never")
        sched.step()
        svc.signals["queue_mean"] = 0.0  # breach quiets; lease hangs
        time.sleep(0.08)
        sched.step()
        state = replay(path)
        assert state["open_leases"] == []
        assert state["alloc"] == {"svc": 2, "bulk": 2}

    def test_replay_tolerates_torn_tail(self, tmp_path):
        from dlrover_tpu.common.journal import replay

        path = str(tmp_path / "journal.jsonl")
        cfg = ClusterConfig(
            total_units=4, queue_high=2.0, journal_path=path
        )
        sched, svc, bulk = two_tenant(breach_sig(), cfg=cfg)
        sched.step()
        with open(path, "a") as f:
            f.write('{"event": "gra')  # died mid-append
        state = replay(path)
        assert state["alloc"] == {"svc": 2, "bulk": 2}


# ---------------------------------------------------------------------------
# registry / config parsing
# ---------------------------------------------------------------------------


class TestRegistryConfig:
    def test_priority_classes_parse(self):
        classes = parse_priority_classes("critical=0, high=10,low=30")
        assert classes == {"critical": 0, "high": 10, "low": 30}
        with pytest.raises(ValueError):
            parse_priority_classes("not-a-pair")

    def test_tenant_spec_parse_with_class_names(self):
        classes = {"critical": 0, "preemptible": 30}
        spec = TenantSpec.parse("api:serve:critical:1:4", classes)
        assert spec.kind == SERVE and spec.priority == 0
        assert spec.floor == 1 and spec.ceiling == 4
        spec = TenantSpec.parse("batch:train:25:2::2", classes)
        assert spec.priority == 25 and spec.node_unit == 2
        with pytest.raises(ValueError):
            TenantSpec.parse("x:serve:no-such-class", classes)

    def test_spec_grid_invariants(self):
        with pytest.raises(ValueError):
            TenantSpec("t", TRAIN, floor=3, node_unit=2)
        with pytest.raises(ValueError):
            TenantSpec("t", TRAIN, ceiling=3, node_unit=2)
        with pytest.raises(ValueError):
            TenantSpec("t", TRAIN, floor=4, ceiling=2)
        with pytest.raises(ValueError):
            TenantSpec("t", "batch")

    def test_registry_from_config_roster(self):
        cfg = ClusterConfig(
            total_units=8,
            tenants="api:serve:critical:1:4;batch:train:preemptible:1",
        )
        reg = TenantRegistry.from_config(cfg)
        assert reg.names() == ["api", "batch"]
        assert reg.spec("api").priority == 0
        assert reg.spec("batch").priority == 30
        assert reg.ceiling("batch", cfg.total_units) == 8
        reg.validate(cfg.total_units)
        with pytest.raises(ValueError):
            reg.validate(1)  # floors exceed a 1-unit pool

    def test_duplicate_registration_rejected(self):
        reg = TenantRegistry()
        reg.register(TenantSpec("t", TRAIN), None)
        with pytest.raises(ValueError):
            reg.register(TenantSpec("t", SERVE), None)

    def test_config_from_env_knobs(self, monkeypatch):
        monkeypatch.setenv("DLROVER_CLUSTER_TOTAL_UNITS", "16")
        monkeypatch.setenv("DLROVER_CLUSTER_QUEUE_HIGH", "3.5")
        monkeypatch.setenv(
            "DLROVER_CLUSTER_TENANTS", "api:serve:0:1"
        )
        cfg = ClusterConfig.from_env(handback_evals=5)
        assert cfg.total_units == 16
        assert cfg.queue_high == 3.5
        assert cfg.tenants == "api:serve:0:1"
        assert cfg.handback_evals == 5  # explicit override wins

    def test_config_validation(self):
        with pytest.raises(ValueError):
            ClusterConfig(total_units=1)
        with pytest.raises(ValueError):
            ClusterConfig(spike_units=0)
        with pytest.raises(ValueError):
            ClusterConfig(revoke_deadline_s=0)


# ---------------------------------------------------------------------------
# brain loop: targets from measured curves, not static knobs
# ---------------------------------------------------------------------------


class DummyController:
    def __init__(self, world, sps):
        self._world = world
        self._sps = sps

    def report(self):
        return {"world": self._world, "steps_per_s": self._sps}


def brain_cluster():
    from dlrover_tpu.brain.datastore import BrainDataStore
    from dlrover_tpu.cluster.brain_loop import BrainFeedback

    reg = TenantRegistry()
    fast = Scripted(initial_units=2)
    slow = Scripted(initial_units=2)
    reg.register(
        TenantSpec("fast", TRAIN, priority=10, floor=1, ceiling=6), fast
    )
    reg.register(
        TenantSpec("slow", TRAIN, priority=30, floor=1, ceiling=6), slow
    )
    sched = ClusterScheduler(reg, ClusterConfig(total_units=6))
    store = BrainDataStore(":memory:")
    brain = BrainFeedback(sched, store=store, min_samples=2)
    brain.add_training_job(
        "fast", DummyController(2, 4.0), model_signature="linear"
    )
    brain.add_training_job(
        "slow", DummyController(2, 2.05), model_signature="saturated"
    )
    return sched, brain, store, fast, slow


def seed_curves(store):
    """fast scales linearly to 6 units; slow saturates at 2."""
    from dlrover_tpu.brain.datastore import JobMetricSample

    for w in range(1, 7):
        store.add_metric(
            JobMetricSample(
                job_uuid="fast", world_size=w, steps_per_second=2.0 * w
            )
        )
    for w, sps in ((1, 2.0), (2, 2.05), (3, 2.08), (4, 2.1)):
        store.add_metric(
            JobMetricSample(
                job_uuid="slow", world_size=w, steps_per_second=sps
            )
        )


class TestBrainFeedback:
    def test_without_samples_no_targets(self):
        sched, brain, store, _, _ = brain_cluster()
        assert brain.evaluate_once() == {}
        assert sched.targets() == {}

    def test_poll_feeds_the_scaling_curve(self):
        sched, brain, store, _, _ = brain_cluster()
        assert brain.poll_once() == 2
        rows = store.job_metrics("fast", limit=10)
        assert len(rows) == 1
        assert rows[0].steps_per_second == 4.0
        assert rows[0].world_size == 2

    def test_targets_follow_marginal_gain_not_knobs(self):
        sched, brain, store, fast, slow = brain_cluster()
        seed_curves(store)
        targets = brain.evaluate_once()
        # the linear scaler gets the spare capacity, the saturated job
        # is cut to its knee — nothing in any static knob says this
        assert targets["fast"] > 2
        assert targets["slow"] <= 2
        assert brain.emissions == len(targets)
        src = sched.status()["targets"]
        assert all(t["source"] == "brain" for t in src.values())

    def test_scheduler_converges_to_brain_targets(self):
        sched, brain, store, fast, slow = brain_cluster()
        seed_curves(store)
        targets = brain.evaluate_once()
        for _ in range(8):
            sched.step()
            if not sched.pending_leases():
                alloc = sched.allocations()
                if alloc.get("fast") == targets["fast"]:
                    break
        alloc = sched.allocations()
        assert alloc["fast"] == targets["fast"]
        assert alloc["slow"] >= 1  # never below floor
        assert alloc["fast"] + alloc["slow"] <= 6
        assert sched.adoptions >= 1

    def test_live_caller_of_cluster_resource_arbiter(self):
        # the acceptance criterion: evaluate_once drives
        # ClusterResourceArbiter.allocate with real sampled jobs
        from dlrover_tpu.brain import algorithms

        sched, brain, store, _, _ = brain_cluster()
        seed_curves(store)
        calls = {}
        orig = algorithms.ClusterResourceArbiter.allocate

        def spy(self, job_uuids, total_hosts, node_unit=1):
            out = orig(self, job_uuids, total_hosts, node_unit)
            calls["jobs"] = list(job_uuids)
            calls["hosts"] = total_hosts
            calls["result"] = dict(out)
            return out

        algorithms.ClusterResourceArbiter.allocate = spy
        try:
            brain.evaluate_once()
        finally:
            algorithms.ClusterResourceArbiter.allocate = orig
        assert calls["jobs"] == ["fast", "slow"]
        assert calls["hosts"] == 6  # no serving tenants: whole pool
        assert sum(calls["result"].values()) <= 6

    def test_serving_holdings_shrink_the_train_budget(self):
        from dlrover_tpu.brain.datastore import BrainDataStore
        from dlrover_tpu.cluster.brain_loop import BrainFeedback

        reg = TenantRegistry()
        reg.register(
            TenantSpec("svc", SERVE, priority=0, floor=2),
            Scripted(initial_units=2, signals=calm_sig()),
        )
        reg.register(
            TenantSpec("train", TRAIN, priority=30, floor=1),
            Scripted(initial_units=2),
        )
        sched = ClusterScheduler(reg, ClusterConfig(total_units=6))
        brain = BrainFeedback(
            sched, store=BrainDataStore(":memory:"), min_samples=1
        )
        brain.add_training_job("train", DummyController(2, 1.0))
        assert brain._train_budget() == 4  # 6 minus svc's 2

    def test_emission_error_survives_and_journals(self):
        from dlrover_tpu.chaos import faults

        sched, brain, store, _, _ = brain_cluster()
        seed_curves(store)
        faults.activate(
            faults.FaultPlan.parse(
                "cluster.brain_target:error:dropped@once"
            )
        )
        try:
            targets = brain.evaluate_once()
        finally:
            faults.deactivate()
        assert targets  # the evaluation itself survived
        assert brain.target_errors >= 1
        errs = [
            e
            for t in targets
            for e in store.job_events(t, "brain_target_error")
        ]
        assert errs


# ---------------------------------------------------------------------------
# chaos: injection points + the scenario twin
# ---------------------------------------------------------------------------


class TestClusterChaos:
    def test_injection_points_registered(self):
        from dlrover_tpu.chaos import faults

        assert "cluster.schedule" in faults.INJECTION_POINTS
        assert "cluster.brain_target" in faults.INJECTION_POINTS

    def test_dark_schedule_round_skips_without_moving(self):
        from dlrover_tpu.chaos import faults

        sched, svc, bulk = two_tenant(breach_sig())
        faults.activate(
            faults.FaultPlan.parse("cluster.schedule:error:dark@once")
        )
        try:
            verdict = sched.step()
        finally:
            faults.deactivate()
        assert verdict["action"] is None
        assert "schedule error" in verdict["reason"]
        assert sched.allocations() == {"svc": 1, "bulk": 3}
        assert any(
            e["event"] == "schedule_error" for e in sched.journal()
        )
        # the next round decides normally
        assert sched.step()["action"] == "grant"

    def test_priority_inversion_storm_scenario(self, tmp_path):
        """The tier-1 synthetic twin of the 4-tenant drill: scripted
        tenants, a dark scheduler round, a dropped brain emission, and
        the full cascade — fast enough for every run."""
        from dlrover_tpu.chaos.scenarios import SCENARIOS

        out = SCENARIOS["priority_inversion_storm"](
            workdir=str(tmp_path)
        )
        assert out["recovered"], out
        assert out["fired"] >= 2
        assert out["cascade"] and set(out["cascade"]) == {"train_lo"}
        assert out["allocations"]["train_hi"] == 4


# ---------------------------------------------------------------------------
# brain datastore: flattened-Prometheus ingestion (the PR 20 fix)
# ---------------------------------------------------------------------------


class TestIngestLabeledGauges:
    def make_store(self):
        from dlrover_tpu.brain.datastore import BrainDataStore

        return BrainDataStore(":memory:")

    def test_labeled_series_aggregate_alias_ignored(self):
        store = self.make_store()
        sample = store.ingest_gauges(
            "job-1",
            {
                'dlrover_steps_per_second{pod="w0"}': 2.0,
                'dlrover_steps_per_second{pod="w1"}': 3.0,
                # the flattener's bare-name alias repeats the LAST
                # labeled sample — counting it would double w1
                "dlrover_steps_per_second": 3.0,
                'dlrover_peak_memory_mb{pod="w0"}': 100.0,
                'dlrover_peak_memory_mb{pod="w1"}': 200.0,
                'dlrover_cpu_percent{pod="w0"}': 10.0,
                'dlrover_cpu_percent{pod="w1"}': 30.0,
                'dlrover_world_size{pod="w0"}': 2.0,
                'dlrover_world_size{pod="w1"}': 2.0,
            },
        )
        assert sample is not None
        assert sample.steps_per_second == 5.0  # sum, not 8.0
        assert sample.peak_memory_mb == 200.0  # max
        assert sample.cpu_percent == 20.0  # mean
        assert sample.world_size == 2  # max

    def test_alias_before_labeled_series_still_ignored(self):
        store = self.make_store()
        sample = store.ingest_gauges(
            "job-1",
            {
                # dict order must not matter: alias first
                "dlrover_tokens_per_second": 30.0,
                'dlrover_tokens_per_second{pod="w0"}': 10.0,
                'dlrover_tokens_per_second{pod="w1"}': 30.0,
            },
        )
        assert sample.tokens_per_second == 40.0

    def test_bare_only_family_still_ingests(self):
        store = self.make_store()
        sample = store.ingest_gauges(
            "job-1", {"dlrover_job_steps_per_second": 7.0}
        )
        assert sample.steps_per_second == 7.0

    def test_unmapped_keys_store_nothing(self):
        store = self.make_store()
        assert (
            store.ingest_gauges(
                "job-1", {'unrelated_gauge{x="1"}': 1.0}
            )
            is None
        )
        assert store.job_metrics("job-1", limit=5) == []

    def test_explicit_world_size_wins(self):
        store = self.make_store()
        sample = store.ingest_gauges(
            "job-1",
            {'dlrover_steps_per_second{pod="w0"}': 1.0},
            world_size=4,
        )
        assert sample.world_size == 4


# ---------------------------------------------------------------------------
# endpoint handler (tpurun-cluster serve surface)
# ---------------------------------------------------------------------------


class TestClusterEndpoint:
    def make_server(self):
        import threading
        import urllib.request

        from dlrover_tpu.cluster.cli import serve_status

        sched, svc, bulk = two_tenant(breach_sig())
        httpd = serve_status(sched, port=0)
        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()
        port = httpd.server_address[1]

        def get(path):
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=5
            ) as r:
                return json.loads(r.read())

        def post(path, body=None):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}{path}",
                data=json.dumps(body or {}).encode(),
                method="POST",
            )
            with urllib.request.urlopen(req, timeout=5) as r:
                return json.loads(r.read())

        return sched, httpd, get, post

    def test_status_journal_step_target(self):
        sched, httpd, get, post = self.make_server()
        try:
            st = get("/cluster/status")
            assert st["allocations"] == {"svc": 1, "bulk": 3}
            assert get("/healthz")["total_units"] == 4
            verdict = post("/cluster/step")
            assert verdict["action"] == "grant"
            journal = get("/cluster/journal")["journal"]
            assert [e["event"] for e in journal][:2] == [
                "decision",
                "revoke",
            ]
            out = post(
                "/cluster/target",
                {"tenant": "bulk", "units": 2, "source": "operator"},
            )
            assert out["targets"]["bulk"]["units"] == 2
        finally:
            httpd.shutdown()
            httpd.server_close()

    def test_bad_target_is_400(self):
        import urllib.error
        import urllib.request

        sched, httpd, get, post = self.make_server()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                post("/cluster/target", {"tenant": "ghost", "units": 1})
            assert ei.value.code == 400
        finally:
            httpd.shutdown()
            httpd.server_close()


# ---------------------------------------------------------------------------
# the real thing (slow tier): 4 tenants, live engines, one trace
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_priority_inversion_drill(tmp_path, tmp_ipc_dir, monkeypatch):
    from dlrover_tpu.checkpoint.saver import AsyncCheckpointSaver
    from dlrover_tpu.cluster.drill import run_priority_inversion_drill

    monkeypatch.setenv("DLROVER_JOB_NAME", f"clusterdrill_{os.getpid()}")
    AsyncCheckpointSaver.reset()
    try:
        out = run_priority_inversion_drill(
            workdir=str(tmp_path / "drill"), timeout_s=240.0
        )
    finally:
        AsyncCheckpointSaver.reset()
    assert out["ok"], out
    assert out["first_victim"] == "train_lo"
    assert out["availability"] == 1.0
    assert out["escalations"] == 0
    assert out["adoptions"] >= 1 and out["brain_adopt_s"] is not None
    assert out["cascade_one_trace"], out.get("trace")
