"""Master crash tolerance (master/persistence.py + the epoch fence).

The coordination-plane contract under test: a SIGKILLed master restarted
against its state journal replays node tables, rendezvous worlds,
kv/sync contents and shard queues; every RPC response carries the boot
epoch; clients fence stale responses and re-attach on a bump; shard
re-issue stays exactly-once through agent re-reports. No jax anywhere —
this is pure control plane.
"""

import json
import os
import threading
import time
from types import SimpleNamespace

import pytest

from dlrover_tpu.chaos import faults
from dlrover_tpu.common import comm
from dlrover_tpu.common.config import get_context
from dlrover_tpu.common.constants import NodeStatus, RendezvousName
from dlrover_tpu.common.serialize import dumps, loads
from dlrover_tpu.master.job_context import JobContext, get_job_context
from dlrover_tpu.master.kv_store import KVStoreService
from dlrover_tpu.master.persistence import (
    MasterPersistence,
    MasterStateStore,
)
from dlrover_tpu.master.rdzv.manager import ElasticTrainingRendezvousManager
from dlrover_tpu.master.shard.task_manager import TaskManager
from dlrover_tpu.master.sync_service import SyncService
from dlrover_tpu.rpc.client import MasterClient, MasterEpochFenced
from dlrover_tpu.rpc.server import HttpMasterServer


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    faults.deactivate()
    # never leak a state dir into other tests' in-process masters
    monkeypatch.setattr(get_context(), "master_state_dir", "")
    yield
    faults.deactivate()
    JobContext.reset()


# ---------------------------------------------------------------------------
# The store: snapshot + WAL + epoch mechanics.
# ---------------------------------------------------------------------------


class TestMasterStateStore:
    def test_epoch_bumps_per_boot(self, tmp_path):
        store = MasterStateStore(str(tmp_path))
        assert store.read_epoch() == 0
        assert store.bump_epoch() == 1
        assert MasterStateStore(str(tmp_path)).bump_epoch() == 2

    def test_wal_append_and_load(self, tmp_path):
        store = MasterStateStore(str(tmp_path))
        store.append("kv.set", {"key": "a", "v": "eA=="})
        store.append("sync.join", {"name": "b", "node": 1})
        snap, wal = store.load()
        assert snap is None
        assert [r["kind"] for r in wal] == ["kv.set", "sync.join"]
        assert [r["seq"] for r in wal] == [1, 2]

    def test_snapshot_compacts_and_seq_filters(self, tmp_path):
        store = MasterStateStore(str(tmp_path))
        store.append("kv.set", {"key": "old", "v": ""})
        store.write_snapshot({"state": "x"})
        store.append("kv.set", {"key": "new", "v": ""})
        snap, wal = store.load()
        assert snap["state"] == "x"
        assert [r["data"]["key"] for r in wal] == ["new"]
        # crash window: snapshot renamed but WAL not yet truncated — a
        # stale record filtered by seq, never replayed twice
        with open(store._wal_path(), "a") as f:
            f.write(
                json.dumps(
                    {"seq": 1, "kind": "kv.set", "data": {"key": "stale"}}
                )
                + "\n"
            )
        _, wal = store.load()
        assert [r["data"]["key"] for r in wal] == ["new"]

    def test_mid_capture_append_survives_compaction(self, tmp_path):
        """The lost-update window: a record journaled while the snapshot
        capture was reading other components is above the caller's seq
        floor — compaction must KEEP it (idempotent replay), not
        truncate it away with the covered records."""
        store = MasterStateStore(str(tmp_path))
        store.append("kv.set", {"key": "covered", "v": ""})
        floor = store.last_seq()
        store.append("kv.set", {"key": "mid-capture", "v": ""})
        store.write_snapshot({"state": "x"}, floor=floor)
        snap, wal = store.load()
        assert snap["wal_seq"] == floor
        assert [r["data"]["key"] for r in wal] == ["mid-capture"]

    def test_torn_tail_ends_replayable_prefix(self, tmp_path):
        store = MasterStateStore(str(tmp_path))
        store.append("kv.set", {"key": "ok", "v": ""})
        with open(store._wal_path(), "a") as f:
            f.write('{"seq": 2, "kind": "kv.se')  # crash mid-append
        _, wal = store.load()
        assert [r["data"]["key"] for r in wal] == ["ok"]

    def test_fresh_store_continues_seq(self, tmp_path):
        store = MasterStateStore(str(tmp_path))
        store.append("kv.set", {"key": "a", "v": ""})
        store2 = MasterStateStore(str(tmp_path))
        assert store2.append("kv.set", {"key": "b", "v": ""}) == 2


# ---------------------------------------------------------------------------
# kv-store + sync-service round trip (satellite: both were silently
# dropped on any master restart before this PR).
# ---------------------------------------------------------------------------


def _mini_master():
    JobContext.reset()
    rdzv = ElasticTrainingRendezvousManager()
    return SimpleNamespace(
        _job_ctx=get_job_context(),
        kv_store=KVStoreService(),
        sync_service=SyncService(default_expected=2),
        task_manager=TaskManager(),
        rdzv_managers={RendezvousName.TRAINING: rdzv},
    )


class TestKvSyncRoundTrip:
    def test_snapshot_plus_wal_replay_is_lossless(self, tmp_path):
        m1 = _mini_master()
        p1 = MasterPersistence(MasterStateStore(str(tmp_path)), snapshot_every=999)
        p1.boot(m1)
        m1.kv_store.set("coord", b"127.0.0.1:1234")
        m1.kv_store.add("counter", 3)
        m1.kv_store.multi_set({"a": b"1", "b": b"2"})
        m1.kv_store.delete("b")
        m1.sync_service.join("bar", 0)
        m1.sync_service.join("bar", 1)  # expected=2 -> finished
        m1.sync_service.set_expected("solo", 1)
        p1.tick(force=True)  # snapshot covers everything so far
        # post-snapshot mutations ride the WAL only
        m1.kv_store.set("late", b"wal-only")
        m1.kv_store.add("counter", 4)
        m1.sync_service.finish("forced")
        # crash (no stop/tick) -> fresh components replay the journal
        m2 = _mini_master()
        p2 = MasterPersistence(MasterStateStore(str(tmp_path)))
        assert p2.boot(m2) == 2
        assert p2.replayed
        assert m2.kv_store.get("coord") == b"127.0.0.1:1234"
        assert m2.kv_store.get("a") == b"1"
        assert m2.kv_store.get("b") == b""
        assert m2.kv_store.get("late") == b"wal-only"
        assert m2.kv_store.add("counter", 0) == 7
        assert m2.sync_service.is_finished("bar")
        assert m2.sync_service.is_finished("forced")
        assert not m2.sync_service.is_finished("never")
        # the barrier membership survives too: a third joiner against
        # expected=1 barrier still completes post-replay
        assert m2.sync_service.join("solo", 5)

    def test_zero_amount_add_polls_do_not_journal(self, tmp_path):
        """Regression (review): the agents' exit-barrier poll idiom is
        kv_store_add(key, 0) every 0.5 s — a journaled no-op per poll
        would flood the WAL into back-to-back snapshot compactions."""
        m1 = _mini_master()
        store = MasterStateStore(str(tmp_path))
        MasterPersistence(store).boot(m1)
        m1.kv_store.add("barrier", 1)  # real mutation: journaled
        before = store.last_seq()
        for _ in range(50):
            assert m1.kv_store.add("barrier", 0) == 1  # poll: silent
        assert store.last_seq() == before

    def test_rdzv_world_replays(self, tmp_path):
        m1 = _mini_master()
        mgr = m1.rdzv_managers[RendezvousName.TRAINING]
        mgr.update_rdzv_params(2, 2, 30.0, 1)
        p1 = MasterPersistence(MasterStateStore(str(tmp_path)))
        p1.boot(m1)
        for rank in (0, 1):
            mgr.join_rendezvous(
                comm.NodeMeta(node_id=rank, node_rank=rank, addr=f"h{rank}")
            )
        round_, _, world = mgr.get_comm_world(0)
        assert len(world) == 2
        m2 = _mini_master()
        m2.rdzv_managers[RendezvousName.TRAINING].update_rdzv_params(
            2, 2, 30.0, 1
        )
        MasterPersistence(MasterStateStore(str(tmp_path))).boot(m2)
        round2, _, world2 = m2.rdzv_managers[
            RendezvousName.TRAINING
        ].get_comm_world(0)
        assert round2 == round_
        assert {m.node_rank for m in world2.values()} == {0, 1}
        assert world2[1].addr == "h1"

    def test_replay_failure_degrades_to_fresh_boot(self, tmp_path):
        m1 = _mini_master()
        p1 = MasterPersistence(MasterStateStore(str(tmp_path)))
        p1.boot(m1)
        m1.kv_store.set("k", b"v")
        faults.activate(
            faults.FaultPlan.parse("master.boot.replay:error:poisoned@once")
        )
        m2 = _mini_master()
        p2 = MasterPersistence(MasterStateStore(str(tmp_path)))
        # the injected replay error must not raise out of boot
        assert p2.boot(m2) == 2
        assert not p2.replayed
        assert m2.kv_store.get("k") == b""
        fired = [
            r for r in faults.records() if r["point"] == "master.boot.replay"
        ]
        assert len(fired) == 1


# ---------------------------------------------------------------------------
# The client-side epoch fence.
# ---------------------------------------------------------------------------


class _EpochTransport:
    """Scripted transport: each call pops the next epoch (None = dark)."""

    def __init__(self, epochs):
        self.epochs = list(epochs)

    def _resp(self):
        if not self.epochs:
            raise ConnectionError("script exhausted")
        ep = self.epochs.pop(0)
        if ep is None:
            raise ConnectionError("master down")
        return dumps(
            comm.BaseResponse(
                success=True,
                data=dumps(comm.KeyValuePair(key="k", value=b"v")),
                master_epoch=ep,
            )
        )

    def get(self, payload):
        return self._resp()

    def report(self, payload):
        return self._resp()

    def close(self):
        pass


def _scripted_client(epochs, retries=3):
    client = MasterClient(
        master_addr="127.0.0.1:1", service_type="http", retries=retries
    )
    client._transport = _EpochTransport(epochs)
    return client


class TestEpochFence:
    def test_bump_fires_listener_once(self):
        client = _scripted_client([1, 1, 2, 2])
        bumps = []
        client.add_epoch_listener(lambda old, new: bumps.append((old, new)))
        for _ in range(4):
            client.kv_store_get("k")
        assert bumps == [(1, 2)]
        assert client.master_epoch == 2

    def test_first_observation_is_not_a_bump(self):
        client = _scripted_client([3])
        bumps = []
        client.add_epoch_listener(lambda old, new: bumps.append((old, new)))
        client.kv_store_get("k")
        assert bumps == [] and client.master_epoch == 3

    def test_stale_epoch_fenced_and_retried(self):
        # call 1 sees epoch 2; call 2's first attempt gets a stale
        # epoch-1 response (the dead master's in-flight answer) — it is
        # fenced and the retry lands on the live epoch-2 master
        client = _scripted_client([2, 1, 2])
        bumps = []
        client.add_epoch_listener(lambda old, new: bumps.append((old, new)))
        client.kv_store_get("k")
        assert client.kv_store_get("k") == b"v"
        assert bumps == []  # fencing is not a bump

    def test_stale_epoch_exhausting_retries_raises(self):
        client = _scripted_client([2, 1, 1, 1], retries=3)
        client.kv_store_get("k")
        with pytest.raises(ConnectionError) as err:
            client.kv_store_get("k")
        assert "stale response" in repr(err.value)

    def test_epoch_zero_means_no_fencing(self):
        client = _scripted_client([0, 0, 0])
        bumps = []
        client.add_epoch_listener(lambda old, new: bumps.append((old, new)))
        for _ in range(3):
            client.kv_store_get("k")
        assert bumps == [] and client.master_epoch == 0

    def test_epoch_injection_point_fires_and_listeners_survive(self):
        # the rpc.client.epoch drill: the injected error is retried like
        # a transport failure, but the re-attach listeners MUST still
        # have fired (a lost bump would strand every re-attach)
        faults.activate(
            faults.FaultPlan.parse("rpc.client.epoch:error:drill@once")
        )
        client = _scripted_client([1, 2, 2])
        bumps = []
        client.add_epoch_listener(lambda old, new: bumps.append((old, new)))
        client.kv_store_get("k")
        assert client.kv_store_get("k") == b"v"  # retried past the fault
        assert bumps == [(1, 2)]
        assert [
            r for r in faults.records() if r["point"] == "rpc.client.epoch"
        ]

    def test_fence_exception_class(self):
        assert issubclass(MasterEpochFenced, ConnectionError)


# ---------------------------------------------------------------------------
# Agent rendezvous: rejection triage + re-registration (satellite: a
# master rejection used to be a dead end — poll forever, then die).
# ---------------------------------------------------------------------------


class _RejectingServicer:
    """Stub master: wraps a real servicer but rejects the first N
    get_comm_world calls the way a restarted, journal-less master does
    (an error response instead of the typed world)."""

    def __init__(self, inner, reject_world_calls=0, protocol_error=False):
        self.inner = inner
        self.reject_left = reject_world_calls
        self.protocol_error = protocol_error
        self.join_calls = 0

    def get(self, request_bytes):
        req = loads(request_bytes)
        message = loads(req.data)
        if isinstance(message, comm.JoinRendezvousRequest):
            self.join_calls += 1
        if isinstance(message, comm.CommWorldRequest):
            if self.protocol_error:
                return dumps(
                    comm.BaseResponse(success=False, reason="unknown message")
                )
            if self.reject_left > 0:
                self.reject_left -= 1
                return dumps(
                    comm.BaseResponse(
                        success=False, reason="unregistered node"
                    )
                )
        return self.inner.get(request_bytes)

    def report(self, request_bytes):
        return self.inner.report(request_bytes)


def _stub_master(num_workers=1, **kwargs):
    from dlrover_tpu.master.local_master import LocalJobMaster

    master = LocalJobMaster(
        num_workers=num_workers, service_type="http", fresh_context=True
    )
    stub = _RejectingServicer(master.servicer, **kwargs)
    server = HttpMasterServer(stub, port=0)
    server.start()
    return master, stub, server


class TestRendezvousRejectionTriage:
    def test_transient_rejection_reregisters_and_completes(self):
        from dlrover_tpu.agent.rendezvous import MasterRendezvousHandler

        master, stub, server = _stub_master(reject_world_calls=2)
        try:
            client = MasterClient(
                master_addr=f"127.0.0.1:{server.port}",
                node_id=0,
                service_type="http",
            )
            handler = MasterRendezvousHandler(
                RendezvousName.TRAINING,
                node_rank=0,
                client=client,
                rdzv_timeout=30.0,
                poll_interval=0.05,
            )
            world = handler.next_rendezvous()
            assert world.world_size == 1 and world.rank == 0
            # the rejections forced RE-REGISTRATION, not bare re-polling
            assert stub.join_calls >= 2
        finally:
            server.stop()
            master._server.stop()

    def test_protocol_error_is_fatal_not_a_timeout(self):
        from dlrover_tpu.agent.rendezvous import (
            MasterRendezvousHandler,
            RendezvousProtocolError,
        )

        master, stub, server = _stub_master(protocol_error=True)
        try:
            client = MasterClient(
                master_addr=f"127.0.0.1:{server.port}",
                node_id=0,
                service_type="http",
            )
            handler = MasterRendezvousHandler(
                RendezvousName.TRAINING,
                node_rank=0,
                client=client,
                rdzv_timeout=30.0,
                poll_interval=0.05,
            )
            t0 = time.monotonic()
            with pytest.raises(RendezvousProtocolError):
                handler.next_rendezvous()
            # fatal fast: a wire-contract bug must not burn the rdzv
            # deadline pretending to be a transient
            assert time.monotonic() - t0 < 10.0
        finally:
            server.stop()
            master._server.stop()


# ---------------------------------------------------------------------------
# In-process master restart: world replay + epoch-fenced re-attach.
# ---------------------------------------------------------------------------


def _live_master(tmp_path, num_workers=2, name="state"):
    from dlrover_tpu.master.local_master import LocalJobMaster

    get_context().master_state_dir = str(tmp_path / name)
    master = LocalJobMaster(
        num_workers=num_workers, service_type="http", fresh_context=True
    )
    master.prepare()
    return master


def _form_world(master, num_workers=2):
    from dlrover_tpu.agent.rendezvous import MasterRendezvousHandler

    clients, handlers, worlds = [], [], {}
    for rank in range(num_workers):
        clients.append(
            MasterClient(
                master_addr=master.addr, node_id=rank, service_type="http"
            )
        )
        handlers.append(
            MasterRendezvousHandler(
                RendezvousName.TRAINING,
                node_rank=rank,
                client=clients[rank],
                rdzv_timeout=30.0,
                poll_interval=0.05,
            )
        )
    threads = [
        threading.Thread(
            target=lambda r=r: worlds.__setitem__(
                r, handlers[r].next_rendezvous()
            )
        )
        for r in range(1, num_workers)
    ]
    for t in threads:
        t.start()
    worlds[0] = handlers[0].next_rendezvous()
    for t in threads:
        t.join(30)
    return clients, handlers, worlds


class TestMasterRestartReattach:
    def test_intact_world_means_zero_restarts(self, tmp_path, monkeypatch):
        from dlrover_tpu.agent.rendezvous import reattach_world

        monkeypatch.setattr(get_context(), "master_reattach_grace_s", 1.0)
        m1 = _live_master(tmp_path)
        clients, handlers, worlds = _form_world(m1)
        m1._server.stop()  # crash: no snapshot tick, no graceful stop
        m2 = _live_master(tmp_path)
        try:
            assert m2.master_epoch == 2
            # rebuild clients against the restarted master's port; the
            # epoch bump is what a live agent would observe on its next
            # heartbeat/poll
            c0 = MasterClient(
                master_addr=m2.addr, node_id=0, service_type="http"
            )
            from dlrover_tpu.agent.rendezvous import MasterRendezvousHandler

            h0 = MasterRendezvousHandler(
                RendezvousName.TRAINING,
                node_rank=0,
                client=c0,
                rdzv_timeout=10.0,
                poll_interval=0.05,
            )
            outcome, world = reattach_world(h0, worlds[0])
            assert outcome == "intact" and world is None
        finally:
            m2.stop()

    def test_lost_journal_reforms_world_via_reregistration(
        self, tmp_path, monkeypatch
    ):
        from dlrover_tpu.agent.rendezvous import (
            MasterRendezvousHandler,
            reattach_world,
        )

        monkeypatch.setattr(get_context(), "master_reattach_grace_s", 1.0)
        m1 = _live_master(tmp_path)
        clients, handlers, worlds = _form_world(m1)
        m1._server.stop()
        # the journal is LOST (epoch survives): the restarted master
        # knows nothing — re-attach must re-form the world
        state = tmp_path / "state"
        os.unlink(state / "snapshot.json")
        if (state / "wal.jsonl").exists():
            os.unlink(state / "wal.jsonl")
        m2 = _live_master(tmp_path)
        try:
            assert m2.master_epoch == 2
            outcomes = {}
            new_handlers = []
            new_clients = []
            for rank in range(2):
                c = MasterClient(
                    master_addr=m2.addr, node_id=rank, service_type="http"
                )
                new_clients.append(c)
                new_handlers.append(
                    MasterRendezvousHandler(
                        RendezvousName.TRAINING,
                        node_rank=rank,
                        client=c,
                        rdzv_timeout=30.0,
                        poll_interval=0.05,
                    )
                )
            t = threading.Thread(
                target=lambda: outcomes.__setitem__(
                    1, reattach_world(new_handlers[1], worlds[1])
                )
            )
            t.start()
            outcomes[0] = reattach_world(new_handlers[0], worlds[0])
            t.join(30)
            results = {rank: out for rank, (out, _w) in outcomes.items()}
            # a fresh coordinator election makes this a restart (the old
            # jax.distributed bootstrap is stale); the key property is
            # that both agents re-formed a full world instead of dying
            assert set(results.values()) <= {"restart", "matched"}
            for rank, (_out, world) in outcomes.items():
                assert world is not None and world.world_size == 2
                assert world.rank == worlds[rank].rank
        finally:
            m2.stop()


# ---------------------------------------------------------------------------
# Shard reconstruction: exactly-once across a master kill (satellite).
# ---------------------------------------------------------------------------


class TestShardExactness:
    DATASET = comm.DatasetShardParams(
        batch_size=2,
        num_minibatches_per_shard=2,
        dataset_size=40,
        dataset_name="ds",
        storage_type="table",
    )

    def _drain(self, client, consumed):
        while True:
            task = client.get_task("ds")
            if task is None or task.task_id < 0 or task.shard is None:
                return
            consumed.append((task.task_id, task.shard.start, task.shard.end))
            client.report_task_result("ds", task.task_id, True)

    def test_no_sample_dropped_or_double_issued(self, tmp_path, monkeypatch):
        monkeypatch.setattr(get_context(), "master_reattach_grace_s", 0.3)
        m1 = _live_master(tmp_path)
        c0 = MasterClient(master_addr=m1.addr, node_id=0, service_type="http")
        c1 = MasterClient(master_addr=m1.addr, node_id=1, service_type="http")
        c0.report_dataset_params(self.DATASET)
        consumed = []  # (task_id, start, end) completed across both lives
        t_held = c0.get_task("ds")  # node 0 holds this through the kill
        t_done = c0.get_task("ds")
        c0.report_task_result("ds", t_done.task_id, True)
        consumed.append((t_done.task_id, t_done.shard.start, t_done.shard.end))
        t_lost = c1.get_task("ds")  # node 1 dies with the master
        assert {t_held.task_id, t_done.task_id, t_lost.task_id} == {0, 1, 2}
        m1._server.stop()  # crash mid-epoch, in-flight shards live
        m2 = _live_master(tmp_path)
        try:
            ds = m2.task_manager.get_dataset("ds")
            assert sorted(ds.doing) == sorted(
                [t_held.task_id, t_lost.task_id]
            )
            assert all(not d.confirmed for d in ds.doing.values())
            # node 0 re-attaches and claims ONLY what it holds
            c0b = MasterClient(
                master_addr=m2.addr, node_id=0, service_type="http"
            )
            c0b.report_task_inflight("ds", [t_held.task_id])
            assert ds.doing[t_held.task_id].confirmed
            # node 1 never re-reports: its shard requeues at the grace
            time.sleep(0.5)
            assert m2.task_manager.reconcile_unconfirmed() == 1
            assert t_lost.task_id not in ds.doing
            # node 0 finishes its held shard, then both drain the rest
            c0b.report_task_result("ds", t_held.task_id, True)
            consumed.append(
                (t_held.task_id, t_held.shard.start, t_held.shard.end)
            )
            self._drain(c0b, consumed)
            # exactness: every sample exactly once, no dropped range,
            # no double-issued task id
            ids = [tid for tid, _s, _e in consumed]
            assert len(ids) == len(set(ids)), ids
            samples = sorted(
                i for _tid, s, e in consumed for i in range(s, e)
            )
            assert samples == list(range(40))
        finally:
            m2.stop()

    def test_streaming_offsets_continue_after_restart(
        self, tmp_path, monkeypatch
    ):
        """Regression (review): the streaming splitter's offset cursor
        must ride the snapshot — a restarted master restarting at
        offset 0 would re-deliver every consumed range."""
        monkeypatch.setattr(get_context(), "master_reattach_grace_s", 0.2)
        m1 = _live_master(tmp_path)
        c0 = MasterClient(master_addr=m1.addr, node_id=0, service_type="http")
        c0.report_dataset_params(
            comm.DatasetShardParams(
                batch_size=1,
                num_minibatches_per_shard=4,
                dataset_name="stream",
                storage_type="streaming",
            )
        )
        seen = []
        for _ in range(3):
            task = c0.get_task("stream")
            seen.append((task.shard.start, task.shard.end))
            c0.report_task_result("stream", task.task_id, True)
        # force a snapshot so replay exercises the SNAPSHOT path (the
        # WAL refill replay would mask a lost cursor)
        m1.persistence.tick(force=True)
        m1._server.stop()
        m2 = _live_master(tmp_path)
        try:
            c0b = MasterClient(
                master_addr=m2.addr, node_id=0, service_type="http"
            )
            c0b.report_task_inflight("stream", [])
            # drain past the replayed todo into a POST-RESTART refill:
            # offsets must continue the dead master's sequence
            for _ in range(20):
                task = c0b.get_task("stream")
                seen.append((task.shard.start, task.shard.end))
                c0b.report_task_result("stream", task.task_id, True)
            starts = [s for s, _e in seen]
            assert starts == sorted(set(starts)), (
                "streaming offsets repeated or went backwards after "
                f"the master restart: {starts}"
            )
        finally:
            m2.stop()

    def test_shuffle_rng_survives_snapshot(self, tmp_path, monkeypatch):
        """Regression (review): a refill WAL record replayed over a
        snapshot must draw from the SAME RNG position the dead master
        had — a fresh Random(seed) yields a different permutation than
        the shards agents already hold."""
        monkeypatch.setattr(get_context(), "master_reattach_grace_s", 30.0)
        m1 = _live_master(tmp_path)
        c0 = MasterClient(master_addr=m1.addr, node_id=0, service_type="http")
        c0.report_dataset_params(
            comm.DatasetShardParams(
                batch_size=1,
                num_minibatches_per_shard=4,
                dataset_size=12,
                num_epochs=2,
                shuffle=True,
                dataset_name="shuf",
                storage_type="text",
            )
        )
        # drain epoch 1 (3 shards), snapshot BETWEEN the two shuffles,
        # then trigger the epoch-2 refill + one issue (WAL-only)
        for _ in range(3):
            task = c0.get_task("shuf")
            c0.report_task_result("shuf", task.task_id, True)
        m1.persistence.tick(force=True)
        held = c0.get_task("shuf")  # epoch-2 refill happens here
        m1._server.stop()
        m2 = _live_master(tmp_path)
        try:
            ds = m2.task_manager.get_dataset("shuf")
            replayed = ds.doing[held.task_id].task.shard.record_indices
            assert list(replayed) == list(held.shard.indices), (
                "replayed epoch-2 permutation diverged from the shard "
                "the agent holds"
            )
            # the whole epoch still partitions the index set exactly
            todo_indices = [
                i for t in ds.todo for i in t.shard.record_indices
            ]
            assert sorted(todo_indices + list(replayed)) == list(range(12))
        finally:
            m2.stop()

    def test_empty_claim_requeues_immediately(self, tmp_path, monkeypatch):
        """A re-attaching node with NO in-flight shard (it finished but
        the done-report died with the master) must free its doing entry
        right away — at-least-once redelivery without the grace wait."""
        monkeypatch.setattr(get_context(), "master_reattach_grace_s", 30.0)
        m1 = _live_master(tmp_path)
        c0 = MasterClient(master_addr=m1.addr, node_id=0, service_type="http")
        c0.report_dataset_params(self.DATASET)
        held = c0.get_task("ds")
        m1._server.stop()
        m2 = _live_master(tmp_path)
        try:
            ds = m2.task_manager.get_dataset("ds")
            assert held.task_id in ds.doing
            c0b = MasterClient(
                master_addr=m2.addr, node_id=0, service_type="http"
            )
            c0b.report_task_inflight("ds", [])
            assert held.task_id not in ds.doing  # requeued, not dropped
            assert ds.todo[0].task_id == held.task_id
        finally:
            m2.stop()


# ---------------------------------------------------------------------------
# The sharding client's re-report hook.
# ---------------------------------------------------------------------------


class TestShardingClientReattach:
    def test_inflight_reported_on_epoch_bump(self, tmp_path, monkeypatch):
        from dlrover_tpu.agent.sharding import IndexShardingClient

        monkeypatch.setattr(get_context(), "master_reattach_grace_s", 30.0)
        m1 = _live_master(tmp_path)
        c0 = MasterClient(master_addr=m1.addr, node_id=0, service_type="http")
        sharding = IndexShardingClient(
            "ds",
            client=c0,
            batch_size=2,
            dataset_size=40,
            num_minibatches_per_shard=2,
            storage_type="table",
        )
        # draw one sample: the shard is now partially consumed in-flight
        assert sharding.fetch_sample_index() == 0
        held = sharding._pending_task.task_id
        m1._server.stop()
        m2 = _live_master(tmp_path)
        try:
            ds = m2.task_manager.get_dataset("ds")
            assert not ds.doing[held].confirmed
            # point the same client at the restarted master; its next
            # RPC observes the epoch bump and re-reports automatically
            c0._transport = type(c0._transport)(m2.addr)
            c0.report_heartbeat()
            assert ds.doing[held].confirmed
        finally:
            m2.stop()


# ---------------------------------------------------------------------------
# The tier-1 synthetic master-kill drill (subprocess master, scripted
# agents, no jax) — the full-storm twin is slow-marked in
# tests/test_goodput_storm.py.
# ---------------------------------------------------------------------------


class TestSyntheticMasterKill:
    def test_kill_replay_reattach_zero_restarts(self, tmp_path):
        from dlrover_tpu.chaos.master_kill import run_master_kill_synthetic

        log = tmp_path / "faults.jsonl"
        result = run_master_kill_synthetic(
            str(tmp_path / "drill"),
            num_agents=2,
            kill_step=30,
            settle_steps=30,
            step_sleep=0.05,
            timeout_s=120.0,
            master_fault_plan=(
                f"seed=7;log={log};master.boot.replay:delay:0.01@once"
            ),
        )
        assert result is not None, "synthetic master-kill drill timed out"
        assert result["agent_errors"] == []
        assert result["epoch"] >= 2
        # the acceptance claim: agents re-attach under the epoch fence
        # with ZERO worker restarts on an unchanged recovered world
        assert result["worker_restarts"] == 0
        assert result["reattach_outcomes"] == ["intact", "intact"]
        assert result["kv_survived"] and result["sync_survived"]
        assert 0 < result["master_mttr_s"] <= 60.0
        assert result["master_kill_goodput"] > 0.1
        assert result.get("master_replay_s", 0) >= 0
        assert result.get("master_boot_samples") == 1
        # the replay injection demonstrably fired inside the REAL
        # restarted master process
        fired = [
            r
            for r in faults.read_log(str(log))
            if r["point"] == "master.boot.replay"
        ]
        assert fired, "master.boot.replay never fired in the master"
