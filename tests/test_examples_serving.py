"""examples/serving_features.py is the user-facing tour of the serving
pillar set; it must keep running as the engine evolves (each pillar it
drives is individually proven elsewhere — this is the integration
smoke over the PUBLIC api surface)."""

import os
import subprocess
import sys


def test_serving_features_example_runs():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    p = subprocess.run(
        [sys.executable, os.path.join(repo, "examples",
                                      "serving_features.py")],
        env={**os.environ, "PYTHONPATH": os.pathsep.join(sys.path)},
        capture_output=True,
        text=True,
        timeout=900,
    )
    assert p.returncode == 0, p.stdout[-2000:] + p.stderr[-2000:]
    for marker in ("1. per_row", "2. prefix", "3. constrained",
                   "4. cancel", "5. int8", "6. speculative"):
        assert marker in p.stdout, (marker, p.stdout)
