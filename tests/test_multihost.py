"""REAL multi-process jax.distributed coverage (2 processes, CPU).

Everything else simulates hosts in-process; this suite runs two actual
OS processes through ``jax.distributed.initialize`` — the same bootstrap
the agent performs from rendezvous — and exercises the cross-host
checkpoint-consistency path (``load_consistent``) with a genuine
``process_allgather``: rank 0 holds a NEWER memory step than rank 1, so
both must fall back to the common storage step instead of mixing
checkpoints.
"""

import json
import os
import subprocess
import sys

import pytest

# The worker subprocesses (and the engine assertions below) need a real
# jax with jax.distributed; skip cleanly at collection on hosts missing
# it instead of erroring the whole collection pass.
pytest.importorskip("jax")

from dlrover_tpu.agent.rendezvous import find_free_port

WORKER = r'''
import os, sys, json, pathlib
sys.path.insert(0, os.environ["REPO_ROOT"])
import jax

jax.config.update("jax_platforms", "cpu")
rank = int(os.environ["RANK"])
jax.distributed.initialize(
    coordinator_address=os.environ["COORD"], num_processes=2, process_id=rank
)
assert jax.process_count() == 2

import numpy as np
import jax.numpy as jnp
from dlrover_tpu.checkpoint.engine import CheckpointEngine

base = pathlib.Path(os.environ["BASE"])
engine = CheckpointEngine(
    str(base / f"ckpt{rank}"), host_rank=0, num_hosts=1,
    standalone=True, replicate=False,
)
# both ranks commit step 3 to (their) storage
assert engine.save_to_storage(3, {"w": jnp.full((4,), 3.0)})
assert engine.wait_saving(60)
# rank 0 then stages a NEWER memory step the other rank never saw —
# via the shm handler directly: save_to_memory itself is collective
# (all-or-none allreduce), which is exactly why live worlds cannot
# diverge; this simulates a stage left behind by a DEAD world
if rank == 0:
    engine.shm.save_pytree(5, {"w": jnp.full((4,), 5.0)}, num_hosts=1)

from jax.experimental import multihost_utils
multihost_utils.sync_global_devices("staged")

step, restored = engine.load_consistent({"w": jnp.zeros(4, jnp.float32)})
out = {"rank": rank, "step": step,
       "w": np.asarray(restored["w"]).tolist() if restored is not None else None}
(base / f"out{rank}.json").write_text(json.dumps(out))
engine.shm.unlink()
engine.close()
'''


TRAIN_WORKER = r'''
import os, sys, json, pathlib
sys.path.insert(0, os.environ["REPO_ROOT"])
import jax

jax.config.update("jax_platforms", "cpu")
rank = int(os.environ["RANK"])
jax.distributed.initialize(
    coordinator_address=os.environ["COORD"], num_processes=2, process_id=rank
)
assert len(jax.devices()) == 2  # global view: one cpu device per process

import numpy as np
import jax.numpy as jnp
from jax.experimental import multihost_utils
from dlrover_tpu.models.gpt import GPT, GPTConfig, cross_entropy_loss
from dlrover_tpu.parallel.mesh import MeshConfig, build_mesh
from dlrover_tpu.parallel.train_step import (
    build_train_step, default_optimizer, init_train_state,
)

cfg = GPTConfig.tiny()
model = GPT(cfg)
mesh = build_mesh(MeshConfig(dp=2, fsdp=1))  # dp across the two HOSTS
tx = default_optimizer(warmup_steps=1)
tokens = jnp.zeros((4, cfg.max_seq_len), jnp.int32)
state, sh = init_train_state(model, tokens, mesh, tx)
step_fn = build_train_step(model, tx, cross_entropy_loss, mesh, sh)

# each host contributes ITS half of the global batch
r = np.random.default_rng(0)  # same seed: deterministic global batch
x_global = r.integers(0, cfg.vocab_size, (4, cfg.max_seq_len)).astype("int32")
y_global = np.roll(x_global, -1, axis=1)
x = multihost_utils.host_local_array_to_global_array(
    x_global[rank * 2:(rank + 1) * 2], mesh, jax.sharding.PartitionSpec(("dp", "fsdp"))
)
y = multihost_utils.host_local_array_to_global_array(
    y_global[rank * 2:(rank + 1) * 2], mesh, jax.sharding.PartitionSpec(("dp", "fsdp"))
)
losses = []
for _ in range(3):
    state, loss = step_fn(state, x, y)
    # loss is replicated across the world -> direct scalar fetch
    losses.append(float(loss))
assert all(np.isfinite(l) for l in losses), losses
assert losses[-1] < losses[0], losses
base = pathlib.Path(os.environ["BASE"])
(base / f"train{rank}.json").write_text(json.dumps({"losses": losses}))
'''


# This container's jaxlib refuses ANY cross-process computation on the
# CPU backend (jit, process_allgather — "Multiprocess computations
# aren't implemented on the CPU backend"), so every genuine 2-process
# world here is environmentally impossible: skip-with-reason, don't
# fail. Matched against the child's output so the suite still runs in
# full on a jaxlib that can (TPU hosts, newer CPU collectives).
_MULTIPROC_UNSUPPORTED = (
    "Multiprocess computations aren't implemented on the CPU backend"
)


def _run_two_ranks(tmp_path, worker_src, timeout, per_rank_env=None):
    """Launch the worker source as 2 jax.distributed ranks; return their
    outputs. The ONE copy of the launch/collect/kill scaffold: env
    contract (RANK/COORD/BASE/REPO_ROOT, cpu pin, scrubbed XLA_FLAGS
    and IPC namespace), sequential communicate with timeout, rc
    asserts, and kill-on-exit."""
    port = find_free_port("127.0.0.1")
    script = tmp_path / "worker.py"
    script.write_text(worker_src)
    procs = []
    for rank in range(2):
        env = dict(
            os.environ,
            RANK=str(rank),
            COORD=f"127.0.0.1:{port}",
            BASE=str(tmp_path),
            REPO_ROOT=os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))
            ),
            JAX_PLATFORMS="cpu",
        )
        # each process gets ONE cpu device (no virtual-8 override); an
        # inherited IPC namespace would alias both ranks' shm/sockets
        env.pop("XLA_FLAGS", None)
        env.pop("DLROVER_IPC_NAMESPACE", None)
        if per_rank_env:
            env.update(per_rank_env(rank))
        procs.append(
            subprocess.Popen(
                [sys.executable, str(script)],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
            )
        )
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out.decode(errors="replace"))
            if p.returncode != 0 and _MULTIPROC_UNSUPPORTED in outs[-1]:
                pytest.skip(
                    "this jaxlib cannot run multiprocess computations "
                    "on the CPU backend (environmental; the real "
                    "2-process world is untestable here)"
                )
            assert p.returncode == 0, outs[-1][-3000:]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return outs


@pytest.mark.slow
def test_train_step_over_real_two_process_mesh(tmp_path):
    """The data plane the agent bootstraps: 2 OS processes, one global
    2-device mesh, dp across hosts — the sharded train step runs with
    XLA-inserted cross-host collectives and both hosts see one loss."""
    _run_two_ranks(tmp_path, TRAIN_WORKER, timeout=240)
    l0 = json.loads((tmp_path / "train0.json").read_text())["losses"]
    l1 = json.loads((tmp_path / "train1.json").read_text())["losses"]
    assert l0 == l1  # one world, one loss


FULL_STACK_TRAINER = r'''
import os, json, pathlib
from dlrover_tpu.common.platform import force_virtual_cpu
force_virtual_cpu(1)  # one cpu device per host, BEFORE jax.distributed
import jax
from dlrover_tpu.trainer.elastic import elastic_context

ctx = elastic_context()  # initialize_jax() from the ELECTED coordinator
assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 2

import numpy as np
import jax.numpy as jnp
from jax.experimental import multihost_utils
from dlrover_tpu.models.gpt import GPT, GPTConfig, cross_entropy_loss
from dlrover_tpu.parallel.mesh import MeshConfig, build_mesh
from dlrover_tpu.parallel.train_step import (
    build_train_step, default_optimizer, init_train_state,
)

cfg = GPTConfig.tiny()
model = GPT(cfg)
mesh = build_mesh(MeshConfig(dp=2, fsdp=1))
tx = default_optimizer(warmup_steps=1)
tokens = jnp.zeros((4, cfg.max_seq_len), jnp.int32)
state, sh = init_train_state(model, tokens, mesh, tx)
step_fn = build_train_step(model, tx, cross_entropy_loss, mesh, sh)
r = np.random.default_rng(0)
xg = r.integers(0, cfg.vocab_size, (4, cfg.max_seq_len)).astype("int32")
yg = np.roll(xg, -1, axis=1)
rank = ctx.process_id
spec = jax.sharding.PartitionSpec(("dp", "fsdp"))
x = multihost_utils.host_local_array_to_global_array(xg[rank*2:(rank+1)*2], mesh, spec)
y = multihost_utils.host_local_array_to_global_array(yg[rank*2:(rank+1)*2], mesh, spec)
losses = []
for step in range(4):
    state, loss = step_fn(state, x, y)
    losses.append(float(loss))
    ctx.report_step(step)
out = pathlib.Path(os.environ["OUT_DIR"])
(out / f"done_{rank}.json").write_text(
    json.dumps({"losses": losses, "world": ctx.num_processes})
)
print(f"rank {rank} trained to loss {losses[-1]:.4f}", flush=True)
'''


@pytest.mark.slow
def test_full_stack_two_host_jax_world(tmp_path):
    """The FLAGSHIP seam end-to-end: tpurun agents rendezvous through a
    real master, elect the jax.distributed coordinator, and the two
    worker processes form ONE 2-device global mesh and train dp=2 with
    cross-host collectives — the exact production bring-up on a TPU
    slice, on CPU devices."""
    from dlrover_tpu.common.constants import JobExitReason

    from e2e_utils import cleanup_namespaces, make_process_master

    out_dir = tmp_path / "out"
    out_dir.mkdir()
    script = tmp_path / "train.py"
    script.write_text(FULL_STACK_TRAINER)
    job = f"mh_full_{os.getpid()}"
    master, scaler, watcher = make_process_master(
        job,
        command=[
            sys.executable,
            "-m",
            "dlrover_tpu.launcher.elastic_run",
            # CPU host simulation: also keeps profile-auto (TPU-only) off
            "--accelerator",
            "cpu",
            "--nnodes",
            "2",
            str(script),
        ],
        env={
            "OUT_DIR": str(out_dir),
            "DLROVER_LOCAL_DEVICES": "1",
            # override pytest's inherited 8-device flag: each HOST must
            # contribute exactly one device to the 2-device global world
            "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
            "PYTHONPATH": os.pathsep.join(sys.path),
        },
        num_workers=2,
    )
    import time as _time

    try:
        master.prepare()
        master.run_in_background()
        deadline = _time.time() + 180
        while _time.time() < deadline and not master._stopped.is_set():
            _time.sleep(0.5)
        assert master.exit_reason == JobExitReason.SUCCEEDED, (
            master.exit_reason
        )
        import math

        for rank in range(2):
            got = json.loads((out_dir / f"done_{rank}.json").read_text())
            assert got["world"] == 2
            assert all(math.isfinite(l) for l in got["losses"])
        l0 = json.loads((out_dir / "done_0.json").read_text())["losses"]
        l1 = json.loads((out_dir / "done_1.json").read_text())["losses"]
        assert l0 == l1  # one world, one loss
        assert l0[-1] < l0[0]  # and it learns
        # the master's PerfMonitor saw the step reports -> goodput live
        assert master.perf_monitor.last_step()[0] >= 2
    finally:
        master.stop()
        scaler.stop()
        cleanup_namespaces(job, 2)


CHAOS_TRAINER = r'''
import os, json, pathlib
from dlrover_tpu.common.platform import force_virtual_cpu
force_virtual_cpu(1)
import jax
from dlrover_tpu.trainer.elastic import elastic_context

ctx = elastic_context()
assert jax.process_count() == 2

import numpy as np
import jax.numpy as jnp
from jax.experimental import multihost_utils
from dlrover_tpu.checkpoint.engine import CheckpointEngine
from dlrover_tpu.models.gpt import GPT, GPTConfig, cross_entropy_loss
from dlrover_tpu.parallel.mesh import MeshConfig, build_mesh
from dlrover_tpu.parallel.train_step import (
    build_train_step, default_optimizer, init_train_state,
)
from dlrover_tpu.trainer.loop import ElasticTrainLoop

rank = ctx.process_id
out = pathlib.Path(os.environ["OUT_DIR"])
progress = out / f"progress_{rank}.txt"

cfg = GPTConfig.tiny()
model = GPT(cfg)
mesh = build_mesh(MeshConfig(dp=2, fsdp=1))
tx = default_optimizer(learning_rate=1e-2, warmup_steps=2)
tokens = jnp.zeros((4, cfg.max_seq_len), jnp.int32)
state, sh = init_train_state(model, tokens, mesh, tx)
step_fn = build_train_step(model, tx, cross_entropy_loss, mesh, sh)

engine = CheckpointEngine(
    os.path.join(os.environ["CKPT_DIR"], f"rank{rank}"),
    mesh=mesh, host_rank=rank, num_hosts=1, replicate=False,
)
spec = jax.sharding.PartitionSpec(("dp", "fsdp"))
r = np.random.default_rng(0)
xg = r.integers(0, cfg.vocab_size, (4, cfg.max_seq_len)).astype("int32")
yg = np.roll(xg, -1, axis=1)

def data():
    while True:
        x = multihost_utils.host_local_array_to_global_array(
            xg[rank*2:(rank+1)*2], mesh, spec)
        y = multihost_utils.host_local_array_to_global_array(
            yg[rank*2:(rank+1)*2], mesh, spec)
        yield x, y

import time
def on_step(step, loss):
    with open(progress, "a") as f:
        f.write(f"{step}\n")
    time.sleep(0.3)

def factory(start):
    # called AFTER the (cross-host-consistent) restore with the agreed
    # start step — the resume marker the test watches for
    if start > 0:
        (out / f"resumed_{rank}_{start - 1}").write_text(str(os.getpid()))
    return data()

loop = ElasticTrainLoop(
    engine, step_fn, ctx=ctx, max_steps=400,
    storage_every=1,  # every step commits: resume agreement always has
                      # a common storage step after a replacement
    on_step=on_step,
)
state = loop.run(state, data_factory=factory)
print(f"rank {rank} finished", flush=True)
'''


@pytest.mark.slow
def test_chaos_kill_on_real_two_host_world(tmp_path):
    """THE production scenario at full depth: a genuine 2-process
    jax.distributed world trains under tpurun agents; one host is
    SIGKILLed; the master replaces it; BOTH fresh worker incarnations
    re-rendezvous into a NEW 2-process world (new coordinator), agree on
    a consistent resume step, and training continues past the kill."""
    from e2e_utils import cleanup_namespaces, make_process_master

    out_dir = tmp_path / "out"
    ckpt_dir = tmp_path / "ckpt"
    out_dir.mkdir()
    ckpt_dir.mkdir()
    script = tmp_path / "train.py"
    script.write_text(CHAOS_TRAINER)
    job = f"mh_chaos_{os.getpid()}"
    wlogs = tmp_path / "wlogs"
    master, scaler, watcher = make_process_master(
        job,
        command=[
            sys.executable,
            "-m",
            "dlrover_tpu.launcher.elastic_run",
            # CPU host simulation: also keeps profile-auto (TPU-only) off
            "--accelerator",
            "cpu",
            "--nnodes",
            "2",
            "--max_restarts",
            "3",
            "--log_dir",
            str(wlogs),
            str(script),
        ],
        env={
            "OUT_DIR": str(out_dir),
            "CKPT_DIR": str(ckpt_dir),
            "DLROVER_LOCAL_DEVICES": "1",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
            "PYTHONPATH": os.pathsep.join(sys.path),
        },
        num_workers=2,
    )
    import signal
    import time as _time

    def steps(rank):
        p = out_dir / f"progress_{rank}.txt"
        if not p.exists():
            return []
        return [int(l) for l in p.read_text().splitlines()]

    try:
        master.prepare()
        master.run_in_background()
        deadline = _time.time() + 180
        while _time.time() < deadline:
            if len(steps(0)) >= 4 and len(steps(1)) >= 4:
                break
            _time.sleep(0.5)
        assert len(steps(0)) >= 4 and len(steps(1)) >= 4, "never trained"

        killed_at = max(steps(0) or [0])
        os.killpg(scaler._procs[0].proc.pid, signal.SIGKILL)

        # both fresh incarnations must resume into a NEW 2-host world
        deadline = _time.time() + 240
        while _time.time() < deadline:
            if list(out_dir.glob("resumed_0_*")) and list(
                out_dir.glob("resumed_1_*")
            ):
                break
            _time.sleep(0.5)
        assert list(out_dir.glob("resumed_0_*")), "rank 0 never resumed"
        assert list(out_dir.glob("resumed_1_*")), "rank 1 never resumed"
        r0 = int(
            list(out_dir.glob("resumed_0_*"))[0].name.rsplit("_", 1)[-1]
        )
        r1 = int(
            list(out_dir.glob("resumed_1_*"))[0].name.rsplit("_", 1)[-1]
        )
        assert r0 == r1, f"ranks resumed from different steps: {r0} vs {r1}"
        assert r0 >= killed_at - 3, (r0, killed_at)

        # and the new world actually trains past the kill point
        deadline = _time.time() + 180
        while _time.time() < deadline:
            s0 = steps(0)
            if s0 and s0[-1] > killed_at + 3:
                break
            _time.sleep(0.5)
        assert steps(0)[-1] > killed_at + 3, "no progress after re-mesh"
        assert steps(1)[-1] > killed_at, "survivor stalled after re-mesh"
    finally:
        master.stop()
        scaler.stop()
        cleanup_namespaces(job, 2)


@pytest.mark.slow
def test_load_consistent_over_real_jax_distributed(tmp_path):
    outs = _run_two_ranks(
        tmp_path,
        WORKER,
        timeout=180,
        per_rank_env=lambda r: {"DLROVER_JOB_NAME": f"mh_{os.getpid()}_{r}"},
    )
    for rank in range(2):
        got = json.loads((tmp_path / f"out{rank}.json").read_text())
        # disagreement (5 vs 3) resolved to the common storage step: no
        # rank may keep the newer step-5 state the other never had
        assert got["step"] == 3, (rank, got, outs)
        assert got["w"] == [3.0] * 4, (rank, got)


PRUNED_WORKER = r'''
import os, sys, json, pathlib
sys.path.insert(0, os.environ["REPO_ROOT"])
import jax

jax.config.update("jax_platforms", "cpu")
rank = int(os.environ["RANK"])
jax.distributed.initialize(
    coordinator_address=os.environ["COORD"], num_processes=2, process_id=rank
)

import numpy as np
import jax.numpy as jnp
from dlrover_tpu.checkpoint.engine import CheckpointEngine

base = pathlib.Path(os.environ["BASE"])
engine = CheckpointEngine(
    str(base / f"ckpt{rank}"), host_rank=0, num_hosts=1,
    standalone=True, replicate=False,
)
# Divergent per-host histories after retention pruning: the newest
# tracker steps (10 vs 6) exist only on ONE host each; the single step
# committed on BOTH is 4. min-of-trackers (the r2 rule) would name
# step 6, which rank 0 does not have -> permanent crash loop.
steps = [4, 10] if rank == 0 else [4, 6]
for s in steps:
    assert engine.save_to_storage(s, {"w": jnp.full((4,), float(s))}), s
    assert engine.wait_saving(60), s

from jax.experimental import multihost_utils
multihost_utils.sync_global_devices("committed")

step, restored = engine.load_consistent({"w": jnp.zeros(4, jnp.float32)})
out = {"rank": rank, "step": step,
       "w": np.asarray(restored["w"]).tolist() if restored is not None else None}
(base / f"out{rank}.json").write_text(json.dumps(out))
engine.shm.unlink()
engine.close()
'''


def test_pruned_history_agreement_over_real_jax_distributed(tmp_path):
    """ADVICE r2 engine fix, proven on a genuine 2-process allgather:
    hosts with divergent pruned histories restore the newest step
    committed on EVERY host (the intersection), not min-of-trackers."""
    outs = _run_two_ranks(
        tmp_path,
        PRUNED_WORKER,
        timeout=180,
        per_rank_env=lambda r: {"DLROVER_JOB_NAME": f"mhp_{os.getpid()}_{r}"},
    )
    for rank in range(2):
        got = json.loads((tmp_path / f"out{rank}.json").read_text())
        assert got["step"] == 4, (rank, got, outs)
        assert got["w"] == [4.0] * 4, (rank, got)


GEN_WORKER = r'''
import os, sys, json, pathlib
sys.path.insert(0, os.environ["REPO_ROOT"])
import jax

jax.config.update("jax_platforms", "cpu")
rank = int(os.environ["RANK"])
jax.distributed.initialize(
    coordinator_address=os.environ["COORD"], num_processes=2, process_id=rank
)
assert len(jax.devices()) == 2

import numpy as np
import jax.numpy as jnp
from jax.experimental import multihost_utils
from dlrover_tpu.models.generation import (
    SamplingConfig, build_generate_fn, left_pad_prompts,
)
from dlrover_tpu.models.llama import Llama, LlamaConfig
from dlrover_tpu.parallel.mesh import MeshConfig, build_mesh
from dlrover_tpu.parallel.train_step import (
    default_optimizer, init_train_state,
)

model = Llama(LlamaConfig.tiny())
mesh = build_mesh(MeshConfig(dp=2, fsdp=1))  # dp across the two HOSTS
tokens = jnp.zeros((4, 8), jnp.int32)
state, sh = init_train_state(
    model, tokens, mesh, default_optimizer(warmup_steps=1)
)

# same global prompt batch on both hosts (same seed); each host feeds
# its half into the SPMD generation program
toks_g, mask_g = left_pad_prompts(
    [[3, 7, 11], [9], [5, 5], [1, 2, 3, 4]], pad_id=0
)
spec = jax.sharding.PartitionSpec(("dp", "fsdp"))
toks = multihost_utils.host_local_array_to_global_array(
    np.asarray(toks_g)[rank * 2:(rank + 1) * 2], mesh, spec
)
mask = multihost_utils.host_local_array_to_global_array(
    np.asarray(mask_g)[rank * 2:(rank + 1) * 2], mesh, spec
)
sampling = SamplingConfig(max_new_tokens=4, temperature=0.0)
fn = build_generate_fn(
    model, sampling, prompt_width=4, mesh=mesh, param_shardings=sh.params
)
out, omask, logp = fn(state.params, toks, mask, jax.random.PRNGKey(0))

# this host's rows of the global result
local = np.concatenate(
    [np.asarray(s.data) for s in out.addressable_shards], axis=0
)

# single-device reference on the SAME params (replicated under dp-only
# sharding, so each host can fetch them whole) and the FULL batch
host_params = jax.device_get(state.params)
fn1 = build_generate_fn(model, sampling, prompt_width=4)
ref, _, _ = fn1(
    jax.tree.map(jnp.asarray, host_params),
    toks_g,
    mask_g,
    jax.random.PRNGKey(0),
)
want = np.asarray(ref)[rank * 2:(rank + 1) * 2]
ok = bool((local == want).all())
base = pathlib.Path(os.environ["BASE"])
(base / f"gen{rank}.json").write_text(json.dumps({
    "ok": ok, "local": local.tolist(), "want": want.tolist(),
}))
assert ok, (local.tolist(), want.tolist())
'''


@pytest.mark.slow
def test_generation_over_real_two_process_mesh(tmp_path):
    """SPMD generation on a REAL 2-process jax.distributed world: the
    same compiled prefill+decode program runs dp-sharded across hosts
    (tests/test_sharded_generation.py proves it on virtual devices;
    this is the genuine multi-controller bootstrap the agent performs),
    and each host's rows match a single-device run bit-for-bit."""
    outs = _run_two_ranks(tmp_path, GEN_WORKER, timeout=300)
    for rank in range(2):
        got = json.loads((tmp_path / f"gen{rank}.json").read_text())
        assert got["ok"], (rank, got, outs)
