"""Flash-checkpoint tests: shm staging, persist, memory/storage restore,
and re-mesh load (save under one mesh topology, restore under another)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.checkpoint.engine import CheckpointEngine
from dlrover_tpu.checkpoint.checkpointer import Checkpointer, StorageType
from dlrover_tpu.checkpoint.meta import CheckpointMeta
from dlrover_tpu.checkpoint.saver import AsyncCheckpointSaver
from dlrover_tpu.checkpoint.shm_handler import SharedMemoryHandler
from dlrover_tpu.checkpoint.storage import PosixCheckpointStorage
from dlrover_tpu.models.gpt import GPT, GPTConfig, cross_entropy_loss
from dlrover_tpu.parallel.mesh import MeshConfig, build_mesh
from dlrover_tpu.parallel.train_step import default_optimizer, init_train_state


@pytest.fixture(autouse=True)
def fresh_saver(tmp_ipc_dir, monkeypatch):
    job = f"ckpt_{os.getpid()}_{id(tmp_ipc_dir)}"
    monkeypatch.setenv("DLROVER_JOB_NAME", job)
    AsyncCheckpointSaver.reset()
    yield
    AsyncCheckpointSaver.reset()
    # Unlink any shm segments this test's job staged (they intentionally
    # survive process exit, so tests must clean up explicitly).
    for name in os.listdir("/dev/shm"):
        if name.startswith(f"dlrover_{job}_"):
            SharedMemoryHandler(0, name=name.split(f"dlrover_{job}_", 1)[1]).unlink()


def _tree_equal(a, b):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y))


class TestShmHandler:
    def test_roundtrip_host_arrays(self):
        shm = SharedMemoryHandler(0, name="t1")
        try:
            tree = {"a": np.arange(12, dtype=np.float32).reshape(3, 4),
                    "b": {"c": np.float64(3.5)}}
            meta = shm.save_pytree(step=7, pytree=tree)
            assert meta.step == 7
            got_meta, arrays = shm.load_pytree_host()
            assert got_meta.step == 7
            np.testing.assert_array_equal(arrays["a"], tree["a"])
            np.testing.assert_allclose(arrays["b/c"], 3.5)
        finally:
            shm.unlink()

    def test_sharded_array_records(self):
        mesh = build_mesh(MeshConfig(dp=1, fsdp=4, tp=2))
        from jax.sharding import NamedSharding, PartitionSpec

        x = jnp.arange(64, dtype=jnp.float32).reshape(8, 8)
        x = jax.device_put(x, NamedSharding(mesh, PartitionSpec("fsdp", "tp")))
        shm = SharedMemoryHandler(0, name="t2")
        try:
            meta = shm.save_pytree(step=1, pytree={"x": x}, mesh=mesh)
            # 8 distinct shards (4x2), no replicas
            assert len(meta.records) == 8
            _, arrays = shm.load_pytree_host()
            np.testing.assert_array_equal(arrays["x"], np.asarray(x))
        finally:
            shm.unlink()

    def test_replicated_array_deduped(self):
        mesh = build_mesh(MeshConfig(dp=8))
        from jax.sharding import NamedSharding, PartitionSpec

        x = jax.device_put(
            jnp.ones((4, 4)), NamedSharding(mesh, PartitionSpec())
        )
        shm = SharedMemoryHandler(0, name="t3")
        try:
            meta = shm.save_pytree(step=1, pytree={"x": x}, mesh=mesh)
            assert len(meta.records) == 1  # replicas not staged 8x
        finally:
            shm.unlink()


class TestStorage:
    def test_done_protocol_and_tracker(self, tmp_path):
        storage = PosixCheckpointStorage(str(tmp_path))
        meta = CheckpointMeta(step=5, host_rank=0, num_hosts=2)
        storage.write_shard(meta, b"payload0")
        assert not storage.commit(5, num_shards=2)  # shard 1 missing
        assert storage.latest_step() is None
        meta1 = CheckpointMeta(step=5, host_rank=1, num_hosts=2)
        storage.write_shard(meta1, b"payload1")
        assert storage.commit(5, num_shards=2)
        assert storage.latest_step() == 5
        assert storage.committed(5)

    def test_keep_latest(self, tmp_path):
        storage = PosixCheckpointStorage(str(tmp_path))
        for step in (1, 2, 3):
            storage.write_shard(CheckpointMeta(step=step), b"x")
            storage.commit(step, 1)
        storage.keep_latest(2)
        assert storage.list_steps() == [2, 3]


class TestEngineEndToEnd:
    def test_save_load_memory_and_storage(self, tmp_path):
        engine = CheckpointEngine(str(tmp_path / "ckpt"), standalone=True)
        tree = {
            "w": jnp.arange(16, dtype=jnp.float32).reshape(4, 4),
            "step": np.int64(3),
        }
        assert engine.save_to_storage(3, tree)
        assert engine.wait_saving(timeout=30)
        # Memory-first load
        step, restored = engine.load(jax.tree.map(jnp.zeros_like, tree))
        assert step == 3
        _tree_equal(tree, restored)
        # Wipe shm → storage fallback
        engine.shm.unlink()
        step, restored = engine.load(jax.tree.map(jnp.zeros_like, tree))
        assert step == 3
        _tree_equal(tree, restored)
        engine.close()

    def test_async_stage_save_and_load(self, tmp_path):
        """save_to_memory(block=False): staging completes in the
        background and the loader (behind the shard lock) sees it."""
        engine = CheckpointEngine(str(tmp_path / "ckpt"), standalone=True)
        tree = {"w": jnp.arange(64, dtype=jnp.float32).reshape(8, 8)}
        assert engine.save_to_memory(5, tree, block=False)
        assert engine.wait_staged(timeout=30)
        step, restored = engine.load(jax.tree.map(jnp.zeros_like, tree))
        assert step == 5
        _tree_equal(tree, restored)
        engine.close()

    def test_async_stage_survives_donation(self, tmp_path):
        """The device-side snapshot makes block=False immune to the
        trainer donating its state buffers on the very next step —
        the exact hazard of the donate=True train step. The CPU
        backend IGNORES donate_argnums, so the hazard is reproduced
        deterministically with jax.Array.delete() — the same
        buffer-invalidated state donation causes on TPU."""
        engine = CheckpointEngine(str(tmp_path / "ckpt"), standalone=True)
        w = jnp.arange(1024, dtype=jnp.float32)
        expect = np.asarray(w).copy()
        assert engine.save_to_memory(1, {"w": w}, block=False)
        w.delete()  # staging must not touch the original from here on
        assert engine.wait_staged(timeout=30)
        step, restored = engine.load({"w": jnp.zeros(1024, jnp.float32)})
        assert step == 1
        np.testing.assert_allclose(np.asarray(restored["w"]), expect)
        engine.close()

    def test_async_stage_in_flight_skips_next_save(self, tmp_path, monkeypatch):
        """The shard lock is reentrant per owner, so the engine itself
        must skip saves while its staging thread runs — otherwise two
        writers interleave on one segment (torn image)."""
        import threading as _threading

        engine = CheckpointEngine(str(tmp_path / "ckpt"), standalone=True)
        release = _threading.Event()
        real_save = engine.shm.save_pytree

        def slow_save(*a, **kw):
            release.wait(30.0)
            return real_save(*a, **kw)

        monkeypatch.setattr(engine.shm, "save_pytree", slow_save)
        tree = {"w": jnp.ones(64, jnp.float32)}
        assert engine.save_to_memory(1, tree, block=False)
        # Both modes must skip while staging is in flight.
        assert not engine.save_to_memory(2, tree, block=False)
        assert not engine.save_to_memory(2, tree, block=True)
        release.set()
        assert engine.wait_staged(timeout=30)
        step, restored = engine.load(jax.tree.map(jnp.zeros_like, tree))
        assert step == 1
        # And afterwards saves work again.
        monkeypatch.setattr(engine.shm, "save_pytree", real_save)
        assert engine.save_to_memory(3, tree, block=True)
        engine.close()

    def test_async_stage_failure_is_sticky_and_recovers(self, tmp_path, monkeypatch):
        """A failed async stage surfaces through wait_staged (consumed
        once), and a storage-bound failure leaves a persist-error
        marker so wait_saving fails fast instead of timing out."""
        engine = CheckpointEngine(str(tmp_path / "ckpt"), standalone=True)
        tree = {"w": jnp.ones(64, jnp.float32)}

        def boom(*a, **kw):
            raise RuntimeError("stage boom")

        real_save = engine.shm.save_pytree
        monkeypatch.setattr(engine.shm, "save_pytree", boom)
        assert engine.save_to_storage(5, tree, block=False)
        assert not engine.wait_staged(timeout=30)
        assert not engine.wait_saving(timeout=30)  # fail-fast, no 300s burn
        # Recovery: a later good save clears the error path.
        monkeypatch.setattr(engine.shm, "save_pytree", real_save)
        engine.storage.clear_persist_error(engine.host_rank)
        assert engine.save_to_memory(6, tree, block=False)
        assert engine.wait_staged(timeout=30)
        engine.close()

    def test_async_stage_storage_persists_behind_lock(self, tmp_path):
        """save_to_storage(block=False) enqueues SAVE while staging
        runs; the persister serializes on the shard lock, so the
        committed image is the complete one."""
        engine = CheckpointEngine(str(tmp_path / "ckpt"), standalone=True)
        tree = {"w": jnp.full((32, 32), 7.0, jnp.float32)}
        assert engine.save_to_storage(9, tree, block=False)
        assert engine.wait_staged(timeout=30)
        assert engine.wait_saving(timeout=30)
        engine.shm.unlink()  # force the storage path
        step, restored = engine.load(jax.tree.map(jnp.zeros_like, tree))
        assert step == 9
        _tree_equal(tree, restored)
        engine.close()

    def test_wait_saving_fails_fast_on_persist_error(self, tmp_path):
        """VERDICT r1 weak #8: a crashed persist must not leave the
        trainer blocking out the whole wait_saving timeout."""
        engine = CheckpointEngine(str(tmp_path / "ckpt"), standalone=True)
        tree = {"w": jnp.ones((4, 4), jnp.float32)}
        # Break persistence: the saver's write_shard raises (disk full).
        import time as _time

        saver = AsyncCheckpointSaver.get_or_create(
            storage_root=str(tmp_path / "ckpt"), host_rank=0, num_hosts=1
        )
        orig_write = saver.storage.write_shard

        def broken_write(meta, payload):
            raise OSError("disk full (induced)")

        saver.storage.write_shard = broken_write
        try:
            t0 = _time.time()
            assert engine.save_to_storage(1, tree)
            ok = engine.wait_saving(timeout=60)
            elapsed = _time.time() - t0
            assert not ok
            assert elapsed < 30, f"blocked {elapsed:.0f}s despite saver error"
            err = engine.storage.persist_error(0)
            assert err is not None and "disk full" in err[1]
        finally:
            saver.storage.write_shard = orig_write
            engine.shm.unlink()
            engine.close()
        # a later successful persist clears the marker
        engine2 = CheckpointEngine(str(tmp_path / "ckpt"), standalone=True)
        try:
            assert engine2.save_to_storage(2, tree)
            assert engine2.wait_saving(timeout=30)
            assert engine2.storage.persist_error(0) is None
        finally:
            engine2.shm.unlink()
            engine2.close()

    def test_storage_retention_prunes_old_steps(self, tmp_path, monkeypatch):
        """The saver keeps only ckpt_keep_latest committed steps —
        unbounded step dirs would eventually fill the volume."""
        from dlrover_tpu.common.config import get_context

        import time as _time

        monkeypatch.setattr(get_context(), "ckpt_keep_latest", 2)
        engine = CheckpointEngine(str(tmp_path / "ckpt"), standalone=True)
        try:
            for step in (1, 2, 3, 4):
                assert engine.save_to_storage(step, {"w": jnp.full(4, float(step))})
                assert engine.wait_saving(timeout=30)
            # wait_saving returns at tracker update; the saver prunes
            # right after — poll briefly
            deadline = _time.time() + 15
            while _time.time() < deadline:
                if engine.storage.list_steps() == [3, 4]:
                    break
                _time.sleep(0.1)
            assert engine.storage.list_steps() == [3, 4]
            assert engine.storage.latest_step() == 4
        finally:
            engine.shm.unlink()
            engine.close()

    def test_retention_by_commit_recency_and_stale_partials(self, tmp_path):
        """A fresh run reusing a root with stale HIGHER-numbered history
        must keep its new low commits; crashed partial dirs past the
        grace window are swept."""
        import time as _time

        storage = PosixCheckpointStorage(str(tmp_path / "ckpt"))
        from dlrover_tpu.checkpoint.meta import CheckpointMeta

        def commit(step):
            meta = CheckpointMeta(step=step, host_rank=0, num_hosts=1)
            storage.write_shard(meta, b"x")
            assert storage.commit(step, 1)

        for old in (500, 501):
            commit(old)
        _time.sleep(0.05)
        commit(1)  # new run, low step, committed most recently
        storage.keep_latest(2)
        steps = storage.list_steps()
        assert 1 in steps, steps  # newest COMMIT survives despite low number
        assert 500 not in steps, steps
        # stale partial: uncommitted dir older than the grace window
        os.makedirs(storage.step_dir(77), exist_ok=True)
        old_time = _time.time() - storage.STALE_PARTIAL_GRACE_S - 10
        os.utime(storage.step_dir(77), (old_time, old_time))
        # a FRESH partial must survive (may be an in-flight persist)
        os.makedirs(storage.step_dir(78), exist_ok=True)
        storage.keep_latest(2)
        assert not os.path.isdir(storage.step_dir(77))
        assert os.path.isdir(storage.step_dir(78))

    def test_saver_restarts_on_namespace_change(self, tmp_path, monkeypatch):
        """A live runner serving an OLD job namespace must be torn down
        when the namespace changes — otherwise a new engine times out
        waiting for queue servers that answer on the old sockets (the
        exact full-suite flake this reproduces: reset() between tests
        leaves the thread alive)."""
        monkeypatch.setenv("DLROVER_JOB_NAME", f"nsA_{os.getpid()}")
        t1 = AsyncCheckpointSaver.start_async_saving_ckpt()
        assert t1.is_alive()
        monkeypatch.setenv("DLROVER_JOB_NAME", f"nsB_{os.getpid()}")
        engine = CheckpointEngine(
            str(tmp_path / "c"), standalone=True, replicate=False
        )
        try:
            assert engine.save_to_memory(1, {"w": jnp.ones(2)})
            step, restored = engine.load({"w": jnp.zeros(2)})
            assert step == 1
        finally:
            engine.shm.unlink()
            engine.close()

    def test_wait_saving_step_zero(self, tmp_path):
        """Step 0 is falsy; `latest or -1` would spin the full timeout
        on the very first persisted checkpoint of a job."""
        import time as _time

        engine = CheckpointEngine(str(tmp_path / "ckpt"), standalone=True)
        try:
            assert engine.save_to_storage(0, {"w": jnp.ones(4)})
            t0 = _time.time()
            assert engine.wait_saving(timeout=30)
            assert _time.time() - t0 < 20
        finally:
            engine.shm.unlink()
            engine.close()

    def test_stale_persist_error_cleared_on_new_engine(self, tmp_path):
        """A marker left by a dead incarnation (step 100) must not
        fail-fast a resumed run saving lower steps."""
        storage = PosixCheckpointStorage(str(tmp_path / "ckpt"))
        storage.record_persist_error(0, 100, "disk full (old run)")
        engine = CheckpointEngine(str(tmp_path / "ckpt"), standalone=True)
        try:
            assert engine.storage.persist_error(0) is None
            assert engine.save_to_storage(60, {"w": jnp.ones(4)})
            assert engine.wait_saving(timeout=30)
        finally:
            engine.shm.unlink()
            engine.close()

    def test_load_consistent_reloads_common_storage_step(
        self, tmp_path, monkeypatch
    ):
        """Simulated host disagreement: this host restored memory step 5
        but 'another host' only reached step 3 — everyone must fall back
        to the common storage step, never mixing shards of two steps."""
        engine = CheckpointEngine(str(tmp_path / "ckpt"), standalone=True)
        try:
            assert engine.save_to_storage(3, {"w": jnp.full((4,), 3.0)})
            assert engine.wait_saving(timeout=30)
            assert engine.save_to_memory(5, {"w": jnp.full((4,), 5.0)})

            def fake_gather(mem_step, st_step, committed):
                # "another host" only staged step 3 in memory; both have
                # storage step 3 committed
                return (
                    [mem_step, 3],
                    [st_step, 3],
                    [set(committed), {3}],
                )

            monkeypatch.setattr(
                engine, "_gather_restore_meta", fake_gather
            )
            step, restored = engine.load_consistent(
                {"w": jnp.zeros(4, jnp.float32)}
            )
            assert step == 3
            np.testing.assert_array_equal(np.asarray(restored["w"]), 3.0)
        finally:
            engine.shm.unlink()
            engine.close()

    def test_load_consistent_survives_pruned_tracker_step(
        self, tmp_path, monkeypatch
    ):
        """ADVICE r2: with per-host roots + retention, min-of-trackers can
        name a step a fast host already pruned. The agreement must pick
        the newest step committed on EVERY host instead — here the fast
        host holds {4, 6, 8}, the slow peer {2, 4}: restore 4, not the
        peer tracker 4's naive min (which happened to survive) nor a
        deleted step."""
        engine = CheckpointEngine(str(tmp_path / "ckpt"), standalone=True)
        try:
            for s in (4, 6, 8):
                assert engine.save_to_storage(s, {"w": jnp.full((4,), float(s))})
                assert engine.wait_saving(timeout=30)

            def fake_gather(mem_step, st_step, committed):
                # peer: tracker 4, committed {2, 4}; we pruned 2 already
                return [-1, -1], [st_step, 4], [set(committed), {2, 4}]

            monkeypatch.setattr(engine, "_gather_restore_meta", fake_gather)
            step, restored = engine.load_consistent(
                {"w": jnp.zeros(4, jnp.float32)}
            )
            assert step == 4
            np.testing.assert_array_equal(np.asarray(restored["w"]), 4.0)

            # disjoint histories → consistent fresh start, not a crash
            monkeypatch.setattr(
                engine,
                "_gather_restore_meta",
                lambda m, s, c: ([-1, -1], [s, 3], [set(c), {1, 3}]),
            )
            step, restored = engine.load_consistent(
                {"w": jnp.zeros(4, jnp.float32)}
            )
            assert step == -1 and restored is None
        finally:
            engine.shm.unlink()
            engine.close()

    def test_load_consistent_stale_high_step_capped_by_tracker(
        self, tmp_path, monkeypatch
    ):
        """A reused root holding a stale higher-numbered committed step
        must not shadow the live (tracker-pointed) history."""
        engine = CheckpointEngine(str(tmp_path / "ckpt"), standalone=True)
        try:
            assert engine.save_to_storage(900, {"w": jnp.full((4,), 900.0)})
            assert engine.wait_saving(timeout=30)
            assert engine.save_to_storage(7, {"w": jnp.full((4,), 7.0)})
            # wait_saving keys on tracker >= step, which 900 already
            # satisfies — poll for the actual step-7 commit instead
            import time as _time

            deadline = _time.time() + 30
            while _time.time() < deadline and not (
                engine.storage.committed(7)
                and engine.storage.latest_step() == 7
            ):
                _time.sleep(0.05)
            assert engine.storage.latest_step() == 7
            # force the storage path (the shm image would also hold 7)
            monkeypatch.setattr(
                engine,
                "_gather_restore_meta",
                lambda m, s, c: ([-1], [s], [set(c)]),
            )
            step, restored = engine.load_consistent(
                {"w": jnp.zeros(4, jnp.float32)}
            )
            assert step == 7
            np.testing.assert_array_equal(np.asarray(restored["w"]), 7.0)
        finally:
            engine.shm.unlink()
            engine.close()

    def test_load_consistent_agreement_keeps_memory_restore(self, tmp_path):
        engine = CheckpointEngine(str(tmp_path / "ckpt"), standalone=True)
        try:
            assert engine.save_to_memory(8, {"w": jnp.full((4,), 8.0)})
            step, restored = engine.load_consistent(
                {"w": jnp.zeros(4, jnp.float32)}
            )
            assert step == 8
            np.testing.assert_array_equal(np.asarray(restored["w"]), 8.0)
        finally:
            engine.shm.unlink()
            engine.close()

    def test_remesh_restore(self, tmp_path):
        """Save a sharded train state under fsdp=4,tp=2 and restore it into
        a dp=2,fsdp=2,tp=2 template — the elastic re-mesh path."""
        cfg = GPTConfig.tiny()
        model = GPT(cfg)
        tx = default_optimizer()
        tokens = jnp.zeros((8, 32), jnp.int32)

        mesh_a = build_mesh(MeshConfig(dp=1, fsdp=4, tp=2))
        state_a, _ = init_train_state(model, tokens, mesh_a, tx, rng=jax.random.PRNGKey(1))
        engine = CheckpointEngine(str(tmp_path / "ckpt"), mesh=mesh_a, standalone=True)
        assert engine.save_to_storage(11, state_a)
        assert engine.wait_saving(timeout=60)

        mesh_b = build_mesh(MeshConfig(dp=2, fsdp=2, tp=2))
        state_b, _ = init_train_state(model, tokens, mesh_b, tx, rng=jax.random.PRNGKey(2))
        step, restored = engine.load(state_b)
        assert step == 11
        # Values equal state_a, shardings equal state_b
        _tree_equal(state_a.params, restored.params)
        wqkv_b = restored.params["block_0"]["CausalSelfAttention_0"]["wqkv"]
        assert wqkv_b.sharding.mesh.shape == mesh_b.shape
        engine.close()

    def test_breakpoint_save(self, tmp_path):
        """Agent persists the staged step even though no SAVE event came
        (trainer 'crashed' right after save_to_memory)."""
        engine = CheckpointEngine(str(tmp_path / "ckpt"), standalone=True)
        tree = {"w": jnp.ones((8, 8))}
        assert engine.save_to_memory(21, tree)
        saver = AsyncCheckpointSaver._instance
        assert saver is not None
        assert saver.save_shm_to_storage()
        assert engine.storage.latest_step() == 21
        engine.close()

    def test_checkpointer_api(self, tmp_path):
        ckpt = Checkpointer(str(tmp_path / "ckpt"))
        tree = {"a": jnp.ones((2, 2))}
        assert ckpt.save_checkpoint(1, tree, StorageType.MEMORY)
        step, restored = ckpt.load_checkpoint(jax.tree.map(jnp.zeros_like, tree))
        assert step == 1
        _tree_equal(tree, restored)
        ckpt.close()


class TestLiveReshard:
    """The elastic replanner's in-memory rung transition
    (docs/elastic_parallelism.md): ``CheckpointEngine.load_resharded``
    drives the staged flash image through RESHARD_RULES with NO
    template state — the old world's programs (and their shardings)
    are gone the moment mesh extents change."""

    def test_dp_to_pp_shrink_bit_exact_vs_fresh_restore(self, tmp_path):
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh_a = build_mesh(MeshConfig(dp=4), devices=jax.devices()[:4])
        host = {
            "params/w": np.arange(16 * 4, dtype=np.float32).reshape(16, 4),
            "opt_state/mu/w": np.full((16, 4), 0.5, np.float32),
            "step": np.int64(3),
        }
        state = {
            "params": {
                "w": jax.device_put(
                    host["params/w"], NamedSharding(mesh_a, P("dp"))
                )
            },
            "opt_state": {
                "mu": {
                    "w": jax.device_put(
                        host["opt_state/mu/w"],
                        NamedSharding(mesh_a, P("dp")),
                    )
                }
            },
            "step": jax.device_put(
                host["step"], NamedSharding(mesh_a, P())
            ),
        }
        engine = CheckpointEngine(str(tmp_path / "ckpt"), standalone=True)
        try:
            assert engine.save_to_memory(3, state)
            # The rung transition: dp4 → dp2·pp2, templateless.
            mesh_b = build_mesh(
                MeshConfig(dp=2, pp=2), devices=jax.devices()[:4]
            )
            step, placed, _extra = engine.load_resharded(mesh_b)
            assert step == 3
            assert set(placed) == set(host)
            # Placed under the TARGET mesh, dp factor kept by respec.
            w = placed["params/w"]
            assert w.sharding.mesh.shape == mesh_b.shape
            assert "dp" in tuple(w.sharding.spec)
            # Bit-exact parity with the fresh template restore of the
            # same image under the same target mesh.
            template = jax.tree.map(
                lambda a: jax.device_put(
                    np.zeros_like(a),
                    NamedSharding(
                        mesh_b, P("dp") if getattr(a, "ndim", 0) else P()
                    ),
                ),
                {
                    "params": {"w": host["params/w"]},
                    "opt_state": {"mu": {"w": host["opt_state/mu/w"]}},
                    "step": host["step"],
                },
            )
            step2, fresh = engine.load(template)
            assert step2 == 3
            assert np.array_equal(
                np.asarray(placed["params/w"]),
                np.asarray(fresh["params"]["w"]),
            )
            assert np.array_equal(
                np.asarray(placed["opt_state/mu/w"]),
                np.asarray(fresh["opt_state"]["mu"]["w"]),
            )
            assert int(placed["step"]) == int(fresh["step"]) == 3
            # ... and with the save-side host values themselves.
            for path, arr in host.items():
                assert np.array_equal(np.asarray(placed[path]), arr), path
        finally:
            engine.close()

    def test_load_resharded_step_mismatch_and_empty_shm(self, tmp_path):
        mesh = build_mesh(MeshConfig(dp=2), devices=jax.devices()[:2])
        engine = CheckpointEngine(str(tmp_path / "ckpt"), standalone=True)
        try:
            engine.shm.invalidate()
            assert engine.load_resharded(mesh) == (-1, None, {})
            assert engine.save_to_memory(5, {"params": {"w": jnp.ones(4)}})
            assert engine.load_resharded(mesh, step=9) == (-1, None, {})
            step, placed, _ = engine.load_resharded(mesh, step=5)
            assert step == 5 and placed is not None
        finally:
            engine.close()

    def test_opt_dp_shard_cuts_per_device_image_bytes(self, tmp_path):
        """Cross-replica optimizer-state sharding (arXiv:2004.13336):
        with moments sharded dim 0 over dp, each device stages 1/dp of
        the optimizer bytes into the checkpoint image (the shardings
        here are exactly what ``state_shardings(shard_opt_over_dp=
        True)`` hands the moment leaves on a dp-only mesh)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = build_mesh(MeshConfig(dp=4), devices=jax.devices()[:4])
        opt = np.zeros((16, 8), np.float32)
        per_dev = {}
        for i, (name, spec) in enumerate(
            (("replicated", P()), ("dp_sharded", P("dp")))
        ):
            engine = CheckpointEngine(
                str(tmp_path / name), standalone=True
            )
            try:
                arr = jax.device_put(opt, NamedSharding(mesh, spec))
                assert engine.save_to_memory(i + 1, {"opt_state": {"mu": arr}})
                meta, _ = engine._read_staged_host()
                recs = [
                    r for r in meta.records if r.path.startswith("opt_state/")
                ]
                assert recs
                per_dev[name] = max(r.nbytes for r in recs)
            finally:
                engine.close()
        assert per_dev["dp_sharded"] * 4 == per_dev["replicated"]
