"""Native tpu_timer bindings: metrics, hang watchdog, timeline, scraper.

The native library is built on demand by load_native() (plain make); the
reference's test model is xpu_timer/test/common_test.cc plus the
collector parser tests in dlrover/python/tests.
"""

import json
import os
import sys
import time
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from dlrover_tpu.agent.metric_collector import (
    ProfilerMetricCollector,
    parse_prometheus,
)
from dlrover_tpu.master.monitor.metric_context import (
    JobMetricContext,
    get_metric_context,
)
from dlrover_tpu.profiler import StepProfiler, TpuTimer, profile_op
from dlrover_tpu.profiler.native import KIND_COLLECTIVE, KIND_MATMUL
from dlrover_tpu.profiler.timeline import read_timeline, to_perfetto


@pytest.fixture(scope="module")
def timer():
    t = TpuTimer.singleton()
    t.config_hang(3.0, 100)  # 100ms min timeout for tests
    return t


class TestNativeCore:
    def test_record_and_metrics(self, timer):
        timer.record("mm", KIND_MATMUL, 0, 100, flops=1e9)
        timer.record("ar", KIND_COLLECTIVE, 0, 50, bytes_moved=1e6)
        text = timer.metrics_text()
        assert 'tpu_timer_tflops{kind="matmul"}' in text
        assert 'tpu_timer_gbps{kind="collective"}' in text

    def test_http_endpoint(self, timer):
        with urllib.request.urlopen(
            f"http://127.0.0.1:{timer.port}/metrics", timeout=5
        ) as resp:
            text = resp.read().decode()
        assert "tpu_timer_hang" in text

    def test_step_watchdog(self, timer):
        for s in range(5):
            timer.step_begin(s)
            time.sleep(0.002)
            timer.step_end(s)
        assert not timer.hang
        timer.step_begin(100)
        time.sleep(1.2)  # > max(100ms, 3x median)
        assert timer.hang
        timer.step_end(100)
        assert not timer.hang

    def test_timeline_roundtrip(self, timer, tmp_path):
        timer.record("mm", KIND_MATMUL, 123, 45, flops=1.0)
        path = str(tmp_path / "t.timeline")
        n = timer.dump_timeline(path)
        assert n > 0
        events = read_timeline(path)
        assert len(events) == n
        perfetto = to_perfetto(events)
        assert len(perfetto["traceEvents"]) == n
        assert perfetto["traceEvents"][0]["ph"] == "X"


class TestHooks:
    def test_step_profiler_wraps_jitted_fn(self, timer):
        @jax.jit
        def step_fn(x):
            return x * 2


        prof = StepProfiler(timer=timer)
        out = prof.step(step_fn, jnp.ones((4,)), step=7)
        assert out.shape == (4,)
        assert "tpu_timer_last_step 7" in timer.metrics_text()

    def test_profile_op_records(self, timer):
        @profile_op("op_mm", KIND_MATMUL, flops=2 * 8 * 8 * 8, timer=timer)
        def mm(a, b):
            return a @ b

        out = mm(jnp.ones((8, 8)), jnp.ones((8, 8)))
        assert out.shape == (8, 8)


class TestCollector:
    def test_step_profiler_overhead_bounded(self, timer):
        """The reference claims ≤0.5% overhead enabled (xpu_timer
        README). CI-grade bound: the wrapper must add only a small
        constant per step — we assert < 1 ms absolute overhead on a
        median step, which at the flagship's 0.36 s/step is < 0.3%."""
        import time as _time

        fn = jax.jit(lambda x: (jnp.sin(x) @ x).sum())
        x = jnp.ones((512, 512))
        float(fn(x))  # compile

        def min_time(call, iters=30):
            # MIN of interleaved-ish samples: robust to noisy-neighbor
            # descheduling, which shifts medians on loaded CI runners
            best = float("inf")
            for _ in range(iters):
                t0 = _time.perf_counter()
                jax.block_until_ready(call())
                best = min(best, _time.perf_counter() - t0)
            return best

        prof = StepProfiler(timer=timer, auto_costs=True)
        # warm the profiler's one-time HLO probe out of the measurement
        prof.step(fn, x, step=0)
        bare = min_time(lambda: fn(x))
        wrapped = min_time(lambda: prof.step(fn, x, step=1))
        overhead = wrapped - bare
        bound = max(1e-3, 0.05 * bare)
        assert overhead < bound, (
            f"profiler adds {overhead*1e3:.2f} ms/step (bare {bare*1e3:.2f})"
        )

    def test_gc_stall_tracer(self, timer, tmp_path):
        import gc as _gc

        from dlrover_tpu.profiler import GcStallTracer

        tracer = GcStallTracer(timer).install()
        try:
            _gc.collect()
            assert tracer.collections >= 1
            assert tracer.total_pause_us >= 0
            # the pause landed in the kind-aggregated gauges...
            assert 'kind="other"' in timer.metrics_text()
            # ...and, named, in the trace ring/timeline
            path = str(tmp_path / "gc.timeline")
            assert timer.dump_timeline(path) > 0
            from dlrover_tpu.profiler.timeline import read_names

            names = read_names(path + ".names")
            events = read_timeline(path)
            assert any(
                "host_gc" in names.get(e.name_id, "") for e in events
            )
        finally:
            tracer.uninstall()
        before = tracer.collections
        _gc.collect()
        assert tracer.collections == before  # uninstalled → no hook

    def test_host_section_records(self, timer, tmp_path):
        import time as _time

        from dlrover_tpu.profiler import host_section

        with host_section("dataloader", timer):
            _time.sleep(0.01)
        path = str(tmp_path / "host.timeline")
        assert timer.dump_timeline(path) > 0
        from dlrover_tpu.profiler.timeline import read_names

        names = read_names(path + ".names")
        events = read_timeline(path)
        ours = [
            e for e in events
            if names.get(e.name_id, "") == "host_dataloader"
        ]
        assert ours and ours[0].dur_us >= 9_000

    def test_parse_prometheus(self):
        text = (
            "# comment\n"
            'tpu_timer_latency_us{kind="step",agg="avg"} 1234.5\n'
            "tpu_timer_hang 1\n"
        )
        gauges = parse_prometheus(text)
        assert gauges['tpu_timer_latency_us{kind="step",agg="avg"}'] == 1234.5
        assert gauges["tpu_timer_hang"] == 1.0

    def test_scrape_to_master_context(self, timer):
        """End-to-end: scrape the real native endpoint, report into the
        master metric context through a stub client."""

        class StubClient:
            node_id = 3

            def __init__(self):
                self.reported = None

            def report_node_metrics(self, gauges):
                self.reported = gauges
                get_metric_context().report(self.node_id, gauges)

        JobMetricContext.reset()
        client = StubClient()
        collector = ProfilerMetricCollector(timer.port, client=client)
        gauges = collector.collect_once()
        assert gauges and client.reported
        ctx = get_metric_context()
        assert ctx.gauge(3, "tpu_timer_hang") in (0.0, 1.0)

    def test_hung_nodes_feed_diagnosis(self):
        JobMetricContext.reset()
        ctx = get_metric_context()
        ctx.report(0, {"tpu_timer_hang": 0.0})
        ctx.report(1, {"tpu_timer_hang": 1.0})
        assert ctx.hung_nodes() == [1]


class TestHloCosts:
    def test_parse_collectives_shapes(self):
        from dlrover_tpu.profiler.hlo import parse_collectives

        hlo = """
  %ar = f32[128,256]{1,0} all-reduce(%p0), replica_groups={}
  %ag.1 = bf16[64]{0} all-gather(%p1), dimensions={0}
  %done = f32[8]{0} all-reduce-done(%start)
  %rs = (f32[32]{0}, f32[16]{0}) reduce-scatter(%a, %b), dimensions={0}
  %cp = u32[4,4]{1,0} collective-permute(%x), source_target_pairs={{0,1}}
"""
        by_op = parse_collectives(hlo)
        assert by_op["all-reduce"] == 128 * 256 * 4
        assert by_op["all-gather"] == 64 * 2
        assert by_op["reduce-scatter"] == 32 * 4 + 16 * 4
        assert by_op["collective-permute"] == 4 * 4 * 4
        assert "all-reduce-done" not in by_op

    def test_analyze_jitted_reports_flops(self):
        import jax
        import jax.numpy as jnp

        from dlrover_tpu.profiler.hlo import analyze_jitted

        @jax.jit
        def f(a, b):
            return (a @ b).sum()

        a = jnp.zeros((64, 128), jnp.float32)
        b = jnp.zeros((128, 32), jnp.float32)
        costs = analyze_jitted(f, a, b)
        # compiler counts at least the dot flops (2*M*N*K)
        assert costs.flops >= 2 * 64 * 128 * 32

    def test_step_profiler_auto_costs(self, tmp_path):
        import jax
        import jax.numpy as jnp

        from dlrover_tpu.profiler.hooks import StepProfiler
        from dlrover_tpu.profiler.native import TpuTimer

        timer = TpuTimer.singleton()
        prof = StepProfiler(timer=timer, auto_costs=True)

        @jax.jit
        def step_fn(a, b):
            return (a @ b).sum()

        a = jnp.ones((32, 64), jnp.float32)
        b = jnp.ones((64, 16), jnp.float32)
        for _ in range(3):
            prof.step(step_fn, a, b)
        text = timer.metrics_text()
        # HLO-derived flops light up the TFLOPS gauge with no manual args
        assert 'tpu_timer_tflops{kind="hlo_flops"}' in text


class TestTimelineNames:
    def test_dump_and_symbolize(self, tmp_path):
        from dlrover_tpu.profiler.native import KIND_MATMUL, TpuTimer
        from dlrover_tpu.profiler.timeline import convert, read_names

        timer = TpuTimer.singleton()
        timer.record("my_special_op", KIND_MATMUL, 1000, 50, flops=1e6)
        tl = tmp_path / "t.timeline"
        out = tmp_path / "t.json"
        assert timer.dump_timeline(str(tl)) > 0
        names = read_names(str(tl) + ".names")
        assert "my_special_op" in names.values()
        convert(str(tl), str(out))
        import json

        trace = json.loads(out.read_text())
        assert any(
            ev["name"] == "my_special_op" for ev in trace["traceEvents"]
        )


_STACK_DUMP_ROUNDTRIP_SRC = r"""
import os, threading
import dlrover_tpu.profiler.stack_dump as sd

sd._DUMP_DIR = os.environ["DUMP_DIR"]
path = sd.install_stack_dump_handler()
assert path is not None
done = threading.Event()
t = threading.Thread(
    target=lambda: done.wait(60), name="wedged-collective"
)
t.start()
text = sd.trigger_and_read(os.getpid(), timeout_s=30.0)
done.set()
t.join()
print("DUMP_BEGIN")
print(text)
print("DUMP_END", flush=True)
"""


class TestStackDump:
    def test_install_trigger_read_roundtrip(self, tmp_path):
        """SIGUSR2 → faulthandler dump → trigger_and_read, in a CLEAN
        subprocess. In-process this test was a tier-1 load-order
        flake with a hard ceiling behind it: faulthandler dumps
        threads newest-first and truncates the list at 100, and the
        MAIN thread — dumped last, the one a hang post-mortem is
        about — fell off the end whenever the suite process had
        leaked its 100th daemon thread (monitors, http servers).
        A fresh process has a handful of threads, so the roundtrip is
        deterministic under any suite load."""
        import os
        import subprocess
        import sys

        env = dict(
            os.environ,
            DUMP_DIR=str(tmp_path),
            DLROVER_JOB_NAME=f"sd_{os.getpid()}",
            PYTHONPATH=os.pathsep.join(sys.path),
        )
        proc = subprocess.run(
            [sys.executable, "-c", _STACK_DUMP_ROUNDTRIP_SRC],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            timeout=120,
        )
        out = proc.stdout.decode(errors="replace")
        assert proc.returncode == 0, out[-3000:]
        text = out.split("DUMP_BEGIN", 1)[-1].split("DUMP_END", 1)[0]
        # both the wedged worker thread (faulthandler prints thread
        # IDS, not names — its frames are the Event.wait) and the main
        # thread's live frames made it into one artifact
        assert "Thread 0x" in text, text
        assert "in wait" in text, text  # the wedged thread's frame
        assert "Current thread" in text, text
        assert "trigger_and_read" in text, text


def _write_ring(path, records, names=None):
    """Hand-author a .timeline ring (+.names sidecar) fixture."""
    import struct

    with open(path, "wb") as f:
        f.write(b"TPUTL001")
        for name_id, kind, start, dur, step in records:
            f.write(struct.Struct("<IIqII").pack(name_id, kind, start, dur, step))
    if names:
        with open(str(path) + ".names", "w") as f:
            for ident, name in names.items():
                f.write(f"{ident}\t{name}\n")


class TestTimelineClusterTools:
    """VERDICT r2 #8: merge / diff / flamegraph CLI (reference
    py_xpu_timer/bin xpu_timer_diff + gen_trace_timeline)."""

    def test_merge_gives_one_lane_per_host(self, tmp_path):
        import json

        from dlrover_tpu.profiler import timeline as tl

        a = tmp_path / "a.timeline"
        b = tmp_path / "b.timeline"
        _write_ring(a, [(0, 8, 100, 50, 1)], {0: "exec:step_fn"})
        _write_ring(b, [(0, 8, 120, 300, 1)], {0: "exec:step_fn"})
        out = tmp_path / "merged.json"
        rc = tl.main(
            ["merge", f"hostA={a}", f"hostB={b}", "-o", str(out)]
        )
        assert rc == 0
        trace = json.loads(out.read_text())["traceEvents"]
        meta = {e["args"]["name"] for e in trace if e.get("ph") == "M"}
        assert meta == {"hostA", "hostB"}
        pids = {e["pid"] for e in trace if e.get("ph") == "X"}
        assert pids == {0, 1}  # one lane per host
        # the straggler host's 300us execute is attributable to hostB
        slow = [e for e in trace if e.get("dur") == 300]
        assert slow and slow[0]["pid"] == 1

    def test_diff_ranks_regressed_family_first(self, tmp_path, capsys):
        from dlrover_tpu.profiler import timeline as tl

        base = tmp_path / "base.timeline"
        new = tmp_path / "new.timeline"
        names = {0: "exec:train_step", 1: "pjrt_h2d"}
        _write_ring(
            base,
            [(0, 8, 0, 100, 1), (0, 8, 200, 100, 2), (1, 3, 0, 20, 1)],
            names,
        )
        _write_ring(
            new,
            [(0, 8, 0, 400, 1), (0, 8, 500, 400, 2), (1, 3, 0, 22, 1)],
            names,
        )
        rows = tl.diff(str(base), str(new))
        assert rows[0]["key"] == "execute:exec:train_step"
        assert rows[0]["delta_us"] == 300.0
        assert rows[0]["delta_pct"] == 300.0
        # text report prints the regressed family on the first data row
        assert tl.main(["diff", str(base), str(new)]) == 0
        out = capsys.readouterr().out
        assert "execute:exec:train_step" in out.splitlines()[1]

    def test_diff_handles_new_and_vanished_keys(self, tmp_path):
        from dlrover_tpu.profiler import timeline as tl

        base = tmp_path / "base.timeline"
        new = tmp_path / "new.timeline"
        _write_ring(base, [(0, 9, 0, 500, 0)], {0: "pjrt_compile"})
        _write_ring(new, [(1, 3, 0, 30, 0)], {1: "pjrt_h2d"})
        rows = tl.diff(str(base), str(new))
        keys = {r["key"]: r for r in rows}
        assert keys["compile:pjrt_compile"]["new_count"] == 0
        assert keys["h2d:pjrt_h2d"]["base_count"] == 0
        assert keys["h2d:pjrt_h2d"]["delta_pct"] is None


FAULTHANDLER_DUMP = '''Thread 0x00007f1122334455 (most recent call first):
  File "/opt/venv/lib/queue.py", line 171 in get
  File "/app/loader.py", line 40 in next_batch
  File "/app/train.py", line 12 in main

Current thread 0x00007f0000000001 (most recent call first):
  File "/app/util.py", line 5 in spin
  File "/app/train.py", line 20 in worker
'''


class TestFlamegraph:
    def test_fold_and_collapsed_output(self, tmp_path):
        from dlrover_tpu.profiler.flamegraph import (
            fold,
            parse_faulthandler,
            write_collapsed,
        )

        stacks = parse_faulthandler(FAULTHANDLER_DUMP)
        assert len(stacks) == 2
        # root-first: main at the base, the blocking get at the leaf
        assert stacks[0][0].startswith("main (train.py:12)")
        assert stacks[0][-1].startswith("get (queue.py:171)")

        # two dumps of the same wedged worker: the stuck stack counts 2
        counts = fold([FAULTHANDLER_DUMP, FAULTHANDLER_DUMP])
        stuck = "main (train.py:12);next_batch (loader.py:40);get (queue.py:171)"
        assert counts[stuck] == 2
        out = tmp_path / "collapsed.txt"
        assert write_collapsed(counts, str(out)) == 2
        lines = out.read_text().splitlines()
        assert f"{stuck} 2" in lines

    def test_cli(self, tmp_path, capsys):
        from dlrover_tpu.profiler.flamegraph import main

        d = tmp_path / "w.stacks"
        d.write_text(FAULTHANDLER_DUMP)
        out = tmp_path / "c.txt"
        assert main([str(d), "-o", str(out)]) == 0
        assert "2 unique stacks" in capsys.readouterr().out


class TestProfilerDaemon:
    """Rank-0 cluster helper service (reference
    hosting_service_server_client.cc): one Prometheus target for the
    whole job + cluster-wide dump coordination, against a LIVE master."""

    @pytest.fixture()
    def live(self):
        from dlrover_tpu.master.job_context import JobContext
        from dlrover_tpu.master.local_master import LocalJobMaster
        from dlrover_tpu.master.monitor.metric_context import (
            JobMetricContext,
        )
        from dlrover_tpu.rpc.client import MasterClient

        JobContext.reset()
        JobMetricContext.reset()
        master = LocalJobMaster(num_workers=2, fresh_context=True)
        master.prepare()
        client = MasterClient(master_addr=master.addr, node_id=-1)
        yield master, client
        master.stop()
        JobContext.reset()
        JobMetricContext.reset()

    def test_metrics_aggregated_with_node_labels(self, live):
        import urllib.request

        from dlrover_tpu.master.monitor.metric_context import (
            get_metric_context,
        )
        from dlrover_tpu.profiler.daemon import ProfilerDaemon

        master, client = live
        get_metric_context().report(
            0, {'tpu_timer_latency_us{kind="step",agg="win_avg"}': 120.0}
        )
        get_metric_context().report(1, {"tpu_timer_hang": 1.0})
        daemon = ProfilerDaemon(client=client, port=0)
        daemon.start()
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{daemon.port}/metrics", timeout=10
            ) as resp:
                body = resp.read().decode()
            assert (
                'tpu_timer_latency_us{node="0",kind="step",agg="win_avg"} 120.0'
                in body
            )
            assert 'tpu_timer_hang{node="1"} 1.0' in body
            with urllib.request.urlopen(
                f"http://127.0.0.1:{daemon.port}/job", timeout=10
            ) as resp:
                job = json.loads(resp.read().decode())
            assert "goodput" in job
        finally:
            daemon.stop()

    def test_dump_queues_stack_dump_for_running_workers(self, live):
        import urllib.request

        from dlrover_tpu.common.constants import NodeStatus, NodeType
        from dlrover_tpu.common.node import Node
        from dlrover_tpu.master.diagnosis.action import (
            DiagnosisActionType,
            NoAction,
        )
        from dlrover_tpu.master.job_context import get_job_context
        from dlrover_tpu.profiler.daemon import ProfilerDaemon

        master, client = live
        job_ctx = get_job_context()
        for nid, status in ((0, NodeStatus.RUNNING), (1, NodeStatus.FAILED)):
            node = Node(
                node_type=NodeType.WORKER, node_id=nid, rank_index=nid
            )
            node.update_status(status)
            job_ctx.update_node(node)
        daemon = ProfilerDaemon(client=client, port=0)
        daemon.start()
        try:
            # GET /dump must be side-effect free (scrapers/prefetchers
            # issue GETs freely); the trigger verb is POST.
            with pytest.raises(urllib.error.HTTPError) as exc_info:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{daemon.port}/dump", timeout=10
                )
            assert exc_info.value.code == 405
            assert isinstance(
                job_ctx.node_actions.next_action(0), NoAction
            )
            with urllib.request.urlopen(
                f"http://127.0.0.1:{daemon.port}/dump", data=b"", timeout=10
            ) as resp:
                out = json.loads(resp.read().decode())
            assert out["dumped"] == [0]  # only the RUNNING worker
            action = job_ctx.node_actions.next_action(0)
            assert not isinstance(action, NoAction)
            assert action.action_type == DiagnosisActionType.STACK_DUMP
            assert isinstance(
                job_ctx.node_actions.next_action(1), NoAction
            )
        finally:
            daemon.stop()


def _named_events(timer, name, tmp=[0]):
    """Events recorded under ``name`` in the trace ring (per-name view
    lives in the timeline; /metrics aggregates by kind)."""
    import tempfile

    from dlrover_tpu.profiler.timeline import read_names, read_timeline

    path = os.path.join(
        tempfile.mkdtemp(prefix="pytracer_tl_"), "t.timeline"
    )
    if timer.dump_timeline(path) <= 0:
        return []
    names = read_names(path + ".names")
    return [
        e for e in read_timeline(path) if names.get(e.name_id, "") == name
    ]


@pytest.mark.skipif(
    sys.version_info < (3, 12),
    reason="sys.monitoring (PEP 669) needs Python 3.12",
)
class TestPyTracer:
    """sys.monitoring host tracer (VERDICT r3 #8; reference
    py_tracing.c): configured functions and data iterators appear in
    the native profiler stream with no user annotations."""

    @pytest.fixture()
    def tracer(self):
        from dlrover_tpu.profiler.py_tracer import FunctionTracer

        t = FunctionTracer()
        yield t
        t.uninstall()

    def test_traced_function_lands_in_metrics(self, tracer):
        def slow_fn():
            time.sleep(0.05)
            return 42

        assert tracer.add_target(slow_fn, name="slow_fn")
        assert tracer.install()
        for _ in range(3):
            assert slow_fn() == 42
        assert tracer.calls == 3
        # per-name visibility is the trace ring/timeline (metrics text
        # aggregates by kind); latency must reflect the sleep (>=45ms)
        ours = _named_events(tracer.timer, "host_py_slow_fn")
        assert len(ours) == 3
        assert all(e.dur_us >= 45_000 for e in ours)

    def test_generator_iterator_traced_per_item(self, tracer):
        def gen():
            for i in range(5):
                time.sleep(0.02)
                yield i

        it = gen()
        assert tracer.add_iterator(it, name="slow_loader")
        assert tracer.install()
        assert list(it) == [0, 1, 2, 3, 4]
        # one RESUME->YIELD span per item (first span is START->YIELD)
        assert tracer.calls >= 5
        ours = _named_events(tracer.timer, "host_py_slow_loader")
        assert len(ours) >= 5
        # per-ITEM spans (~20ms each), not one whole-generator span;
        # the final exhausted resume adds one near-zero span
        per_item = [e for e in ours if 15_000 <= e.dur_us < 120_000]
        assert len(per_item) == 5

    def test_python_next_iterator_traced(self, tracer):
        class Loader:
            def __init__(self):
                self.n = 0

            def __iter__(self):
                return self

            def __next__(self):
                if self.n >= 3:
                    raise StopIteration
                self.n += 1
                time.sleep(0.01)
                return self.n

        it = Loader()
        assert tracer.add_iterator(it, name="loader_next")
        assert tracer.install()
        assert list(it) == [1, 2, 3]
        assert tracer.calls >= 3

    def test_untraced_code_not_instrumented(self, tracer):
        """The whole point of set_local_events: functions never added
        as targets must not hit our callbacks."""

        def bystander():
            return sum(range(100))

        def target():
            return 1

        assert tracer.add_target(target)
        assert tracer.install()
        target()
        calls_after_target = tracer.calls
        for _ in range(50):
            bystander()
        assert tracer.calls == calls_after_target

    def test_env_spec_targets(self, tracer, monkeypatch):
        from dlrover_tpu.profiler import py_tracer as mod

        monkeypatch.setenv(
            mod.TARGETS_ENV, "json:JSONEncoder.encode, nosuch:fn"
        )
        assert tracer.add_env_targets() == 1
        assert tracer.install()
        import json as _json

        _json.dumps({"a": 1})
        assert tracer.calls >= 1

    def test_crash_hook_records_and_chains(self, tracer):
        import sys as _sys

        from dlrover_tpu.profiler.py_tracer import install_crash_hook

        seen = {}
        orig = _sys.excepthook

        def prev_hook(tp, e, tb):
            seen["prev"] = tp

        _sys.excepthook = prev_hook
        try:
            install_crash_hook(tracer.timer)
            _sys.excepthook(ValueError, ValueError("boom"), None)
            assert seen["prev"] is ValueError  # chained
            assert _named_events(tracer.timer, "host_crash_ValueError")
        finally:
            _sys.excepthook = orig

    def test_crash_hook_rewrap_no_duplicate_records(self, tracer):
        """After an external sys.excepthook replacement that chains back
        into a superseded generation of our hook, a reinstall must not
        double-count the crash (identity dedup per exception object)."""
        import sys as _sys

        from dlrover_tpu.profiler.py_tracer import install_crash_hook

        orig = _sys.excepthook
        try:
            install_crash_hook(tracer.timer)
            old_ours = _sys.excepthook

            def external(tp, e, tb):  # replaces ours, chains back into it
                old_ours(tp, e, tb)

            _sys.excepthook = external
            install_crash_hook(tracer.timer)  # re-wraps around external
            before = len(_named_events(tracer.timer, "host_crash_KeyError"))
            _sys.excepthook(KeyError, KeyError("dup"), None)
            after = len(_named_events(tracer.timer, "host_crash_KeyError"))
            assert after - before == 1  # ours -> external -> old ours: 1 record
        finally:
            _sys.excepthook = orig

    def test_loop_auto_traces_dataloader(self, tmp_path):
        """No user annotations: ElasticTrainLoop wires the tracer to its
        own data iterator; a slow loader shows up in the profiler."""
        import jax
        import jax.numpy as jnp

        from dlrover_tpu.checkpoint.engine import CheckpointEngine
        from dlrover_tpu.profiler.py_tracer import FunctionTracer
        from dlrover_tpu.trainer.loop import ElasticTrainLoop

        def step_fn(state, x):
            return state + jnp.sum(x), jnp.sum(x)

        def slow_data():
            while True:
                time.sleep(0.02)
                yield (jnp.ones((2, 2)),)

        engine = CheckpointEngine(
            str(tmp_path / "ckpt"), standalone=True, replicate=False
        )
        try:
            loop = ElasticTrainLoop(
                engine, step_fn, max_steps=4, storage_every=100
            )
            loop.run(jnp.zeros(()), slow_data())
            tracer = FunctionTracer.singleton()
            assert tracer.calls >= 4
            assert _named_events(tracer.timer, "host_py_data_iter")
        finally:
            engine.shm.unlink()
            engine.close()
            FunctionTracer.singleton().uninstall()


@pytest.mark.skipif(
    sys.version_info < (3, 12),
    reason="sys.monitoring (PEP 669) needs Python 3.12",
)
class TestTracerSlotSharing:
    """The sys.monitoring slot is process-global; instances share it
    through the module registry. Reinstall and cross-instance teardown
    must never strand another tracer's events."""

    def test_uninstall_reinstall_records_again(self):
        from dlrover_tpu.profiler.py_tracer import FunctionTracer

        t = FunctionTracer()

        def fn():
            time.sleep(0.01)

        assert t.add_target(fn, name="reinstall_fn")
        assert t.install()
        fn()
        assert t.calls == 1
        t.uninstall()
        assert t.install()  # must re-claim the registry entries
        fn()
        assert t.calls == 2
        t.uninstall()

    def test_teardown_of_one_tracer_keeps_the_other_live(self):
        from dlrover_tpu.profiler.py_tracer import FunctionTracer

        a, b = FunctionTracer(), FunctionTracer()

        def fa():
            return 1

        def fb():
            return 2

        assert a.add_target(fa, name="fa") and a.install()
        assert b.add_target(fb, name="fb") and b.install()
        fa(), fb()
        assert a.calls == 1 and b.calls == 1
        b.uninstall()  # must NOT free the slot (a still has targets)
        fa()
        assert a.calls == 2, "surviving tracer was stranded"
        a.uninstall()

    def test_same_code_object_not_double_owned(self):
        from dlrover_tpu.profiler.py_tracer import FunctionTracer

        a, b = FunctionTracer(), FunctionTracer()

        def shared():
            return 0

        assert a.add_target(shared, name="mine") and a.install()
        b.install()
        assert not b.add_target(shared, name="theirs")
        shared()
        assert a.calls == 1 and b.calls == 0
        a.uninstall()
        b.uninstall()
