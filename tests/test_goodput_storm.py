"""Preemption-storm goodput e2e (VERDICT r3 #7).

North star: >90% goodput with flash checkpointing every 10 steps under
preemptions (BASELINE; reference README.md:55-56 69%→95%,
docs/blogs/flash_checkpoint.md:403-417). The harness lives in product
code (dlrover_tpu.chaos.goodput_storm) so the benchmark reports the
same measured number.

This is the suite's longest test (~8 min: >380 productive steps so the
compressed-time MTBF/MTTR ratio mirrors production — see the harness
docstring). Run it alone:

    python -m pytest tests/test_goodput_storm.py -q
"""

import pytest


@pytest.mark.slow
def test_goodput_storm_meets_north_star(tmp_path):
    from dlrover_tpu.chaos import run_goodput_storm

    result = run_goodput_storm(str(tmp_path / "storm"))
    assert result is not None, "storm harness timed out"
    assert result["kills"] == 3
    assert result["steps"] >= 30  # the storm spans real training
    # Both numbers are the PerfMonitor's own, not re-derivations.
    # training_goodput carries the >=0.90 north star: it is the
    # fraction the recovery machinery (flash ckpt + warm restart)
    # controls. The strict number also charges first-boot/provisioning,
    # which on this compressed run (MTBF 2 min vs production hours) is
    # bounded below 0.90 by arithmetic: ~25 s of one-core cold boot
    # amortized over ~8 min instead of days — assert it is in the
    # production-extrapolable band and record both in the bench.
    # With soft re-mesh, survivors ride through kills without
    # restarting (measured: strict 0.948 / training 0.982 — most kills
    # cause NO watermark stall at all); the bounds keep headroom for
    # the victim-held-watermark case and noisy-neighbor CI boxes.
    assert result["training_goodput"] >= 0.90, result
    assert result["goodput"] >= 0.85, result
    # MTTR itself is the product claim: recovery (detect -> relaunch ->
    # re-rendezvous -> shm restore -> stepping) in seconds, not minutes.
    assert result["mttr_s"] <= 25.0, result
